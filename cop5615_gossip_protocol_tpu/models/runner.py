"""Single-device round-loop harness.

Replaces the reference's L3/L5 machinery — the ParentActor counting
CompletedMessage/PushSumResult arrivals and killing the process
(program.fs:38-67), the Stopwatch (program.fs:22), and the per-topology
kickoff scripts (program.fs:151-330) — with a data-driven loop: global
convergence is a reduction (`sum(conv) >= target`) evaluated as the
`lax.while_loop` predicate, and the result is a value returned to the
caller, not a side-effecting `Environment.Exit`.

The loop runs in jit'd *chunks* of `cfg.chunk_rounds` rounds: each chunk is
one `lax.while_loop` that early-exits on convergence, and the host syncs only
at chunk boundaries — where checkpoint/metrics hooks fire. Timing is split
compile vs run (SURVEY.md §5 tracing plan): XLA compile time would otherwise
dominate and corrupt small-run comparisons against the reference's
Stopwatch numbers.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import sys
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..config import SimConfig
from ..ops import delivery as delivery_mod
from ..ops import faults as faults_mod
from ..ops import sampling
from ..ops import telemetry as telemetry_mod
from ..ops.topology import Topology, imp_split, stencil_offsets
from ..utils.metrics import RUN_RECORD_SCHEMA_VERSION
from . import gossip as gossip_mod
from . import pipeline as pipeline_mod
from . import pushsum as pushsum_mod

# fold_in tag for the leader draw. Round keys are fold_in(base, round) with
# round < max_rounds <= 2**30 (enforced in SimConfig), so a tag above that
# range can never collide with a round key.
_LEADER_TAG = 2**31 - 1


@dataclasses.dataclass
class RunResult:
    """Structured replacement for the reference's single
    'Convergence Time: %f ms' print (program.fs:51-52)."""

    algorithm: str
    topology: str
    semantics: str
    n_requested: int
    population: int
    target_count: int
    rounds: int
    converged_count: int
    converged: bool
    compile_s: float
    run_s: float
    build_s: float = 0.0
    # Why the run ended: "converged" (target/quorum reached), "stalled"
    # (the cfg.stall_chunks watchdog saw no converged-count progress — the
    # reference's line-topology hang, program.fs:334, as a measured event),
    # "max_rounds" (the round cap), "unhealthy" (the cfg.mass_tolerance
    # health sentinel tripped — non-finite state or mass divergence; the
    # offending round is in unhealthy_round), or "deadline_exceeded" (the
    # caller's deadline cancelled the run at a chunk boundary — partial
    # state/telemetry, exact rounds; schema v5). Always present in the
    # JSONL record.
    outcome: str = "converged"
    # First round the health sentinel tripped (outcome="unhealthy" only).
    unhealthy_round: Optional[int] = None
    # Graceful-degradation audit trail (models/runner.run's fallback
    # ladder): one {"from", "to", "reason", "transient_retries"} dict per
    # rung walked, None when the requested engine ran. Rides the JSONL
    # record so a degraded run is visible downstream.
    degradations: Optional[list] = None
    # push-sum only:
    true_mean: Optional[float] = None
    estimate_mae: Optional[float] = None
    # JSONL format version (utils/metrics.RUN_RECORD_SCHEMA_VERSION) so
    # consumers can detect field drift instead of guessing from shape.
    schema_version: int = RUN_RECORD_SCHEMA_VERSION
    # Per-chunk timing split of run_s (models/pipeline.py): host time spent
    # enqueueing chunks vs blocked on the predicate/telemetry readback.
    dispatch_s: float = 0.0
    fetch_s: float = 0.0
    # Full run budget (schema v4, models/pipeline.py module docstring):
    # the first chunk's enqueue time alone (residual first-execution cost
    # past the measured warmup), host time in chunk-boundary hooks
    # (checkpoint IO + watchdog sync), and telemetry aux collection time
    # (a subset of fetch_s). to_record derives residual_s = run_s −
    # dispatch_s − fetch_s − hook_s, so the whole non-engine wall is
    # named — benchmarks/wallwalk.py is the report over these fields.
    first_dispatch_s: float = 0.0
    hook_s: float = 0.0
    aux_s: float = 0.0
    # Directly bracketed engine-setup and result-finalize phases of the
    # single-device paths (_run_resolved/_run_fused): setup covers
    # round-fn construction + plane/state builds + device transfers
    # between entry and the warmup; finalize covers the host fetches
    # assembling this result after the loop. The sharded run functions do
    # not bracket them (0.0) — their setup lands in wallwalk's derived
    # harness remainder, visibly lowering its closure instead of hiding.
    setup_s: float = 0.0
    finalize_s: float = 0.0
    # Observability payloads — data, not measurements: excluded from
    # to_record. telemetry is an ops/telemetry.TelemetryTrajectory when
    # cfg.telemetry was on; chunk_log is the driver's per-chunk event list
    # (the run-event log's chunk-retired events, utils/events.py).
    telemetry: Optional[object] = None
    chunk_log: Optional[list] = None
    # Checkpoint-hook I/O failures the driver survived under the ISSUE 19
    # continue policy ({"rounds", "error"} per lost interval) — the CLI
    # turns them into checkpoint-failed events. None when every hook
    # succeeded (the overwhelmingly common case).
    hook_failures: Optional[list] = None

    @property
    def wall_ms(self) -> float:
        """Steady-state run wall-clock in ms — the number comparable to the
        reference's convergence-time print (its Stopwatch starts after
        topology build, program.fs:175)."""
        return self.run_s * 1e3

    def to_record(self) -> dict:
        rec = {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if f.name not in ("telemetry", "chunk_log", "hook_failures")
        }
        rec["wall_ms"] = self.wall_ms
        rec["rounds_per_sec"] = self.rounds / self.run_s if self.run_s > 0 else None
        # The unnamed remainder of the run loop (pure Python bookkeeping —
        # deque ops, logging); wallwalk pins it small.
        rec["residual_s"] = (
            self.run_s - self.dispatch_s - self.fetch_s - self.hook_s
        )
        return rec


class StallWatchdog:
    """Converged-count progress watchdog over chunk boundaries
    (cfg.stall_chunks): the reference's only non-convergence behavior was
    hanging forever (program.fs:334); here a stall becomes the measured
    outcome="stalled". One instance per run drives EVERY chunked driver
    (single-device, fused, and the sharded compositions) so the rule
    cannot drift between engines. Callers guard the call with
    ``cfg.stall_chunks`` — the converged-count read is a device sync that
    a disabled watchdog must not pay."""

    def __init__(self, stall_chunks: int):
        self.limit = int(stall_chunks)
        self.stalled = False
        self._last = None
        self._misses = 0

    def no_progress(self, metric: int) -> bool:
        """Record this chunk's progress metric (the termination
        predicate's remaining gap, _progress_gap — NOT the raw conv count:
        under a crash model the quorum need falls as nodes die, so a flat
        conv count can still be progress); True once it has been flat for
        ``limit`` consecutive chunks."""
        if not self.limit:
            return False
        if metric == self._last:
            self._misses += 1
            if self._misses >= self.limit:
                self.stalled = True
        else:
            self._last, self._misses = metric, 0
        return self.stalled


def _progress_gap(life, quorum: float, target: int, conv, rounds: int):
    """The stall watchdog's metric at a chunk boundary: remaining distance
    to the SAME predicate the done flag evaluates. Legacy: target − conv
    count. Crash model: quorum_need(alive) − conv-among-live at the last
    executed round — both terms move, so a shrinking need counts as
    progress even while the conv count is flat. ``conv`` and the ``life``
    planes must be shape-aligned (both [n], or both padded planes — pad
    slots carry death round 0 / revival NEVER and conv 0, so they
    cancel)."""
    conv_i = jnp.asarray(conv).astype(jnp.int32)
    if life is None:
        return int(target) - int(jnp.sum(conv_i))
    alive = faults_mod.alive_at(life.death, rounds - 1, life.revive)
    conv_alive = int(jnp.sum(jnp.where(alive, conv_i, jnp.int32(0))))
    need = int(faults_mod.quorum_need(
        jnp.sum(alive.astype(jnp.int32)), quorum
    ))
    return need - conv_alive


def _check_dtype(cfg: SimConfig) -> jnp.dtype:
    dtype = jnp.dtype(cfg.dtype)
    if cfg.dtype == "float64" and not jax.config.jax_enable_x64:
        raise ValueError(
            "dtype=float64 requires jax_enable_x64 "
            "(jax.config.update('jax_enable_x64', True)); on TPU prefer "
            "float32 with the rescaled default delta (SimConfig.resolved_delta)"
        )
    return dtype


@functools.lru_cache(maxsize=None)
def _leader_program(upper: int):
    """One fused fold_in+randint program per distinct bound (the key rides
    as an argument). Module-level cache, NOT the serving warm-engine pool:
    leader draws are a models-layer concern, and a pool entry per
    population would both occupy warm-ENGINE LRU slots and skew the
    gossip_tpu_engine_pool_* metrics serving dashboards read. Distinct
    bounds per process are bounded by distinct populations — tiny scalar
    programs, no eviction needed."""
    return jax.jit(
        lambda k: jax.random.randint(
            jax.random.fold_in(k, _LEADER_TAG), (), 0, upper,
            dtype=jnp.int32,
        )
    )


def draw_leader(base_key: jax.Array, topo: Topology, cfg: SimConfig) -> jax.Array:
    """Leader ∈ [0, nodes) — the reference draws Random().Next(0, nodes)
    where `nodes` excludes the Q1 extra actor (program.fs:173).

    Jitted, cached per bound: eagerly, fold_in + randint compile TWO
    one-off XLA programs per process (~0.7 s of every cold run's setup
    bucket — the largest single item wallwalk attributed there, ISSUE 9
    satellite); cached, one fused program compiles once and every
    same-population run (suite cells, serving buckets, sweeps) reuses it.
    Same ops, same stream — the drawn leader is bitwise unchanged."""
    upper = int(topo.target_count if cfg.reference else topo.n)
    return _leader_program(upper)(base_key)


def _life_dev(cfg: SimConfig, n: int):
    """Device copies of the churn planes (ops/faults.life_planes), or None
    without a crash model. Pure functions of (cfg, n) — every engine
    rebuilds the identical planes, so checkpoints never store them."""
    planes = faults_mod.life_planes(cfg, n)
    if planes is None:
        return None
    return faults_mod.LifePlanes(
        death=jnp.asarray(planes.death),
        revive=None if planes.revive is None else jnp.asarray(planes.revive),
    )


def _freeze_dead(life, old, new, round_idx):
    """Crash semantics for one round (ops/faults.py docstring): a node dead
    during ``round_idx`` keeps its protocol state frozen — it neither
    converges nor advances. Push-sum (s, w) deliberately take the NEW
    values: mass delivered to a dead node parks there, so total mass over
    live + dead nodes is conserved. Under a recovery model the dead set
    shrinks as revivals land (faults.alive_at). No-op without a crash
    model."""
    if life is None:
        return new
    dead = ~faults_mod.alive_at(life.death, round_idx, life.revive)
    if isinstance(new, pushsum_mod.PushSumState):
        return new._replace(
            term=jnp.where(dead, old.term, new.term),
            conv=jnp.where(dead, old.conv, new.conv),
        )
    return gossip_mod.GossipState(
        count=jnp.where(dead, old.count, new.count),
        active=jnp.where(dead, old.active, new.active),
        conv=jnp.where(dead, old.conv, new.conv),
    )


def make_revive_fn(cfg: SimConfig, n: int, life):
    """Rejoin reset applied at the START of the revival round's body
    (ops/faults.py "Crash-recovery"), or None when the round needs no
    reset: gossip revivals ALWAYS rejoin susceptible (count 0, inactive,
    unconverged — the receiver-side suppression then sees conv=0, so the
    rejoined node can absorb again); push-sum revivals reset to
    (s=x_i, w=0, term=initial, conv=0) under rejoin='fresh' and keep their
    parked state untouched under rejoin='restore' (no reset — the alive
    mask alone resumes them). Applying the reset inside round ``revival``'s
    body keeps checkpoint resume bitwise: a checkpoint cut just before the
    revival round holds the un-reset state, and the resumed round applies
    the identical reset."""
    if life is None or life.revive is None:
        return None
    revive = life.revive
    if cfg.algorithm == "push-sum":
        if cfg.rejoin != "fresh":
            return None
        init_term = cfg.initial_term_round

        def revive_fn(state, round_idx):
            rn = faults_mod.revived_at(revive, round_idx)
            return pushsum_mod.PushSumState(
                s=jnp.where(rn, jnp.arange(n, dtype=state.s.dtype), state.s),
                w=jnp.where(rn, jnp.zeros((), state.w.dtype), state.w),
                term=jnp.where(rn, jnp.int32(init_term), state.term),
                conv=jnp.where(rn, False, state.conv),
            )

    else:

        def revive_fn(state, round_idx):
            rn = faults_mod.revived_at(revive, round_idx)
            return gossip_mod.GossipState(
                count=jnp.where(rn, jnp.int32(0), state.count),
                active=jnp.where(rn, False, state.active),
                conv=jnp.where(rn, False, state.conv),
            )

    return revive_fn


def _byz_dev(cfg: SimConfig, n: int):
    """Device copy of the adversary plane (ops/faults.byzantine_plane), or
    None without a byzantine model. Config-pure like the churn planes —
    every engine rebuilds the identical plane, checkpoints never store
    it."""
    byz = faults_mod.byzantine_plane(cfg, n)
    return None if byz is None else jnp.asarray(byz)


def make_byz_send_fn(cfg: SimConfig, byz):
    """Push-sum wire corruption at send-time (cfg.byzantine_mode): the
    adversary's KEPT state follows the honest update (s_keep/w_keep are
    untouched) — only the pair handed to delivery is corrupted.
    mass_inflate sends the UNHALVED state (a copy of the node's mass is
    injected per round, ratio preserved); mass_deflate negates the sent
    pair (mass drained); garble swaps the s/w channels (finite, NaN-free
    garbage). ``send_ok`` is already alive/gate-masked, so dead or gated
    adversaries stay silent like honest nodes. None for gossip / without
    a plane."""
    if byz is None or cfg.algorithm != "push-sum":
        return None
    mode = cfg.byzantine_mode

    def corrupt(s_send, w_send, state, send_ok, round_idx):
        lying = faults_mod.byzantine_at(byz, round_idx) & send_ok
        if mode == "mass_inflate":
            return (
                jnp.where(lying, state.s, s_send),
                jnp.where(lying, state.w, w_send),
            )
        if mode == "mass_deflate":
            return (
                jnp.where(lying, -s_send, s_send),
                jnp.where(lying, -w_send, w_send),
            )
        # garble: the channels swapped — finite garbage, wire unchanged.
        return (
            jnp.where(lying, w_send, s_send),
            jnp.where(lying, s_send, w_send),
        )

    return corrupt


def make_byz_override_fn(cfg: SimConfig, byz, life):
    """Gossip adversary behavior as a state override applied at the END of
    the round body, after _freeze_dead — the fused kernels apply it at
    the same position, so cross-engine trajectories stay bitwise.
    stale_rumor pins count 0 / active 1 / conv 0 (perpetual rumor
    re-injection after local convergence — the node spams forever and
    never converges); garble latches conv 1 (fake convergence reported to
    the termination predicate). Dead adversaries stay frozen. None for
    push-sum / without a plane."""
    if byz is None or cfg.algorithm == "push-sum":
        return None
    mode = cfg.byzantine_mode

    def override(state, round_idx):
        lying = faults_mod.byzantine_at(byz, round_idx)
        if life is not None:
            lying = lying & faults_mod.alive_at(
                life.death, round_idx, life.revive
            )
        if mode == "stale_rumor":
            return gossip_mod.GossipState(
                count=jnp.where(lying, jnp.int32(0), state.count),
                active=state.active | lying,
                conv=state.conv & ~lying,
            )
        # garble
        return state._replace(conv=state.conv | lying)

    return override


def make_robust_clip_fn(cfg: SimConfig):
    """--robust-agg clip (push-sum, chunked engine): bound the aggregate
    (s, w) inbox a receiver accepts this round to a dynamic envelope —
    cap = 2 * max(w_keep, 1), proportional to the receiver's own kept
    weight. Pair-consistent: both channels scale together, so the inbox
    ratio (and with it the estimate) passes through unchanged — clipping
    discards WEIGHT, never injects bias. Non-positive-w inboxes are
    rejected outright (mass_deflate's signature). None unless
    robust_agg == 'clip' (trim lives in the pool delivery,
    ops/delivery.deliver_pool_trimmed)."""
    if cfg.robust_agg != "clip" or cfg.algorithm != "push-sum":
        return None

    def clip(inbox_s, inbox_w, w_keep):
        dt = inbox_w.dtype
        one = jnp.ones((), dt)
        cap = jnp.asarray(2.0, dt) * jnp.maximum(w_keep, one)
        over = inbox_w > cap
        scale = jnp.where(over, cap / jnp.where(over, inbox_w, one), one)
        scale = jnp.where(inbox_w > 0, scale, jnp.zeros((), dt))
        return inbox_s * scale, inbox_w * scale

    return clip


def _done_predicate(cfg: SimConfig, life, target: int):
    """The while-loop termination predicate, as ``done(state, round_idx)``
    with round_idx the round JUST EXECUTED. Legacy: converged_count >=
    target. Crash model: quorum over live nodes — sum(conv & alive) >=
    quorum_need(sum(alive)) (ops/faults.py), so a run with churn terminates
    with a meaningful answer instead of spinning to max_rounds. Under a
    recovery model the live set grows back as revivals land."""
    if life is None:
        def done(state, round_idx):
            return jnp.sum(state.conv) >= target
    else:
        quorum = cfg.quorum

        def done(state, round_idx):
            alive = faults_mod.alive_at(life.death, round_idx, life.revive)
            need = faults_mod.quorum_need(
                jnp.sum(alive.astype(jnp.int32)), quorum
            )
            return jnp.sum((state.conv & alive).astype(jnp.int32)) >= need

    return done


def resolve_deliver_fn(topo: Topology, cfg: SimConfig):
    """Pick the delivery implementation: stencil (masked circular shifts —
    no scatter, no sort) where the topology's displacement set is small,
    scatter-add otherwise. ``delivery="stencil"`` fails loudly on topologies
    that cannot support it (full is implicit; imp2d/imp3d have random
    long-range edges)."""
    offsets = stencil_offsets(topo)
    if cfg.delivery == "stencil" and offsets is None:
        raise ValueError(
            "delivery='stencil' requires an offset-structured topology "
            "(line/ring/grid2d/ref2d/grid3d/torus3d); "
            f"{topo.kind!r} has no small displacement set"
        )
    n = topo.n
    if cfg.delivery != "scatter" and offsets is not None:
        return lambda v, t: delivery_mod.deliver_stencil(v, t, offsets, n)
    return lambda v, t: delivery_mod.deliver(v, t, n)


def make_round_fn(topo: Topology, cfg: SimConfig, base_key: jax.Array):
    """Build (round_fn, state0, key_data, topo_args).

    ``round_fn(state, round_idx, key_data, *topo_args) -> state`` is one
    synchronous protocol round, pure and jittable — the unit
    `__graft_entry__.entry` compile-checks. ``topo_args`` carries the
    neighbor tensors, and ``key_data`` the raw form of ``base_key``
    (ops/sampling.key_split), as explicit arguments: arrays closed over by a
    jitted round would be baked into the executable as constants, which the
    axon remote-TPU platform re-ships on EVERY dispatch (~100 ms/launch,
    measured — it dominated all small-N walls). ``key_data`` is returned
    alongside so callers feed back the exact data matching the impl the
    round function captured — re-splitting a different key would silently
    mix streams.
    """
    dtype = _check_dtype(cfg)
    n = topo.n

    if cfg.delivery in ("pool", "matmul") and (
        cfg.dup_rate > 0 or cfg.delay_rounds > 0
    ):
        raise ValueError(
            "dup/delay fault models run on the scatter/stencil chunked "
            f"paths only; {cfg.delivery} delivery supports the drop gate "
            "(--fault-rate) and crash models"
        )

    if cfg.delivery in ("pool", "matmul"):
        # delivery='matmul' is the MXU execution of the SAME pooled
        # sampling stream: identical choices/offsets per round, delivery
        # recast as a blocked one-hot dot_general (ops/delivery.
        # deliver_matmul) instead of masked rolls — gossip inboxes are
        # bitwise the pool path's, push-sum reassociates within the float
        # contract (tests/test_delivery_matmul.py).
        if topo.implicit:
            return _make_pool_round_fn(topo, cfg, base_key, dtype)
        if topo.kind in ("imp2d", "imp3d"):
            if cfg.reference:
                raise ValueError(
                    f"delivery={cfg.delivery!r} on imp topologies re-draws "
                    "the random long-range edge per round and cannot "
                    "reproduce the reference's static extra edge (Q9, "
                    "program.fs:308-310); use batched semantics or "
                    "delivery='scatter'"
                )
            split = imp_split(topo)
            if split is None:
                raise ValueError(
                    f"imp pooled delivery unavailable for this {topo.kind!r} "
                    "instance (lattice slots are not offset-structured)"
                )
            return _make_imp_pool_round_fn(topo, cfg, base_key, dtype, split)
        raise ValueError(
            f"delivery={cfg.delivery!r} applies to the implicit full "
            f"topology and the imp2d/imp3d random-extra-edge topologies; "
            f"{topo.kind!r} has neither an implicit nor a lattice+extra "
            "structure"
        )

    key_data, key_impl = sampling.key_split(base_key)

    if topo.implicit:
        topo_args = ()
    else:
        topo_args = (jnp.asarray(topo.neighbors), jnp.asarray(topo.degree))

    deliver_fn = resolve_deliver_fn(topo, cfg)
    life = _life_dev(cfg, n)
    revive_fn = make_revive_fn(cfg, n, life)
    byz = _byz_dev(cfg, n)
    corrupt_fn = make_byz_send_fn(cfg, byz)
    byz_override = make_byz_override_fn(cfg, byz, life)
    clip_fn = make_robust_clip_fn(cfg)

    def _rejoin(state, round_idx):
        """Revival-round reset, applied at round-body entry (see
        make_revive_fn). Identity without a recovery model / under
        rejoin='restore' push-sum."""
        if revive_fn is None:
            return state
        return revive_fn(state, round_idx)

    def targets_and_gate(round_idx, key_data, *targs):
        # ids generated inside the trace (lax.iota) — never a baked constant.
        with jax.named_scope("sample"):
            ids = jnp.arange(n, dtype=jnp.int32)
            kr = sampling.round_key(sampling.key_join(key_data, key_impl), round_idx)
            bits = sampling.uniform_bits(kr, n)
            if topo.implicit:
                targets = sampling.targets_full(bits, ids, n)
                send_ok = jnp.ones((n,), bool)
            else:
                neighbors, degree = targs
                targets = sampling.targets_explicit(bits, neighbors, degree)
                send_ok = degree > 0
            gate = sampling.send_gate(kr, n, cfg.fault_rate)
            if gate is not True:
                send_ok = send_ok & gate
            if life is not None:
                # Dead nodes never send; revived nodes resume.
                send_ok = send_ok & faults_mod.alive_at(
                    life.death, round_idx, life.revive
                )
            dup = sampling.dup_gate(kr, n, cfg.dup_rate)
            return targets, send_ok, dup

    def make_df(dup):
        """Per-round delivery fn with the duplicate-delivery gate folded
        in: a dup-gated sender's message lands twice (at-least-once
        delivery). ``dup is False`` (dup_rate == 0) keeps the base fn —
        zero-cost and bitwise the unfaulted delivery."""
        if dup is False:
            return deliver_fn

        def df(v, t):
            return deliver_fn(v, t) + deliver_fn(
                jnp.where(dup, v, jnp.zeros((), v.dtype)), t
            )

        return df

    D = cfg.delay_rounds

    if cfg.algorithm == "push-sum":
        state0 = pushsum_mod.init_state(n, dtype, cfg.initial_term_round)
        delta = cfg.resolved_delta
        term_rounds = cfg.term_rounds

        if D:
            # Bounded message delay: this round's deliveries are parked in
            # a ring of D send planes and absorbed D rounds later —
            # in-flight mass lives in the ring, so Σs and Σw are conserved
            # over state + ring (tests pin it). The carry is (state, ring).
            ring0 = jnp.zeros((D, 2, n), dtype)
            state0 = (state0, ring0)

            def round_fn(carry, round_idx, key_data, *targs):
                state, ring = carry
                state = _rejoin(state, round_idx)
                targets, send_ok, dup = targets_and_gate(
                    round_idx, key_data, *targs
                )
                df = make_df(dup)
                s_send, w_send, s_keep, w_keep = pushsum_mod.halve_and_send(
                    state.s, state.w, send_ok
                )
                if corrupt_fn is not None:
                    # Corruption happens at send-time: the lie is what
                    # enters the ring, so it arrives D rounds later like
                    # any other in-flight message.
                    s_send, w_send = corrupt_fn(
                        s_send, w_send, state, send_ok, round_idx
                    )
                fresh = jnp.stack([df(s_send, targets), df(w_send, targets)])
                slot = lax.rem(round_idx, jnp.int32(D))
                arrive = lax.dynamic_index_in_dim(
                    ring, slot, axis=0, keepdims=False
                )
                ring = lax.dynamic_update_index_in_dim(ring, fresh, slot, 0)
                in_s, in_w = arrive[0], arrive[1]
                if clip_fn is not None:
                    in_s, in_w = clip_fn(in_s, in_w, w_keep)
                new = pushsum_mod.absorb(
                    state, s_keep, w_keep, in_s, in_w, delta,
                    term_rounds, cfg.termination == "global",
                )
                return (_freeze_dead(life, state, new, round_idx), ring)

        else:

            def round_fn(state, round_idx, key_data, *targs):
                state = _rejoin(state, round_idx)
                targets, send_ok, dup = targets_and_gate(
                    round_idx, key_data, *targs
                )
                if corrupt_fn is None and clip_fn is None:
                    new = pushsum_mod.round_from_targets(
                        state, targets, send_ok, n, delta, term_rounds,
                        make_df(dup), cfg.termination == "global",
                    )
                else:
                    # round_from_targets inlined so the wire pair can be
                    # corrupted after the halve and the inbox clipped
                    # before the absorb — identical op sequence otherwise.
                    df = make_df(dup)
                    with jax.named_scope("pushsum_halve"):
                        s_send, w_send, s_keep, w_keep = (
                            pushsum_mod.halve_and_send(
                                state.s, state.w, send_ok
                            )
                        )
                    if corrupt_fn is not None:
                        s_send, w_send = corrupt_fn(
                            s_send, w_send, state, send_ok, round_idx
                        )
                    with jax.named_scope("pushsum_deliver"):
                        in_s = df(s_send, targets)
                        in_w = df(w_send, targets)
                    if clip_fn is not None:
                        in_s, in_w = clip_fn(in_s, in_w, w_keep)
                    with jax.named_scope("pushsum_absorb"):
                        new = pushsum_mod.absorb(
                            state, s_keep, w_keep, in_s, in_w, delta,
                            term_rounds, cfg.termination == "global",
                        )
                return _freeze_dead(life, state, new, round_idx)

    else:
        leader = draw_leader(base_key, topo, cfg)
        state0 = gossip_mod.init_state(
            n, leader, leader_counts_receipt=cfg.reference and topo.kind == "full"
        )
        rumor_target = cfg.resolved_rumor_target
        suppress = cfg.resolved_suppress

        if D:
            ring0 = jnp.zeros((D, n), jnp.int32)
            state0 = (state0, ring0)

            def round_fn(carry, round_idx, key_data, *targs):
                state, ring = carry
                state = _rejoin(state, round_idx)
                targets, send_ok, dup = targets_and_gate(
                    round_idx, key_data, *targs
                )
                vals = gossip_mod.send_values(state, send_ok)
                fresh = make_df(dup)(vals, targets)
                slot = lax.rem(round_idx, jnp.int32(D))
                arrive = lax.dynamic_index_in_dim(
                    ring, slot, axis=0, keepdims=False
                )
                ring = lax.dynamic_update_index_in_dim(ring, fresh, slot, 0)
                new = gossip_mod.absorb(state, arrive, rumor_target, suppress)
                new = _freeze_dead(life, state, new, round_idx)
                if byz_override is not None:
                    new = byz_override(new, round_idx)
                return (new, ring)

        else:

            def round_fn(state, round_idx, key_data, *targs):
                state = _rejoin(state, round_idx)
                targets, send_ok, dup = targets_and_gate(
                    round_idx, key_data, *targs
                )
                new = gossip_mod.round_from_targets(
                    state, targets, send_ok, n, rumor_target, suppress,
                    make_df(dup),
                )
                new = _freeze_dead(life, state, new, round_idx)
                if byz_override is not None:
                    new = byz_override(new, round_idx)
                return new

    return round_fn, state0, key_data, topo_args


def _make_pool_round_fn(topo: Topology, cfg: SimConfig, base_key: jax.Array, dtype):
    """Offset-pool round for the implicit full topology: the round draws
    cfg.pool_size shared uniform displacements, every node picks one, and
    delivery is pool_size masked rolls (ops/delivery.deliver_pool) — no
    scatter, no sort. This is the delivery mode the north-star benchmark
    measures (~12x the per-round throughput of the scatter path at 1M nodes
    on v5e; bench.py)."""
    n = topo.n
    K = cfg.pool_size
    key_data, key_impl = sampling.key_split(base_key)
    life = _life_dev(cfg, n)
    revive_fn = make_revive_fn(cfg, n, life)
    byz = _byz_dev(cfg, n)
    corrupt_fn = make_byz_send_fn(cfg, byz)
    byz_override = make_byz_override_fn(cfg, byz, life)
    clip_fn = make_robust_clip_fn(cfg)
    trim = cfg.robust_agg == "trim"
    matmul = cfg.delivery == "matmul"

    def deliver_channels(channels, choice, offs):
        """The round's delivery mechanism: masked rolls (pool) or the
        blocked one-hot dot_general over the SAME implied targets
        (matmul — the MXU tier). Integer channels are bitwise-identical
        either way; floats differ only by summation order. robust_agg=
        'trim' swaps in the trimmed pool aggregation (deliver_pool minus
        each receiver's largest-|w| slot channel); config restricts trim
        to delivery='pool'."""
        if trim:
            return delivery_mod.deliver_pool_trimmed(channels, choice, offs)
        if matmul:
            ids = jnp.arange(n, dtype=jnp.int32)
            targets = sampling.targets_pool(choice, offs, ids, n)
            return delivery_mod.deliver_matmul(channels, targets, n)
        return delivery_mod.deliver_pool(channels, choice, offs)

    def _rejoin(state, round_idx):
        if revive_fn is None:
            return state
        return revive_fn(state, round_idx)

    def pool_parts(round_idx, key_data):
        with jax.named_scope("sample"):
            kr = sampling.round_key(sampling.key_join(key_data, key_impl), round_idx)
            offs = sampling.pool_offsets(kr, K, n)
            # Packed draw: one threefry word per 8 nodes instead of one per
            # node — a choice consumes 4 bits, not 32 (sampling.py). Stream-
            # identical to the fused pool kernel's in-kernel draw.
            choice = sampling.pool_choice_packed(kr, n, K)
            gate = sampling.send_gate(kr, n, cfg.fault_rate)
            send_ok = jnp.ones((n,), bool) if gate is True else gate
            if life is not None:
                send_ok = send_ok & faults_mod.alive_at(
                    life.death, round_idx, life.revive
                )
            return choice, offs, send_ok

    if cfg.algorithm == "push-sum":
        state0 = pushsum_mod.init_state(n, dtype, cfg.initial_term_round)
        delta = cfg.resolved_delta
        term_rounds = cfg.term_rounds

        def round_fn(state, round_idx, key_data):
            state = _rejoin(state, round_idx)
            choice, offs, send_ok = pool_parts(round_idx, key_data)
            with jax.named_scope("pushsum_halve"):
                s_send, w_send, s_keep, w_keep = pushsum_mod.halve_and_send(
                    state.s, state.w, send_ok
                )
            if corrupt_fn is not None:
                s_send, w_send = corrupt_fn(
                    s_send, w_send, state, send_ok, round_idx
                )
            with jax.named_scope("pushsum_deliver"):
                inbox = deliver_channels(
                    jnp.stack([s_send, w_send]), choice, offs
                )
            in_s, in_w = inbox[0], inbox[1]
            if clip_fn is not None:
                in_s, in_w = clip_fn(in_s, in_w, w_keep)
            with jax.named_scope("pushsum_absorb"):
                new = pushsum_mod.absorb(
                    state, s_keep, w_keep, in_s, in_w, delta,
                    term_rounds, cfg.termination == "global",
                )
            return _freeze_dead(life, state, new, round_idx)

    else:
        leader = draw_leader(base_key, topo, cfg)
        state0 = gossip_mod.init_state(
            n, leader, leader_counts_receipt=cfg.reference and topo.kind == "full"
        )
        rumor_target = cfg.resolved_rumor_target
        suppress = cfg.resolved_suppress

        def round_fn(state, round_idx, key_data):
            state = _rejoin(state, round_idx)
            choice, offs, send_ok = pool_parts(round_idx, key_data)
            with jax.named_scope("gossip_send"):
                vals = gossip_mod.send_values(state, send_ok)
            with jax.named_scope("gossip_deliver"):
                inbox = deliver_channels(vals[None], choice, offs)[0]
            with jax.named_scope("gossip_absorb"):
                # Suppression is receiver-side (models/gossip.absorb): no
                # pool_lookup backward rolls needed.
                new = gossip_mod.absorb(state, inbox, rumor_target, suppress)
            new = _freeze_dead(life, state, new, round_idx)
            if byz_override is not None:
                new = byz_override(new, round_idx)
            return new

    return round_fn, state0, key_data, ()


def imp_pool_parts(topo: Topology, cfg: SimConfig, round_k, disp_cols, degree):
    """The imp pooled round's sampling, shared (exactly) with its tests.

    Slot selection draws the SAME uniform words the static-graph path does
    (ops/sampling.uniform_bits off the round key, slot = word % degree), so
    WHICH neighbor slot each node samples is identical across delivery
    modes; only the long-range slot's target changes — from the build-time
    static edge to one of the round's K shared pool displacements
    (marginally still uniform over j != i). Returns
    (d_sampled, is_extra, choice, offs, send_ok)."""
    n = topo.n
    bits = sampling.uniform_bits(round_k, n)
    # The same slot selection as the static path, byte for byte — only the
    # "neighbor" rows here hold displacements, with -1 sentineling the extra
    # slot (ops/topology.imp_split), so a sampled -1 IS the extra draw.
    d = sampling.targets_explicit(bits, disp_cols, degree)
    is_extra = (d == -1) & (degree > 0)
    offs = sampling.pool_offsets(round_k, cfg.pool_size, n)
    choice = sampling.pool_choice_packed(
        sampling.imp_choice_key(round_k), n, cfg.pool_size
    )
    send_ok = degree > 0
    gate = sampling.send_gate(round_k, n, cfg.fault_rate)
    if gate is not True:
        send_ok = send_ok & gate
    return d, is_extra, choice, offs, send_ok


def _make_imp_pool_round_fn(
    topo: Topology, cfg: SimConfig, base_key: jax.Array, dtype, split
):
    """Pooled-rewiring round for imp2d/imp3d: lattice edges deliver as
    static stencil rolls, the random long-range slot as K shared per-round
    pool displacements (ops/delivery.deliver_imp_pool) — the whole round is
    rolls and elementwise work, no scatter.

    Semantics: the reference's Imp3D fixes one uniformly random extra
    neighbor per node at build time (program.fs:308-310); this mode re-draws
    it per round from the pool, keeping the same per-node sampling marginals
    (slot uniform over degree; long-range target uniform over j != i up to
    the documented modulo bias) while making the joint per-round — the same
    TPU-first recast the implicit full topology ships as pool sampling
    (ops/sampling.pool_offsets). Convergence equivalence vs the static-iid
    graph is pinned statistically (tests/test_imp_pool.py); per-round cost
    drops from scatter-bound (~12 ns/edge element on v5e — hardware floor
    for random access) to stencil-class."""
    n = topo.n
    key_data, key_impl = sampling.key_split(base_key)
    topo_args = (jnp.asarray(split.disp_cols), jnp.asarray(split.degree))
    lattice_offsets = tuple(int(q) for q in split.lattice_offsets)
    life = _life_dev(cfg, n)
    revive_fn = make_revive_fn(cfg, n, life)
    byz = _byz_dev(cfg, n)
    corrupt_fn = make_byz_send_fn(cfg, byz)
    byz_override = make_byz_override_fn(cfg, byz, life)
    clip_fn = make_robust_clip_fn(cfg)
    matmul = cfg.delivery == "matmul"

    def deliver_channels(channels, d, is_extra, choice, offs):
        """Lattice + pooled long-range delivery: class/pool masked rolls
        (pool) or the blocked one-hot dot_general over the materialized
        per-node targets (matmul). Each sent value lands in exactly one
        slot in both forms, so integer channels are bitwise-identical;
        floats differ only by summation order. Non-senders' displacement
        (d = -1 on the extra slot) resolves to a harmless target — their
        channel values are already zeroed by the send gate."""
        if matmul:
            ids = jnp.arange(n, dtype=jnp.int32)
            disp = jnp.where(is_extra, offs[choice], d)
            targets = jnp.remainder(ids + disp, n)
            return delivery_mod.deliver_matmul(channels, targets, n)
        return delivery_mod.deliver_imp_pool(
            channels, d, is_extra, choice, lattice_offsets, offs
        )

    def _rejoin(state, round_idx):
        if revive_fn is None:
            return state
        return revive_fn(state, round_idx)

    def parts(round_idx, key_data, disp_cols, degree):
        with jax.named_scope("sample"):
            kr = sampling.round_key(
                sampling.key_join(key_data, key_impl), round_idx
            )
            d, is_extra, choice, offs, send_ok = imp_pool_parts(
                topo, cfg, kr, disp_cols, degree
            )
            if life is not None:
                send_ok = send_ok & faults_mod.alive_at(
                    life.death, round_idx, life.revive
                )
            return d, is_extra, choice, offs, send_ok

    if cfg.algorithm == "push-sum":
        state0 = pushsum_mod.init_state(n, dtype, cfg.initial_term_round)
        delta = cfg.resolved_delta
        term_rounds = cfg.term_rounds

        def round_fn(state, round_idx, key_data, *targs):
            state = _rejoin(state, round_idx)
            d, is_extra, choice, offs, send_ok = parts(round_idx, key_data, *targs)
            with jax.named_scope("pushsum_halve"):
                s_send, w_send, s_keep, w_keep = pushsum_mod.halve_and_send(
                    state.s, state.w, send_ok
                )
            if corrupt_fn is not None:
                s_send, w_send = corrupt_fn(
                    s_send, w_send, state, send_ok, round_idx
                )
            with jax.named_scope("pushsum_deliver"):
                inbox = deliver_channels(
                    jnp.stack([s_send, w_send]), d, is_extra, choice, offs
                )
            in_s, in_w = inbox[0], inbox[1]
            if clip_fn is not None:
                in_s, in_w = clip_fn(in_s, in_w, w_keep)
            with jax.named_scope("pushsum_absorb"):
                new = pushsum_mod.absorb(
                    state, s_keep, w_keep, in_s, in_w, delta,
                    term_rounds, cfg.termination == "global",
                )
            return _freeze_dead(life, state, new, round_idx)

    else:
        leader = draw_leader(base_key, topo, cfg)
        state0 = gossip_mod.init_state(n, leader, leader_counts_receipt=False)
        rumor_target = cfg.resolved_rumor_target
        suppress = cfg.resolved_suppress

        def round_fn(state, round_idx, key_data, *targs):
            state = _rejoin(state, round_idx)
            d, is_extra, choice, offs, send_ok = parts(round_idx, key_data, *targs)
            with jax.named_scope("gossip_send"):
                vals = gossip_mod.send_values(state, send_ok)
            with jax.named_scope("gossip_deliver"):
                inbox = deliver_channels(
                    vals[None], d, is_extra, choice, offs
                )[0]
            with jax.named_scope("gossip_absorb"):
                new = gossip_mod.absorb(state, inbox, rumor_target, suppress)
            new = _freeze_dead(life, state, new, round_idx)
            if byz_override is not None:
                new = byz_override(new, round_idx)
            return new

    return round_fn, state0, key_data, topo_args


def _run_reference_walk(topo: Topology, cfg: SimConfig, key, target: int) -> RunResult:
    from . import reference as reference_mod

    _check_dtype(cfg)
    leader = draw_leader(key, topo, cfg)
    final, compile_s, run_s = reference_mod.run_walk(topo, cfg, key, leader, target)
    converged_count = int(jnp.sum(final.conv))
    result = RunResult(
        algorithm=cfg.algorithm,
        topology=topo.kind,
        semantics=cfg.semantics,
        n_requested=topo.n_requested,
        population=topo.n,
        target_count=target,
        rounds=int(final.steps),  # message hops, not synchronous rounds
        converged_count=converged_count,
        converged=converged_count >= target,
        compile_s=compile_s,
        run_s=run_s,
        outcome="converged" if converged_count >= target else "max_rounds",
    )
    ratio = final.s / final.w
    true_mean = (topo.n - 1) / 2.0
    err = jnp.where(final.conv, jnp.abs(ratio - true_mean), 0.0)
    result.true_mean = true_mean
    result.estimate_mae = float(jnp.sum(err) / jnp.maximum(converged_count, 1))
    return result


def _host_done(cfg, life_np, state, rounds: int, target: int) -> bool:
    """Host-side evaluation of the termination predicate against the final
    state — the same rule _done_predicate traces (quorum over live nodes
    under a crash model, converged_count >= target otherwise), for engines
    whose in-kernel done flag is not directly observable."""
    import numpy as np

    conv = np.asarray(state.conv) != 0
    if life_np is None:
        return bool(conv.sum() >= target)
    alive = np.asarray(
        faults_mod.alive_at(life_np.death, rounds - 1, life_np.revive)
    )
    need = int(faults_mod.quorum_need(int(alive.sum()), cfg.quorum))
    return bool((conv & alive).sum() >= need)


def _finalize_result(
    topo, cfg, state, rounds, target, compile_s, run_s,
    done=None, stalled: bool = False, loop=None, collector=None,
    unhealthy_round=None, cancelled: bool = False,
) -> RunResult:
    # Host-side numpy from here down: the run is over, so the single
    # np.asarray fetch per plane costs one device sync the old eager-jnp
    # reductions paid anyway — but zero XLA programs. Eagerly, this block
    # compiled ~2 (gossip) to ~8 (push-sum) one-off programs per cold
    # process, the whole `finalize` bucket wallwalk named (~149 ms on the
    # CPU stand-in — ISSUE 9 satellite); the reported numbers are
    # diagnostics (never trajectory state), computed in float64 now.
    #
    # EXCEPT when the mesh spans OS processes (jax.distributed,
    # parallel/mesh.initialize_distributed): the state arrays are then
    # not host-addressable and np.asarray would raise — every process
    # instead runs the same GLOBAL jnp reductions (replicated scalar
    # out, readable on each process), the ISSUE 15 multi-process path.
    import numpy as np

    addressable = getattr(state.conv, "is_fully_addressable", True)
    if addressable:
        conv_np = np.asarray(state.conv)
        converged_count = int(conv_np.sum())
    else:
        converged_count = int(
            jnp.sum((jnp.asarray(state.conv) != 0).astype(jnp.int32))
        )
    converged = (converged_count >= target) if done is None else bool(done)
    if unhealthy_round is not None:
        # A tripped sentinel overrides everything: the state is corrupt (or
        # conservation broke), so any "converged" verdict it produced is
        # untrusted.
        converged = False
    result = RunResult(
        algorithm=cfg.algorithm,
        topology=topo.kind,
        semantics=cfg.semantics,
        n_requested=topo.n_requested,
        population=topo.n,
        target_count=target,
        rounds=rounds,
        converged_count=converged_count,
        converged=converged,
        compile_s=compile_s,
        run_s=run_s,
        outcome=(
            "unhealthy" if unhealthy_round is not None
            else "converged" if converged
            # The cancel hook is only consulted while unconverged, so a
            # cancelled run is by construction not a converged one.
            else "deadline_exceeded" if cancelled
            else ("stalled" if stalled else "max_rounds")
        ),
        unhealthy_round=unhealthy_round,
    )
    if cfg.algorithm == "push-sum":
        # w == 0 is reachable under rejoin='fresh' (revived nodes restart
        # weightless) and in unhealthy states — guard the ratio so the MAE
        # report never manufactures inf/NaN of its own.
        true_mean = (topo.n - 1) / 2.0
        if addressable:
            s_np = np.asarray(state.s, dtype=np.float64)
            w_np = np.asarray(state.w, dtype=np.float64)
            w_safe = np.where(w_np != 0, w_np, 1.0)
            ratio = np.where(w_np != 0, s_np / w_safe, 0.0)
            err = np.where(conv_np, np.abs(ratio - true_mean), 0.0)
            mae = float(err.sum() / max(converged_count, 1))
        else:
            # Process-spanning state: the same formula as a global jnp
            # reduction (float64 via a local x64 scope — diagnostics
            # only, never trajectory state).
            with jax.experimental.enable_x64():
                s_g = jnp.asarray(state.s).astype(jnp.float64)
                w_g = jnp.asarray(state.w).astype(jnp.float64)
                w_safe = jnp.where(w_g != 0, w_g, 1.0)
                ratio = jnp.where(w_g != 0, s_g / w_safe, 0.0)
                err = jnp.where(
                    jnp.asarray(state.conv) != 0,
                    jnp.abs(ratio - true_mean), 0.0,
                )
                mae = float(jnp.sum(err)) / max(converged_count, 1)
        result.true_mean = true_mean
        import math

        result.estimate_mae = mae if math.isfinite(mae) else None
    if loop is not None:
        result.dispatch_s = loop.dispatch_s
        result.fetch_s = loop.fetch_s
        result.first_dispatch_s = loop.first_dispatch_s
        result.hook_s = loop.hook_s
        result.aux_s = loop.aux_s
        result.chunk_log = loop.chunk_log
        if getattr(loop, "hook_failures", None):
            result.hook_failures = list(loop.hook_failures)
    if collector is not None:
        result.telemetry = collector.finalize()
    return result


def _cancel_fn(deadline: Optional[float]):
    """The run_chunks cancellation hook for an absolute ``time.monotonic``
    deadline (None = no deadline, no hook — the loop is schedule-identical
    to before). Clock-only: legal under buffer donation."""
    if deadline is None:
        return None

    def should_cancel(rounds: int) -> bool:
        return time.monotonic() >= deadline

    return should_cancel


def _run_fused(
    topo: Topology,
    cfg: SimConfig,
    key: jax.Array,
    on_chunk,
    start_state,
    start_round: int,
    interpret: bool,
    variant: str = "stencil",
    on_telemetry=None,
    t_enter: Optional[float] = None,
    deadline: Optional[float] = None,
    probe=None,
) -> RunResult:
    """Chunk loop over a Pallas multi-round engine: one kernel launch per
    cfg.chunk_rounds rounds. ``variant`` picks the kernel family:
    "stencil" — the whole-array VMEM engine (ops/fused.py, offset-structured
    topologies to ~128k aligned nodes); "stencil2" — its tiled VMEM-resident
    big-population extension (ops/fused_stencil.py); "pool" — the
    implicit-full VMEM pool engine (ops/fused_pool.py) whose chunks
    additionally consume the per-round displacement pools; "pool2" — the
    HBM-streaming pool tier past the VMEM cap (ops/fused_pool2.py, state in
    ping/pong HBM planes, streamed through VMEM per tile); "imp" — the
    imp2d/imp3d pooled-long-range engine (ops/fused_imp.py), which also
    consumes per-round choice keys."""
    if t_enter is None:
        t_enter = time.perf_counter()
    from ..ops import fused

    if start_state is not None:
        # COPY the resume state: the padding/astype transforms below are
        # identities on already-aligned float32 arrays, and under buffer
        # donation the first chunk would otherwise consume the CALLER's
        # arrays (models/runner.run applies the same rule).
        start_state = jax.tree.map(
            lambda x: jnp.array(x, copy=True), start_state
        )

    target = cfg.resolved_target_count(topo.n, topo.target_count)

    def extra_args(start, count):
        return ()

    if variant in ("pool", "pool2"):
        from ..ops import fused_pool

        if variant == "pool":
            make_pushsum = fused_pool.make_pushsum_pool_chunk
            make_gossip = fused_pool.make_gossip_pool_chunk
        else:
            from ..ops import fused_pool2

            make_pushsum = fused_pool2.make_pushsum_pool2_chunk
            make_gossip = fused_pool2.make_gossip_pool2_chunk

        def extra_args(start, count):  # noqa: F811
            return (fused_pool.round_offsets(key, start, count, cfg.pool_size, topo.n),)

    elif variant in ("imp", "imp_hbm"):
        from ..ops import fused_imp, fused_pool

        if variant == "imp":
            make_pushsum = fused_imp.make_pushsum_imp_chunk
            make_gossip = fused_imp.make_gossip_imp_chunk
        else:
            from ..ops import fused_imp_hbm

            make_pushsum = fused_imp_hbm.make_pushsum_imp_hbm_chunk
            make_gossip = fused_imp_hbm.make_gossip_imp_hbm_chunk

        def extra_args(start, count):  # noqa: F811
            return (
                fused_pool.round_offsets(key, start, count, cfg.pool_size, topo.n),
                fused_imp.choice_round_keys(key, start, count),
            )

    elif variant == "stencil2":
        from ..ops import fused_stencil

        make_pushsum = fused_stencil.make_pushsum_stencil2_chunk
        make_gossip = fused_stencil.make_gossip_stencil2_chunk
    elif variant == "stencil_hbm":
        from ..ops import fused_stencil_hbm

        make_pushsum = fused_stencil_hbm.make_pushsum_stencil_hbm_chunk
        make_gossip = fused_stencil_hbm.make_gossip_stencil_hbm_chunk
    else:
        make_pushsum = fused.make_pushsum_chunk
        make_gossip = fused.make_gossip_chunk

    if cfg.algorithm == "push-sum":
        chunk_fn, layout = make_pushsum(topo, cfg, interpret=interpret)
        if start_state is not None and jnp.asarray(start_state.s).dtype != jnp.float32:
            # Mirror the strict config-match check at resume (cli.py): a
            # float64 checkpoint silently downcast to the float32-only fused
            # engine would lose precision without a trace.
            raise ValueError(
                "fused engine resume requires a float32 checkpoint, got "
                f"{jnp.asarray(start_state.s).dtype}; resume with "
                "engine='chunked' (matching the checkpoint dtype) instead"
            )
        st = start_state or pushsum_mod.init_state(
            topo.n, jnp.float32, cfg.initial_term_round
        )
        state_dev = (
            fused._pad2d(jnp.asarray(st.s, jnp.float32), layout, 0.0),
            fused._pad2d(jnp.asarray(st.w, jnp.float32), layout, 1.0),
            fused._pad2d(jnp.asarray(st.term, jnp.int32), layout, 0),
            fused._pad2d(jnp.asarray(st.conv).astype(jnp.int32), layout, 0),
        )

        def to_canonical(state_dev):
            s, w, t, c = (x.reshape(-1)[: topo.n] for x in state_dev)
            return pushsum_mod.PushSumState(s=s, w=w, term=t, conv=c != 0)

    else:
        chunk_fn, layout = make_gossip(topo, cfg, interpret=interpret)
        st = start_state or gossip_mod.init_state(
            topo.n,
            draw_leader(key, topo, cfg),
            leader_counts_receipt=cfg.reference and topo.kind == "full",
        )
        state_dev = (
            fused._pad2d(jnp.asarray(st.count, jnp.int32), layout, 0),
            fused._pad2d(jnp.asarray(st.active).astype(jnp.int32), layout, 0),
            fused._pad2d(jnp.asarray(st.conv).astype(jnp.int32), layout, 0),
        )

        def to_canonical(state_dev):
            cnt, act, cv = (x.reshape(-1)[: topo.n] for x in state_dev)
            return gossip_mod.GossipState(count=cnt, active=act != 0, conv=cv != 0)

    K = cfg.chunk_rounds
    telemetry = cfg.telemetry
    if telemetry and variant not in ("stencil", "pool"):
        # Callers gate on this too (run()'s tier selection); defense in
        # depth because a silent arity mismatch here would be cryptic.
        raise ValueError(
            "telemetry counters run in the fused stencil and pool kernels "
            f"only; the {variant!r} tier does not carry the counter block — "
            "use engine='chunked' or a telemetry-capable population"
        )
    if cfg.byzantine_model and variant not in ("stencil", "pool"):
        # Same defense-in-depth as telemetry: the adversary plane is an
        # extra VMEM operand of those two kernels only.
        raise ValueError(
            "the byzantine adversary plane is threaded through the fused "
            f"stencil and pool kernels only; the {variant!r} tier does "
            "not carry it — use engine='chunked'"
        )
    if cfg.robust_agg != "none":
        raise ValueError(
            "robust aggregation runs in the chunked XLA round bodies; "
            "the fused kernels do not implement clip/trim — use "
            "engine='chunked'"
        )

    def chunk_call(state_dev, rnd, done, cap):
        # Keys/offsets are derived INSIDE the jit: per-chunk eager fold_in
        # vmaps cost ~120 ms/chunk over the remote tunnel. The base key is
        # deliberately CLOSED OVER (a baked constant): this loop is
        # single-device/single-key, and passing even a uint32[2] runtime
        # argument instead costs a consistent ~30 ms per dispatch on the
        # axon tunnel (measured on the 1M-node flagship chunk, ~140 ms
        # baked vs ~170 ms as argument).
        keys = fused.round_keys(key, rnd, K)
        outs = chunk_fn(state_dev, keys, *extra_args(rnd, K), rnd, cap)
        new_state, executed = outs[0], outs[1]
        # Early exit (executed short of this chunk's budget) means the
        # kernel's own termination predicate fired; latching it into a
        # carried done flag makes an overshoot dispatch observable as a
        # no-op (executed == 0, the kernel seeds done from the incoming
        # conv plane) — the contract the pipelined driver relies on.
        expected = jnp.minimum(jnp.int32(K), jnp.maximum(cap - rnd, 0))
        ret = (new_state, rnd + executed, done | (executed < expected))
        if telemetry:
            # The in-kernel counter block: [K_pad, 128] with the schema's
            # columns in the first lanes (ops/telemetry.py), a fresh OUTPUT
            # outside the donated state argument.
            ret += (outs[2],)
        return ret

    # Donation aliases each chunk's output planes onto its input's buffers
    # (zero steady-state copies) — legal only when nothing reads retired
    # state: chunk hooks and the watchdog do (models/pipeline.py).
    donate = on_chunk is None and not cfg.stall_chunks
    if probe is not None:
        # Trace-only short-circuit (see run()): the plain jittable chunk,
        # ready to make_jaxpr/lower hardware-free (interpret flag already
        # baked into the kernel builder above). ``variant`` reports which
        # fused tier the dispatch resolved, so the auditor can assert tier
        # coverage without duplicating the routing logic.
        return probe(
            chunk_call,
            (
                state_dev, jnp.int32(start_round), jnp.bool_(False),
                jnp.int32(min(start_round + 1, cfg.max_rounds)),
            ),
            donate=donate,
            variant=variant,
        )
    chunk_j = jax.jit(chunk_call, donate_argnums=(0,) if donate else ())

    rnd0 = jnp.int32(start_round)
    done0_dev = jnp.bool_(False)
    t0 = time.perf_counter()
    setup_s = t0 - t_enter  # engine build + transfers between entry/warmup
    # Warmup executes ONE real round and discards the result (state_dev is
    # untouched — under donation the warmup consumes a copy; round keys are
    # absolute, so the main loop recomputes the same round 0 identically).
    # A zero-round warmup (cap == start) would leave the kernel's active
    # path unexercised, and the axon tunnel defers a ~1 s one-time cost to
    # the first execution that reaches it — which would land inside the
    # timed run loop instead of here.
    warm = chunk_j(
        jax.tree.map(jnp.copy, state_dev) if donate else state_dev,
        rnd0, done0_dev,
        jnp.int32(min(start_round + 1, cfg.max_rounds)),
    )
    int(warm[1])  # sync via data-dependent output (block_until_ready can
    del warm      # return early over the tunnel)
    compile_s = time.perf_counter() - t0

    watchdog = StallWatchdog(cfg.stall_chunks)
    life_np = faults_mod.life_planes(cfg, topo.n)
    life_dev = _life_dev(cfg, topo.n)

    def dispatch(state, rnd, done, round_end):
        return chunk_j(state, rnd, done, jnp.int32(round_end))

    on_retire = None
    if on_chunk is not None:
        def on_retire(rounds, state):
            on_chunk(rounds, to_canonical(state))

    should_stop = None
    if cfg.stall_chunks:
        # The kernel executes full chunks while unconverged, so a stalled
        # topology would otherwise spin to max_rounds. Canonical state,
        # not the raw planes — pool2 packs term+conv in one plane.
        def should_stop(rounds, state):
            return watchdog.no_progress(
                _progress_gap(
                    life_dev, cfg.quorum, target,
                    to_canonical(state).conv, rounds,
                )
            )

    collector = (
        telemetry_mod.Collector(start_round, on_rows=on_telemetry)
        if telemetry else None
    )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=state_dev, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds, stride=K,
        depth=cfg.pipeline_chunks, donate=donate,
        on_retire=on_retire, should_stop=should_stop,
        on_aux=collector.on_aux if collector else None,
        should_cancel=_cancel_fn(deadline),
        step_timing=cfg.step_timing,
        hook_error=("raise" if cfg.strict_checkpoint else "continue"),
    )
    run_s = time.perf_counter() - t1

    t_fin = time.perf_counter()
    final = to_canonical(loop.state)
    done = _host_done(cfg, life_np, final, loop.rounds, target)
    result = _finalize_result(
        topo, cfg, final, loop.rounds, target, compile_s, run_s,
        done=done, stalled=watchdog.stalled, loop=loop,
        collector=collector, cancelled=loop.cancelled,
    )
    result.setup_s = setup_s
    result.finalize_s = time.perf_counter() - t_fin
    return result


# Graceful engine degradation (run()'s fallback ladder). Environmental
# failures — a Pallas/XLA compile error, OOM, a missing collective
# implementation, a dropped device tunnel — surface as these exception
# types; config-contract errors stay ValueError and always fail fast (a
# silently degraded answer to an invalid request would mask the bug).
# OSError is deliberately NOT here: inside _run_resolved it comes from
# user hooks (checkpoint writes, log appends — e.g. a full disk), which no
# other engine rung can fix; re-simulating on their account would only
# replay the same I/O failure.
_DEGRADABLE_ERRORS = (
    RuntimeError,  # jaxlib XlaRuntimeError derives from it (compile/OOM)
    ImportError,  # missing shard_map / Pallas on old runtimes
    MemoryError,
    NotImplementedError,
)

# Substrings marking an error as a TRANSIENT dispatch failure (gRPC-status
# vocabulary the TPU runtime uses): retried on the same rung with
# exponential backoff before the ladder moves down.
_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
)
_TRANSIENT_RETRIES = 3


def _strict_engine(cfg: SimConfig) -> bool:
    """cfg.strict_engine, overridable either way by the
    GOSSIP_TPU_STRICT_ENGINE env var (scripts/tier1.sh exports 1 so CI
    never silently degrades; the chaos job exercises the ladder with 0)."""
    env = os.environ.get("GOSSIP_TPU_STRICT_ENGINE", "")
    if env != "":
        return env not in ("0", "false", "no")
    return cfg.strict_engine


def _engine_desc(cfg: SimConfig) -> str:
    return f"engine={cfg.engine}/devices={cfg.n_devices or 1}"


def _engine_ladder(cfg: SimConfig) -> list:
    """The documented fallback ladder, most- to least-capable:

        requested config
          -> engine='chunked' (same devices)   [fused/auto kernel failures]
          -> engine='chunked', single device   [sharded/collective failures]

    Every step preserves semantics: the chunked XLA engines are the
    reference implementations the fused kernels are pinned against, and
    the sharded engine is stream-identical to single-device (gossip
    bitwise; push-sum up to documented reassociation on the scatter path).
    """
    rungs = [cfg]
    c = cfg
    if c.engine != "chunked":
        c = dataclasses.replace(c, engine="chunked")
        rungs.append(c)
    if c.n_devices is not None and c.n_devices > 1:
        c = dataclasses.replace(c, n_devices=None)
        rungs.append(c)
    return rungs


def _resolve_plan_auto(topo: Topology, cfg: SimConfig,
                       on_event: Optional[Callable] = None) -> SimConfig:
    """plan='auto' (ISSUE 17): consult the measured cost model
    (analysis/cost.py — candidates enumerated by the SAME refusal rules
    this dispatch applies, scored from the calibrated floors in
    analysis/calibration.json) and return the winner's config: plan='hand'
    plus the winner's forcing overrides, so the resolved run takes the
    EXISTING dispatch path — the ladder, probe hook, and auditor all see
    an ordinary hand config. The ranked table is reported through
    ``on_event("plan-chosen", ...)`` (candidates, scores, winner); a
    request no candidate serves raises ValueError with every refusal
    reason, mirroring the hand dispatch's failure mode."""
    from ..analysis import cost

    decision = cost.choose(topo, cfg)
    record = decision.event_record()
    print(
        f"plan-chosen: {record['winner']} "
        f"(~{record['predicted_us_per_round']:.0f} us/round predicted; "
        f"{len(record['candidates'])} candidate(s), "
        f"{len(record['refused'])} refused)",
        file=sys.stderr,
    )
    if on_event is not None:
        on_event("plan-chosen", **record)
    return dataclasses.replace(
        cfg, plan="hand", **decision.winner.override_dict
    )


def run(
    topo: Topology,
    cfg: SimConfig,
    key: Optional[jax.Array] = None,
    on_chunk: Optional[Callable[[int, object], None]] = None,
    start_state=None,
    start_round: int = 0,
    on_telemetry: Optional[Callable[[int, object], None]] = None,
    on_event: Optional[Callable] = None,
    deadline: Optional[float] = None,
    probe=None,
) -> RunResult:
    """Run one simulation to convergence (or cfg.max_rounds) — the public
    entry every caller (CLI, suite, tests) goes through.

    ``deadline`` (absolute ``time.monotonic`` seconds, ISSUE 8) bounds how
    long the run may hold the engine: the chunk driver consults it at
    every retired boundary and a fired deadline ends the run within one
    chunk with ``outcome="deadline_exceeded"`` — partial state and
    telemetry, exact ``rounds``, the engine free for the next caller. No
    deadline (None) leaves the loop schedule-identical to before.

    Engine resilience: environmental failures (_DEGRADABLE_ERRORS — compile
    errors, OOM, missing runtime features, dropped device connections) walk
    the documented fallback ladder (_engine_ladder: fused->chunked,
    sharded->single-device) instead of killing the run; transient dispatch
    errors (_TRANSIENT_MARKERS) retry the same rung with exponential
    backoff first. Each rung change is printed to stderr, reported through
    ``on_event("engine-degraded", ...)`` (the CLI wires this to the
    run-event log, utils/events.py), and recorded in
    ``RunResult.degradations``. ``cfg.strict_engine`` / the
    GOSSIP_TPU_STRICT_ENGINE env var restore fail-fast. ValueError —
    config-contract violations — always fails fast: a degraded answer to an
    invalid request would mask the bug.

    ``probe(chunk_fn, args, donate=...)``, when given, short-circuits the
    run with the probe's return value after engine construction but BEFORE
    warmup/execution: the probe receives the chunk program (jitted for the
    sharded compositions, the plain jittable for the single-device paths),
    ready-to-trace arguments, and the donation decision the run would have
    made — the static auditor (cop5615_gossip_protocol_tpu/analysis) walks
    every engine cell hardware-free through this hook. The degradation
    ladder does not apply under a probe (a probed rung failing is the
    finding, not a condition to recover from).

    See _run_resolved for the hook/resume contracts.
    """
    if cfg.plan == "auto":
        # Resolve BEFORE the probe short-circuit so the static auditor
        # audits the autotuned plan's wire exactly as it does hand-picked
        # ones, and before the ladder so degradation rungs derive from
        # the chosen plan.
        cfg = _resolve_plan_auto(topo, cfg, on_event)
    if probe is not None:
        return _run_resolved(
            topo, cfg, key=key, on_chunk=on_chunk,
            start_state=start_state, start_round=start_round,
            on_telemetry=on_telemetry, deadline=deadline, probe=probe,
        )
    strict = _strict_engine(cfg)
    rungs = _engine_ladder(cfg)
    degradations: list = []
    backoff = float(os.environ.get("GOSSIP_TPU_RETRY_BASE_S", "0.5") or 0.5)
    for i, rung in enumerate(rungs):
        attempt = 0
        while True:
            try:
                result = _run_resolved(
                    topo, rung, key=key, on_chunk=on_chunk,
                    start_state=start_state, start_round=start_round,
                    on_telemetry=on_telemetry, deadline=deadline,
                )
                if degradations:
                    result.degradations = degradations
                return result
            except _DEGRADABLE_ERRORS as e:
                if strict:
                    raise
                msg = f"{type(e).__name__}: {e}"
                if any(m in str(e) for m in _TRANSIENT_MARKERS) and (
                    attempt < _TRANSIENT_RETRIES
                ):
                    attempt += 1
                    delay = backoff * 2 ** (attempt - 1)
                    print(
                        f"transient engine error (retry {attempt}/"
                        f"{_TRANSIENT_RETRIES} in {delay:.1f}s): {msg}",
                        file=sys.stderr,
                    )
                    time.sleep(delay)
                    continue
                if i == len(rungs) - 1:
                    raise  # bottom of the ladder — nothing left to try
                step = {
                    "from": _engine_desc(rung),
                    "to": _engine_desc(rungs[i + 1]),
                    "reason": msg[:500],
                    "transient_retries": attempt,
                }
                degradations.append(step)
                print(
                    f"engine degraded ({step['from']} -> {step['to']}): "
                    f"{msg}",
                    file=sys.stderr,
                )
                if on_event is not None:
                    on_event("engine-degraded", **step)
                break
    raise AssertionError("unreachable: ladder loop exits by return/raise")


def _run_resolved(
    topo: Topology,
    cfg: SimConfig,
    key: Optional[jax.Array] = None,
    on_chunk: Optional[Callable[[int, object], None]] = None,
    start_state=None,
    start_round: int = 0,
    on_telemetry: Optional[Callable[[int, object], None]] = None,
    deadline: Optional[float] = None,
    probe=None,
) -> RunResult:
    """One attempt at one ladder rung: dispatch to the engine cfg names and
    run to completion on it.

    ``on_chunk(rounds_done, state)`` fires at every chunk boundary. It is
    the CHECKPOINT hook: it reads retired device state, which forces buffer
    donation off and serializes the boundary (models/pipeline.py) — use it
    only for state capture (checkpoints, debugging). Counters and
    trajectories belong to the telemetry plane (``cfg.telemetry`` /
    ``RunResult.telemetry``, ops/telemetry.py), which accumulates per-round
    rows on device and keeps donation + speculative pipelining intact.

    ``start_state``/``start_round`` resume a checkpointed run: round keys
    are derived from the absolute round index, so the resumed trajectory is
    bitwise the one the original run would have taken (utils/checkpoint.py).
    """
    t_enter = time.perf_counter()  # setup_s bracket start (RunResult)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    if topo.partial and not (
        cfg.engine == "fused"
        and cfg.n_devices is not None
        and cfg.n_devices > 1
    ):
        raise ValueError(
            "a host-sharded topology build (build_topology rows=...) "
            "carries only its own adjacency row slice; it serves the "
            "offset-structured fused sharded compositions only "
            "(engine='fused', n_devices > 1 — they read the analytic "
            "displacement classes, never a neighbor row). The chunked/"
            "single-device engines gather whole neighbor tensors — build "
            "the full adjacency (rows=None) for them"
        )
    if cfg.n_devices is not None and cfg.n_devices > 1:
        if cfg.reference and cfg.algorithm == "push-sum":
            raise ValueError(
                "reference-semantics push-sum is a single random walk "
                "(one message in flight) and cannot be sharded; drop "
                "n_devices or use batched semantics"
            )
        if cfg.engine == "fused":
            if cfg.telemetry:
                raise ValueError(
                    "telemetry counters run in the single-device fused "
                    "stencil/pool kernels and the chunked/sharded XLA "
                    "engines; the sharded fused compositions do not carry "
                    "the counter block — drop the engine override (the "
                    "sharded XLA engine psums the block in-trace)"
                )
            if cfg.mass_tolerance is not None:
                raise ValueError(
                    "the health sentinel (--mass-tolerance) runs in the "
                    "chunked and sharded XLA round bodies; the sharded "
                    "fused compositions do not carry it — drop the engine "
                    "override"
                )
            if cfg.byzantine_model:
                raise ValueError(
                    "the byzantine adversary plane is threaded through "
                    "the chunked engine and the single-device fused "
                    "stencil/pool kernels; the sharded fused compositions "
                    "do not carry the plane — drop the engine override"
                )
            if cfg.robust_agg != "none":
                raise ValueError(
                    "robust aggregation (--robust-agg) bounds inboxes in "
                    "the chunked XLA round bodies only; the sharded fused "
                    "compositions do not carry it — drop the engine "
                    "override"
                )
            if topo.kind in ("imp2d", "imp3d") and cfg.delivery == "matmul":
                raise ValueError(
                    "engine='fused' with delivery='matmul' on imp kinds "
                    "is not served: the imp x HBM x sharded composition "
                    "delivers by lattice/pool class rolls — use "
                    "delivery='pool' for that composition, or the "
                    "single-device chunked engine for the matmul tier"
                )
            if topo.implicit and cfg.delivery in ("pool", "matmul"):
                # Implicit-full pool compositions, tiered like the
                # single-device engines: the VMEM replicated composition
                # (VERDICT r3 #1 — one all_gather of the state planes per
                # super-step, the single-device pool kernel per shard)
                # while the population fits its kernel's residency cap,
                # the replicated-pool2 composition past it (ROADMAP item
                # 1 — the pool2 zero-send-plane HBM pipeline per shard,
                # ONE all_gather of the compact windowed send summaries
                # per round, aggregate ceiling >= 2^28). Both bitwise the
                # engine they shard.
                from ..parallel.fused_pool_sharded import (
                    plan_fused_pool_sharded,
                    run_fused_pool_sharded,
                )
                from ..parallel.pool2_sharded import (
                    plan_pool2_sharded,
                    run_pool2_sharded,
                )

                if cfg.delivery == "matmul":
                    # The matmul tier's sharded home is the replicated-
                    # pool2 composition (per-shard one-hot MXU blend after
                    # its one all_gather); the VMEM replicated composition
                    # keeps the roll formulation.
                    plan_vmem = (
                        "the VMEM replicated pool composition serves "
                        "delivery='pool'; the matmul tier's sharded home "
                        "is the replicated-pool2 composition"
                    )
                else:
                    plan_vmem = plan_fused_pool_sharded(
                        topo, cfg, cfg.n_devices
                    )
                if not isinstance(plan_vmem, str):
                    return run_fused_pool_sharded(
                        topo, cfg, key=key, on_chunk=on_chunk,
                        start_state=start_state, start_round=start_round,
                        deadline=deadline, probe=probe,
                    )
                plan_p2 = plan_pool2_sharded(topo, cfg, cfg.n_devices)
                if not isinstance(plan_p2, str):
                    return run_pool2_sharded(
                        topo, cfg, key=key, on_chunk=on_chunk,
                        start_state=start_state, start_round=start_round,
                        deadline=deadline, probe=probe,
                    )
                raise ValueError(
                    f"engine='fused' with n_devices={cfg.n_devices} "
                    f"unavailable: VMEM pool composition: {plan_vmem}; "
                    f"replicated-pool2 composition: {plan_p2}"
                )
            if topo.kind in ("imp2d", "imp3d") and cfg.delivery == "pool":
                # imp x HBM x sharded (ROADMAP item 1): lattice classes by
                # halo windows (batched ppermute / in-kernel DMA), the
                # pooled long-range classes from one all_gather of the
                # windowed send summaries per round — bitwise the
                # single-device fused_imp_hbm engine. Raises with the plan
                # reason when the composition cannot serve the config.
                from ..parallel.fused_imp_hbm_sharded import (
                    run_imp_hbm_sharded,
                )

                return run_imp_hbm_sharded(
                    topo, cfg, key=key, on_chunk=on_chunk,
                    start_state=start_state, start_round=start_round,
                    deadline=deadline, probe=probe,
                )
            # Fused x sharded lattice compositions, tiered like the
            # single-device engines: per-shard multi-round Pallas chunks
            # under shard_map with halo ppermutes at super-step boundaries
            # — VMEM-resident (parallel/fused_sharded.py) while the shard
            # fits its plane budget, HBM-streaming
            # (parallel/fused_hbm_sharded.py) past it, so sharding
            # MULTIPLIES the single-chip population ceiling (VERDICT r4
            # #1) instead of capping shards at VMEM. Both support
            # termination='global' via the psum'd per-round unstable
            # stream (VERDICT r4 #8). Raises with both reasons when
            # neither has an exact plan.
            from ..parallel.fused_hbm_sharded import (
                plan_stencil_hbm_sharded,
                run_stencil_hbm_sharded,
            )
            from ..parallel.fused_sharded import (
                plan_fused_sharded,
                run_fused_sharded,
            )

            plan_vmem = plan_fused_sharded(topo, cfg, cfg.n_devices)
            if not isinstance(plan_vmem, str):
                return run_fused_sharded(
                    topo, cfg, key=key, on_chunk=on_chunk,
                    start_state=start_state, start_round=start_round,
                    deadline=deadline, probe=probe,
                )
            plan_hbm = plan_stencil_hbm_sharded(topo, cfg, cfg.n_devices)
            if not isinstance(plan_hbm, str):
                return run_stencil_hbm_sharded(
                    topo, cfg, key=key, on_chunk=on_chunk,
                    start_state=start_state, start_round=start_round,
                    deadline=deadline, probe=probe,
                )
            raise ValueError(
                f"engine='fused' with n_devices={cfg.n_devices} "
                f"unavailable: VMEM composition: {plan_vmem}; "
                f"HBM-streaming composition: {plan_hbm}"
            )
        if cfg.delivery == "matmul":
            raise ValueError(
                "delivery='matmul' has no sharded XLA path (the chunked "
                "sharded engine delivers pool rounds by global rolls / "
                "scatter, which would break the matmul tier's zero-scatter "
                "contract); the MXU tier runs on the single-device chunked "
                "engine, the fused pool kernels, and the replicated-pool2 "
                "composition (engine='fused') — drop n_devices or use "
                "delivery='pool'"
            )
        if cfg.byzantine_model or cfg.robust_agg != "none":
            raise ValueError(
                "the byzantine adversary plane and robust aggregation run "
                "on the single-device chunked engine (and, for the plane, "
                "the fused stencil/pool kernels); the sharded XLA "
                "composition does not thread them through its shard-mapped "
                "round body — drop n_devices"
            )
        # delivery='stencil' is legal under sharding: the halo-exchange plan
        # (parallel/halo.py) implements it as local shifts + boundary
        # ppermutes; run_sharded raises if no exact plan exists.
        from ..parallel.sharded import run_sharded  # circular-import guard

        return run_sharded(
            topo, cfg, key=key, on_chunk=on_chunk,
            start_state=start_state, start_round=start_round,
            on_telemetry=on_telemetry, deadline=deadline, probe=probe,
        )
    target = cfg.resolved_target_count(topo.n, topo.target_count)
    if cfg.reference and cfg.algorithm == "push-sum":
        if cfg.delivery in ("stencil", "pool"):
            raise ValueError(
                f"delivery={cfg.delivery!r} does not apply to "
                "reference-semantics push-sum — the single-walk simulator "
                "has no batched delivery step"
            )
        if cfg.engine == "fused":
            raise ValueError(
                "engine='fused' does not apply to reference-semantics "
                "push-sum — the single-walk simulator (one message in "
                "flight) has no multi-round batched kernel; drop the "
                "engine override or use batched semantics"
            )
        if deadline is not None:
            raise ValueError(
                "deadline cancellation runs at chunk boundaries; the "
                "reference-semantics single-walk simulator has none — "
                "drop the deadline or use batched semantics"
            )
        if probe is not None:
            raise ValueError(
                "reference-semantics push-sum has no chunk program to "
                "probe; audit batched semantics instead"
            )
        # Reference fidelity: single-walk push-sum (one message in flight,
        # SURVEY.md §3.3). Gossip has no such mode — the reference's gossip
        # is all informed nodes spamming concurrently, which the batched
        # round (one send per informed node per round) already models.
        return _run_reference_walk(topo, cfg, key, target)

    if cfg.engine != "chunked":
        # Two Pallas engines share one dispatch: the pool engine for pool
        # delivery on the implicit full topology (ops/fused_pool.py — the
        # flagship benchmark path, ~2.7x the chunked pool round on v5e),
        # the stencil engine otherwise (ops/fused.py). termination='global'
        # rides the same dispatch: every push-sum kernel implements the
        # global-residual criterion in-kernel (VERDICT r3 #5); gossip can
        # never reach here with it (SimConfig rejects the combination).
        if cfg.delivery in ("pool", "matmul"):
            if topo.implicit:
                from ..ops import fused_pool

                # VMEM-resident engine up to its cap; the HBM-streaming
                # tier (ops/fused_pool2.py) past it — per-node round cost
                # stays in the fused class instead of cliffing onto the
                # chunked XLA path (VERDICT r2 #2). Both kernels serve
                # delivery='matmul' too: the lane-rotation blend lowers to
                # one-hot 128x128 MXU tiles (ops/fused_pool._lane_blend_mm)
                # while sampling and trajectories stay bitwise the pool
                # formulation's.
                if topo.n <= fused_pool.MAX_POOL_NODES:
                    variant = "pool"
                    reason = fused_pool.pool_fused_support(topo, cfg)
                else:
                    from ..ops import fused_pool2

                    variant = "pool2"
                    reason = fused_pool2.pool2_support(topo, cfg)
            elif cfg.delivery == "matmul":
                # The imp kernels deliver by lattice/pool class rolls; the
                # matmul tier's fused home is the implicit-full pool
                # kernels. auto demotes to the chunked engine (which runs
                # the one-hot dot_general round); engine='fused' fails
                # loudly below.
                variant = "imp"
                reason = (
                    "the fused imp tiers deliver by lattice/pool class "
                    "rolls; delivery='matmul' runs the chunked engine on "
                    "imp kinds (the MXU tier's fused home is the "
                    "implicit-full pool kernels)"
                )
            else:
                from ..ops import fused_imp

                # VMEM imp engine up to its plane budget; the HBM-streaming
                # tier (ops/fused_imp_hbm.py) past it — imp2d/imp3d no
                # longer cliff onto the chunked path at scale (VERDICT r3
                # #2a).
                variant = "imp"
                reason = fused_imp.imp_fused_support(topo, cfg)
                if reason is not None:
                    from ..ops import fused_imp_hbm

                    hbm_reason = fused_imp_hbm.imp_hbm_support(topo, cfg)
                    if hbm_reason is None:
                        variant, reason = "imp_hbm", None
            auto_ok = reason is None
        else:
            from ..ops import fused

            # The proven whole-array engine keeps its domain; the tiled
            # stencil2 engine takes over where v1 refuses (population past
            # 128k, wrap topologies at unaligned n); past stencil2's VMEM
            # budget the HBM-streaming tier serves every arithmetic
            # lattice kind (torus3d/ring wrap columns; grid2d/grid3d/
            # line/ref2d boundary masks) so the grid-scale rows never
            # cliff onto the chunked path.
            reason_v1 = fused.fused_support(topo, cfg)
            if reason_v1 is None:
                variant, reason = "stencil", None
            else:
                from ..ops import fused_stencil

                variant = "stencil2"
                reason = fused_stencil.stencil2_support(topo, cfg)
                if reason is not None:
                    from ..ops import fused_stencil_hbm

                    hbm_reason = fused_stencil_hbm.stencil_hbm_support(topo, cfg)
                    if hbm_reason is None:
                        variant, reason = "stencil_hbm", None
            # Explicit delivery='stencil' is the same formulation the fused
            # stencil engines execute — it participates in auto-fusing just
            # like explicit delivery='pool' does on the pool branch (only
            # 'scatter' pins the XLA path).
            auto_ok = reason is None and cfg.delivery in ("auto", "stencil")
        if cfg.telemetry and reason is None and variant not in (
            "stencil", "pool"
        ):
            # The counter block is implemented in the VMEM-resident stencil
            # and pool kernels; the streaming HBM/imp tiers do not carry it.
            # Under engine='auto' this demotes the run to the chunked XLA
            # engine (which always supports telemetry); engine='fused'
            # fails loudly below.
            reason = (
                "telemetry counters run in the fused stencil/pool kernels "
                f"only (selected tier: {variant!r})"
            )
            auto_ok = False
        if cfg.mass_tolerance is not None and reason is None:
            # The health sentinel reduces over every round's state inside
            # the XLA while body; the Pallas tiers do not carry it. Under
            # engine='auto' this demotes the run to the chunked engine;
            # engine='fused' fails loudly below.
            reason = (
                "the health sentinel (--mass-tolerance) runs in the "
                "chunked/sharded XLA round bodies only"
            )
            auto_ok = False
        if cfg.byzantine_model and reason is None and variant not in (
            "stencil", "pool"
        ):
            # The adversary plane rides as an extra VMEM operand in the
            # whole-array stencil and pool kernels; the streaming HBM/imp
            # tiers do not thread it. auto demotes to the chunked engine;
            # engine='fused' fails loudly below.
            reason = (
                "the byzantine adversary plane rides the fused "
                f"stencil/pool kernels only (selected tier: {variant!r}); "
                "other tiers run it on the chunked engine"
            )
            auto_ok = False
        if cfg.robust_agg != "none" and reason is None:
            # clip/trim bound contributions in the XLA round bodies; no
            # fused kernel implements them. auto demotes; engine='fused'
            # fails loudly below.
            reason = (
                "robust aggregation (--robust-agg) bounds inboxes in the "
                "chunked XLA round bodies only"
            )
            auto_ok = False
        if cfg.engine == "fused":
            if variant != "pool" and cfg.delivery == "scatter":
                raise ValueError(
                    "engine='fused' delivers via the stencil formulation "
                    "only; delivery='scatter' would be silently ignored — "
                    "use delivery='auto'/'stencil' or engine='chunked'"
                )
            if reason is not None:
                raise ValueError(f"engine='fused' unavailable: {reason}")
            # Explicit fused runs everywhere: interpreted off-TPU (tests).
            return _run_fused(
                topo, cfg, key, on_chunk, start_state, start_round,
                interpret=jax.default_backend() != "tpu", variant=variant,
                on_telemetry=on_telemetry, t_enter=t_enter,
                deadline=deadline, probe=probe,
            )
        # auto: compiled engines on TPU only — interpret mode would make CPU
        # runs slower, and the chunked XLA path is already fast there.
        if auto_ok and jax.default_backend() == "tpu":
            return _run_fused(
                topo, cfg, key, on_chunk, start_state, start_round,
                interpret=False, variant=variant,
                on_telemetry=on_telemetry, t_enter=t_enter,
                deadline=deadline, probe=probe,
            )

    round_fn, state0, key_data, topo_args = make_round_fn(topo, cfg, key)
    has_ring = cfg.delay_rounds > 0  # carry is (state, delay ring)

    def proto_of(carry_state):
        return carry_state[0] if has_ring else carry_state

    life_np = faults_mod.life_planes(cfg, topo.n)
    life_dev = _life_dev(cfg, topo.n)
    done_fn = _done_predicate(cfg, life_dev, target)
    done0 = False
    if start_state is not None:
        if has_ring:
            raise ValueError(
                "resume with delay_rounds > 0 is unsupported: the in-flight "
                "delivery ring is not checkpointed, so the resumed "
                "trajectory could not be bitwise-faithful"
            )
        # COPY, not asarray: on jax-array inputs asarray is identity, and
        # under buffer donation the first chunk would consume the CALLER's
        # arrays (resume callers — cli --resume, hooks capturing state —
        # still hold references).
        state0 = jax.tree.map(lambda x: jnp.array(x, copy=True), start_state)
        # Seed the loop predicate from the resumed state: a checkpoint taken
        # at/after convergence must execute ZERO further rounds, matching the
        # fused kernels (which seed their done flag from the incoming conv
        # plane) — otherwise the resumed trajectory gains a phantom round.
        # Same predicate the original run evaluated after its last round.
        done0 = _host_done(cfg, life_np, state0, start_round, target)

    # Telemetry plane (ops/telemetry.py): the while body additionally
    # writes one float32 counter row per executed round into a fixed
    # (chunk_rounds, N_COLS) buffer created INSIDE the chunk — a fresh
    # output outside the donated carry, returned alongside the predicate
    # scalars and fetched asynchronously by the driver. A Python-level
    # flag, so telemetry=False traces the identical program as before.
    telemetry = cfg.telemetry
    row_fn = (
        telemetry_mod.make_row_fn(topo, cfg, key) if telemetry else None
    )
    stride = cfg.chunk_rounds

    # Health sentinel (cfg.mass_tolerance, push-sum only — SimConfig
    # validates): every executed round additionally reduces a non-finite
    # flag over (s, w) (and the delay ring) and the mass-conservation
    # residual |Σw − n| against the tolerance. The first round either check
    # trips latches into a ``health`` int32 scalar riding the carry next to
    # the done flag (NEVER = healthy) and forces termination — the driver
    # reports outcome="unhealthy" with the offending round instead of
    # converging wrong or spinning to max_rounds. A Python-level flag:
    # sentinel off traces the bitwise-identical program.
    sentinel = cfg.mass_tolerance is not None
    never_i32 = jnp.int32(faults_mod.NEVER)
    if sentinel:
        tol = cfg.mass_tolerance

        def sentinel_bad(carry_state):
            st = proto_of(carry_state)
            finite = jnp.isfinite(st.s).all() & jnp.isfinite(st.w).all()
            total_w = jnp.sum(st.w)
            if has_ring:
                ring = carry_state[1]
                finite = finite & jnp.isfinite(ring).all()
                # In-flight delivery mass counts: conservation holds over
                # state + ring (ops/faults.py docstring).
                total_w = total_w + jnp.sum(ring[:, 1, :])
            resid = jnp.abs(total_w - jnp.asarray(topo.n, st.w.dtype))
            return (~finite) | (resid > jnp.asarray(tol, st.w.dtype))

    def chunk(state, rnd, done, *rest):
        if sentinel:
            health, round_end, key_data = rest[0], rest[1], rest[2]
            targs = rest[3:]
        else:
            round_end, key_data = rest[0], rest[1]
            targs = rest[2:]
        rnd_in = rnd  # loop-entry round: telemetry rows index from here
        buf_i = 4 if sentinel else 3

        def cond(c):
            return jnp.logical_and(~c[2], c[1] < round_end)

        def body(c):
            s, r = c[0], c[1]
            s = round_fn(s, r, key_data, *targs)
            d = done_fn(proto_of(s), r)
            if sentinel:
                h = c[3]
                h = jnp.where(
                    (h == never_i32) & sentinel_bad(s), r, h
                )
                d = d | (h != never_i32)
                out = (s, r + 1, d, h)
            else:
                out = (s, r + 1, d)
            if telemetry:
                row = row_fn(proto_of(s), r, key_data)
                out += (lax.dynamic_update_index_in_dim(
                    c[buf_i], row, r - rnd_in, 0
                ),)
            return out

        carry = (state, rnd, done)
        if sentinel:
            carry += (health,)
        if telemetry:
            carry += (jnp.zeros((stride, telemetry_mod.N_COLS), jnp.float32),)
        return lax.while_loop(cond, body, carry)

    # Donation: steady-state chunks alias their output state onto the input
    # buffers (zero copies). Off when retired state must stay readable —
    # chunk hooks and the stall watchdog (models/pipeline.py docstring).
    donate = on_chunk is None and not cfg.stall_chunks
    if probe is not None:
        # Trace-only short-circuit (see run()): hands the probe the PLAIN
        # jittable chunk — before the warm-engine pool build, so auditor
        # traces never occupy pool LRU slots or skew its metrics.
        h0 = never_i32 if sentinel else None
        pre = (h0,) if sentinel else ()
        return probe(
            chunk,
            (state0, jnp.int32(start_round), jnp.bool_(done0))
            + pre
            + (jnp.int32(min(start_round + 1, cfg.max_rounds)), key_data)
            + topo_args,
            donate=donate,
        )
    # Warm-engine pool (serving/pool.py): the jitted chunk is cached under
    # the canonical engine key (serving/keys.py — seed excluded: key
    # material and topology tensors ride the chunk arguments; crash models
    # re-pin the seed via the fault class, whose planes ARE baked
    # constants), so repeated same-shape runs — suite grids, serving
    # fallbacks, CI reruns — skip retracing. The donate flag splits the
    # key: donating and non-donating wrappers compile differently.
    from ..serving import keys as keys_mod
    from ..serving import pool as pool_mod

    chunk_j, _ = pool_mod.default_pool().get_or_build(
        ("run-chunk", keys_mod.canonical_key(cfg, topo), donate),
        lambda: jax.jit(chunk, donate_argnums=(0,) if donate else ()),
    )
    rnd0 = jnp.int32(start_round)
    done0_dev = jnp.bool_(done0)
    health0 = never_i32 if sentinel else None

    def _chunk_args(health, round_end):
        pre = (health,) if sentinel else ()
        return pre + (jnp.int32(round_end), key_data) + topo_args

    t0 = time.perf_counter()
    setup_s = t0 - t_enter  # round-fn/plane/state builds + transfers
    # Warmup runs ONE real round and DISCARDS the result — the timed loop
    # recomputes round 0 from the original state on the same absolute-round
    # key stream, so run_s covers every round that `rounds` counts (same
    # accounting rule as _run_fused). Under donation the warmup consumes a
    # COPY so state0 stays live for the timed loop. A zero-round warmup
    # would leave the while body unexecuted, and the axon tunnel defers a
    # one-time cost to the first execution that reaches it — which would
    # land inside the timed loop. Clamped so max_rounds still bounds the
    # trajectory.
    warm = chunk_j(
        jax.tree.map(jnp.copy, state0) if donate else state0,
        rnd0, done0_dev,
        *_chunk_args(health0, min(start_round + 1, cfg.max_rounds)),
    )
    int(warm[1])  # data-dependent sync; block_until_ready can return early
    del warm
    compile_s = time.perf_counter() - t0

    watchdog = StallWatchdog(cfg.stall_chunks)

    if sentinel:
        def dispatch(state, rnd, done, health, round_end):
            return chunk_j(state, rnd, done, *_chunk_args(health, round_end))
    else:
        def dispatch(state, rnd, done, round_end):
            return chunk_j(state, rnd, done, *_chunk_args(None, round_end))

    on_retire = None
    if on_chunk is not None:
        def on_retire(rounds, state):
            on_chunk(rounds, proto_of(state))

    should_stop = None
    if cfg.stall_chunks:
        def should_stop(rounds, state):
            return watchdog.no_progress(
                _progress_gap(
                    life_dev, cfg.quorum, target,
                    proto_of(state).conv, rounds,
                )
            )

    collector = (
        telemetry_mod.Collector(start_round, on_rows=on_telemetry)
        if telemetry else None
    )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=state0, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds,
        stride=cfg.chunk_rounds, depth=cfg.pipeline_chunks, donate=donate,
        on_retire=on_retire, should_stop=should_stop,
        on_aux=collector.on_aux if collector else None,
        health0=health0,
        should_cancel=_cancel_fn(deadline),
        step_timing=cfg.step_timing,
        hook_error=("raise" if cfg.strict_checkpoint else "continue"),
    )
    run_s = time.perf_counter() - t1

    unhealthy_round = None
    if sentinel and loop.health is not None and loop.health != int(never_i32):
        unhealthy_round = int(loop.health)

    t_fin = time.perf_counter()
    result = _finalize_result(
        topo, cfg, proto_of(loop.state), loop.rounds, target,
        compile_s, run_s, done=loop.done, stalled=watchdog.stalled,
        loop=loop, collector=collector, unhealthy_round=unhealthy_round,
        cancelled=loop.cancelled,
    )
    result.setup_s = setup_s
    result.finalize_s = time.perf_counter() - t_fin
    return result
