"""Speculative chunk pipelining — the shared host-side chunk-loop driver.

Every execution engine runs rounds in jit'd chunks, and before this module
each paid a blocking host sync per chunk: dispatch chunk k, read its round
counter, decide, dispatch chunk k+1. On the remote-tunnel TPU every one of
those syncs costs a full dispatch floor (~110-140 ms measured,
BENCH_TABLES.md "dispatch floor"), so a multi-chunk run paid
chunks x floor in series instead of hiding the floor under compute.

This driver keeps ``cfg.pipeline_chunks`` chunks in flight: chunk k+1 is
dispatched BEFORE chunk k's termination predicate is read, and the
predicate scalars are fetched asynchronously so the retire-side block is
one transfer, not a round trip per scalar. Correctness hinges on the
overshoot contract every chunk function must satisfy (pinned per engine by
tests/test_pipeline.py): dispatched at an already-terminal carry, a chunk
is a bitwise NO-OP — protocol state unchanged, round counter unchanged.
The XLA engines get this from the ``~done`` guard in their while_loop
predicate; the fused Pallas kernels seed their in-kernel done flag from
the incoming conv plane (the same property checkpoint resume already
relies on). Because overshoot is free, the reported ``rounds`` stays
EXACT — it is the retired carry's own counter, never rounded up to the
pipeline depth.

Chunk-boundary side effects keep their serial semantics:

- ``on_retire`` (the checkpoint/metrics hook) fires at RETIRED chunks, in
  order, with that chunk's state — never for an in-flight speculative
  chunk — so a checkpoint written at boundary k is exactly the serial
  loop's boundary-k checkpoint.
- ``should_stop`` (the stall watchdog) is consulted at retired boundaries
  in order. When it fires at chunk k, the in-flight speculative chunks
  are DISCARDED: the run's result is carry k, bitwise the serial loop's —
  the speculative compute past a stall is wasted, not observed.
- Both callbacks read retired state, which is incompatible with buffer
  donation (a donated carry's buffers die when the next chunk consumes
  them); engines therefore donate only on hook-free runs. ``run_chunks``
  enforces the invariant.

Buffer donation: with ``donate=True`` the engine's ``dispatch`` consumes
its state argument (``jax.jit(..., donate_argnums=(0,))``), so
steady-state chunks alias their output planes onto the input's buffers and
copy nothing. The round/done scalars ride OUTSIDE the donated argument —
they stay readable after the state buffers are reused, which is what lets
the driver retire chunk k while chunk k+1 already owns its memory. On a
done/max_rounds exit the newest in-flight carry is returned (its buffers
are the only live ones); the overshoot contract makes it bitwise the
retired carry.

The sharded fused compositions stack a second speculation layer INSIDE the
dispatch (parallel/overlap.py): their super-step loop defers each
termination psum under the next super-step's kernel and rolls back to a
double-buffered copy when the verdict fires. The contracts compose because
that loop preserves exactly what this driver assumes — the retired carry
is the serial schedule's bitwise state, ``rounds`` is exact, and a
dispatch at a terminal carry stays a no-op (the pending verdict is drained
before the chunk returns, so the ``done`` scalar this driver prefetches is
never stale across dispatches).

Cancellation (ISSUE 8): ``should_cancel(rounds)`` — the serving plane's
per-request deadline hook — is consulted at every RETIRED boundary, like
the watchdog, but it reads only the clock (never device state), so it is
legal under buffer donation. Because a cancel must take effect at the
NEXT retired chunk (the deadline contract: deadline + one chunk + ε), a
cancellable loop runs at pipeline depth 1 — speculative chunks dispatched
past a deadline would push the cancel horizon out by the whole pipeline
depth. A fired cancel ends the run AT that boundary with
``ChunkLoopResult.cancelled=True``; the retired carry is the result
(partial but exact — ``rounds`` is the retired counter), and the engines
map it to ``outcome="deadline_exceeded"``. A loop without the hook is
bitwise and schedule-identical to before.

Telemetry rides the same machinery (ops/telemetry.py): a chunk may return a
fourth element — an auxiliary on-device buffer (the per-round counter
block) — which the driver prefetches with the predicate scalars and hands
to ``on_aux`` at retire time. Aux buffers are fresh chunk OUTPUTS, never
part of the donated state carry, so ``on_aux`` composes with donation and
speculation: the telemetry plane observes the run without de-optimizing it.
Aux of a discarded speculative chunk is never observed (it executed no real
rounds past the retired boundary by the overshoot contract).

The driver also measures the per-chunk timing split — ``dispatch_s`` (host
time to enqueue the chunk) and ``fetch_s`` (host time blocked on the
predicate readback + aux collection) — into ``ChunkLoopResult.chunk_log``
for the structured run-event log, and tags dispatch/fetch/retire with
``jax.profiler`` trace annotations so chunk boundaries are legible in a
Perfetto/TensorBoard capture (``--profile DIR``).

Full run budget (ISSUE 7): beyond the dispatch/fetch totals the loop also
attributes

- ``first_dispatch_s`` — the FIRST chunk's enqueue time alone. The warmup
  dispatch in the engines eats the trace+compile cost, but any residual
  first-execution work (donation rewiring, transfer warm-up, a cold axon
  tunnel) lands here, split out from the steady-state dispatch floor;
- ``hook_s`` — host time inside the chunk-boundary callbacks
  (``on_retire`` — the checkpoint/IO hook — and ``should_stop``, the
  watchdog's converged-count sync);
- ``aux_s`` — host time collecting telemetry aux buffers inside the fetch
  block (a SUBSET of ``fetch_s``: fetch minus aux is the true
  device-wait).

Together with the loop's own wall these close the non-engine budget:
``residual = run_s − dispatch_s − fetch_s − hook_s`` is pure Python
bookkeeping (deque ops, logging) and benchmarks/wallwalk.py pins that the
named buckets cover >= 90% of the non-engine wall. All measurements are
``perf_counter`` brackets around code that already ran — zero extra host
syncs, donation and speculation untouched.
"""

from __future__ import annotations

import collections
import dataclasses
import sys
import time
from typing import Callable, Optional

try:  # host-side profiler annotations; inert when no trace is active
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # noqa: BLE001 — the driver must not require jax

    class _TraceAnnotation:
        def __init__(self, *a, **k):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False


def _prefetch(x) -> None:
    """Start the device->host copy of a predicate scalar without blocking —
    by retire time the value is usually already resident."""
    fn = getattr(x, "copy_to_host_async", None)
    if fn is not None:
        try:
            fn()
        except Exception:  # noqa: BLE001 — a failed hint must never kill a run
            pass


@dataclasses.dataclass
class ChunkLoopResult:
    """Outcome of one pipelined chunk loop."""

    state: object  # final carry state (live buffers, donate-safe)
    rounds: int  # exact executed-round count (the retired carry's counter)
    done: bool  # the engine's own termination flag at the final boundary
    chunks_retired: int  # boundaries observed (serial-equivalent count)
    chunks_speculative: int  # dispatched-then-discarded chunks (stall exits)
    dispatch_s: float = 0.0  # total host time enqueueing chunks
    fetch_s: float = 0.0  # total host time blocked on predicate/aux readback
    # Run-budget attribution (module docstring): the first chunk's enqueue
    # time alone; host time in the on_retire/should_stop callbacks; host
    # time collecting telemetry aux buffers (subset of fetch_s).
    first_dispatch_s: float = 0.0
    hook_s: float = 0.0
    aux_s: float = 0.0
    # Per RETIRED chunk, in order: {"rounds", "dispatch_s", "fetch_s"} —
    # the structured run-event log's chunk-retired events (utils/events.py).
    chunk_log: list = dataclasses.field(default_factory=list)
    # The engine's health-sentinel scalar at the final boundary (the first
    # round the sentinel tripped, or the engine's NEVER constant while
    # healthy); None when the loop ran without a health carry (health0 not
    # given). The driver maps it to outcome="unhealthy".
    health: object = None
    # The should_cancel hook ended the run at a retired boundary (the
    # deadline contract, ISSUE 8). The engines map it to
    # outcome="deadline_exceeded"; the carry is the retired (partial)
    # state and ``rounds`` stays exact.
    cancelled: bool = False
    # on_retire OSErrors survived under hook_error="continue" (ISSUE 19):
    # {"rounds", "error"} per failed boundary, in order. The runner lifts
    # it onto RunResult so the CLI can emit checkpoint-failed events.
    hook_failures: list = dataclasses.field(default_factory=list)


def run_chunks(
    *,
    dispatch: Callable,
    state0,
    rnd0,
    done0,
    start_round: int,
    max_rounds: int,
    stride: int,
    depth: int,
    donate: bool = False,
    on_retire: Optional[Callable[[int, object], None]] = None,
    should_stop: Optional[Callable[[int, object], bool]] = None,
    on_aux: Optional[Callable[[int, int, object], None]] = None,
    health0=None,
    should_cancel: Optional[Callable[[int], bool]] = None,
    step_timing: bool = False,
    hook_error: str = "raise",
) -> ChunkLoopResult:
    """Drive ``dispatch(state, rnd, done, round_end) -> (state, rnd, done)``
    to termination with up to ``depth`` chunks in flight.

    ``dispatch`` is the engine's jitted chunk: it advances up to
    ``round_end`` (absolute round index), early-exits on its own
    termination predicate, and must be an overshoot no-op (see module
    docstring). ``rnd``/``done`` are device scalars returned fresh each
    call — with ``donate=True`` only the state argument is donated, so
    they remain readable after the state's buffers are recycled.

    ``dispatch`` may return one more element, an auxiliary device buffer
    (the telemetry counter block); it is prefetched with the predicate
    scalars and handed to ``on_aux(rounds_before, rounds_after, aux)`` at
    each retired boundary, in order. Unlike ``on_retire``/``should_stop``,
    ``on_aux`` reads no protocol state and is LEGAL under donation — aux
    buffers are fresh chunk outputs outside the donated carry.

    ``health0`` (optional) threads an engine health-sentinel scalar through
    the loop: the contract becomes ``dispatch(state, rnd, done, health,
    round_end) -> (state, rnd, done, health[, aux])``. The scalar rides
    next to the done flag — outside any donated buffers, prefetched with
    the other scalars — and the final boundary's value lands in
    ``ChunkLoopResult.health``. A sentinel trip must also raise the
    engine's done flag (the loop itself never interprets health values, so
    termination stays the engine's decision).

    ``should_cancel(rounds)`` (optional) is the deadline/cancellation
    hook: consulted at every retired boundary, it reads the CLOCK, not
    device state, so it composes with donation. When it returns True the
    run ends at that boundary with ``cancelled=True`` (partial state,
    exact ``rounds``). A cancellable loop runs at depth 1 — see the module
    docstring — so cancellation latency is bounded by one chunk.

    ``stride`` is the engine's natural chunk length in rounds: a chunk
    dispatched at boundary k targets ``min(start + (k+1)*stride,
    max_rounds)`` — the identical schedule the serial loop produces,
    because a non-terminal chunk always runs to its round_end exactly.

    ``step_timing`` (ISSUE 18, cfg.step_timing): when True each chunk_log
    entry additionally records ``t_retire`` (perf_counter at the retire)
    and ``wall_s`` (retire-to-retire wall; the first entry measures from
    loop entry) — the per-dispatch super-step wall the autotuner's
    measured-vs-predicted table reads (``step_timing_report``). Clock
    reads at boundaries the loop already observes: no extra syncs, no
    schedule change, and with the flag off chunk_log is byte-identical
    to before (the off-path bitwise-neutrality pin).

    ``hook_error`` (ISSUE 19) is the checkpoint-hook I/O failure policy:
    ``on_retire`` is where checkpoint writes happen, and an OSError there
    (full disk, injected ENOSPC) used to propagate into the engines'
    degradation ladder — which deliberately does NOT degrade on OSError,
    so the run died for an observability-plane failure. Under
    ``"continue"`` (what the engines pass unless cfg.strict_checkpoint)
    the loop records the failure in ``ChunkLoopResult.hook_failures``,
    bumps the ``gossip_tpu_checkpoint_failed_total`` registry counter,
    warns on stderr and keeps simulating — losing a checkpoint interval,
    never the run. ``"raise"`` (the default, and --strict-checkpoint)
    restores fail-fast. Only OSError is policy-managed; any other hook
    exception propagates unchanged.
    """
    if hook_error not in ("raise", "continue"):
        raise ValueError(
            f"hook_error must be 'raise' or 'continue', got {hook_error!r}")
    depth = max(1, int(depth))
    if should_cancel is not None:
        # Speculation would push the cancel horizon out by the pipeline
        # depth (in-flight chunks must drain or be wasted); a deadline-
        # bounded run trades the overlap for a one-chunk cancel bound.
        depth = 1
    if donate and (on_retire is not None or should_stop is not None):
        raise ValueError(
            "buffer donation recycles retired chunk state; chunk-boundary "
            "hooks (checkpoint/watchdog) require donate=False"
        )
    has_health = health0 is not None
    aux_i = 4 if has_health else 3  # dispatch-output index of the aux buffer

    inflight: collections.deque = collections.deque()
    # Newest dispatched carry: (state, rnd, done, health, aux).
    head = (state0, rnd0, done0, health0, None)
    last_end = start_round
    retired_count = 0
    dispatched_count = 0
    dispatch_total = 0.0
    fetch_total = 0.0
    first_dispatch = 0.0
    hook_total = 0.0
    aux_total = 0.0
    chunk_log: list = []
    hook_failures: list = []

    def fill() -> None:
        """Top the pipeline up. Chunks whose round_end would not advance
        past max_rounds are guaranteed no-ops and are never dispatched —
        except the very first chunk, which the serial loops also issue
        (a resume at max_rounds still observes one boundary)."""
        nonlocal head, last_end, dispatch_total, dispatched_count
        nonlocal first_dispatch
        while len(inflight) < depth and (
            last_end < max_rounds or (not inflight and retired_count == 0)
        ):
            last_end = min(last_end + stride, max_rounds)
            t0 = time.perf_counter()
            with _TraceAnnotation("chunkloop.dispatch"):
                if has_health:
                    out = dispatch(head[0], head[1], head[2], head[3], last_end)
                else:
                    out = dispatch(head[0], head[1], head[2], last_end)
            disp_s = time.perf_counter() - t0
            dispatch_total += disp_s
            if dispatched_count == 0:
                first_dispatch = disp_s
            dispatched_count += 1
            health = out[3] if has_health else None
            aux = out[aux_i] if len(out) > aux_i else None
            _prefetch(out[1])
            _prefetch(out[2])
            if health is not None:
                _prefetch(health)
            if aux is not None:
                _prefetch(aux)
            head = (out[0], out[1], out[2], health, aux)
            inflight.append((head, disp_s))

    fill()  # dispatches at least one chunk, so the retire loop runs
    final = head
    rounds = start_round
    done_b = False
    t_prev_retire = time.perf_counter()

    def result(carry, spec: int, cancelled: bool = False) -> ChunkLoopResult:
        return ChunkLoopResult(
            state=carry[0], rounds=rounds, done=done_b,
            chunks_retired=retired_count, chunks_speculative=spec,
            dispatch_s=dispatch_total, fetch_s=fetch_total,
            first_dispatch_s=first_dispatch, hook_s=hook_total,
            aux_s=aux_total,
            chunk_log=chunk_log,
            health=int(carry[3]) if has_health else None,
            cancelled=cancelled,
            hook_failures=hook_failures,
        )

    while inflight:
        cur, disp_s = inflight.popleft()
        prev_rounds = rounds
        t0 = time.perf_counter()
        with _TraceAnnotation("chunkloop.fetch"):
            rounds = int(cur[1])  # blocks until chunk k completes
            done_b = bool(cur[2])
            if on_aux is not None and cur[4] is not None:
                # The aux copy was prefetched at dispatch; by retire time it
                # is usually resident — this is a collection, not a sync.
                t_aux = time.perf_counter()
                on_aux(prev_rounds, rounds, cur[4])
                aux_total += time.perf_counter() - t_aux
        fetch_s = time.perf_counter() - t0
        fetch_total += fetch_s
        retired_count += 1
        entry = {"rounds": rounds, "dispatch_s": disp_s, "fetch_s": fetch_s}
        if step_timing:
            t_retire = time.perf_counter()
            entry["t_retire"] = t_retire
            entry["wall_s"] = t_retire - t_prev_retire
            t_prev_retire = t_retire
        chunk_log.append(entry)
        if on_retire is not None:
            with _TraceAnnotation("chunkloop.retire"):
                t_hook = time.perf_counter()
                try:
                    on_retire(rounds, cur[0])
                except OSError as e:
                    if hook_error != "continue":
                        raise
                    hook_failures.append({
                        "rounds": rounds,
                        "error": f"{type(e).__name__}: {e}",
                    })
                    print(
                        f"[pipeline] chunk-boundary hook failed at "
                        f"rounds={rounds}: {e} — continuing (this interval's "
                        "checkpoint is lost; --strict-checkpoint fails fast)",
                        file=sys.stderr,
                    )
                    try:
                        from ..utils import obs as obs_mod
                        obs_mod.default_registry().counter(
                            "gossip_tpu_checkpoint_failed_total",
                            "chunk-boundary checkpoint-hook I/O failures "
                            "survived under hook_error='continue'",
                        ).inc()
                    except Exception:  # noqa: BLE001 — metrics must not kill
                        pass
                finally:
                    hook_total += time.perf_counter() - t_hook
        if done_b or rounds >= max_rounds:
            # Overshoot chunks are bitwise no-ops, so the newest carry IS
            # this one — and under donation it is the one with live buffers.
            final = head if donate else cur
            inflight.clear()
            break
        if should_cancel is not None and should_cancel(rounds):
            # Deadline fired: the run ends AT this boundary with the
            # retired (partial) carry. depth == 1 here by construction, so
            # no speculative chunk is in flight and — donation included —
            # this carry's buffers are the live ones (cur IS head).
            return result(cur, len(inflight), cancelled=True)
        if should_stop is not None:
            t_hook = time.perf_counter()
            stop = should_stop(rounds, cur[0])
            hook_total += time.perf_counter() - t_hook
            if stop:
                # Serial semantics: the run ends AT this boundary.
                # In-flight speculative chunks executed real rounds past
                # the stall — discard them unobserved (donate=False here
                # by construction).
                return result(cur, len(inflight))
        final = cur
        fill()
    return result(final, 0)


# -------------------------------------------- step-timing post-processing


def step_timing_report(chunk_log, start_round: int = 0,
                       per_process_t=None) -> Optional[dict]:
    """Turn a ``step_timing=True`` chunk_log into the per-dispatch
    attribution record (ISSUE 18): the super-step wall list, measured
    median/max us-per-round, and the straggler section. Pure host
    arithmetic over an already-collected log — callable on any RunResult
    whose run threaded the flag. Returns None when the log carries no
    timing rows (the flag was off, or the loop never retired a chunk).

    ``per_process_t`` (optional) is ``{process_index: [t_retire, ...]}``
    per-process retire timestamps from a multi-process mesh (each process
    runs its own driver over the same SPMD program, so boundary k is the
    same super-step everywhere); it feeds :func:`straggler_report`.
    Single-process runs report zero skew over one process."""
    rows = [e for e in (chunk_log or ()) if "wall_s" in e]
    if not rows:
        return None
    walls = [float(e["wall_s"]) for e in rows]
    prev = start_round
    per_round_us = []
    rounds_list = []
    for e, w in zip(rows, walls):
        r = int(e["rounds"])
        delta = r - prev
        prev = r
        rounds_list.append(r)
        if delta > 0:
            per_round_us.append(w / delta * 1e6)
    srt = sorted(per_round_us)
    straggler = (
        straggler_report(per_process_t) if per_process_t else
        {"processes": 1, "boundaries": len(rows),
         "max_skew_s": 0.0, "median_skew_s": 0.0}
    )
    return {
        "dispatches": len(rows),
        "wall_s": walls,
        "rounds": rounds_list,
        "median_us_per_round": srt[len(srt) // 2] if srt else None,
        "max_us_per_round": srt[-1] if srt else None,
        "straggler": straggler,
    }


def straggler_report(per_process_t) -> dict:
    """Per-device skew from per-process retire timestamps: boundary k's
    skew is ``max_p t[p][k] - min_p t[p][k]`` (the SPMD chunk loop
    retires the same super-step at boundary k on every process, so the
    spread IS the straggler gap — the clocks only need to agree to the
    skews being compared, which process-local perf_counter deltas off a
    shared dispatch epoch give). Truncates to the shortest process log
    (a process killed mid-run still yields a report)."""
    cols = [list(map(float, ts)) for ts in (
        per_process_t.values() if isinstance(per_process_t, dict)
        else per_process_t
    )]
    cols = [c for c in cols if c]
    if len(cols) < 2:
        return {"processes": len(cols),
                "boundaries": len(cols[0]) if cols else 0,
                "max_skew_s": 0.0, "median_skew_s": 0.0}
    n = min(len(c) for c in cols)
    skews = [
        max(c[k] for c in cols) - min(c[k] for c in cols)
        for k in range(n)
    ]
    srt = sorted(skews)
    return {
        "processes": len(cols),
        "boundaries": n,
        "max_skew_s": srt[-1],
        "median_skew_s": srt[len(srt) // 2],
    }
