"""Reference-fidelity push-sum: a single random walk.

The reference's push-sum keeps exactly ONE message in flight: every
ComputePushSum receipt triggers exactly one send (program.fs:110-143), so the
protocol is a lone random walk carrying half-masses through the graph
(SURVEY.md §3.3) — convergence time is walk cover/mixing time, not O(log N)
synchronous rounds. This mode exists for apples-to-apples validation against
the reference at small N (SURVEY.md §7 hard part 5); it is inherently
sequential — a `lax.while_loop` advancing one hop per iteration — and is
never the benchmark path.

Faithful details carried over:

- Kickoff (PushSum handler, program.fs:110-116): the leader halves (s, w)
  and sends the halves to a random neighbor.
- Non-converged receipt (program.fs:119-143): absorb, compare pre/post
  ratio to delta, reset-or-increment termRound, latch convergence at
  term_rounds (reporting pre-absorb values — quirk Q5 — which we mirror by
  latching before the absorb overwrites state), then halve and forward.
- Converged receipt (program.fs:125-127): relay the incoming (s, w)
  UNTOUCHED to a random neighbor — mass conservation holds, the node's own
  state is frozen (Q5).
- termRound resets to 0 when convergence fires (program.fs:136).
- Q8: if the walk reaches a degree-0 orphan (possible in Imp3D — random
  extra edges can point at orphans), the reference actor crashes on the
  empty-array index and the message is lost in the restart — the walk dies.
  We model that as a `dead` latch that freezes the walk.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import SimConfig
from ..ops import sampling
from ..ops.topology import Topology


class WalkCarry(NamedTuple):
    s: jnp.ndarray  # [n]
    w: jnp.ndarray  # [n]
    term: jnp.ndarray  # [n] int32
    conv: jnp.ndarray  # [n] bool
    cur: jnp.ndarray  # () int32 — node about to process the in-flight message
    msg_s: jnp.ndarray  # () — in-flight sum mass
    msg_w: jnp.ndarray  # () — in-flight weight mass
    steps: jnp.ndarray  # () int32 — hops taken
    dead: jnp.ndarray  # () bool — walk hit an orphan (Q8)


def make_walk(topo: Topology, cfg: SimConfig, base_key: jax.Array, leader: jax.Array):
    """Build (step_fn, carry0, key_data, topo_args) for the single-walk
    push-sum.

    step_fn(carry, key_data, *topo_args) -> carry advances one message hop
    (``key_data`` is the raw base key from ops/sampling.key_split, passed as
    a runtime argument — baked key constants cost ~100 ms per dispatch on
    the axon platform). carry0 is the post-kickoff state: leader already
    halved, halves in flight toward a random neighbor of the leader.
    """
    dtype = jnp.dtype(cfg.dtype)
    n = topo.n
    delta = jnp.asarray(cfg.resolved_delta, dtype)
    term_rounds = cfg.term_rounds
    key_data, key_impl = sampling.key_split(base_key)

    if topo.implicit:
        topo_args = ()
    else:
        topo_args = (jnp.asarray(topo.neighbors), jnp.asarray(topo.degree))

    def pick_neighbor(key, node, *targs):
        """Uniform random neighbor of `node` — Random().Next(0, deg) +
        index (program.fs:91 et al.). Returns (target, ok) where ok is False
        for a degree-0 orphan."""
        bits = jax.random.bits(key, (), jnp.uint32)
        if topo.implicit:
            shift = 1 + (bits % jnp.uint32(n - 1)).astype(jnp.int32)
            return (node + shift) % n, jnp.bool_(True)
        neighbors, degree = targs
        deg = degree[node]
        slot = (bits % jnp.maximum(deg, 1).astype(jnp.uint32)).astype(jnp.int32)
        return neighbors[node, slot], deg > 0

    # Kickoff: PushSum handler (program.fs:110-116).
    s0 = jnp.arange(n, dtype=dtype)
    w0 = jnp.ones((n,), dtype=dtype)
    half_s = s0[leader] * 0.5
    half_w = w0[leader] * 0.5
    s0 = s0.at[leader].set(half_s)
    w0 = w0.at[leader].set(half_w)
    first_target, first_ok = pick_neighbor(
        jax.random.fold_in(base_key, 0), leader, *topo_args
    )
    carry0 = WalkCarry(
        s=s0,
        w=w0,
        term=jnp.full((n,), cfg.initial_term_round, dtype=jnp.int32),
        conv=jnp.zeros((n,), bool),
        cur=first_target.astype(jnp.int32),
        msg_s=half_s,
        msg_w=half_w,
        steps=jnp.int32(1),
        dead=~first_ok,
    )

    def step_fn(c: WalkCarry, key_data, *targs) -> WalkCarry:
        cur = c.cur
        key = jax.random.fold_in(sampling.key_join(key_data, key_impl), c.steps)
        s_c = c.s[cur]
        w_c = c.w[cur]
        newsum = s_c + c.msg_s
        newweight = w_c + c.msg_w
        cal = jnp.abs(s_c / w_c - newsum / newweight)

        is_conv = c.conv[cur]
        # Non-converged branch (program.fs:129-143):
        term_new = jnp.where(cal > delta, 0, c.term[cur] + 1)
        fires = term_new >= term_rounds
        term_new = jnp.where(fires, 0, term_new)  # reset after firing, program.fs:136
        s_cur_new = newsum * 0.5
        w_cur_new = newweight * 0.5

        # Converged relay (program.fs:125-127) leaves state untouched and
        # forwards the incoming message unchanged.
        s_out = jnp.where(is_conv, c.msg_s, s_cur_new)
        w_out = jnp.where(is_conv, c.msg_w, w_cur_new)
        s_new = c.s.at[cur].set(jnp.where(is_conv, s_c, s_cur_new))
        w_new = c.w.at[cur].set(jnp.where(is_conv, w_c, w_cur_new))
        term_arr = c.term.at[cur].set(jnp.where(is_conv, c.term[cur], term_new))
        conv_arr = c.conv.at[cur].set(is_conv | fires)

        target, ok = pick_neighbor(key, cur, *targs)
        return WalkCarry(
            s=s_new,
            w=w_new,
            term=term_arr,
            conv=conv_arr,
            cur=target.astype(jnp.int32),
            msg_s=s_out,
            msg_w=w_out,
            steps=c.steps + 1,
            dead=c.dead | ~ok,
        )

    return step_fn, carry0, key_data, topo_args


def run_walk(topo: Topology, cfg: SimConfig, base_key: jax.Array, leader: jax.Array, target: int):
    """Drive the walk to convergence / death / cfg.max_rounds hops.

    Returns (final WalkCarry, compile_s, run_s). In walk mode the harness's
    "rounds" counts message hops — the comparable quantity to the
    reference's per-message processing (SURVEY.md §3.3).
    """
    import time

    step_fn, carry0, key_data, topo_args = make_walk(topo, cfg, base_key, leader)

    def whole(c: WalkCarry, key_data, max_steps, *targs):
        def cond(c):
            return (~c.dead) & (c.steps < max_steps) & (jnp.sum(c.conv) < target)

        def body(c):
            return step_fn(c, key_data, *targs)

        return lax.while_loop(cond, body, c)

    whole_j = jax.jit(whole)
    t0 = time.perf_counter()
    # Warmup executes ONE hop and discards it (max_steps is a traced bound,
    # so the same executable serves both calls; the timed run recomputes the
    # hop from carry0 on the same absolute-step key stream). Without it the
    # axon tunnel's deferred first-execution cost would land in run_s —
    # the same accounting rule as the batched engines' warmups.
    warm = whole_j(
        carry0, key_data,
        jnp.int32(min(int(carry0.steps) + 1, cfg.max_rounds)), *topo_args,
    )
    int(warm.steps)  # data-dependent sync; block_until_ready can lie here
    del warm
    compile_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    final = whole_j(carry0, key_data, jnp.int32(cfg.max_rounds), *topo_args)
    int(final.steps)  # force completion before stopping the clock
    run_s = time.perf_counter() - t1
    return final, compile_s, run_s
