"""Push-sum (distributed averaging) — batched synchronous-round kernel.

Reference semantics (program.fs:110-143): each node holds (sum, weight) with
sum initialized to its index (program.fs:107-108, 159) and weight 1
(program.fs:78); on each message it absorbs the incoming half-masses,
compares the pre/post ratio s/w against delta, counts consecutive sub-delta
rounds (C = 3, program.fs:135), then halves its state and forwards one half
to a uniformly random neighbor. The reference keeps exactly ONE message in
flight — a single random walk (SURVEY.md §3.3); this module implements the
standard *synchronous* push-sum instead: every round, every node halves and
sends to a random neighbor, and all deliveries land as one scatter-add. That
converges in O(log N) rounds on good expanders and is the mode the
benchmarks measure; the faithful single-walk lives in models/reference.py.

Key semantic carry-over: in the reference a node's termination counter only
advances when it *receives* a message (there is no clock — only message
handlers). The batched kernel keeps that gate (``received = inbox_w > 0``):
a node that merely halves has a bitwise-unchanged ratio, and counting those
no-op rounds as "stable" would declare convergence on nodes the mass has
never reached.

Invariants (tested): Σ sum and Σ weight are conserved by every round up to
fp error; converged ratios approach the true mean (pop-1)/2.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.delivery import deliver


class PushSumState(NamedTuple):
    s: jnp.ndarray  # [n] float — running sum mass
    w: jnp.ndarray  # [n] float — running weight mass
    term: jnp.ndarray  # [n] int32 — consecutive sub-delta receipt rounds
    conv: jnp.ndarray  # [n] bool — latched converged flag


def init_state(pop: int, dtype, initial_term: int) -> PushSumState:
    """s_i = i mirrors `InitializeVariables i` (program.fs:107-108, 159);
    initial_term = 1 replicates quirk Q4 (program.fs:79) in reference
    semantics, 0 in honest mode."""
    return PushSumState(
        s=jnp.arange(pop, dtype=dtype),
        w=jnp.ones((pop,), dtype=dtype),
        term=jnp.full((pop,), initial_term, dtype=jnp.int32),
        conv=jnp.zeros((pop,), dtype=bool),
    )


def halve_and_send(s, w, send_ok):
    """Split each sending node's mass in half (program.fs:113-114, 140-141).

    Returns (s_send, w_send, s_keep, w_keep). Nodes with send_ok False
    (degree-0 orphans, injected faults) keep their full mass — mass is
    conserved regardless.
    """
    s_send = jnp.where(send_ok, s * jnp.asarray(0.5, s.dtype), jnp.zeros((), s.dtype))
    w_send = jnp.where(send_ok, w * jnp.asarray(0.5, w.dtype), jnp.zeros((), w.dtype))
    return s_send, w_send, s - s_send, w - w_send


def absorb(state: PushSumState, s_keep, w_keep, inbox_s, inbox_w, delta,
           term_rounds, global_termination: bool = False, valid=None):
    """Absorb one round of deliveries and advance the termination counters.

    Mirrors the ComputePushSum handler (program.fs:119-143): ratio change is
    measured pre- vs post-absorb; > delta resets the counter, <= delta
    increments it (program.fs:130-133); reaching term_rounds latches
    convergence (program.fs:135-137). The receipt gate stands in for the
    reference's "no message, no handler" semantics.

    ``global_termination`` replaces the per-node latch with the global
    residual rule (SimConfig.termination): conv becomes all-or-nothing —
    every node converged iff EVERY node's per-round ratio change satisfies
    |Δ(s/w)| <= delta * max(|s/w|, 1) this round. The residual is RELATIVE
    (unlike the reference's absolute test): at equilibrium each absorb still
    re-rounds the mixed masses, so max-over-nodes |Δ| floors at a few ulps
    of the ratio scale (~(n-1)/2) — an absolute delta below that would
    never fire at float32. Non-receiving nodes have Δ = 0 and never block.
    Under node sharding each shard's all() composes with the runner's
    sum(conv) >= n predicate into the global all() exactly.

    ``valid`` (optional [n] bool) masks padded slots out of the global
    latch: pad lanes have Δ = 0 so they never *block* the all(), but the
    broadcast must not mark them converged — that would inflate
    converged_count by the pad count (and in degenerate meshes with
    n_pad - n_loc >= n could fire the psum predicate with a shard still
    unstable) and break the estimate_mae gate, which relies on pad slots
    never converging. Single-device callers have no padding and leave it
    None.
    """
    s_new = s_keep + inbox_s
    w_new = w_keep + inbox_w
    received = inbox_w > 0
    ratio_old = state.s / state.w
    ratio_new = s_new / w_new
    stable = jnp.abs(ratio_new - ratio_old) <= jnp.asarray(delta, state.s.dtype)
    if global_termination:
        tol = jnp.asarray(delta, state.s.dtype) * jnp.maximum(
            jnp.abs(ratio_old), jnp.asarray(1, state.s.dtype)
        )
        stable_g = jnp.abs(ratio_new - ratio_old) <= tol
        conv_new = jnp.broadcast_to(jnp.all(stable_g), state.conv.shape)
        if valid is not None:
            conv_new = conv_new & valid
        return PushSumState(s=s_new, w=w_new, term=state.term, conv=conv_new)
    term_new = jnp.where(
        received, jnp.where(stable, state.term + 1, 0), state.term
    )
    conv_new = state.conv | (term_new >= term_rounds)
    return PushSumState(s=s_new, w=w_new, term=term_new, conv=conv_new)


def round_from_targets(
    state: PushSumState, targets, send_ok, pop: int, delta, term_rounds,
    deliver_fn=None, global_termination: bool = False,
) -> PushSumState:
    """One full synchronous round on a single device (sharded delivery lives
    in parallel/sharded.py, built from the same halve_and_send/absorb).

    ``deliver_fn(values, targets) -> inbox`` overrides the default scatter-add
    (the runner passes the stencil fast path for offset-structured topologies).
    """
    if deliver_fn is None:
        deliver_fn = lambda v, t: deliver(v, t, pop)  # noqa: E731
    # named_scope tags flow into profiler traces (cli --profile) so per-round
    # cost splits into halve / deliver / absorb (SURVEY.md §5 tracing plan).
    with jax.named_scope("pushsum_halve"):
        s_send, w_send, s_keep, w_keep = halve_and_send(state.s, state.w, send_ok)
    with jax.named_scope("pushsum_deliver"):
        inbox_s = deliver_fn(s_send, targets)
        inbox_w = deliver_fn(w_send, targets)
    with jax.named_scope("pushsum_absorb"):
        return absorb(state, s_keep, w_keep, inbox_s, inbox_w, delta,
                      term_rounds, global_termination)
