"""Fused x sharded: per-shard multi-round Pallas chunks under shard_map.

The round-2 runner hard-rejected engine='fused' with n_devices > 1: the
fastest engines (VMEM-resident Pallas chunks) and the scaling mechanism
(node-sharded shard_map) could not be used together. This module composes
them with the halo-amortization trick:

- each device holds its shard of the [R_glob, 128] padded node layout plus
  an H-row halo on each side (H >= CR * per-round halo width);
- one "super-step" = exchange halos (ONE batched ppermute pair for every
  plane under the default overlap schedule — parallel/halo.py; one pair
  per plane with --overlap-collectives off), then run CR whole rounds
  INSIDE one per-shard `pallas_call` — the halo regions are *recomputed
  redundantly* on each device, shrinking by the stencil width per round,
  and stay valid for exactly CR rounds;
- global convergence (`lax.psum` of middle-region converged counts) is
  evaluated at super-step boundaries only — and, under the overlap
  schedule, DEFERRED one super-step so the reduction rides under the next
  kernel instead of between two kernels (parallel/overlap.py; rounds stay
  exact via the double-buffered rollback). Collectives per CR rounds: one
  batched halo volley + one scalar psum, instead of per-round exchanges.

Exactness at any population:
- sampling runs at GLOBAL positions — the kernel hashes each extended slot's
  global padded index (mod R_glob rows), so every device draws exactly the
  bits the single-device engines draw for those nodes (threefry is
  position-wise); sampled displacements use the sharded+halo'd slices of the
  same per-slot displacement plane;
- delivery of mod-n displacement class d reads TWO in-buffer circular rolls,
  by signed(-d) and signed(n-d) (both mapped to [-n_pad/2, n_pad/2)),
  blended at global index >= d: the first serves edges that do not cross
  the global wrap, the second those that do (whose buffer-relative distance
  shifts by the pad Z) — bit-identical to the single-device mod-n blend;
- rolls are circular over the extended buffer; wrapped-in garbage lands
  only in the invalidated halo margin, which the next exchange refreshes.

Round-count semantics: local-termination convergence is detected at
CR-round granularity, so `rounds` is the first super-step boundary at/after
true convergence and the state has evolved to that boundary. At
chunk_rounds=1 this degenerates to exact per-round detection and
trajectories match the single-device engines bitwise (gossip) — the
contract tests/test_fused_sharded.py pins; the coarser granularity trades
detection latency for an O(CR) cut in collective rounds, the knob
BASELINE.json's multi-host configs turn. termination='global' (VERDICT r4
#8) is EXACT at any CR: the kernel emits per-round middle unstable counts,
the psum'd vector names the first globally-stable round, and a capped
rerun of the same deterministic chunk lands the state there — stop round
and state match the chunked sharded global path's.

Reference mapping: C15's recast (the reference's only parallelism is
actor-per-node on one machine's threads, program.fs:23) — the hot loop
(program.fs:89-105, 110-143) fused across rounds AND sharded across chips.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import SimConfig
from ..ops.fused import clamp_cap_and_pad, threefry2x32_hash
from ..ops.fused_pool import (
    LANES,
    TILE,
    PoolLayout,
    _copy_in,
    _iota2,
    _make_gather,
    absorb_gossip_tile,
    absorb_pushsum_tile,
    build_pool_layout,
)
from ..ops.fused_stencil import _build_disp_planes
from ..ops.topology import Topology, stencil_offsets
from ..utils import compat
from ..analysis.wire_specs import C, Regions, WireSpec

_VMEM_BUDGET = 100 * 1024 * 1024


def _signed_pad(d: int, n_pad: int) -> int:
    d = d % n_pad
    return d if d <= n_pad // 2 else d - n_pad


def first_zero_round(u_glob, executed):
    """(fired, idx) of the first executed round whose psum'd global
    unstable count is zero — the global-termination verdict at chunk
    granularity. Kernels write -1 for rounds not executed, so the sentinel
    can never collide with a real zero; the iota gate makes that explicit.
    Shared by the VMEM and HBM-streaming sharded compositions."""
    k = u_glob.shape[0]
    ok = (u_glob == 0) & (
        jnp.arange(k, dtype=jnp.int32) < executed.astype(jnp.int32)
    )
    return ok.any(), jnp.argmax(ok).astype(jnp.int32)


def global_verdict_step(run_capped, planes_mid, executed, u, rnd, rows_loc,
                        n, axis):
    """One super-step of termination='global' composition (VERDICT r4 #8),
    the ONE home shared by the VMEM and HBM-streaming sharded lattice
    compositions: psum the kernel's per-round middle unstable vector, name
    the first globally-stable round, RErun the deterministic chunk capped
    there when the verdict fired mid-chunk (same keys — the capped replay
    is bitwise the prefix), and latch the all-or-nothing conv plane on
    valid lanes. ``run_capped(cap)`` re-executes the same chunk with the
    given round cap and returns mid-sliced planes; ``planes_mid`` is the
    uncapped chunk's mid-sliced (s, w, term, conv) output. Returns
    (planes', rnd', fired) — the exact stop round and state of the chunked
    sharded global path (models/pushsum.absorb global_termination)."""
    u_glob = lax.psum(u, axis)
    fired, idx = first_zero_round(u_glob, executed)
    planes_mid = lax.cond(
        fired & (idx + 1 < executed),
        lambda: run_capped(rnd + idx + 1),
        lambda: planes_mid,
    )
    dev = lax.axis_index(axis)
    pos = (
        (dev.astype(jnp.int32) * rows_loc
         + lax.broadcasted_iota(jnp.int32, (rows_loc, LANES), 0)) * LANES
        + lax.broadcasted_iota(jnp.int32, (rows_loc, LANES), 1)
    )
    conv = jnp.where(fired & (pos < n), jnp.int32(1), jnp.int32(0))
    planes_mid = (planes_mid[0], planes_mid[1], planes_mid[2], conv)
    return planes_mid, rnd + jnp.where(fired, idx + 1, executed), fired


def threefry_bits_rows(k1, k2, global_rows, cols: int):
    """uint32 [rows, cols] threefry words at explicit global row indices —
    the sharded-halo variant of ops/fused.threefry_bits_2d: each element
    hashes counter i = global_row * cols + lane, so a device generates, for
    any (possibly wrapping) window of global rows, exactly the bits the
    single-device engines generate there."""
    rows = global_rows.shape[0]
    i = (
        global_rows.astype(jnp.uint32)[:, None] * jnp.uint32(cols)
        + jax.lax.broadcasted_iota(jnp.uint32, (rows, cols), 1)
    )
    return threefry2x32_hash(k1, k2, i)


def plan_fused_sharded(topo: Topology, cfg: SimConfig, n_dev: int):
    """(H_rows, rows_loc, CR, layout) or a string reason why not."""
    if jax.process_count() > 1:
        # Multi-process support matrix (ISSUE 15): this composition's
        # VMEM-resident planes are placed with single-process
        # jax.device_put; the dispatch falls through to the HBM-streaming
        # sharded composition (parallel/fused_hbm_sharded.py), which
        # serves multi-process meshes — as do the chunked sharded engine
        # and the replicated-pool2 composition.
        return (
            "the VMEM fused x sharded composition is single-process; "
            "under a multi-process mesh the dispatch serves the "
            "HBM-streaming sharded composition "
            "(parallel/fused_hbm_sharded.py) instead"
        )
    if topo.implicit:
        return (
            "implicit (full) topology has no displacement structure for "
            "the halo composition; use delivery='pool' (the fused pool x "
            "sharded composition, parallel/fused_pool_sharded.py)"
        )
    offsets = stencil_offsets(topo)
    if offsets is None:
        return f"topology {topo.kind!r} has no small displacement set"
    if cfg.dtype != "float32":
        return "fused engine supports float32 only"
    if not jax.config.jax_threefry_partitionable:
        return "requires jax_threefry_partitionable=True"
    if cfg.telemetry:
        return (
            "telemetry counters run in the single-device fused kernels and "
            "the chunked/sharded XLA engines; this composition does not "
            "carry the counter block"
        )
    if cfg.step_timing and cfg.overlap_collectives:
        return (
            "step_timing under the overlapped super-step schedule would "
            "force the deferred termination psum to drain at every timed "
            "boundary (a host sync inside the overlap window); use "
            "overlap_collectives=False or step_timing=False"
        )
    if cfg.faulted:
        # No failure-model support in this engine yet — rejecting on
        # the aggregate flag (not just fault_rate) keeps a crash/dup/
        # delay config from silently running unfaulted here. The
        # stencil (ops/fused.py) and pool tiers (ops/fused_pool.py,
        # ops/fused_pool2.py) run drop+crash in-kernel.
        return "failure models not supported in this fused kernel"
    if cfg.delivery == "scatter":
        return (
            "the fused kernel delivers via the stencil formulation only; "
            "delivery='scatter' would be silently ignored"
        )
    layout = build_pool_layout(topo.n)
    R = layout.rows
    if R % n_dev != 0 or (R // n_dev) % TILE != 0:
        return (
            f"padded layout ({R} rows) must split into whole {TILE}-row "
            f"tiles per device; {n_dev} devices do not divide it"
        )
    rows_loc = R // n_dev
    n_pad = layout.n_pad
    n = topo.n
    # Max |in-buffer shift| over both blend variants of every class.
    w = 0
    for d in (int(x) for x in offsets):
        w = max(w, abs(_signed_pad(-d, n_pad)), abs(_signed_pad(n - d, n_pad)))
    CR = max(1, min(int(cfg.chunk_rounds), 64))
    max_deg = topo.max_deg
    per_node = (4 + 4 + 2) if cfg.algorithm == "push-sum" else (3 + 2)

    def h_for(cr):
        return -(-((-(-(cr * w) // LANES) + 1)) // TILE) * TILE

    def fits(cr):
        h = h_for(cr)
        vmem = (rows_loc + 2 * h) * LANES * 4 * (per_node + max_deg + 1)
        return h <= rows_loc and vmem <= _VMEM_BUDGET

    # Shrink the fused chunk until the halo fits a shard (halo slices come
    # from the neighbor shards' planes) AND the extended planes fit VMEM.
    while CR > 1 and not fits(CR):
        CR //= 2
    if not fits(CR):
        return (
            f"per-round halo ({w} slots) at a {rows_loc}-row shard exceeds "
            "the shard or the VMEM plane budget even at chunk_rounds=1; "
            "use the chunked collective engine"
        )
    return (h_for(CR), rows_loc, CR, layout)


def make_stencil_shard_chunk(
    topo: Topology, cfg: SimConfig, H: int, rows_loc: int,
    layout: PoolLayout, *, interpret: bool = False
):
    """Per-device chunk kernel: ``chunk_fn(ext_state, keys, row0, start,
    cap) -> (ext_state', executed, conv_mid, u)`` runs up to CR =
    keys.shape[0] rounds on one device's halo-extended planes. ``row0`` is
    the device's first extended row's GLOBAL row index (may be negative mod
    R_glob — passed pre-wrapped). Valid output region after k rounds
    shrinks k halo widths from each end; callers slice the middle shard.
    ``u[k]`` is round k's middle-region metric (unstable valid lanes under
    termination='global', converged count otherwise; -1 when round k was
    not executed) — the per-round stream the global verdict needs at
    super-step granularity (VERDICT r4 #8)."""
    R_glob = layout.rows
    n = layout.n
    n_pad = layout.n_pad
    rows_ext = rows_loc + 2 * H
    n_ext = rows_ext * LANES
    T = rows_ext // TILE
    ext_layout = PoolLayout(n=n_ext, n_pad=n_ext, rows=rows_ext, tiles=T)
    offsets = [int(d) for d in stencil_offsets(topo)]
    # Per class d: a receiver at global index p reads the sender at
    # buffer-relative offset sigma = signed_pad(-d) when p >= d (the edge
    # does not cross the global wrap) or signed_pad(n - d) when p < d (it
    # does; the pad Z shifts the buffer distance). A forward circular roll
    # by e delivers out[j] = in[j - e], so e = -sigma mod n_ext.
    shift_pairs = [
        (
            d,
            (-_signed_pad(-d, n_pad)) % n_ext,
            (-_signed_pad(n - d, n_pad)) % n_ext,
        )
        for d in offsets
    ]
    max_deg = topo.max_deg
    pushsum = cfg.algorithm == "push-sum"
    global_term = pushsum and cfg.termination == "global"
    delta = np.float32(cfg.resolved_delta)
    term_rounds = np.int32(cfg.term_rounds)
    rumor_target = np.int32(cfg.resolved_rumor_target)
    suppress = cfg.resolved_suppress

    def kernel(*refs):
        if pushsum:
            (scal_ref, keys_ref, disp_h, deg_h, s0, w0, t0, c0,
             s_o, w_o, t_o, c_o, meta_o, u_o,
             s_v, w_v, t_v, c_v, ds_v, dw_v, dd_v, disp_v, deg_v,
             flags, sems) = refs
        else:
            (scal_ref, keys_ref, disp_h, deg_h, n0, a0, c0,
             n_o, a_o, c_o, meta_o, u_o,
             n_v, a_v, c_v, dd_v, disp_v, deg_v, flags, sems) = refs
        k = pl.program_id(0)
        K = pl.num_programs(0)
        gather, _ = _make_gather(ext_layout, interpret)
        row_l = _iota2((TILE, LANES), 0)
        lane = _iota2((TILE, LANES), 1)
        row0 = scal_ref[0]  # global row of extended row 0 (pre-wrapped)

        @pl.when(k == 0)
        def _init():
            if pushsum:
                _copy_in([(s0, s_v), (w0, w_v), (t0, t_v), (c0, c_v),
                          (disp_h, disp_v), (deg_h, deg_v)], sems)
            else:
                _copy_in([(n0, n_v), (a0, a_v), (c0, c_v),
                          (disp_h, disp_v), (deg_h, deg_v)], sems)
            flags[0] = jnp.int32(0)
            flags[1] = jnp.int32(0)

        u_o[k] = jnp.int32(-1)
        active = scal_ref[1] + k < scal_ref[2]  # start + k < cap

        def tile_coords(t):
            r0 = t * TILE
            grow = lax.rem(row0 + r0 + row_l, jnp.int32(R_glob))
            gflat = grow * LANES + lane  # global padded flat index
            return r0, grow, gflat

        @pl.when(active)
        def _round():
            kk = k % 8
            k1 = keys_ref[kk, 0]
            k2 = keys_ref[kk, 1]

            def p1(t, _):
                r0, grow, gflat = tile_coords(t)
                bits = threefry_bits_rows(k1, k2, grow[:, 0], LANES)
                deg = deg_v[pl.ds(r0, TILE), :]
                deg_safe = jnp.maximum(deg, 1).astype(jnp.uint32)
                slot = (bits % deg_safe).astype(jnp.int32)
                d = disp_v[0, pl.ds(r0, TILE), :]
                for j in range(1, max_deg):
                    d = jnp.where(slot == j, disp_v[j, pl.ds(r0, TILE), :], d)
                padm = gflat >= n
                if pushsum:
                    send_ok = (deg > 0) & ~padm
                    ss = jnp.where(send_ok, s_v[pl.ds(r0, TILE), :] * 0.5, 0.0)
                    ws = jnp.where(send_ok, w_v[pl.ds(r0, TILE), :] * 0.5, 0.0)
                    marked = jnp.where(send_ok, d, jnp.int32(-1))
                    ds_v[pl.ds(r0, TILE), :] = ss
                    ds_v[pl.ds(rows_ext + r0, TILE), :] = ss
                    dw_v[pl.ds(r0, TILE), :] = ws
                    dw_v[pl.ds(rows_ext + r0, TILE), :] = ws
                else:
                    sending = (
                        (a_v[pl.ds(r0, TILE), :] != 0) & (deg > 0) & ~padm
                    )
                    marked = jnp.where(sending, d, jnp.int32(-1))
                dd_v[pl.ds(r0, TILE), :] = marked
                dd_v[pl.ds(rows_ext + r0, TILE), :] = marked
                return 0

            lax.fori_loop(0, T, p1, 0)

            def p2(t, acc):
                r0, grow, gflat = tile_coords(t)
                padm = gflat >= n
                mid = (row_l + r0 >= H) & (row_l + r0 < H + rows_loc)
                if pushsum:
                    inbox_s = jnp.zeros((TILE, LANES), jnp.float32)
                    inbox_w = jnp.zeros((TILE, LANES), jnp.float32)
                    planes = ((ds_v, jnp.float32(0)), (dw_v, jnp.float32(0)))
                    for d_c, e1, e2 in shift_pairs:
                        sa, wa = gather(dd_v, planes, e1, t, d_c)
                        sb, wb = gather(dd_v, planes, e2, t, d_c)
                        take = gflat >= d_c
                        inbox_s = inbox_s + jnp.where(take, sa, sb)
                        inbox_w = inbox_w + jnp.where(take, wa, wb)
                    if global_term:
                        # Global residual: term/conv stream through (the
                        # run loop latches conv after the psum'd verdict);
                        # the metric is MIDDLE unstable valid lanes.
                        return acc + absorb_pushsum_tile(
                            r0, padm, inbox_s, inbox_w,
                            s_v, w_v, t_v, c_v, ds_v, dw_v, delta,
                            term_rounds, global_term=True, count_mask=mid,
                        )
                    # absorb's own count covers halo copies of remote
                    # nodes; recount over the middle region only.
                    absorb_pushsum_tile(
                        r0, padm, inbox_s, inbox_w,
                        s_v, w_v, t_v, c_v, ds_v, dw_v, delta, term_rounds,
                    )
                    conv_mid = jnp.where(
                        mid, c_v[pl.ds(r0, TILE), :], jnp.int32(0)
                    )
                    return acc + jnp.sum(conv_mid, dtype=jnp.int32)
                inbox = jnp.zeros((TILE, LANES), jnp.int32)
                for d_c, e1, e2 in shift_pairs:
                    ga = gather(dd_v, ((dd_v, jnp.int32(-1)),), e1, t, d_c)[0]
                    gb = gather(dd_v, ((dd_v, jnp.int32(-1)),), e2, t, d_c)[0]
                    g = jnp.where(gflat >= d_c, ga, gb)
                    inbox = inbox + jnp.where(g == d_c, jnp.int32(1), jnp.int32(0))
                absorb_gossip_tile(
                    r0, padm, inbox, n_v, a_v, c_v, rumor_target, suppress
                )
                conv_mid = jnp.where(mid, c_v[pl.ds(r0, TILE), :], jnp.int32(0))
                return acc + jnp.sum(conv_mid, dtype=jnp.int32)

            total = lax.fori_loop(0, T, p2, jnp.int32(0))
            flags[0] = flags[0] + 1
            flags[1] = total
            u_o[k] = total

        @pl.when(k == K - 1)
        def _emit():
            if pushsum:
                _copy_in([(s_v, s_o), (w_v, w_o), (t_v, t_o), (c_v, c_o)], sems)
            else:
                _copy_in([(n_v, n_o), (a_v, a_o), (c_v, c_o)], sems)
            meta_o[0] = flags[0]
            meta_o[1] = flags[1]

    def chunk_fn(ext_state, keys, row0, start, cap, disp_ext, deg_ext):
        cap, keys = clamp_cap_and_pad(start, cap, keys)
        K = keys.shape[0]
        f32 = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.float32)
        i32 = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.int32)
        if pushsum:
            out_shape = (f32, f32, i32, i32)
            scratch = [
                pltpu.VMEM((rows_ext, LANES), jnp.float32),
                pltpu.VMEM((rows_ext, LANES), jnp.float32),
                pltpu.VMEM((rows_ext, LANES), jnp.int32),
                pltpu.VMEM((rows_ext, LANES), jnp.int32),
                pltpu.VMEM((2 * rows_ext, LANES), jnp.float32),
                pltpu.VMEM((2 * rows_ext, LANES), jnp.float32),
                pltpu.VMEM((2 * rows_ext, LANES), jnp.int32),
                pltpu.VMEM((max_deg, rows_ext, LANES), jnp.int32),
                pltpu.VMEM((rows_ext, LANES), jnp.int32),
                pltpu.SMEM((2,), jnp.int32),
                pltpu.SemaphoreType.DMA((6,)),
            ]
        else:
            out_shape = (i32, i32, i32)
            scratch = [
                pltpu.VMEM((rows_ext, LANES), jnp.int32),
                pltpu.VMEM((rows_ext, LANES), jnp.int32),
                pltpu.VMEM((rows_ext, LANES), jnp.int32),
                pltpu.VMEM((2 * rows_ext, LANES), jnp.int32),
                pltpu.VMEM((max_deg, rows_ext, LANES), jnp.int32),
                pltpu.VMEM((rows_ext, LANES), jnp.int32),
                pltpu.SMEM((2,), jnp.int32),
                pltpu.SemaphoreType.DMA((5,)),
            ]
        outs = pl.pallas_call(
            kernel,
            grid=(K,),
            out_shape=out_shape + (
                jax.ShapeDtypeStruct((2,), jnp.int32),
                jax.ShapeDtypeStruct((K,), jnp.int32),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((8, 2), lambda k: (k // 8, 0), memory_space=pltpu.SMEM),
            ]
            + [pl.BlockSpec(memory_space=pl.ANY)] * (2 + len(ext_state)),
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * len(ext_state)
                + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
            ),
            scratch_shapes=scratch,
            compiler_params=compat.pallas_tpu_compiler_params(
                vmem_limit_bytes=120 * 1024 * 1024
            ),
            interpret=interpret,
        )(
            jnp.stack(
                [jnp.int32(row0), jnp.int32(start), jnp.int32(cap)]
            ),
            keys,
            disp_ext,
            deg_ext,
            *ext_state,
        )
        meta = outs[len(ext_state)]
        u = outs[len(ext_state) + 1]
        return tuple(outs[: len(ext_state)]), meta[0], meta[1], u

    return chunk_fn, rows_ext


def run_fused_sharded(
    topo: Topology,
    cfg: SimConfig,
    mesh=None,
    key=None,
    on_chunk=None,
    start_state=None,
    start_round: int = 0,
    probe=None,
    deadline=None,
):
    """Sharded fused run — the engine='fused', n_devices > 1 path.

    Same contract as parallel/sharded.run_sharded; convergence is detected
    at super-step (fused-chunk) granularity, so `rounds` is the first
    boundary at/after true convergence (exact at chunk_rounds=1).

    cfg.overlap_collectives (default on): batched single-pair halo wires
    and the deferred-verdict overlapped super-step loop
    (parallel/overlap.py) — bitwise-identical to the serial schedule.
    termination='global' keeps the serial loop (capped-rerun verdict) on
    batched wires. ``probe(chunk_sharded, args)`` short-circuits the run
    for benchmarks/comm_audit.py (trace, never execute)."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import gossip as gossip_mod
    from ..models import pushsum as pushsum_mod
    from ..models.runner import _check_dtype, draw_leader
    from ..ops import sampling
    from ..ops.fused import round_keys
    from . import halo as halo_mod
    from . import overlap as overlap_mod
    from .mesh import NODE_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh(cfg.n_devices)
    n_dev = mesh.devices.size
    plan = plan_fused_sharded(topo, cfg, n_dev)
    if isinstance(plan, str):
        raise ValueError(f"engine='fused' with n_devices={n_dev} unavailable: {plan}")
    H, rows_loc, CR, layout = plan
    _check_dtype(cfg)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    interpret = jax.default_backend() != "tpu"
    chunk_fn, rows_ext = make_stencil_shard_chunk(
        topo, cfg, H, rows_loc, layout, interpret=interpret
    )
    R_glob = layout.rows
    n = topo.n
    target = cfg.resolved_target_count(n, topo.target_count)
    pushsum = cfg.algorithm == "push-sum"
    global_term = pushsum and cfg.termination == "global"
    key_data_host, key_impl = sampling.key_split(key)

    disp_np, deg_np = _build_disp_planes(topo, layout)
    shard_rows = NamedSharding(mesh, P(NODE_AXIS, None))
    shard_disp = NamedSharding(mesh, P(None, NODE_AXIS, None))
    repl = NamedSharding(mesh, P())

    plane_fields = (
        [("s", np.float32, 0.0), ("w", np.float32, 1.0),
         ("term", np.int32, cfg.initial_term_round), ("conv", np.int32, 0)]
        if pushsum
        else [("count", np.int32, 0), ("active", np.int32, 0),
              ("conv", np.int32, 0)]
    )

    def to_planes(state):
        """Canonical (flat, unpadded) state -> padded [R_glob, 128] planes.
        Pad fills mirror parallel/sharded.py: inert weight 1 so pad ratios
        are 0/1, never NaN."""
        outs = []
        for f, dt, fill in plane_fields:
            x = np.asarray(getattr(state, f)).astype(dt)
            full = np.full(layout.n_pad, fill, dtype=dt)
            full[: x.shape[0]] = x
            outs.append(full.reshape(R_glob, LANES))
        return tuple(outs)

    if start_state is not None:
        st0 = jax.tree.map(np.asarray, start_state)
    elif pushsum:
        st0 = pushsum_mod.init_state(n, jnp.float32, cfg.initial_term_round)
    else:
        st0 = gossip_mod.init_state(
            n, draw_leader(key, topo, cfg),
            leader_counts_receipt=cfg.reference and topo.kind == "full",
        )
    planes0 = tuple(
        jax.device_put(p, shard_rows) for p in to_planes(st0)
    )
    disp_dev = jax.device_put(disp_np, shard_disp)
    deg_dev = jax.device_put(deg_np, shard_rows)
    done0 = bool(np.asarray(st0.conv).sum() >= target)

    perm_fwd = [(d, (d + 1) % n_dev) for d in range(n_dev)]
    perm_bwd = [(d, (d - 1) % n_dev) for d in range(n_dev)]
    overlap = cfg.overlap_collectives

    def ext_rows(x):
        """[rows_loc, ...] local plane -> halo-extended [rows_ext, ...]:
        left halo = left neighbor's last H rows, right = right neighbor's
        first H rows (ring order = global row order)."""
        left = lax.ppermute(x[-H:], NODE_AXIS, perm_fwd)
        right = lax.ppermute(x[:H], NODE_AXIS, perm_bwd)
        return jnp.concatenate([left, x, right], axis=0)

    def exchange(planes):
        """State-plane halo exchange: one batched ppermute pair for all
        planes under the overlap schedule, one pair per plane otherwise."""
        if overlap:
            return halo_mod.exchange_rows_batched(
                planes, H, NODE_AXIS, n_dev
            )
        return tuple(ext_rows(p) for p in planes)

    def chunk_local(planes_in, rnd_in, done_in, round_end, key_data,
                    disp_loc, deg_loc):
        # The displacement/degree planes are round-invariant: assemble
        # their halo-extended form ONCE per jitted call, not per super-step
        # (max_deg+1 loop-invariant ppermute pairs otherwise); the batched
        # wire folds even those into one pair.
        if overlap:
            topo_ext = halo_mod.exchange_rows_batched(
                tuple(disp_loc[j] for j in range(disp_loc.shape[0]))
                + (deg_loc,),
                H, NODE_AXIS, n_dev,
            )
            disp_ext = jnp.stack(topo_ext[:-1])
            deg_ext = topo_ext[-1]
        else:
            disp_ext = jnp.stack(
                [ext_rows(disp_loc[j]) for j in range(disp_loc.shape[0])]
            )
            deg_ext = ext_rows(deg_loc)

        base = sampling.key_join(key_data, key_impl)
        dev = lax.axis_index(NODE_AXIS)
        row0 = lax.rem(
            dev.astype(jnp.int32) * rows_loc - H + 2 * R_glob,
            jnp.int32(R_glob),
        )

        if overlap and not global_term:
            # Overlapped super-step schedule (parallel/overlap.py): verdict
            # psum deferred under the next kernel, next exchange adjacent
            # to the kernel output, exact rollback on a fired verdict.
            def compute(ext_state, rnd, cap):
                keys = round_keys(base, rnd, CR)
                out_ext, executed, conv_mid, _u = chunk_fn(
                    ext_state, keys, row0, rnd, cap, disp_ext, deg_ext
                )
                mid = tuple(o[H:H + rows_loc] for o in out_ext)
                return mid, executed, conv_mid

            return overlap_mod.overlapped_superstep_loop(
                planes_in, rnd_in, done_in, round_end,
                exchange=exchange, compute=compute,
                psum_metric=lambda m: lax.psum(m, NODE_AXIS),
                target=target,
            )

        def cond(c):
            _, rnd, done = c
            return jnp.logical_and(~done, rnd < round_end)

        def body(c):
            planes, rnd, _ = c
            ext_state = exchange(planes)
            keys = round_keys(base, rnd, CR)
            out_ext, executed, conv_mid, u = chunk_fn(
                ext_state, keys, row0, rnd, round_end, disp_ext, deg_ext
            )
            if global_term:
                def run_capped(cap):
                    out2 = chunk_fn(
                        ext_state, keys, row0, rnd, cap, disp_ext, deg_ext
                    )[0]
                    return tuple(o[H : H + rows_loc] for o in out2)

                return global_verdict_step(
                    run_capped, tuple(o[H : H + rows_loc] for o in out_ext),
                    executed, u, rnd, rows_loc, n, NODE_AXIS,
                )
            planes = tuple(o[H : H + rows_loc] for o in out_ext)
            total = lax.psum(conv_mid, NODE_AXIS)
            return (planes, rnd + executed, total >= target)

        return lax.while_loop(cond, body, (planes_in, rnd_in, done_in))

    plane_specs = tuple(P(NODE_AXIS, None) for _ in planes0)
    # Donation (models/pipeline.py): output planes alias the input's
    # buffers; off when retired state must stay readable.
    donate = on_chunk is None and not cfg.stall_chunks
    chunk_sharded = jax.jit(
        compat.shard_map(
            chunk_local,
            mesh=mesh,
            in_specs=(
                plane_specs, P(), P(),
                P(), P(), P(None, NODE_AXIS, None), P(NODE_AXIS, None),
            ),
            out_specs=(plane_specs, P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    def rep_put(x):
        return jax.device_put(x, repl)

    kd_dev = rep_put(np.asarray(key_data_host))
    rnd0 = rep_put(np.int32(start_round))
    done0_dev = rep_put(np.bool_(done0))

    def to_canonical(planes):
        flats = [p.reshape(-1)[:n] for p in planes]
        if pushsum:
            return pushsum_mod.PushSumState(
                s=flats[0], w=flats[1], term=flats[2], conv=flats[3] != 0
            )
        return gossip_mod.GossipState(
            count=flats[0], active=flats[1] != 0, conv=flats[2] != 0
        )

    if probe is not None:
        return probe(chunk_sharded, (
            planes0, rnd0, done0_dev,
            rep_put(np.int32(min(start_round + CR, cfg.max_rounds))),
            kd_dev, disp_dev, deg_dev,
        ), donate=donate)

    t0 = time.perf_counter()
    warm = chunk_sharded(
        tuple(jnp.copy(p) for p in planes0) if donate else planes0,
        rnd0, done0_dev,
        rep_put(np.int32(min(start_round + CR, cfg.max_rounds))),
        kd_dev, disp_dev, deg_dev,
    )
    int(warm[1])
    del warm
    compile_s = time.perf_counter() - t0

    from ..models import pipeline as pipeline_mod
    from ..models.runner import (
        StallWatchdog,
        _cancel_fn,
        _finalize_result,
        _progress_gap,
    )

    watchdog = StallWatchdog(cfg.stall_chunks)

    def dispatch(planes, rnd, done, round_end):
        return chunk_sharded(
            planes, rnd, done, rep_put(np.int32(round_end)), kd_dev,
            disp_dev, deg_dev,
        )

    on_retire = None
    if on_chunk is not None:
        def on_retire(rounds, planes):
            on_chunk(rounds, to_canonical(planes))

    should_stop = None
    if cfg.stall_chunks:
        # This engine rejects crash models (plan gate), so the gap is the
        # legacy target distance.
        def should_stop(rounds, planes):
            return watchdog.no_progress(
                _progress_gap(None, cfg.quorum, target, planes[-1], rounds)
            )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=planes0, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds,
        stride=cfg.chunk_rounds * 8, depth=cfg.pipeline_chunks,
        donate=donate, on_retire=on_retire, should_stop=should_stop,
        should_cancel=_cancel_fn(deadline),
        step_timing=cfg.step_timing,
        hook_error=("raise" if cfg.strict_checkpoint else "continue"),
    )
    run_s = time.perf_counter() - t1

    return _finalize_result(
        topo, cfg, to_canonical(loop.state), loop.rounds, target,
        compile_s, run_s, done=loop.done, stalled=watchdog.stalled,
        cancelled=loop.cancelled,
    )


# --- Declared wire contract (analysis/wire_specs.py) -----------------------
# Per SUPER-STEP: the batched schedule packs every state plane's halo into
# ONE ppermute pair + the deferred verdict psum; the serial schedule pays a
# pair per plane. Per-dispatch setup: batched = pre-loop state-exchange
# pair + round-invariant disp/deg pair (4 ppermutes) + the drain psum;
# serial extends disp/deg per neighbor slot instead (max_deg + 1 pairs, no
# pre-loop exchange, no drain).
WIRE_SPEC = WireSpec(
    engine="fused-sharded",
    variants={
        ("overlap", "wire"): Regions(
            body={"ppermute": C(fixed=2), "psum": C(fixed=1)},
            setup={"ppermute": C(fixed=4), "psum": C(fixed=1)},
        ),
        ("serial", "wire"): Regions(
            body={"ppermute": C(per_plane=2), "psum": C(fixed=1)},
            setup={"ppermute": C(per_pair=2)},
        ),
    },
    mechanism={"wire": "xla-ppermute"},
    equal_bytes=("ppermute",),
)
