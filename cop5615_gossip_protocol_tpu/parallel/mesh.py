"""Device mesh construction — the communication backend's topology.

Replaces the reference's L1 "communication backend" (one in-process Akka
ActorSystem with a thread-pool dispatcher, program.fs:23; Akka.Cluster is
referenced in project3.fsproj:13-15 but never configured — SURVEY.md C14).
Here the backend is a `jax.sharding.Mesh` with a single ``"nodes"`` axis:
each device owns a contiguous shard of the node dimension, cross-shard
message traffic is XLA collectives (`psum_scatter`, `all_gather`, `psum`)
riding ICI within a slice and DCN across slices — no hand-written transport.

The elastic re-placement contract (ISSUE 19): state leaves this module
only in GLOBAL row order (checkpoints store host-side global arrays,
utils/checkpoint) and re-enters exclusively through `put_global` /
`put_rows` against whatever mesh the RESUMING process built — so a
checkpoint cut at P devices owes nothing to that mesh and resumes at any
P' (shrink, grow, down to one device) by re-placement alone. Trajectory
bitwiseness across the move is pinned in tests/test_recovery.py
(test_elastic_mesh_resume_bitwise): exact for integer gossip state
everywhere, and for push-sum float32 state within the sharded family —
the single-device chunked engine preserves denormals the sharded
all-reduce flushes to zero, the one documented P'=1 caveat (README
"Durability")."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

NODE_AXIS = "nodes"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the node dimension.

    On a TPU slice the default device order already follows the physical
    torus, so contiguous node shards map to ICI-adjacent chips — grid
    topologies' halo traffic stays on-torus.
    """
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices < 1 or n_devices > len(devices):
        raise ValueError(
            f"n_devices={n_devices} out of range; {len(devices)} device(s) visible"
        )
    return Mesh(np.asarray(devices[:n_devices]), (NODE_AXIS,))


def put_global(host_array, sharding):
    """Host array -> global device array under ``sharding``, process-safe.

    Single-process meshes shard straight from host memory (`jax.device_put`
    — wrapping in jnp.asarray first would commit the whole array to the
    default device before resharding, a transient full-size HBM spike at
    the 16M-node scale). When the mesh spans OS processes
    (initialize_distributed) the sharding is not fully addressable and
    `jax.device_put` cannot build the global array: every process instead
    materializes its own addressable shards from the (deterministically
    rebuilt) host array via `jax.make_array_from_callback`. Extracted from
    parallel/sharded.py's dev_put (ISSUE 15) so every sharded composition
    shares the one multi-process placement path."""
    host_array = np.asarray(host_array)
    if sharding.is_fully_addressable:
        return jax.device_put(host_array, sharding)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx]
    )


def put_rows(sharding, shape, dtype, rows_fn):
    """Host-SHARDED construction of a row-sharded [rows, ...] device array:
    ``rows_fn(lo, hi) -> np.ndarray[hi-lo, ...]`` builds ONLY the requested
    row range, and `jax.make_array_from_callback` invokes it once per
    addressable shard — so peak host memory is O(rows / n_processes ...
    per-device shard), never the global array (ISSUE 15 tentpole: a 2^30
    plane build must not materialize on one host). Works on single- and
    multi-process meshes alike (the callback path is addressable-shard
    local in both)."""
    rows = shape[0]

    def build(idx):
        rs = idx[0]
        lo = rs.start or 0
        hi = rows if rs.stop is None else rs.stop
        block = rows_fn(lo, hi)
        rest = tuple(idx[1:])
        if rest:
            block = block[(slice(None),) + rest]
        return np.ascontiguousarray(block.astype(dtype, copy=False))

    return jax.make_array_from_callback(tuple(shape), sharding, build)


def flat_id_rows(lanes: int):
    """(lo, hi) -> [hi - lo, lanes] int64 global FLAT ids for a
    row-of-lanes plane layout — the shared ingredient of the host-sharded
    fresh-plane builders (push-sum's s_i = i, gossip's leader membership,
    pad masks are all pure functions of the flat id). One home (ISSUE 15)
    so the compositions' per-shard builders cannot drift in id math."""
    def ids(lo: int, hi: int):
        return np.arange(
            lo * lanes, hi * lanes, dtype=np.int64
        ).reshape(hi - lo, lanes)

    return ids


def const_row_builder(value, dtype, lanes: int):
    """rows_fn filling every cell with ``value`` — the constant planes
    (w = 1, term = initial, conv = 0) of a host-sharded fresh start."""
    def build(lo: int, hi: int):
        return np.full((hi - lo, lanes), value, dtype)

    return build


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bring-up: `jax.distributed.initialize` then build the mesh
    over `jax.devices()` (global). The same `shard_map` program then spans
    hosts — XLA routes inter-host collective legs over DCN. The reference has
    no counterpart (its Akka.Cluster dependency is never exercised, C14);
    this is the capability it only gestured at. No-op if already initialized.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Idempotent bring-up for notebook/CLI reuse — but ONLY for the
        # already-initialized case. A connect failure must propagate: if it
        # were swallowed, every process would proceed as a lone process 0
        # and silently run its own full simulation.
        if "already initialized" not in str(e).lower():
            raise
