"""Device mesh construction — the communication backend's topology.

Replaces the reference's L1 "communication backend" (one in-process Akka
ActorSystem with a thread-pool dispatcher, program.fs:23; Akka.Cluster is
referenced in project3.fsproj:13-15 but never configured — SURVEY.md C14).
Here the backend is a `jax.sharding.Mesh` with a single ``"nodes"`` axis:
each device owns a contiguous shard of the node dimension, cross-shard
message traffic is XLA collectives (`psum_scatter`, `all_gather`, `psum`)
riding ICI within a slice and DCN across slices — no hand-written transport.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

NODE_AXIS = "nodes"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the node dimension.

    On a TPU slice the default device order already follows the physical
    torus, so contiguous node shards map to ICI-adjacent chips — grid
    topologies' halo traffic stays on-torus.
    """
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices < 1 or n_devices > len(devices):
        raise ValueError(
            f"n_devices={n_devices} out of range; {len(devices)} device(s) visible"
        )
    return Mesh(np.asarray(devices[:n_devices]), (NODE_AXIS,))


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bring-up: `jax.distributed.initialize` then build the mesh
    over `jax.devices()` (global). The same `shard_map` program then spans
    hosts — XLA routes inter-host collective legs over DCN. The reference has
    no counterpart (its Akka.Cluster dependency is never exercised, C14);
    this is the capability it only gestured at. No-op if already initialized.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Idempotent bring-up for notebook/CLI reuse — but ONLY for the
        # already-initialized case. A connect failure must propagate: if it
        # were swallowed, every process would proceed as a lone process 0
        # and silently run its own full simulation.
        if "already initialized" not in str(e).lower():
            raise
