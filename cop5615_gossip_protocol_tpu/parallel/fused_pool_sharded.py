"""Fused pool x sharded: the flagship implicit-full topology across chips.

parallel/fused_sharded.py composes the fused engines with node sharding for
offset-STRUCTURED topologies (halo amortization needs bounded displacement
width). The implicit full topology has no such structure — each round's pool
displacements are uniform over the whole ring (ops/sampling.pool_offsets),
so information propagates globally every round and no halo can stay valid
across rounds. What IS bounded is the payload: everything a round delivers
derives from three per-node planes (send halves s/2, w/2 and the pool
choice). This module therefore composes per round instead of per super-step:

1. each device derives its shard of the send planes locally (one halve —
   plain XLA elementwise; for gossip a single active-senders int plane);
2. ONE `all_gather` per round replicates those planes ([R_glob, 128] rows);
3. a per-shard `pallas_call` rebuilds the single-device pool kernel's
   doubled send planes in VMEM from the gathered rows, regenerates the
   pool-choice plane IN-KERNEL at global positions (threefry is
   position-wise, so the plane is bitwise the single-device `_choice_tile`
   stream — zero collective payload for it), and replays the single-device
   p2 delivery+absorb (ops/fused_pool._make_gather_modn, same slot order,
   same float accumulation order) on exactly its own tiles.

Because every tile's arithmetic is the single-device fused pool kernel's
arithmetic on the same operands, sharded trajectories are BITWISE the
single-device fused pool trajectories at every device count — gossip int
state exactly, push-sum floats to the last bit — and hence match the
chunked collective pool path (parallel/halo.deliver_pool_sharded) wherever
that path matches the single-device engines (tests/test_halo.py). rounds
are detected exactly per round (one scalar psum), not at super-step
granularity.

Collective payload per round: 8 bytes/node (push-sum s/2 + w/2) or 4
(gossip) — within ~1.5x of the information-theoretic floor for a topology
whose every message crosses shards with probability (n_dev-1)/n_dev.
Termination='global' is supported: the kernel's absorb returns the
unstable-lane count (the same rule as absorb_pushsum_tile's global branch), a
scalar psum composes the verdict, and the conv latch is applied in XLA.

Reference mapping: C15's recast of the reference's WHOLE runtime — the
full-topology push-sum/gossip hot loop (program.fs:23, 191-225) — at the
BASELINE.json multi-chip shapes (VERDICT r3 #1).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import SimConfig
from ..ops.fused_pool import (
    LANES,
    TC_CONV_BIT as _TC_CONV_BIT,
    TC_TERM_MASK as _TC_TERM_MASK,
    TILE,
    _choice_tile,
    _copy_in,
    _iota2,
    _make_gather_modn,
    absorb_gossip_tile,
    build_pool_layout,
    pool_common_support,
)
from ..ops.topology import Topology


def plan_fused_pool_sharded(topo: Topology, cfg: SimConfig, n_dev: int):
    """(rows_loc, layout) or a string reason why the composition can't run."""
    if cfg.delivery != "pool":
        return (
            "the fused pool composition requires delivery='pool' (the same "
            "gate as the single-device pool engine dispatch)"
        )
    reason = pool_common_support(topo, cfg)
    if reason is not None:
        return reason
    layout = build_pool_layout(topo.n)
    R = layout.rows
    if R % n_dev != 0 or (R // n_dev) % TILE != 0:
        return (
            f"padded layout ({R} rows) must split into whole {TILE}-row "
            f"tiles per device; {n_dev} devices do not divide it"
        )
    return (R // n_dev, layout)


def make_pool_shard_round(
    cfg: SimConfig, rows_loc: int, layout, *, interpret: bool = False
):
    """Per-device one-round kernel.

    push-sum: ``round_fn(s_full, w_full, (s, w, tc)_loc, key2, offs, tile0)
    -> ((s, w, tc)_loc', metric)`` — metric is the shard's converged count
    (local latch) or unstable count (global residual).
    gossip: ``round_fn(vals_full, state3_loc, key2, offs, tile0)
    -> (state3_loc', conv_count)``.

    ``*_full`` are the all-gathered [R_glob, 128] send planes; ``tile0``
    the device's first global tile index. The kernel body is the
    single-device pool kernel's round (ops/fused_pool.py) restricted to
    the shard's tiles, reading sends from the gathered planes — bitwise
    the same trajectory at every device count."""
    R = layout.rows
    N = layout.n
    T_glob = R // TILE
    T_loc = rows_loc // TILE
    P = cfg.pool_size
    pushsum = cfg.algorithm == "push-sum"
    global_term = cfg.termination == "global"
    delta = np.float32(cfg.resolved_delta)
    term_rounds = np.int32(cfg.term_rounds)
    rumor_target = np.int32(cfg.resolved_rumor_target)
    suppress = cfg.resolved_suppress

    def kernel_pushsum(
        scal_ref, key_ref, offs_ref, s_full, w_full, tc0,
        s_o, w_o, tc_o, meta_o,
        s_v, w_v, tc_v, ds_d, dw_d, dc_d, sems,
    ):
        gather_modn, _ = _make_gather_modn(layout, interpret)
        row_l = _iota2((TILE, LANES), 0)
        lane = _iota2((TILE, LANES), 1)
        tile0 = scal_ref[0]
        # The gathered s/w planes stay RAW — they double as both the send
        # planes (the halve moves to the inbox, see p2) and this shard's
        # own state (read at its global rows). Margins mirror rows
        # [0, TILE): _make_gather reads rows [sa, sa+TILE) with sa < R, so
        # R+TILE rows replace the single-device engine's full second copy.
        # term+conv ride ONE packed plane (conv in bit 30) to halve the
        # per-round counter traffic.
        cps = [
            pltpu.make_async_copy(src, dst, sems.at[i])
            for i, (src, dst) in enumerate(
                [(tc0, tc_v),
                 (s_full, ds_d.at[pl.ds(0, R), :]),
                 (w_full, dw_d.at[pl.ds(0, R), :]),
                 (s_full.at[pl.ds(0, TILE), :], ds_d.at[pl.ds(R, TILE), :]),
                 (w_full.at[pl.ds(0, TILE), :], dw_d.at[pl.ds(R, TILE), :])]
            )
        ]
        for cp in cps:
            cp.start()
        # The choice-plane build needs only the round key — it runs UNDER
        # the in-flight state/plane DMAs; the wait lands after it.

        def gen(tg, _):
            # Choice plane with pads folded in as -1 (matches no slot): the
            # raw pad values (w = 1) are never delivered — the
            # single-device ws pad masking, moved into the mask plane.
            r0 = tg * TILE
            jflat = (r0 + row_l) * LANES + lane
            padm = jflat >= N
            ch = jnp.where(
                padm, jnp.int32(-1),
                _choice_tile(key_ref[0], key_ref[1], tg, P),
            )
            dc_d[pl.ds(r0, TILE), :] = ch

            @pl.when(tg == 0)
            def _margin():
                dc_d[pl.ds(R, TILE), :] = ch

            return 0

        lax.fori_loop(0, T_glob, gen, 0)
        for cp in cps:
            cp.wait()

        def p2(t, acc):
            r0 = t * TILE
            tg = tile0 + t
            r0g = tg * TILE
            jflat = (r0g + row_l) * LANES + lane
            padm = jflat >= N
            raw_s = jnp.zeros((TILE, LANES), jnp.float32)
            raw_w = jnp.zeros((TILE, LANES), jnp.float32)
            planes = ((ds_d, jnp.float32(0)), (dw_d, jnp.float32(0)))
            for slot in range(P):
                d = offs_ref[slot]
                s1, w1 = gather_modn(dc_d, planes, d, tg, slot, jflat)
                raw_s = raw_s + s1
                raw_w = raw_w + w1
            # Halve AFTER the masked-gather sum: x0.5 is an exact
            # power-of-two scaling that commutes with every IEEE rounding
            # in the sum, so this is bitwise the single-device inbox built
            # from pre-halved sends (the subnormal caveat needs a weight
            # below 2^-125, i.e. ~125 consecutive non-receipt halvings —
            # probability ~e^-125 per node; pinned bitwise by the tests).
            half = jnp.float32(0.5)
            inbox_s = jnp.where(padm, 0.0, raw_s * half)
            inbox_w = jnp.where(padm, 0.0, raw_w * half)
            s_t = ds_d[pl.ds(r0g, TILE), :]
            w_t = dw_d[pl.ds(r0g, TILE), :]
            s_send = jnp.where(padm, 0.0, s_t * half)
            w_send = jnp.where(padm, 0.0, w_t * half)
            s_new = (s_t - s_send) + inbox_s
            w_new = (w_t - w_send) + inbox_w
            if global_term:
                ratio_old = s_t / w_t
                tol = delta * jnp.maximum(
                    jnp.abs(ratio_old), jnp.float32(1)
                )
                unstable = (
                    jnp.abs(s_new / w_new - ratio_old) > tol
                ) & ~padm
                s_v[pl.ds(r0, TILE), :] = s_new
                w_v[pl.ds(r0, TILE), :] = w_new
                return acc + jnp.sum(
                    unstable.astype(jnp.int32), dtype=jnp.int32
                )
            received = inbox_w > 0
            stable = jnp.abs(s_new / w_new - s_t / w_t) <= delta
            tc = tc_v[pl.ds(r0, TILE), :]
            term = tc & _TC_TERM_MASK
            conv_old = (tc & _TC_CONV_BIT) != 0
            term_new = jnp.where(
                received, jnp.where(stable, term + 1, jnp.int32(0)), term
            )
            conv_new = (
                (conv_old | (term_new >= term_rounds)) & ~padm
            )
            tc_new = jnp.where(
                conv_new, term_new | _TC_CONV_BIT, term_new
            )
            s_v[pl.ds(r0, TILE), :] = s_new
            w_v[pl.ds(r0, TILE), :] = w_new
            tc_v[pl.ds(r0, TILE), :] = tc_new
            return acc + jnp.sum(conv_new.astype(jnp.int32), dtype=jnp.int32)

        total = lax.fori_loop(0, T_loc, p2, jnp.int32(0))
        meta_o[0] = total
        _copy_in([(s_v, s_o), (w_v, w_o), (tc_v, tc_o)], sems)

    def kernel_gossip(
        scal_ref, key_ref, offs_ref, act_full, n0, c0,
        n_o, a_o, c_o, meta_o,
        n_v, a_v, c_v, dm_d, sems,
    ):
        _, gather_plain_modn = _make_gather_modn(layout, interpret)
        row_l = _iota2((TILE, LANES), 0)
        lane = _iota2((TILE, LANES), 1)
        tile0 = scal_ref[0]
        r0_loc = scal_ref[1]
        # Own active rows copy straight from the gathered plane in the same
        # DMA volley (not from dm_d, which gen overwrites with marks).
        _copy_in(
            [(n0, n_v), (c0, c_v),
             (act_full, dm_d.at[pl.ds(0, R), :]),
             (act_full.at[pl.ds(r0_loc, rows_loc), :], a_v)],
            sems,
        )

        def gen(tg, _):
            # Marked plane = sender's choice or -1 — the single-device
            # gossip pool kernel's send-gate-folded plane, rebuilt in place
            # from the gathered raw active plane + in-kernel global choice.
            r0 = tg * TILE
            jflat = (r0 + row_l) * LANES + lane
            padm = jflat >= N
            ch = _choice_tile(key_ref[0], key_ref[1], tg, P)
            marked = jnp.where(
                (dm_d[pl.ds(r0, TILE), :] != 0) & ~padm, ch, jnp.int32(-1)
            )
            dm_d[pl.ds(r0, TILE), :] = marked

            @pl.when(tg == 0)
            def _margin():
                dm_d[pl.ds(R, TILE), :] = marked

            return 0

        lax.fori_loop(0, T_glob, gen, 0)

        def p2(t, acc):
            r0 = t * TILE
            tg = tile0 + t
            jflat = (tg * TILE + row_l) * LANES + lane
            padm = jflat >= N
            inbox = jnp.zeros((TILE, LANES), jnp.int32)
            for slot in range(P):
                d = offs_ref[slot]
                g = gather_plain_modn(dm_d, d, tg, jflat)
                inbox = inbox + jnp.where(g == slot, jnp.int32(1), jnp.int32(0))
            return acc + absorb_gossip_tile(
                r0, padm, inbox, n_v, a_v, c_v, rumor_target, suppress
            )

        total = lax.fori_loop(0, T_loc, p2, jnp.int32(0))
        meta_o[0] = total
        _copy_in([(n_v, n_o), (a_v, a_o), (c_v, c_o)], sems)

    f32l = jax.ShapeDtypeStruct((rows_loc, LANES), jnp.float32)
    i32l = jax.ShapeDtypeStruct((rows_loc, LANES), jnp.int32)
    smem_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # tile0
        pl.BlockSpec(memory_space=pltpu.SMEM),  # round key [2] uint32
        pl.BlockSpec(memory_space=pltpu.SMEM),  # offs [P]
    ]
    params = pltpu.CompilerParams(vmem_limit_bytes=120 * 1024 * 1024)

    if pushsum:

        def round_fn(s_full, w_full, state3, key2, offs, tile0):
            s, w, tc = state3
            outs = pl.pallas_call(
                kernel_pushsum,
                grid=(1,),
                out_shape=(f32l, f32l, i32l,
                           jax.ShapeDtypeStruct((1,), jnp.int32)),
                in_specs=smem_specs + [pl.BlockSpec(memory_space=pl.ANY)] * 3,
                out_specs=tuple(
                    [pl.BlockSpec(memory_space=pl.ANY)] * 3
                    + [pl.BlockSpec(memory_space=pltpu.SMEM)]
                ),
                scratch_shapes=[
                    pltpu.VMEM((rows_loc, LANES), jnp.float32),
                    pltpu.VMEM((rows_loc, LANES), jnp.float32),
                    pltpu.VMEM((rows_loc, LANES), jnp.int32),
                    pltpu.VMEM((R + TILE, LANES), jnp.float32),
                    pltpu.VMEM((R + TILE, LANES), jnp.float32),
                    pltpu.VMEM((R + TILE, LANES), jnp.int32),
                    pltpu.SemaphoreType.DMA((5,)),
                ],
                compiler_params=params,
                interpret=interpret,
            )(
                jnp.stack([jnp.int32(tile0), jnp.int32(tile0) * TILE]),
                key2, offs.astype(jnp.int32), s_full, w_full, tc,
            )
            return tuple(outs[:3]), outs[3][0]

    else:

        def round_fn(act_full, state3, key2, offs, tile0):
            cnt, act, cv = state3
            outs = pl.pallas_call(
                kernel_gossip,
                grid=(1,),
                out_shape=(i32l, i32l, i32l,
                           jax.ShapeDtypeStruct((1,), jnp.int32)),
                in_specs=smem_specs + [pl.BlockSpec(memory_space=pl.ANY)] * 3,
                out_specs=tuple(
                    [pl.BlockSpec(memory_space=pl.ANY)] * 3
                    + [pl.BlockSpec(memory_space=pltpu.SMEM)]
                ),
                scratch_shapes=[
                    pltpu.VMEM((rows_loc, LANES), jnp.int32),
                    pltpu.VMEM((rows_loc, LANES), jnp.int32),
                    pltpu.VMEM((rows_loc, LANES), jnp.int32),
                    pltpu.VMEM((R + TILE, LANES), jnp.int32),
                    pltpu.SemaphoreType.DMA((4,)),
                ],
                compiler_params=params,
                interpret=interpret,
            )(
                jnp.stack([jnp.int32(tile0), jnp.int32(tile0) * TILE]),
                key2, offs.astype(jnp.int32), act_full, cnt, cv,
            )
            return tuple(outs[:3]), outs[3][0]

    return round_fn


def run_fused_pool_sharded(
    topo: Topology,
    cfg: SimConfig,
    mesh=None,
    key=None,
    on_chunk=None,
    start_state=None,
    start_round: int = 0,
):
    """Sharded fused pool run — engine='fused', n_devices > 1, implicit full
    topology with delivery='pool'. Same contract as run_sharded; rounds are
    detected exactly per round (scalar psum each round)."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import gossip as gossip_mod
    from ..models import pushsum as pushsum_mod
    from ..models.runner import _check_dtype, _finalize_result, draw_leader
    from ..ops import sampling
    from ..ops.fused import round_keys
    from ..ops.fused_pool import round_offsets
    from .mesh import NODE_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh(cfg.n_devices)
    n_dev = mesh.devices.size
    plan = plan_fused_pool_sharded(topo, cfg, n_dev)
    if isinstance(plan, str):
        raise ValueError(
            f"engine='fused' with n_devices={n_dev} unavailable: {plan}"
        )
    rows_loc, layout = plan
    _check_dtype(cfg)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    interpret = jax.default_backend() != "tpu"
    round_fn = make_pool_shard_round(
        cfg, rows_loc, layout, interpret=interpret
    )
    R_glob = layout.rows
    T_loc = rows_loc // TILE
    n = topo.n
    target = cfg.resolved_target_count(n, topo.target_count)
    pushsum = cfg.algorithm == "push-sum"
    global_term = cfg.termination == "global"
    key_data_host, key_impl = sampling.key_split(key)

    shard_rows = NamedSharding(mesh, P(NODE_AXIS, None))
    repl = NamedSharding(mesh, P())

    def _pad_plane(x, fill, dt):
        full = np.full(layout.n_pad, fill, dtype=dt)
        full[: x.shape[0]] = x.astype(dt)
        return full.reshape(R_glob, LANES)

    def to_planes(state):
        if pushsum:
            tc = (
                np.asarray(state.term).astype(np.int64)
                | np.where(np.asarray(state.conv), int(_TC_CONV_BIT), 0)
            ).astype(np.int32)
            return (
                _pad_plane(np.asarray(state.s), 0.0, np.float32),
                _pad_plane(np.asarray(state.w), 1.0, np.float32),
                _pad_plane(tc, cfg.initial_term_round, np.int32),
            )
        return (
            _pad_plane(np.asarray(state.count), 0, np.int32),
            _pad_plane(np.asarray(state.active), 0, np.int32),
            _pad_plane(np.asarray(state.conv), 0, np.int32),
        )

    if start_state is not None:
        st0 = jax.tree.map(np.asarray, start_state)
    elif pushsum:
        st0 = pushsum_mod.init_state(n, jnp.float32, cfg.initial_term_round)
    else:
        st0 = gossip_mod.init_state(
            n, draw_leader(key, topo, cfg),
            leader_counts_receipt=cfg.reference and topo.kind == "full",
        )
    planes0 = tuple(jax.device_put(p, shard_rows) for p in to_planes(st0))
    done0 = bool(np.asarray(st0.conv).sum() >= target)

    K = int(cfg.chunk_rounds)

    def chunk_local(carry, round_end, key_data):
        base = sampling.key_join(key_data, key_impl)
        dev = lax.axis_index(NODE_AXIS)
        tile0 = dev.astype(jnp.int32) * T_loc
        pos = (
            (dev.astype(jnp.int32) * rows_loc
             + _iota2((rows_loc, LANES), 0)) * LANES
            + _iota2((rows_loc, LANES), 1)
        )
        valid = pos < n
        # Per-round keys/offset pools derived ONCE per dispatch (the host
        # loop guarantees round_end <= start + chunk_rounds) — the in-loop
        # fold_in vmaps cost tens of us per round otherwise.
        rnd0 = carry[1]
        keys_all = round_keys(base, rnd0, K)
        offs_all = round_offsets(base, rnd0, K, cfg.pool_size, n)

        def cond(c):
            _, rnd, done = c
            return jnp.logical_and(~done, rnd < round_end)

        def body(c):
            planes, rnd, _ = c
            idx = rnd - rnd0
            key2 = lax.dynamic_index_in_dim(keys_all, idx, keepdims=False)
            offs = lax.dynamic_index_in_dim(offs_all, idx, keepdims=False)
            if pushsum:
                # RAW planes ride the gather; the kernel halves + masks in
                # VMEM (one HBM read instead of a halve pass + re-read).
                s_full = lax.all_gather(
                    planes[0], NODE_AXIS, axis=0, tiled=True
                )
                w_full = lax.all_gather(
                    planes[1], NODE_AXIS, axis=0, tiled=True
                )
                out, metric = round_fn(
                    s_full, w_full, planes, key2, offs, tile0
                )
                total = lax.psum(metric, NODE_AXIS)
                if global_term:
                    fired = total == 0
                    tc = jnp.where(
                        fired & valid, out[2] | _TC_CONV_BIT, out[2]
                    )
                    return ((out[0], out[1], tc), rnd + 1, fired)
                return (out, rnd + 1, total >= target)
            act_full = lax.all_gather(planes[1], NODE_AXIS, axis=0, tiled=True)
            out, metric = round_fn(act_full, planes, key2, offs, tile0)
            total = lax.psum(metric, NODE_AXIS)
            return (out, rnd + 1, total >= target)

        return lax.while_loop(cond, body, carry)

    plane_specs = tuple(P(NODE_AXIS, None) for _ in planes0)
    chunk_sharded = jax.jit(
        jax.shard_map(
            chunk_local,
            mesh=mesh,
            in_specs=((plane_specs, P(), P()), P(), P()),
            out_specs=(plane_specs, P(), P()),
            check_vma=False,
        )
    )

    def rep_put(x):
        return jax.device_put(x, repl)

    kd_dev = rep_put(np.asarray(key_data_host))
    carry = (planes0, rep_put(np.int32(start_round)), rep_put(np.bool_(done0)))

    def to_canonical(planes):
        flats = [p.reshape(-1)[:n] for p in planes]
        if pushsum:
            tc = flats[2]
            return pushsum_mod.PushSumState(
                s=flats[0], w=flats[1],
                term=tc & _TC_TERM_MASK, conv=(tc & _TC_CONV_BIT) != 0,
            )
        return gossip_mod.GossipState(
            count=flats[0], active=flats[1] != 0, conv=flats[2] != 0
        )

    t0 = time.perf_counter()
    # One real round, discarded — the absolute-round key stream makes the
    # timed loop recompute round 0 identically (the uniform warmup rule).
    warm = chunk_sharded(
        carry, rep_put(np.int32(min(start_round + 1, cfg.max_rounds))), kd_dev
    )
    int(warm[1])
    del warm
    compile_s = time.perf_counter() - t0

    rounds = start_round
    t1 = time.perf_counter()
    while True:
        round_end = min(rounds + cfg.chunk_rounds, cfg.max_rounds)
        carry = chunk_sharded(carry, rep_put(np.int32(round_end)), kd_dev)
        planes, rnd, done = carry
        rounds = int(rnd)
        if on_chunk is not None:
            on_chunk(rounds, to_canonical(planes))
        if bool(done) or rounds >= cfg.max_rounds:
            break
    run_s = time.perf_counter() - t1

    return _finalize_result(
        topo, cfg, to_canonical(carry[0]), rounds, target, compile_s, run_s
    )
