"""Fused pool x sharded: the flagship implicit-full topology across chips.

parallel/fused_sharded.py composes the fused engines with node sharding for
offset-STRUCTURED topologies (halo amortization needs bounded displacement
width). The implicit full topology has no such structure — each round's pool
displacements are uniform over the whole ring (ops/sampling.pool_offsets),
so information propagates globally every round: every node's next state
depends on the whole population, i.e. the halo IS the population.

r5 redesign (VERDICT r4 #5/#7 — the per-round composition ran one
all_gather + one kernel launch + one psum PER ROUND and measured 1.8-2.0x
the single-device engine on a 1-device mesh): this module takes the halo
recompute idea to its full-graph limit. Each super-step:

1. ONE all_gather reassembles the full padded state planes on every
   device (4 planes push-sum, 3 gossip);
2. every device runs the PROVEN single-device multi-round pool kernel
   (ops/fused_pool.make_*_pool_chunk — VMEM-resident state, in-kernel
   convergence, packed in-kernel choices) on its full copy for up to
   chunk_rounds rounds — redundant across devices, exactly like the
   lattice composition's halo recompute, except the "halo" is everything;
3. each device keeps its shard slice of the result; the in-kernel
   convergence verdict is already GLOBAL (the kernel sees the whole
   population), so rounds stop exactly where the single-device engine
   stops — no psum, no verdict rerun.

Why redundant compute is the right trade here: the plan inherits
pool_common_support's population gate (n <= MAX_POOL_NODES = 2^21 — the
VMEM residency bound that makes the single-device kernel exist at all),
so a full round costs ~0.1 ms on one core; meanwhile the collective
payload drops from 2 planes per ROUND (the r4 design — within 1.5x of the
information floor, but paid every round along with a kernel entry and an
HBM state round-trip) to ~4 planes per CHUNK — a ~K/2 x cut in collective
bytes and launches for the BASELINE.json multi-host shapes, which at
these populations are latency/collective-bound, not FLOP-bound. On the
1-device hardware mesh the composition is now within ~1.1x of the
single-device engine (tests_tpu/test_fused_pool_sharded_compiled.py; the
r4 per-round design measured 1.84-2.0x).

Because the chunk IS the single-device kernel on the same operands,
sharded trajectories are BITWISE the single-device fused pool
trajectories at every device count — gossip int state exactly, push-sum
floats to the last bit — and hence match the chunked collective pool path
(parallel/halo.deliver_pool_sharded) wherever that path matches the
single-device engines (tests/test_halo.py). termination='global' rides
the kernel's in-kernel global-residual verdict and all-or-nothing latch
unchanged.

Populations past 2^21 on a mesh: the full topology's per-round
information flow is global, so any exact sharding must move (or
recompute) population-scale data every round; the HBM-streaming pool2
tier covers 2^21..2^27 on ONE chip instead, and the lattice compositions
scale the structured topologies across chips.

Reference mapping: C15's recast of the reference's WHOLE runtime — the
full-topology push-sum/gossip hot loop (program.fs:23, 191-225) — at the
BASELINE.json multi-chip shapes (VERDICT r3 #1).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..config import SimConfig
from ..ops.fused_pool import (
    LANES,
    TILE,
    build_pool_layout,
    make_gossip_pool_chunk,
    make_pushsum_pool_chunk,
    pool_common_support,
)
from ..ops.topology import Topology
from ..utils import compat
from ..analysis.wire_specs import C, Regions, WireSpec


def plan_fused_pool_sharded(topo: Topology, cfg: SimConfig, n_dev: int):
    """(rows_loc, layout) or a string reason why the composition can't run."""
    if jax.process_count() > 1:
        # Multi-process support matrix (ISSUE 15): the VMEM replicated
        # pool composition places its planes with single-process
        # jax.device_put; the implicit-full dispatch falls through to the
        # replicated-pool2 composition (parallel/pool2_sharded.py), which
        # serves multi-process meshes.
        return (
            "the VMEM replicated pool composition is single-process; "
            "under a multi-process mesh the dispatch serves the "
            "replicated-pool2 composition (parallel/pool2_sharded.py) "
            "instead"
        )
    if cfg.delivery != "pool":
        return (
            "the fused pool composition requires delivery='pool' (the same "
            "gate as the single-device pool engine dispatch)"
        )
    reason = pool_common_support(topo, cfg)
    if reason is not None:
        return reason
    if cfg.revive_model:
        # The composition's kernels predate the revival plane; a revive
        # config must not silently run crash-stop here.
        return (
            "crash-recovery (revive) runs on the chunked, sharded, and "
            "single-device VMEM fused stencil/pool engines only"
        )
    if cfg.mass_tolerance is not None:
        return (
            "the health sentinel (--mass-tolerance) runs in the chunked "
            "and sharded XLA round bodies only"
        )
    if cfg.telemetry:
        return (
            "telemetry counters run in the single-device fused kernels and "
            "the chunked/sharded XLA engines; this composition does not "
            "carry the counter block"
        )
    layout = build_pool_layout(topo.n)
    R = layout.rows
    if R % n_dev != 0 or (R // n_dev) % TILE != 0:
        return (
            f"padded layout ({R} rows) must split into whole {TILE}-row "
            f"tiles per device; {n_dev} devices do not divide it"
        )
    return (R // n_dev, layout)


def run_fused_pool_sharded(
    topo: Topology,
    cfg: SimConfig,
    mesh=None,
    key=None,
    on_chunk=None,
    start_state=None,
    start_round: int = 0,
    probe=None,
    deadline=None,
):
    """Sharded fused pool run — engine='fused', n_devices > 1, implicit full
    topology with delivery='pool'. Same contract as run_sharded; rounds are
    EXACT (the replicated in-kernel verdict is already global).

    cfg.overlap_collectives (default on) batches the super-step's gather
    wire: ONE all_gather carrying every plane (parallel/halo.py
    gather_rows_batched, bitcast-packed) instead of one per plane.
    Termination is already off the critical path here by construction — the
    in-kernel verdict is computed on the replicated full copy, no reduction
    collective exists to defer. ``probe(chunk_sharded, args)``
    short-circuits the run for benchmarks/comm_audit.py."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import gossip as gossip_mod
    from ..models import pipeline as pipeline_mod
    from ..models import pushsum as pushsum_mod
    from ..models.runner import (
        StallWatchdog,
        _cancel_fn,
        _check_dtype,
        _finalize_result,
        _host_done,
        _progress_gap,
        draw_leader,
    )
    from ..ops import faults as faults_mod
    from ..ops import sampling
    from ..ops.fused import build_death2d, round_keys
    from ..ops.fused_pool import round_offsets
    from . import halo as halo_mod
    from .mesh import NODE_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh(cfg.n_devices)
    n_dev = mesh.devices.size
    plan = plan_fused_pool_sharded(topo, cfg, n_dev)
    if isinstance(plan, str):
        raise ValueError(
            f"engine='fused' with n_devices={n_dev} unavailable: {plan}"
        )
    rows_loc, layout = plan
    _check_dtype(cfg)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    interpret = jax.default_backend() != "tpu"
    pushsum = cfg.algorithm == "push-sum"
    make = make_pushsum_pool_chunk if pushsum else make_gossip_pool_chunk
    chunk_fn, _layout = make(topo, cfg, interpret=interpret)
    R_glob = layout.rows
    n = topo.n
    target = cfg.resolved_target_count(n, topo.target_count)
    key_data_host, key_impl = sampling.key_split(key)

    shard_rows = NamedSharding(mesh, P(NODE_AXIS, None))
    repl = NamedSharding(mesh, P())

    plane_fields = (
        [("s", np.float32, 0.0), ("w", np.float32, 1.0),
         ("term", np.int32, cfg.initial_term_round), ("conv", np.int32, 0)]
        if pushsum
        else [("count", np.int32, 0), ("active", np.int32, 0),
              ("conv", np.int32, 0)]
    )

    def to_planes(state):
        outs = []
        for f, dt, fill in plane_fields:
            x = np.asarray(getattr(state, f)).astype(dt)
            full = np.full(layout.n_pad, fill, dtype=dt)
            full[: x.shape[0]] = x
            outs.append(full.reshape(R_glob, LANES))
        return tuple(outs)

    if start_state is not None:
        st0 = jax.tree.map(np.asarray, start_state)
    elif pushsum:
        st0 = pushsum_mod.init_state(n, jnp.float32, cfg.initial_term_round)
    else:
        st0 = gossip_mod.init_state(
            n, draw_leader(key, topo, cfg),
            leader_counts_receipt=cfg.reference and topo.kind == "full",
        )
    planes0 = tuple(jax.device_put(p, shard_rows) for p in to_planes(st0))
    done0 = _host_done(
        cfg, faults_mod.life_planes(cfg, n), st0, start_round, target
    )
    # Crash model: the reused pool kernel already runs the quorum verdict
    # in-kernel; this replicated plane lets the composition's OWN done
    # mirror it — without it a crash run's legacy target could stay
    # unreachable and the inner while_loop would spin at executed == 0.
    death2d = build_death2d(cfg, n, layout.n_pad)

    K = int(cfg.chunk_rounds)

    def chunk_local(planes_in, rnd_in, done_in, round_end, key_data):
        base = sampling.key_join(key_data, key_impl)
        dev = lax.axis_index(NODE_AXIS)
        row0 = dev.astype(jnp.int32) * rows_loc

        def cond(c):
            _, rnd, done = c
            return jnp.logical_and(~done, rnd < round_end)

        def body(c):
            planes, rnd, _ = c
            # ONE gather wire per super-step (batched across planes under
            # the default overlap schedule — parallel/halo.py; one
            # all_gather per plane with --overlap-collectives off); the
            # replicated chunk then runs up to K rounds with state
            # VMEM-resident and the global verdict in-kernel.
            if cfg.overlap_collectives:
                full = halo_mod.gather_rows_batched(planes, NODE_AXIS)
            else:
                full = tuple(
                    lax.all_gather(p, NODE_AXIS, axis=0, tiled=True)
                    for p in planes
                )
            keys = round_keys(base, rnd, K)
            offs = round_offsets(base, rnd, K, cfg.pool_size, n)
            out_full, executed = chunk_fn(full, keys, offs, rnd, round_end)
            if death2d is None:
                done = jnp.sum(out_full[-1], dtype=jnp.int32) >= target
            else:
                # Quorum over live nodes at the last executed round —
                # replicated, so it agrees with the in-kernel verdict.
                alive = death2d > rnd + executed - 1
                conv_alive = jnp.sum(
                    jnp.where(alive, out_full[-1], jnp.int32(0)),
                    dtype=jnp.int32,
                )
                need = faults_mod.quorum_need(
                    jnp.sum(alive.astype(jnp.int32), dtype=jnp.int32),
                    cfg.quorum,
                )
                done = conv_alive >= need
            planes_new = tuple(
                # Both indices pinned to int32: under x64 the bare literal
                # promotes to int64 and dynamic_slice rejects the mixed
                # index dtypes (the r5 tier-1 failure class).
                lax.dynamic_slice(o, (row0, jnp.int32(0)), (rows_loc, LANES))
                for o in out_full
            )
            return (planes_new, rnd + executed, done)

        return lax.while_loop(cond, body, (planes_in, rnd_in, done_in))

    plane_specs = tuple(P(NODE_AXIS, None) for _ in planes0)
    # Donation (models/pipeline.py): output shards alias the input's
    # buffers; off when retired state must stay readable.
    donate = on_chunk is None and not cfg.stall_chunks
    chunk_sharded = jax.jit(
        compat.shard_map(
            chunk_local,
            mesh=mesh,
            in_specs=(plane_specs, P(), P(), P(), P()),
            out_specs=(plane_specs, P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    def rep_put(x):
        return jax.device_put(x, repl)

    kd_dev = rep_put(np.asarray(key_data_host))
    rnd0 = rep_put(np.int32(start_round))
    done0_dev = rep_put(np.bool_(done0))

    def to_canonical(planes):
        flats = [p.reshape(-1)[:n] for p in planes]
        if pushsum:
            return pushsum_mod.PushSumState(
                s=flats[0], w=flats[1], term=flats[2], conv=flats[3] != 0
            )
        return gossip_mod.GossipState(
            count=flats[0], active=flats[1] != 0, conv=flats[2] != 0
        )

    if probe is not None:
        return probe(chunk_sharded, (
            planes0, rnd0, done0_dev,
            rep_put(np.int32(min(start_round + 1, cfg.max_rounds))), kd_dev,
        ), donate=donate)

    t0 = time.perf_counter()
    # One real round, discarded — the absolute-round key stream makes the
    # timed loop recompute round 0 identically (the uniform warmup rule).
    # Under donation the warmup consumes a COPY so planes0 stays live.
    warm = chunk_sharded(
        tuple(jnp.copy(p) for p in planes0) if donate else planes0,
        rnd0, done0_dev,
        rep_put(np.int32(min(start_round + 1, cfg.max_rounds))), kd_dev,
    )
    int(warm[1])
    del warm
    compile_s = time.perf_counter() - t0

    watchdog = StallWatchdog(cfg.stall_chunks)

    def dispatch(planes, rnd, done, round_end):
        return chunk_sharded(
            planes, rnd, done, rep_put(np.int32(round_end)), kd_dev
        )

    on_retire = None
    if on_chunk is not None:
        def on_retire(rounds, planes):
            on_chunk(rounds, to_canonical(planes))

    should_stop = None
    if cfg.stall_chunks:
        def should_stop(rounds, planes):
            life2d = (
                None if death2d is None
                else faults_mod.LifePlanes(death=death2d, revive=None)
            )
            return watchdog.no_progress(
                _progress_gap(
                    life2d, cfg.quorum, target, planes[-1], rounds
                )
            )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=planes0, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds,
        stride=cfg.chunk_rounds, depth=cfg.pipeline_chunks, donate=donate,
        on_retire=on_retire, should_stop=should_stop,
        should_cancel=_cancel_fn(deadline),
        step_timing=cfg.step_timing,
        hook_error=("raise" if cfg.strict_checkpoint else "continue"),
    )
    run_s = time.perf_counter() - t1

    return _finalize_result(
        topo, cfg, to_canonical(loop.state), loop.rounds, target,
        compile_s, run_s, done=loop.done, stalled=watchdog.stalled,
        cancelled=loop.cancelled,
    )


# --- Declared wire contract (analysis/wire_specs.py) -----------------------
# Per SUPER-STEP: ONE all_gather of the replicated state planes (batched),
# or one gather per plane serially. The composition's verdict is
# replicated in-kernel — NO reduction collective exists on either
# schedule, and no per-dispatch setup collectives at all.
WIRE_SPEC = WireSpec(
    engine="fused-pool-sharded",
    variants={
        ("overlap", "wire"): Regions(
            body={"all_gather": C(fixed=1)}, setup={},
        ),
        ("serial", "wire"): Regions(
            body={"all_gather": C(per_plane=1)}, setup={},
        ),
    },
    mechanism={"wire": "all-gather"},
    equal_bytes=("all_gather",),
)
