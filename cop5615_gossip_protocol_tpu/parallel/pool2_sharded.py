"""Replicated-pool2: the full topology past one chip's HBM ceiling.

The full topology is the O(N^2) wall this framework exists to demolish,
and until this module its AGGREGATE ceiling was one chip's HBM budget:
parallel/fused_pool_sharded.py replicates the whole population on every
device (so it inherits the VMEM pool kernel's 2^21 cap), and the
HBM-streaming pool2 tier (ops/fused_pool2.py) is single-device at 2^27.
Sharding the full topology exactly is fundamentally different from the
lattice compositions — each round's pool displacements are uniform over
the whole ring, so every node's next state depends on the whole
population and a CR-round halo would be the population itself. This
module is the shard-sweep form of the replicated trick (ROADMAP item 1):

- state planes are row-sharded ([rows_loc, 128] per device) — the
  push-sum (s, w, packed term+conv) / gossip (count, active) planes of
  the pool2 tier; conv stays derived for gossip (count monotonicity);
- one super-step = ONE round (global information flow admits nothing
  coarser), and its only wire is ONE batched all_gather of the COMPACT
  per-shard send summaries: just the windowed planes delivery actually
  reads — raw (s, w) for push-sum, the active plane for gossip — never
  term/conv, never the choice planes (the packed pool choice, the drop
  gate, and the pad mask are REGENERATED inside the window consumer at
  global positions, exactly the single-device zero-send-plane design);
- each device then runs the pool2 one-sweep round body over ITS OWN
  shard rows only: per processing tile, the P slot windows are DMA'd
  from the gathered full copy at the round's traced displacements (the
  d / d+Z mod-n blend straddle-predicated per tile — ops/fused_pool2.
  _slot_plan, the same code), the choice/gate masks are regenerated with
  ops/fused_pool2._choice_window / _gate_window (they already work at
  arbitrary global rows), and the absorb is the single-device tile
  formula verbatim — so each output row is computed from identical
  inputs by identical ops and trajectories are BITWISE the single-device
  pool2 engine's (gossip ints exactly, push-sum to the last bit via the
  power-of-two halve lemma);
- termination composes by psum: the per-shard conv-among-live count (or
  the global-residual unstable count) reduces across the mesh, and under
  cfg.overlap_collectives (default on) that psum is DEFERRED one
  super-step so it rides under the next round's kernel
  (parallel/overlap.py; rounds stay exact — the verdict granularity is
  one round). Crash-stop + drop faults run in-kernel like the
  single-device tier (streamed death windows, regenerated gates,
  per-round quorum needs as a pure function of the death plane).

Ceiling: the per-device residency is the gathered windowed planes (the
irreducible information floor of a full-topology round) plus its own
shard's planes — NOT the whole ping/pong state — so the aggregate
population the plan admits is ~2^29 for push-sum and ~2^30 for gossip at
the 12 GB plane budget (>= 2^28, the BENCH_TABLES "topology ceilings"
row), with per-round HBM traffic within a small factor of the
single-device pool2 roofline row (the gather IS the window read).

Reference mapping: the reference caps its full-topology runs at ~2000
actors on one machine's threads (report.pdf p.3 SS4); this composition
runs the same hot loop (program.fs:191-225) at 2^28+ nodes across a mesh.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import SimConfig
from ..ops import faults as faults_mod
from ..ops.fused import build_death2d, gate_round_keys, threefry_bits_2d
from ..ops.fused_pool import (
    LANES,
    TC_CONV_BIT,
    TC_TERM_MASK,
    build_pool_layout,
)
from ..ops.fused_pool import _lane_masks_mm
from ..ops.fused_pool2 import (
    _PT_CANDIDATES,
    _choice_window,
    _copy_all,
    _counted_window_roll,
    _gate_window,
    _masked_window_roll,
    _slot_plan,
    _win_plan,
)
from ..ops.sampling import POOL_CHOICE_BITS, gate_threshold
from ..ops.topology import Topology
from ..analysis.wire_specs import C, Regions, WireSpec

# Per-device HBM for the resident planes: the gathered windowed copy (+
# margin), this shard's in/out planes, and the overlap schedule's
# double-buffer carry. Imported from the ONE home (the HBM x sharded
# lattice composition, 12 of the v5e's 16 GB) so a chip-class retune
# cannot drift the compositions' plan ceilings apart.
from .fused_hbm_sharded import _HBM_PLANE_BUDGET  # noqa: E402


def plan_pool2_sharded(topo: Topology, cfg: SimConfig, n_dev: int):
    """(rows_loc, PT, layout) or a string reason why the composition can't
    run. The plan is a pure function of (kind, n, cfg, n_dev) — no
    adjacency arrays exist for the implicit full topology — so it also
    serves the plan-level ceiling rows in BENCH_TABLES hardware-free."""
    if not topo.implicit:
        return (
            "the replicated-pool2 composition serves the implicit full "
            "topology only"
        )
    if cfg.delivery not in ("pool", "matmul"):
        return (
            "the replicated-pool2 composition requires delivery='pool' or "
            "delivery='matmul' (the same gate as the single-device pool "
            "engine dispatch; matmul runs the per-shard one-hot MXU blend "
            "after the one all_gather — the wire is unchanged)"
        )
    if cfg.dtype != "float32":
        return "fused engine supports float32 only"
    if not jax.config.jax_threefry_partitionable:
        return "requires jax_threefry_partitionable=True"
    if cfg.dup_rate > 0 or cfg.delay_rounds > 0:
        return "dup/delay fault models run on the chunked engine only"
    if cfg.revive_model:
        return (
            "crash-recovery (revive) runs on the chunked, sharded, and "
            "VMEM fused stencil/pool engines only"
        )
    if cfg.mass_tolerance is not None:
        return (
            "the health sentinel (--mass-tolerance) runs in the chunked "
            "and sharded XLA round bodies only"
        )
    if cfg.telemetry:
        return (
            "telemetry counters run in the single-device fused kernels and "
            "the chunked/sharded XLA engines; this composition does not "
            "carry the counter block"
        )
    if cfg.pool_size > 1 << POOL_CHOICE_BITS:
        return (
            f"pool_size {cfg.pool_size} exceeds the packed-choice limit "
            f"{1 << POOL_CHOICE_BITS}"
        )
    layout = build_pool_layout(topo.n)
    R = layout.rows
    if R % n_dev != 0:
        return (
            f"padded layout ({R} rows) must split evenly; {n_dev} devices "
            "do not divide it"
        )
    rows_loc = R // n_dev
    PT = next(
        (pt for pt in _PT_CANDIDATES if rows_loc % pt == 0), None
    )
    if PT is None:
        return (
            f"no processing tile divides the {rows_loc}-row shard "
            f"(candidates {_PT_CANDIDATES}); use fewer devices"
        )
    pushsum = cfg.algorithm == "push-sum"
    n_wp = 2 if pushsum else 1  # gathered windowed planes (s,w | active)
    n_state = 3 if pushsum else 2  # s,w,tc | count,active
    M = PT + 16
    gathered = n_wp * (R + M) * LANES * 4
    own = 2 * n_state * rows_loc * LANES * 4  # in + out shard planes
    # Overlap double buffer: the loop carries the next gathered copy and
    # the retired mid planes next to the active ones (parallel/overlap.py)
    # — budgeted unconditionally so geometry is knob-invariant.
    carry = gathered + n_state * rows_loc * LANES * 4
    if gathered + own + carry > _HBM_PLANE_BUDGET:
        return (
            f"population {topo.n} exceeds the replicated-pool2 plane "
            f"budget: the gathered windowed copy ({gathered >> 20} MiB) "
            "plus the shard planes and the overlap carry do not fit "
            f"{_HBM_PLANE_BUDGET >> 30} GiB per device"
        )
    return (rows_loc, PT, layout)


def make_pushsum_pool2_shard_chunk(
    topo: Topology, cfg: SimConfig, rows_loc: int, PT: int, layout,
    *, interpret: bool = False
):
    """Per-device one-round kernel: ``chunk_fn(state3, gathered2, keys,
    offs, [gkeys,] row0, rnd) -> (state3', u)`` advances this shard's
    (s, w, packed tc) planes by ONE round, reading the P slot windows from
    the gathered margined full (s, w) copies — the single-device pool2
    round body (ops/fused_pool2.make_pushsum_pool2_chunk) restricted to
    this shard's rows, bitwise. ``u`` is the shard's termination metric:
    conv-among-live count (local termination) or unstable valid-lane count
    (termination='global'). The caller guarantees one active round per
    invocation (the super-step loops never dispatch past round_end)."""
    R = layout.rows
    N = layout.n
    Z = layout.n_pad - layout.n
    T = rows_loc // PT
    M = PT + 16
    P = cfg.pool_size
    delta = np.float32(cfg.resolved_delta)
    term_rounds = np.int32(cfg.term_rounds)
    global_term = cfg.termination == "global"
    # delivery='matmul': the per-shard window blend after the one
    # all_gather runs as one-hot 128x128 MXU tiles — bitwise the roll
    # blend, and the WIRE is unchanged (the static auditor proves the
    # WIRE_SPEC holds for both deliveries).
    matmul = cfg.delivery == "matmul"
    use_gate = cfg.fault_rate > 0
    thresh = np.uint32(gate_threshold(cfg.fault_rate)) if use_gate else None
    crashed = build_death2d(cfg, topo.n, layout.n_pad) is not None
    n_fetch = 2 * P + 3 + ((P + 1) if crashed else 0)

    def kernel(*refs):
        it = iter(refs)
        scal_ref, keys_ref = next(it), next(it)
        gkeys_ref = next(it) if use_gate else None
        offs_ref = next(it)
        death_own_in = next(it) if crashed else None
        death_mir = next(it) if crashed else None
        s_in, w_in, tc_in = next(it), next(it), next(it)
        gs, gw = next(it), next(it)
        s_o, w_o, tc_o, u_o = next(it), next(it), next(it), next(it)
        own_s, own_w, own_tc = next(it), next(it), next(it)
        own_d = next(it) if crashed else None
        scr_ch, scr_ch2 = next(it), next(it)
        win_s, win_w = next(it), next(it)
        win_d = next(it) if crashed else None
        win_s2, win_w2 = next(it), next(it)
        win_d2 = next(it) if crashed else None
        sems, str_sems = next(it), next(it)
        row_l = lax.broadcasted_iota(jnp.int32, (PT, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (PT, LANES), 1)
        row0 = scal_ref[0]
        rnd = scal_ref[1]
        k1 = keys_ref[0]
        k2 = keys_ref[1]
        g1 = gkeys_ref[0] if use_gate else None
        g2 = gkeys_ref[1] if use_gate else None

        def win_plans(g0):
            plans = []
            for slot in range(P):
                d = offs_ref[slot]
                straddle, ws8, rl, off = _slot_plan(g0, d, Z, R, PT)
                plans.append((d, straddle, ws8, rl, off))
            return plans

        def masked_choice(ws8, death_win):
            ch = _choice_window(k1, k2, ws8, M, R, N, P)
            if use_gate:
                ch = jnp.where(
                    _gate_window(g1, g2, ws8, M, R, thresh), ch,
                    jnp.int32(-1),
                )
            if crashed:
                ch = jnp.where(death_win > rnd, ch, jnp.int32(-1))
            return ch

        def tile(t, acc):
            r0 = t * PT
            g0 = row0 + r0  # global tile start (shards partition [0, R))
            plans = win_plans(g0)
            pairs = []
            for slot, (_, _, ws8, _, _) in enumerate(plans):
                pairs.append((gs.at[pl.ds(ws8, M), :], win_s.at[slot]))
                pairs.append((gw.at[pl.ds(ws8, M), :], win_w.at[slot]))
                if crashed:
                    pairs.append(
                        (death_mir.at[pl.ds(ws8, M), :], win_d.at[slot])
                    )
            pairs.append((s_in.at[pl.ds(r0, PT), :], own_s))
            pairs.append((w_in.at[pl.ds(r0, PT), :], own_w))
            pairs.append((tc_in.at[pl.ds(r0, PT), :], own_tc))
            if crashed:
                pairs.append((death_own_in.at[pl.ds(r0, PT), :], own_d))
            _copy_all(pairs, sems)
            jflat = (g0 + row_l) * LANES + lane
            padm = jflat >= N
            raw_s = jnp.zeros((PT, LANES), jnp.float32)
            raw_w = jnp.zeros((PT, LANES), jnp.float32)
            for slot in range(P):
                d, straddle, ws8, rl, off = plans[slot]
                scr_ch[:] = masked_choice(
                    ws8, win_d[slot] if crashed else None
                )
                # One mask pair per slot rotation, shared by s and w.
                mm = _lane_masks_mm(rl) if matmul else None
                cs = _masked_window_roll(
                    win_s.at[slot], scr_ch, slot, off, PT, rl, lane,
                    interpret, 0.0, matmul, mm,
                )
                cw = _masked_window_roll(
                    win_w.at[slot], scr_ch, slot, off, PT, rl, lane,
                    interpret, 0.0, matmul, mm,
                )
                if Z != 0:
                    ws8_2, rl2, off2 = _win_plan(g0, d + jnp.int32(Z), R)

                    @pl.when(straddle)
                    def _fetch_wrap():
                        wrap_pairs = [
                            (gs.at[pl.ds(ws8_2, M), :], win_s2),
                            (gw.at[pl.ds(ws8_2, M), :], win_w2),
                        ]
                        if crashed:
                            wrap_pairs.append(
                                (death_mir.at[pl.ds(ws8_2, M), :], win_d2)
                            )
                        _copy_all(wrap_pairs, str_sems)
                        scr_ch2[:] = masked_choice(
                            ws8_2, win_d2[:] if crashed else None
                        )
                    use2 = straddle & (jflat < d)
                    mm2 = _lane_masks_mm(rl2) if matmul else None
                    cs = jnp.where(
                        use2,
                        _masked_window_roll(win_s2, scr_ch2, slot, off2,
                                            PT, rl2, lane, interpret, 0.0,
                                            matmul, mm2),
                        cs,
                    )
                    cw = jnp.where(
                        use2,
                        _masked_window_roll(win_w2, scr_ch2, slot, off2,
                                            PT, rl2, lane, interpret, 0.0,
                                            matmul, mm2),
                        cw,
                    )
                raw_s = raw_s + cs
                raw_w = raw_w + cw
            # Halve AFTER the masked sums — bitwise the pre-halved-send
            # delivery (power-of-two scaling commutes with rounding).
            half = jnp.float32(0.5)
            inbox_s = jnp.where(padm, 0.0, raw_s * half)
            inbox_w = jnp.where(padm, 0.0, raw_w * half)
            s_t = own_s[:]
            w_t = own_w[:]
            blocked = padm
            if use_gate:
                own_gate = threefry_bits_2d(
                    g1, g2, PT, LANES, row0=g0
                ) >= thresh
                blocked = blocked | ~own_gate
            if crashed:
                blocked = blocked | (own_d[:] <= rnd)
            s_send = jnp.where(blocked, 0.0, s_t * half)
            w_send = jnp.where(blocked, 0.0, w_t * half)
            s_new = (s_t - s_send) + inbox_s
            w_new = (w_t - w_send) + inbox_w
            if global_term:
                ratio_old = s_t / w_t
                tol = delta * jnp.maximum(jnp.abs(ratio_old), jnp.float32(1))
                unstable = (
                    jnp.abs(s_new / w_new - ratio_old) > tol
                ) & ~padm
                tc_new = own_tc[:]
                tile_metric = jnp.sum(
                    unstable.astype(jnp.int32), dtype=jnp.int32
                )
            else:
                received = inbox_w > 0
                stable = jnp.abs(s_new / w_new - s_t / w_t) <= delta
                term = own_tc[:] & TC_TERM_MASK
                conv_old = (own_tc[:] & TC_CONV_BIT) != 0
                term_new = jnp.where(
                    received,
                    jnp.where(stable, term + 1, jnp.int32(0)),
                    term,
                )
                conv_new = (conv_old | (term_new >= term_rounds)) & ~padm
                tc_cand = jnp.where(
                    conv_new, term_new | TC_CONV_BIT, term_new
                )
                if crashed:
                    alive_own = own_d[:] > rnd
                    tc_new = jnp.where(alive_own, tc_cand, own_tc[:])
                    tile_metric = jnp.sum(
                        (conv_new & alive_own).astype(jnp.int32),
                        dtype=jnp.int32,
                    )
                else:
                    tc_new = tc_cand
                    tile_metric = jnp.sum(
                        conv_new.astype(jnp.int32), dtype=jnp.int32
                    )
            own_s[:] = s_new
            own_w[:] = w_new
            own_tc[:] = tc_new
            _copy_all([
                (own_s, s_o.at[pl.ds(r0, PT), :]),
                (own_w, w_o.at[pl.ds(r0, PT), :]),
                (own_tc, tc_o.at[pl.ds(r0, PT), :]),
            ], str_sems)
            return acc + tile_metric

        total = lax.fori_loop(0, T, tile, jnp.int32(0), unroll=False)
        u_o[0] = total

    def chunk_fn(state3, gathered2, keys, offs, gkeys, death_own,
                 death_mir, row0, rnd):
        s, w, tc = state3
        gs, gw = gathered2
        i32 = jax.ShapeDtypeStruct((rows_loc, LANES), jnp.int32)
        f32 = jax.ShapeDtypeStruct((rows_loc, LANES), jnp.float32)
        in_specs = [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ]
        operands = [
            jnp.stack([jnp.int32(row0), jnp.int32(rnd)]),
            keys,
        ]
        if use_gate:
            in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            operands.append(gkeys)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(offs)
        if crashed:
            in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 2
            operands += [death_own, death_mir]
        in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 5
        operands += [s, w, tc, gs, gw]
        scratch = [
            pltpu.VMEM((PT, LANES), jnp.float32),
            pltpu.VMEM((PT, LANES), jnp.float32),
            pltpu.VMEM((PT, LANES), jnp.int32),
        ]
        if crashed:
            scratch.append(pltpu.VMEM((PT, LANES), jnp.int32))  # own_d
        scratch += [
            pltpu.VMEM((M, LANES), jnp.int32),
            pltpu.VMEM((M, LANES), jnp.int32),
            pltpu.VMEM((P, M, LANES), jnp.float32),
            pltpu.VMEM((P, M, LANES), jnp.float32),
        ]
        if crashed:
            scratch.append(pltpu.VMEM((P, M, LANES), jnp.int32))  # win_d
        scratch += [
            pltpu.VMEM((M, LANES), jnp.float32),
            pltpu.VMEM((M, LANES), jnp.float32),
        ]
        if crashed:
            scratch.append(pltpu.VMEM((M, LANES), jnp.int32))  # win_d2
        scratch += [
            pltpu.SemaphoreType.DMA((n_fetch,)),
            pltpu.SemaphoreType.DMA((3,)),
        ]
        from ..utils import compat

        outs = pl.pallas_call(
            kernel,
            grid=(1,),
            out_shape=(
                f32, f32, i32,
                jax.ShapeDtypeStruct((1,), jnp.int32),
            ),
            in_specs=in_specs,
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * 3
                + [pl.BlockSpec(memory_space=pltpu.SMEM)]
            ),
            scratch_shapes=scratch,
            compiler_params=compat.pallas_tpu_compiler_params(
                vmem_limit_bytes=96 * 1024 * 1024
            ),
            interpret=interpret,
        )(*operands)
        return (outs[0], outs[1], outs[2]), outs[3][0]

    return chunk_fn


def make_gossip_pool2_shard_chunk(
    topo: Topology, cfg: SimConfig, rows_loc: int, PT: int, layout,
    *, interpret: bool = False
):
    """Gossip analog: shard planes (count, active) — conv stays derived
    (count monotonicity, ops/fused_pool2.make_gossip_pool2_chunk); the
    gathered copy is the active plane alone. ``u`` is the shard's
    conv(-among-live) count."""
    R = layout.rows
    N = layout.n
    Z = layout.n_pad - layout.n
    T = rows_loc // PT
    M = PT + 16
    P = cfg.pool_size
    rumor_target = np.int32(cfg.resolved_rumor_target)
    suppress = cfg.resolved_suppress
    matmul = cfg.delivery == "matmul"  # see make_pushsum_pool2_shard_chunk
    use_gate = cfg.fault_rate > 0
    thresh = np.uint32(gate_threshold(cfg.fault_rate)) if use_gate else None
    crashed = build_death2d(cfg, topo.n, layout.n_pad) is not None
    n_fetch = P + 2 + ((P + 1) if crashed else 0)

    def kernel(*refs):
        it = iter(refs)
        scal_ref, keys_ref = next(it), next(it)
        gkeys_ref = next(it) if use_gate else None
        offs_ref = next(it)
        death_own_in = next(it) if crashed else None
        death_mir = next(it) if crashed else None
        n_in, a_in = next(it), next(it)
        ga = next(it)
        n_o, a_o, u_o = next(it), next(it), next(it)
        own_n, own_a = next(it), next(it)
        own_d = next(it) if crashed else None
        scr_ch, scr_ch2 = next(it), next(it)
        win_a = next(it)
        win_d = next(it) if crashed else None
        win_a2 = next(it)
        win_d2 = next(it) if crashed else None
        sems, str_sems = next(it), next(it)
        row_l = lax.broadcasted_iota(jnp.int32, (PT, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (PT, LANES), 1)
        row0 = scal_ref[0]
        rnd = scal_ref[1]
        k1 = keys_ref[0]
        k2 = keys_ref[1]
        g1 = gkeys_ref[0] if use_gate else None
        g2 = gkeys_ref[1] if use_gate else None

        def masked_choice(ws8, death_win):
            ch = _choice_window(k1, k2, ws8, M, R, N, P)
            if use_gate:
                ch = jnp.where(
                    _gate_window(g1, g2, ws8, M, R, thresh), ch,
                    jnp.int32(-1),
                )
            if crashed:
                ch = jnp.where(death_win > rnd, ch, jnp.int32(-1))
            return ch

        def tile(t, acc):
            r0 = t * PT
            g0 = row0 + r0
            plans = []
            for slot in range(P):
                d = offs_ref[slot]
                straddle, ws8, rl, off = _slot_plan(g0, d, Z, R, PT)
                plans.append((d, straddle, ws8, rl, off))
            pairs = []
            for slot, (_, _, ws8, _, _) in enumerate(plans):
                pairs.append((ga.at[pl.ds(ws8, M), :], win_a.at[slot]))
                if crashed:
                    pairs.append(
                        (death_mir.at[pl.ds(ws8, M), :], win_d.at[slot])
                    )
            pairs.append((n_in.at[pl.ds(r0, PT), :], own_n))
            pairs.append((a_in.at[pl.ds(r0, PT), :], own_a))
            if crashed:
                pairs.append((death_own_in.at[pl.ds(r0, PT), :], own_d))
            _copy_all(pairs, sems)
            jflat = (g0 + row_l) * LANES + lane
            padm = jflat >= N
            inbox = jnp.zeros((PT, LANES), jnp.int32)
            for slot in range(P):
                d, straddle, ws8, rl, off = plans[slot]
                scr_ch[:] = masked_choice(
                    ws8, win_d[slot] if crashed else None
                )
                g = _counted_window_roll(
                    win_a.at[slot], scr_ch, slot, off, PT, rl, lane,
                    interpret, matmul,
                )
                if Z != 0:
                    ws8_2, rl2, off2 = _win_plan(g0, d + jnp.int32(Z), R)

                    @pl.when(straddle)
                    def _fetch_wrap():
                        wrap_pairs = [(ga.at[pl.ds(ws8_2, M), :], win_a2)]
                        if crashed:
                            wrap_pairs.append(
                                (death_mir.at[pl.ds(ws8_2, M), :], win_d2)
                            )
                        _copy_all(wrap_pairs, str_sems)
                        scr_ch2[:] = masked_choice(
                            ws8_2, win_d2[:] if crashed else None
                        )
                    use2 = straddle & (jflat < d)
                    g = jnp.where(
                        use2,
                        _counted_window_roll(win_a2, scr_ch2, slot, off2,
                                             PT, rl2, lane, interpret,
                                             matmul),
                        g,
                    )
                inbox = inbox + g
            inbox = jnp.where(padm, jnp.int32(0), inbox)
            if suppress:
                inbox = jnp.where(
                    own_n[:] >= rumor_target, jnp.int32(0), inbox
                )
            if crashed:
                alive_own = own_d[:] > rnd
                inbox = jnp.where(alive_own, inbox, jnp.int32(0))
            count_new = own_n[:] + inbox
            active_new = jnp.where(
                (own_a[:] != 0) | (inbox > 0), jnp.int32(1), jnp.int32(0)
            )
            conv_new = (count_new >= rumor_target) & ~padm
            if crashed:
                conv_new = conv_new & alive_own
            own_n[:] = count_new
            own_a[:] = active_new
            _copy_all([
                (own_n, n_o.at[pl.ds(r0, PT), :]),
                (own_a, a_o.at[pl.ds(r0, PT), :]),
            ], str_sems)
            return acc + jnp.sum(conv_new.astype(jnp.int32), dtype=jnp.int32)

        total = lax.fori_loop(0, T, tile, jnp.int32(0), unroll=False)
        u_o[0] = total

    def chunk_fn(state2, gathered1, keys, offs, gkeys, death_own,
                 death_mir, row0, rnd):
        cnt, act = state2
        (ga,) = gathered1
        i32 = jax.ShapeDtypeStruct((rows_loc, LANES), jnp.int32)
        in_specs = [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ]
        operands = [
            jnp.stack([jnp.int32(row0), jnp.int32(rnd)]),
            keys,
        ]
        if use_gate:
            in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            operands.append(gkeys)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(offs)
        if crashed:
            in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 2
            operands += [death_own, death_mir]
        in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 3
        operands += [cnt, act, ga]
        scratch = [
            pltpu.VMEM((PT, LANES), jnp.int32),
            pltpu.VMEM((PT, LANES), jnp.int32),
        ]
        if crashed:
            scratch.append(pltpu.VMEM((PT, LANES), jnp.int32))  # own_d
        scratch += [
            pltpu.VMEM((M, LANES), jnp.int32),
            pltpu.VMEM((M, LANES), jnp.int32),
            pltpu.VMEM((P, M, LANES), jnp.int32),
        ]
        if crashed:
            scratch.append(pltpu.VMEM((P, M, LANES), jnp.int32))  # win_d
        scratch.append(pltpu.VMEM((M, LANES), jnp.int32))
        if crashed:
            scratch.append(pltpu.VMEM((M, LANES), jnp.int32))  # win_d2
        scratch += [
            pltpu.SemaphoreType.DMA((n_fetch,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        from ..utils import compat

        outs = pl.pallas_call(
            kernel,
            grid=(1,),
            out_shape=(
                i32, i32,
                jax.ShapeDtypeStruct((1,), jnp.int32),
            ),
            in_specs=in_specs,
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * 2
                + [pl.BlockSpec(memory_space=pltpu.SMEM)]
            ),
            scratch_shapes=scratch,
            compiler_params=compat.pallas_tpu_compiler_params(
                vmem_limit_bytes=96 * 1024 * 1024
            ),
            interpret=interpret,
        )(*operands)
        return (outs[0], outs[1]), outs[2][0]

    return chunk_fn


def run_pool2_sharded(
    topo: Topology,
    cfg: SimConfig,
    mesh=None,
    key=None,
    on_chunk=None,
    start_state=None,
    start_round: int = 0,
    probe=None,
    deadline=None,
):
    """Sharded replicated-pool2 run — engine='fused', n_devices > 1,
    implicit full topology with delivery='pool', populations past the
    VMEM replicated composition's 2^21 cap.

    One super-step = one round: ONE batched all_gather of the windowed
    send-summary planes (parallel/halo.gather_rows_batched; one gather
    per plane with --overlap-collectives off), then each device's
    one-round pool2 sweep over its own shard rows, then the psum'd
    termination verdict — DEFERRED one super-step under the overlap
    schedule (parallel/overlap.py; `rounds` stays exact, the verdict
    granularity is already one round). Trajectories are bitwise the
    single-device pool2 engine's (tests/test_pool2_sharded.py).
    termination='global' latches the all-or-nothing conv plane after the
    psum'd zero-unstable verdict, at the exact verdict round.

    ``probe(chunk_sharded, args)`` short-circuits the run for
    benchmarks/comm_audit.py (trace, never execute)."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import gossip as gossip_mod
    from ..models import pipeline as pipeline_mod
    from ..models import pushsum as pushsum_mod
    from ..models.runner import (
        StallWatchdog,
        _cancel_fn,
        _check_dtype,
        _finalize_result,
        _host_done,
        _progress_gap,
        draw_leader,
    )
    from ..ops import sampling
    from ..ops.fused import round_keys
    from ..ops.fused_pool import round_offsets
    from ..utils import compat
    from . import halo as halo_mod
    from . import overlap as overlap_mod
    from .mesh import NODE_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh(cfg.n_devices)
    n_dev = mesh.devices.size
    plan = plan_pool2_sharded(topo, cfg, n_dev)
    if isinstance(plan, str):
        raise ValueError(
            f"engine='fused' with n_devices={n_dev} unavailable: {plan}"
        )
    rows_loc, PT, layout = plan
    _check_dtype(cfg)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    interpret = jax.default_backend() != "tpu"
    pushsum = cfg.algorithm == "push-sum"
    global_term = pushsum and cfg.termination == "global"
    make = (
        make_pushsum_pool2_shard_chunk if pushsum
        else make_gossip_pool2_shard_chunk
    )
    chunk_fn = make(topo, cfg, rows_loc, PT, layout, interpret=interpret)
    R_glob = layout.rows
    n = topo.n
    PTM = PT + 16
    target = cfg.resolved_target_count(n, topo.target_count)
    quorum = cfg.quorum
    key_data_host, key_impl = sampling.key_split(key)
    use_gate = cfg.fault_rate > 0

    shard_rows = NamedSharding(mesh, P(NODE_AXIS, None))
    repl = NamedSharding(mesh, P())

    death2d = build_death2d(cfg, n, layout.n_pad)
    crashed = death2d is not None
    if crashed:
        death_mir = jnp.concatenate([death2d, death2d[:PTM]], axis=0)
        death_sorted = jnp.sort(
            jnp.asarray(faults_mod.death_plane(cfg, n))
        )
        death_own_dev = jax.device_put(death2d, shard_rows)
        death_mir_dev = jax.device_put(death_mir, repl)
        death_sorted_dev = jax.device_put(death_sorted, repl)

    def to_planes(state):
        """Canonical state -> padded shard planes. Push-sum packs term +
        conv into the pool2 tier's tc plane; gossip drops conv (derived)."""
        if pushsum:
            s = np.full(layout.n_pad, 0.0, np.float32)
            w = np.full(layout.n_pad, 1.0, np.float32)
            tc = np.zeros(layout.n_pad, np.int32)
            s[:n] = np.asarray(state.s, np.float32)
            w[:n] = np.asarray(state.w, np.float32)
            term = np.asarray(state.term, np.int32)
            conv = np.asarray(state.conv) != 0
            tc[:n] = np.where(conv, term | TC_CONV_BIT, term)
            return tuple(
                x.reshape(R_glob, LANES) for x in (s, w, tc)
            )
        cnt = np.zeros(layout.n_pad, np.int32)
        act = np.zeros(layout.n_pad, np.int32)
        cnt[:n] = np.asarray(state.count, np.int32)
        act[:n] = np.asarray(state.active).astype(np.int32)
        return tuple(x.reshape(R_glob, LANES) for x in (cnt, act))

    if start_state is not None:
        st0 = jax.tree.map(np.asarray, start_state)
    elif pushsum:
        st0 = pushsum_mod.init_state(n, jnp.float32, cfg.initial_term_round)
    else:
        st0 = gossip_mod.init_state(
            n, draw_leader(key, topo, cfg),
            leader_counts_receipt=cfg.reference and topo.kind == "full",
        )
    planes0 = tuple(jax.device_put(p, shard_rows) for p in to_planes(st0))
    done0 = _host_done(
        cfg, faults_mod.life_planes(cfg, n), st0, start_round, target
    )
    overlap = cfg.overlap_collectives
    rumor_target = cfg.resolved_rumor_target

    def windowed(planes):
        return planes[:2] if pushsum else planes[1:2]

    def exchange(planes):
        """The super-step wire: ONE batched all_gather of the compact
        windowed send summaries (raw s/w for push-sum, the active plane
        for gossip), margin-extended for the kernel's 8-aligned window
        DMAs (rows [R, R+PT+16) mirror rows [0, PT+16) — the XLA-side
        form of the single-device tier's in-kernel margin maintenance).
        The local planes pass through untouched — the kernel reads its
        own tiles from them directly."""
        wp = windowed(planes)
        if overlap:
            full = halo_mod.gather_rows_batched(wp, NODE_AXIS)
        else:
            full = tuple(
                lax.all_gather(p, NODE_AXIS, axis=0, tiled=True)
                for p in wp
            )
        full = tuple(
            jnp.concatenate([p, p[:PTM]], axis=0) for p in full
        )
        return (planes, full)

    def chunk_local(planes_in, rnd_in, done_in, round_end, key_data,
                    *fault_args):
        base = sampling.key_join(key_data, key_impl)
        dev = lax.axis_index(NODE_AXIS)
        row0 = dev.astype(jnp.int32) * rows_loc
        if crashed:
            death_own_loc, death_mir_loc, death_sorted_loc = fault_args
        else:
            death_own_loc = death_mir_loc = death_sorted_loc = None

        def metric_shift(u, rnd):
            """Shift the shard's verdict metric so the fixed-target
            overlapped loop fires at the right predicate: fault-free
            local termination uses the static target unshifted; a crash
            model's per-round quorum need and the global-residual
            zero-unstable verdict are folded in on device 0 (psum adds
            the shift exactly once), keeping `psum(metric) >= target`
            equivalent to the engine's own predicate."""
            if global_term:
                # fires iff the summed unstable count is zero.
                return jnp.where(
                    dev == 0, jnp.int32(target), jnp.int32(0)
                ) - u
            if crashed:
                alive = jnp.int32(n) - jnp.searchsorted(
                    death_sorted_loc, rnd, side="right"
                ).astype(jnp.int32)
                need = faults_mod.quorum_need(alive, quorum)
                return u - jnp.where(
                    dev == 0, need - jnp.int32(target), jnp.int32(0)
                )
            return u

        def compute(ext, rnd, cap):
            planes_cur, full = ext
            keys = round_keys(base, rnd, 1)
            offs = round_offsets(base, rnd, 1, cfg.pool_size, n)
            gkeys = gate_round_keys(keys)[0] if use_gate else None
            out, u = chunk_fn(
                planes_cur, full, keys[0], offs[0], gkeys,
                death_own_loc, death_mir_loc, row0, rnd,
            )
            return out, jnp.int32(1), metric_shift(u, rnd)

        if overlap:
            planes_f, rnd_f, done_f = overlap_mod.overlapped_superstep_loop(
                planes_in, rnd_in, done_in, round_end,
                exchange=exchange, compute=compute,
                psum_metric=lambda m: lax.psum(m, NODE_AXIS),
                target=target,
            )
        else:
            def cond(c):
                _, rnd, done = c
                return jnp.logical_and(~done, rnd < round_end)

            def body(c):
                planes, rnd, _ = c
                out, executed, metric = compute(exchange(planes), rnd,
                                                round_end)
                total = lax.psum(metric, NODE_AXIS)
                return (out, rnd + executed, total >= target)

            planes_f, rnd_f, done_f = lax.while_loop(
                cond, body, (planes_in, rnd_in, done_in)
            )

        if global_term:
            # All-or-nothing latch at the fired verdict — the sharded
            # form of the single-device tier's in-kernel conv-bit OR.
            pos = (
                (row0 + lax.broadcasted_iota(
                    jnp.int32, (rows_loc, LANES), 0)) * LANES
                + lax.broadcasted_iota(jnp.int32, (rows_loc, LANES), 1)
            )
            tc = planes_f[2]
            tc = jnp.where(
                done_f & (pos < n), tc | TC_CONV_BIT, tc
            )
            planes_f = (planes_f[0], planes_f[1], tc)
        return planes_f, rnd_f, done_f

    plane_specs = tuple(P(NODE_AXIS, None) for _ in planes0)
    fault_specs = (P(NODE_AXIS, None), P(), P()) if crashed else ()
    donate = on_chunk is None and not cfg.stall_chunks
    chunk_sharded = jax.jit(
        compat.shard_map(
            chunk_local,
            mesh=mesh,
            in_specs=(plane_specs, P(), P(), P(), P()) + fault_specs,
            out_specs=(plane_specs, P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    def rep_put(x):
        return jax.device_put(x, repl)

    kd_dev = rep_put(np.asarray(key_data_host))
    rnd0 = rep_put(np.int32(start_round))
    done0_dev = rep_put(np.bool_(done0))
    fault_dev = (
        (death_own_dev, death_mir_dev, death_sorted_dev) if crashed else ()
    )

    def to_canonical(planes):
        flats = [p.reshape(-1)[:n] for p in planes]
        if pushsum:
            tc = flats[2]
            return pushsum_mod.PushSumState(
                s=flats[0], w=flats[1], term=tc & TC_TERM_MASK,
                conv=(tc & TC_CONV_BIT) != 0,
            )
        return gossip_mod.GossipState(
            count=flats[0], active=flats[1] != 0,
            conv=flats[0] >= rumor_target,
        )

    if probe is not None:
        return probe(chunk_sharded, (
            planes0, rnd0, done0_dev,
            rep_put(np.int32(min(start_round + 1, cfg.max_rounds))),
            kd_dev, *fault_dev,
        ), donate=donate)

    t0 = time.perf_counter()
    warm = chunk_sharded(
        tuple(jnp.copy(p) for p in planes0) if donate else planes0,
        rnd0, done0_dev,
        rep_put(np.int32(min(start_round + 1, cfg.max_rounds))),
        kd_dev, *fault_dev,
    )
    int(warm[1])
    del warm
    compile_s = time.perf_counter() - t0

    watchdog = StallWatchdog(cfg.stall_chunks)

    def dispatch(planes, rnd, done, round_end):
        return chunk_sharded(
            planes, rnd, done, rep_put(np.int32(round_end)), kd_dev,
            *fault_dev,
        )

    on_retire = None
    if on_chunk is not None:
        def on_retire(rounds, planes):
            on_chunk(rounds, to_canonical(planes))

    should_stop = None
    if cfg.stall_chunks:
        def should_stop(rounds, planes):
            life2d = (
                None if death2d is None
                else faults_mod.LifePlanes(death=death2d, revive=None)
            )
            if pushsum:
                conv = ((planes[2] & TC_CONV_BIT) != 0).astype(jnp.int32)
            else:
                conv = (planes[0] >= rumor_target).astype(jnp.int32)
            return watchdog.no_progress(
                _progress_gap(life2d, quorum, target, conv, rounds)
            )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=planes0, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds,
        stride=8, depth=cfg.pipeline_chunks, donate=donate,
        on_retire=on_retire, should_stop=should_stop,
        should_cancel=_cancel_fn(deadline),
    )
    run_s = time.perf_counter() - t1

    return _finalize_result(
        topo, cfg, to_canonical(loop.state), loop.rounds, target,
        compile_s, run_s, done=loop.done, stalled=watchdog.stalled,
        cancelled=loop.cancelled,
    )


# --- Declared wire contract (analysis/wire_specs.py) -----------------------
# Per SUPER-STEP: the ONLY delivery wire is ONE all_gather of the compact
# windowed send summaries (the active plane for gossip; raw s/w windows
# for push-sum — batched into one gather under the overlap schedule, one
# per window serially) + the ONE deferred verdict psum. No ppermutes, no
# scatters, no remote DMAs, zero stragglers. Batched setup = the pre-loop
# gather + the drain psum.
WIRE_SPEC = WireSpec(
    engine="pool2-sharded",
    variants={
        ("overlap", "wire"): Regions(
            body={"all_gather": C(fixed=1), "psum": C(fixed=1)},
            setup={"all_gather": C(fixed=1), "psum": C(fixed=1)},
        ),
        ("serial", "wire"): Regions(
            body={"all_gather": C(per_window=1), "psum": C(fixed=1)},
            setup={},
        ),
    },
    mechanism={"wire": "all-gather"},
    equal_bytes=("all_gather",),
)
