"""Halo-exchange delivery for offset-structured topologies under sharding.

The generic sharded delivery (parallel/sharded.py deliver_sharded) scatters
into a full-length [n_pad] contribution vector on every device and
`psum_scatter`s — O(N) per-device memory and collective payload, which is
what caps the multi-host scale targets (VERDICT r1 #3). For topologies whose
edges live on a small set of fixed index displacements (line / ring / grids /
tori — ops/topology.stencil_offsets), delivery needs none of that: a global
circular roll by displacement ``d`` decomposes into

    local shift by d  +  ppermute of a |d|-wide boundary slice
                          around the device ring

so per-device memory is O(n_loc + Σ|d|) and the collective payload is the
halo slices only — the shard-boundary neighbor exchange the survey's
"long-context" row planned (SURVEY.md §5), the moral analog of ring
attention's ring exchange, riding ICI neighbor links on a TPU torus.

Offsets are used in *signed* form (d > n/2 ≡ d - n): a torus wrap edge such
as x = g-1 → x = 0 has modular displacement n-(g-1) but signed displacement
-(g-1) — the halo stays a few lattice rows wide instead of O(n).

Wire packaging is orthogonal to delivery semantics: the per-class schedule
issues one ppermute per offset class, the BATCHED schedule
(deliver_halo_batched / exchange_rows_batched / gather_rows_batched —
cfg.overlap_collectives, default on) packs every class's / plane's boundary
slices into one contiguous buffer and issues ONE ppermute pair (or one
all_gather) per round/super-step. Same bytes, same values, same
accumulation order — bitwise-identical trajectories, fewer larger wires
(benchmarks/comm_audit.py pins the counts).

Correctness at padded populations (n_pad > n): a signed roll is only the
same as the modular roll when no real edge's value crosses the global
[0, n) boundary — wrap edges of ring/torus at non-divisible populations
would land in pad slots. ``plan_halo`` checks this on the host (exactly, per
offset class) and returns None when the halo path cannot be exact; callers
fall back to scatter + psum_scatter. Accumulation follows the same static
offset order as the single-device stencil path (ops/delivery.deliver_stencil),
so sharded trajectories are bit-identical to single-device ones — int exact,
floats to the last bit, pinned by tests/test_halo.py.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.topology import Topology, stencil_offsets


@dataclasses.dataclass(frozen=True)
class HaloPlan:
    """Host-side delivery plan: modular offset classes (for masking against
    per-edge displacements) and their signed roll amounts."""

    n: int
    n_pad: int
    n_loc: int
    n_dev: int
    offsets_mod: np.ndarray  # [k] int64 — (target - sender) mod n classes
    offsets_signed: np.ndarray  # [k] int64 — roll amounts, |s| <= n_loc

    @property
    def halo_width(self) -> int:
        return int(np.max(np.abs(self.offsets_signed)))


def plan_imp_halo(split, n: int, n_dev: int) -> HaloPlan | None:
    """Halo plan over an imp topology's LATTICE classes only
    (ops/topology.imp_split) — the sharded imp-pool path delivers the
    lattice edges by halo rolls and the pooled long-range slot by dynamic
    global rolls; the lattice classes alone must satisfy the same
    exactness conditions plan_halo checks for whole topologies."""
    if n_dev < 1:
        return None
    n_pad = ((n + n_dev - 1) // n_dev) * n_dev
    n_loc = n_pad // n_dev
    mod = split.lattice_offsets.astype(np.int64)
    signed = np.where(mod <= n // 2, mod, mod - n)
    if mod.size == 0 or np.abs(signed).max() > n_loc:
        return None
    # No n_pad != n exactness scan: the caller (parallel/sharded.py) rejects
    # non-divisible populations on this path outright — the pool rolls need
    # an unpadded ring anyway.
    return HaloPlan(
        n=n, n_pad=n_pad, n_loc=n_loc, n_dev=n_dev,
        offsets_mod=mod, offsets_signed=signed,
    )


def plan_halo(topo: Topology, n_dev: int) -> HaloPlan | None:
    """Build the halo plan, or None when halo delivery cannot be exact:
    implicit topology, too many offset classes, a halo wider than a shard,
    or a padded population whose wrap edges would cross the global boundary.
    """
    offsets = stencil_offsets(topo)
    if offsets is None or n_dev < 1:
        return None
    n = topo.n
    n_pad = ((n + n_dev - 1) // n_dev) * n_dev
    n_loc = n_pad // n_dev
    mod = offsets.astype(np.int64)
    signed = np.where(mod <= n // 2, mod, mod - n)
    if np.abs(signed).max() > n_loc:
        # A roll wider than a shard would need multi-hop ppermute; at that
        # point the topology is not "local" relative to the mesh and the
        # scatter path is the honest choice.
        return None
    if n_pad != n:
        # Exactness check: under a signed (non-circular-at-n) roll, every
        # real edge must land inside [0, n). Edge i --(class d)--> t crosses
        # iff i + signed(d) falls outside — only wrap edges do.
        ids = np.arange(n, dtype=np.int64)[:, None]
        cols = np.arange(topo.max_deg)[None, :]
        live = cols < topo.degree[:, None]
        disp = (topo.neighbors.astype(np.int64) - ids) % n
        for d, s in zip(mod, signed):
            senders = np.nonzero((disp == d) & live)[0]
            if senders.size and (
                (senders + s).min() < 0 or (senders + s).max() >= n
            ):
                return None
    return HaloPlan(
        n=n, n_pad=n_pad, n_loc=n_loc, n_dev=n_dev,
        offsets_mod=mod, offsets_signed=signed,
    )


def resolve_halo_transport(cfg, backend: str | None = None) -> str:
    """Capability check for the halo wire of the HBM-streaming x sharded
    composition: ``"dma"`` = in-kernel ``pltpu.make_async_remote_copy``
    neighbor DMA (zero XLA collectives on the halo path), ``"ppermute"`` =
    the batched XLA wire (``exchange_rows_batched`` / per-plane ppermutes).

    ``cfg.halo_dma``: "auto" selects per backend — DMA on TPU, where the
    Mosaic remote-copy path exists; the XLA wire on CPU/interpret backends,
    where Pallas remote DMA cannot execute (the interpreter has no
    inter-device DMA engine). "on" forces the DMA program (execution needs
    a TPU; CPU callers may still TRACE it — benchmarks/comm_audit.py's
    probe hook audits the DMA kernel hardware-free this way). "off" pins
    the XLA wire everywhere. Both transports deliver identical halo bytes
    into identical kernel operands, so trajectories are bitwise
    transport-invariant."""
    mode = getattr(cfg, "halo_dma", "auto")
    if mode == "off":
        return "ppermute"
    if mode == "on":
        return "dma"
    if backend is None:
        import jax

        backend = jax.default_backend()
    return "dma" if backend == "tpu" else "ppermute"


def _ring_perm(n_dev: int, step: int) -> list[tuple[int, int]]:
    return [(k, (k + step) % n_dev) for k in range(n_dev)]


def halo_roll(x_loc, s: int, axis: str, n_dev: int):
    """Global circular roll by static ``s`` of a node-sharded [..., n_loc]
    array (node dimension last — stacked message channels ride the same
    ppermute), from inside shard_map: local shift + one ppermute of the
    |s|-wide boundary slice. ``s`` = 0 is the identity; |s| <= n_loc
    required (plan_halo guarantees it). With n_dev == 1 this is jnp.roll.
    """
    s = int(s)
    if s == 0:
        return x_loc
    if n_dev == 1:
        return jnp.roll(x_loc, s, axis=-1)
    if s > 0:
        # out[t] = x[t - s]; the top s lanes of device k feed device k+1.
        send = x_loc[..., -s:]
        recv = lax.ppermute(send, axis, _ring_perm(n_dev, +1))
        return jnp.concatenate([recv, x_loc[..., :-s]], axis=-1)
    m = -s
    # out[t] = x[t + m]; the bottom m lanes of device k feed device k-1.
    send = x_loc[..., :m]
    recv = lax.ppermute(send, axis, _ring_perm(n_dev, -1))
    return jnp.concatenate([x_loc[..., m:], recv], axis=-1)


def global_roll_dynamic(x_loc, r, axis: str, n_dev: int):
    """Global circular roll by a *traced* amount ``r`` of a node-sharded
    [..., n_loc] array: out[t] = x[(t - r) mod n], n = n_dev * n_loc.

    ``halo_roll`` needs static offsets narrower than a shard; the offset-pool
    path (ops/sampling.pool_offsets) draws its displacements per round
    *inside* the jit'd loop, uniform over the whole ring — dynamic and
    arbitrarily wide. A dynamic shift cannot pick a ppermute permutation at
    trace time, so the roll decomposes as r = q * n_loc + s with

      1. shard rotation by q: ceil(log2 n_dev) ppermute stages, stage b
         rotating by 2^b and kept iff bit b of q is set (every device
         computes the same replicated q, so the selects agree);
      2. one more static ppermute by 1 for the neighbor shard the stitch
         needs (out lane j < s reads from the *previous* source shard);
      3. two local rolls by s and a lane select to stitch.

    Per-device payload is O(n_loc * log n_dev) and memory O(n_loc) — never a
    full-length vector. Cost is independent of r; r = 0 is the identity.
    """
    n_loc = x_loc.shape[-1]
    if n_dev == 1:
        return jnp.roll(x_loc, r, axis=-1)
    r = jnp.asarray(r)
    q = r // n_loc  # source shard rotation, in [0, n_dev)
    s = r - q * n_loc  # intra-shard shift, in [0, n_loc)
    a = x_loc  # after rotation: device d holds the shard of device (d - q)
    for b in range((n_dev - 1).bit_length()):
        step = 1 << b
        rotated = lax.ppermute(a, axis, _ring_perm(n_dev, +step))
        a = jnp.where(((q >> b) & 1) == 1, rotated, a)
    bshard = lax.ppermute(a, axis, _ring_perm(n_dev, +1))  # shard of (d-q-1)
    # out[j] = a[j - s] for j >= s, bshard[j - s + n_loc] for j < s; both are
    # lane j of the corresponding local roll by s.
    a_roll = jnp.roll(a, s, axis=-1)
    b_roll = jnp.roll(bshard, s, axis=-1)
    lane = jnp.arange(n_loc)
    return jnp.where(lane >= s, a_roll, b_roll)


def deliver_pool_sharded(channels_loc, choice_loc, offsets, axis: str, n_dev: int):
    """Sharded offset-pool delivery (ops/delivery.deliver_pool under
    shard_map): K masked *dynamic* global rolls instead of a scatter into a
    full-length vector + psum_scatter. ``channels_loc`` is [C, n_loc] — the
    stacked message channels ride the same ppermutes. Accumulation follows
    the same static pool-slot order as the single-device path, so sharded
    pool trajectories are bit-identical to single-device ones (pinned by
    tests/test_halo.py)."""
    inbox = jnp.zeros_like(channels_loc)
    zero = jnp.zeros((), channels_loc.dtype)
    for k in range(offsets.shape[0]):
        masked = jnp.where(choice_loc == k, channels_loc, zero)
        inbox = inbox + global_roll_dynamic(masked, offsets[k], axis, n_dev)
    return inbox


def deliver_halo(values_loc, disp_loc, plan: HaloPlan, axis: str,
                 batched: bool = False):
    """Sharded stencil delivery: inbox shard from |offsets| masked halo
    rolls. ``values_loc`` is [..., n_loc] — push-sum stacks its s and w
    channels so both ride one ppermute per offset class. ``disp_loc`` is the
    per-sender modular displacement (targets - global_ids) mod n for this
    shard; masking selects, per offset class, exactly the senders using that
    displacement (mirrors ops/delivery.deliver_stencil); per-channel
    accumulation order is unchanged by stacking, so results stay bit-identical
    to the single-device stencil path.

    ``batched=True`` routes the BATCHED HALO WIRE (``deliver_halo_batched``):
    every class's boundary slice rides ONE ppermute pair per round instead of
    one ppermute per class — the same bytes in fewer, larger wires, which on
    ICI turns per-class wire latency into a single volley. The delivered
    values and the accumulation order are identical either way, so the two
    schedules are bitwise-interchangeable (tests/test_overlap.py)."""
    if batched:
        return deliver_halo_batched(values_loc, disp_loc, plan, axis)
    zero = jnp.zeros((), values_loc.dtype)
    inbox = jnp.zeros_like(values_loc)
    for d, s in zip(plan.offsets_mod, plan.offsets_signed):
        masked = jnp.where(disp_loc == d, values_loc, zero)
        inbox = inbox + halo_roll(masked, int(s), axis, plan.n_dev)
    return inbox


def deliver_halo_batched(values_loc, disp_loc, plan: HaloPlan, axis: str):
    """Batched-wire variant of ``deliver_halo``: pack every offset class's
    boundary slice into ONE contiguous send buffer per ring direction, issue
    a single ppermute pair (forward + backward) per round, then unpack and
    stitch each class's roll locally. Per-class masked values, per-class
    stitch geometry, and the accumulation order all match the per-class
    schedule exactly — only the wire packaging changes, so trajectories are
    bitwise-identical (ints exactly, floats to the last bit).

    On a single-device "mesh" there are no wires at all; the per-class
    jnp.roll path is already wire-free and is reused unchanged."""
    n_dev = plan.n_dev
    classes = list(zip(plan.offsets_mod, plan.offsets_signed))
    zero = jnp.zeros((), values_loc.dtype)
    masked = [
        jnp.where(disp_loc == d, values_loc, zero) for d, _ in classes
    ]
    if n_dev == 1:
        inbox = jnp.zeros_like(values_loc)
        for m, (_, s) in zip(masked, classes):
            inbox = inbox + halo_roll(m, int(s), axis, 1)
        return inbox
    # Wire layout: positive rolls ship the top |s| lanes to device k+1,
    # negative rolls the bottom |s| lanes to device k-1 (halo_roll's own
    # geometry). Classes with s == 0 need no wire.
    fwd = [(i, int(s)) for i, (_, s) in enumerate(classes) if s > 0]
    bwd = [(i, -int(s)) for i, (_, s) in enumerate(classes) if s < 0]

    def volley(sends, step):
        if not sends:
            return {}
        packed = jnp.concatenate(
            [masked[i][..., -w:] if step > 0 else masked[i][..., :w]
             for i, w in sends],
            axis=-1,
        )
        recv = lax.ppermute(packed, axis, _ring_perm(n_dev, step))
        out, off = {}, 0
        for i, w in sends:
            out[i] = recv[..., off:off + w]
            off += w
        return out

    recv_f = volley(fwd, +1)
    recv_b = volley(bwd, -1)
    inbox = jnp.zeros_like(values_loc)
    for i, (_, s) in enumerate(classes):
        s = int(s)
        if s == 0:
            rolled = masked[i]
        elif s > 0:
            rolled = jnp.concatenate(
                [recv_f[i], masked[i][..., :-s]], axis=-1
            )
        else:
            rolled = jnp.concatenate(
                [masked[i][..., -s:], recv_b[i]], axis=-1
            )
        inbox = inbox + rolled
    return inbox


def exchange_rows_batched(planes, H: int, axis: str, n_dev: int):
    """Halo-extend node-sharded [rows_loc, LANES] planes with ONE ppermute
    pair for ALL planes: each plane is bitcast to int32 (bitwise-exact for
    the compositions' float32/int32 planes), stacked, the H-row boundary
    slices exchanged around the device ring in a single forward + backward
    volley, and unpacked back to the original dtypes. Replaces one ppermute
    pair PER PLANE (parallel/fused_sharded.ext_rows): a push-sum super-step's
    8 wires become 2, same bytes. Left halo = left neighbor's last H rows,
    right = right neighbor's first H rows (ring order = global row order) —
    identical to the per-plane exchange, hence bitwise-neutral."""
    cast = [
        p if p.dtype == jnp.int32 else lax.bitcast_convert_type(p, jnp.int32)
        for p in planes
    ]
    stack = jnp.stack(cast)
    left = lax.ppermute(stack[:, -H:], axis, _ring_perm(n_dev, +1))
    right = lax.ppermute(stack[:, :H], axis, _ring_perm(n_dev, -1))
    ext = jnp.concatenate([left, stack, right], axis=1)
    return tuple(
        ext[i] if p.dtype == jnp.int32
        else lax.bitcast_convert_type(ext[i], p.dtype)
        for i, p in enumerate(planes)
    )


def band_segments(rows_loc: int, n_dev: int) -> int:
    """Segment count of the banded reduce_scatter wire: each banded
    delivery is issued as this many independent reduce_scatters over
    row SLICES of the band, so the per-collective send operand is
    [n_dev * rows_loc / n_seg, LANES] instead of the O(N) full-length
    contribution buffer a single collective would need.
    gcd(rows_loc, n_dev) — the largest segment count that both slices
    the band into whole rows and is bounded by the mesh: on power-of-two
    meshes over the 512-multiple pool layouts this is n_dev exactly and
    the operand is the O(N/P) shard size; a smaller common divisor (a
    mesh width not dividing the shard) inflates the operand by
    n_dev/n_seg, which the plan's scatter_buf budget accounts for using
    this same function. The ONE home for the count, shared by the wire
    builder, the plan's budget, and the WIRE_SPEC environment
    (analysis/wire_specs.wire_env), so declaration and program cannot
    drift."""
    import math

    return math.gcd(rows_loc, n_dev)


def _band_segment_buffer(rolled, low, base, seg_lo: int, rows_seg: int,
                         rows_loc: int, n_dev: int, axis: str):
    """Per-sender reduce_scatter operand for ONE segment of a banded row
    delivery: the [n_dev * rows_seg, LANES] buffer whose receiver-r chunk
    holds THIS shard's rows of band offsets [seg_lo, seg_lo + rows_seg)
    of receiver r's band (zeros elsewhere).

    Band semantics: receiver r's core band is global rows
    [r*rows_loc + base, (r+1)*rows_loc + base) mod R of the row-sharded
    plane (R = n_dev * rows_loc, ``base`` a replicated traced scalar in
    [0, R)). Every global row lands in exactly ONE receiver cell across
    the segments, so each reduce_scatter sum has a single nonzero
    contributor per cell — adding exact zeros — and the delivered rows
    are bitwise copies of the source rows for int and float planes alike.

    Geometry: sender s's contribution to receiver r covers band offsets u
    with (shift_r + u) mod R < rows_loc, shift_r = ((r-s)*rows_loc + base)
    mod R. Because R ≡ 0 (mod rows_loc), every nonzero chunk is the SAME
    local circular roll by a = base mod rows_loc (``rolled``,
    precomputed once per plane), masked to its piece: the low band
    offsets (u < rows_loc - a, the precomputed ``low`` column) when
    shift_r < rows_loc, the high ones when shift_r wraps
    (> R - rows_loc)."""
    R = n_dev * rows_loc
    s = lax.axis_index(axis).astype(jnp.int32)
    zero = jnp.zeros((), rolled.dtype)
    seg = rolled[seg_lo:seg_lo + rows_seg]
    low_seg = low[seg_lo:seg_lo + rows_seg]
    chunks = []
    for r in range(n_dev):
        shift = lax.rem(
            (jnp.int32(r) - s) * jnp.int32(rows_loc) + base + jnp.int32(R),
            jnp.int32(R),
        )
        mask = jnp.where(
            shift < jnp.int32(rows_loc), low_seg,
            jnp.where(shift > jnp.int32(R - rows_loc), ~low_seg, False),
        )
        chunks.append(jnp.where(mask, seg, zero))
    return jnp.concatenate(chunks, axis=0)


def scatter_band_rows(plane_bases, rows_loc: int, margin: int, axis: str,
                      n_dev: int, batched: bool = True):
    """The replicated-pool2 reduce_scatter wire (ISSUE 15): deliver each
    device one [rows_loc + margin, LANES] BAND per (plane, base) item —
    the O(N/P + margins) row range its pool-slot windows actually consume
    — instead of all-gathering the full O(N) summary copy.

    ``plane_bases`` is a list of (plane_loc [rows_loc, LANES], base)
    items; items sharing a base (push-sum's s/w pair per slot) should be
    adjacent so the batched schedule groups them. Core rows arrive via
    ``band_segments`` segmented ``lax.psum_scatter`` calls (the
    reduce_scatter primitive; single nonzero contributor per cell, see
    _band_segment_buffer — bitwise-exact, and the per-collective operand
    stays O(N/P)); margin rows — the first ``margin`` rows of the NEXT
    device's band — via one ppermute volley around the ring.
    ``batched=True`` (the overlap schedule) groups same-base items into
    one reduce_scatter per (base, segment) and packs ALL margins into a
    single ppermute; ``batched=False`` issues per-item collectives. Same
    bytes, same values either way.

    margin <= rows_loc required (the margin comes from ONE ring
    neighbor); callers' plans enforce it. With n_dev == 1 there is no
    wire at all — the band is a local roll plus its own wrap rows."""
    if n_dev == 1:
        out = []
        for p, base in plane_bases:
            full = jnp.roll(p, -base, axis=0)
            out.append(jnp.concatenate([full, full[:margin]], axis=0))
        return out
    n_seg = band_segments(rows_loc, n_dev)
    rows_seg = rows_loc // n_seg

    def rs_group(group):
        """Segmented reduce_scatters for items sharing a base."""
        base = group[0][1]
        a = lax.rem(base, jnp.int32(rows_loc))
        u = lax.broadcasted_iota(jnp.int32, (rows_loc, 1), 0)
        low = u < jnp.int32(rows_loc) - a
        rolleds = [jnp.roll(p, -a, axis=0) for p, _ in group]
        seg_cores = []
        for si in range(n_seg):
            bufs = jnp.stack([
                _band_segment_buffer(
                    rolled, low, base, si * rows_seg, rows_seg,
                    rows_loc, n_dev, axis,
                )
                for rolled in rolleds
            ])
            seg_cores.append(lax.psum_scatter(
                bufs, axis, scatter_dimension=1, tiled=True
            ))
        cores = jnp.concatenate(seg_cores, axis=1)
        return [cores[i] for i in range(len(group))]

    if batched:
        groups: list = []
        for item in plane_bases:
            if groups and groups[-1][0][1] is item[1]:
                groups[-1].append(item)
            else:
                groups.append([item])
        cores = [c for g in groups for c in rs_group(g)]
        stack = jnp.stack([
            c[:margin] if c.dtype == jnp.int32
            else lax.bitcast_convert_type(c[:margin], jnp.int32)
            for c in cores
        ])
        recv = lax.ppermute(stack, axis, _ring_perm(n_dev, -1))
        return [
            jnp.concatenate([
                c,
                recv[i] if c.dtype == jnp.int32
                else lax.bitcast_convert_type(recv[i], c.dtype),
            ], axis=0)
            for i, c in enumerate(cores)
        ]
    out = []
    for p, base in plane_bases:
        (core,) = rs_group([(p, base)])
        recv = lax.ppermute(core[:margin], axis, _ring_perm(n_dev, -1))
        out.append(jnp.concatenate([core, recv], axis=0))
    return out


def gather_rows_batched(planes, axis: str):
    """All-gather node-sharded [rows_loc, LANES] planes into full
    [R_glob, LANES] copies with ONE all_gather for ALL planes (bitcast to
    int32, stacked, gathered along the row axis, unpacked) — the batched
    wire for the fused pool x sharded composition, which previously paid
    one all_gather per plane per super-step. Bitcast is bitwise-exact, so
    the gathered copies are identical to the per-plane gathers."""
    cast = [
        p if p.dtype == jnp.int32 else lax.bitcast_convert_type(p, jnp.int32)
        for p in planes
    ]
    stack = jnp.stack(cast)
    full = lax.all_gather(stack, axis, axis=1, tiled=True)
    return tuple(
        full[i] if p.dtype == jnp.int32
        else lax.bitcast_convert_type(full[i], p.dtype)
        for i, p in enumerate(planes)
    )

