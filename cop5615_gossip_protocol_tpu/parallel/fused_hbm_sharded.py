"""HBM-streaming stencil x sharded: lattice scale PAST VMEM, across chips.

parallel/fused_sharded.py composes the VMEM-resident fused engines with node
sharding, which caps the PER-SHARD population at the VMEM plane budget
(~2^21 pool / ~1.2M stencil slots). One chip alone streams 2^27 nodes
through HBM (ops/fused_stencil_hbm.py) — so sharding used to SHRINK the
reachable population instead of multiplying it (VERDICT r4 missing #1).
This module runs the HBM-streaming stencil engine inside the same
halo-amortized shard_map skeleton:

- each device holds its shard of the global [R_glob, 128] padded node
  layout plus an H-row halo per side, ALL IN HBM (that is the point);
- one super-step = ONE batched ppermute pair carrying every plane's halo
  slices (parallel/halo.exchange_rows_batched; one pair per plane under
  --overlap-collectives off), then ONE per-shard `pallas_call` that streams
  PT-row processing tiles through VMEM for CR whole rounds — ping/pong
  parity planes, mirrored-margin delivery windows, in-consumer threefry at
  GLOBAL positions: the single-device streamed architecture of
  ops/fused_stencil_hbm.py re-indexed so that extended row r is global row
  (row0 + r) mod R_glob;
- under the default overlap schedule (parallel/overlap.py) the super-steps
  are double-buffered: the exchange for super-step k+1 writes the inactive
  ring copy right after super-step k's kernel, and the termination psum for
  super-step k reduces under super-step k+1's kernel (one-super-step
  verdict lag; `rounds` stays exact — a fired deferred verdict discards
  the in-flight speculative super-step and returns the retired copy);
- halo regions are recomputed redundantly and stay valid for exactly CR
  rounds: delivery is exact in slot space (out[j] reads in[j - e]), so
  contamination from the buffer edges advances at most w slots per round
  (w = the largest in-buffer window shift) and H >= ceil(CR*w/128) + 1
  rows keeps the middle shard exact — the parallel/fused_sharded.py
  invariant, unchanged by streaming;
- convergence composes at super-step boundaries: local termination psums
  the last round's middle-region converged count (CR-granular, exact at
  chunk_rounds=1); termination='global' psums the kernel's PER-ROUND
  middle unstable-lane counts and, when an interior round's global count
  hits zero, REruns the chunk capped at that round — the stop round and
  final state are exactly the sharded chunked global path's
  (parallel/sharded.py + models/pushsum.absorb global_termination).

Delivery windows ride the extended ring: per class d, the in-buffer
circular roll pair (e1 for receivers at global flat >= d, e2 below — the
fused_sharded blend); non-wrap lattices need only the signed single window
(boundary live-masks already kill every would-be wrapping sender, the
ops/fused_stencil_hbm._signed_pad_shift argument), and wrap lattices at
Z = 0 have e1 == e2. When the blend is live (wrap, Z > 0), a tile fetches
ONE window at the variant it actually uses; only tiles whose global slot
interval contains a blend crossing (at most ~2 per class per device) fetch
the second, predicated — the streamed engines' straddle-tile scheme with
the tile->global map made runtime (row0-dependent).

The aggregate population ceiling is therefore n_dev * (single-chip HBM
budget): 8 x 2^27 = 2^30 nodes on the BASELINE.json v4-8 shape — sharding
now multiplies the ceiling. Trajectories match the chunked sharded path
bit-for-bit for integer state (gossip) and up to compiler reassociation
for push-sum (tests/test_fused_hbm_sharded.py; tests_tpu/ on hardware).

Reference mapping: C15's recast of the reference's whole runtime — the
lattice hot loop (program.fs:89-105, 110-143) over Imp3D-family wirings
(program.fs:295-306), actor-per-node on one machine's threads capped at
~2000 nodes (program.fs:23, report.pdf p.3 §4) — at a billion nodes
across a mesh.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import SimConfig
from ..ops.fused import clamp_cap_and_pad, threefry2x32_hash
from ..ops.fused_pool import LANES, build_pool_layout
from ..ops.fused_pool2 import _copy_all, _win_plan
from ..ops.fused_stencil_hbm import (
    _HBM_KINDS,
    _lattice_params,
    _sample_disp_dirs,
    _window_marked,
    _window_vals,
)
from ..ops.topology import Topology, stencil_offsets
from ..utils import compat
from .fused_sharded import _signed_pad

_PT_CANDIDATES = (2048, 1024, 512, 256)
# Per-device HBM for the kernel's resident planes (state parities +
# delivery). The v5e chip has 16 GB; leave room for the XLA-side extended
# inputs and collective buffers.
_HBM_PLANE_BUDGET = 12 * 2**30
_VMEM_SCRATCH_BUDGET = 80 * 2**20


def _class_sigmas(topo: Topology, layout):
    """Per class d: (d, sigma1, sigma2) signed in-buffer sender offsets —
    the ONE home for the wrap/non-wrap case analysis that both the window
    rolls (_class_windows) and the halo-sufficiency width
    (_halo_width_slots) derive from, so the two can never drift. sigma1
    serves receivers at global flat >= d, sigma2 those below (the
    fused_sharded mod-n blend pair); sigma2 is None when one window is
    exact for every receiver: non-wrap lattices (boundary live-masks kill
    every would-be wrapping sender — the
    ops/fused_stencil_hbm._signed_pad_shift argument) and wrap lattices at
    Z = 0 (both variants coincide)."""
    offsets = [int(d) for d in stencil_offsets(topo)]
    _, wrap = _lattice_params(topo)
    n_pad = layout.n_pad
    N = layout.n
    out = []
    for d in offsets:
        if wrap:
            s1 = _signed_pad(-d, n_pad)
            s2 = _signed_pad(N - d, n_pad)
            out.append((d, s1, None if s1 == s2 else s2))
        else:
            out.append((d, -(d if d <= N // 2 else d - N), None))
    return out


def _halo_width_slots(topo: Topology, layout) -> int:
    """Largest |in-buffer shift| any delivery window uses — the per-round
    contamination advance from the extended buffer's edges."""
    return max(
        max(abs(s1), abs(s2 if s2 is not None else 0))
        for _, s1, s2 in _class_sigmas(topo, layout)
    )


def plan_stencil_hbm_sharded(topo: Topology, cfg: SimConfig, n_dev: int):
    """(H, rows_loc, CR, PT, layout) or a string reason why not.

    Mirrors plan_fused_sharded's gates; the budgets differ: state lives in
    HBM, so the population check is the per-device HBM plane budget (the
    single-chip tier's 2^27-class ceiling, times the mesh), and VMEM only
    bounds the PT-row streaming scratch."""
    if topo.implicit:
        return (
            "implicit (full) topology has no displacement structure for "
            "the halo composition; use delivery='pool' (the fused pool x "
            "sharded composition)"
        )
    if topo.kind not in _HBM_KINDS:
        return (
            f"topology {topo.kind!r} has no arithmetic displacement "
            f"columns (served kinds: {', '.join(_HBM_KINDS)})"
        )
    offsets = stencil_offsets(topo)
    if offsets is None:
        return f"topology {topo.kind!r} has no small displacement set"
    if cfg.dtype != "float32":
        return "fused engine supports float32 only"
    if not jax.config.jax_threefry_partitionable:
        return "requires jax_threefry_partitionable=True"
    if cfg.telemetry:
        return (
            "telemetry counters run in the single-device fused kernels and "
            "the chunked/sharded XLA engines; this composition does not "
            "carry the counter block"
        )
    if cfg.faulted:
        # No failure-model support in this engine yet — rejecting on
        # the aggregate flag (not just fault_rate) keeps a crash/dup/
        # delay config from silently running unfaulted here. The
        # stencil (ops/fused.py) and pool tiers (ops/fused_pool.py,
        # ops/fused_pool2.py) run drop+crash in-kernel.
        return "failure models not supported in this fused kernel"
    if cfg.delivery == "scatter":
        return (
            "the fused kernel delivers via the stencil formulation only; "
            "delivery='scatter' would be silently ignored"
        )
    layout = build_pool_layout(topo.n)
    R = layout.rows
    if R % n_dev != 0:
        return (
            f"padded layout ({R} rows) must split evenly; {n_dev} devices "
            "do not divide it"
        )
    rows_loc = R // n_dev
    Z = layout.n_pad - layout.n
    _, wrap = _lattice_params(topo)
    blend = wrap and Z != 0
    w = _halo_width_slots(topo, layout)
    pushsum = cfg.algorithm == "push-sum"
    hbm_planes = 11 if pushsum else 7  # 2 parities x state + delivery
    # The overlapped super-step schedule (parallel/overlap.py) carries the
    # halo-extended ring AND a retired mid copy per plane in the XLA-side
    # loop carry (the double buffer the deferred verdict rolls back to);
    # those rows live in HBM next to the kernel's resident planes, so the
    # plan budgets them UNCONDITIONALLY — even for the serial schedule
    # (--overlap-collectives off, or termination='global', which keeps the
    # serial loop), which never allocates them. Deliberate conservatism:
    # the plan's geometry (H, CR, PT) must be identical across the overlap
    # knob, or a budget-edge population would pick a smaller CR only on
    # one schedule and super-step-granular `rounds` would differ — breaking
    # the knob's bitwise-interchangeability and resume contracts for a few
    # spare rows of headroom.
    n_state = 4 if pushsum else 3
    CR0 = max(1, min(int(cfg.chunk_rounds), 64))
    win_per_class = (3 if pushsum else 1) * (2 if blend else 1)
    n_win = len(offsets) * win_per_class

    def fit(cr):
        h_min = -(-(cr * w) // LANES) + 1
        cands = []
        for pt in _PT_CANDIDATES:
            r = (-rows_loc) % pt
            if r % 2:
                continue  # 2H cannot hit an odd residue mod an even PT
            h = h_min + ((r // 2 - h_min) % (pt // 2))
            rows_ext = rows_loc + 2 * h
            if rows_ext // pt < 2 or h > rows_loc:
                continue
            vmem = (
                (7 if pushsum else 4) * pt * LANES * 4
                + n_win * (pt + 16) * LANES * 4
            )
            if vmem > _VMEM_SCRATCH_BUDGET:
                continue
            carry_rows = n_state * (rows_ext + rows_loc)
            hbm = (
                hbm_planes * (rows_ext + pt + 16) + carry_rows
            ) * LANES * 4
            if hbm > _HBM_PLANE_BUDGET:
                continue
            cands.append((rows_ext, pt, h))
        if not cands:
            return None
        # Largest PT whose halo waste stays within ~12% of the leanest
        # candidate: fewer, larger DMA volleys per round beat a few percent
        # of redundant halo rows.
        lean = min(c[0] for c in cands)
        ok = [c for c in cands if c[0] <= lean + max(lean // 8, 1)]
        return max(ok, key=lambda c: c[1])

    CR = CR0
    while CR > 1 and fit(CR) is None:
        CR //= 2
    b = fit(CR)
    if b is None:
        return (
            f"no processing-tile split fits: per-round halo ({w} slots) at "
            f"a {rows_loc}-row shard exceeds the shard, the VMEM streaming "
            "scratch, or the per-device HBM plane budget even at "
            "chunk_rounds=1; use the chunked collective engine"
        )
    _, PT, H = b
    return (H, rows_loc, CR, PT, layout)


def _class_windows(topo: Topology, layout, rows_ext: int):
    """Per class d: (d, e1, e2) in-buffer forward roll amounts over the
    extended ring (n_ext = rows_ext * 128) — a forward roll by e delivers
    out[j] = in[j - e], so e = (-sigma) mod n_ext for each of
    _class_sigmas' sender offsets. e2 is None whenever sigma2 is."""
    n_ext = rows_ext * LANES
    return [
        (d, (-s1) % n_ext, None if s2 is None else (-s2) % n_ext)
        for d, s1, s2 in _class_sigmas(topo, layout)
    ]


def _tile_blend_plan(row0, r0, d: int, R_glob: int, n_pad: int, PT: int):
    """Scalar blend facts for one (tile, class): the tile's global slot
    interval is [lo, lo + PT*128) mod n_pad; the blend select
    (take = gflat >= d) changes value only at crossings d and 0, so a tile
    containing neither is UNIFORM and needs one window — the variant of its
    first slot. Conservative at the lo == crossing edge (marks nonuniform,
    costing one spare fetch, never correctness). Returns
    (nonuniform, take_lo) traced booleans."""
    lo = lax.rem(row0 + r0, jnp.int32(R_glob)) * jnp.int32(LANES)
    PTL = jnp.int32(PT * LANES)
    npj = jnp.int32(n_pad)
    c_d = lax.rem(jnp.int32(d) - lo + 2 * npj, npj) < PTL
    c_0 = lax.rem(npj - lo, npj) < PTL
    return c_d | c_0, lo >= jnp.int32(d)


def _start_class_volley(windows, r0, row0, pairs, wsems, stride: int,
                        R_glob: int, n_pad: int, PT: int, M: int,
                        rows_ext: int):
    """Start every class's PRIMARY window DMA before waiting on any (the
    stencil_hbm gossip lesson — serialized start/wait pairs leave each ~MB
    transfer's latency exposed), at the blend variant this tile actually
    uses; tiles containing a blend crossing (at most ~2 per class per
    device) fetch the second variant predicated, start+wait inside the
    pl.when. ``pairs`` is [(hbm_plane, window_stack), ...] — one pair for
    the gossip marked plane, three (ds, dw, dm) for push-sum. Returns
    (plans, wrap_plans, nonunis, cps); callers wait on ``cps`` and consume
    through the (rl, off) plans. The ONE home for the composition's
    subtlest predicate, shared by both kernels."""
    n_pairs = len(pairs)
    plans, cps, nonunis = [], [], []
    for ci, (d_c, e1, e2) in enumerate(windows):
        if e2 is None:
            e_sel = jnp.int32(e1)
            nonunis.append(None)
        else:
            nonuni, take_lo = _tile_blend_plan(
                row0, r0, d_c, R_glob, n_pad, PT
            )
            nonunis.append(nonuni)
            e_sel = jnp.where(
                nonuni | take_lo, jnp.int32(e1), jnp.int32(e2)
            )
        ws8, rl, off = _win_plan(r0, e_sel, rows_ext)
        slot = ci * stride
        for si, (pln, wref) in enumerate(pairs):
            cp = pltpu.make_async_copy(
                pln.at[pl.ds(ws8, M), :], wref.at[slot],
                wsems.at[slot * n_pairs + si],
            )
            cp.start()
            cps.append(cp)
        plans.append((rl, off))
    wrap_plans = []
    for ci, (d_c, e1, e2) in enumerate(windows):
        if e2 is None:
            wrap_plans.append(None)
            continue
        ws8_2, rl2, off2 = _win_plan(r0, jnp.int32(e2), rows_ext)
        wrap_plans.append((rl2, off2))
        slot2 = ci * stride + 1

        @pl.when(nonunis[ci])
        def _fetch_wrap(ws8_2=ws8_2, slot2=slot2):
            cps2 = [
                pltpu.make_async_copy(
                    pln.at[pl.ds(ws8_2, M), :], wref.at[slot2],
                    wsems.at[slot2 * n_pairs + si],
                )
                for si, (pln, wref) in enumerate(pairs)
            ]
            for cp2 in cps2:
                cp2.start()
            for cp2 in cps2:
                cp2.wait()

    return plans, wrap_plans, nonunis, cps


def make_pushsum_stencil_hbm_shard_chunk(
    topo: Topology, cfg: SimConfig, H: int, rows_loc: int, PT: int,
    layout, *, interpret: bool = False
):
    """Per-device chunk kernel: ``chunk_fn(ext_state, keys, row0, start,
    cap) -> (mid_state4, executed, u)`` runs up to K = keys.shape[0]
    push-sum rounds on one device's halo-extended planes, HBM-streamed.
    ``row0`` is the extended buffer's first GLOBAL row (pre-wrapped);
    ``u[k]`` is round k's middle-region metric — unstable valid lanes
    under termination='global', converged count otherwise; -1 on rounds
    not executed."""
    R_glob = layout.rows
    N = layout.n
    n_pad = layout.n_pad
    Z = n_pad - N
    rows_ext = rows_loc + 2 * H
    T = rows_ext // PT
    M = PT + 16
    dirs_builder, wrap = _lattice_params(topo)
    blend = wrap and Z != 0
    windows = _class_windows(topo, layout, rows_ext)
    C = len(windows)
    stride = 2 if blend else 1
    delta = np.float32(cfg.resolved_delta)
    term_rounds = np.int32(cfg.term_rounds)
    global_term = cfg.termination == "global"

    def kernel(
        scal_ref, keys_ref, s_in, w_in, t_in, c_in,
        sA, wA, tA, cA, sB, wB, tB, cB, ds_p, dw_p, dm_p, meta_o, u_o,
        scr_s, scr_w, scr_t, scr_c, scr_ds, scr_dw, scr_dm,
        win_s, win_w, win_m, flags, sems, wsems,
    ):
        k = pl.program_id(0)
        K = pl.num_programs(0)
        row_l = lax.broadcasted_iota(jnp.int32, (PT, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (PT, LANES), 1)
        row0 = scal_ref[0]

        def tile_globals(r0):
            grow = lax.rem(row0 + r0 + row_l, jnp.int32(R_glob))
            gflat = grow * LANES + lane
            return grow, gflat

        @pl.when(k == 0)
        def _init():
            def cp(t, _):
                r0 = t * PT
                _copy_all([
                    (s_in.at[pl.ds(r0, PT), :], scr_s),
                    (w_in.at[pl.ds(r0, PT), :], scr_w),
                    (t_in.at[pl.ds(r0, PT), :], scr_t),
                    (c_in.at[pl.ds(r0, PT), :], scr_c),
                ], sems)
                _copy_all([
                    (scr_s, sA.at[pl.ds(r0, PT), :]),
                    (scr_w, wA.at[pl.ds(r0, PT), :]),
                    (scr_t, tA.at[pl.ds(r0, PT), :]),
                    (scr_c, cA.at[pl.ds(r0, PT), :]),
                ], sems)
                return 0

            lax.fori_loop(0, T, cp, 0, unroll=False)
            flags[0] = jnp.int32(0)  # rounds executed

        u_o[k] = jnp.int32(-1)
        active = scal_ref[1] + k < scal_ref[2]

        def round_body(cur, nxt):
            (s_c, w_c, t_c, c_c) = cur
            (s_n, w_n, t_n, c_n) = nxt
            kk = k % 8
            k1 = keys_ref[kk, 0]
            k2 = keys_ref[kk, 1]

            def p1(t, _):
                r0 = t * PT
                _copy_all([
                    (s_c.at[pl.ds(r0, PT), :], scr_s),
                    (w_c.at[pl.ds(r0, PT), :], scr_w),
                ], sems)
                grow, gflat = tile_globals(r0)
                padm = gflat >= N
                bits = threefry2x32_hash(
                    k1, k2,
                    grow.astype(jnp.uint32) * jnp.uint32(LANES)
                    + lane.astype(jnp.uint32),
                )
                d, deg_t = _sample_disp_dirs(bits, dirs_builder(gflat))
                send_ok = (deg_t > 0) & ~padm
                scr_ds[:] = jnp.where(send_ok, scr_s[:] * 0.5, 0.0)
                scr_dw[:] = jnp.where(send_ok, scr_w[:] * 0.5, 0.0)
                scr_dm[:] = jnp.where(send_ok, d, jnp.int32(-1))
                _copy_all([
                    (scr_ds, ds_p.at[pl.ds(r0, PT), :]),
                    (scr_dw, dw_p.at[pl.ds(r0, PT), :]),
                    (scr_dm, dm_p.at[pl.ds(r0, PT), :]),
                ], sems)

                @pl.when(t == 0)
                def _mirror0():
                    _copy_all([
                        (scr_ds, ds_p.at[pl.ds(rows_ext, PT), :]),
                        (scr_dw, dw_p.at[pl.ds(rows_ext, PT), :]),
                        (scr_dm, dm_p.at[pl.ds(rows_ext, PT), :]),
                    ], sems)

                @pl.when(t == 1)
                def _mirror1():
                    _copy_all([
                        (scr_ds.at[pl.ds(0, 16), :],
                         ds_p.at[pl.ds(rows_ext + PT, 16), :]),
                        (scr_dw.at[pl.ds(0, 16), :],
                         dw_p.at[pl.ds(rows_ext + PT, 16), :]),
                        (scr_dm.at[pl.ds(0, 16), :],
                         dm_p.at[pl.ds(rows_ext + PT, 16), :]),
                    ], sems)

                return 0

            lax.fori_loop(0, T, p1, 0, unroll=False)

            def p2(t, acc):
                r0 = t * PT
                _copy_all([
                    (s_c.at[pl.ds(r0, PT), :], scr_s),
                    (w_c.at[pl.ds(r0, PT), :], scr_w),
                    (t_c.at[pl.ds(r0, PT), :], scr_t),
                    (c_c.at[pl.ds(r0, PT), :], scr_c),
                ], sems)
                _, gflat = tile_globals(r0)
                padm = gflat >= N
                mid = (row_l + r0 >= H) & (row_l + r0 < H + rows_loc)

                plans, wrap_plans, nonunis, cps = _start_class_volley(
                    windows, r0, row0,
                    [(ds_p, win_s), (dw_p, win_w), (dm_p, win_m)],
                    wsems, stride, R_glob, n_pad, PT, M, rows_ext,
                )
                for cp in cps:
                    cp.wait()

                inbox_s = jnp.zeros((PT, LANES), jnp.float32)
                inbox_w = jnp.zeros((PT, LANES), jnp.float32)
                for ci, (d_c, e1, e2) in enumerate(windows):
                    rl, off = plans[ci]
                    s1 = ci * stride
                    cs = _window_vals(
                        win_s.at[s1], win_m.at[s1], off, PT, rl, d_c,
                        lane, interpret,
                    )
                    cw = _window_vals(
                        win_w.at[s1], win_m.at[s1], off, PT, rl, d_c,
                        lane, interpret,
                    )
                    if e2 is not None:
                        rl2, off2 = wrap_plans[ci]
                        s2 = s1 + 1
                        use2 = nonunis[ci] & (gflat < d_c)
                        cs = jnp.where(
                            use2,
                            _window_vals(win_s.at[s2], win_m.at[s2], off2,
                                         PT, rl2, d_c, lane, interpret),
                            cs,
                        )
                        cw = jnp.where(
                            use2,
                            _window_vals(win_w.at[s2], win_m.at[s2], off2,
                                         PT, rl2, d_c, lane, interpret),
                            cw,
                        )
                    inbox_s = inbox_s + cs
                    inbox_w = inbox_w + cw
                inbox_s = jnp.where(padm, 0.0, inbox_s)
                inbox_w = jnp.where(padm, 0.0, inbox_w)
                s_t = scr_s[:]
                w_t = scr_w[:]
                s_send = jnp.where(padm, 0.0, s_t * 0.5)
                w_send = jnp.where(padm, 0.0, w_t * 0.5)
                s_new = (s_t - s_send) + inbox_s
                w_new = (w_t - w_send) + inbox_w
                if global_term:
                    # Global residual: term/conv stream through unchanged
                    # (the XLA side latches conv after the psum'd verdict);
                    # the metric counts MIDDLE unstable valid lanes.
                    ratio_old = s_t / w_t
                    tol = delta * jnp.maximum(
                        jnp.abs(ratio_old), jnp.float32(1)
                    )
                    unstable = (
                        jnp.abs(s_new / w_new - ratio_old) > tol
                    ) & ~padm & mid
                    term_new = scr_t[:]
                    conv_new = scr_c[:]
                    tile_metric = jnp.sum(
                        unstable.astype(jnp.int32), dtype=jnp.int32
                    )
                else:
                    received = inbox_w > 0
                    stable = jnp.abs(s_new / w_new - s_t / w_t) <= delta
                    term_new = jnp.where(
                        received,
                        jnp.where(stable, scr_t[:] + 1, jnp.int32(0)),
                        scr_t[:],
                    )
                    conv_new = jnp.where(
                        padm,
                        jnp.int32(0),
                        jnp.where(
                            (scr_c[:] != 0) | (term_new >= term_rounds),
                            jnp.int32(1),
                            jnp.int32(0),
                        ),
                    )
                    tile_metric = jnp.sum(
                        jnp.where(mid, conv_new, jnp.int32(0)),
                        dtype=jnp.int32,
                    )
                scr_s[:] = s_new
                scr_w[:] = w_new
                scr_t[:] = term_new
                scr_c[:] = conv_new
                _copy_all([
                    (scr_s, s_n.at[pl.ds(r0, PT), :]),
                    (scr_w, w_n.at[pl.ds(r0, PT), :]),
                    (scr_t, t_n.at[pl.ds(r0, PT), :]),
                    (scr_c, c_n.at[pl.ds(r0, PT), :]),
                ], sems)
                return acc + tile_metric

            total = lax.fori_loop(0, T, p2, jnp.int32(0), unroll=False)
            flags[0] = flags[0] + 1
            u_o[k] = total

        A = (sA, wA, tA, cA)
        B = (sB, wB, tB, cB)
        par = flags[0] % 2  # snapshot before the mutating branches

        @pl.when(active & (par == 0))
        def _round_even():
            round_body(A, B)

        @pl.when(active & (par == 1))
        def _round_odd():
            round_body(B, A)

        @pl.when(k == K - 1)
        def _emit():
            meta_o[0] = flags[0]
            meta_o[1] = flags[0] % 2

    def chunk_fn(ext_state, keys, row0, start, cap):
        s, w, t, c = ext_state
        cap, keys = clamp_cap_and_pad(start, cap, keys)
        K = keys.shape[0]
        f32 = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.float32)
        i32 = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.int32)
        f32m = jax.ShapeDtypeStruct((rows_ext + M, LANES), jnp.float32)
        i32m = jax.ShapeDtypeStruct((rows_ext + M, LANES), jnp.int32)
        outs = pl.pallas_call(
            kernel,
            grid=(K,),
            out_shape=(
                f32, f32, i32, i32,
                f32, f32, i32, i32,
                f32m, f32m, i32m,
                jax.ShapeDtypeStruct((2,), jnp.int32),
                jax.ShapeDtypeStruct((K,), jnp.int32),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((8, 2), lambda k: (k // 8, 0),
                             memory_space=pltpu.SMEM),
            ] + [pl.BlockSpec(memory_space=pl.ANY)] * 4,
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * 11
                + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
            ),
            scratch_shapes=[
                pltpu.VMEM((PT, LANES), jnp.float32),
                pltpu.VMEM((PT, LANES), jnp.float32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.float32),
                pltpu.VMEM((PT, LANES), jnp.float32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((C * stride, M, LANES), jnp.float32),
                pltpu.VMEM((C * stride, M, LANES), jnp.float32),
                pltpu.VMEM((C * stride, M, LANES), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
                pltpu.SemaphoreType.DMA((4,)),
                pltpu.SemaphoreType.DMA((C * stride * 3,)),
            ],
            compiler_params=compat.pallas_tpu_compiler_params(
                vmem_limit_bytes=96 * 1024 * 1024
            ),
            interpret=interpret,
        )(
            jnp.stack([jnp.int32(row0), jnp.int32(start), jnp.int32(cap)]),
            keys,
            s, w, t, c,
        )
        meta = outs[11]
        parity = meta[1]

        def sel(a, b):
            return jnp.where(
                parity == 0, a[H:H + rows_loc], b[H:H + rows_loc]
            )

        mid_state = tuple(sel(outs[i], outs[4 + i]) for i in range(4))
        return mid_state, meta[0], outs[12]

    return chunk_fn, rows_ext


def make_gossip_stencil_hbm_shard_chunk(
    topo: Topology, cfg: SimConfig, H: int, rows_loc: int, PT: int,
    layout, *, interpret: bool = False
):
    """Gossip analog: one marked-displacement delivery plane; receiver-side
    suppression on the streamed conv tile; ``u[k]`` is round k's
    middle-region converged count (-1 when not executed)."""
    R_glob = layout.rows
    N = layout.n
    n_pad = layout.n_pad
    Z = n_pad - N
    rows_ext = rows_loc + 2 * H
    T = rows_ext // PT
    M = PT + 16
    dirs_builder, wrap = _lattice_params(topo)
    blend = wrap and Z != 0
    windows = _class_windows(topo, layout, rows_ext)
    C = len(windows)
    stride = 2 if blend else 1
    rumor_target = np.int32(cfg.resolved_rumor_target)
    suppress = cfg.resolved_suppress

    def kernel(
        scal_ref, keys_ref, n_in, a_in, c_in,
        nA, aA, cA, nB, aB, cB, dm_p, meta_o, u_o,
        scr_n, scr_a, scr_c, scr_m, win_m, flags, sems, wsems,
    ):
        k = pl.program_id(0)
        K = pl.num_programs(0)
        row_l = lax.broadcasted_iota(jnp.int32, (PT, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (PT, LANES), 1)
        row0 = scal_ref[0]

        def tile_globals(r0):
            grow = lax.rem(row0 + r0 + row_l, jnp.int32(R_glob))
            gflat = grow * LANES + lane
            return grow, gflat

        @pl.when(k == 0)
        def _init():
            def cp(t, _):
                r0 = t * PT
                _copy_all([
                    (n_in.at[pl.ds(r0, PT), :], scr_n),
                    (a_in.at[pl.ds(r0, PT), :], scr_a),
                    (c_in.at[pl.ds(r0, PT), :], scr_c),
                ], sems)
                _copy_all([
                    (scr_n, nA.at[pl.ds(r0, PT), :]),
                    (scr_a, aA.at[pl.ds(r0, PT), :]),
                    (scr_c, cA.at[pl.ds(r0, PT), :]),
                ], sems)
                return 0

            lax.fori_loop(0, T, cp, 0, unroll=False)
            flags[0] = jnp.int32(0)

        u_o[k] = jnp.int32(-1)
        active = scal_ref[1] + k < scal_ref[2]

        def round_body(cur, nxt):
            (n_c, a_c, c_c) = cur
            (n_n, a_n, c_n) = nxt
            kk = k % 8
            k1 = keys_ref[kk, 0]
            k2 = keys_ref[kk, 1]

            def p1(t, _):
                r0 = t * PT
                _copy_all([(a_c.at[pl.ds(r0, PT), :], scr_a)], sems)
                grow, gflat = tile_globals(r0)
                padm = gflat >= N
                bits = threefry2x32_hash(
                    k1, k2,
                    grow.astype(jnp.uint32) * jnp.uint32(LANES)
                    + lane.astype(jnp.uint32),
                )
                d, deg_t = _sample_disp_dirs(bits, dirs_builder(gflat))
                sending = (scr_a[:] != 0) & (deg_t > 0) & ~padm
                scr_m[:] = jnp.where(sending, d, jnp.int32(-1))
                _copy_all([(scr_m, dm_p.at[pl.ds(r0, PT), :])], sems)

                @pl.when(t == 0)
                def _mirror0():
                    _copy_all(
                        [(scr_m, dm_p.at[pl.ds(rows_ext, PT), :])], sems
                    )

                @pl.when(t == 1)
                def _mirror1():
                    _copy_all([
                        (scr_m.at[pl.ds(0, 16), :],
                         dm_p.at[pl.ds(rows_ext + PT, 16), :]),
                    ], sems)

                return 0

            lax.fori_loop(0, T, p1, 0, unroll=False)

            def p2(t, acc):
                r0 = t * PT
                _copy_all([
                    (n_c.at[pl.ds(r0, PT), :], scr_n),
                    (a_c.at[pl.ds(r0, PT), :], scr_a),
                    (c_c.at[pl.ds(r0, PT), :], scr_c),
                ], sems)
                _, gflat = tile_globals(r0)
                padm = gflat >= N
                mid = (row_l + r0 >= H) & (row_l + r0 < H + rows_loc)

                plans, wrap_plans, nonunis, cps = _start_class_volley(
                    windows, r0, row0, [(dm_p, win_m)],
                    wsems, stride, R_glob, n_pad, PT, M, rows_ext,
                )
                for cp in cps:
                    cp.wait()

                inbox = jnp.zeros((PT, LANES), jnp.int32)
                for ci, (d_c, e1, e2) in enumerate(windows):
                    rl, off = plans[ci]
                    s1 = ci * stride
                    g = _window_marked(
                        win_m.at[s1], off, PT, rl, lane, interpret
                    )
                    if e2 is not None:
                        rl2, off2 = wrap_plans[ci]
                        g = jnp.where(
                            nonunis[ci] & (gflat < d_c),
                            _window_marked(win_m.at[s1 + 1], off2, PT, rl2,
                                           lane, interpret),
                            g,
                        )
                    inbox = inbox + jnp.where(
                        g == d_c, jnp.int32(1), jnp.int32(0)
                    )
                inbox = jnp.where(padm, jnp.int32(0), inbox)
                if suppress:
                    inbox = jnp.where(scr_c[:] != 0, jnp.int32(0), inbox)
                count_new = scr_n[:] + inbox
                active_new = jnp.where(
                    (scr_a[:] != 0) | (inbox > 0), jnp.int32(1), jnp.int32(0)
                )
                conv_new = jnp.where(
                    (count_new >= rumor_target) & ~padm,
                    jnp.int32(1), jnp.int32(0),
                )
                scr_n[:] = count_new
                scr_a[:] = active_new
                scr_c[:] = conv_new
                _copy_all([
                    (scr_n, n_n.at[pl.ds(r0, PT), :]),
                    (scr_a, a_n.at[pl.ds(r0, PT), :]),
                    (scr_c, c_n.at[pl.ds(r0, PT), :]),
                ], sems)
                return acc + jnp.sum(
                    jnp.where(mid, conv_new, jnp.int32(0)), dtype=jnp.int32
                )

            total = lax.fori_loop(0, T, p2, jnp.int32(0), unroll=False)
            flags[0] = flags[0] + 1
            u_o[k] = total

        A = (nA, aA, cA)
        B = (nB, aB, cB)
        par = flags[0] % 2

        @pl.when(active & (par == 0))
        def _round_even():
            round_body(A, B)

        @pl.when(active & (par == 1))
        def _round_odd():
            round_body(B, A)

        @pl.when(k == K - 1)
        def _emit():
            meta_o[0] = flags[0]
            meta_o[1] = flags[0] % 2

    def chunk_fn(ext_state, keys, row0, start, cap):
        cnt, act, cv = ext_state
        cap, keys = clamp_cap_and_pad(start, cap, keys)
        K = keys.shape[0]
        i32 = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.int32)
        i32m = jax.ShapeDtypeStruct((rows_ext + M, LANES), jnp.int32)
        outs = pl.pallas_call(
            kernel,
            grid=(K,),
            out_shape=(
                i32, i32, i32, i32, i32, i32, i32m,
                jax.ShapeDtypeStruct((2,), jnp.int32),
                jax.ShapeDtypeStruct((K,), jnp.int32),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((8, 2), lambda k: (k // 8, 0),
                             memory_space=pltpu.SMEM),
            ] + [pl.BlockSpec(memory_space=pl.ANY)] * 3,
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * 7
                + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
            ),
            scratch_shapes=[
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((C * stride, M, LANES), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
                pltpu.SemaphoreType.DMA((3,)),
                pltpu.SemaphoreType.DMA((C * stride,)),
            ],
            compiler_params=compat.pallas_tpu_compiler_params(
                vmem_limit_bytes=96 * 1024 * 1024
            ),
            interpret=interpret,
        )(
            jnp.stack([jnp.int32(row0), jnp.int32(start), jnp.int32(cap)]),
            keys,
            cnt, act, cv,
        )
        meta = outs[7]
        parity = meta[1]

        def sel(a, b):
            return jnp.where(
                parity == 0, a[H:H + rows_loc], b[H:H + rows_loc]
            )

        mid_state = tuple(sel(outs[i], outs[3 + i]) for i in range(3))
        return mid_state, meta[0], outs[8]

    return chunk_fn, rows_ext


def run_stencil_hbm_sharded(
    topo: Topology,
    cfg: SimConfig,
    mesh=None,
    key=None,
    on_chunk=None,
    start_state=None,
    start_round: int = 0,
    probe=None,
    deadline=None,
):
    """Sharded HBM-streaming run — engine='fused', n_devices > 1, lattices
    past the VMEM composition's per-shard budget.

    Same contract as parallel/fused_sharded.run_fused_sharded for local
    termination (detection at super-step granularity, exact at
    chunk_rounds=1). termination='global' stops at the EXACT verdict round:
    the kernel reports per-round middle unstable counts, the psum'd vector
    names the first globally-stable round, and a capped rerun of the same
    chunk (same keys — deterministic) lands the state there, matching the
    chunked sharded global path's stop round and state.

    cfg.overlap_collectives (default on) runs the overlapped super-step
    schedule (parallel/overlap.py): batched single-pair halo wires,
    double-buffered ring, the termination psum folded under the next
    super-step's kernel. Off = the serial schedule; both are
    bitwise-identical (pure scheduling). termination='global' keeps the
    serial loop (its verdict can demand a capped chunk rerun) but still
    rides the batched wires. ``probe(chunk_sharded, args)``, when given,
    receives the jitted chunk program and example arguments and its return
    value replaces the run (benchmarks/comm_audit.py's trace hook — no
    execution happens)."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import gossip as gossip_mod
    from ..models import pushsum as pushsum_mod
    from ..models.runner import _check_dtype, _finalize_result, draw_leader
    from ..ops import sampling
    from ..ops.fused import round_keys
    from . import halo as halo_mod
    from . import overlap as overlap_mod
    from .fused_sharded import global_verdict_step
    from .mesh import NODE_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh(cfg.n_devices)
    n_dev = mesh.devices.size
    plan = plan_stencil_hbm_sharded(topo, cfg, n_dev)
    if isinstance(plan, str):
        raise ValueError(
            f"engine='fused' with n_devices={n_dev} unavailable: {plan}"
        )
    H, rows_loc, CR, PT, layout = plan
    _check_dtype(cfg)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    interpret = jax.default_backend() != "tpu"
    pushsum = cfg.algorithm == "push-sum"
    global_term = cfg.termination == "global"
    make = (
        make_pushsum_stencil_hbm_shard_chunk
        if pushsum
        else make_gossip_stencil_hbm_shard_chunk
    )
    chunk_fn, rows_ext = make(
        topo, cfg, H, rows_loc, PT, layout, interpret=interpret
    )
    R_glob = layout.rows
    n = topo.n
    target = cfg.resolved_target_count(n, topo.target_count)
    key_data_host, key_impl = sampling.key_split(key)

    shard_rows = NamedSharding(mesh, P(NODE_AXIS, None))
    repl = NamedSharding(mesh, P())

    plane_fields = (
        [("s", np.float32, 0.0), ("w", np.float32, 1.0),
         ("term", np.int32, cfg.initial_term_round), ("conv", np.int32, 0)]
        if pushsum
        else [("count", np.int32, 0), ("active", np.int32, 0),
              ("conv", np.int32, 0)]
    )

    def to_planes(state):
        outs = []
        for f, dt, fill in plane_fields:
            x = np.asarray(getattr(state, f)).astype(dt)
            full = np.full(layout.n_pad, fill, dtype=dt)
            full[: x.shape[0]] = x
            outs.append(full.reshape(R_glob, LANES))
        return tuple(outs)

    if start_state is not None:
        st0 = jax.tree.map(np.asarray, start_state)
    elif pushsum:
        st0 = pushsum_mod.init_state(n, jnp.float32, cfg.initial_term_round)
    else:
        st0 = gossip_mod.init_state(
            n, draw_leader(key, topo, cfg),
            leader_counts_receipt=cfg.reference and topo.kind == "full",
        )
    planes0 = tuple(jax.device_put(p, shard_rows) for p in to_planes(st0))
    done0 = bool(np.asarray(st0.conv).sum() >= target)

    perm_fwd = [(d, (d + 1) % n_dev) for d in range(n_dev)]
    perm_bwd = [(d, (d - 1) % n_dev) for d in range(n_dev)]
    overlap = cfg.overlap_collectives

    def exchange(planes):
        """Halo-extend the mid planes: the batched wire (one ppermute pair
        for all planes, parallel/halo.py) under the overlap schedule, one
        pair per plane on the serial one — identical received bytes."""
        if overlap:
            return halo_mod.exchange_rows_batched(
                planes, H, NODE_AXIS, n_dev
            )

        def ext_rows(x):
            left = lax.ppermute(x[-H:], NODE_AXIS, perm_fwd)
            right = lax.ppermute(x[:H], NODE_AXIS, perm_bwd)
            return jnp.concatenate([left, x, right], axis=0)

        return tuple(ext_rows(p) for p in planes)

    def chunk_local(planes_in, rnd_in, done_in, round_end, key_data):
        base = sampling.key_join(key_data, key_impl)
        dev = lax.axis_index(NODE_AXIS)
        row0 = lax.rem(
            dev.astype(jnp.int32) * rows_loc - H + 2 * R_glob,
            jnp.int32(R_glob),
        )

        if overlap and not (pushsum and global_term):
            # Overlapped schedule (parallel/overlap.py): the verdict psum
            # for super-step k reduces under super-step k+1's kernel, the
            # next exchange writes the inactive ring copy right after the
            # kernel, and a fired deferred verdict rolls back to the
            # retired double-buffer copy — rounds stay exact.
            def compute(ext_state, rnd, cap):
                keys = round_keys(base, rnd, CR)
                out, executed, u = chunk_fn(ext_state, keys, row0, rnd, cap)
                conv_last = lax.dynamic_index_in_dim(
                    u, jnp.maximum(executed - 1, 0), keepdims=False
                )
                return out, executed, conv_last

            return overlap_mod.overlapped_superstep_loop(
                planes_in, rnd_in, done_in, round_end,
                exchange=exchange, compute=compute,
                psum_metric=lambda m: lax.psum(m, NODE_AXIS),
                target=target,
            )

        def cond(c):
            _, rnd, done = c
            return jnp.logical_and(~done, rnd < round_end)

        def body(c):
            planes, rnd, _ = c
            ext_state = exchange(planes)
            keys = round_keys(base, rnd, CR)
            out, executed, u = chunk_fn(ext_state, keys, row0, rnd, round_end)
            if pushsum and global_term:
                def run_capped(cap):
                    return chunk_fn(ext_state, keys, row0, rnd, cap)[0]

                return global_verdict_step(
                    run_capped, out, executed, u, rnd, rows_loc, n,
                    NODE_AXIS,
                )
            conv_last = lax.dynamic_index_in_dim(
                u, jnp.maximum(executed - 1, 0), keepdims=False
            )
            total = lax.psum(conv_last, NODE_AXIS)
            return (out, rnd + executed, total >= target)

        return lax.while_loop(cond, body, (planes_in, rnd_in, done_in))

    plane_specs = tuple(P(NODE_AXIS, None) for _ in planes0)
    # Donation (models/pipeline.py): output planes alias the input's
    # buffers; off when retired state must stay readable.
    donate = on_chunk is None and not cfg.stall_chunks
    chunk_sharded = jax.jit(
        compat.shard_map(
            chunk_local,
            mesh=mesh,
            in_specs=(plane_specs, P(), P(), P(), P()),
            out_specs=(plane_specs, P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    def rep_put(x):
        return jax.device_put(x, repl)

    kd_dev = rep_put(np.asarray(key_data_host))
    rnd0 = rep_put(np.int32(start_round))
    done0_dev = rep_put(np.bool_(done0))

    def to_canonical(planes):
        flats = [p.reshape(-1)[:n] for p in planes]
        if pushsum:
            return pushsum_mod.PushSumState(
                s=flats[0], w=flats[1], term=flats[2], conv=flats[3] != 0
            )
        return gossip_mod.GossipState(
            count=flats[0], active=flats[1] != 0, conv=flats[2] != 0
        )

    if probe is not None:
        return probe(chunk_sharded, (
            planes0, rnd0, done0_dev,
            rep_put(np.int32(min(start_round + CR, cfg.max_rounds))),
            kd_dev,
        ))

    t0 = time.perf_counter()
    warm = chunk_sharded(
        tuple(jnp.copy(p) for p in planes0) if donate else planes0,
        rnd0, done0_dev,
        rep_put(np.int32(min(start_round + CR, cfg.max_rounds))),
        kd_dev,
    )
    int(warm[1])
    del warm
    compile_s = time.perf_counter() - t0

    from ..models import pipeline as pipeline_mod
    from ..models.runner import StallWatchdog, _cancel_fn, _progress_gap

    watchdog = StallWatchdog(cfg.stall_chunks)

    def dispatch(planes, rnd, done, round_end):
        return chunk_sharded(
            planes, rnd, done, rep_put(np.int32(round_end)), kd_dev
        )

    on_retire = None
    if on_chunk is not None:
        def on_retire(rounds, planes):
            on_chunk(rounds, to_canonical(planes))

    should_stop = None
    if cfg.stall_chunks:
        # This engine rejects failure models (plan gate): legacy gap. The
        # conv plane is unpacked here (packing is the single-device pool2
        # tier's trick), so the plane sum IS the conv count.
        def should_stop(rounds, planes):
            return watchdog.no_progress(
                _progress_gap(None, cfg.quorum, target, planes[-1], rounds)
            )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=planes0, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds,
        stride=CR * 8, depth=cfg.pipeline_chunks, donate=donate,
        on_retire=on_retire, should_stop=should_stop,
        should_cancel=_cancel_fn(deadline),
    )
    run_s = time.perf_counter() - t1

    return _finalize_result(
        topo, cfg, to_canonical(loop.state), loop.rounds, target,
        compile_s, run_s, done=loop.done, stalled=watchdog.stalled,
        cancelled=loop.cancelled,
    )
