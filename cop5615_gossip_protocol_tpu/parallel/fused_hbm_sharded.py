"""HBM-streaming stencil x sharded: lattice scale PAST VMEM, across chips.

parallel/fused_sharded.py composes the VMEM-resident fused engines with node
sharding, which caps the PER-SHARD population at the VMEM plane budget
(~2^21 pool / ~1.2M stencil slots). One chip alone streams 2^27 nodes
through HBM (ops/fused_stencil_hbm.py) — so sharding used to SHRINK the
reachable population instead of multiplying it (VERDICT r4 missing #1).
This module runs the HBM-streaming stencil engine inside the same
halo-amortized shard_map skeleton, with the r5 ONE-SWEEP round body
(ROADMAP item 3 — until ISSUE 9 this composition still ran the OLD
delivery-plane architecture: a p1 sweep writing halved-send + marked
planes to HBM, then a p2 sweep reading them back):

- each device holds its shard of the global [R_glob, 128] padded node
  layout plus an H-row halo per side, ALL IN HBM (that is the point);
- the round is ONE tile sweep with NO delivery planes at all — state lives
  in two HBM plane sets (ping/pong parities, allocated as kernel outputs);
  the windowed planes (push-sum s/w, gossip active) carry mirrored margins
  so delivery windows read the RAW current-parity state directly; the
  halve commutes into the inbox (exact power-of-two scaling — the
  fused_pool_sharded lemma), and the sampled displacement is REGENERATED
  inside the window consumer at GLOBAL positions (threefry is
  position-wise, the direction pairs arithmetic), so the marked plane
  never exists in memory. Every class's window NEED is clustered with its
  neighbors exactly like the single-device engine (_shard_delivery_plan):
  over the extended ring ALL of a torus's classes — both mod-n blend
  variants included, since signed(-d) and signed(n-d) are both within the
  halo width — typically collapse to ONE fetched window and ONE regen per
  tile. HBM traffic per node per round drops from ~5 plane r/w + 3C
  delivery windows to ~4 plane r/w + ~2 raw windows;
- blend classes read both variants' plans out of the (shared) group
  window and select elementwise at global flat >= d — exactly the chunked
  mod-n blend, with no runtime straddle predicates left: window geometry
  is static per tile, only the regen's global-row map carries row0;
- halo regions are recomputed redundantly and stay valid for exactly CR
  rounds: delivery is exact in slot space, so contamination from the
  buffer edges advances at most w slots per round and H >= ceil(CR*w/128)
  + 1 rows keeps the middle shard exact — unchanged by the one-sweep port;
- the halo wire itself is IN-KERNEL on TPU (cfg.halo_dma, default auto):
  at super-step entry each device pushes its H-row mid boundary slices
  straight into its ring neighbors' parity-A planes with
  `pltpu.make_async_remote_copy` — zero XLA collectives on the halo path —
  and round 0 of the super-step runs its tiles INTERIOR-FIRST
  (_visit_order: tiles whose window reads cannot touch halo or mirror
  rows stream while the neighbor DMA is in flight; the recv-semaphore
  wait lands immediately before the first boundary tile). CPU/interpret
  backends keep the PR 5 batched-ppermute wire behind the capability
  check (parallel/halo.resolve_halo_transport) — both transports feed the
  kernels identical halo bytes, so trajectories are bitwise
  transport-invariant, and benchmarks/comm_audit.py pins the mechanism
  (in-kernel-dma vs xla-ppermute) from the traced programs;
- under the overlap schedule (parallel/overlap.py) the super-steps are
  double-buffered and the termination psum for super-step k reduces under
  super-step k+1's kernel (one-super-step verdict lag; `rounds` stays
  exact). With in-kernel DMA the schedule hands the HALO SLOT to the
  kernel: the XLA-side exchange is the identity and the kernel owns the
  wire — the "documented next step" of the ISSUE 5 tile-order note, done;
- convergence composes at super-step boundaries exactly as before: local
  termination psums the last round's middle-region converged count;
  termination='global' psums per-round middle unstable counts and reruns
  the chunk capped at the verdict round (parallel/fused_sharded.py).

The aggregate population ceiling is therefore n_dev * (single-chip HBM
budget): 8 x 2^27 = 2^30 nodes on the BASELINE.json v4-8 shape.
Trajectories match the chunked sharded path bit-for-bit for integer state
(gossip) and up to compiler reassociation for push-sum
(tests/test_fused_hbm_sharded.py; tests_tpu/ on hardware).

Reference mapping: C15's recast of the reference's whole runtime — the
lattice hot loop (program.fs:89-105, 110-143) over Imp3D-family wirings
(program.fs:295-306), actor-per-node on one machine's threads capped at
~2000 nodes (program.fs:23, report.pdf p.3 §4) — at a billion nodes
across a mesh.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import SimConfig
from ..ops.fused import clamp_cap_and_pad
from ..ops.fused_pool import LANES, build_pool_layout
from ..ops.fused_pool2 import _copy_all
from ..ops.fused_stencil_hbm import (
    _HBM_KINDS,
    _centered_sq,
    _group_window_starts,
    _lattice_params,
    _plan_from_needs,
    _regen_marked_plane,
    _window_counted,
    _window_vals,
)
from ..ops.topology import Topology, stencil_offsets
from ..utils import compat
from ..analysis.wire_specs import C, Regions, WireSpec
from .fused_sharded import _signed_pad

_PT_CANDIDATES = (2048, 1024, 512, 256)
# Per-device HBM for the kernel's resident planes (state parities +
# margins). The v5e chip has 16 GB; leave room for the XLA-side extended
# inputs and collective buffers.
_HBM_PLANE_BUDGET = 12 * 2**30
_VMEM_SCRATCH_BUDGET = 80 * 2**20


def _class_sigmas(topo: Topology, layout):
    """Per class d: (d, sigma1, sigma2) signed in-buffer sender offsets —
    the ONE home for the wrap/non-wrap case analysis that the delivery
    plan (_shard_delivery_plan) and the halo-sufficiency width
    (_halo_width_slots) derive from, so the two can never drift. sigma1
    serves receivers at global flat >= d, sigma2 those below (the
    fused_sharded mod-n blend pair); sigma2 is None when one window is
    exact for every receiver: non-wrap lattices (boundary live-masks kill
    every would-be wrapping sender — the
    ops/fused_stencil_hbm._signed_pad_shift argument) and wrap lattices at
    Z = 0 (both variants coincide)."""
    offsets = [int(d) for d in stencil_offsets(topo)]
    _, wrap = _lattice_params(topo)
    n_pad = layout.n_pad
    N = layout.n
    out = []
    for d in offsets:
        if wrap:
            s1 = _signed_pad(-d, n_pad)
            s2 = _signed_pad(N - d, n_pad)
            out.append((d, s1, None if s1 == s2 else s2))
        else:
            out.append((d, -(d if d <= N // 2 else d - N), None))
    return out


def _halo_width_slots(topo: Topology, layout) -> int:
    """Largest |in-buffer shift| any delivery window uses — the per-round
    contamination advance from the extended buffer's edges."""
    return max(
        max(abs(s1), abs(s2 if s2 is not None else 0))
        for _, s1, s2 in _class_sigmas(topo, layout)
    )


def _shard_delivery_plan(topo: Topology, layout, rows_ext: int, PT: int):
    """Static one-sweep delivery plan over the halo-extended ring — the
    ops/fused_stencil_hbm._delivery_plan architecture re-based from the
    global padded ring to this shard's rows_ext-row extended buffer.

    Every class variant is one window NEED: the forward in-buffer roll
    e = (-sigma) mod n_ext from _class_sigmas (a forward roll by e
    delivers out[j] = in[j - e]). Blend classes contribute BOTH variants
    unconditionally — signed(-d) and signed(n-d) are both within the halo
    width, so unlike the single-device engine's Z-displaced clusters the
    two variants land rows apart and (typically) inside the SAME group
    window; no per-tile liveness predicates are needed, and window
    geometry is fully static per tile. Needs whose centered row shifts lie
    within one processing tile share one fetched window and one regen.

    Returns (classes, groups, M, blend):
      classes[ci] = (d_c, ((group_idx, e, sq, take1), ...)) — one or two
        reads; ``take1`` marks the gflat >= d side of the blend (None for
        single-need classes; the second read is always the wrap side);
      groups[gi]  = (sq_hi, m_rows, None) — window start r0 - sq_hi - 1
        and margin rows, in the (sq_hi, m, live) shape
        _group_window_starts consumes (liveness always None here);
      M           = max margin rows any window can read past rows_ext;
      blend       = whether any class carries the two-variant pair.
    """
    n_ext = rows_ext * LANES
    sigmas = _class_sigmas(topo, layout)
    blend = any(s2 is not None for _, _, s2 in sigmas)

    def sq_of(e):
        return _centered_sq(e, rows_ext)

    needs = []  # (ci, d, e, sq, take1)
    for ci, (d, s1, s2) in enumerate(sigmas):
        e1 = (-s1) % n_ext
        if s2 is None:
            needs.append((ci, d, e1, sq_of(e1), None))
        else:
            needs.append((ci, d, e1, sq_of(e1), True))
            needs.append((ci, d, (-s2) % n_ext, sq_of((-s2) % n_ext), False))

    classes, groups, M = _plan_from_needs(
        needs, [d for d, _s1, _s2 in sigmas], PT, with_liveness=False
    )
    return classes, groups, M, blend


def _boundary_split(H: int, PT: int, T: int, S: int) -> tuple[int, int]:
    """(b_lo, b_hi): how many leading/trailing tiles of the extended
    buffer can read halo rows [0, H) / [rows_ext - H, rows_ext) or the
    mirror margin (whose contents replicate rows [0, M) — halo included),
    through their own-state tile or any delivery window. ``S`` is the
    plan's largest |window row shift| (max |sq| over every class variant);
    the slack terms cover the -1 centering, 8-alignment, and the off+1
    row of the window read. Conservative by construction (a spare
    boundary tile costs overlap, never correctness); in-kernel halo DMA
    streams the [b_lo, T - b_hi) interior tiles while the neighbor copies
    are in flight and waits immediately before the first boundary tile."""
    b_lo = min(T, max(1, -(-(H + S + 16) // PT)))
    b_hi = min(T - b_lo, max(1 if T > b_lo else 0, -(-(H + S + 24) // PT)))
    return b_lo, b_hi


def _visit_order(T: int, b_lo: int, b_hi: int) -> list[int]:
    """Interior-first tile permutation: [b_lo, T - b_hi) stream first
    (their reads cannot touch halo or mirror rows), then the b_lo leading
    and b_hi trailing boundary tiles. A permutation of range(T); per-tile
    work is independent (each tile reads the immutable current parity and
    writes its own next-parity rows, and the round metric is an integer
    sum), so any visit order is bitwise-neutral — pinned by
    tests/test_hbm_inkernel_halo.py."""
    return (
        list(range(b_lo, T - b_hi))
        + list(range(b_lo))
        + list(range(T - b_hi, T))
    )


def _visit_tile(u, T: int, b_lo: int, b_hi: int):
    """Traced form of _visit_order: the tile index visited at loop step
    ``u``."""
    n_int = T - b_lo - b_hi
    v = u - jnp.int32(n_int)
    return jnp.where(
        u < n_int,
        u + jnp.int32(b_lo),
        jnp.where(v < b_lo, v, jnp.int32(T - b_hi - b_lo) + v),
    )


def plan_stencil_hbm_sharded(topo: Topology, cfg: SimConfig, n_dev: int):
    """(H, rows_loc, CR, PT, layout) or a string reason why not.

    Mirrors plan_fused_sharded's gates; the budgets differ: state lives in
    HBM, so the population check is the per-device HBM plane budget (the
    single-chip tier's 2^27-class ceiling, times the mesh), and VMEM only
    bounds the PT-row streaming scratch. The plan is deliberately
    invariant to BOTH scheduling knobs (overlap_collectives, halo_dma):
    the overlapped schedule's extended-ring carry is budgeted
    unconditionally, so geometry (H, CR, PT) can never differ across a
    knob — a budget-edge population picking a smaller CR on one schedule
    would break super-step-granular `rounds` interchangeability and the
    resume contracts for a few spare rows of headroom."""
    if topo.implicit:
        return (
            "implicit (full) topology has no displacement structure for "
            "the halo composition; use delivery='pool' (the fused pool x "
            "sharded composition)"
        )
    if topo.kind in ("imp2d", "imp3d"):
        # Not "no displacement columns" — the imp kinds HAVE a full
        # lattice; their random long-range edge is what this composition
        # cannot halo. The imp x HBM x sharded composition serves them
        # under pooled long-range sampling (the runner routes
        # delivery='pool' there before consulting this plan).
        return (
            f"topology {topo.kind!r} carries a random long-range edge the "
            "halo composition cannot serve; use delivery='pool' (the "
            "imp x HBM x sharded composition, "
            "parallel/fused_imp_hbm_sharded.py)"
        )
    if topo.kind not in _HBM_KINDS:
        return (
            f"topology {topo.kind!r} has no arithmetic displacement "
            f"columns (served kinds: {', '.join(_HBM_KINDS)})"
        )
    offsets = stencil_offsets(topo)
    if offsets is None:
        return f"topology {topo.kind!r} has no small displacement set"
    if cfg.dtype != "float32":
        return "fused engine supports float32 only"
    if not jax.config.jax_threefry_partitionable:
        return "requires jax_threefry_partitionable=True"
    if cfg.telemetry:
        return (
            "telemetry counters run in the single-device fused kernels and "
            "the chunked/sharded XLA engines; this composition does not "
            "carry the counter block"
        )
    if cfg.step_timing and cfg.overlap_collectives:
        return (
            "step_timing under the overlapped super-step schedule would "
            "force the deferred termination psum to drain at every timed "
            "boundary (a host sync inside the overlap window); use "
            "overlap_collectives=False or step_timing=False"
        )
    if cfg.faulted:
        # No failure-model support in this engine yet — rejecting on
        # the aggregate flag (not just fault_rate) keeps a crash/dup/
        # delay config from silently running unfaulted here. The
        # stencil (ops/fused.py) and pool tiers (ops/fused_pool.py,
        # ops/fused_pool2.py) run drop+crash in-kernel.
        return "failure models not supported in this fused kernel"
    if cfg.delivery == "scatter":
        return (
            "the fused kernel delivers via the stencil formulation only; "
            "delivery='scatter' would be silently ignored"
        )
    layout = build_pool_layout(topo.n)
    R = layout.rows
    if R % n_dev != 0:
        return (
            f"padded layout ({R} rows) must split evenly; {n_dev} devices "
            "do not divide it"
        )
    rows_loc = R // n_dev
    w = _halo_width_slots(topo, layout)
    pushsum = cfg.algorithm == "push-sum"
    n_state = 4 if pushsum else 3
    CR0 = max(1, min(int(cfg.chunk_rounds), 64))

    def fit(cr):
        h_min = -(-(cr * w) // LANES) + 1
        cands = []
        for pt in _PT_CANDIDATES:
            r = (-rows_loc) % pt
            if r % 2:
                continue  # 2H cannot hit an odd residue mod an even PT
            h = h_min + ((r // 2 - h_min) % (pt // 2))
            rows_ext = rows_loc + 2 * h
            if rows_ext // pt < 2 or h > rows_loc:
                continue
            _cls, grp, m_max, _bl = _shard_delivery_plan(
                topo, layout, rows_ext, pt
            )
            sum_m = sum(m for _, m, _l in grp)
            # Streaming scratch: own-state tiles + one window set per
            # group (raw value planes + the regen mark plane).
            vmem = (
                (4 if pushsum else 3) * pt
                + sum_m * (3 if pushsum else 2)
            ) * LANES * 4
            if vmem > _VMEM_SCRATCH_BUDGET:
                continue
            # Resident planes: two margined parities per windowed plane,
            # two plain parities per i32 plane, the XLA-side extended
            # inputs, and the overlap schedule's double-buffer carry
            # (budgeted unconditionally — see the docstring).
            carry_rows = n_state * (rows_ext + rows_loc)
            hbm = (
                (4 if pushsum else 2) * (rows_ext + m_max)
                + 4 * rows_ext
                + n_state * rows_ext
                + carry_rows
            ) * LANES * 4
            if hbm > _HBM_PLANE_BUDGET:
                continue
            cands.append((rows_ext, pt, h))
        if not cands:
            return None
        # Largest PT whose halo waste stays within ~12% of the leanest
        # candidate: fewer, larger DMA volleys per round beat a few percent
        # of redundant halo rows.
        lean = min(c[0] for c in cands)
        ok = [c for c in cands if c[0] <= lean + max(lean // 8, 1)]
        return max(ok, key=lambda c: c[1])

    CR = CR0
    while CR > 1 and fit(CR) is None:
        CR //= 2
    b = fit(CR)
    if b is None:
        return (
            f"no processing-tile split fits: per-round halo ({w} slots) at "
            f"a {rows_loc}-row shard exceeds the shard, the VMEM streaming "
            "scratch, or the per-device HBM plane budget even at "
            "chunk_rounds=1; use the chunked collective engine"
        )
    _, PT, H = b
    return (H, rows_loc, CR, PT, layout)


def _halo_rdmas(mid_ins, planesA, H: int, rows_loc: int, ssems, rsems,
                left, right):
    """The in-kernel halo wire: per state plane, one async remote copy of
    my LAST H mid rows into the right neighbor's left-halo rows [0, H) and
    one of my FIRST H mid rows into the left neighbor's right-halo rows
    [H + rows_loc, rows_ext) — exactly the bytes
    parallel/halo.exchange_rows_batched ships per plane, with no XLA
    collective. SPMD-symmetric slots: my send on slot i and my neighbor's
    send INTO me on slot i share semaphores, so ``.wait()`` on each
    descriptor drains both the outbound send and the inbound receive. A
    pure function of its refs — the start site and the wait site recreate
    identical descriptor lists."""
    cps = []
    for p, (src, dst) in enumerate(zip(mid_ins, planesA)):
        cps.append(pltpu.make_async_remote_copy(
            src_ref=src.at[pl.ds(rows_loc - H, H), :],
            dst_ref=dst.at[pl.ds(0, H), :],
            send_sem=ssems.at[2 * p], recv_sem=rsems.at[2 * p],
            device_id=(right,),
            device_id_type=pltpu.DeviceIdType.MESH,
        ))
        cps.append(pltpu.make_async_remote_copy(
            src_ref=src.at[pl.ds(0, H), :],
            dst_ref=dst.at[pl.ds(H + rows_loc, H), :],
            send_sem=ssems.at[2 * p + 1], recv_sem=rsems.at[2 * p + 1],
            device_id=(left,),
            device_id_type=pltpu.DeviceIdType.MESH,
        ))
    return cps


def _neighbor_barrier(left, right):
    """Block until both ring neighbors have entered this kernel: a remote
    DMA writes straight into the neighbor's output planes, so the write
    must not land before the neighbor's invocation owns those buffers.
    Uses the global barrier semaphore (collective_id in the compiler
    params)."""
    bar = pltpu.get_barrier_semaphore()
    for nb in (left, right):
        pltpu.semaphore_signal(
            bar, inc=1, device_id=(nb,),
            device_id_type=pltpu.DeviceIdType.MESH,
        )
    pltpu.semaphore_wait(bar, 2)


def make_pushsum_stencil_hbm_shard_chunk(
    topo: Topology, cfg: SimConfig, H: int, rows_loc: int, PT: int,
    layout, *, dma: bool = False, interpret: bool = False
):
    """Per-device one-sweep chunk kernel: ``chunk_fn(state, keys, row0,
    dev, start, cap) -> (mid_state4, executed, u)`` runs up to
    K = keys.shape[0] push-sum rounds on one device's planes, HBM-streamed
    with the delivery-plane-free round body. ``state`` is the
    halo-EXTENDED planes (rows_ext) under the XLA wire, or the MID planes
    (rows_loc) under in-kernel DMA (``dma=True`` — the kernel performs the
    halo exchange itself at super-step entry, interior-first). ``row0`` is
    the extended buffer's first GLOBAL row (pre-wrapped); ``u[k]`` is
    round k's middle-region metric — unstable valid lanes under
    termination='global', converged count otherwise; -1 on rounds not
    executed."""
    R_glob = layout.rows
    N = layout.n
    rows_ext = rows_loc + 2 * H
    T = rows_ext // PT
    n_dev = R_glob // rows_loc
    classes, groups, M, _blend = _shard_delivery_plan(
        topo, layout, rows_ext, PT
    )
    G = len(groups)
    mt = -(-M // PT)  # mirror tiles replicating rows [0, M)
    dirs_builder, wrap = _lattice_params(topo)
    S = max(
        abs(sq) for _d, reads in classes for _gi, _e, sq, _t1 in reads
    )
    b_lo, b_hi = _boundary_split(H, PT, T, S)
    n_int = T - b_lo - b_hi
    delta = np.float32(cfg.resolved_delta)
    term_rounds = np.int32(cfg.term_rounds)
    global_term = cfg.termination == "global"
    in_rows = rows_loc if dma else rows_ext

    def kernel(*refs):
        (scal_ref, keys_ref, s_in, w_in, t_in, c_in,
         sA, wA, tA, cA, sB, wB, tB, cB, meta_o, u_o) = refs[:16]
        scratch = refs[16:]
        win_s = scratch[0:G]
        win_w = scratch[G:2 * G]
        mk = scratch[2 * G:3 * G]
        (scr_s, scr_w, scr_t, scr_c, flags, sems, wsems) = scratch[
            3 * G:3 * G + 7
        ]
        dma_sems = scratch[3 * G + 7:]
        k = pl.program_id(0)
        K = pl.num_programs(0)
        row_l = lax.broadcasted_iota(jnp.int32, (PT, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (PT, LANES), 1)
        row0 = scal_ref[0]
        dev = scal_ref[3]
        if dma:
            ssems, rsems = dma_sems
            left = lax.rem(dev + jnp.int32(n_dev - 1), jnp.int32(n_dev))
            right = lax.rem(dev + jnp.int32(1), jnp.int32(n_dev))

        def tile_globals(r0):
            grow = lax.rem(row0 + r0 + row_l, jnp.int32(R_glob))
            gflat = grow * LANES + lane
            return grow, gflat

        def rdmas():
            return _halo_rdmas(
                (s_in, w_in, t_in, c_in), (sA, wA, tA, cA),
                H, rows_loc, ssems, rsems, left, right,
            )

        def drain_halo():
            """Wait the neighbor copies, then mirror parity A's first M
            rows (left halo included — hence after the wait) into the
            window margin."""
            for cp in rdmas():
                cp.wait()
            _copy_all([
                (sA.at[pl.ds(0, M), :], sA.at[pl.ds(rows_ext, M), :]),
                (wA.at[pl.ds(0, M), :], wA.at[pl.ds(rows_ext, M), :]),
            ], sems)

        @pl.when(k == 0)
        def _init():
            if dma:
                # Hand the halo slot to the kernel: barrier with the ring
                # neighbors, push my boundary slices into their parity-A
                # halos, and land my own mid rows — the halo recv drains
                # under round 0's interior tiles (drain_halo at the first
                # boundary tile).
                _neighbor_barrier(left, right)
                for cp in rdmas():
                    cp.start()
                _copy_all([
                    (s_in, sA.at[pl.ds(H, rows_loc), :]),
                    (w_in, wA.at[pl.ds(H, rows_loc), :]),
                    (t_in, tA.at[pl.ds(H, rows_loc), :]),
                    (c_in, cA.at[pl.ds(H, rows_loc), :]),
                ], sems)
            else:
                def cp(t, _):
                    r0 = t * PT
                    _copy_all([
                        (s_in.at[pl.ds(r0, PT), :], scr_s),
                        (w_in.at[pl.ds(r0, PT), :], scr_w),
                        (t_in.at[pl.ds(r0, PT), :], scr_t),
                        (c_in.at[pl.ds(r0, PT), :], scr_c),
                    ], sems)
                    _copy_all([
                        (scr_s, sA.at[pl.ds(r0, PT), :]),
                        (scr_w, wA.at[pl.ds(r0, PT), :]),
                        (scr_t, tA.at[pl.ds(r0, PT), :]),
                        (scr_c, cA.at[pl.ds(r0, PT), :]),
                    ], sems)
                    for i in range(mt):
                        rows_i = min(PT, M - i * PT)

                        @pl.when(t == i)
                        def _m(i=i, rows_i=rows_i):
                            _copy_all([
                                (scr_s.at[pl.ds(0, rows_i), :],
                                 sA.at[pl.ds(rows_ext + i * PT, rows_i), :]),
                                (scr_w.at[pl.ds(0, rows_i), :],
                                 wA.at[pl.ds(rows_ext + i * PT, rows_i), :]),
                            ], sems)
                    return 0

                lax.fori_loop(0, T, cp, 0, unroll=False)
            flags[0] = jnp.int32(0)  # rounds executed

        u_o[k] = jnp.int32(-1)
        active = scal_ref[1] + k < scal_ref[2]

        if dma:
            # A zero-round chunk (overshoot dispatch past termination)
            # still started the neighbor copies — drain them so the kernel
            # never exits with an in-flight DMA.
            @pl.when((k == 0) & ~active)
            def _drain_idle():
                drain_halo()

        def round_body(cur, nxt):
            (s_c, w_c, t_c, c_c) = cur
            (s_n, w_n, t_n, c_n) = nxt
            kk = k % 8
            k1 = keys_ref[kk, 0]
            k2 = keys_ref[kk, 1]

            def tile(t, acc):
                r0 = t * PT
                _copy_all([
                    (s_c.at[pl.ds(r0, PT), :], scr_s),
                    (w_c.at[pl.ds(r0, PT), :], scr_w),
                    (t_c.at[pl.ds(r0, PT), :], scr_t),
                    (c_c.at[pl.ds(r0, PT), :], scr_c),
                ], sems)
                starts = _group_window_starts(groups, r0, rows_ext)
                cps = []
                for gi, (_ws8u, dma0, _live) in enumerate(starts):
                    m = groups[gi][1]
                    for j, (pln, wref) in enumerate(
                        [(s_c, win_s[gi]), (w_c, win_w[gi])]
                    ):
                        cp = pltpu.make_async_copy(
                            pln.at[pl.ds(dma0, m), :], wref,
                            wsems.at[2 * gi + j],
                        )
                        cp.start()
                        cps.append(cp)
                # Regenerate each group's marked plane (the sender draws at
                # the window's mirror-wrapped rows, re-based to GLOBAL
                # positions) while the raw windows stream.
                for gi, (ws8u, _dma0, _live) in enumerate(starts):
                    _regen_marked_plane(
                        mk[gi], groups[gi][1], ws8u, k1, k2, R_glob, N,
                        dirs_builder, wrap, ring_rows=rows_ext, row0=row0,
                    )
                for cp in cps:
                    cp.wait()
                _, gflat = tile_globals(r0)
                padm = gflat >= N
                mid = (row_l + r0 >= H) & (row_l + r0 < H + rows_loc)
                inbox_s = jnp.zeros((PT, LANES), jnp.float32)
                inbox_w = jnp.zeros((PT, LANES), jnp.float32)
                # Accumulate in sorted-offsets order — the chunked path's
                # association tree; groups only choose the buffer. Blend
                # classes read both variants and select elementwise at
                # global flat >= d (the mod-n blend).
                for d_c, reads in classes:
                    cs = cw = None
                    for gi, e, sq, _take1 in reads:
                        ws8u = starts[gi][0]
                        off = jnp.asarray(
                            r0 - sq - 1 + 2 * rows_ext, jnp.int32
                        ) - ws8u
                        rl = e % LANES
                        vs = _window_vals(
                            win_s[gi], mk[gi], off, PT, rl, d_c, lane,
                            interpret,
                        )
                        vw = _window_vals(
                            win_w[gi], mk[gi], off, PT, rl, d_c, lane,
                            interpret,
                        )
                        if cs is None:
                            cs, cw = vs, vw
                        else:
                            # second read is always the wrap (take1=False)
                            # side: select it below d_c.
                            cs = jnp.where(gflat >= d_c, cs, vs)
                            cw = jnp.where(gflat >= d_c, cw, vw)
                    inbox_s = inbox_s + cs
                    inbox_w = inbox_w + cw
                # Halve AFTER the masked sums — bitwise the pre-halved-send
                # delivery (exact power-of-two scaling commutes with every
                # rounding in the sum).
                half = jnp.float32(0.5)
                inbox_s = jnp.where(padm, 0.0, inbox_s * half)
                inbox_w = jnp.where(padm, 0.0, inbox_w * half)
                s_t = scr_s[:]
                w_t = scr_w[:]
                s_send = jnp.where(padm, 0.0, s_t * half)
                w_send = jnp.where(padm, 0.0, w_t * half)
                s_new = (s_t - s_send) + inbox_s
                w_new = (w_t - w_send) + inbox_w
                if global_term:
                    # Global residual: term/conv stream through unchanged
                    # (the XLA side latches conv after the psum'd verdict);
                    # the metric counts MIDDLE unstable valid lanes.
                    ratio_old = s_t / w_t
                    tol = delta * jnp.maximum(
                        jnp.abs(ratio_old), jnp.float32(1)
                    )
                    unstable = (
                        jnp.abs(s_new / w_new - ratio_old) > tol
                    ) & ~padm & mid
                    term_new = scr_t[:]
                    conv_new = scr_c[:]
                    tile_metric = jnp.sum(
                        unstable.astype(jnp.int32), dtype=jnp.int32
                    )
                else:
                    received = inbox_w > 0
                    stable = jnp.abs(s_new / w_new - s_t / w_t) <= delta
                    term_new = jnp.where(
                        received,
                        jnp.where(stable, scr_t[:] + 1, jnp.int32(0)),
                        scr_t[:],
                    )
                    conv_new = jnp.where(
                        padm,
                        jnp.int32(0),
                        jnp.where(
                            (scr_c[:] != 0) | (term_new >= term_rounds),
                            jnp.int32(1),
                            jnp.int32(0),
                        ),
                    )
                    tile_metric = jnp.sum(
                        jnp.where(mid, conv_new, jnp.int32(0)),
                        dtype=jnp.int32,
                    )
                scr_s[:] = s_new
                scr_w[:] = w_new
                scr_t[:] = term_new
                scr_c[:] = conv_new
                _copy_all([
                    (scr_s, s_n.at[pl.ds(r0, PT), :]),
                    (scr_w, w_n.at[pl.ds(r0, PT), :]),
                    (scr_t, t_n.at[pl.ds(r0, PT), :]),
                    (scr_c, c_n.at[pl.ds(r0, PT), :]),
                ], sems)
                # Margin mirrors for the NEXT round's windows: rows
                # [rows_ext, rows_ext + M) replicate [0, M).
                for i in range(mt):
                    rows_i = min(PT, M - i * PT)

                    @pl.when(t == i)
                    def _m(i=i, rows_i=rows_i):
                        _copy_all([
                            (scr_s.at[pl.ds(0, rows_i), :],
                             s_n.at[pl.ds(rows_ext + i * PT, rows_i), :]),
                            (scr_w.at[pl.ds(0, rows_i), :],
                             w_n.at[pl.ds(rows_ext + i * PT, rows_i), :]),
                        ], sems)
                return acc + tile_metric

            def step(u, acc):
                if dma:
                    # Interior-first: boundary tiles run last, behind the
                    # halo drain (a per-tile-independent reordering —
                    # bitwise-neutral, the metric is an integer sum).
                    t = _visit_tile(u, T, b_lo, b_hi)

                    @pl.when((k == 0) & (u == n_int))
                    def _wait_halo():
                        drain_halo()
                else:
                    t = u
                return tile(t, acc)

            total = lax.fori_loop(0, T, step, jnp.int32(0), unroll=False)
            flags[0] = flags[0] + 1
            u_o[k] = total

        A = (sA, wA, tA, cA)
        B = (sB, wB, tB, cB)
        par = flags[0] % 2  # snapshot before the mutating branches

        @pl.when(active & (par == 0))
        def _round_even():
            round_body(A, B)

        @pl.when(active & (par == 1))
        def _round_odd():
            round_body(B, A)

        @pl.when(k == K - 1)
        def _emit():
            meta_o[0] = flags[0]
            meta_o[1] = flags[0] % 2

    def chunk_fn(state, keys, row0, dev, start, cap):
        s, w, t, c = state
        cap, keys = clamp_cap_and_pad(start, cap, keys)
        K = keys.shape[0]
        f32m = jax.ShapeDtypeStruct((rows_ext + M, LANES), jnp.float32)
        i32 = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.int32)
        scratch = (
            [pltpu.VMEM((m, LANES), jnp.float32) for _, m, _l in groups]
            + [pltpu.VMEM((m, LANES), jnp.float32) for _, m, _l in groups]
            + [pltpu.VMEM((m, LANES), jnp.int32) for _, m, _l in groups]
            + [
                pltpu.VMEM((PT, LANES), jnp.float32),
                pltpu.VMEM((PT, LANES), jnp.float32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
                pltpu.SemaphoreType.DMA((4,)),
                pltpu.SemaphoreType.DMA((2 * G,)),
            ]
        )
        params = dict(vmem_limit_bytes=96 * 1024 * 1024)
        if dma:
            scratch += [
                pltpu.SemaphoreType.DMA((8,)),
                pltpu.SemaphoreType.DMA((8,)),
            ]
            params["collective_id"] = 0
        outs = pl.pallas_call(
            kernel,
            grid=(K,),
            out_shape=(
                f32m, f32m, i32, i32,
                f32m, f32m, i32, i32,
                jax.ShapeDtypeStruct((2,), jnp.int32),
                jax.ShapeDtypeStruct((K,), jnp.int32),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((8, 2), lambda k: (k // 8, 0),
                             memory_space=pltpu.SMEM),
            ] + [pl.BlockSpec(memory_space=pl.ANY)] * 4,
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * 8
                + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
            ),
            scratch_shapes=scratch,
            compiler_params=compat.pallas_tpu_compiler_params(**params),
            interpret=interpret,
        )(
            jnp.stack([jnp.int32(row0), jnp.int32(start), jnp.int32(cap),
                       jnp.int32(dev)]),
            keys,
            s, w, t, c,
        )
        meta = outs[8]
        parity = meta[1]

        def sel(a, b):
            return jnp.where(
                parity == 0, a[H:H + rows_loc], b[H:H + rows_loc]
            )

        mid_state = tuple(sel(outs[i], outs[4 + i]) for i in range(4))
        return mid_state, meta[0], outs[9]

    return chunk_fn, in_rows


def make_gossip_stencil_hbm_shard_chunk(
    topo: Topology, cfg: SimConfig, H: int, rows_loc: int, PT: int,
    layout, *, dma: bool = False, interpret: bool = False
):
    """Gossip analog of the one-sweep port: windows read the raw ACTIVE
    plane and the regenerated marked plane gates per-class counting
    (ops/fused_stencil_hbm._window_counted); receiver-side suppression on
    the streamed conv tile; ``u[k]`` is round k's middle-region converged
    count (-1 when not executed)."""
    R_glob = layout.rows
    N = layout.n
    rows_ext = rows_loc + 2 * H
    T = rows_ext // PT
    n_dev = R_glob // rows_loc
    classes, groups, M, _blend = _shard_delivery_plan(
        topo, layout, rows_ext, PT
    )
    G = len(groups)
    mt = -(-M // PT)
    dirs_builder, wrap = _lattice_params(topo)
    S = max(
        abs(sq) for _d, reads in classes for _gi, _e, sq, _t1 in reads
    )
    b_lo, b_hi = _boundary_split(H, PT, T, S)
    n_int = T - b_lo - b_hi
    rumor_target = np.int32(cfg.resolved_rumor_target)
    suppress = cfg.resolved_suppress
    in_rows = rows_loc if dma else rows_ext

    def kernel(*refs):
        (scal_ref, keys_ref, n_in, a_in, c_in,
         nA, aA, cA, nB, aB, cB, meta_o, u_o) = refs[:13]
        scratch = refs[13:]
        win_a = scratch[0:G]
        mk = scratch[G:2 * G]
        (scr_n, scr_a, scr_c, flags, sems, wsems) = scratch[2 * G:2 * G + 6]
        dma_sems = scratch[2 * G + 6:]
        k = pl.program_id(0)
        K = pl.num_programs(0)
        row_l = lax.broadcasted_iota(jnp.int32, (PT, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (PT, LANES), 1)
        row0 = scal_ref[0]
        dev = scal_ref[3]
        if dma:
            ssems, rsems = dma_sems
            left = lax.rem(dev + jnp.int32(n_dev - 1), jnp.int32(n_dev))
            right = lax.rem(dev + jnp.int32(1), jnp.int32(n_dev))

        def tile_globals(r0):
            grow = lax.rem(row0 + r0 + row_l, jnp.int32(R_glob))
            gflat = grow * LANES + lane
            return grow, gflat

        def rdmas():
            return _halo_rdmas(
                (n_in, a_in, c_in), (nA, aA, cA),
                H, rows_loc, ssems, rsems, left, right,
            )

        def drain_halo():
            for cp in rdmas():
                cp.wait()
            _copy_all([
                (aA.at[pl.ds(0, M), :], aA.at[pl.ds(rows_ext, M), :]),
            ], sems)

        @pl.when(k == 0)
        def _init():
            if dma:
                _neighbor_barrier(left, right)
                for cp in rdmas():
                    cp.start()
                _copy_all([
                    (n_in, nA.at[pl.ds(H, rows_loc), :]),
                    (a_in, aA.at[pl.ds(H, rows_loc), :]),
                    (c_in, cA.at[pl.ds(H, rows_loc), :]),
                ], sems)
            else:
                def cp(t, _):
                    r0 = t * PT
                    _copy_all([
                        (n_in.at[pl.ds(r0, PT), :], scr_n),
                        (a_in.at[pl.ds(r0, PT), :], scr_a),
                        (c_in.at[pl.ds(r0, PT), :], scr_c),
                    ], sems)
                    _copy_all([
                        (scr_n, nA.at[pl.ds(r0, PT), :]),
                        (scr_a, aA.at[pl.ds(r0, PT), :]),
                        (scr_c, cA.at[pl.ds(r0, PT), :]),
                    ], sems)
                    for i in range(mt):
                        rows_i = min(PT, M - i * PT)

                        @pl.when(t == i)
                        def _m(i=i, rows_i=rows_i):
                            _copy_all([
                                (scr_a.at[pl.ds(0, rows_i), :],
                                 aA.at[pl.ds(rows_ext + i * PT, rows_i), :]),
                            ], sems)
                    return 0

                lax.fori_loop(0, T, cp, 0, unroll=False)
            flags[0] = jnp.int32(0)

        u_o[k] = jnp.int32(-1)
        active = scal_ref[1] + k < scal_ref[2]

        if dma:
            @pl.when((k == 0) & ~active)
            def _drain_idle():
                drain_halo()

        def round_body(cur, nxt):
            (n_c, a_c, c_c) = cur
            (n_n, a_n, c_n) = nxt
            kk = k % 8
            k1 = keys_ref[kk, 0]
            k2 = keys_ref[kk, 1]

            def tile(t, acc):
                r0 = t * PT
                _copy_all([
                    (n_c.at[pl.ds(r0, PT), :], scr_n),
                    (a_c.at[pl.ds(r0, PT), :], scr_a),
                    (c_c.at[pl.ds(r0, PT), :], scr_c),
                ], sems)
                starts = _group_window_starts(groups, r0, rows_ext)
                cps = []
                for gi, (_ws8u, dma0, _live) in enumerate(starts):
                    m = groups[gi][1]
                    cp = pltpu.make_async_copy(
                        a_c.at[pl.ds(dma0, m), :], win_a[gi],
                        wsems.at[gi],
                    )
                    cp.start()
                    cps.append(cp)
                for gi, (ws8u, _dma0, _live) in enumerate(starts):
                    _regen_marked_plane(
                        mk[gi], groups[gi][1], ws8u, k1, k2, R_glob, N,
                        dirs_builder, wrap, ring_rows=rows_ext, row0=row0,
                    )
                for cp in cps:
                    cp.wait()
                _, gflat = tile_globals(r0)
                padm = gflat >= N
                mid = (row_l + r0 >= H) & (row_l + r0 < H + rows_loc)
                inbox = jnp.zeros((PT, LANES), jnp.int32)
                for d_c, reads in classes:
                    g = None
                    for gi, e, sq, _take1 in reads:
                        ws8u = starts[gi][0]
                        off = jnp.asarray(
                            r0 - sq - 1 + 2 * rows_ext, jnp.int32
                        ) - ws8u
                        rl = e % LANES
                        v = _window_counted(
                            win_a[gi], mk[gi], off, PT, rl, d_c, lane,
                            interpret,
                        )
                        if g is None:
                            g = v
                        else:
                            # second read is the wrap (take1=False) side.
                            g = jnp.where(gflat >= d_c, g, v)
                    inbox = inbox + g
                inbox = jnp.where(padm, jnp.int32(0), inbox)
                if suppress:
                    inbox = jnp.where(scr_c[:] != 0, jnp.int32(0), inbox)
                count_new = scr_n[:] + inbox
                active_new = jnp.where(
                    (scr_a[:] != 0) | (inbox > 0), jnp.int32(1),
                    jnp.int32(0),
                )
                conv_new = jnp.where(
                    (count_new >= rumor_target) & ~padm,
                    jnp.int32(1), jnp.int32(0),
                )
                scr_n[:] = count_new
                scr_a[:] = active_new
                scr_c[:] = conv_new
                _copy_all([
                    (scr_n, n_n.at[pl.ds(r0, PT), :]),
                    (scr_a, a_n.at[pl.ds(r0, PT), :]),
                    (scr_c, c_n.at[pl.ds(r0, PT), :]),
                ], sems)
                for i in range(mt):
                    rows_i = min(PT, M - i * PT)

                    @pl.when(t == i)
                    def _m(i=i, rows_i=rows_i):
                        _copy_all([
                            (scr_a.at[pl.ds(0, rows_i), :],
                             a_n.at[pl.ds(rows_ext + i * PT, rows_i), :]),
                        ], sems)
                return acc + jnp.sum(
                    jnp.where(mid, conv_new, jnp.int32(0)), dtype=jnp.int32
                )

            def step(u, acc):
                if dma:
                    t = _visit_tile(u, T, b_lo, b_hi)

                    @pl.when((k == 0) & (u == n_int))
                    def _wait_halo():
                        drain_halo()
                else:
                    t = u
                return tile(t, acc)

            total = lax.fori_loop(0, T, step, jnp.int32(0), unroll=False)
            flags[0] = flags[0] + 1
            u_o[k] = total

        A = (nA, aA, cA)
        B = (nB, aB, cB)
        par = flags[0] % 2

        @pl.when(active & (par == 0))
        def _round_even():
            round_body(A, B)

        @pl.when(active & (par == 1))
        def _round_odd():
            round_body(B, A)

        @pl.when(k == K - 1)
        def _emit():
            meta_o[0] = flags[0]
            meta_o[1] = flags[0] % 2

    def chunk_fn(state, keys, row0, dev, start, cap):
        cnt, act, cv = state
        cap, keys = clamp_cap_and_pad(start, cap, keys)
        K = keys.shape[0]
        i32 = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.int32)
        i32m = jax.ShapeDtypeStruct((rows_ext + M, LANES), jnp.int32)
        scratch = (
            [pltpu.VMEM((m, LANES), jnp.int32) for _, m, _l in groups]
            + [pltpu.VMEM((m, LANES), jnp.int32) for _, m, _l in groups]
            + [
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
                pltpu.SemaphoreType.DMA((3,)),
                pltpu.SemaphoreType.DMA((G,)),
            ]
        )
        params = dict(vmem_limit_bytes=96 * 1024 * 1024)
        if dma:
            scratch += [
                pltpu.SemaphoreType.DMA((6,)),
                pltpu.SemaphoreType.DMA((6,)),
            ]
            params["collective_id"] = 0
        outs = pl.pallas_call(
            kernel,
            grid=(K,),
            out_shape=(
                i32, i32m, i32, i32, i32m, i32,
                jax.ShapeDtypeStruct((2,), jnp.int32),
                jax.ShapeDtypeStruct((K,), jnp.int32),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((8, 2), lambda k: (k // 8, 0),
                             memory_space=pltpu.SMEM),
            ] + [pl.BlockSpec(memory_space=pl.ANY)] * 3,
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * 6
                + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
            ),
            scratch_shapes=scratch,
            compiler_params=compat.pallas_tpu_compiler_params(**params),
            interpret=interpret,
        )(
            jnp.stack([jnp.int32(row0), jnp.int32(start), jnp.int32(cap),
                       jnp.int32(dev)]),
            keys,
            cnt, act, cv,
        )
        meta = outs[6]
        parity = meta[1]

        def sel(a, b):
            return jnp.where(
                parity == 0, a[H:H + rows_loc], b[H:H + rows_loc]
            )

        mid_state = tuple(sel(outs[i], outs[3 + i]) for i in range(3))
        return mid_state, meta[0], outs[7]

    return chunk_fn, in_rows


def run_stencil_hbm_sharded(
    topo: Topology,
    cfg: SimConfig,
    mesh=None,
    key=None,
    on_chunk=None,
    start_state=None,
    start_round: int = 0,
    probe=None,
    deadline=None,
):
    """Sharded HBM-streaming run — engine='fused', n_devices > 1, lattices
    past the VMEM composition's per-shard budget.

    Same contract as parallel/fused_sharded.run_fused_sharded for local
    termination (detection at super-step granularity, exact at
    chunk_rounds=1). termination='global' stops at the EXACT verdict round:
    the kernel reports per-round middle unstable counts, the psum'd vector
    names the first globally-stable round, and a capped rerun of the same
    chunk (same keys — deterministic) lands the state there, matching the
    chunked sharded global path's stop round and state.

    cfg.overlap_collectives (default on) runs the overlapped super-step
    schedule (parallel/overlap.py): batched single-pair halo wires,
    double-buffered ring, the termination psum folded under the next
    super-step's kernel. Off = the serial schedule; both are
    bitwise-identical (pure scheduling). termination='global' keeps the
    serial loop (its verdict can demand a capped chunk rerun) but still
    rides the batched wires.

    cfg.halo_dma (default auto) selects the halo TRANSPORT
    (parallel/halo.resolve_halo_transport): on TPU the exchange moves
    INTO the kernel as async-remote-copy neighbor DMA and the XLA-side
    exchange degenerates to the identity (zero XLA collectives on the
    halo path — benchmarks/comm_audit.py pins it); CPU/interpret backends
    keep the batched-ppermute wire. Bitwise transport-invariant.

    Fresh starts build their state planes HOST-SHARDED (ISSUE 15,
    parallel/mesh.put_rows — each process materializes only its own
    devices' rows; tests/test_hostmem.py pins no global-N intermediate),
    and a SPEC-ONLY topology (build_topology rows=(0, 0)) suffices: the
    composition reads the analytic displacement classes, never a
    neighbor row. The mesh may span OS processes
    (parallel/mesh.initialize_distributed): placement goes through the
    process-safe parallel/mesh.put_global path, and under a
    multi-process mesh the VMEM composition's plan refuses so the
    dispatch routes HERE at any population
    (tests/test_multiprocess.py pins the gloo runs bitwise).

    ``probe(chunk_sharded, args)``, when given, receives the jitted chunk
    program and example arguments and its return value replaces the run
    (benchmarks/comm_audit.py's trace hook — no execution happens)."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import gossip as gossip_mod
    from ..models import pushsum as pushsum_mod
    from ..models.runner import _check_dtype, _finalize_result, draw_leader
    from ..ops import sampling
    from ..ops.fused import round_keys
    from . import halo as halo_mod
    from . import mesh as mesh_mod
    from . import overlap as overlap_mod
    from .fused_sharded import global_verdict_step
    from .mesh import NODE_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh(cfg.n_devices)
    n_dev = mesh.devices.size
    plan = plan_stencil_hbm_sharded(topo, cfg, n_dev)
    if isinstance(plan, str):
        raise ValueError(
            f"engine='fused' with n_devices={n_dev} unavailable: {plan}"
        )
    H, rows_loc, CR, PT, layout = plan
    _check_dtype(cfg)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    backend = jax.default_backend()
    transport = halo_mod.resolve_halo_transport(cfg, backend)
    dma = transport == "dma"
    # The remote-copy kernel never runs under the Pallas interpreter (no
    # inter-device DMA engine there): on TPU it compiles, elsewhere it can
    # only be TRACED (the comm-audit probe) — execution is gated below.
    interpret = backend != "tpu" and not dma
    pushsum = cfg.algorithm == "push-sum"
    global_term = cfg.termination == "global"
    make = (
        make_pushsum_stencil_hbm_shard_chunk
        if pushsum
        else make_gossip_stencil_hbm_shard_chunk
    )
    chunk_fn, _in_rows = make(
        topo, cfg, H, rows_loc, PT, layout, dma=dma, interpret=interpret
    )
    R_glob = layout.rows
    n = topo.n
    target = cfg.resolved_target_count(n, topo.target_count)
    key_data_host, key_impl = sampling.key_split(key)

    shard_rows = NamedSharding(mesh, P(NODE_AXIS, None))
    repl = NamedSharding(mesh, P())

    plane_fields = (
        [("s", np.float32, 0.0), ("w", np.float32, 1.0),
         ("term", np.int32, cfg.initial_term_round), ("conv", np.int32, 0)]
        if pushsum
        else [("count", np.int32, 0), ("active", np.int32, 0),
              ("conv", np.int32, 0)]
    )

    def to_planes(state):
        outs = []
        for f, dt, fill in plane_fields:
            x = np.asarray(getattr(state, f)).astype(dt)
            full = np.full(layout.n_pad, fill, dtype=dt)
            full[: x.shape[0]] = x
            outs.append(full.reshape(R_glob, LANES))
        return tuple(outs)

    def fresh_planes_sharded():
        """Host-SHARDED fresh-start planes (ISSUE 15): every plane is a
        pure function of the global row index (push-sum s_i = i, w = 1,
        term = initial; gossip all-zero but the drawn leader), so each
        process materializes ONLY its own devices' rows through
        mesh.put_rows — no canonical state and no global-N host array on
        the build path (tests/test_hostmem.py pins it). Values are
        exactly to_planes(init_state(...))'s, bitwise."""
        shp = (R_glob, LANES)
        flat_ids = mesh_mod.flat_id_rows(LANES)

        def const_rows(value, dt):
            return mesh_mod.const_row_builder(value, dt, LANES)

        if pushsum:
            term0 = cfg.initial_term_round

            def s_rows(lo, hi):
                ids = flat_ids(lo, hi)
                return np.where(ids < n, ids, 0).astype(np.float32)

            builders = (
                (np.float32, s_rows),
                (np.float32, const_rows(1.0, np.float32)),
                (np.int32, const_rows(term0, np.int32)),
                (np.int32, const_rows(0, np.int32)),
            )
        else:
            leader = int(draw_leader(key, topo, cfg))

            def act_rows(lo, hi):
                return (flat_ids(lo, hi) == leader).astype(np.int32)

            builders = (
                (np.int32, const_rows(0, np.int32)),
                (np.int32, act_rows),
                (np.int32, const_rows(0, np.int32)),
            )
        return tuple(
            mesh_mod.put_rows(shard_rows, shp, dt, fn)
            for dt, fn in builders
        )

    if start_state is None:
        planes0 = fresh_planes_sharded()
        done0 = bool(0 >= target)  # fresh conv plane is all-false
    else:
        st0 = jax.tree.map(np.asarray, start_state)
        planes0 = tuple(
            mesh_mod.put_global(p, shard_rows) for p in to_planes(st0)
        )
        done0 = bool(np.asarray(st0.conv).sum() >= target)

    perm_fwd = [(d, (d + 1) % n_dev) for d in range(n_dev)]
    perm_bwd = [(d, (d - 1) % n_dev) for d in range(n_dev)]
    overlap = cfg.overlap_collectives

    def exchange(planes):
        """Halo-extend the mid planes — or, under in-kernel DMA, hand the
        halo slot to the kernel: the exchange is the identity and the
        kernel performs the neighbor copies itself (zero XLA collectives
        on the halo path). The XLA wire is the batched single-pair volley
        (parallel/halo.py) under the overlap schedule, one pair per plane
        on the serial one — identical received bytes all three ways."""
        if dma:
            return planes
        if overlap:
            return halo_mod.exchange_rows_batched(
                planes, H, NODE_AXIS, n_dev
            )

        def ext_rows(x):
            left = lax.ppermute(x[-H:], NODE_AXIS, perm_fwd)
            right = lax.ppermute(x[:H], NODE_AXIS, perm_bwd)
            return jnp.concatenate([left, x, right], axis=0)

        return tuple(ext_rows(p) for p in planes)

    def chunk_local(planes_in, rnd_in, done_in, round_end, key_data):
        base = sampling.key_join(key_data, key_impl)
        dev = lax.axis_index(NODE_AXIS)
        row0 = lax.rem(
            dev.astype(jnp.int32) * rows_loc - H + 2 * R_glob,
            jnp.int32(R_glob),
        )

        if overlap and not (pushsum and global_term):
            # Overlapped schedule (parallel/overlap.py): the verdict psum
            # for super-step k reduces under super-step k+1's kernel, the
            # next exchange writes the inactive ring copy right after the
            # kernel, and a fired deferred verdict rolls back to the
            # retired double-buffer copy — rounds stay exact.
            def compute(ext_state, rnd, cap):
                keys = round_keys(base, rnd, CR)
                out, executed, u = chunk_fn(
                    ext_state, keys, row0, dev, rnd, cap
                )
                conv_last = lax.dynamic_index_in_dim(
                    u, jnp.maximum(executed - 1, 0), keepdims=False
                )
                return out, executed, conv_last

            return overlap_mod.overlapped_superstep_loop(
                planes_in, rnd_in, done_in, round_end,
                exchange=exchange, compute=compute,
                psum_metric=lambda m: lax.psum(m, NODE_AXIS),
                target=target,
            )

        def cond(c):
            _, rnd, done = c
            return jnp.logical_and(~done, rnd < round_end)

        def body(c):
            planes, rnd, _ = c
            ext_state = exchange(planes)
            keys = round_keys(base, rnd, CR)
            out, executed, u = chunk_fn(
                ext_state, keys, row0, dev, rnd, round_end
            )
            if pushsum and global_term:
                def run_capped(cap):
                    return chunk_fn(ext_state, keys, row0, dev, rnd, cap)[0]

                return global_verdict_step(
                    run_capped, out, executed, u, rnd, rows_loc, n,
                    NODE_AXIS,
                )
            conv_last = lax.dynamic_index_in_dim(
                u, jnp.maximum(executed - 1, 0), keepdims=False
            )
            total = lax.psum(conv_last, NODE_AXIS)
            return (out, rnd + executed, total >= target)

        return lax.while_loop(cond, body, (planes_in, rnd_in, done_in))

    plane_specs = tuple(P(NODE_AXIS, None) for _ in planes0)
    # Donation (models/pipeline.py): output planes alias the input's
    # buffers; off when retired state must stay readable.
    donate = on_chunk is None and not cfg.stall_chunks
    chunk_sharded = jax.jit(
        compat.shard_map(
            chunk_local,
            mesh=mesh,
            in_specs=(plane_specs, P(), P(), P(), P()),
            out_specs=(plane_specs, P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    def rep_put(x):
        return mesh_mod.put_global(x, repl)

    kd_dev = rep_put(np.asarray(key_data_host))
    rnd0 = rep_put(np.int32(start_round))
    done0_dev = rep_put(np.bool_(done0))

    def to_canonical(planes):
        flats = [p.reshape(-1)[:n] for p in planes]
        if pushsum:
            return pushsum_mod.PushSumState(
                s=flats[0], w=flats[1], term=flats[2], conv=flats[3] != 0
            )
        return gossip_mod.GossipState(
            count=flats[0], active=flats[1] != 0, conv=flats[2] != 0
        )

    if probe is not None:
        return probe(chunk_sharded, (
            planes0, rnd0, done0_dev,
            rep_put(np.int32(min(start_round + CR, cfg.max_rounds))),
            kd_dev,
        ), donate=donate)

    if dma and backend != "tpu":
        raise ValueError(
            "halo_dma='on' builds the in-kernel async-remote-copy halo "
            "program, which only EXECUTES on TPU backends (the Pallas "
            "interpreter has no inter-device DMA); use halo_dma='auto' "
            "for the batched-ppermute wire here, or trace the DMA program "
            "hardware-free through the probe hook (benchmarks/comm_audit)"
        )

    t0 = time.perf_counter()
    warm = chunk_sharded(
        tuple(jnp.copy(p) for p in planes0) if donate else planes0,
        rnd0, done0_dev,
        rep_put(np.int32(min(start_round + CR, cfg.max_rounds))),
        kd_dev,
    )
    int(warm[1])
    del warm
    compile_s = time.perf_counter() - t0

    from ..models import pipeline as pipeline_mod
    from ..models.runner import StallWatchdog, _cancel_fn, _progress_gap

    watchdog = StallWatchdog(cfg.stall_chunks)

    def dispatch(planes, rnd, done, round_end):
        return chunk_sharded(
            planes, rnd, done, rep_put(np.int32(round_end)), kd_dev
        )

    on_retire = None
    if on_chunk is not None:
        def on_retire(rounds, planes):
            on_chunk(rounds, to_canonical(planes))

    should_stop = None
    if cfg.stall_chunks:
        # This engine rejects failure models (plan gate): legacy gap. The
        # conv plane is unpacked here (packing is the single-device pool2
        # tier's trick), so the plane sum IS the conv count.
        def should_stop(rounds, planes):
            return watchdog.no_progress(
                _progress_gap(None, cfg.quorum, target, planes[-1], rounds)
            )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=planes0, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds,
        stride=CR * 8, depth=cfg.pipeline_chunks, donate=donate,
        on_retire=on_retire, should_stop=should_stop,
        should_cancel=_cancel_fn(deadline),
        step_timing=cfg.step_timing,
        hook_error=("raise" if cfg.strict_checkpoint else "continue"),
    )
    run_s = time.perf_counter() - t1

    return _finalize_result(
        topo, cfg, to_canonical(loop.state), loop.rounds, target,
        compile_s, run_s, done=loop.done, stalled=watchdog.stalled,
        cancelled=loop.cancelled,
    )


# --- Declared wire contract (analysis/wire_specs.py) -----------------------
# Per SUPER-STEP on the XLA wire: ONE batched halo ppermute pair (serial:
# a pair per state plane) + the deferred verdict psum; batched setup is
# the pre-loop exchange pair + the drain psum (serial pays neither). With
# halo_dma='on' the halo moves INTO the kernel: one async remote copy per
# plane per ring direction, ZERO XLA collectives on the halo path (the
# psum is the verdict, not delivery), and the remote copies ship exactly
# the bytes the ppermute wire shipped (dma_bytes_match).
WIRE_SPEC = WireSpec(
    engine="hbm-sharded",
    variants={
        ("overlap", "wire"): Regions(
            body={"ppermute": C(fixed=2), "psum": C(fixed=1)},
            setup={"ppermute": C(fixed=2), "psum": C(fixed=1)},
        ),
        ("serial", "wire"): Regions(
            body={"ppermute": C(per_plane=2), "psum": C(fixed=1)},
            setup={},
        ),
        ("overlap", "dma"): Regions(
            body={"remote_dma": C(per_plane=2), "psum": C(fixed=1)},
            setup={"psum": C(fixed=1)},
        ),
        ("serial", "dma"): Regions(
            body={"remote_dma": C(per_plane=2), "psum": C(fixed=1)},
            setup={},
        ),
    },
    mechanism={"wire": "xla-ppermute", "dma": "in-kernel-dma"},
    equal_bytes=("ppermute",),
    dma_bytes_match="ppermute",
)
