"""Node-dimension-sharded round runner (shard_map over a 1-D TPU mesh).

The scaling recast of the reference's only parallelism — actor-per-node
concurrency on one machine's thread pool (SURVEY.md C15), capped at ~2000
nodes (report.pdf p.3 §4). Here each device owns a contiguous shard of the
per-node state vectors; one synchronous round is:

1. every device draws the round's full-length random words (bit-identical
   with the single-device runner — see ops/sampling.py) and slices its shard;
2. local nodes pick global partner indices; delivery is then
   **halo exchange** (offset-structured topologies: per displacement class,
   a local shift plus one `ppermute` of the boundary slice — O(n_loc + halo)
   per device, parallel/halo.py), **pool rolls** (implicit full with
   offset-pool sampling at mesh-divisible populations: K dynamic global
   rolls of log2(n_dev) ppermute stages each, O(n_loc) per device —
   parallel/halo.global_roll_dynamic), or **scatter + psum_scatter**
   (irregular topologies and the non-divisible fallback: scatter into a
   full-length contribution vector, then one reduce-scatter over the
   "nodes" axis hands each device its summed inbox shard);
3. local absorb/update, then a scalar `psum` of converged counts serves as
   the global termination predicate (the ParentActor's count-and-exit,
   program.fs:47-60, as a reduction).

The whole round loop — collectives included — lives inside one jit'd
`lax.while_loop`, so a chunk of thousands of rounds runs with zero host
round-trips. Gossip's converged-target suppression (the shared dictionary
probe, program.fs:92) is applied receiver-side (models/gossip.absorb): a
converged node drops its own inbox, consulting the same round-start conv
vintage a sender-side probe would — identical trajectories with zero
suppression collectives (previously a backward halo roll per offset class
or an all_gather of the converged vector).

Population is padded to a device multiple; padded slots are invalid (never
send, never targeted, never counted). Equivalence with the single-device
runner, by state type and delivery path:

- gossip is bit-identical at ANY device count — integer sums are
  order-free and the random stream is device-count-invariant
  (test_sharded.py pins exact trajectories);
- push-sum over halo or pool-roll delivery preserves the single-device
  per-class accumulation order — round counts match exactly in practice;
- push-sum over scatter + psum_scatter REASSOCIATES partial sums: at
  float32 the ulp differences, amplified by the term-counter reset
  (program.fs:130-133's consecutive-stability test), can shift round
  counts by tens of percent while the converged set and estimate quality
  stay equivalent (measured: n=344 full converges in 174-234 rounds
  across mesh sizes vs 199 single-device, estimate_mae ~8e-6 in every
  case). float64 keeps trajectories aligned — test_sharded.py pins both
  contracts.

The same program spans OS processes: after parallel/mesh.initialize_distributed
(CLI: --coordinator/--num-processes/--process-id) the mesh covers all
processes' devices, host->device transfers go through
`jax.make_array_from_callback` (the shardings are no longer fully
addressable), and the collectives cross the process boundary.
tests/test_multiprocess.py runs two real processes over gloo CPU
collectives: gossip trajectories stay bit-identical to the single-process
mesh (the random stream is process-count-invariant); push-sum round counts
may shift (cross-process reductions reassociate float sums, and the
3-stable-rounds termination test amplifies ulp differences) while
convergence quality is unchanged.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import SimConfig
from ..models import gossip as gossip_mod
from ..models import pushsum as pushsum_mod
from ..models.runner import (
    RunResult,
    StallWatchdog,
    _cancel_fn,
    _check_dtype,
    _finalize_result,
    _freeze_dead,
    _host_done,
    _progress_gap,
    draw_leader,
)
from ..models import pipeline as pipeline_mod
from ..ops import faults as faults_mod
from ..ops import sampling
from ..ops import telemetry as telemetry_mod
from ..ops.topology import Topology, imp_split
from ..utils import compat
from . import halo as halo_mod
from ..analysis.wire_specs import C, Regions, WireSpec
from . import mesh as mesh_mod
from .mesh import NODE_AXIS, make_mesh


def _pad_to(x: np.ndarray, rows: int, fill=0) -> np.ndarray:
    if x.shape[0] == rows:
        return x
    pad = np.full((rows - x.shape[0],) + x.shape[1:], fill, dtype=x.dtype)
    return np.concatenate([x, pad], axis=0)


def run_sharded(
    topo: Topology,
    cfg: SimConfig,
    mesh: Optional[Mesh] = None,
    key: Optional[jax.Array] = None,
    on_chunk: Optional[Callable[[int, object], None]] = None,
    start_state=None,
    start_round: int = 0,
    on_telemetry: Optional[Callable[[int, object], None]] = None,
    probe=None,
    deadline: Optional[float] = None,
) -> RunResult:
    """Sharded analog of models.runner.run — same config, same result.
    ``deadline`` (absolute monotonic seconds) threads the run_chunks
    cancellation hook: a fired deadline ends the run at the next retired
    chunk with outcome="deadline_exceeded" (models/pipeline.py).
    ``start_state`` (unpadded, from utils/checkpoint.py) resumes a run;
    round keys use absolute round indices, so a resumed sharded run follows
    the same stream as the uninterrupted one.

    cfg.overlap_collectives (default on) routes halo delivery through the
    BATCHED wire (parallel/halo.deliver_halo_batched): every offset class's
    boundary slice rides one ppermute pair per round instead of one
    ppermute per class — bitwise-identical delivery, fewer larger wires.
    ``probe(chunk_sharded, args)``, when given, replaces the run with the
    probe's return value (benchmarks/comm_audit.py's trace hook)."""
    if mesh is None:
        mesh = make_mesh(cfg.n_devices)
    n_dev = mesh.devices.size
    dtype = _check_dtype(cfg)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)

    if cfg.dup_rate > 0 or cfg.delay_rounds > 0:
        raise ValueError(
            "dup/delay fault models are single-device chunked-engine "
            "features; sharded runs support the drop gate (--fault-rate) "
            "and crash models"
        )

    n = topo.n
    n_pad = ((n + n_dev - 1) // n_dev) * n_dev
    n_loc = n_pad // n_dev
    target = cfg.resolved_target_count(n, topo.target_count)
    # Churn planes: rebuilt from the config (ops/faults.py), death padded
    # with round 0 so pad slots count as dead (and revival padded with
    # NEVER so they stay dead) — alive-count psums need no extra masking.
    # Closed over — sliced per shard inside the trace.
    life_np = faults_mod.life_planes(cfg, n)
    death_full = (
        None if life_np is None
        else jnp.asarray(faults_mod.pad_death_plane(life_np.death, n_pad))
    )
    revive_full = (
        None if life_np is None or life_np.revive is None
        else jnp.asarray(faults_mod.pad_revival_plane(life_np.revive, n_pad))
    )
    # The base key crosses the jit/shard_map boundary as a replicated runtime
    # ARGUMENT (raw data + static impl, ops/sampling.key_split): closed over,
    # it would bake into the executable as a constant, which the axon
    # platform re-ships on every chunk dispatch (~100 ms/launch).
    key_data_host, key_impl = sampling.key_split(key)
    if n_pad != n and not jax.config.jax_threefry_partitionable:
        # The stream contract (ops/sampling.py: every device draws the same
        # full-length words and slices its shard) holds at padded lengths
        # only under the position-wise partitionable threefry — legacy
        # threefry bits depend on the total draw length, so a padded
        # full-length draw would silently diverge from the single-device
        # stream. Same guard the fused engines apply.
        raise ValueError(
            f"sharded runs at a population ({n}) not divisible by the mesh "
            f"({n_dev} devices) require jax_threefry_partitionable=True; "
            "enable it or pick a divisible population"
        )

    shard = NamedSharding(mesh, P(NODE_AXIS))
    repl = NamedSharding(mesh, P())

    # Delivery plan: halo exchange (local shifts + boundary ppermutes —
    # O(n_loc + halo) per device) for offset-structured topologies, else
    # scatter into a full-length contrib vector + psum_scatter (O(n_pad)).
    plan = None
    if cfg.delivery in ("auto", "stencil") and not topo.implicit:
        plan = halo_mod.plan_halo(topo, n_dev)
    # Offset-pool delivery on the implicit full topology (the flagship
    # benchmark path): when the population divides the mesh exactly, the
    # K per-round displacement rolls run as dynamic global rolls —
    # log2(n_dev) ppermute stages each, O(n_loc) per-device memory
    # (parallel/halo.global_roll_dynamic) — instead of scattering into a
    # full-length vector and psum_scattering it. Non-divisible populations
    # fall back to the scatter path: pad slots inside the ring would
    # corrupt the roll.
    pool_roll = topo.implicit and cfg.delivery == "pool" and n_pad == n
    # Sharded imp-pool: lattice classes deliver by halo rolls, the pooled
    # long-range slot by K dynamic global rolls — both existing sharded
    # primitives; accumulation order (sorted lattice classes, then pool
    # slots) matches the single-device deliver_imp_pool exactly.
    imp_plan = imp_split_t = None
    if cfg.delivery == "pool" and not topo.implicit:
        if cfg.reference:
            raise ValueError(
                "delivery='pool' on imp topologies cannot reproduce the "
                "reference's static extra edge (Q9); use batched semantics"
            )
        split = imp_split(topo)
        imp_plan = None if split is None else halo_mod.plan_imp_halo(
            split, n, n_dev
        )
        if imp_plan is None:
            raise ValueError(
                f"sharded imp pooled delivery needs an exact lattice halo "
                f"plan for {topo.kind!r} at n={n} on {n_dev} devices "
                "(lattice halo must fit a shard); use fewer devices or "
                "delivery='scatter'"
            )
        if n_pad != n:
            # The pool rolls require an unpadded ring (same constraint as
            # the full-topology pool-roll path).
            raise ValueError(
                f"sharded imp pooled delivery requires the population "
                f"({n}) to divide the mesh ({n_dev} devices); pad slots "
                "inside the ring would corrupt the dynamic pool rolls"
            )
        imp_split_t = split
    if cfg.delivery == "stencil" and plan is None:
        raise ValueError(
            "delivery='stencil' under sharding requires an offset-structured "
            "topology whose halo fits a shard (line/ring/grid2d/ref2d/"
            "grid3d/torus3d; wrap-edge topologies additionally need the "
            f"population to divide the mesh) — {topo.kind!r} at n={n} on "
            f"{n_dev} devices has no exact halo plan; use delivery='auto'"
        )

    def dev_put(host_array, sharding=shard):
        """Host -> global device array, process-safe — the one placement
        path shared by every sharded composition (parallel/mesh.put_global)."""
        return mesh_mod.put_global(host_array, sharding)

    valid = dev_put(np.arange(n_pad) < n)
    if topo.implicit or imp_plan is not None:
        # The imp-pool path ships its own displacement/degree planes below;
        # transferring the full neighbor table too would be the exact
        # transient-HBM spike dev_put exists to avoid.
        topo_args = (valid,)
        topo_specs = (P(NODE_AXIS),)
    else:
        neighbors = _pad_to(topo.neighbors, n_pad)
        degree = _pad_to(topo.degree, n_pad)
        topo_args = (dev_put(neighbors), dev_put(degree), valid)
        topo_specs = (P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS))

    # --- local round bodies (operate on [n_loc] shards) -------------------

    def _death_loc(start):
        """This shard's slice of the crash plane (crash model only)."""
        return lax.dynamic_slice(death_full, (start,), (n_loc,))

    def _revive_loc(start):
        """This shard's slice of the revival plane, None sans recovery."""
        if revive_full is None:
            return None
        return lax.dynamic_slice(revive_full, (start,), (n_loc,))

    def _life_loc(start):
        """This shard's churn planes as a LifePlanes of local slices —
        feeds the shared freeze/predicate helpers (models/runner.py)."""
        return faults_mod.LifePlanes(
            death=_death_loc(start), revive=_revive_loc(start)
        )

    def _alive_loc(start, round_idx):
        return faults_mod.alive_at(
            _death_loc(start), round_idx, _revive_loc(start)
        )

    def _gate_crash(send_ok, start, round_idx):
        """Dead nodes never send (ops/faults.py); revived nodes resume;
        no-op sans crash model."""
        if death_full is None:
            return send_ok
        return send_ok & _alive_loc(start, round_idx)

    def targets_and_gate(round_idx, key_data, *targs):
        kr = sampling.round_key(sampling.key_join(key_data, key_impl), round_idx)
        # Full-length draws on every device, then slice: keeps the stream
        # identical to the single-device runner and independent of n_dev.
        dev = lax.axis_index(NODE_AXIS)
        start = dev * n_loc
        gids = start + jnp.arange(n_loc, dtype=jnp.int32)
        if topo.implicit:
            (valid_loc,) = targs
            if cfg.delivery == "pool":
                # Scatter fallback for pool sampling at non-divisible
                # populations: same (choice, offsets, send_ok) stream as the
                # pool-roll path — pool_parts is the single source of that
                # stream — materialized into explicit targets.
                choice, offs, send_ok = pool_parts(round_idx, key_data, valid_loc)
                targets = sampling.targets_pool(choice, offs, gids, n)
                return targets, send_ok, valid_loc, gids
            bits_full = sampling.uniform_bits(kr, n_pad)
            bits = lax.dynamic_slice(bits_full, (start,), (n_loc,))
            targets = sampling.targets_full(bits, gids, n)
            send_ok = valid_loc
        else:
            bits_full = sampling.uniform_bits(kr, n_pad)
            bits = lax.dynamic_slice(bits_full, (start,), (n_loc,))
            neighbors_loc, degree_loc, valid_loc = targs
            targets = sampling.targets_explicit(bits, neighbors_loc, degree_loc)
            send_ok = (degree_loc > 0) & valid_loc
        gate_full = sampling.send_gate(kr, n_pad, cfg.fault_rate)
        if gate_full is not True:
            send_ok = send_ok & lax.dynamic_slice(gate_full, (start,), (n_loc,))
        send_ok = _gate_crash(send_ok, start, round_idx)
        return targets, send_ok, valid_loc, gids

    def pool_parts(round_idx, key_data, valid_loc):
        """(choice, offsets, send_ok) shards — the single source of the pool
        sampling stream for BOTH sharded pool paths (roll delivery and the
        non-divisible scatter fallback), matching the single-device pool
        runner (models/runner.py _make_pool_round_fn): shared per-round
        offsets off the replicated round key, packed choice words sliced
        per shard."""
        kr = sampling.round_key(sampling.key_join(key_data, key_impl), round_idx)
        dev = lax.axis_index(NODE_AXIS)
        start = dev * n_loc
        offs = sampling.pool_offsets(kr, cfg.pool_size, n)
        choice_full = sampling.pool_choice_packed(
            kr, n, cfg.pool_size, out_len=n_pad
        )
        choice = lax.dynamic_slice(choice_full, (start,), (n_loc,))
        send_ok = valid_loc
        gate_full = sampling.send_gate(kr, n_pad, cfg.fault_rate)
        if gate_full is not True:
            send_ok = send_ok & lax.dynamic_slice(gate_full, (start,), (n_loc,))
        send_ok = _gate_crash(send_ok, start, round_idx)
        return choice, offs, send_ok

    if plan is not None:

        def deliver_sharded(values, targets, gids):
            """Halo delivery: per offset class, a local shift plus one
            ppermute of the boundary slice (parallel/halo.py). ``values``
            may be [..., n_loc] (stacked channels share the ppermutes).
            Same static accumulation order as the single-device stencil
            path — sharded trajectories stay bit-identical. Batched wires
            (one ppermute pair for all classes) under the default overlap
            schedule; per-class wires with --overlap-collectives off."""
            disp = jnp.remainder(targets - gids, n)
            return halo_mod.deliver_halo(
                values, disp, plan, NODE_AXIS,
                batched=cfg.overlap_collectives,
            )

    else:

        def deliver_sharded(values, targets, gids):
            """Scatter into a full-length contribution vector, then
            reduce-scatter so each device receives its own summed inbox
            shard. ``values`` may be [..., n_loc]: stacked channels share
            one scatter pass and one collective (as the halo and pool
            delivery paths already do)."""
            contrib = jnp.zeros(values.shape[:-1] + (n_pad,), values.dtype)
            contrib = contrib.at[..., targets].add(values)
            return lax.psum_scatter(
                contrib, NODE_AXIS, scatter_dimension=contrib.ndim - 1,
                tiled=True,
            )

    def imp_parts(round_idx, key_data, disp_loc, deg_loc, valid_loc):
        """Sharded mirror of models/runner.imp_pool_parts: full-length
        draws sliced per shard (stream identical to single-device)."""
        kr = sampling.round_key(sampling.key_join(key_data, key_impl), round_idx)
        dev = lax.axis_index(NODE_AXIS)
        start = dev * n_loc
        bits_full = sampling.uniform_bits(kr, n_pad)
        bits = lax.dynamic_slice(bits_full, (start,), (n_loc,))
        d = sampling.targets_explicit(bits, disp_loc, deg_loc)
        is_extra = (d == -1) & (deg_loc > 0)
        offs = sampling.pool_offsets(kr, cfg.pool_size, n)
        choice_full = sampling.pool_choice_packed(
            sampling.imp_choice_key(kr), n, cfg.pool_size, out_len=n_pad
        )
        choice = lax.dynamic_slice(choice_full, (start,), (n_loc,))
        send_ok = (deg_loc > 0) & valid_loc
        gate_full = sampling.send_gate(kr, n_pad, cfg.fault_rate)
        if gate_full is not True:
            send_ok = send_ok & lax.dynamic_slice(gate_full, (start,), (n_loc,))
        send_ok = _gate_crash(send_ok, start, round_idx)
        return d, is_extra, choice, offs, send_ok

    def deliver_imp_sharded(channels, d, is_extra, choice, offs):
        zero = jnp.zeros((), channels.dtype)
        lat = jnp.where(is_extra[None, :], zero, channels)
        inbox = halo_mod.deliver_halo(
            lat, d, imp_plan, NODE_AXIS, batched=cfg.overlap_collectives
        )
        choice_eff = jnp.where(is_extra, choice, jnp.int32(-1))
        ext = jnp.where(is_extra[None, :], channels, zero)
        # Pool rolls accumulate INTO the lattice inbox (not into a separate
        # accumulator later added on): the single-device deliver_imp_pool is
        # one left-fold over lattice-then-pool classes, and a different
        # association tree shifts f32 sums by an ulp — enough to drift the
        # term counter's round counts (the r2 reassociation lesson).
        for k in range(offs.shape[0]):
            masked = jnp.where(choice_eff == k, ext, zero)
            inbox = inbox + halo_mod.global_roll_dynamic(
                masked, offs[k], NODE_AXIS, n_dev
            )
        return inbox

    if imp_plan is not None:
        disp_dev = dev_put(_pad_to(imp_split_t.disp_cols, n_pad, -1))
        deg_dev = dev_put(_pad_to(imp_split_t.degree, n_pad))
        topo_args = (disp_dev, deg_dev, valid)
        topo_specs = (P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS))

    if cfg.algorithm == "push-sum":
        delta = cfg.resolved_delta
        term_rounds = cfg.term_rounds

        if imp_plan is not None:

            def round_fn(state, round_idx, key_data, *targs):
                disp_loc, deg_loc, valid_loc = targs
                d, is_extra, choice, offs, send_ok = imp_parts(
                    round_idx, key_data, disp_loc, deg_loc, valid_loc
                )
                s_send, w_send, s_keep, w_keep = pushsum_mod.halve_and_send(
                    state.s, state.w, send_ok
                )
                inbox = deliver_imp_sharded(
                    jnp.stack([s_send, w_send]), d, is_extra, choice, offs
                )
                return pushsum_mod.absorb(
                    state, s_keep, w_keep, inbox[0], inbox[1], delta,
                    term_rounds, cfg.termination == "global",
                    valid=valid_loc,
                )

        elif pool_roll:

            def round_fn(state, round_idx, key_data, *targs):
                (valid_loc,) = targs
                choice, offs, send_ok = pool_parts(round_idx, key_data, valid_loc)
                s_send, w_send, s_keep, w_keep = pushsum_mod.halve_and_send(
                    state.s, state.w, send_ok
                )
                # s and w stacked: both channels ride each roll's ppermutes.
                inbox = halo_mod.deliver_pool_sharded(
                    jnp.stack([s_send, w_send]), choice, offs, NODE_AXIS, n_dev
                )
                return pushsum_mod.absorb(
                    state, s_keep, w_keep, inbox[0], inbox[1], delta, term_rounds,
                    cfg.termination == "global", valid=valid_loc,
                )

        else:

            def round_fn(state, round_idx, key_data, *targs):
                targets, send_ok, valid_loc, gids = targets_and_gate(
                    round_idx, key_data, *targs
                )
                s_send, w_send, s_keep, w_keep = pushsum_mod.halve_and_send(
                    state.s, state.w, send_ok
                )
                # Stack s/w so both channels share the delivery's
                # collectives (one ppermute set per offset class on the halo
                # path; one scatter + reduce-scatter on the fallback).
                inbox = deliver_sharded(
                    jnp.stack([s_send, w_send]), targets, gids
                )
                inbox_s, inbox_w = inbox[0], inbox[1]
                return pushsum_mod.absorb(
                    state, s_keep, w_keep, inbox_s, inbox_w, delta, term_rounds,
                    cfg.termination == "global", valid=valid_loc,
                )

        s0 = np.arange(n_pad, dtype=dtype)
        s0[n:] = 0.0  # padded slots carry no sum mass...
        # ...but weight 1 (not 0) so their never-updated ratio is 0/1, not a
        # NaN that would trip jax_debug_nans; they never send, so the extra
        # weight is inert and excluded from all real-node accounting.
        state0 = pushsum_mod.PushSumState(
            s=dev_put(s0),
            w=dev_put(np.ones(n_pad, dtype=dtype)),
            term=dev_put(np.full(n_pad, cfg.initial_term_round, np.int32)),
            conv=dev_put(np.zeros(n_pad, bool)),
        )
    else:
        rumor_target = cfg.resolved_rumor_target
        suppress = cfg.resolved_suppress
        leader = int(draw_leader(key, topo, cfg))
        count0 = np.zeros(n_pad, np.int32)
        active0 = np.zeros(n_pad, bool)
        active0[leader] = True
        if cfg.reference and topo.kind == "full":
            count0[leader] = 1  # C13: full kicks off with CallChildActor
        state0 = gossip_mod.GossipState(
            count=dev_put(count0), active=dev_put(active0), conv=dev_put(np.zeros(n_pad, bool))
        )

        if imp_plan is not None:

            def round_fn(state, round_idx, key_data, *targs):
                d, is_extra, choice, offs, send_ok = imp_parts(
                    round_idx, key_data, *targs
                )
                vals = gossip_mod.send_values(state, send_ok)
                inbox = deliver_imp_sharded(
                    vals[None].astype(jnp.int32), d, is_extra, choice, offs
                )[0]
                return gossip_mod.absorb(state, inbox, rumor_target, suppress)

        elif pool_roll:

            def round_fn(state, round_idx, key_data, *targs):
                (valid_loc,) = targs
                choice, offs, send_ok = pool_parts(round_idx, key_data, valid_loc)
                vals = gossip_mod.send_values(state, send_ok)
                inbox = halo_mod.deliver_pool_sharded(
                    vals[None], choice, offs, NODE_AXIS, n_dev
                )[0]
                # Receiver-side suppression: purely local, no collective.
                return gossip_mod.absorb(state, inbox, rumor_target, suppress)

        else:

            def round_fn(state, round_idx, key_data, *targs):
                targets, send_ok, _, gids = targets_and_gate(
                    round_idx, key_data, *targs
                )
                vals = gossip_mod.send_values(state, send_ok)
                inbox = deliver_sharded(vals, targets, gids)
                return gossip_mod.absorb(state, inbox, rumor_target, suppress)

    if death_full is not None:
        # Crash semantics around the base round: a revival-round reset at
        # body entry (the sharded mirror of runner.make_revive_fn — gossip
        # rejoins susceptible; push-sum resets only under rejoin='fresh')
        # and the dead-node freeze after (runner._freeze_dead — push-sum
        # mass still parks in s/w). Elementwise on local shards, so the
        # trajectory matches the single-device engine exactly.
        base_round_fn = round_fn
        pushsum = cfg.algorithm == "push-sum"
        fresh_rejoin = cfg.rejoin == "fresh"
        init_term = cfg.initial_term_round

        def _rejoin_loc(state, round_idx, start):
            revive_loc = _revive_loc(start)
            if revive_loc is None:
                return state
            if pushsum and not fresh_rejoin:
                return state
            rn = faults_mod.revived_at(revive_loc, round_idx)
            if pushsum:
                gids = start + jnp.arange(n_loc, dtype=jnp.int32)
                return pushsum_mod.PushSumState(
                    s=jnp.where(rn, gids.astype(state.s.dtype), state.s),
                    w=jnp.where(rn, jnp.zeros((), state.w.dtype), state.w),
                    term=jnp.where(rn, jnp.int32(init_term), state.term),
                    conv=jnp.where(rn, False, state.conv),
                )
            return gossip_mod.GossipState(
                count=jnp.where(rn, jnp.int32(0), state.count),
                active=jnp.where(rn, False, state.active),
                conv=jnp.where(rn, False, state.conv),
            )

        def round_fn(state, round_idx, key_data, *targs):  # noqa: F811
            start = lax.axis_index(NODE_AXIS) * n_loc
            state = _rejoin_loc(state, round_idx, start)
            new = base_round_fn(state, round_idx, key_data, *targs)
            return _freeze_dead(_life_loc(start), state, new, round_idx)

    done0 = False
    if start_state is not None:
        fills = {"s": 0.0, "w": 1.0, "term": cfg.initial_term_round,
                 "conv": False, "count": 0, "active": False}
        state0 = type(state0)(**{
            f: dev_put(_pad_to(np.asarray(getattr(start_state, f)), n_pad, fills[f]))
            for f in state0._fields
        })
        # Seed the loop predicate from the resumed state — a checkpoint taken
        # at/after convergence must execute zero further rounds (matches the
        # single-device runner and the fused kernels' conv-plane seeding).
        done0 = _host_done(cfg, life_np, start_state, start_round, target)

    # --- chunked while_loop under shard_map -------------------------------

    # Telemetry plane: each executed round psums one counter row into a
    # replicated (chunk_rounds, N_COLS) block that rides out of the chunk
    # next to the predicate scalars (ops/telemetry.py — the "in-trace psum
    # of the counter block"). Python-level flag: off traces the identical
    # program as before.
    telemetry = cfg.telemetry
    tele_row = (
        telemetry_mod.make_sharded_row_fn(
            topo, cfg, n_pad, n_loc, NODE_AXIS, death_full, key_impl,
            revive_full,
        )
        if telemetry else None
    )
    stride = cfg.chunk_rounds

    # Health sentinel (cfg.mass_tolerance; see models/runner.py for the
    # full contract): psum'd non-finite count and mass residual per
    # executed round; a trip latches the replicated health scalar and
    # raises the done flag. Python-level flag — off traces the identical
    # program.
    sentinel = cfg.mass_tolerance is not None
    never_i32 = jnp.int32(faults_mod.NEVER)
    if sentinel:
        tol = cfg.mass_tolerance

        def sentinel_bad(state):
            bad_ct = lax.psum(
                jnp.sum((~jnp.isfinite(state.s)).astype(jnp.int32))
                + jnp.sum((~jnp.isfinite(state.w)).astype(jnp.int32)),
                NODE_AXIS,
            )
            # Pad slots carry weight 1 by construction, so the padded
            # invariant is n_pad (same correction as the telemetry mass
            # column).
            total_w = lax.psum(jnp.sum(state.w), NODE_AXIS)
            resid = jnp.abs(total_w - jnp.asarray(n_pad, state.w.dtype))
            return (bad_ct > 0) | (resid > jnp.asarray(tol, state.w.dtype))

    def chunk_local(state_in, rnd_in, done_in, *rest):
        if sentinel:
            health_in, round_end, key_data = rest[0], rest[1], rest[2]
            targs = rest[3:]
        else:
            round_end, key_data = rest[0], rest[1]
            targs = rest[2:]
        rnd0_in = rnd_in  # loop-entry round: telemetry rows index from here
        buf_i = 4 if sentinel else 3

        def cond(c):
            return jnp.logical_and(~c[2], c[1] < round_end)

        def body(c):
            state, rnd = c[0], c[1]
            state = round_fn(state, rnd, key_data, *targs)
            if death_full is None:
                conv_count = lax.psum(jnp.sum(state.conv), NODE_AXIS)
                done = conv_count >= target
            else:
                # Quorum over live nodes (ops/faults.py): pad slots have
                # death round 0 / revival NEVER, so the alive psum is
                # exactly the live population with no valid-mask needed.
                start = lax.axis_index(NODE_AXIS) * n_loc
                alive = _alive_loc(start, rnd)
                conv_alive = lax.psum(
                    jnp.sum((state.conv & alive).astype(jnp.int32)),
                    NODE_AXIS,
                )
                alive_count = lax.psum(
                    jnp.sum(alive.astype(jnp.int32)), NODE_AXIS
                )
                done = conv_alive >= faults_mod.quorum_need(
                    alive_count, cfg.quorum
                )
            if sentinel:
                health = c[3]
                health = jnp.where(
                    (health == never_i32) & sentinel_bad(state), rnd, health
                )
                done = done | (health != never_i32)
                out = (state, rnd + 1, done, health)
            else:
                out = (state, rnd + 1, done)
            if telemetry:
                row = tele_row(state, rnd, key_data)
                out += (lax.dynamic_update_index_in_dim(
                    c[buf_i], row, rnd - rnd0_in, 0
                ),)
            return out

        carry = (state_in, rnd_in, done_in)
        if sentinel:
            carry += (health_in,)
        if telemetry:
            carry += (jnp.zeros((stride, telemetry_mod.N_COLS), jnp.float32),)
        return lax.while_loop(cond, body, carry)

    state_specs = jax.tree.map(lambda _: P(NODE_AXIS), state0)
    # Donation (models/pipeline.py): each chunk's output shards alias the
    # input's buffers. Off when retired state must stay readable (chunk
    # hooks / stall watchdog).
    donate = on_chunk is None and not cfg.stall_chunks
    out_specs = (state_specs, P(), P())
    in_scalar_specs = (P(), P(), P())  # rnd, done, round_end
    if sentinel:
        out_specs += (P(),)  # replicated health scalar
        in_scalar_specs = (P(), P(), P(), P())  # + health
    if telemetry:
        out_specs += (P(),)  # replicated counter block
    chunk_sharded = jax.jit(
        compat.shard_map(
            chunk_local,
            mesh=mesh,
            in_specs=(state_specs,) + in_scalar_specs + (P(),) + topo_specs,
            out_specs=out_specs,
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    def rep_put(x):
        return dev_put(x, repl)

    rnd0 = rep_put(np.int32(start_round))
    done0_dev = rep_put(np.bool_(done0))
    kd_dev = rep_put(np.asarray(key_data_host))
    health0 = rep_put(np.int32(faults_mod.NEVER)) if sentinel else None

    def _chunk_args(health, round_end):
        pre = (health,) if sentinel else ()
        return pre + (rep_put(np.int32(round_end)), kd_dev) + topo_args

    if probe is not None:
        return probe(chunk_sharded, (
            state0, rnd0, done0_dev,
            *_chunk_args(health0, min(start_round + 1, cfg.max_rounds)),
        ), donate=donate)

    t0 = time.perf_counter()
    # Warmup runs ONE real round and DISCARDS the result — the timed loop
    # recomputes round 0 from the original state (absolute-round keys make
    # both exact), so run_s covers every round that `rounds` counts. Under
    # donation the warmup consumes a COPY so state0 stays live. A
    # zero-round warmup would leave the while body unexecuted and the axon
    # tunnel defers a one-time cost to the first execution that reaches it,
    # which would land in the timed loop.
    warm = chunk_sharded(
        jax.tree.map(jnp.copy, state0) if donate else state0,
        rnd0, done0_dev,
        *_chunk_args(health0, min(start_round + 1, cfg.max_rounds)),
    )
    int(warm[1])  # data-dependent sync; block_until_ready can return early
    del warm
    compile_s = time.perf_counter() - t0

    watchdog = StallWatchdog(cfg.stall_chunks)

    if sentinel:
        def dispatch(state, rnd, done, health, round_end):
            return chunk_sharded(
                state, rnd, done, *_chunk_args(health, round_end)
            )
    else:
        def dispatch(state, rnd, done, round_end):
            return chunk_sharded(
                state, rnd, done, *_chunk_args(None, round_end)
            )

    on_retire = None if on_chunk is None else on_chunk

    should_stop = None
    if cfg.stall_chunks:
        # Watchdog (models/runner.StallWatchdog): replicated scalar
        # reduction, process-safe like the trace hook. Pad slots carry
        # death round 0 / revival NEVER / conv 0, so the padded gap equals
        # the real one.
        life_pad = (
            None if death_full is None
            else faults_mod.LifePlanes(death=death_full, revive=revive_full)
        )

        def should_stop(rounds, state):
            return watchdog.no_progress(
                _progress_gap(
                    life_pad, cfg.quorum, target, state.conv, rounds
                )
            )

    collector = (
        telemetry_mod.Collector(start_round, on_rows=on_telemetry)
        if telemetry else None
    )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=state0, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds,
        stride=cfg.chunk_rounds, depth=cfg.pipeline_chunks, donate=donate,
        on_retire=on_retire, should_stop=should_stop,
        on_aux=collector.on_aux if collector else None,
        health0=health0,
        should_cancel=_cancel_fn(deadline),
        step_timing=cfg.step_timing,
        hook_error=("raise" if cfg.strict_checkpoint else "continue"),
    )
    run_s = time.perf_counter() - t1

    unhealthy_round = None
    if sentinel and loop.health is not None and (
        loop.health != int(faults_mod.NEVER)
    ):
        unhealthy_round = int(loop.health)

    # _finalize_result's reductions are jnp, not host numpy: when the mesh
    # spans processes the state arrays are not host-addressable, but every
    # process can run the same global reduction (replicated scalar out).
    # Padded slots never converge, so gating on `conv` excludes them.
    return _finalize_result(
        topo, cfg, loop.state, loop.rounds, target, compile_s, run_s,
        done=loop.done, stalled=watchdog.stalled, loop=loop,
        collector=collector, unhealthy_round=unhealthy_round,
        cancelled=loop.cancelled,
    )


# --- Declared wire contract (analysis/wire_specs.py) -----------------------
# The chunked XLA engine's collectives per ROUND, as data — the static
# auditor diffs this declaration against the traced chunk program, and
# tests/test_comm_audit.py asserts declaration <-> trace agreement (the
# counts live here, nowhere else). Modes: "halo" = exact offset-class plan
# (batched to ONE ppermute pair under the overlap schedule, one ppermute
# per offset class serially), "pool" = dynamic pool rolls (pool_size x
# (log2(n_dev) + 1) ppermute stages, schedule-invariant — dynamic rolls
# cannot be statically packed), "scatter" = the psum_scatter fallback when
# no exact halo plan exists. The psum is the termination verdict.
WIRE_SPEC = WireSpec(
    engine="sharded",
    variants={
        ("overlap", "halo"): Regions(
            body={"ppermute": C(fixed=2), "psum": C(fixed=1)}, setup={},
        ),
        ("serial", "halo"): Regions(
            body={"ppermute": C(per_class=1), "psum": C(fixed=1)}, setup={},
        ),
        ("overlap", "pool"): Regions(
            body={"ppermute": C(per_roll=1), "psum": C(fixed=1)}, setup={},
        ),
        ("serial", "pool"): Regions(
            body={"ppermute": C(per_roll=1), "psum": C(fixed=1)}, setup={},
        ),
        ("overlap", "scatter"): Regions(
            body={"reduce_scatter": C(fixed=1), "psum": C(fixed=1)},
            setup={},
        ),
        ("serial", "scatter"): Regions(
            body={"reduce_scatter": C(fixed=1), "psum": C(fixed=1)},
            setup={},
        ),
    },
    mechanism={
        "halo": "xla-ppermute", "pool": "xla-ppermute", "scatter": "scatter",
    },
    equal_bytes=("ppermute",),
)
