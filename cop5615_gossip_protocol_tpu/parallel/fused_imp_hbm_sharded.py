"""imp2d/imp3d x HBM x sharded: the marquee kind past one chip's HBM.

The reference caps Imp3D — its hardest configuration — at 2,000 actors on
one machine's threads (report.pdf p.3 SS4). The single-device HBM tier
(ops/fused_imp_hbm.py) streams it at 2^27 nodes on one chip, but until
this module the imp kinds were the ONLY lattice family with no
HBM x sharded composition (ROADMAP item 1): n_devices > 1 fell through to
a ValueError. This module composes the imp class-id delivery under the
one-sweep shard_map skeleton of parallel/fused_hbm_sharded.py, with the
long-range pool classes riding the replicated-window wire of the pool
compositions:

- state planes are row-sharded ([rows_loc, 128] per device: push-sum
  s/w/term/conv, gossip count/active/conv) and one super-step is ONE
  round — the pooled long-range classes are uniform over the whole ring,
  so nothing coarser admits an exact shard;
- the LATTICE classes (the full grid2d/grid3d lattice of the honest imp
  kinds — non-wrap, boundary live-masks, signed displacements) deliver
  from a halo-EXTENDED buffer exactly like the stencil composition: their
  window needs feed through the shared grouping core
  (ops/fused_stencil_hbm._plan_from_needs) over the extended ring, so
  neighboring classes collapse to one fetched window and one mark regen
  per tile. The halo transport resolves through
  parallel/halo.resolve_halo_transport: ONE batched ppermute pair per
  super-step on CPU (per-plane pairs with --overlap-collectives off), and
  on TPU the in-kernel `pltpu.make_async_remote_copy` neighbor DMA of the
  stencil composition (--halo-dma; zero XLA collectives on the lattice
  halo path, round 0 interior-first via _visit_order so the copies fly
  under the interior tiles);
- the POOL classes (the re-drawn long-range edge: P shared per-round
  displacements, uniform mod n) read their windows from ONE batched
  all_gather of the compact windowed send summaries per super-step
  (parallel/halo.gather_rows_batched — raw s/w for push-sum, the active
  plane for gossip, margin-extended for the kernel's 8-aligned window
  DMAs), with the d / d+Z mod-n blend pair fetched per slot exactly like
  the single-device engine;
- the marked class plane NEVER exists in memory: the sampled class
  (lattice class q in sorted-offset order, L + packed pool choice for the
  long-range slot, -1 for non-senders) is REGENERATED inside the window
  consumer at GLOBAL positions — threefry is position-wise, the boundary
  live-masks arithmetic, and the packed choice words re-derive from the
  global row (ops/fused_imp_hbm._sample_class_imp, re-based through the
  extended ring / the gathered mirror margin) — so each output row is
  computed from identical inputs by identical ops and trajectories are
  BITWISE the single-device fused_imp_hbm engine's (gossip ints exactly;
  push-sum via the power-of-two halve lemma: raw windows summed in the
  single-device accumulation order, halved after);
- termination composes by psum (deferred one super-step under
  cfg.overlap_collectives, parallel/overlap.py — rounds stay exact at the
  one-round super-step granularity); termination='global' uses the
  device-0 metric shift of the replicated-pool2 composition and latches
  the all-or-nothing conv plane at the fired verdict round.

Per-device residency is the gathered windowed planes plus the
halo-extended shard, so the aggregate population the plan admits is
~2^28+ for imp3d push-sum at the 12 GB plane budget — the BENCH_TABLES
"topology ceilings" imp row, hardware-free at plan level through
plan_imp_hbm_sharded_shape.

Reference mapping: the reference's Imp3D wiring (program.fs:295-313) and
lattice hot loop (program.fs:89-105, 110-143), actor-per-node capped at
~2,000 nodes — here at 2^28 nodes across a mesh.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import SimConfig
from ..ops.fused import threefry2x32_hash
from ..ops.fused_imp_hbm import _imp_dirs, _sample_class_imp
from ..ops.fused_pool import LANES, build_pool_layout
from ..ops.fused_pool2 import _copy_all, _win_plan
from ..ops.fused_stencil_hbm import (
    _centered_sq,
    _group_window_starts,
    _plan_from_needs,
    _window_counted,
    _window_vals,
)
from ..ops.sampling import POOL_CHOICE_BITS, POOL_PACK
from ..ops.topology import Topology, imp_split
from ..utils import compat
from ..analysis.wire_specs import C, Regions, WireSpec
from .fused_hbm_sharded import (
    _HBM_PLANE_BUDGET,
    _VMEM_SCRATCH_BUDGET,
    _boundary_split,
    _halo_rdmas,
    _neighbor_barrier,
    _visit_tile,
)

# The budget constants are the sibling composition's (imported above from
# the ONE home, fused_hbm_sharded, so a chip-class retune cannot drift
# the compositions' plan ceilings apart): per-device HBM for the resident
# planes (gathered windowed copies + extended shard + overlap carry),
# VMEM only for the PT-row streaming scratch.

# The sibling compositions' tile candidates plus two small tail entries:
# a shard here is rows_loc = R/n_dev rows and every margin must fit one
# ring revolution (m <= rows_ext), so small test shards need tiles the
# pool engines never shrink to. Multiples of 8 (the DMA alignment); real
# populations always take the large end.
_PT_CANDIDATES = (2048, 1024, 512, 256, 128, 64)


def _imp_lattice_offsets(kind: str, n: int):
    """Sorted mod-n lattice displacement classes of an honest imp kind —
    arithmetic in (kind, n) alone (ops/topology.build_imp2d/_imp3d append
    the one long-range edge AFTER the full-grid lattice columns), so the
    shape-level plan needs no adjacency arrays. None when n is not a
    perfect square/cube (no honest lattice exists)."""
    if kind == "imp2d":
        s = round(n ** 0.5)
        if s * s != n:
            return None
        return sorted({n - 1, 1, n - s, s})
    g = round(n ** (1 / 3))
    if g * g * g != n:
        return None
    g2 = g * g
    return sorted({n - 1, 1, n - g, g, n - g2, g2})


def _imp_lat_plan(kind: str, layout, rows_ext: int, PT: int):
    """Lattice-class window needs over the halo-extended ring, fed through
    the shared grouping core (ops/fused_stencil_hbm._plan_from_needs) —
    the imp displacement classes ARE the "needs" the planner abstracts.
    Non-wrap lattice: one signed need per class (boundary live-masks kill
    every would-be wrapping sender), keyed by CLASS ID q in sorted-offset
    order (the id the regenerated mark plane carries — the imp engines
    mask on class ids, not displacements).

    Returns (classes, groups, M) in the _shard_delivery_plan shapes:
    classes[q] = (q, ((group_idx, e, sq, None),)); groups[gi] =
    (sq_hi, m_rows, None); M = max margin rows past rows_ext."""
    n_ext = rows_ext * LANES
    N = layout.n
    offs = _imp_lattice_offsets(kind, N)
    assert offs is not None
    needs = []
    for q, d in enumerate(offs):
        signed = d if d <= N // 2 else d - N
        e = signed % n_ext
        needs.append((q, d, e, _centered_sq(e, rows_ext), None))
    classes, groups, M = _plan_from_needs(
        needs, list(range(len(offs))), PT, with_liveness=False
    )
    return classes, groups, M


def plan_imp_hbm_sharded_shape(kind: str, n: int, cfg: SimConfig,
                               n_dev: int):
    """(H, rows_loc, PT, layout) or a string reason — a pure function of
    (kind, n, cfg, n_dev), no adjacency arrays, so it also serves the
    plan-level BENCH_TABLES "topology ceilings" imp rows hardware-free."""
    if kind not in ("imp2d", "imp3d"):
        return f"topology {kind!r} is not an imp (lattice+extra) kind"
    if jax.process_count() > 1:
        # Multi-process support matrix (ISSUE 15): the imp composition's
        # replicated class planes are placed with single-process
        # jax.device_put, and its adjacency build is host-global (the imp
        # rng is sequential). Multi-process meshes serve the chunked
        # sharded engine on imp kinds (delivery='pool' there runs the
        # sharded dynamic-roll composition), or the HBM-streaming /
        # replicated-pool2 compositions on lattice/full kinds.
        return (
            "the imp x HBM x sharded composition is single-process; "
            "multi-process meshes serve the chunked sharded engine "
            "(drop the engine override) — or the HBM-streaming sharded / "
            "replicated-pool2 compositions on lattice/full kinds"
        )
    if cfg.delivery != "pool":
        return (
            "the imp x HBM x sharded composition serves the pooled "
            "long-range recast only (delivery='pool' — the same gate as "
            "the single-device imp engine dispatch)"
        )
    if cfg.reference:
        return (
            "pooled long-range sampling cannot reproduce the reference's "
            "static extra edge (Q9); reference semantics use scatter"
        )
    if cfg.dtype != "float32":
        return "fused engine supports float32 only"
    if not jax.config.jax_threefry_partitionable:
        return "requires jax_threefry_partitionable=True"
    if cfg.faulted:
        return "failure models not supported in this fused kernel"
    if cfg.telemetry:
        return (
            "telemetry counters run in the single-device fused kernels and "
            "the chunked/sharded XLA engines; this composition does not "
            "carry the counter block"
        )
    if cfg.step_timing and cfg.overlap_collectives:
        return (
            "step_timing under the overlapped super-step schedule would "
            "force the deferred termination psum to drain at every timed "
            "boundary (a host sync inside the overlap window); use "
            "overlap_collectives=False or step_timing=False"
        )
    if cfg.mass_tolerance is not None:
        return (
            "the health sentinel (--mass-tolerance) runs in the chunked "
            "and sharded XLA round bodies only"
        )
    if cfg.pool_size > 1 << POOL_CHOICE_BITS:
        return (
            f"pool_size {cfg.pool_size} exceeds the packed-choice limit "
            f"{1 << POOL_CHOICE_BITS}"
        )
    offs = _imp_lattice_offsets(kind, n)
    if offs is None:
        return (
            f"honest {kind} lattices need a perfect "
            f"{'square' if kind == 'imp2d' else 'cube'} population; "
            f"{n} is not one"
        )
    layout = build_pool_layout(n)
    R = layout.rows
    if R % n_dev != 0:
        return (
            f"padded layout ({R} rows) must split evenly; {n_dev} devices "
            "do not divide it"
        )
    rows_loc = R // n_dev
    N = layout.n
    Z = layout.n_pad - layout.n
    w = max(abs(d if d <= N // 2 else d - N) for d in offs)
    P = cfg.pool_size
    n_pw = P * (1 if Z == 0 else 2)
    pushsum = cfg.algorithm == "push-sum"
    n_state = 4 if pushsum else 3
    n_wp = 2 if pushsum else 1
    h_min = -(-w // LANES) + 1
    cands = []
    for pt in _PT_CANDIDATES:
        r = (-rows_loc) % pt
        if r % 2:
            continue  # 2H cannot hit an odd residue mod an even PT
        h = h_min + ((r // 2 - h_min) % (pt // 2))
        rows_ext = rows_loc + 2 * h
        if rows_ext % pt or rows_ext // pt < 1 or h > rows_loc:
            continue
        _cls, grp, m_lat = _imp_lat_plan(kind, layout, rows_ext, pt)
        sum_m = sum(m for _, m, _l in grp)
        MP = pt + 16
        # The mirror margins replicate ring rows [0, M) past the ring's
        # end in ONE copy (`p[:M]`, and in-kernel the non-overlapping
        # drain_halo self-copy), so each margin must fit inside one ring
        # revolution: a clipped margin silently clamps the window DMAs
        # and corrupts boundary deliveries.
        if m_lat > rows_ext or MP > R:
            continue
        # VMEM streaming scratch: own-state tiles + lattice group windows
        # (value planes + the regen mark plane) + the per-slot pool
        # windows off the gathered copy (both blend variants).
        vmem = (
            n_state * pt
            + sum_m * (n_wp + 1)
            + n_pw * MP * (n_wp + 1)
        ) * LANES * 4
        if vmem > _VMEM_SCRATCH_BUDGET:
            continue
        # Per-device HBM: the gathered margined windowed copies, the
        # halo-extended input planes, the in-kernel-DMA assembly planes
        # (margined windowed + plain), the output planes, and the overlap
        # schedule's double-buffer carry — ALL budgeted unconditionally so
        # geometry (H, PT) is invariant to the scheduling knobs.
        gathered = n_wp * (R + MP)
        ext_in = n_state * rows_ext
        ext_asm = n_wp * (rows_ext + m_lat) + (n_state - n_wp) * rows_ext
        outp = n_state * rows_ext
        carry = gathered + ext_in + n_state * rows_loc
        if (gathered + ext_in + ext_asm + outp + carry) * LANES * 4 > (
            _HBM_PLANE_BUDGET
        ):
            continue
        cands.append((rows_ext, pt, h))
    if not cands:
        return (
            f"no processing-tile split fits: the lattice halo ({w} slots) "
            f"at a {rows_loc}-row shard exceeds the shard, the VMEM "
            "streaming scratch, or the per-device HBM plane budget (the "
            "gathered windowed copy is the floor); use the chunked "
            "collective engine"
        )
    # Largest PT whose halo waste stays near the leanest candidate —
    # fewer, larger DMA volleys beat a few percent of redundant halo rows.
    lean = min(c[0] for c in cands)
    ok = [c for c in cands if c[0] <= lean + max(lean // 8, 1)]
    _, PT, H = max(ok, key=lambda c: c[1])
    return (H, rows_loc, PT, layout)


def plan_imp_hbm_sharded(topo: Topology, cfg: SimConfig, n_dev: int):
    """(H, rows_loc, PT, layout) or a string reason why the composition
    can't run this instance. The topo-level gate additionally requires the
    built instance's lattice slots to be offset-structured (imp_split) —
    the shape-level core (plan_imp_hbm_sharded_shape) carries every other
    check and the budget fit."""
    if topo.kind not in ("imp2d", "imp3d"):
        return f"topology {topo.kind!r} is not an imp (lattice+extra) kind"
    if imp_split(topo) is None:
        return "lattice slots are not offset-structured for this instance"
    return plan_imp_hbm_sharded_shape(topo.kind, topo.n, cfg, n_dev)


def _regen_imp_marks(dst, rows: int, base_row, k1, k2, ck1, ck2, R: int,
                     N: int, dirs, cls_of, L: int, P: int, *,
                     ring_rows=None, row0=None):
    """Sampled-CLASS plane regenerated at (wrapped) global rows
    [base_row, base_row+rows) — the sender's draw of the single-device imp
    engines, bit for bit: slot = untagged threefry word % degree over
    [lattice dirs..., extra], lattice slots map to their sorted-offset
    class id, the extra slot to L + the packed pool choice
    (ops/fused_imp_hbm._sample_class_imp). Non-senders mark -1.

    ``ring_rows``/``row0`` re-base the row map for the halo-extended
    buffer (window rows index the rows_ext ring, global row =
    (row0 + ext_row mod ring_rows) mod R — the fused_hbm_sharded
    _regen_marked_plane convention); without them ``base_row`` indexes the
    gathered copy's mirrored global ring (rows >= R wrap to rows - R).

    The packed choice re-derives elementwise from the global row (word =
    hash at (grow // POOL_PACK) * LANES + lane, sliced at
    4 * (grow % POOL_PACK)) — the same words _choice_tile_pt expands,
    valid at ARBITRARY window alignment. Computed in 512-row chunks (the
    whole-window live set blows Mosaic's scoped VMEM stack)."""
    RC = 512

    def chunk(o: int, ln: int):
        rl = lax.broadcasted_iota(jnp.int32, (ln, LANES), 0)
        ll = lax.broadcasted_iota(jnp.int32, (ln, LANES), 1)
        pos = base_row + o + rl
        if ring_rows is not None:
            pos = row0 + lax.rem(pos, jnp.int32(ring_rows))
        grow = lax.rem(pos, jnp.int32(R))
        jflat = grow * LANES + ll
        padm = jflat >= N
        bits = threefry2x32_hash(k1, k2, jflat.astype(jnp.uint32))
        word = threefry2x32_hash(
            ck1, ck2,
            ((grow // POOL_PACK) * LANES + ll).astype(jnp.uint32),
        )
        shift = (
            jnp.uint32(POOL_CHOICE_BITS)
            * (grow % POOL_PACK).astype(jnp.uint32)
        )
        choice = ((word >> shift) & jnp.uint32(P - 1)).astype(jnp.int32)
        cls, send_ok = _sample_class_imp(
            bits, choice, jflat, padm, dirs, cls_of, L
        )
        dst[pl.ds(o, ln), :] = jnp.where(send_ok, cls, jnp.int32(-1))

    for o in range(0, rows, RC):
        chunk(o, min(RC, rows - o))


def make_pushsum_imp_hbm_shard_chunk(
    topo: Topology, cfg: SimConfig, H: int, rows_loc: int, PT: int,
    layout, *, dma: bool = False, interpret: bool = False
):
    """Per-device ONE-ROUND kernel: ``chunk_fn(state4, gathered2, keys,
    offs, ckeys, row0, dev) -> (mid_state4, u)`` advances this shard's
    (s, w, term, conv) planes by one round. ``state4`` is the halo-EXTENDED
    margined planes under the XLA wire (rows_ext + M_lat windowed,
    rows_ext plain), or the MID planes under in-kernel DMA (``dma=True`` —
    the kernel performs the lattice halo exchange itself, interior-first).
    ``gathered2`` is the margined full (s, w) copy the pool windows read.
    ``u`` is the round's middle-region metric: unstable valid lanes under
    termination='global', converged count otherwise."""
    R_glob = layout.rows
    N = layout.n
    Z = layout.n_pad - layout.n
    rows_ext = rows_loc + 2 * H
    T = rows_ext // PT
    n_dev = R_glob // rows_loc
    dirs, lat_offs, L = _imp_dirs(topo)
    cls_of = {d: q for q, d in enumerate(lat_offs)}
    classes, groups, M_lat = _imp_lat_plan(topo.kind, layout, rows_ext, PT)
    G = len(groups)
    P = cfg.pool_size
    stride = 1 if Z == 0 else 2
    n_pw = P * stride
    MP = PT + 16
    S = max(abs(sq) for _q, reads in classes for _gi, _e, sq, _t1 in reads)
    b_lo, b_hi = _boundary_split(H, PT, T, S)
    n_int = T - b_lo - b_hi
    delta = np.float32(cfg.resolved_delta)
    term_rounds = np.int32(cfg.term_rounds)
    global_term = cfg.termination == "global"
    in_rows = rows_loc if dma else rows_ext
    n_fetch = 2 * G + 2 * n_pw + 4

    def kernel(*refs):
        it = iter(refs)
        scal_ref, keys_ref, ckeys_ref, offs_ref = (
            next(it), next(it), next(it), next(it)
        )
        s_in, w_in, t_in, c_in = next(it), next(it), next(it), next(it)
        gs, gw = next(it), next(it)
        if dma:
            sA, wA, tA, cA = next(it), next(it), next(it), next(it)
        s_o, w_o, t_o, c_o, u_o = (
            next(it), next(it), next(it), next(it), next(it)
        )
        win_s = [next(it) for _ in range(G)]
        win_w = [next(it) for _ in range(G)]
        mk = [next(it) for _ in range(G)]
        pwin_s = [next(it) for _ in range(n_pw)]
        pwin_w = [next(it) for _ in range(n_pw)]
        pmk = [next(it) for _ in range(n_pw)]
        own_s, own_w, own_t, own_c = next(it), next(it), next(it), next(it)
        sems, str_sems = next(it), next(it)
        dma_sems = (next(it), next(it)) if dma else None
        row_l = lax.broadcasted_iota(jnp.int32, (PT, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (PT, LANES), 1)
        row0 = scal_ref[0]
        dev = scal_ref[1]
        k1 = keys_ref[0]
        k2 = keys_ref[1]
        ck1 = ckeys_ref[0]
        ck2 = ckeys_ref[1]

        if dma:
            cur = (sA, wA, tA, cA)
            ssems, rsems = dma_sems
            left = lax.rem(dev + jnp.int32(n_dev - 1), jnp.int32(n_dev))
            right = lax.rem(dev + jnp.int32(1), jnp.int32(n_dev))

            def rdmas():
                return _halo_rdmas(
                    (s_in, w_in, t_in, c_in), (sA, wA, tA, cA),
                    H, rows_loc, ssems, rsems, left, right,
                )

            def drain_halo():
                for cp in rdmas():
                    cp.wait()
                _copy_all([
                    (sA.at[pl.ds(0, M_lat), :],
                     sA.at[pl.ds(rows_ext, M_lat), :]),
                    (wA.at[pl.ds(0, M_lat), :],
                     wA.at[pl.ds(rows_ext, M_lat), :]),
                ], str_sems)

            # Hand the halo slot to the kernel: barrier with the ring
            # neighbors, push my boundary slices into their assembly
            # planes, land my own mid rows — the recv drains under the
            # interior tiles (drain_halo before the first boundary tile).
            _neighbor_barrier(left, right)
            for cp in rdmas():
                cp.start()
            _copy_all([
                (s_in, sA.at[pl.ds(H, rows_loc), :]),
                (w_in, wA.at[pl.ds(H, rows_loc), :]),
                (t_in, tA.at[pl.ds(H, rows_loc), :]),
                (c_in, cA.at[pl.ds(H, rows_loc), :]),
            ], str_sems)
        else:
            cur = (s_in, w_in, t_in, c_in)

        s_c, w_c, t_c, c_c = cur

        def regen(dst, rows, base_row, *, ring):
            _regen_imp_marks(
                dst, rows, base_row, k1, k2, ck1, ck2, R_glob, N,
                dirs, cls_of, L, P,
                ring_rows=rows_ext if ring else None,
                row0=row0 if ring else None,
            )

        def tile(t, acc):
            r0 = t * PT
            starts = _group_window_starts(groups, r0, rows_ext)
            g0 = lax.rem(row0 + jnp.int32(r0), jnp.int32(R_glob))
            pplans = []
            pairs = []
            for gi, (_ws8u, dma0, _live) in enumerate(starts):
                m = groups[gi][1]
                pairs.append((s_c.at[pl.ds(dma0, m), :], win_s[gi]))
                pairs.append((w_c.at[pl.ds(dma0, m), :], win_w[gi]))
            for slot in range(P):
                d = offs_ref[slot]
                for v in range(stride):
                    e = d if v == 0 else d + jnp.int32(Z)
                    ws8, rl, off = _win_plan(g0, e, R_glob)
                    wi = slot * stride + v
                    pplans.append((ws8, rl, off))
                    pairs.append((gs.at[pl.ds(ws8, MP), :], pwin_s[wi]))
                    pairs.append((gw.at[pl.ds(ws8, MP), :], pwin_w[wi]))
            pairs.append((s_c.at[pl.ds(r0, PT), :], own_s))
            pairs.append((w_c.at[pl.ds(r0, PT), :], own_w))
            pairs.append((t_c.at[pl.ds(r0, PT), :], own_t))
            pairs.append((c_c.at[pl.ds(r0, PT), :], own_c))
            cps = [
                pltpu.make_async_copy(src, dst, sems.at[i])
                for i, (src, dst) in enumerate(pairs)
            ]
            for cp in cps:
                cp.start()
            # Regenerate every window's class plane while the raw windows
            # stream: lattice groups at extended-ring rows, pool windows
            # at the gathered copy's (mirror-wrapped) global rows.
            for gi, (ws8u, _dma0, _live) in enumerate(starts):
                regen(mk[gi], groups[gi][1], ws8u, ring=True)
            for wi, (ws8, _rl, _off) in enumerate(pplans):
                regen(pmk[wi], MP, ws8, ring=False)
            for cp in cps:
                cp.wait()
            grow = lax.rem(row0 + r0 + row_l, jnp.int32(R_glob))
            gflat = grow * LANES + lane
            padm = gflat >= N
            mid = (row_l + r0 >= H) & (row_l + r0 < H + rows_loc)
            inbox_s = jnp.zeros((PT, LANES), jnp.float32)
            inbox_w = jnp.zeros((PT, LANES), jnp.float32)
            # Accumulate in the single-device order: lattice classes in
            # sorted-offset order, then pool slots (the chunked path's
            # association tree); groups only choose the buffer.
            for q, reads in classes:
                ((gi, e, sq, _t1),) = reads  # non-wrap: one read per class
                ws8u = starts[gi][0]
                off = jnp.asarray(
                    r0 - sq - 1 + 2 * rows_ext, jnp.int32
                ) - ws8u
                rl = e % LANES
                inbox_s = inbox_s + _window_vals(
                    win_s[gi], mk[gi], off, PT, rl, q, lane, interpret
                )
                inbox_w = inbox_w + _window_vals(
                    win_w[gi], mk[gi], off, PT, rl, q, lane, interpret
                )
            for slot in range(P):
                wi = slot * stride
                _ws8, rl, off = pplans[wi]
                cs = _window_vals(
                    pwin_s[wi], pmk[wi], off, PT, rl, L + slot, lane,
                    interpret,
                )
                cw = _window_vals(
                    pwin_w[wi], pmk[wi], off, PT, rl, L + slot, lane,
                    interpret,
                )
                if Z != 0:
                    _ws8b, rlb, offb = pplans[wi + 1]
                    take = gflat >= offs_ref[slot]
                    cs = jnp.where(take, cs, _window_vals(
                        pwin_s[wi + 1], pmk[wi + 1], offb, PT, rlb,
                        L + slot, lane, interpret,
                    ))
                    cw = jnp.where(take, cw, _window_vals(
                        pwin_w[wi + 1], pmk[wi + 1], offb, PT, rlb,
                        L + slot, lane, interpret,
                    ))
                inbox_s = inbox_s + cs
                inbox_w = inbox_w + cw
            # Halve AFTER the masked sums — bitwise the single-device
            # engine's pre-halved delivery planes (exact power-of-two
            # scaling commutes with every rounding in the sum).
            half = jnp.float32(0.5)
            inbox_s = jnp.where(padm, 0.0, inbox_s * half)
            inbox_w = jnp.where(padm, 0.0, inbox_w * half)
            s_t = own_s[:]
            w_t = own_w[:]
            # Every real imp node has the always-live extra slot, so the
            # send gate is exactly ~padm (the single-device p2 formula).
            s_send = jnp.where(padm, 0.0, s_t * half)
            w_send = jnp.where(padm, 0.0, w_t * half)
            s_new = (s_t - s_send) + inbox_s
            w_new = (w_t - w_send) + inbox_w
            if global_term:
                ratio_old = s_t / w_t
                tol = delta * jnp.maximum(jnp.abs(ratio_old), jnp.float32(1))
                unstable = (
                    jnp.abs(s_new / w_new - ratio_old) > tol
                ) & ~padm & mid
                term_new = own_t[:]
                conv_new = own_c[:]
                tile_metric = jnp.sum(
                    unstable.astype(jnp.int32), dtype=jnp.int32
                )
            else:
                received = inbox_w > 0
                stable = jnp.abs(s_new / w_new - s_t / w_t) <= delta
                term_new = jnp.where(
                    received,
                    jnp.where(stable, own_t[:] + 1, jnp.int32(0)),
                    own_t[:],
                )
                conv_new = jnp.where(
                    padm,
                    jnp.int32(0),
                    jnp.where(
                        (own_c[:] != 0) | (term_new >= term_rounds),
                        jnp.int32(1),
                        jnp.int32(0),
                    ),
                )
                tile_metric = jnp.sum(
                    jnp.where(mid, conv_new, jnp.int32(0)), dtype=jnp.int32
                )
            own_s[:] = s_new
            own_w[:] = w_new
            own_t[:] = term_new
            own_c[:] = conv_new
            _copy_all([
                (own_s, s_o.at[pl.ds(r0, PT), :]),
                (own_w, w_o.at[pl.ds(r0, PT), :]),
                (own_t, t_o.at[pl.ds(r0, PT), :]),
                (own_c, c_o.at[pl.ds(r0, PT), :]),
            ], str_sems)
            return acc + tile_metric

        def step(u, acc):
            if dma:
                # Interior-first: boundary tiles run last, behind the halo
                # drain (per-tile-independent — bitwise-neutral).
                t = _visit_tile(u, T, b_lo, b_hi)

                @pl.when(u == n_int)
                def _wait_halo():
                    drain_halo()
            else:
                t = u
            return tile(t, acc)

        total = lax.fori_loop(0, T, step, jnp.int32(0), unroll=False)
        u_o[0] = total

    def chunk_fn(state4, gathered2, keys, offs, ckeys, row0, dev):
        s, w, t, c = state4
        gs, gw = gathered2
        f32e = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.float32)
        i32e = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.int32)
        f32m = jax.ShapeDtypeStruct((rows_ext + M_lat, LANES), jnp.float32)
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * 4 + [
            pl.BlockSpec(memory_space=pl.ANY)
        ] * 6
        out_shape = []
        if dma:
            out_shape += [f32m, f32m, i32e, i32e]  # assembly planes
        out_shape += [
            f32e, f32e, i32e, i32e,
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ]
        scratch = (
            [pltpu.VMEM((m, LANES), jnp.float32) for _, m, _l in groups]
            + [pltpu.VMEM((m, LANES), jnp.float32) for _, m, _l in groups]
            + [pltpu.VMEM((m, LANES), jnp.int32) for _, m, _l in groups]
            + [pltpu.VMEM((MP, LANES), jnp.float32)] * n_pw
            + [pltpu.VMEM((MP, LANES), jnp.float32)] * n_pw
            + [pltpu.VMEM((MP, LANES), jnp.int32)] * n_pw
            + [
                pltpu.VMEM((PT, LANES), jnp.float32),
                pltpu.VMEM((PT, LANES), jnp.float32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.SemaphoreType.DMA((n_fetch,)),
                pltpu.SemaphoreType.DMA((4,)),
            ]
        )
        params = dict(vmem_limit_bytes=96 * 1024 * 1024)
        if dma:
            scratch += [
                pltpu.SemaphoreType.DMA((8,)),
                pltpu.SemaphoreType.DMA((8,)),
            ]
            params["collective_id"] = 0
        outs = pl.pallas_call(
            kernel,
            grid=(1,),
            out_shape=tuple(out_shape),
            in_specs=in_specs,
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * (len(out_shape) - 1)
                + [pl.BlockSpec(memory_space=pltpu.SMEM)]
            ),
            scratch_shapes=scratch,
            compiler_params=compat.pallas_tpu_compiler_params(**params),
            interpret=interpret,
        )(
            jnp.stack([jnp.int32(row0), jnp.int32(dev)]),
            keys, ckeys, offs,
            s, w, t, c, gs, gw,
        )
        base = 4 if dma else 0
        mid = tuple(
            outs[base + i][H:H + rows_loc] for i in range(4)
        )
        return mid, outs[base + 4][0]

    return chunk_fn, in_rows, M_lat


def make_gossip_imp_hbm_shard_chunk(
    topo: Topology, cfg: SimConfig, H: int, rows_loc: int, PT: int,
    layout, *, dma: bool = False, interpret: bool = False
):
    """Gossip analog: shard planes (count, active, conv); windows read the
    raw ACTIVE plane (halo-extended for the lattice classes, gathered for
    the pool slots) and the regenerated class plane gates per-class
    counting (ops/fused_stencil_hbm._window_counted); receiver-side
    suppression against the round-start conv tile. ``u`` is the round's
    middle-region converged count."""
    R_glob = layout.rows
    N = layout.n
    Z = layout.n_pad - layout.n
    rows_ext = rows_loc + 2 * H
    T = rows_ext // PT
    n_dev = R_glob // rows_loc
    dirs, lat_offs, L = _imp_dirs(topo)
    cls_of = {d: q for q, d in enumerate(lat_offs)}
    classes, groups, M_lat = _imp_lat_plan(topo.kind, layout, rows_ext, PT)
    G = len(groups)
    P = cfg.pool_size
    stride = 1 if Z == 0 else 2
    n_pw = P * stride
    MP = PT + 16
    S = max(abs(sq) for _q, reads in classes for _gi, _e, sq, _t1 in reads)
    b_lo, b_hi = _boundary_split(H, PT, T, S)
    n_int = T - b_lo - b_hi
    rumor_target = np.int32(cfg.resolved_rumor_target)
    suppress = cfg.resolved_suppress
    in_rows = rows_loc if dma else rows_ext
    n_fetch = G + n_pw + 3

    def kernel(*refs):
        it = iter(refs)
        scal_ref, keys_ref, ckeys_ref, offs_ref = (
            next(it), next(it), next(it), next(it)
        )
        n_in, a_in, c_in = next(it), next(it), next(it)
        ga = next(it)
        if dma:
            nA, aA, cA = next(it), next(it), next(it)
        n_o, a_o, c_o, u_o = next(it), next(it), next(it), next(it)
        win_a = [next(it) for _ in range(G)]
        mk = [next(it) for _ in range(G)]
        pwin_a = [next(it) for _ in range(n_pw)]
        pmk = [next(it) for _ in range(n_pw)]
        own_n, own_a, own_c = next(it), next(it), next(it)
        sems, str_sems = next(it), next(it)
        dma_sems = (next(it), next(it)) if dma else None
        row_l = lax.broadcasted_iota(jnp.int32, (PT, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (PT, LANES), 1)
        row0 = scal_ref[0]
        dev = scal_ref[1]
        k1 = keys_ref[0]
        k2 = keys_ref[1]
        ck1 = ckeys_ref[0]
        ck2 = ckeys_ref[1]

        if dma:
            ssems, rsems = dma_sems
            left = lax.rem(dev + jnp.int32(n_dev - 1), jnp.int32(n_dev))
            right = lax.rem(dev + jnp.int32(1), jnp.int32(n_dev))

            def rdmas():
                return _halo_rdmas(
                    (n_in, a_in, c_in), (nA, aA, cA),
                    H, rows_loc, ssems, rsems, left, right,
                )

            def drain_halo():
                for cp in rdmas():
                    cp.wait()
                _copy_all([
                    (aA.at[pl.ds(0, M_lat), :],
                     aA.at[pl.ds(rows_ext, M_lat), :]),
                ], str_sems)

            _neighbor_barrier(left, right)
            for cp in rdmas():
                cp.start()
            _copy_all([
                (n_in, nA.at[pl.ds(H, rows_loc), :]),
                (a_in, aA.at[pl.ds(H, rows_loc), :]),
                (c_in, cA.at[pl.ds(H, rows_loc), :]),
            ], str_sems)
            cur = (nA, aA, cA)
        else:
            cur = (n_in, a_in, c_in)

        n_c, a_c, c_c = cur

        def regen(dst, rows, base_row, *, ring):
            _regen_imp_marks(
                dst, rows, base_row, k1, k2, ck1, ck2, R_glob, N,
                dirs, cls_of, L, P,
                ring_rows=rows_ext if ring else None,
                row0=row0 if ring else None,
            )

        def tile(t, acc):
            r0 = t * PT
            starts = _group_window_starts(groups, r0, rows_ext)
            g0 = lax.rem(row0 + jnp.int32(r0), jnp.int32(R_glob))
            pplans = []
            pairs = []
            for gi, (_ws8u, dma0, _live) in enumerate(starts):
                m = groups[gi][1]
                pairs.append((a_c.at[pl.ds(dma0, m), :], win_a[gi]))
            for slot in range(P):
                d = offs_ref[slot]
                for v in range(stride):
                    e = d if v == 0 else d + jnp.int32(Z)
                    ws8, rl, off = _win_plan(g0, e, R_glob)
                    wi = slot * stride + v
                    pplans.append((ws8, rl, off))
                    pairs.append((ga.at[pl.ds(ws8, MP), :], pwin_a[wi]))
            pairs.append((n_c.at[pl.ds(r0, PT), :], own_n))
            pairs.append((a_c.at[pl.ds(r0, PT), :], own_a))
            pairs.append((c_c.at[pl.ds(r0, PT), :], own_c))
            cps = [
                pltpu.make_async_copy(src, dst, sems.at[i])
                for i, (src, dst) in enumerate(pairs)
            ]
            for cp in cps:
                cp.start()
            for gi, (ws8u, _dma0, _live) in enumerate(starts):
                regen(mk[gi], groups[gi][1], ws8u, ring=True)
            for wi, (ws8, _rl, _off) in enumerate(pplans):
                regen(pmk[wi], MP, ws8, ring=False)
            for cp in cps:
                cp.wait()
            grow = lax.rem(row0 + r0 + row_l, jnp.int32(R_glob))
            gflat = grow * LANES + lane
            padm = gflat >= N
            mid = (row_l + r0 >= H) & (row_l + r0 < H + rows_loc)
            inbox = jnp.zeros((PT, LANES), jnp.int32)
            for q, reads in classes:
                ((gi, e, sq, _t1),) = reads
                ws8u = starts[gi][0]
                off = jnp.asarray(
                    r0 - sq - 1 + 2 * rows_ext, jnp.int32
                ) - ws8u
                rl = e % LANES
                inbox = inbox + _window_counted(
                    win_a[gi], mk[gi], off, PT, rl, q, lane, interpret
                )
            for slot in range(P):
                wi = slot * stride
                _ws8, rl, off = pplans[wi]
                g = _window_counted(
                    pwin_a[wi], pmk[wi], off, PT, rl, L + slot, lane,
                    interpret,
                )
                if Z != 0:
                    _ws8b, rlb, offb = pplans[wi + 1]
                    g = jnp.where(
                        gflat >= offs_ref[slot],
                        g,
                        _window_counted(
                            pwin_a[wi + 1], pmk[wi + 1], offb, PT, rlb,
                            L + slot, lane, interpret,
                        ),
                    )
                inbox = inbox + g
            inbox = jnp.where(padm, jnp.int32(0), inbox)
            if suppress:
                inbox = jnp.where(own_c[:] != 0, jnp.int32(0), inbox)
            count_new = own_n[:] + inbox
            active_new = jnp.where(
                (own_a[:] != 0) | (inbox > 0), jnp.int32(1), jnp.int32(0)
            )
            conv_new = jnp.where(
                count_new >= rumor_target, jnp.int32(1), jnp.int32(0)
            )
            own_n[:] = count_new
            own_a[:] = active_new
            own_c[:] = conv_new
            _copy_all([
                (own_n, n_o.at[pl.ds(r0, PT), :]),
                (own_a, a_o.at[pl.ds(r0, PT), :]),
                (own_c, c_o.at[pl.ds(r0, PT), :]),
            ], str_sems)
            return acc + jnp.sum(
                jnp.where(mid, conv_new, jnp.int32(0)), dtype=jnp.int32
            )

        def step(u, acc):
            if dma:
                t = _visit_tile(u, T, b_lo, b_hi)

                @pl.when(u == n_int)
                def _wait_halo():
                    drain_halo()
            else:
                t = u
            return tile(t, acc)

        total = lax.fori_loop(0, T, step, jnp.int32(0), unroll=False)
        u_o[0] = total

    def chunk_fn(state3, gathered1, keys, offs, ckeys, row0, dev):
        cnt, act, cv = state3
        (ga,) = gathered1
        i32e = jax.ShapeDtypeStruct((rows_ext, LANES), jnp.int32)
        i32m = jax.ShapeDtypeStruct((rows_ext + M_lat, LANES), jnp.int32)
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * 4 + [
            pl.BlockSpec(memory_space=pl.ANY)
        ] * 4
        out_shape = []
        if dma:
            out_shape += [i32e, i32m, i32e]  # assembly: count, active, conv
        out_shape += [
            i32e, i32e, i32e,
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ]
        scratch = (
            [pltpu.VMEM((m, LANES), jnp.int32) for _, m, _l in groups]
            + [pltpu.VMEM((m, LANES), jnp.int32) for _, m, _l in groups]
            + [pltpu.VMEM((MP, LANES), jnp.int32)] * n_pw
            + [pltpu.VMEM((MP, LANES), jnp.int32)] * n_pw
            + [
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.VMEM((PT, LANES), jnp.int32),
                pltpu.SemaphoreType.DMA((n_fetch,)),
                pltpu.SemaphoreType.DMA((3,)),
            ]
        )
        params = dict(vmem_limit_bytes=96 * 1024 * 1024)
        if dma:
            scratch += [
                pltpu.SemaphoreType.DMA((6,)),
                pltpu.SemaphoreType.DMA((6,)),
            ]
            params["collective_id"] = 0
        outs = pl.pallas_call(
            kernel,
            grid=(1,),
            out_shape=tuple(out_shape),
            in_specs=in_specs,
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pl.ANY)] * (len(out_shape) - 1)
                + [pl.BlockSpec(memory_space=pltpu.SMEM)]
            ),
            scratch_shapes=scratch,
            compiler_params=compat.pallas_tpu_compiler_params(**params),
            interpret=interpret,
        )(
            jnp.stack([jnp.int32(row0), jnp.int32(dev)]),
            keys, ckeys, offs,
            cnt, act, cv, ga,
        )
        base = 3 if dma else 0
        mid = tuple(
            outs[base + i][H:H + rows_loc] for i in range(3)
        )
        return mid, outs[base + 3][0]

    return chunk_fn, in_rows, M_lat


def run_imp_hbm_sharded(
    topo: Topology,
    cfg: SimConfig,
    mesh=None,
    key=None,
    on_chunk=None,
    start_state=None,
    start_round: int = 0,
    probe=None,
    deadline=None,
):
    """Sharded imp x HBM run — engine='fused', n_devices > 1, imp2d/imp3d
    under pooled long-range sampling (delivery='pool'), populations past
    one chip's HBM plane budget.

    One super-step = one round: the lattice halo wire (batched ppermute
    pair on CPU; in-kernel async-remote-copy on TPU via --halo-dma) plus
    ONE batched all_gather of the windowed send summaries for the pool
    classes, then each device's one-round class-id sweep over its extended
    buffer, then the psum'd termination verdict — deferred one super-step
    under cfg.overlap_collectives (parallel/overlap.py). Trajectories are
    bitwise the single-device fused_imp_hbm engine's
    (tests/test_fused_imp_hbm_sharded.py). termination='global' latches
    the all-or-nothing conv plane at the exact fired verdict round.

    ``probe(chunk_sharded, args)`` short-circuits the run for
    benchmarks/comm_audit.py (trace, never execute)."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import gossip as gossip_mod
    from ..models import pipeline as pipeline_mod
    from ..models import pushsum as pushsum_mod
    from ..models.runner import (
        StallWatchdog,
        _cancel_fn,
        _check_dtype,
        _finalize_result,
        _progress_gap,
        draw_leader,
    )
    from ..ops import sampling
    from ..ops.fused import round_keys
    from ..ops.fused_imp import choice_round_keys
    from ..ops.fused_pool import round_offsets
    from . import halo as halo_mod
    from . import overlap as overlap_mod
    from .mesh import NODE_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh(cfg.n_devices)
    n_dev = mesh.devices.size
    plan = plan_imp_hbm_sharded(topo, cfg, n_dev)
    if isinstance(plan, str):
        raise ValueError(
            f"engine='fused' with n_devices={n_dev} unavailable: {plan}"
        )
    H, rows_loc, PT, layout = plan
    _check_dtype(cfg)
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    backend = jax.default_backend()
    transport = halo_mod.resolve_halo_transport(cfg, backend)
    dma = transport == "dma"
    # The remote-copy kernel only EXECUTES on TPU; elsewhere it can only
    # be TRACED (the comm-audit probe) — execution is gated below.
    interpret = backend != "tpu" and not dma
    pushsum = cfg.algorithm == "push-sum"
    global_term = pushsum and cfg.termination == "global"
    make = (
        make_pushsum_imp_hbm_shard_chunk if pushsum
        else make_gossip_imp_hbm_shard_chunk
    )
    chunk_fn, _in_rows, M_lat = make(
        topo, cfg, H, rows_loc, PT, layout, dma=dma, interpret=interpret
    )
    R_glob = layout.rows
    rows_ext = rows_loc + 2 * H
    MP = PT + 16
    n = topo.n
    Pool = cfg.pool_size
    target = cfg.resolved_target_count(n, topo.target_count)
    key_data_host, key_impl = sampling.key_split(key)

    shard_rows = NamedSharding(mesh, P(NODE_AXIS, None))
    repl = NamedSharding(mesh, P())

    plane_fields = (
        [("s", np.float32, 0.0), ("w", np.float32, 1.0),
         ("term", np.int32, cfg.initial_term_round), ("conv", np.int32, 0)]
        if pushsum
        else [("count", np.int32, 0), ("active", np.int32, 0),
              ("conv", np.int32, 0)]
    )
    # Indices of the windowed planes delivery actually reads — the planes
    # the all_gather ships and the margin extension covers.
    win_idx = (0, 1) if pushsum else (1,)

    def to_planes(state):
        outs = []
        for f, dt, fill in plane_fields:
            x = np.asarray(getattr(state, f)).astype(dt)
            full = np.full(layout.n_pad, fill, dtype=dt)
            full[: x.shape[0]] = x
            outs.append(full.reshape(R_glob, LANES))
        return tuple(outs)

    if start_state is not None:
        st0 = jax.tree.map(np.asarray, start_state)
    elif pushsum:
        st0 = pushsum_mod.init_state(n, jnp.float32, cfg.initial_term_round)
    else:
        # reference semantics are plan-rejected, so no counts receipt.
        st0 = gossip_mod.init_state(
            n, draw_leader(key, topo, cfg), leader_counts_receipt=False
        )
    planes0 = tuple(jax.device_put(p, shard_rows) for p in to_planes(st0))
    done0 = bool(np.asarray(st0.conv).sum() >= target)

    perm_fwd = [(d, (d + 1) % n_dev) for d in range(n_dev)]
    perm_bwd = [(d, (d - 1) % n_dev) for d in range(n_dev)]
    overlap = cfg.overlap_collectives
    rumor_target = cfg.resolved_rumor_target

    def windowed(planes):
        return tuple(planes[i] for i in win_idx)

    def exchange(planes):
        """The super-step wires: ONE batched all_gather of the windowed
        send summaries (margin-extended for the pool windows' 8-aligned
        DMAs) + the lattice halo transport — batched ppermute pair on the
        XLA wire, or the identity under in-kernel DMA (the kernel owns the
        lattice wire). The windowed ext planes additionally carry the
        M_lat mirror margin the group windows read."""
        wp = windowed(planes)
        if overlap:
            full = halo_mod.gather_rows_batched(wp, NODE_AXIS)
        else:
            full = tuple(
                lax.all_gather(p, NODE_AXIS, axis=0, tiled=True)
                for p in wp
            )
        full = tuple(jnp.concatenate([p, p[:MP]], axis=0) for p in full)
        if dma:
            return (planes, full)
        if overlap:
            ext = halo_mod.exchange_rows_batched(planes, H, NODE_AXIS, n_dev)
        else:
            def ext_rows(x):
                left = lax.ppermute(x[-H:], NODE_AXIS, perm_fwd)
                right = lax.ppermute(x[:H], NODE_AXIS, perm_bwd)
                return jnp.concatenate([left, x, right], axis=0)

            ext = tuple(ext_rows(p) for p in planes)
        ext = tuple(
            jnp.concatenate([p, p[:M_lat]], axis=0) if i in win_idx else p
            for i, p in enumerate(ext)
        )
        return (ext, full)

    def chunk_local(planes_in, rnd_in, done_in, round_end, key_data):
        base = sampling.key_join(key_data, key_impl)
        dev = lax.axis_index(NODE_AXIS)
        row0 = lax.rem(
            dev.astype(jnp.int32) * rows_loc - H + 2 * R_glob,
            jnp.int32(R_glob),
        )

        def metric_shift(u):
            """Global-residual verdict through the fixed-target loop: the
            shifted metric fires psum(metric) >= target iff the summed
            unstable count is zero (the replicated-pool2 trick — the
            shift rides device 0 so psum adds it exactly once)."""
            if global_term:
                return jnp.where(
                    dev == 0, jnp.int32(target), jnp.int32(0)
                ) - u
            return u

        def compute(ext_pack, rnd, cap):
            ext_planes, full = ext_pack
            keys = round_keys(base, rnd, 1)
            offs = round_offsets(base, rnd, 1, Pool, n)
            ckeys = choice_round_keys(base, rnd, 1)
            out, u = chunk_fn(
                ext_planes, full, keys[0], offs[0], ckeys[0], row0, dev
            )
            return out, jnp.int32(1), metric_shift(u)

        if overlap:
            planes_f, rnd_f, done_f = overlap_mod.overlapped_superstep_loop(
                planes_in, rnd_in, done_in, round_end,
                exchange=exchange, compute=compute,
                psum_metric=lambda m: lax.psum(m, NODE_AXIS),
                target=target,
            )
        else:
            def cond(c):
                _, rnd, done = c
                return jnp.logical_and(~done, rnd < round_end)

            def body(c):
                planes, rnd, _ = c
                out, executed, metric = compute(
                    exchange(planes), rnd, round_end
                )
                total = lax.psum(metric, NODE_AXIS)
                return (out, rnd + executed, total >= target)

            planes_f, rnd_f, done_f = lax.while_loop(
                cond, body, (planes_in, rnd_in, done_in)
            )

        if global_term:
            # All-or-nothing latch at the fired verdict — the sharded form
            # of the single-device engine's latch_conv_global_streamed.
            pos = (
                (dev.astype(jnp.int32) * rows_loc + lax.broadcasted_iota(
                    jnp.int32, (rows_loc, LANES), 0)) * LANES
                + lax.broadcasted_iota(jnp.int32, (rows_loc, LANES), 1)
            )
            cv = jnp.where(
                done_f & (pos < n), jnp.int32(1), planes_f[3]
            )
            planes_f = (planes_f[0], planes_f[1], planes_f[2], cv)
        return planes_f, rnd_f, done_f

    plane_specs = tuple(P(NODE_AXIS, None) for _ in planes0)
    donate = on_chunk is None and not cfg.stall_chunks
    chunk_sharded = jax.jit(
        compat.shard_map(
            chunk_local,
            mesh=mesh,
            in_specs=(plane_specs, P(), P(), P(), P()),
            out_specs=(plane_specs, P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    def rep_put(x):
        return jax.device_put(x, repl)

    kd_dev = rep_put(np.asarray(key_data_host))
    rnd0 = rep_put(np.int32(start_round))
    done0_dev = rep_put(np.bool_(done0))

    def to_canonical(planes):
        flats = [p.reshape(-1)[:n] for p in planes]
        if pushsum:
            return pushsum_mod.PushSumState(
                s=flats[0], w=flats[1], term=flats[2], conv=flats[3] != 0
            )
        return gossip_mod.GossipState(
            count=flats[0], active=flats[1] != 0, conv=flats[2] != 0
        )

    if probe is not None:
        return probe(chunk_sharded, (
            planes0, rnd0, done0_dev,
            rep_put(np.int32(min(start_round + 1, cfg.max_rounds))),
            kd_dev,
        ), donate=donate)

    if dma and backend != "tpu":
        raise ValueError(
            "halo_dma='on' builds the in-kernel async-remote-copy halo "
            "program, which only EXECUTES on TPU backends (the Pallas "
            "interpreter has no inter-device DMA); use halo_dma='auto' "
            "for the batched-ppermute wire here, or trace the DMA program "
            "hardware-free through the probe hook (benchmarks/comm_audit)"
        )

    t0 = time.perf_counter()
    warm = chunk_sharded(
        tuple(jnp.copy(p) for p in planes0) if donate else planes0,
        rnd0, done0_dev,
        rep_put(np.int32(min(start_round + 1, cfg.max_rounds))),
        kd_dev,
    )
    int(warm[1])
    del warm
    compile_s = time.perf_counter() - t0

    watchdog = StallWatchdog(cfg.stall_chunks)

    def dispatch(planes, rnd, done, round_end):
        return chunk_sharded(
            planes, rnd, done, rep_put(np.int32(round_end)), kd_dev
        )

    on_retire = None
    if on_chunk is not None:
        def on_retire(rounds, planes):
            on_chunk(rounds, to_canonical(planes))

    should_stop = None
    if cfg.stall_chunks:
        # This composition rejects failure models (plan gate), so the
        # progress gap is the plain target − conv-count distance; gossip
        # conv is stored (plane 2), push-sum conv is plane 3.
        def should_stop(rounds, planes):
            if pushsum:
                conv = planes[3]
            else:
                conv = (planes[0] >= rumor_target).astype(jnp.int32)
            return watchdog.no_progress(
                _progress_gap(None, cfg.quorum, target, conv, rounds)
            )

    t1 = time.perf_counter()
    loop = pipeline_mod.run_chunks(
        dispatch=dispatch, state0=planes0, rnd0=rnd0, done0=done0_dev,
        start_round=start_round, max_rounds=cfg.max_rounds,
        stride=8, depth=cfg.pipeline_chunks, donate=donate,
        on_retire=on_retire, should_stop=should_stop,
        should_cancel=_cancel_fn(deadline),
        step_timing=cfg.step_timing,
        hook_error=("raise" if cfg.strict_checkpoint else "continue"),
    )
    run_s = time.perf_counter() - t1

    return _finalize_result(
        topo, cfg, to_canonical(loop.state), loop.rounds, target,
        compile_s, run_s, done=loop.done, stalled=watchdog.stalled,
        cancelled=loop.cancelled,
    )


# --- Declared wire contract (analysis/wire_specs.py) -----------------------
# Per SUPER-STEP on the XLA wire: ONE batched halo pair for the lattice
# classes + ONE all_gather of the pooled long-range classes' windowed send
# summaries + the deferred verdict psum — zero stragglers. Serial pays a
# pair per state plane and a gather per send window. Batched setup =
# pre-loop exchange pair + pre-loop gather + drain psum. With
# halo_dma='on' the lattice halo moves in-kernel (one async remote copy
# per plane per ring direction, same bytes as the pair) while the pooled
# long-range wire stays the ONE all_gather.
WIRE_SPEC = WireSpec(
    engine="imp-hbm-sharded",
    variants={
        ("overlap", "wire"): Regions(
            body={
                "ppermute": C(fixed=2), "all_gather": C(fixed=1),
                "psum": C(fixed=1),
            },
            setup={
                "ppermute": C(fixed=2), "all_gather": C(fixed=1),
                "psum": C(fixed=1),
            },
        ),
        ("serial", "wire"): Regions(
            body={
                "ppermute": C(per_plane=2), "all_gather": C(per_window=1),
                "psum": C(fixed=1),
            },
            setup={},
        ),
        ("overlap", "dma"): Regions(
            body={
                "remote_dma": C(per_plane=2), "all_gather": C(fixed=1),
                "psum": C(fixed=1),
            },
            setup={"all_gather": C(fixed=1), "psum": C(fixed=1)},
        ),
        ("serial", "dma"): Regions(
            body={
                "remote_dma": C(per_plane=2), "all_gather": C(per_window=1),
                "psum": C(fixed=1),
            },
            setup={},
        ),
    },
    mechanism={"wire": "xla-ppermute", "dma": "in-kernel-dma"},
    equal_bytes=("ppermute", "all_gather"),
    dma_bytes_match="ppermute",
)
