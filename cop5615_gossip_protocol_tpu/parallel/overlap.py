"""Collective/compute overlap for the sharded super-step compositions.

The serial super-step schedule of the fused x sharded lattice compositions
(parallel/fused_sharded.py, parallel/fused_hbm_sharded.py) put BOTH
collectives on the critical path between kernel launches:

    exchange halos -> kernel (CR rounds) -> psum verdict -> cond
         ^------------- next super-step waits here -------------'

so inter-device traffic serialized against the tile-streaming grid — the
measured 2.30x ms/round gap of the HBM x sharded composition against the
single-device streamed engine (tests_tpu/test_fused_hbm_sharded_compiled.py
budget history). This module restructures the schedule along the overlap
discipline of distributed training stacks (PAPERS.md: Ring Attention's
ring-exchange overlap; Wang et al.'s decomposition-for-overlap):

1. **Batched halo wires** — the exchange arrives here already packed into
   one ppermute pair for ALL planes (parallel/halo.exchange_rows_batched):
   a super-step issues one wire volley, not a pair per plane per class.

2. **Double-buffered extended ring** — the loop carries the halo-EXTENDED
   planes for the next super-step next to the retired mid planes of the
   last one. The exchange for super-step k+1 is issued immediately after
   super-step k's kernel writes its planes — adjacent in the schedule,
   writing the inactive ring copy — so the only thing between kernel k and
   kernel k+1 is the wire itself; everything else has moved off that edge.

3. **Off-critical-path termination** — the converged-count psum for
   super-step k is folded into super-step k+1's body: the verdict for k is
   reduced WHILE k+1's kernel runs (the two are data-independent, which is
   what lets the scheduler overlap them), a one-super-step verdict lag.
   ``rounds`` stays EXACT via the same double buffer: when the deferred
   verdict fires, the in-flight speculative super-step is discarded
   unobserved and the loop returns the retired mid planes and round counter
   of the verdict's own super-step — bitwise the serial schedule's exit
   state (the models/pipeline.py overshoot idea, one level down). The last
   pending verdict of a dispatch is drained after the loop, so the chunk's
   returned ``done`` flag is never stale across dispatches.

All three are pure scheduling: every kernel consumes exactly the operands
the serial schedule feeds it, so trajectories stay bitwise-identical to the
single-device engines (tests/test_overlap.py pins the loop against the
serial schedule; the existing parity suites pin the compositions against
the single-device engines with the overlap schedule ON).

A note on tile order: the ideal schedule also overlaps the halo wire with
the kernel's INTERIOR tiles (interior-first tile order, so only the
boundary tiles wait on the in-flight halo). At the XLA graph boundary a
`pallas_call` is one atomic op — a consumer cannot observe partial
outputs — so within-kernel tile reordering cannot release an XLA wire
early; issuing the batched exchange ADJACENT to the kernel output (this
module) is the implementable form of that idea for the XLA transport.
ISSUE 9 lands the full form for the HBM-streaming composition: the wires
move INTO the kernel as `pltpu.make_async_remote_copy` neighbor DMA
(parallel/fused_hbm_sharded.py, cfg.halo_dma), the super-step schedule
hands the halo slot to the kernel — ``exchange`` degenerates to the
identity below, the kernel owns the transfer — and round 0 of each
super-step streams its interior tiles in _visit_order while the neighbor
copies are in flight, waiting only before the first boundary tile. Zero
XLA collectives remain on that halo path (benchmarks/comm_audit.py pins
the mechanism per composition); the deferred verdict psum of this module
is unchanged and still rides under the next super-step's kernel.

Cost: one speculative super-step of kernel work is wasted per converged
run; the carry holds one extra copy of the mid planes; and each DISPATCH
pays one redundant exchange volley — the pre-loop exchange recomputes what
the previous dispatch's last body iteration produced and dropped (the
final ``ext_next`` at a round_end exit is equally unobserved), so N
super-steps cost N+1 volleys, ~1/N extra wire volume at the default
8-super-step stride. Deliberate: carrying the extended ring ACROSS
dispatches would put rows_ext-shaped planes into the pipelined driver's
dispatch contract (models/pipeline.py) and grow every engine's
checkpoint/resume surface for a boundary-only saving that the drain psum
already overlaps; benchmarks/comm_audit.py reports the volley under
"setup collectives" so the cost stays visible. termination='global' keeps
the serial schedule: its verdict can demand a capped RErun of the same
chunk (parallel/fused_sharded.global_verdict_step), which needs the
chunk's input still at hand — deferring it would mean carrying two
extended generations.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def overlapped_superstep_loop(
    planes_in, rnd_in, done_in, round_end, *, exchange, compute, psum_metric,
    target,
):
    """Run super-steps to ``round_end`` with the deferred-verdict schedule.

    ``exchange(planes) -> ext``: halo-extend mid planes (the batched wire).
    ``compute(ext, rnd, cap) -> (mid, executed, metric)``: one super-step —
    up to CR rounds; ``metric`` is the LOCAL termination contribution of the
    last executed round (per-shard middle-region converged count).
    ``psum_metric(metric) -> total``: the cross-device reduction.
    ``target``: the verdict fires when the reduced metric reaches it.

    Returns ``(planes, rnd, done)`` with the exact semantics of the serial
    loop: ``planes``/``rnd`` are the state and round counter of the LAST
    super-step at/before the verdict, and ``done`` reflects the verdict of
    the last executed super-step (drained before returning, never deferred
    across dispatches). A call at ``done_in`` or ``rnd_in >= round_end``
    executes zero super-steps and is a bitwise no-op on the planes — the
    overshoot contract the pipelined driver (models/pipeline.py) relies on.
    """
    zero_metric = jnp.int32(0)  # psums below any target (targets are >= 1)

    def cond(c):
        _, _, rnd, _, done = c
        return jnp.logical_and(~done, rnd < round_end)

    def body(c):
        ext, mid_prev, rnd, pend, _ = c
        # Speculative kernel for this super-step and the deferred verdict
        # for the previous one are data-independent: the reduction rides
        # UNDER the kernel instead of between two kernels.
        mid, executed, metric = compute(ext, rnd, round_end)
        fired = psum_metric(pend) >= target
        # Next super-step's wires, issued adjacent to the kernel output —
        # the inactive ring copy of the double buffer. Unused when the
        # verdict fired (the loop exits), like any overshoot work.
        ext_next = exchange(mid)
        mid_keep = tuple(
            jnp.where(fired, a, b) for a, b in zip(mid_prev, mid)
        )
        rnd_keep = jnp.where(fired, rnd, rnd + executed)
        pend_keep = jnp.where(fired, zero_metric, metric.astype(jnp.int32))
        return (ext_next, mid_keep, rnd_keep, pend_keep, fired)

    ext0 = exchange(planes_in)
    ext_f, mid_f, rnd_f, pend_f, done_f = lax.while_loop(
        cond, body, (ext0, tuple(planes_in), rnd_in, zero_metric, done_in)
    )
    # Drain: the last super-step's verdict is still pending when the loop
    # exits at round_end; a fired exit zeroed its pend, so the extra psum
    # is inert there. One reduction per dispatch, not per super-step.
    done_final = done_f | (psum_metric(pend_f) >= target)
    return mid_f, rnd_f, done_final
