"""Simulation configuration.

The reference has no config system: three raw positional CLI args
(program.fs:19-21) and hard-coded constants — rumor threshold 10
(program.fs:102), delta = 1e-10 (program.fs:187, 223, 263, 328), push-sum
termination rounds C = 3 (program.fs:135). This module lifts all of those into
one frozen dataclass, adds the knobs a real framework needs (seed, dtype,
mesh, fault injection, checkpointing cadence), and resolves the
dtype-dependent precision policy: push-sum at delta = 1e-10 requires float64,
which is emulated/slow on TPU, so under float32 the default delta is rescaled
(SURVEY.md §5 "Config / flag system").

Two fidelity modes (SURVEY.md §7 design stance):

- ``semantics="batched"`` — honest synchronous rounds, all nodes active: the
  performant mode the benchmarks measure.
- ``semantics="reference"`` — replicates the reference's observable quirks
  (SURVEY.md §2 Q1-Q9) for apples-to-apples validation at small N: N+1
  population with target N (Q1), gossip convergence on the 11th receipt (Q2),
  push-sum termRound starting at 1 (Q4), "2D" wired as a line (Q6), Imp3D
  rounding/orphans/random-extra (C3, Q8, Q9), and single-walk push-sum
  (one message in flight, SURVEY.md §3.3).
"""

from __future__ import annotations

import dataclasses

# Canonical topology kinds. CLI-parity spellings ("2D", "Imp3D") are
# normalized by `normalize_topology`.
TOPOLOGIES = (
    "line",  # program.fs:151-171 — path graph, ends have one neighbor
    "ring",  # line with wraparound (new; degree-regular variant)
    "full",  # program.fs:191-206 — complete graph, represented implicitly
    "grid2d",  # honest 2D 4-neighborhood grid (what the reference "2D" claims to be)
    "ref2d",  # the reference's actual "2D": N rounded up to a square, wired as a line (Q6)
    "imp2d",  # 2D grid + one random long-range edge per node (BASELINE.json configs)
    "grid3d",  # 3D 6-neighborhood grid
    "torus3d",  # 3D grid with wraparound — degree-regular 6 (BASELINE.json 10M config)
    "imp3d",  # program.fs:267-313 — 3D grid + one random extra neighbor
)

ALGORITHMS = ("gossip", "push-sum")
SEMANTICS = ("batched", "reference")

# Replica-sweep / serving-batch lane cap (models/sweep.py re-exports it):
# bounds the REPLICA_TAG0 fold_in region — see the TAG MAP in ops/faults.py.
# Lives here so SimConfig.__post_init__ can validate `replicas` without
# importing the sweep engine.
MAX_REPLICAS = 4096

_CLI_TOPOLOGY_ALIASES = {
    "line": "line",
    "ring": "ring",
    "full": "full",
    "2d": "grid2d",  # honest mode; reference semantics swaps this to ref2d
    "grid2d": "grid2d",
    "ref2d": "ref2d",
    "imp2d": "imp2d",
    "3d": "grid3d",
    "grid3d": "grid3d",
    "torus3d": "torus3d",
    "imp3d": "imp3d",
}

_CLI_ALGORITHM_ALIASES = {
    "gossip": "gossip",
    "push-sum": "push-sum",
    "pushsum": "push-sum",
    "push_sum": "push-sum",
}


def normalize_topology(name: str, semantics: str = "batched") -> str:
    """Map a CLI topology spelling to a canonical kind.

    The reference CLI accepts {line, full, 2D, Imp3D} (program.fs:150). In
    reference semantics "2D" maps to ``ref2d`` — the line-wired grid the
    reference actually builds (program.fs:242-248) — while in batched
    semantics it maps to the honest ``grid2d``.
    """
    key = name.strip().lower()
    if key not in _CLI_TOPOLOGY_ALIASES:
        raise ValueError(
            f"unknown topology {name!r}; expected one of "
            f"{sorted(set(_CLI_TOPOLOGY_ALIASES))}"
        )
    kind = _CLI_TOPOLOGY_ALIASES[key]
    if kind == "grid2d" and semantics == "reference" and key == "2d":
        return "ref2d"
    return kind


def normalize_algorithm(name: str) -> str:
    key = name.strip().lower()
    if key not in _CLI_ALGORITHM_ALIASES:
        raise ValueError(
            f"unknown algorithm {name!r}; expected one of {sorted(set(_CLI_ALGORITHM_ALIASES))}"
        )
    return _CLI_ALGORITHM_ALIASES[key]


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Full description of one simulation run.

    ``n`` is the *requested* node count; topology builders may round it
    (2D up to a square, program.fs:228-229; Imp3D down to a cube,
    program.fs:27-31) and, in reference semantics, add the extra actor of
    quirk Q1. The actual population lives on the built Topology.
    """

    n: int
    topology: str = "full"
    algorithm: str = "gossip"
    semantics: str = "batched"
    seed: int = 0

    # Precision policy (SURVEY.md §7 hard part 2).
    dtype: str = "float32"
    delta: float | None = None  # push-sum stability threshold; None → per-dtype default

    rumor_threshold: int = 10  # program.fs:102
    term_rounds: int = 3  # program.fs:135

    max_rounds: int = 1_000_000
    chunk_rounds: int = 4096  # rounds per jit'd while_loop chunk (checkpoint/metrics cadence)

    # Speculative chunk pipelining depth (models/pipeline.py): how many
    # chunks the host keeps in flight — chunk k+1 is dispatched before
    # chunk k's termination predicate is read, hiding the per-dispatch
    # launch floor under compute. 1 = the serial loop. Bitwise-neutral by
    # the overshoot contract (a chunk dispatched past termination is a
    # no-op), pinned per engine by tests/test_pipeline.py; a loop-control
    # knob like chunk_rounds, so resume accepts a changed value.
    pipeline_chunks: int = 2

    # Collective/compute overlap for the sharded engines (parallel/halo.py
    # batched wires + parallel/overlap.py deferred-verdict super-steps):
    # on (default) packs every plane's/class's halo slices into ONE
    # ppermute pair (or one all_gather) per round/super-step and folds the
    # fused compositions' termination psum under the next super-step's
    # kernel; off restores the serial per-plane/per-class schedule. Pure
    # scheduling — trajectories are bitwise-identical either way
    # (tests/test_overlap.py), so resume accepts a changed value like the
    # other loop-control knobs. benchmarks/comm_audit.py pins the
    # per-super-step collective counts both ways.
    overlap_collectives: bool = True

    # In-kernel halo delivery for the HBM-streaming x sharded composition
    # (parallel/fused_hbm_sharded.py): "auto" (default) moves the
    # super-step halo exchange INTO the Pallas kernel as
    # pltpu.make_async_remote_copy neighbor DMA on TPU backends — zero XLA
    # collectives on the halo path, boundary-tile DMA overlapped with
    # interior tile streaming — and keeps the batched-ppermute wire
    # (parallel/halo.py) on CPU/interpret backends, where Pallas remote
    # DMA cannot execute. "on" forces the DMA kernel (TPU execution only;
    # CPU builds may still TRACE it — benchmarks/comm_audit.py audits the
    # DMA program hardware-free that way); "off" pins the XLA wire
    # everywhere. Both transports feed the kernels identical halo bytes,
    # so trajectories are bitwise transport-invariant; the knob changes
    # the traced program (it is part of the serving compile class), but
    # resume accepts a changed value like the other scheduling knobs.
    halo_dma: str = "auto"

    # Fraction of population that must converge. None → 1.0 in batched mode;
    # in reference semantics the builder's target_count (N of N+1, Q1) rules.
    target_frac: float | None = None

    # Gossip: skip sends whose target already converged (the reference's racy
    # shared dictionary, program.fs:92, made race-free as a read of last
    # round's converged vector). None → True in reference semantics.
    suppress_converged: bool | None = None

    # --- failure model (ops/faults.py is the semantics home) -------------
    # Per round, each node fails to send with this probability — the send
    # drop gate (SURVEY.md §5 "Failure detection"; ops/sampling.send_gate).
    fault_rate: float = 0.0

    # Crash-stop node death: with crash_rate p every node independently
    # survives each round with probability 1-p (geometric death round);
    # crash_schedule "round:count,..." kills exactly count uniformly random
    # nodes at each listed round instead. Dead nodes neither send nor
    # advance protocol state; push-sum mass parks on them (conserved).
    crash_rate: float = 0.0
    crash_schedule: str | None = None

    # Crash-recovery (ops/faults.revival_plane): with revive_rate p every
    # DEAD node independently rejoins each round with probability p
    # (geometric dead-time, revival >= death + 1); revive_schedule
    # "round:count,..." rejoins exactly count uniformly random dead nodes
    # at each listed round instead. Requires a crash model (there is
    # nothing to revive otherwise — hard error).
    revive_rate: float = 0.0
    revive_schedule: str | None = None

    # Push-sum rejoin semantics (gossip revivals always rejoin susceptible
    # with count 0): "restore" — the node reclaims its parked (s, w) mass
    # (total mass over live + dead + parked conserved, the crash-stop
    # invariant extended); "fresh" — the node resets to (s=x_i, w=0),
    # discarding parked mass and re-creating its value (the modeled fault:
    # conservation intentionally breaks, like dup_rate).
    rejoin: str = "restore"

    # Byzantine adversaries (ops/faults.byzantine_plane, the third seeded
    # plane): with byzantine_rate F each node independently turns
    # adversarial from round 0 with probability F; byzantine_schedule
    # "round:count,..." turns exactly count uniformly random distinct
    # nodes at each listed round instead. Adversaries are ALIVE — they
    # send every round and count toward the quorum's live set (lying
    # about convergence is part of the attack surface; quorum < 1.0 is a
    # legitimate countermeasure for gossip stale_rumor). Chunked engine
    # first-class plus the fused stencil/pool kernels; every other
    # composition refuses loudly.
    byzantine_rate: float = 0.0
    byzantine_schedule: str | None = None

    # Adversary behavior. Push-sum modes corrupt the sent (s, w) WIRE pair
    # (the node's own kept state follows the honest update, so corruption
    # is purely what neighbors receive): "mass_inflate" — the sent pair is
    # the UNHALVED state (a copy of the node's mass is injected every
    # round; the ratio is preserved, so the run converges to a biased
    # estimate unless the sentinel or robust_agg intervenes);
    # "mass_deflate" — the sent pair negated (mass drained);
    # "garble" — the s/w channels swapped (finite, NaN-free garbage).
    # Gossip modes corrupt protocol STATE: "stale_rumor" — perpetual rumor
    # re-injection after local convergence (count pinned 0, active pinned
    # 1 — the node spams forever and never converges); "garble" — fake
    # convergence reported to the termination predicate (conv latched 1
    # regardless of receipts). Mode x algorithm validity is enforced at
    # config time.
    byzantine_mode: str = "mass_inflate"

    # Robust-aggregation countermeasure (push-sum, chunked engine):
    # bounds the per-round contributions a RECEIVER accepts. "clip" —
    # each received (s, w) pair is scaled down to a dynamic envelope (cap
    # proportional to the receiver's own kept weight; negative-w
    # contributions are zeroed), pair-consistent so honest ratios pass
    # through unchanged; "trim" — drop the single largest-|w| per-slot
    # contribution channel before absorbing (pool delivery only: the pool
    # tier's sampled contributions arrive as pool_size distinct
    # channels); "none" (default) accepts everything. Clip/trim DISCARD
    # mass by design, so robust_agg excludes mass_tolerance (like
    # dup_rate does).
    robust_agg: str = "none"

    # Per round, each sent message is additionally delivered twice with
    # this probability — at-least-once delivery. For push-sum duplicated
    # mass is CREATED (total mass inflates by the duplicate): that loss of
    # conservation is the fault being modeled, not a bug. Chunked engine,
    # scatter/stencil delivery only.
    dup_rate: float = 0.0

    # Bounded message delay: every round's delivered planes are deferred
    # through a ring of this depth before being absorbed — in-flight mass
    # lives in the ring (conservation holds over state + ring). Chunked
    # engine, scatter/stencil delivery only.
    delay_rounds: int = 0

    # Fraction of LIVE nodes that must be converged to end a crash-model
    # run: sum(conv & alive) >= quorum_need(sum(alive), quorum)
    # (ops/faults.quorum_need). Only meaningful with a crash model — the
    # legacy converged_count >= target predicate rules otherwise.
    # Byzantine nodes COUNT AS LIVE here: adversaries keep sending, so
    # excluding them from the live set would let the quorum predicate
    # silently neutralize stale_rumor/garble attacks the campaign is
    # measuring.
    quorum: float = 1.0

    # Stall watchdog: terminate with outcome="stalled" after this many
    # consecutive chunks with no progress in the converged count (the
    # reference's line-topology hang, program.fs:334, as a measured event).
    # 0 disables.
    stall_chunks: int = 0

    # Health sentinel (push-sum, chunked/sharded XLA engines): when set,
    # every round body additionally reduces a non-finite flag over (s, w)
    # and the mass-conservation residual |Σw − population| against this
    # tolerance; the first round either trips ends the run with
    # outcome="unhealthy" and the offending round in
    # RunResult.unhealthy_round — silent numerical corruption becomes a
    # structured outcome instead of converging wrong or spinning to
    # max_rounds. None (default) traces the bitwise-identical program
    # without the checks (a Python-level flag, like telemetry). The fused
    # tiers do not carry the sentinel: engine='auto' demotes to chunked,
    # engine='fused' rejects loudly.
    mass_tolerance: float | None = None

    # Fail-fast engine selection: disable models/runner.py's graceful
    # degradation ladder (fused→chunked, sharded→single-device on
    # environmental failures) and re-raise the first engine error — the
    # pre-recovery-plane behavior. The GOSSIP_TPU_STRICT_ENGINE env var
    # ("1"/"0") overrides this flag either way (scripts/tier1.sh exports 1
    # so CI never silently degrades).
    strict_engine: bool = False

    # Fail-fast checkpoint I/O: an OSError inside the chunk-boundary
    # checkpoint hook (full disk, torn mount) aborts the run instead of
    # the default lose-one-interval-and-continue policy
    # (models/pipeline.run_chunks hook_error; ISSUE 19). Python-level
    # loop knob like strict_engine — never part of the traced program,
    # exempt from the resume config-mismatch check.
    strict_checkpoint: bool = False

    # In-program telemetry plane (ops/telemetry.py): the chunk program
    # accumulates one per-round counter row (converged/live counts, quorum
    # gap, active count or estimate MAE, mass residual, drop/dup events) on
    # device and returns the block alongside the termination predicate, so
    # full per-round trajectories stream out of the pipelined, donated
    # engines with no extra host syncs. A Python-level flag: off (default)
    # traces the bitwise-identical program as a build without the plane.
    # Supported by the chunked, sharded, fused stencil/pool, and replica-
    # sweep engines; the streaming HBM tiers and sharded fused compositions
    # reject it.
    telemetry: bool = False

    # Per-super-step runtime attribution (ISSUE 18): when on, the chunk
    # driver (models/pipeline.run_chunks) additionally stamps a
    # perf_counter retire timestamp + retire-to-retire wall on every
    # chunk_log entry — clock-only host reads at boundaries the driver
    # already observes, so donation and speculative pipelining are
    # untouched and the off state traces the bitwise-identical program
    # (a Python-level flag like telemetry). pipeline.step_timing_report
    # turns the log into the measured-vs-predicted table the autotuner's
    # calibration is judged against (analysis/cost.measured_vs_predicted,
    # trend.py --step-timing). The sharded FUSED compositions refuse it
    # under cfg.overlap_collectives: their super-step loop defers each
    # termination psum under the next kernel (parallel/overlap.py), and
    # per-step timing there would force the deferred verdict to drain —
    # a host sync inside the overlap window.
    step_timing: bool = False

    # Round engine: "chunked" = jit'd lax.while_loop dispatching one fused
    # XLA round program per round; "fused" = the Pallas multi-round kernel
    # (ops/fused.py — whole chunks of rounds with VMEM-resident state and
    # in-kernel threefry, offset-structured topologies, float32, n <= ~128k);
    # "auto" = fused on TPU where eligible, else chunked.
    engine: str = "auto"

    # Plan selection policy (ISSUE 17). "hand" (default) = the runner's
    # maintained dispatch ladder picks the engine/composition/wire.
    # "auto" = the measured cost model (analysis/cost.py) enumerates the
    # legal candidates the refusal rules admit, scores each from the
    # calibrated floors in analysis/calibration.json (regenerate with
    # `python benchmarks/suite.py --autotune`), and the runner executes
    # the winner — logging a structured `plan-chosen` event with the
    # ranked table. The hand rules stay the oracle: tests pin that the
    # autotuner reproduces the ladder's choice on every BENCH/serving
    # cell under the committed calibration.
    plan: str = "hand"

    # Delivery strategy: "scatter" = scatter-add (any topology), "stencil" =
    # masked circular shifts (offset-structured topologies only — line, ring,
    # grids, tori; ops/topology.stencil_offsets), "pool" = offset-pool
    # sampling on the implicit full topology (each round draws pool_size
    # shared uniform displacements; delivery is pool_size masked rolls — no
    # scatter/sort; partner marginals stay uniform, draws within a round are
    # correlated: ops/sampling.pool_offsets), "matmul" = the MXU tier: the
    # SAME pooled sampling stream as "pool" (identical choices/offsets, so
    # trajectories are stream-identical) with delivery recast as a blocked
    # one-hot dot_general (ops/delivery.deliver_matmul; the fused pool
    # kernels execute the lane blend as 128x128 one-hot MXU tiles) —
    # gossip inboxes are bitwise the pool path's (integer-exact sums),
    # push-sum reassociates within the documented float contract; "auto" =
    # stencil where the topology supports it, else scatter.
    delivery: str = "auto"

    # Offset-pool width for delivery="pool". Power of two so the per-node
    # slot choice is exact uniform bits (no modulo bias). 4 measures fastest
    # at 1M nodes on v5e (fewer rolls) with no convergence penalty
    # (tests/test_pool.py; chunked-path sweep r2: K=4 -> 0.54s, K=8 -> 1.18s,
    # K=16 -> 1.81s wall, all mae ~0.028; the fused pool engine
    # (ops/fused_pool.py) takes the 1M wall to ~0.16s at K=4 and supports
    # K <= 16, the packed-choice 4-bit budget).
    pool_size: int = 4

    # Sharding: number of mesh devices for the node dimension; None/1 → single device.
    n_devices: int | None = None

    # Delivery wire of the replicated-pool2 composition
    # (parallel/pool2_sharded.py): "all_gather" replicates the compact
    # windowed send summaries on every device each round — O(N) received
    # bytes and resident copy per device, the gather-bound wall; "
    # "reduce_scatter" delivers each device only the O(N/P) summary band
    # its own windows consume plus the pooled margins (one banded
    # reduce_scatter per pool slot + one margin ppermute volley) — a pure
    # reorganization of who holds which rows, so trajectories are BITWISE
    # the gather wire's (tests/test_pool2_sharded.py pins it at 2 and 4
    # devices). "auto" (default) picks reduce_scatter when the mesh is
    # wider than the pool (n_devices > pool_size — each band is then
    # strictly smaller than the gathered copy) and the gather wire
    # otherwise. Part of the serving compile class like halo_dma; resume
    # accepts a changed value (pure wire packaging).
    pool2_wire: str = "auto"

    # Vmapped replica sweep (models/sweep.py, --replicas): run this many
    # seeds of the configuration as lanes of ONE chunked program. 1 = the
    # plain single run. A config-level field (not just a CLI flag) so the
    # sweep engine's support contract fails at CONFIG time — before any
    # topology build — instead of deep in models/sweep._reject_unsupported.
    replicas: int = 1

    # Push-sum termination criterion. "local" is the reference's own
    # (program.fs:119-137): each node latches converged after term_rounds
    # consecutive sub-delta receipt rounds — local stability, which on
    # slow-mixing graphs latches early/late relative to true equilibrium and
    # at torus scale spends tens of thousands of rounds on stragglers.
    # "global" stops when max over nodes of the per-round ratio change
    # |Δ(s/w)| is <= delta — the honest global-residual rule (the same
    # quantity --trace-convergence reports per chunk); every node is then
    # declared converged at once.
    termination: str = "local"

    def __post_init__(self) -> None:
        if self.n <= 0:
            raise ValueError(f"n must be positive, got {self.n}")
        if self.topology not in TOPOLOGIES:
            raise ValueError(
                f"unknown topology {self.topology!r}; expected one of {TOPOLOGIES}"
            )
        if self.algorithm not in ALGORITHMS:
            raise ValueError(
                f"unknown algorithm {self.algorithm!r}; expected one of {ALGORITHMS}"
            )
        if self.semantics not in SEMANTICS:
            raise ValueError(
                f"unknown semantics {self.semantics!r}; expected one of {SEMANTICS}"
            )
        if self.dtype not in ("float32", "float64", "bfloat16"):
            raise ValueError(f"unsupported dtype {self.dtype!r}")
        if self.term_rounds < 1:
            raise ValueError("term_rounds must be >= 1")
        if self.rumor_threshold < 1:
            raise ValueError("rumor_threshold must be >= 1")
        if not (0.0 <= self.fault_rate < 1.0):
            raise ValueError("fault_rate must be in [0, 1)")
        if not (0.0 <= self.crash_rate < 1.0):
            raise ValueError("crash_rate must be in [0, 1)")
        if not (0.0 <= self.dup_rate < 1.0):
            raise ValueError("dup_rate must be in [0, 1)")
        if self.crash_schedule is not None:
            if self.crash_rate > 0:
                raise ValueError(
                    "crash_rate and crash_schedule are mutually exclusive "
                    "(the schedule IS the death process)"
                )
            from .ops.faults import parse_crash_schedule

            parse_crash_schedule(self.crash_schedule)  # fail at config time
        if not (0.0 <= self.revive_rate < 1.0):
            raise ValueError("revive_rate must be in [0, 1)")
        if self.revive_schedule is not None:
            if self.revive_rate > 0:
                raise ValueError(
                    "revive_rate and revive_schedule are mutually exclusive "
                    "(the schedule IS the recovery process)"
                )
            from .ops.faults import parse_schedule

            parse_schedule(self.revive_schedule, "revive")  # same grammar
        if self.revive_model and not self.crash_model:
            raise ValueError(
                "revive_rate/revive_schedule describe how CRASHED nodes "
                "rejoin; without crash_rate/crash_schedule there is nothing "
                "to revive — the flags would silently mean nothing"
            )
        if self.rejoin not in ("restore", "fresh"):
            raise ValueError(
                f"unknown rejoin {self.rejoin!r}; expected restore|fresh"
            )
        if not (0.0 <= self.byzantine_rate < 1.0):
            raise ValueError("byzantine_rate must be in [0, 1)")
        if self.byzantine_schedule is not None:
            if self.byzantine_rate > 0:
                raise ValueError(
                    "byzantine_rate and byzantine_schedule are mutually "
                    "exclusive (the schedule IS the adversary onset process)"
                )
            from .ops.faults import parse_schedule

            parse_schedule(self.byzantine_schedule, "byzantine")  # same grammar
        if self.byzantine_mode not in (
            "mass_inflate", "mass_deflate", "stale_rumor", "garble"
        ):
            raise ValueError(
                f"unknown byzantine_mode {self.byzantine_mode!r}; expected "
                "mass_inflate|mass_deflate|stale_rumor|garble"
            )
        if self.byzantine_model:
            valid_modes = (
                ("mass_inflate", "mass_deflate", "garble")
                if self.algorithm == "push-sum"
                else ("stale_rumor", "garble")
            )
            if self.byzantine_mode not in valid_modes:
                raise ValueError(
                    f"byzantine_mode {self.byzantine_mode!r} does not apply "
                    f"to algorithm {self.algorithm!r}: push-sum adversaries "
                    "corrupt the sent (s, w) wire pair "
                    "(mass_inflate|mass_deflate|garble); gossip adversaries "
                    "corrupt protocol state (stale_rumor|garble)"
                )
        if self.robust_agg not in ("none", "clip", "trim"):
            raise ValueError(
                f"unknown robust_agg {self.robust_agg!r}; expected "
                "none|clip|trim"
            )
        if self.robust_agg != "none":
            if self.algorithm != "push-sum":
                raise ValueError(
                    "robust_agg bounds the push-sum (s, w) contributions a "
                    "receiver accepts; gossip receipts carry no mass to "
                    "clip or trim"
                )
            if self.mass_tolerance is not None:
                raise ValueError(
                    "robust_agg contradicts mass_tolerance: clip/trim "
                    "DISCARD suspect mass by design, so the conservation "
                    "sentinel would trip on the countermeasure, not "
                    "corruption"
                )
            if self.robust_agg == "trim" and self.delivery != "pool":
                raise ValueError(
                    "robust_agg='trim' drops the largest-|w| channel among "
                    "the pool tier's per-slot sampled contributions; other "
                    "deliveries accumulate a single inbox with no channels "
                    "to trim — use delivery='pool' or robust_agg='clip'"
                )
            if self.robust_agg == "trim" and self.topology != "full":
                raise ValueError(
                    "robust_agg='trim' applies to the implicit full "
                    "topology's uniform pool-slot channels; the imp "
                    "lattice+pool delivery mixes channel classes with no "
                    "single slot order to trim over — use robust_agg='clip'"
                )
        if not (0 <= self.delay_rounds <= 64):
            raise ValueError(
                f"delay_rounds must be in [0, 64], got {self.delay_rounds} "
                "(the ring buffer holds delay_rounds full delivery planes)"
            )
        if not (0.0 < self.quorum <= 1.0):
            raise ValueError(f"quorum must be in (0, 1], got {self.quorum}")
        # Valid-but-suspect combinations (a silent no-op is not an invalid
        # config — sweep drivers reuse a quorum across faulted and
        # fault-free cells): lint_warnings is the single source of the
        # conditions and texts; warn here for API users, while the CLI
        # prints the same strings to stderr and stamps them into the
        # run-start event.
        for lint in self.lint_warnings:
            import warnings

            warnings.warn(lint, RuntimeWarning, stacklevel=2)
        if self.halo_dma not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown halo_dma {self.halo_dma!r}; expected auto|on|off"
            )
        if self.pool2_wire not in ("auto", "reduce_scatter", "all_gather"):
            raise ValueError(
                f"unknown pool2_wire {self.pool2_wire!r}; expected "
                "auto|reduce_scatter|all_gather"
            )
        if self.stall_chunks < 0:
            raise ValueError("stall_chunks must be >= 0")
        if self.mass_tolerance is not None:
            if self.mass_tolerance <= 0:
                raise ValueError(
                    f"mass_tolerance must be > 0, got {self.mass_tolerance}"
                )
            if self.algorithm != "push-sum":
                raise ValueError(
                    "mass_tolerance watches the push-sum conservation "
                    "invariant Σw == population; gossip state has no mass "
                    "to diverge"
                )
            if self.dup_rate > 0:
                raise ValueError(
                    "mass_tolerance contradicts dup_rate: at-least-once "
                    "delivery CREATES mass by design, so the sentinel "
                    "would trip on the modeled fault, not corruption"
                )
            if self.revive_model and self.rejoin == "fresh":
                raise ValueError(
                    "mass_tolerance contradicts rejoin='fresh': fresh "
                    "revivals discard parked mass and re-create their "
                    "value by design — use rejoin='restore' (conserving) "
                    "with the sentinel"
                )
            if self.semantics == "reference":
                raise ValueError(
                    "mass_tolerance runs inside the synchronous chunk "
                    "program; reference-semantics push-sum is a single "
                    "random walk with no round body — use batched semantics"
                )
        if (
            self.telemetry
            and self.semantics == "reference"
            and self.algorithm == "push-sum"
        ):
            raise ValueError(
                "telemetry accumulates per-ROUND counters inside the "
                "synchronous chunk program; reference-semantics push-sum is "
                "a single random walk (one message in flight) with no round "
                "structure to trace — use batched semantics"
            )
        if self.semantics == "reference" and (
            self.crash_model
            or self.dup_rate > 0
            or self.delay_rounds > 0
            or self.byzantine_model
            or self.robust_agg != "none"
        ):
            raise ValueError(
                "crash/dup/delay/byzantine fault models (and robust_agg) "
                "contradict reference semantics — the reference models zero "
                "faults (program.fs has no failure path); use batched "
                "semantics"
            )
        if self.crash_model and self.termination == "global":
            raise ValueError(
                "termination='global' (every node's residual stable) is "
                "undefined under a crash model — dead nodes park arriving "
                "mass and never stabilize; use the local latch with quorum"
            )
        if self.crash_model and self.target_frac is not None:
            raise ValueError(
                "target_frac and the crash model's quorum rule are two "
                "different termination targets; use quorum"
            )
        if not (1 <= self.max_rounds <= 2**30):
            # The upper bound keeps round-indexed PRNG fold_in tags disjoint
            # from the leader-draw tag (models/runner.py _LEADER_TAG).
            raise ValueError("max_rounds must be in [1, 2**30]")
        if self.chunk_rounds < 1:
            raise ValueError("chunk_rounds must be >= 1")
        if not (1 <= self.pipeline_chunks <= 64):
            raise ValueError(
                f"pipeline_chunks must be in [1, 64], got "
                f"{self.pipeline_chunks} (each in-flight chunk holds live "
                "round state; depth beyond a few buys nothing past the "
                "dispatch floor)"
            )
        if self.delivery not in ("auto", "scatter", "stencil", "pool", "matmul"):
            raise ValueError(
                f"unknown delivery {self.delivery!r}; "
                "expected auto|scatter|stencil|pool|matmul"
            )
        if self.delivery == "pool" and self.topology not in (
            "full", "imp2d", "imp3d"
        ):
            raise ValueError(
                "delivery='pool' applies to the implicit full topology "
                "(offset-pool sampling) and to imp2d/imp3d (pooled "
                "long-range edges over the lattice stencil); "
                f"got topology={self.topology!r}"
            )
        if self.delivery == "matmul" and self.topology not in (
            "full", "imp2d", "imp3d"
        ):
            raise ValueError(
                "delivery='matmul' recasts the pooled delivery as a "
                "blocked one-hot dot_general (the MXU tier) and applies "
                "where pooled sampling applies: the implicit full topology "
                "and imp2d/imp3d; offset-structured kinds keep their "
                "stencil/scatter plans — "
                f"got topology={self.topology!r}"
            )
        if not (2 <= self.pool_size <= 1024) or self.pool_size & (self.pool_size - 1):
            raise ValueError(
                f"pool_size must be a power of two in [2, 1024], got {self.pool_size}"
            )
        if self.engine not in ("auto", "chunked", "fused"):
            raise ValueError(
                f"unknown engine {self.engine!r}; expected auto|chunked|fused"
            )
        if self.plan not in ("hand", "auto"):
            raise ValueError(
                f"unknown plan {self.plan!r}; expected hand|auto"
            )
        if self.plan == "auto" and self.semantics == "reference":
            raise ValueError(
                "plan='auto' scores the batched chunk engines "
                "(analysis/cost.py); reference semantics runs its own "
                "single-walk simulator with nothing to choose between — "
                "use batched semantics or plan='hand'"
            )
        if not (1 <= self.replicas <= MAX_REPLICAS):
            raise ValueError(
                f"replicas must be in [1, {MAX_REPLICAS}], got "
                f"{self.replicas} (the REPLICA_TAG0 fold_in region caps the "
                "lane count — TAG MAP in ops/faults.py)"
            )
        if self.replicas > 1:
            # The replica sweep vmaps the chunked XLA engines
            # (models/sweep.py); these contracts used to surface only after
            # topology build (_reject_unsupported) — fail at config time,
            # like the revive/crash checks above.
            if self.engine == "fused":
                raise ValueError(
                    "engine='fused' does not apply to replica sweeps: the "
                    "Pallas tiers opt out of the batch dimension "
                    "(plan/tiering gate); the sweep always runs the chunked "
                    "XLA engines — drop the engine override"
                )
            if self.semantics == "reference":
                raise ValueError(
                    "replica sweeps vmap the batched synchronous-round "
                    "engines; reference semantics (single-walk push-sum, Q1 "
                    "population) has no batched replica axis — use batched "
                    "semantics"
                )
            if self.n_devices is not None and self.n_devices > 1:
                raise ValueError(
                    "replica sweeps are single-device (the replica axis IS "
                    "the parallelism); drop n_devices or run replicas "
                    "unbatched"
                )
            if self.stall_chunks:
                raise ValueError(
                    "stall_chunks watchdog semantics are per-run; a batched "
                    "sweep has no single progress gap to watch — run stall "
                    "diagnostics unbatched"
                )
            if self.mass_tolerance is not None:
                raise ValueError(
                    "the health sentinel (mass_tolerance) carries one "
                    "per-run health scalar through the chunk loop; a "
                    "batched sweep has no per-replica outcome channel for "
                    "it — run health-sentinel diagnostics unbatched"
                )
        if (
            self.dtype == "bfloat16"
            and self.algorithm == "push-sum"
            and self.topology in ("line", "ring", "ref2d")
        ):
            # Measured (tests/test_bfloat16.py preamble): on 1-D chains the
            # bf16 ratio latches stable after ~O(n) rounds while mixing
            # needs O(n^2) — estimates land 39-49% off the true mean at
            # n=256. That is not a degraded mode, it is a wrong answer;
            # fail loudly instead of returning it.
            raise ValueError(
                "bfloat16 push-sum on 1-D chain topologies (line/ring/ref2d) "
                "latches its coarse ratio as stable long before the chain "
                "mixes — measured ~40-49% relative estimate error. Use "
                "float32, or bfloat16 on expander-class topologies "
                "(full/torus3d/grid3d/imp2d/imp3d: <0.5% rel error; grid2d: "
                "few-percent, documented degraded)"
            )
        if self.termination not in ("local", "global"):
            raise ValueError(
                f"unknown termination {self.termination!r}; expected local|global"
            )
        if self.termination == "global" and self.algorithm != "push-sum":
            raise ValueError(
                "termination='global' is a push-sum residual criterion "
                "(max |Δ(s/w)| <= delta); gossip terminates on receipt "
                "counts only"
            )
        if self.termination == "global" and self.semantics == "reference":
            raise ValueError(
                "termination='global' replaces the reference's local "
                "stability rule (program.fs:119-137) and contradicts "
                "reference semantics; use batched semantics"
            )

    # -- resolved policy ---------------------------------------------------

    @property
    def reference(self) -> bool:
        return self.semantics == "reference"

    @property
    def crash_model(self) -> bool:
        """True when nodes can die (ops/faults.death_plane is non-None)."""
        return self.crash_rate > 0.0 or self.crash_schedule is not None

    @property
    def revive_model(self) -> bool:
        """True when crashed nodes can rejoin (ops/faults.revival_plane is
        non-None)."""
        return self.revive_rate > 0.0 or self.revive_schedule is not None

    @property
    def byzantine_model(self) -> bool:
        """True when nodes can lie (ops/faults.byzantine_plane is non-None).
        Byzantine nodes are ALIVE: they send every round and count toward
        the quorum's live set — independent of the crash model."""
        return self.byzantine_rate > 0.0 or self.byzantine_schedule is not None

    @property
    def lint_warnings(self) -> tuple[str, ...]:
        """Valid-but-suspect combinations, as human-readable strings — the
        single source of both the conditions and the texts. The CLI prints
        each to stderr and stamps them into the run-start event;
        __post_init__ raises each as a RuntimeWarning for API users."""
        out = []
        if self.quorum != 1.0 and not self.crash_model:
            out.append(
                "quorum < 1.0 without a crash model has no effect (the "
                "legacy converged_count >= target predicate rules); set "
                "crash_rate/crash_schedule, or use target_frac to relax a "
                "fault-free target"
            )
        if self.robust_agg != "none" and not self.byzantine_model:
            out.append(
                "robust_agg without a byzantine model bounds contributions "
                "that are all honest — pure overhead that can only discard "
                "legitimate mass; set byzantine_rate/byzantine_schedule, or "
                "drop --robust-agg"
            )
        return tuple(out)

    @property
    def faulted(self) -> bool:
        """Any failure-model knob set — engines that support none of them
        gate on this."""
        return (
            self.fault_rate > 0.0
            or self.crash_model
            or self.dup_rate > 0.0
            or self.delay_rounds > 0
            or self.byzantine_model
        )

    @property
    def resolved_delta(self) -> float:
        """Push-sum delta. The reference hard-codes 1e-10 (program.fs:187).

        1e-10 is unreachable below float64 (f32 ratio noise floor is ~1e-7
        relative), so the float32/bfloat16 default is rescaled; an explicit
        ``delta`` always wins.
        """
        if self.delta is not None:
            return self.delta
        if self.dtype == "float64":
            return 1e-10
        if self.dtype == "float32":
            return 1e-6
        # bfloat16: 8-bit mantissa — ratio ulp near mean (n-1)/2 is coarser
        # than any tighter threshold. Quality envelope pinned by
        # tests/test_bfloat16.py: <0.5% rel error on expander-class
        # topologies (full, torus3d, grid3d, imp2d, imp3d); few-percent on
        # grid2d (documented degraded); 1-D chains are REJECTED at config
        # time (__post_init__) — measured ~40-49% error there.
        return 1e-2

    @property
    def resolved_rumor_target(self) -> int:
        """Receipt count at which a gossip node converges.

        Reference quirk Q2: the `messageCount = 10` check precedes the
        increment (program.fs:102-105), so conversion happens on the 11th
        receipt. Batched mode uses the honest threshold.
        """
        return self.rumor_threshold + 1 if self.reference else self.rumor_threshold

    @property
    def initial_term_round(self) -> int:
        """Push-sum termRound initial value — 1 in the reference (Q4,
        program.fs:79), so only two consecutive sub-delta rounds trigger the
        first conversion; honest mode starts at 0."""
        return 1 if self.reference else 0

    @property
    def resolved_suppress(self) -> bool:
        if self.suppress_converged is not None:
            return self.suppress_converged
        return self.reference

    def resolved_pool2_wire(self, n_devices: int) -> str:
        """Delivery wire the replicated-pool2 composition runs on THIS
        mesh: "auto" picks the banded reduce_scatter exactly when every
        band is smaller than the gathered copy (n_devices > pool_size —
        each device then receives P bands of ~R/n_devices rows instead of
        the full R-row summary); explicit values force either wire (the
        gather wire is the bitwise oracle the band wire is pinned
        against)."""
        if self.pool2_wire != "auto":
            return self.pool2_wire
        return (
            "reduce_scatter" if n_devices > self.pool_size else "all_gather"
        )

    def resolved_target_count(self, population: int, builder_target: int) -> int:
        """Number of converged nodes that ends the run."""
        if self.target_frac is not None:
            return max(1, min(population, int(round(self.target_frac * population))))
        if self.reference:
            return builder_target  # Q1: N of N+1
        return population
