"""`python -m cop5615_gossip_protocol_tpu N TOPOLOGY ALGORITHM` — the
reference's `dotnet run N topology algorithm` entry (program.fs:19-21)."""

from .cli import main

raise SystemExit(main())
