"""Declarative per-composition collective wire contracts.

Each sharded composition exports a ``WIRE_SPEC`` — its expected per-round /
per-super-step (body) and per-dispatch (setup) collectives AS DATA, in
terms of a handful of named structural quantities (state planes, send
windows, halo offset classes, pool-roll stages). The counts live ONCE, in
the composition's own module; this checker diffs the declaration against
the TRACED chunk program (analysis/trace.py), so

- tests/test_comm_audit.py asserts declaration <-> trace agreement instead
  of duplicating literals, and
- a new composition cannot ship without declaring its wire contract (an
  engine with no WIRE_SPEC is itself a finding).

This is the first externalized fragment of the ROADMAP item-4 plan IR: the
declaration says what the composition's delivery plan SHOULD put on the
wire; the trace proves the lowered program does exactly that.

Count term language — ``C`` is a linear form over the wire environment:

    expected = fixed + per_plane*planes + per_window*windows
             + per_class*classes + per_pair*disp_pairs + per_roll*rolls
             + per_slot*slots + per_wslot*wslots

where ``planes`` = state planes (gossip 3: count/active/conv; push-sum 4:
s/w/term/conv), ``windows`` = batched send-summary windows (gossip 1,
push-sum 2), ``classes`` = halo offset classes of the topology's exact
plan, ``disp_pairs`` = round-invariant disp/deg exchange pairs
(max_deg + 1), ``rolls`` = pool-roll ppermute count
(pool_size * (log2(n_devices) + 1)), ``slots`` = pool slots (pool_size —
the replicated-pool2 reduce_scatter wire issues one banded collective
per slot), ``wslots`` = windows x slots (its serial schedule's
per-window-per-slot wires). ``wire_env`` computes the environment from
the same plan functions the engines call — never from the trace.

STRICTNESS: within a declared region, every collective class not named
must count ZERO in the trace. "imp DMA mode keeps zero XLA collectives on
the halo path" is therefore not a special assertion — it falls out of the
dma variant declaring no ppermute.
"""

from __future__ import annotations

import dataclasses
import importlib
import math
from typing import Mapping, Optional

from .jaxpr_walk import COLLECTIVE_PRIMS, REMOTE_DMA
from .report import Finding

ALL_WIRE_PRIMS = tuple(COLLECTIVE_PRIMS) + (REMOTE_DMA,)


@dataclasses.dataclass(frozen=True)
class C:
    """One collective class's expected count as a linear form over the
    wire environment (see module docstring)."""

    fixed: int = 0
    per_plane: int = 0
    per_window: int = 0
    per_class: int = 0
    per_pair: int = 0
    per_roll: int = 0
    per_slot: int = 0
    per_wslot: int = 0
    per_slot_seg: int = 0
    per_wslot_seg: int = 0

    def expected(self, env: Mapping[str, int]) -> int:
        return (
            self.fixed
            + self.per_plane * env.get("planes", 0)
            + self.per_window * env.get("windows", 0)
            + self.per_class * env.get("classes", 0)
            + self.per_pair * env.get("disp_pairs", 0)
            + self.per_roll * env.get("rolls", 0)
            + self.per_slot * env.get("slots", 0)
            + self.per_wslot * env.get("wslots", 0)
            + self.per_slot_seg * env.get("slot_segs", 0)
            + self.per_wslot_seg * env.get("wslot_segs", 0)
        )


@dataclasses.dataclass(frozen=True)
class Regions:
    """Expected counts by region; unnamed collective classes must be 0."""

    body: Mapping[str, C]
    setup: Mapping[str, C]


@dataclasses.dataclass(frozen=True)
class WireSpec:
    """One composition's declared wire contract.

    variants   — (schedule, mode) -> Regions, schedule in
                 {"overlap", "serial"} (cfg.overlap_collectives), mode the
                 delivery/transport the run resolves ("wire"/"dma" for the
                 halo engines; "halo"/"pool"/"scatter" for the chunked
                 sharded engine).
    mechanism  — mode -> the AuditReport.halo_mechanism() string the
                 traced program must classify as.
    equal_bytes — body byte payloads that must be identical across the two
                 schedules of the same mode (batching changes packaging,
                 not payload).
    dma_bytes_match — when set, the dma mode's remote_dma body bytes must
                 equal this prim's body bytes in wire mode at the same
                 schedule (same payload, different transport).
    """

    engine: str
    variants: Mapping[tuple, Regions]
    mechanism: Mapping[str, str]
    equal_bytes: tuple = ()
    dma_bytes_match: Optional[str] = None


# Engine name -> module exporting its WIRE_SPEC (lazy: importing a spec
# must not drag every composition in).
SPEC_HOMES = {
    "sharded": "cop5615_gossip_protocol_tpu.parallel.sharded",
    "fused-sharded": "cop5615_gossip_protocol_tpu.parallel.fused_sharded",
    "fused-pool-sharded":
        "cop5615_gossip_protocol_tpu.parallel.fused_pool_sharded",
    "hbm-sharded": "cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded",
    "imp-hbm-sharded":
        "cop5615_gossip_protocol_tpu.parallel.fused_imp_hbm_sharded",
    "pool2-sharded": "cop5615_gossip_protocol_tpu.parallel.pool2_sharded",
}


def get_spec(engine: str) -> WireSpec:
    if engine not in SPEC_HOMES:
        raise KeyError(
            f"engine {engine!r} declares no WIRE_SPEC home — every sharded "
            "composition must declare its wire contract "
            "(analysis/wire_specs.py SPEC_HOMES)"
        )
    return importlib.import_module(SPEC_HOMES[engine]).WIRE_SPEC


def wire_env(engine: str, topo, cfg, n_devices: int) -> tuple[dict, str]:
    """(environment, mode) for one cell, computed from the same plan
    functions the engines dispatch on — never from the traced program."""
    planes = 4 if cfg.algorithm == "push-sum" else 3
    windows = 2 if cfg.algorithm == "push-sum" else 1
    env = {"planes": planes, "windows": windows}
    if engine == "sharded":
        if cfg.delivery == "pool":
            env["rolls"] = cfg.pool_size * (
                int(math.log2(n_devices)) + 1
            )
            return env, "pool"
        from ..parallel import halo as halo_mod

        plan = halo_mod.plan_halo(topo, n_devices)
        if plan is None:
            return env, "scatter"
        env["classes"] = int(plan.offsets_mod.shape[0])
        return env, "halo"
    if engine == "fused-sharded":
        env["disp_pairs"] = int(topo.max_deg) + 1
    if engine in ("hbm-sharded", "imp-hbm-sharded"):
        return env, ("dma" if cfg.halo_dma == "on" else "wire")
    if engine == "pool2-sharded":
        env["slots"] = cfg.pool_size
        env["wslots"] = windows * cfg.pool_size
        wire = cfg.resolved_pool2_wire(n_devices)
        # The plan demotes auto to the gather wire when the band margin
        # cannot fit one ring neighbor; mirror it from the same plan
        # function so declaration and dispatch cannot drift. The banded
        # wire's per-round reduce_scatter count is slots x its SEGMENT
        # count (parallel/halo.band_segments — the O(N/P)-operand
        # discipline), from the same plan geometry.
        from ..parallel.halo import band_segments
        from ..parallel.pool2_sharded import plan_pool2_sharded

        plan = plan_pool2_sharded(topo, cfg, n_devices)
        if not isinstance(plan, str):
            wire = plan[3]
            n_seg = band_segments(plan[0], n_devices)
            env["slot_segs"] = cfg.pool_size * n_seg
            env["wslot_segs"] = windows * cfg.pool_size * n_seg
        return env, ("rs" if wire == "reduce_scatter" else "gather")
    return env, "wire"


def expected_counts(spec: WireSpec, env: Mapping[str, int], schedule: str,
                    mode: str) -> dict:
    """{"body": {prim: n}, "setup": {prim: n}} over ALL wire prims (the
    undeclared ones expected 0)."""
    regions = spec.variants[(schedule, mode)]
    out = {}
    for region_name, declared in (
        ("body", regions.body), ("setup", regions.setup)
    ):
        out[region_name] = {
            prim: (declared[prim].expected(env) if prim in declared else 0)
            for prim in ALL_WIRE_PRIMS
        }
    return out


def check_report(report, topo, cfg) -> list[Finding]:
    """Diff one traced cell's counts against its composition's declared
    contract (counts and mechanism; byte equalities need the paired
    schedule/transport — see check_cell_group)."""
    schedule = "overlap" if report.overlap else "serial"
    try:
        spec = get_spec(report.engine)
    except KeyError as e:
        return [Finding(
            checker="wire-spec", where=report.engine, rule="no-spec",
            detail=str(e),
        )]
    env, mode = wire_env(report.engine, topo, cfg, report.n_devices)
    where = (
        f"{report.engine}/{report.topology}/{report.algorithm}/"
        f"{schedule}/{mode}"
    )
    if (schedule, mode) not in spec.variants:
        return [Finding(
            checker="wire-spec", where=where, rule="no-variant",
            detail=(
                f"WIRE_SPEC for {report.engine} declares no "
                f"({schedule}, {mode}) variant"
            ),
        )]
    findings = []
    want = expected_counts(spec, env, schedule, mode)
    for region in ("body", "setup"):
        for prim in ALL_WIRE_PRIMS:
            got = report.counts[region].get(prim, {}).get("count", 0)
            exp = want[region][prim]
            if got != exp:
                findings.append(Finding(
                    checker="wire-spec", where=where,
                    rule=f"{region}-{prim}",
                    detail=(
                        f"declared {exp} {prim} in {region}, traced {got} "
                        f"(env {env})"
                    ),
                ))
    mech_want = spec.mechanism.get(mode)
    if mech_want is not None and report.halo_mechanism() != mech_want:
        findings.append(Finding(
            checker="wire-spec", where=where, rule="mechanism",
            detail=(
                f"declared halo mechanism {mech_want!r}, traced program "
                f"classifies as {report.halo_mechanism()!r}"
            ),
        ))
    return findings


def check_schedule_pair(spec: WireSpec, on_report, off_report) -> list:
    """Cross-schedule byte equality: batching changes packaging, never
    payload. Both reports must be the same cell with overlap on/off."""
    findings = []
    for prim in spec.equal_bytes:
        b_on, b_off = on_report.body_bytes(prim), off_report.body_bytes(prim)
        if b_on != b_off:
            findings.append(Finding(
                checker="wire-spec",
                where=(
                    f"{on_report.engine}/{on_report.topology}/"
                    f"{on_report.algorithm}"
                ),
                rule=f"bytes-{prim}",
                detail=(
                    f"body {prim} payload differs across schedules: "
                    f"overlap {b_on} B vs serial {b_off} B — batching must "
                    "repackage, not change, the wire payload"
                ),
            ))
    return findings


def check_transport_pair(spec: WireSpec, wire_report, dma_report) -> list:
    """Cross-transport byte equality: the in-kernel DMA halo ships exactly
    the bytes the XLA wire shipped (same payload, different transport)."""
    if spec.dma_bytes_match is None:
        return []
    want = wire_report.body_bytes(spec.dma_bytes_match)
    got = dma_report.body_bytes(REMOTE_DMA)
    if want != got:
        return [Finding(
            checker="wire-spec",
            where=(
                f"{dma_report.engine}/{dma_report.topology}/"
                f"{dma_report.algorithm}/dma"
            ),
            rule="bytes-transport",
            detail=(
                f"remote-DMA halo ships {got} B but the XLA "
                f"{spec.dma_bytes_match} wire ships {want} B — transport "
                "changed the payload"
            ),
        )]
    return []
