"""Findings, the suppression baseline, and report rendering.

A Finding is one contract violation with a STABLE fingerprint — checker +
location + rule — so the committed baseline (analysis/baseline.json) can
suppress known, justified findings without pinning line numbers or message
wording. The CLI (analysis/__main__.py) exits non-zero on any finding whose
fingerprint is not baselined, and reports baselined fingerprints that no
longer fire (stale suppressions) so the baseline can only shrink.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

# Default committed baseline, next to this module.
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation.

    checker   — which checker fired (wire-spec, host-sync, donation,
                dtype-policy, prng-tags, lint).
    where     — stable location: a file path, an engine/topology/algorithm
                cell, or a symbol name. Never a line number.
    rule      — short machine id of the violated rule within the checker.
    detail    — human sentence; excluded from the fingerprint so wording
                can improve without churning baselines.
    """

    checker: str
    where: str
    rule: str
    detail: str

    @property
    def fingerprint(self) -> str:
        return f"{self.checker}::{self.where}::{self.rule}"

    def to_record(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["fingerprint"] = self.fingerprint
        return rec


def load_baseline(path: Path | str | None = None) -> dict:
    """The committed suppression baseline: {"suppressions": [{fingerprint,
    reason}, ...]}. Missing file = empty baseline."""
    p = Path(path) if path is not None else BASELINE_PATH
    if not p.exists():
        return {"suppressions": []}
    with open(p) as f:
        data = json.load(f)
    if not isinstance(data.get("suppressions"), list):
        raise ValueError(f"baseline {p} must carry a 'suppressions' list")
    for s in data["suppressions"]:
        if "fingerprint" not in s or "reason" not in s:
            raise ValueError(
                f"baseline entry {s!r} needs 'fingerprint' and 'reason' "
                "(a suppression without a recorded justification is just "
                "a deleted finding)"
            )
    return data


def apply_baseline(findings, baseline: dict):
    """Split findings into (new, suppressed) and report stale suppressions.

    Returns (new_findings, suppressed_findings, stale_fingerprints)."""
    allowed = {s["fingerprint"] for s in baseline.get("suppressions", [])}
    new = [f for f in findings if f.fingerprint not in allowed]
    suppressed = [f for f in findings if f.fingerprint in allowed]
    fired = {f.fingerprint for f in findings}
    stale = sorted(allowed - fired)
    return new, suppressed, stale


def render_table(findings) -> list[str]:
    """Markdown findings table (empty list for a clean tree)."""
    if not findings:
        return ["No findings."]
    out = [
        "| checker | where | rule | detail |",
        "|---|---|---|---|",
    ]
    for f in sorted(findings, key=lambda x: x.fingerprint):
        detail = f.detail.replace("|", "\\|").replace("\n", " ")
        out.append(f"| {f.checker} | {f.where} | {f.rule} | {detail} |")
    return out


def write_json(findings, new, suppressed, stale, path: str) -> None:
    """CI artifact: every finding plus the baseline disposition."""
    rec = {
        "total": len(findings),
        "new": [f.to_record() for f in new],
        "suppressed": [f.to_record() for f in suppressed],
        "stale_suppressions": stale,
    }
    with open(path, "w") as f:
        json.dump(rec, f, indent=2)
        f.write("\n")
