"""Region-aware jaxpr visitor — the auditor's one program walker.

Generalized from benchmarks/comm_audit.py's ad-hoc walk (which is now a
thin client): a single recursive descent over a closed jaxpr that

- tracks the REGION of every equation — ``body`` (inside a while loop's
  cond or body, i.e. the per-round / per-super-step steady state) vs
  ``setup`` (the rest of the dispatch, paid once per chunk);
- descends into every sub-jaxpr a primitive carries (cond/body of while,
  branches of cond, pjit/shard_map/custom_* calls, and pallas_call's
  kernel jaxpr), so in-kernel structure is visible to the same visitor;
- classifies Pallas ``dma_start`` equations as LOCAL (HBM<->VMEM copies)
  vs REMOTE (``make_async_remote_copy`` neighbor DMAs, carrying a
  device_id operand) and sizes the transfer.

Primitive taxonomies live here so every checker names the same sets:
COLLECTIVE_PRIMS (XLA cross-device collectives), REMOTE_DMA (the
pseudo-collective), HOST_SYNC_PRIMS (host round-trips that must never
appear inside a chunk-loop body).
"""

from __future__ import annotations

from typing import Callable, Iterator

import numpy as np

# XLA cross-device collectives (jaxpr primitive names).
COLLECTIVE_PRIMS = (
    "ppermute", "psum", "all_gather", "reduce_scatter", "all_to_all",
)

# Pseudo-collective: an in-kernel async remote copy (neighbor DMA). Not an
# XLA collective — counted separately so the mechanism column can assert
# the halo path carries NO XLA collective while still shipping bytes.
REMOTE_DMA = "remote_dma"

# The payload-moving subset the wire/recv byte columns sum: every prim
# that ships neighbor/band payload between devices. psum is deliberately
# absent — the tables report it in its own count column, and its result
# aval equals its operand aval so it would double-count the contribution
# buffer rather than measure delivered payload. This tuple + the two
# reducers below are THE formula: benchmarks/comm_audit.py's table and
# analysis/cost.py's wire term both call them (ISSUE 17 satellite — one
# formula, pinned equal in tests/test_autotune.py).
WIRE_PRIMS = ("ppermute", "all_gather", "reduce_scatter", REMOTE_DMA)

# Host round-trips: each of these forces a device->host sync (or a host
# callback) every time it executes. Inside a chunk-loop body that is once
# per ROUND — the exact per-dispatch cost the chunked drivers exist to
# amortize away — so the host-sync checker forbids them there. Outside the
# body they are merely discouraged (setup runs once per chunk).
HOST_SYNC_PRIMS = (
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_local_array_to_global_array",
)


def aval_bytes(aval) -> int:
    """Payload bytes of one abstract value; 0 for tokens/abstract units."""
    try:
        return int(np.prod(aval.shape)) * aval.dtype.itemsize
    except Exception:  # noqa: BLE001 — abstract tokens etc. carry no bytes
        return 0


def sub_jaxprs(eqn):
    """(jaxpr, enters_loop_body) for every sub-jaxpr of an eqn. A while
    loop's cond and body both run once per iteration, so both count as
    loop-body regions; everything else (pjit/shard_map/cond branches/
    pallas_call kernels) inherits the caller's region."""
    for _name, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for v in vals:
            jx = getattr(v, "jaxpr", None)
            if jx is not None:
                yield jx, eqn.primitive.name == "while"
            elif hasattr(v, "eqns"):
                yield v, eqn.primitive.name == "while"


def remote_dma_info(eqn):
    """(is_remote, bytes) for a Pallas ``dma_start`` eqn. The primitive's
    flat operands unflatten through its ``tree`` param into (src_ref,
    src_transforms, dst_ref, dst_transforms, sems...); a REMOTE copy
    carries a non-empty device_id leaf at the tail, a local HBM<->VMEM
    copy carries None. Bytes = the sliced source shape (the NDIndexer's
    static slice sizes) x itemsize; 0 when the indexer cannot be sized."""
    import jax

    try:
        tup = jax.tree_util.tree_unflatten(eqn.params["tree"], eqn.invars)
    except Exception:  # noqa: BLE001 — unfamiliar tree layout
        return False, 0
    dev = tup[-1]
    if dev is None or dev == ():
        return False, 0
    size = 0
    try:
        src, src_transforms = tup[0], tup[1]
        shape = None
        for tr in src_transforms or ():
            get_shape = getattr(tr, "get_indexer_shape", None)
            if get_shape is not None:
                shape = tuple(get_shape())
        if shape is None:
            shape = tuple(src.aval.shape)
        size = int(np.prod(shape)) * src.aval.dtype.itemsize
    except Exception:  # noqa: BLE001 — bytes are best-effort
        size = 0
    return True, size


def walk(jaxpr, visit: Callable[[object, bool], None],
         in_body: bool = False) -> None:
    """Depth-first visit of every eqn: ``visit(eqn, in_body)`` with
    ``in_body`` True inside any while loop's cond/body (transitively)."""
    for eqn in jaxpr.eqns:
        visit(eqn, in_body)
        for sub, enters_body in sub_jaxprs(eqn):
            walk(sub, visit, in_body or enters_body)


def iter_eqns(jaxpr, in_body: bool = False) -> Iterator[tuple]:
    """Generator form of ``walk``: yields (eqn, in_body) pairs."""
    for eqn in jaxpr.eqns:
        yield eqn, in_body
        for sub, enters_body in sub_jaxprs(eqn):
            yield from iter_eqns(sub, in_body or enters_body)


def collect_collectives(jaxpr) -> dict:
    """Count collective primitives (and remote DMAs) by region over one
    closed/open jaxpr:
    {"body": {prim: {"count", "bytes", "bytes_out"}}, "setup": ...}.

    ``bytes`` sums the operand avals (what each device feeds the wire),
    ``bytes_out`` the RESULT avals — the per-device received payload,
    which is the honest measure for asymmetric collectives: an
    all_gather's input is one shard but every device receives the full
    n_dev-wide copy, while a reduce_scatter's input is the full-width
    contribution buffer but each device receives only its own shard.
    The replicated-pool2 O(N) -> O(N/P + margins) wire delta (ISSUE 15)
    is a bytes_out delta; benchmarks/comm_audit.py reports the column."""
    counts = {"body": {}, "setup": {}}

    def visit(eqn, in_body):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            region = counts["body" if in_body else "setup"]
            slot = region.setdefault(
                name, {"count": 0, "bytes": 0, "bytes_out": 0}
            )
            slot["count"] += 1
            slot["bytes"] += sum(aval_bytes(v.aval) for v in eqn.invars)
            slot["bytes_out"] += sum(
                aval_bytes(v.aval) for v in eqn.outvars
            )
        elif name == "dma_start":
            remote, size = remote_dma_info(eqn)
            if remote:
                region = counts["body" if in_body else "setup"]
                slot = region.setdefault(
                    REMOTE_DMA, {"count": 0, "bytes": 0, "bytes_out": 0}
                )
                slot["count"] += 1
                slot["bytes"] += size
                # A remote copy's received payload is the copy itself.
                slot["bytes_out"] += size

    walk(jaxpr, visit)
    return counts


def _body_sum(counts: dict, field: str) -> int:
    body = counts.get("body", {})
    return sum(body.get(p, {}).get(field, 0) for p in WIRE_PRIMS)


def body_wire_bytes(counts: dict) -> int:
    """Per-step bytes each device FEEDS the wire primitives (operand
    avals), summed over ``WIRE_PRIMS`` in the body region of a
    ``collect_collectives`` result."""
    return _body_sum(counts, "bytes")


def body_recv_bytes(counts: dict) -> int:
    """Per-step bytes each device RECEIVES from the wire primitives
    (result avals) — the honest column for asymmetric collectives: an
    all_gather receives the n_dev-wide copy, a reduce_scatter only the
    local shard. The replicated-pool2 O(N) -> O(N/P + margins) band-wire
    delta (ISSUE 15) lives here, and the cost model's wire term is
    ``body_recv_bytes(counts) * wire_byte_ns``."""
    return _body_sum(counts, "bytes_out")


def count_collectives(fn, args) -> dict:
    """Trace ``fn(*args)`` to a jaxpr and count collective primitives by
    region (inside/outside while bodies). Never executes the program."""
    import jax

    return collect_collectives(jax.make_jaxpr(fn)(*args).jaxpr)
