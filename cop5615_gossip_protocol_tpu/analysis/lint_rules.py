"""AST lints for repo conventions — the rules a jaxpr trace cannot see.

Three rule families, each an independent pass returning ``report.Finding``
records (all take a ``root`` so the fixture tests can point them at a
seeded-bad tree):

host-conversion (``check_host_conversions``)
    Inside TRACED scopes in the ``ops/``/``parallel/``/``models/`` hot
    paths, forbid forcing a traced value to the host: ``.item()``,
    ``np.asarray(...)``, and ``int()``/``float()``/``bool()`` applied to a
    traced-scope parameter. A traced scope is a function passed (by name)
    into a tracing entry point — ``lax.while_loop``/``fori_loop``/
    ``scan``/``cond``/``switch``, ``pl.pallas_call``, ``jax.jit``,
    ``shard_map`` — plus everything nested inside one. Each of these
    either crashes at trace time (wasting the dispatch) or, worse,
    silently freezes a traced value at its tracer-constant. The check is
    name-level dataflow (an expression mentioning a scope parameter), the
    static approximation that catches the real bug class with no false
    positives on static plan math.

schema-lockstep (``check_schema_lockstep``)
    Every row/record builder that emits a ``"schema_version"`` key must
    source the value from a ``*SCHEMA_VERSION`` module constant — never an
    int literal — and every ``*SCHEMA_VERSION`` constant must actually be
    read somewhere in its module. Together these pin the repo's
    version-bump discipline: you cannot widen a row format without the
    constant moving with it (utils/events.py, utils/metrics.py,
    ops/telemetry.py, serving/server.py all carry one).

refusal-names-composition (``check_refusals``)
    The PR 10 rule, enforced: every STATIC engine-refusal message in the
    models/runner.py ladder (a ``raise ValueError`` whose text names an
    engine override) must name a real serving composition or route
    (tokens derived from analysis/wire_specs.SPEC_HOMES plus the
    single-device engines) instead of dead-ending. Messages built from
    interpolated call results (e.g. a ``*_support`` reason) are dynamic
    and skipped — the static text around them is still checked when it
    carries the refusal.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .report import Finding

PACKAGE_ROOT = Path(__file__).resolve().parents[1]

# Call targets whose function-valued arguments are traced. Matching is on
# the callee's final attribute/name, so jax.lax.while_loop, lax.while_loop
# and a bare while_loop all hit.
_TRACING_ENTRY_POINTS = frozenset({
    "while_loop", "fori_loop", "scan", "cond", "switch", "pallas_call",
    "jit", "shard_map", "run_scoped", "custom_vjp", "custom_jvp", "vmap",
    "pmap", "checkpoint", "remat",
})

# Hot-path directories for the host-conversion lint (relative to the
# package root).
_HOT_DIRS = ("ops", "parallel", "models")


def _callee_name(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _iter_py(root: Path, subdirs=None):
    dirs = [root / d for d in subdirs] if subdirs else [root]
    for d in dirs:
        if not d.exists():
            continue
        for path in sorted(d.rglob("*.py")):
            yield path


def _traced_functions(tree: ast.AST) -> list:
    """FunctionDef/Lambda nodes handed (by name or inline) to a tracing
    entry point anywhere in the module, plus every def nested inside one.
    """
    defs: dict[str, list] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    traced: list = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _callee_name(node.func) in _TRACING_ENTRY_POINTS):
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in defs:
                traced.extend(defs[arg.id])
            elif isinstance(arg, (ast.Lambda, ast.FunctionDef)):
                traced.append(arg)
    # Everything nested inside a traced def is traced too.
    out = []
    for fn in traced:
        out.append(fn)
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                out.append(sub)
    return out


def _fn_params(fn) -> set:
    a = fn.args
    return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs} | (
        {a.vararg.arg} if a.vararg else set()
    ) | ({a.kwarg.arg} if a.kwarg else set())


def check_host_conversions(root: Path | None = None) -> list[Finding]:
    """No host-forcing conversions inside traced scopes (hot paths)."""
    root = root or PACKAGE_ROOT
    subdirs = _HOT_DIRS if root == PACKAGE_ROOT else None
    findings = []
    for path in _iter_py(root, subdirs):
        rel = str(path.relative_to(root.parent if subdirs else root))
        tree = ast.parse(path.read_text(), filename=rel)
        for fn in _traced_functions(tree):
            params = _fn_params(fn)
            name = getattr(fn, "name", "<lambda>")
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = node.func
                    if (isinstance(callee, ast.Attribute)
                            and callee.attr == "item" and not node.args):
                        findings.append(Finding(
                            checker="lint", where=f"{rel}::{name}",
                            rule="traced-item",
                            detail=(
                                f".item() at line {node.lineno} inside the "
                                f"traced scope {name!r} — forces a device->"
                                "host sync (or crashes at trace time); "
                                "return the value and read it at a chunk "
                                "boundary"
                            ),
                        ))
                    elif (isinstance(callee, ast.Attribute)
                            and callee.attr == "asarray"
                            and isinstance(callee.value, ast.Name)
                            and callee.value.id in ("np", "numpy")):
                        findings.append(Finding(
                            checker="lint", where=f"{rel}::{name}",
                            rule="traced-np-asarray",
                            detail=(
                                f"np.asarray at line {node.lineno} inside "
                                f"the traced scope {name!r} — materializes "
                                "a traced value on the host; use jnp"
                            ),
                        ))
                    elif (isinstance(callee, ast.Name)
                            and callee.id in ("int", "float", "bool")
                            and node.args and any(
                                isinstance(sub, ast.Name)
                                and sub.id in params
                                for sub in ast.walk(node.args[0]))):
                        findings.append(Finding(
                            checker="lint", where=f"{rel}::{name}",
                            rule=f"traced-{callee.id}",
                            detail=(
                                f"{callee.id}() on a traced-scope "
                                f"parameter at line {node.lineno} in "
                                f"{name!r} — freezes the tracer to a "
                                "Python scalar; keep it a jnp value"
                            ),
                        ))
    return findings


def check_schema_lockstep(root: Path | None = None) -> list[Finding]:
    """schema_version values come from constants; constants are used."""
    root = root or PACKAGE_ROOT
    findings = []
    for path in _iter_py(root):
        rel = str(path.relative_to(root.parent))
        tree = ast.parse(path.read_text(), filename=rel)
        constants: set[str] = set()
        loads: set[str] = set()
        for node in ast.walk(tree):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
                if isinstance(node, ast.AnnAssign) and node.value is not None
                else []
            )
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id.endswith(
                    "SCHEMA_VERSION"
                ):
                    constants.add(tgt.id)
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ) and node.id.endswith("SCHEMA_VERSION"):
                loads.add(node.id)
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if not (isinstance(k, ast.Constant)
                            and k.value == "schema_version"):
                        continue
                    ok = (isinstance(v, ast.Name)
                          and v.id.endswith("SCHEMA_VERSION")) or (
                        isinstance(v, ast.Attribute)
                        and v.attr.endswith("SCHEMA_VERSION"))
                    if not ok:
                        findings.append(Finding(
                            checker="lint", where=f"{rel}:schema_version",
                            rule="schema-literal",
                            detail=(
                                f"row builder at line {k.lineno} writes "
                                "schema_version from "
                                f"{ast.unparse(v)!r} — source it from the "
                                "module's *SCHEMA_VERSION constant so the "
                                "format cannot move without the version"
                            ),
                        ))
        for const in sorted(constants - loads):
            findings.append(Finding(
                checker="lint", where=f"{rel}::{const}",
                rule="schema-constant-unused",
                detail=(
                    f"{const} is defined but never read in {rel} — its row "
                    "builder is versioning some other way; wire the "
                    "constant through or delete it"
                ),
            ))
    return findings


def _composition_tokens() -> tuple:
    """Tokens that count as naming a real serving composition/route,
    derived from the wire-spec registry (so the lint can never accept a
    name the engine matrix does not actually serve)."""
    from .wire_specs import SPEC_HOMES

    toks = {"chunked", "composition", "batched semantics"}
    toks.update(SPEC_HOMES)
    return tuple(sorted(toks))


def _static_text(node: ast.expr, str_locals: dict,
                 call_locals: set) -> tuple[str, bool]:
    """(joined static text, delegates_to_computed_reason) of a message
    expr. Interpolated NAMES resolve through same-function string-literal
    assignments. ONLY an interpolated call result — a direct call, or a
    name assigned from one (a ``*_support`` reason) — counts as
    delegating the refusal text to another surface; interpolated DATA
    (``{cfg.topology}``, subscripts, parameters) does not exempt the
    static text around it from naming a composition."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.JoinedStr):
        text, dyn = "", False
        for part in node.values:
            t, d = _static_text(part, str_locals, call_locals)
            text += t
            dyn = dyn or d
        return text, dyn
    if isinstance(node, ast.FormattedValue):
        return _static_text(node.value, str_locals, call_locals)
    if isinstance(node, ast.Name) and node.id in str_locals:
        return " ".join(str_locals[node.id]), False
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        lt, ld = _static_text(node.left, str_locals, call_locals)
        rt, rd = _static_text(node.right, str_locals, call_locals)
        return lt + rt, ld or rd
    if isinstance(node, ast.Call):
        return "", True
    if isinstance(node, ast.Name) and node.id in call_locals:
        return "", True
    return "", False


# The runner-ladder functions whose ValueError raises are engine refusals.
_LADDER_FUNCS = ("run", "_run_resolved", "_run_fused", "_strict_engine",
                 "_engine_ladder")


def check_refusals(runner_path: Path | None = None) -> list[Finding]:
    """Every static engine-refusal in the runner ladder names a real
    composition (see module docstring)."""
    path = runner_path or (PACKAGE_ROOT / "models" / "runner.py")
    rel = str(path.relative_to(path.parents[2]))
    tree = ast.parse(path.read_text(), filename=rel)
    tokens = _composition_tokens()
    findings = []
    for fn in tree.body:
        if not (isinstance(fn, ast.FunctionDef)
                and fn.name in _LADDER_FUNCS):
            continue
        # Local names assigned string literals (static refusal `reason`s)
        # vs assigned from calls (computed reasons — a *_support result).
        str_locals: dict[str, list] = {}
        call_locals: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                text, dyn = _static_text(node.value, {}, set())
                if text and not dyn:
                    str_locals.setdefault(
                        node.targets[0].id, []
                    ).append(text)
                elif isinstance(node.value, ast.Call):
                    call_locals.add(node.targets[0].id)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Raise)
                    and isinstance(node.exc, ast.Call)
                    and _callee_name(node.exc.func) == "ValueError"
                    and node.exc.args):
                continue
            text, delegated = _static_text(
                node.exc.args[0], str_locals, call_locals
            )
            is_refusal = "engine='" in text or "engine override" in text
            if not is_refusal:
                continue
            if delegated and not any(t in text for t in tokens):
                # Message interpolates a computed reason (a *_support
                # result) — judged by that surface, not here.
                continue
            if not any(t in text for t in tokens):
                findings.append(Finding(
                    checker="lint", where=f"{rel}::{fn.name}:{node.lineno}",
                    rule="refusal-dead-end",
                    detail=(
                        f"engine refusal at line {node.lineno} names no "
                        "real serving composition — tell the caller which "
                        "engine/composition serves this config (tokens: "
                        "chunked, sharded, ..., 'composition') instead of "
                        "dead-ending"
                    ),
                ))
    return findings


def check_multiprocess_refusals(parallel_dir: Path | None = None) -> list[Finding]:
    """ISSUE 15 extension of the refusal rule to the multi-process
    support matrix: a composition's plan/support function that refuses a
    MULTI-PROCESS mesh (a returned static reason mentioning
    'single-process' or 'multi-process') must name a real serving
    composition — the runner's combined refusal interpolates these plan
    reasons verbatim, so a dead-end here is a dead-end for the user
    exactly like a runner-ladder one."""
    pdir = parallel_dir or (PACKAGE_ROOT / "parallel")
    tokens = _composition_tokens()
    findings = []
    for path in sorted(pdir.glob("*.py")):
        rel = str(path.relative_to(path.parents[2]))
        tree = ast.parse(path.read_text(), filename=rel)
        for fn in ast.walk(tree):
            if not (isinstance(fn, ast.FunctionDef) and (
                fn.name.startswith("plan_") or fn.name.endswith("_support")
            )):
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Return)
                        and node.value is not None):
                    continue
                text, _dyn = _static_text(node.value, {}, set())
                if "single-process" not in text and (
                    "multi-process" not in text
                ):
                    continue
                if not any(t in text for t in tokens):
                    findings.append(Finding(
                        checker="lint",
                        where=f"{rel}::{fn.name}:{node.lineno}",
                        rule="refusal-dead-end",
                        detail=(
                            "multi-process plan refusal names no real "
                            "serving composition — tell the caller which "
                            "composition serves multi-process meshes "
                            "instead of dead-ending"
                        ),
                    ))
    return findings


def run_lints(root: Path | None = None) -> list[Finding]:
    """All four lint families over the real tree."""
    out = check_host_conversions(root)
    out += check_schema_lockstep(root)
    if root is None:
        out += check_refusals()
        out += check_multiprocess_refusals()
    return out
