"""The PRNG fold_in TAG MAP, machine-verified.

ops/faults.py's module docstring is the canonical human-readable TAG MAP:
every stream that folds into ``PRNGKey(cfg.seed)`` (or the runner's base
key) must occupy a region pairwise disjoint from every other, or two
"independent" streams silently share bits. Historically that disjointness
was proved by prose; this module proves it mechanically:

1. ``REGISTRY`` rebuilds the map from the REAL constants (imported from
   ops/faults, ops/sampling, models/sweep, models/runner — the values can
   never drift from what the engines fold), at both stream levels:
   base-key regions and the per-ROUND-key tags.
2. ``check_disjoint`` asserts the base-key regions are pairwise disjoint
   (and the round-key tags pairwise distinct) by interval arithmetic.
3. ``harvest_fold_ins`` walks every module's AST for ``fold_in`` call
   sites and classifies the tag operand: a registered tag name, a
   registered region base (+ offset), or a round-index fold. Any fold
   whose tag it cannot classify — and any ``*_TAG*`` constant assigned
   anywhere in the package but absent from the registry — is a finding,
   so a new stream CANNOT be added without extending the map.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .report import Finding

# Package root (the scanned tree).
PACKAGE_ROOT = Path(__file__).resolve().parents[1]

# Round indices fold directly into the base key; SimConfig caps max_rounds
# at 2**30 exactly to keep this region closed (config.py validation).
ROUND_REGION_END = 2**30


def registry() -> dict:
    """The TAG MAP as data, rebuilt from the engine constants.

    ``base``: {name: (start, end)} half-open intervals folded into the
    base key. ``round``: {name: tag} singletons folded into per-round keys
    (a separate stream level — they need only be distinct from each
    other)."""
    from ..models import runner, sweep
    from ..ops import faults, sampling

    base = {
        "round-indices": (0, ROUND_REGION_END),
        "CRASH_TAG": (faults.CRASH_TAG, faults.CRASH_TAG + 1),
        "REVIVE_TAG": (faults.REVIVE_TAG, faults.REVIVE_TAG + 1),
        "BYZ_TAG": (faults.BYZ_TAG, faults.BYZ_TAG + 1),
        "REPLICA_TAG0": (
            sweep.REPLICA_TAG0, sweep.REPLICA_TAG0 + sweep.MAX_REPLICAS,
        ),
        # Batch filler lanes are capped at MAX_REPLICAS total
        # (models/sweep.run_batched_keys validates lanes <= MAX_REPLICAS).
        "LANE_FILLER_TAG0": (
            sweep.LANE_FILLER_TAG0,
            sweep.LANE_FILLER_TAG0 + sweep.MAX_REPLICAS,
        ),
        "_LEADER_TAG": (runner._LEADER_TAG, runner._LEADER_TAG + 1),
    }
    round_level = {
        "_POOL_TAG": sampling._POOL_TAG,
        "IMP_CHOICE_TAG": sampling.IMP_CHOICE_TAG,
        "GATE_TAG": sampling.GATE_TAG,
        "DUP_TAG": sampling.DUP_TAG,
    }
    return {"base": base, "round": round_level}


def check_disjoint(reg: dict | None = None) -> list[Finding]:
    """Pairwise disjointness of the base-key regions; distinctness of the
    round-key tags; every tag within uint32 fold_in range."""
    reg = reg or registry()
    findings = []
    base = sorted(reg["base"].items(), key=lambda kv: kv[1])
    for (na, (sa, ea)), (nb, (sb, eb)) in zip(base, base[1:]):
        if ea > sb:
            findings.append(Finding(
                checker="prng-tags", where=f"{na}+{nb}",
                rule="base-region-overlap",
                detail=(
                    f"base-key regions overlap: {na}=[{sa}, {ea}) and "
                    f"{nb}=[{sb}, {eb}) — two 'independent' streams share "
                    "fold_in values"
                ),
            ))
    for name, (start, end) in reg["base"].items():
        if not (0 <= start < end <= 2**32):
            findings.append(Finding(
                checker="prng-tags", where=name, rule="base-region-range",
                detail=f"region [{start}, {end}) escapes uint32 fold_in "
                       "range",
            ))
    seen: dict[int, str] = {}
    for name, tag in reg["round"].items():
        if tag in seen:
            findings.append(Finding(
                checker="prng-tags", where=f"{seen[tag]}+{name}",
                rule="round-tag-collision",
                detail=f"round-key tags {seen[tag]} and {name} share value "
                       f"{tag:#x}",
            ))
        seen[tag] = name
        if not (0 <= tag < 2**32):
            findings.append(Finding(
                checker="prng-tags", where=name, rule="round-tag-range",
                detail=f"tag {tag:#x} escapes uint32 fold_in range",
            ))
    return findings


def _tag_operand_names(node: ast.expr) -> list[str]:
    """Identifier names appearing in a fold_in tag expression."""
    names = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            names.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            names.append(sub.attr)
    return names


def _fold_in_tag(node: ast.AST):
    """The tag operand of a ``fold_in`` call in EITHER callee form —
    ``key.fold_in(...)`` / ``jax.random.fold_in(key, tag)`` (attribute)
    or a bare ``fold_in(key, tag)`` from-import (name) — positional or
    ``data=`` keyword. None when ``node`` is not a fold_in call."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    callee = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    if callee != "fold_in":
        return None
    if len(node.args) >= 2:
        return node.args[1]
    for kw in node.keywords:
        if kw.arg == "data":
            return kw.value
    return None


def _const_targets(node: ast.AST):
    """Assignment target names of a plain or annotated assignment."""
    if isinstance(node, ast.Assign):
        return [t for t in node.targets if isinstance(t, ast.Name)]
    if isinstance(node, ast.AnnAssign) and isinstance(
        node.target, ast.Name
    ) and node.value is not None:
        return [node.target]
    return []


def harvest_fold_ins(root: Path | None = None,
                     reg: dict | None = None) -> list[Finding]:
    """AST-harvest every ``fold_in(key, tag)`` call under ``root`` and
    flag (a) tag expressions naming no registered tag and no plausible
    round-index variable shape, (b) integer-constant tags outside every
    registered region, and (c) ``*_TAG``/``*_TAG0`` module constants not
    present in the registry."""
    root = root or PACKAGE_ROOT
    reg = reg or registry()
    known_names = set(reg["base"]) | set(reg["round"])
    findings = []
    region_list = list(reg["base"].values())
    for path in sorted(root.rglob("*.py")):
        rel = str(path.relative_to(root.parent))
        tree = ast.parse(path.read_text(), filename=rel)
        for node in ast.walk(tree):
            # (c) unregistered *_TAG constants (plain or annotated
            # assignments at any level).
            for tgt in _const_targets(node):
                if (tgt.id.endswith("_TAG") or tgt.id.endswith("_TAG0")
                        ) and tgt.id not in known_names:
                    findings.append(Finding(
                        checker="prng-tags", where=f"{rel}::{tgt.id}",
                        rule="unregistered-tag-constant",
                        detail=(
                            f"{tgt.id} is assigned in {rel} but absent "
                            "from the analysis/tags.py registry — "
                            "register it (and the ops/faults.py TAG "
                            "MAP) before folding it"
                        ),
                    ))
            tag = _fold_in_tag(node)
            if tag is None:
                continue
            if isinstance(tag, ast.Constant) and isinstance(tag.value, int):
                # (b) a literal tag must land in a registered region (the
                # round region admits literal round indices like 0).
                if not any(s <= tag.value < e for s, e in region_list):
                    findings.append(Finding(
                        checker="prng-tags",
                        where=f"{rel}:{tag.value:#x}",
                        rule="literal-tag-outside-map",
                        detail=(
                            f"fold_in literal {tag.value:#x} in {rel} lies "
                            "in no registered TAG MAP region"
                        ),
                    ))
                continue
            names = _tag_operand_names(tag)
            if any(n in known_names for n in names):
                continue  # registered tag / region base (+ offset)
            if any(n.endswith("_TAG") or n.endswith("_TAG0") for n in names):
                findings.append(Finding(
                    checker="prng-tags",
                    where=f"{rel}:{ast.unparse(tag)}",
                    rule="unregistered-tag-fold",
                    detail=(
                        f"fold_in tag expression {ast.unparse(tag)!r} in "
                        f"{rel} names a *_TAG constant the registry does "
                        "not know"
                    ),
                ))
            # Otherwise: a round-index-class fold (a traced round variable
            # or derived key) — the round region covers it by construction.
    return findings


def check_tags() -> list[Finding]:
    """The full PRNG tag audit: registry disjointness + AST harvest."""
    reg = registry()
    return check_disjoint(reg) + harvest_fold_ins(reg=reg)
