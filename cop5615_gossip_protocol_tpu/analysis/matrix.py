"""The audited engine matrix: every cell, every checker, one driver.

``AUDIT_GRID`` is the canonical list of sharded engine x topology x
algorithm x transport cells (benchmarks/comm_audit.py renders the same
grid as a table; tests/test_comm_audit.py pins declaration <-> trace
agreement over the tier-1 subset). ``SINGLE_GRID`` adds the single-device
chunked/fused cells reachable through models.runner.run's probe hook on
CPU.

``audit_matrix`` traces every cell ONCE under ``jax.experimental
.enable_x64`` (so the dtype-policy scan can see weak-type f64 promotions
— counts and region structure are dtype-independent) and runs the full
checker set:

- wire-spec declaration diff + cross-schedule byte equality + (for the
  dma transports) cross-transport byte equality (sharded cells);
- host-sync freedom and dtype policy (every cell);
- donation aliasing — lowering-level everywhere; compiled
  ``input_output_alias`` proof on the cheap XLA engines ('sharded',
  'chunked'), where a deferred ``jax.buffer_donor`` could silently not
  alias;
- the MXU matmul contract on ``delivery='matmul'`` cells (dot_general
  present, zero scatter primitives — contracts.check_matmul_delivery);
- the PRNG TAG MAP audit and the AST lint families (once per run, not
  per cell).

Populations are the smallest each composition's plan accepts; the audited
structure (the jaxpr) is population-independent, so small is right.
"""

from __future__ import annotations

from . import contracts, lint_rules, tags, trace, wire_specs
from .report import Finding

# (engine, topology, algorithm, n, n_devices, extra cfg) — the sharded
# grid. halo_dma='on' rows trace the in-kernel async-remote-copy kernel
# hardware-free; their wire siblings double as the transport-pair byte
# oracle.
AUDIT_GRID = (
    ("sharded", "torus3d", "gossip", 4096, 8, {}),
    ("sharded", "torus3d", "push-sum", 4096, 8, {}),
    ("sharded", "full", "push-sum", 1024, 8, {"delivery": "pool"}),
    # Non-divisible ring: no exact halo plan -> scatter + reduce-scatter
    # fallback (wire batching does not apply; audited for the record).
    ("sharded", "ring", "gossip", 1001, 8, {}),
    ("fused-sharded", "torus3d", "gossip", 131072, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("fused-sharded", "torus3d", "push-sum", 131072, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("fused-pool-sharded", "full", "gossip", 131072, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("fused-pool-sharded", "full", "push-sum", 131072, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("hbm-sharded", "torus3d", "gossip", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("hbm-sharded", "torus3d", "push-sum", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("hbm-sharded", "torus3d", "gossip", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8, "halo_dma": "on"}),
    ("hbm-sharded", "torus3d", "push-sum", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8, "halo_dma": "on"}),
    ("imp-hbm-sharded", "imp3d", "gossip", 27000, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("imp-hbm-sharded", "imp3d", "push-sum", 27000, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("imp-hbm-sharded", "imp3d", "gossip", 27000, 2,
     {"engine": "fused", "delivery": "pool", "halo_dma": "on"}),
    ("imp-hbm-sharded", "imp3d", "push-sum", 27000, 2,
     {"engine": "fused", "delivery": "pool", "halo_dma": "on"}),
    # pool2_wire auto resolves per mesh width: the gather wire at 2
    # devices (pool_size 4 >= mesh — every band would exceed the full
    # copy), the banded reduce_scatter wire at 8 (ISSUE 15 — one banded
    # collective per pool slot + one margin ppermute volley, O(N/P +
    # margins) received bytes; the recv-bytes delta vs the gather rows
    # is pinned in tests/test_comm_audit.py).
    ("pool2-sharded", "full", "gossip", 262144, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("pool2-sharded", "full", "push-sum", 262144, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("pool2-sharded", "full", "gossip", 262144, 8,
     {"engine": "fused", "delivery": "pool"}),
    ("pool2-sharded", "full", "push-sum", 262144, 8,
     {"engine": "fused", "delivery": "pool"}),
    # MXU matmul tier (ISSUE 12): the per-shard one-hot blend after the
    # one all_gather — the SAME WIRE_SPEC as the pool rows must hold
    # (the matmul rung moves compute units, never wire structure), plus
    # the matmul contract (dot_general present, scatter absent).
    ("pool2-sharded", "full", "gossip", 262144, 2,
     {"engine": "fused", "delivery": "matmul"}),
    ("pool2-sharded", "full", "push-sum", 262144, 2,
     {"engine": "fused", "delivery": "matmul"}),
)

# Single-device cells through models.runner.run (n_devices=1): the chunked
# XLA engine and each fused tier the dispatch resolves on CPU (interpret
# mode — the probe fires before execution, so tracing stays hardware-free).
SINGLE_GRID = (
    ("chunked", "full", "gossip", 256, 1, {}),
    ("chunked", "torus3d", "push-sum", 4096, 1, {}),
    ("chunked", "ring", "gossip", 1001, 1, {}),
    ("fused", "full", "gossip", 4096, 1,
     {"engine": "fused", "delivery": "pool"}),
    ("fused", "torus3d", "push-sum", 4096, 1,
     {"engine": "fused", "chunk_rounds": 8}),
    # MXU matmul tier (ISSUE 12): the chunked blocked one-hot dot_general
    # round and the fused pool kernel's in-kernel one-hot lane blend —
    # both must satisfy the matmul contract (dot_general present, zero
    # scatter primitives).
    ("chunked", "full", "gossip", 256, 1, {"delivery": "matmul"}),
    ("chunked", "full", "push-sum", 1024, 1, {"delivery": "matmul"}),
    ("fused", "full", "gossip", 4096, 1,
     {"engine": "fused", "delivery": "matmul"}),
    # Byzantine adversary plane (ISSUE 16): the chunked round bodies with
    # send-time corruption / post-freeze overrides, the robust-clip inbox
    # bound, and both fused carriers (stencil + pool) with the plane as an
    # extra VMEM input. The sharded compositions refuse the plane
    # (models/runner.py), so no AUDIT_GRID rows exist — these cells pin
    # that the plane changes no wire structure anywhere it runs.
    ("chunked", "ring", "gossip", 1001, 1,
     {"byzantine_rate": 0.05, "byzantine_mode": "stale_rumor"}),
    ("chunked", "full", "push-sum", 1024, 1,
     {"byzantine_rate": 0.05, "byzantine_mode": "mass_inflate",
      "robust_agg": "clip"}),
    ("fused", "torus3d", "push-sum", 4096, 1,
     {"engine": "fused", "chunk_rounds": 8, "byzantine_rate": 0.05,
      "byzantine_mode": "mass_inflate"}),
    ("fused", "full", "gossip", 4096, 1,
     {"engine": "fused", "delivery": "pool", "byzantine_rate": 0.05,
      "byzantine_mode": "garble"}),
)

# Representative plan='auto' mesh requests (ISSUE 17): the winners
# resolve against the COMMITTED calibration (analysis/calibration.json)
# and the resulting engine rows are audited exactly like hand-picked
# AUDIT_GRID rows — the acceptance hook "the static auditor verifies the
# chosen plan's wire". (topology, algorithm, n, n_devices, extra cfg.)
AUTOTUNE_AUDIT = (
    ("torus3d", "gossip", 4096, 8, {}),
    ("full", "push-sum", 262144, 8, {"engine": "fused",
                                     "delivery": "pool"}),
    ("full", "push-sum", 262144, 2, {"engine": "fused",
                                     "delivery": "matmul"}),
    ("full", "push-sum", 262144, 8, {"engine": "fused",
                                     "delivery": "matmul"}),
)


def autotuned_cells() -> tuple:
    """AUDIT_GRID-style rows for the plans the autotuner CHOOSES on the
    AUTOTUNE_AUDIT requests: resolve plan='auto' with the committed
    calibration, translate each winner into (engine, ..., extra) with
    the winner's forcing overrides (e.g. the chosen pool2_wire) pinned —
    so the full matrix audits the autotuned plans' wire with the same
    checkers, specs, and schedule pairing as every hand row."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology

    from . import cost

    rows = []
    for topo_name, algo, n, n_dev, extra in AUTOTUNE_AUDIT:
        cfg = SimConfig(n=n, topology=topo_name, algorithm=algo,
                        plan="auto", n_devices=n_dev, **extra)
        topo = build_topology(topo_name, n)
        decision = cost.choose(topo, cfg)
        engine = decision.winner.name.split(":")[0]
        cell_extra = dict(extra)
        cell_extra.update(decision.winner.override_dict)
        rows.append((engine, topo_name, algo, n, n_dev, cell_extra))
    return tuple(rows)


# Serving batch-engine cells (ISSUE 14): the vmapped continuous chunk +
# the lane-refill program, traced through models.sweep.probe_batch_programs.
# The refill path's contract is the host-sync WHOLE-program check — the
# refill decision is host-side/clock-only, so no callback primitive may
# appear anywhere in the refill program (contracts.check_host_sync_whole).
BATCH_GRID = (
    ("full", "gossip", 64, 4, {}),
    ("full", "push-sum", 64, 4, {"telemetry": True}),
)

# Engines whose donation check also compiles and proves the HLO
# input_output_alias map (cheap XLA programs; the Pallas compositions'
# interpret-mode compiles are left to the execution suites).
_COMPILE_DONATION_ENGINES = frozenset({"sharded", "chunked"})


def setup_tracing_runtime(extra_devices: int = 0) -> None:
    """The one jax bootstrap every tracing CLI shares: CPU platform pin
    (this container's sitecustomize force-registers a TPU plugin — the
    env var alone does not stick), the partitionable threefry the
    cross-engine stream contract is defined over, and enough virtual host
    devices for the widest AUDIT_GRID mesh. Divergence here between entry
    points would silently audit different runtime configs."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from cop5615_gossip_protocol_tpu.utils import compat

    jax.config.update("jax_threefry_partitionable", True)
    need = max(extra_devices, max(g[4] for g in AUDIT_GRID))
    compat.set_host_device_count(need)


def _x64():
    import jax

    return jax.experimental.enable_x64()


def _trace_cell_x64(engine, topo, algo, n, n_dev, overlap, extra):
    with _x64():
        cell = trace.trace_cell(engine, topo, algo, n, n_dev, overlap, extra)
        cell.closed_jaxpr  # force the trace inside the x64 context
    return cell


def _report_of(cell) -> trace.AuditReport:
    return trace.AuditReport(
        engine=cell.engine, topology=cell.topology,
        algorithm=cell.algorithm, n=cell.n, n_devices=cell.n_devices,
        overlap=cell.overlap, counts=cell.counts,
    )


def _cell_contracts(cell, compile_check: bool) -> list[Finding]:
    out = contracts.check_host_sync(cell)
    out += contracts.check_dtype_policy(cell)
    out += contracts.check_matmul_delivery(cell)
    with _x64():
        out += contracts.check_donation(cell, compile_check=compile_check)
    return out


def audit_matrix(grid=None, single_grid=None, quick: bool = False,
                 progress=None) -> list[Finding]:
    """Run every checker over every cell; returns the combined findings.

    ``quick`` audits the XLA 'sharded'/'chunked' rows only (seconds).
    ``progress`` is an optional callable(str) for CLI status lines."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology

    say = progress or (lambda _msg: None)
    findings: list[Finding] = []
    grid = AUDIT_GRID if grid is None else grid
    single_grid = SINGLE_GRID if single_grid is None else single_grid
    if grid is AUDIT_GRID and not quick:
        # Full audits also walk the AUTOTUNED plans (ISSUE 17): resolve
        # the plan='auto' requests against the committed calibration and
        # audit whatever the cost model picked with the same checkers as
        # the hand rows above.
        say("resolve autotuned plans (analysis/calibration.json)")
        grid = grid + autotuned_cells()

    # Sharded cells, paired by schedule (and by transport for dma rows).
    wire_reports: dict[tuple, trace.AuditReport] = {}
    for engine, topo_name, algo, n, n_dev, extra in grid:
        if quick and engine != "sharded":
            continue
        spec = wire_specs.get_spec(engine)
        topo = build_topology(topo_name, n)
        pair = {}
        for overlap in (True, False):
            say(f"trace {engine}/{topo_name}/{algo}"
                f"{'/dma' if extra.get('halo_dma') == 'on' else ''}"
                f" overlap={'on' if overlap else 'off'}")
            cell = _trace_cell_x64(
                engine, topo_name, algo, n, n_dev, overlap, extra
            )
            rep = _report_of(cell)
            pair[overlap] = rep
            cfg = SimConfig(
                n=n, topology=topo_name, algorithm=algo,
                overlap_collectives=overlap, **extra,
            )
            findings += wire_specs.check_report(rep, topo, cfg)
            findings += _cell_contracts(
                cell, compile_check=engine in _COMPILE_DONATION_ENGINES
            )
        findings += wire_specs.check_schedule_pair(
            spec, pair[True], pair[False]
        )
        key = (engine, topo_name, algo, n, n_dev)
        if extra.get("halo_dma") == "on":
            wire = wire_reports.get(key)
            if wire is None:
                # A dma row with no traced wire sibling is a FINDING, not
                # a silent skip — otherwise the dma-bytes-match guarantee
                # would quietly depend on grid row ordering.
                findings.append(Finding(
                    checker="wire-spec",
                    where=f"{engine}/{topo_name}/{algo}/dma",
                    rule="no-wire-sibling",
                    detail=(
                        "halo_dma='on' grid row has no earlier wire-"
                        "transport sibling with the same (engine, "
                        "topology, algorithm, n, n_devices) — the cross-"
                        "transport byte equality cannot be checked; add "
                        "or reorder the wire row in AUDIT_GRID"
                    ),
                ))
            else:
                findings += wire_specs.check_transport_pair(
                    spec, wire, pair[True]
                )
        else:
            wire_reports[key] = pair[True]

    # Single-device cells: no WIRE_SPEC (nothing on the wire), contracts
    # only.
    for engine, topo_name, algo, n, n_dev, extra in single_grid:
        if quick and engine != "chunked":
            continue
        for overlap in (True, False):
            say(f"trace {engine}/{topo_name}/{algo}"
                f" overlap={'on' if overlap else 'off'}")
            cell = _trace_cell_x64(
                engine, topo_name, algo, n, n_dev, overlap, extra
            )
            findings += _cell_contracts(
                cell, compile_check=engine in _COMPILE_DONATION_ENGINES
            )

    # Serving batch-engine cells (one trace covers chunk + refill): the
    # continuous chunk gets the body host-sync/dtype/donation contracts;
    # the refill program gets the WHOLE-program host-sync check (the
    # ISSUE 14 refill-path lint) plus donation.
    if not quick:
        for topo_name, algo, n, lanes, extra in BATCH_GRID:
            say(f"trace batch/{topo_name}/{algo} lanes={lanes}")
            with _x64():
                cells = trace.trace_batch_cells(
                    topo_name, algo, n, lanes, extra
                )
                for cell in cells:
                    cell.closed_jaxpr
            for cell in cells:
                if cell.info.get("variant") == "batch-refill":
                    findings += contracts.check_host_sync_whole(cell)
                else:
                    findings += contracts.check_host_sync(cell)
                    findings += contracts.check_dtype_policy(cell)
                with _x64():
                    findings += contracts.check_donation(
                        cell, compile_check=True
                    )

    say("prng-tag map")
    findings += tags.check_tags()
    say("ast lints")
    findings += lint_rules.run_lints()
    return findings
