"""Static program auditor (ISSUE 11): contract checks over TRACED programs.

The engine matrix's correctness rests on structural invariants — collective
wire counts, donation aliasing, host-sync freedom, dtype policy, PRNG tag
disjointness, repo conventions — that were historically pinned dynamically
(per-configuration golden tests) or by docstring. This package proves them
statically, without executing a single program:

- ``jaxpr_walk``   — the reusable jaxpr visitor (region-aware: inside vs
                     outside while bodies; descends pallas_call; classifies
                     in-kernel remote DMAs). benchmarks/comm_audit.py is a
                     thin CLI over it.
- ``trace``        — hardware-free tracing of every engine's jitted chunk
                     through the run functions' ``probe`` hooks
                     (single-device chunked/fused AND the six sharded
                     compositions), returning AuditReports.
- ``wire_specs``   — declarative per-composition collective contracts (the
                     compositions each export WIRE_SPEC; the checker diffs
                     declaration against trace). The first externalized
                     fragment of the ROADMAP item-4 plan IR.
- ``contracts``    — host-sync freedom, dtype policy (f64/weak-type
                     promotion under an x64 trace), and donation
                     (input-output aliasing must cover the state carry)
                     checkers.
- ``tags``         — the PRNG fold_in TAG MAP (ops/faults.py docstring),
                     machine-verified: region registry + pairwise
                     disjointness + repo-wide AST harvest of fold_in sites.
- ``lint_rules``   — AST lints for repo conventions (no host conversions in
                     traced bodies, schema-version lockstep, refusal
                     messages name a real composition).
- ``matrix``       — the audited grid (AUDIT_GRID — sharded cells — plus
                     the single-device SINGLE_GRID) and ``audit_matrix``,
                     which traces every cell once under x64 and runs the
                     full checker set.
- ``report``       — Finding records, the committed suppression baseline
                     (baseline.json — empty: the tree audits clean),
                     JSON + human table rendering.

CLI: ``python -m cop5615_gossip_protocol_tpu.analysis`` (see __main__.py)
exits non-zero on any non-baselined finding (and on stale suppressions, so
the baseline only shrinks); the ``static-audit`` CI job runs it on every
push. Each checker's fires direction is pinned against the seeded-bad
fixtures in tests/fixtures/analysis/ (tests/test_static_audit.py).
"""

from .report import Finding, load_baseline, render_table  # noqa: F401
