"""CLI: ``python -m cop5615_gossip_protocol_tpu.analysis``.

Audits the full engine matrix statically (see matrix.audit_matrix — every
cell is TRACED, never executed, so the whole run is CPU-only and takes a
few minutes; ``--quick`` audits the XLA rows + lints in seconds) and exits

    0  every finding baselined (or none),
    1  at least one non-baselined finding,
    2  a baselined fingerprint no longer fires (stale suppression — the
       baseline may only shrink; delete the entry). Only FULL runs judge
       staleness: a --quick/--lint-only run audits a subset of the scope
       the baseline was recorded against.

``--json`` writes the CI artifact (all findings + baseline disposition);
the ``static-audit`` job uploads it on every push. To baseline a finding,
add ``{"fingerprint": ..., "reason": ...}`` to analysis/baseline.json —
a suppression without a recorded justification is rejected at load.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cop5615_gossip_protocol_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--json", type=str, default=None, metavar="FILE",
                    help="write the findings report as JSON (CI artifact)")
    ap.add_argument("--quick", action="store_true",
                    help="XLA engine rows + tag/lint passes only (seconds)")
    ap.add_argument("--lint-only", action="store_true",
                    help="AST lints + PRNG tag map only — no programs "
                    "traced (the tag registry still imports the engine "
                    "modules to read the real constants)")
    ap.add_argument("--baseline", type=str, default=None,
                    help="suppression baseline path (default: the "
                    "committed analysis/baseline.json)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-cell progress lines")
    args = ap.parse_args(argv)

    from . import report
    from .report import apply_baseline, load_baseline, render_table

    say = (lambda _m: None) if args.quiet else (
        lambda m: print(f"[static-audit] {m}", file=sys.stderr, flush=True)
    )

    # CPU pin FIRST, on every mode: even --lint-only reaches jax (the tag
    # registry imports the engine modules for the real constants), and on
    # a TPU host an unpinned import would claim the chip.
    from . import matrix

    matrix.setup_tracing_runtime()

    if args.lint_only:
        from . import lint_rules, tags

        findings = tags.check_tags() + lint_rules.run_lints()
    else:
        findings = matrix.audit_matrix(quick=args.quick, progress=say)

    baseline = load_baseline(args.baseline)
    new, suppressed, stale = apply_baseline(findings, baseline)
    # The stale check is only sound against the scope the baseline was
    # recorded for — the FULL matrix. A reduced run (--quick/--lint-only)
    # never fires traced-cell findings, so their suppressions would be
    # falsely reported stale (and deleted by a developer following the
    # message).
    full_scope = not (args.quick or args.lint_only)
    if not full_scope:
        stale = []

    print("# Static audit")
    print()
    print("\n".join(render_table(new)))
    if suppressed:
        print(f"\n{len(suppressed)} baselined finding(s) suppressed.")
    if stale:
        print("\nSTALE suppressions (no longer fire — delete them):")
        for fp in stale:
            print(f"  - {fp}")
    if args.json:
        report.write_json(findings, new, suppressed, stale, args.json)
        say(f"wrote {args.json}")

    if new:
        say(f"{len(new)} non-baselined finding(s)")
        return 1
    if stale:
        say(f"{len(stale)} stale suppression(s)")
        return 2
    say("clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
