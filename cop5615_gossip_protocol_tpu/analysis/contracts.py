"""Jaxpr/lowering-level contract checkers over traced chunk programs.

Each checker takes a ``trace.TracedCell`` (or a cell spec) and returns a
list of ``report.Finding`` — empty when the contract holds. Nothing here
executes a program: host-sync and dtype policy walk the traced jaxpr,
donation inspects the LOWERED module's aliasing attributes (and, under
``compile_check``, the compiled HLO's ``input_output_alias`` map — still
trace/compile only, never dispatch).

Host-sync freedom
    No callback/infeed primitive (jaxpr_walk.HOST_SYNC_PRIMS) inside a
    chunk-loop body: each would force a device<->host round-trip once per
    ROUND — exactly the per-dispatch cost the chunked drivers amortize.

Dtype policy
    Traced under ``jax.experimental.enable_x64``: any float64 abstract
    value inside the loop body is either a real f64 plane (banned outside
    the verdict/mass-accumulator allowlist) or a weak-type promotion (a
    Python/np.float64 scalar leaking into f32 arithmetic — the classic
    "fine on CPU-without-x64, silently doubles HBM traffic under x64"
    bug). The engines compute in float32 with f64 reserved for HOST-side
    diagnostics, so a clean body is the expected state.

Donation
    Whenever a run function reports donate=True, the state carry (argument
    0, every engine's chunk signature) must actually be covered by
    input-output aliasing — an unaliased donated buffer silently costs a
    full state copy per chunk. Single-device lowerings resolve aliasing at
    lowering time (``tf.aliasing_output``); shard_map lowerings defer to
    the compiler (``jax.buffer_donor``), which ``compile_check=True``
    resolves through the compiled HLO's ``input_output_alias`` map.

MXU matmul delivery
    A ``delivery='matmul'`` cell's traced chunk must aggregate on the MXU:
    at least one ``dot_general`` in the program (the blocked one-hot
    delivery, or the fused kernels' 128x128 one-hot lane blend) and ZERO
    scatter-family primitives anywhere in it — a scatter reappearing would
    mean the tier silently fell back to the dynamic-address path whose
    ~8-12 ns/element floor the tier exists to escape. Fires direction
    pinned by the seeded-bad fixture (tests/fixtures/analysis).
"""

from __future__ import annotations

import re

from . import jaxpr_walk
from .report import Finding


def _cell_where(cell) -> str:
    tags = [cell.engine, cell.topology, cell.algorithm,
            "overlap" if cell.overlap else "serial"]
    if cell.extras.get("halo_dma") == "on":
        tags.append("dma")
    if cell.extras.get("crash_rate") or cell.extras.get("crash_schedule"):
        tags.append("crash")
    if cell.extras.get("revive_rate") or cell.extras.get("revive_schedule"):
        tags.append("revive")
    return "/".join(tags)


def check_host_sync(cell) -> list[Finding]:
    """No host round-trip primitive inside the chunk-loop body."""
    hits: dict[str, int] = {}
    for eqn, in_body in jaxpr_walk.iter_eqns(cell.closed_jaxpr.jaxpr):
        if in_body and eqn.primitive.name in jaxpr_walk.HOST_SYNC_PRIMS:
            hits[eqn.primitive.name] = hits.get(eqn.primitive.name, 0) + 1
    return [
        Finding(
            checker="host-sync",
            where=_cell_where(cell),
            rule=f"body-{prim}",
            detail=(
                f"{count}x {prim} inside the chunk-loop body — a "
                "device<->host round-trip per round; hoist it to a chunk "
                "boundary hook or the telemetry plane"
            ),
        )
        for prim, count in sorted(hits.items())
    ]


def check_host_sync_whole(cell) -> list[Finding]:
    """The refill-path lint (ISSUE 14): chunk-BOUNDARY programs — the
    continuous-batching lane-refill and lane-init programs — must be pure
    device programs with no callback primitive ANYWHERE, not just inside
    a loop body (they have none): the refill decision is host-side and
    clock-only by contract (models/sweep.serve_lanes), so a callback
    appearing in the traced refill program would mean the decision leaked
    INTO the trace — a device<->host round trip per refill, and a refill
    schedule no longer replayable from the host alone. Fires direction
    pinned on the seeded-bad ``host_callback_refill`` fixture."""
    hits: dict[str, int] = {}
    for eqn, _in_body in jaxpr_walk.iter_eqns(cell.closed_jaxpr.jaxpr):
        if eqn.primitive.name in jaxpr_walk.HOST_SYNC_PRIMS:
            hits[eqn.primitive.name] = hits.get(eqn.primitive.name, 0) + 1
    where = _cell_where(cell)
    variant = cell.info.get("variant")
    if variant:
        where = f"{where}/{variant}"
    return [
        Finding(
            checker="host-sync",
            where=where,
            rule=f"refill-{prim}",
            detail=(
                f"{count}x {prim} in a chunk-boundary (refill/lane-init) "
                "program — the continuous-batching refill path must stay "
                "host-side and clock-only (pure selects over the batch "
                "carry); a callback here is a device<->host round trip "
                "per refill"
            ),
        )
        for prim, count in sorted(hits.items())
    ]


def check_matmul_delivery(cell) -> list[Finding]:
    """delivery='matmul' cells aggregate on the MXU: >= 1 dot_general in
    the traced chunk, zero scatter-family primitives anywhere in it.

    Scans the WHOLE program, not just the while body: the fused tiers'
    round loop is the pallas_call grid (no XLA while wraps the kernel), so
    a body-only scan would miss them — and a scatter anywhere in a matmul
    chunk is a fallback onto the dynamic-address path either way. No-op
    for cells that did not resolve the matmul rung."""
    if cell.extras.get("delivery") != "matmul":
        return []
    where = _cell_where(cell)
    dots = 0
    scatters: dict[str, int] = {}
    for eqn, _in_body in jaxpr_walk.iter_eqns(cell.closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name == "dot_general":
            dots += 1
        elif name.startswith("scatter"):
            scatters[name] = scatters.get(name, 0) + 1
    findings = []
    if dots == 0:
        findings.append(Finding(
            checker="matmul-delivery", where=where, rule="no-dot-general",
            detail=(
                "delivery='matmul' resolved but the traced chunk contains "
                "no dot_general — the round is not aggregating on the MXU "
                "(the one-hot delivery silently fell back to a VPU "
                "formulation)"
            ),
        ))
    for prim, count in sorted(scatters.items()):
        findings.append(Finding(
            checker="matmul-delivery", where=where, rule=f"scatter-{prim}",
            detail=(
                f"{count}x {prim} in a delivery='matmul' chunk — the MXU "
                "tier must carry zero scatter primitives (a scatter is the "
                "~8-12 ns/element dynamic-address fallback the tier "
                "exists to escape)"
            ),
        ))
    return findings


# f64 reduction primitives that MAY carry float64 inside a body when the
# value is a declared verdict/mass accumulator. Empty today: every engine
# computes in float32 and keeps f64 on the host (models/runner.py
# _finalize_result). Extend via the allowlist argument, not by widening
# this set.
_F64_ACCUMULATOR_PRIMS: frozenset = frozenset()


def check_dtype_policy(cell, allowlist: frozenset = _F64_ACCUMULATOR_PRIMS,
                       ) -> list[Finding]:
    """No f64 avals (and hence no weak-type f64 promotions) in the body.

    Meaningful only when ``cell`` was traced under
    ``jax.experimental.enable_x64()`` — without x64 every float is forced
    to f32 and the scan can never fire. ``matrix.audit_matrix`` traces the
    dtype cells that way."""
    hits: dict[str, int] = {}
    for eqn, in_body in jaxpr_walk.iter_eqns(cell.closed_jaxpr.jaxpr):
        if not in_body or eqn.primitive.name in allowlist:
            continue
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None and str(dt) == "float64":
                hits[eqn.primitive.name] = hits.get(eqn.primitive.name, 0) + 1
    return [
        Finding(
            checker="dtype-policy",
            where=_cell_where(cell),
            rule=f"body-f64-{prim}",
            detail=(
                f"{count}x {prim} produces float64 inside the loop body "
                "under an x64 trace — a stray f64 plane or a weak-type "
                "promotion (np.float64/Python-float scalar reaching f32 "
                "arithmetic); pin the scalar's dtype"
            ),
        )
        for prim, count in sorted(hits.items())
    ]


_MAIN_SIG = re.compile(r"@main\((.*?)\)\s*->", re.S)
# One compiled-HLO alias entry: "{out...}: (param, {...}" — we only need
# the source param number.
_ALIAS_ENTRY = re.compile(r"\{[^{}]*\}:\s*\((\d+)\s*,")


def _lowered(cell):
    """Lower the cell's chunk with the donation the run reported. Sharded
    cells captured an already-jitted fn (donate_argnums baked in); the
    single-device paths hand the probe the plain jittable.

    Returns None when the cell cannot LOWER on this backend: the
    ``halo_dma='on'`` cells build TPU-style async-remote-copy kernels
    (interpret=False) that trace hardware-free for the wire counts but
    have no CPU lowering. Their donation contract is covered by the wire
    sibling — same chunk skeleton, same carry, interpret-mode kernels."""
    import jax

    fn = cell.fn
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn, donate_argnums=(0,) if cell.donate else ())
    try:
        return fn.lower(*cell.args)
    except ValueError as e:
        if "interpret mode" in str(e):
            return None
        raise


def check_donation(cell, compile_check: bool = False) -> list[Finding]:
    """Donation must cover the whole state carry when donate=True.

    Lowering level: every state leaf (args 0..N-1 of the flat @main
    signature) must carry ``tf.aliasing_output`` (alias resolved) or
    ``jax.buffer_donor`` (deferred to the compiler). ``compile_check``
    additionally compiles and requires every state-leaf param to appear as
    a source in the HLO ``input_output_alias`` map — the proof that a
    deferred donor actually aliased instead of silently copying."""
    if not cell.donate:
        return []
    findings = []
    where = _cell_where(cell)
    lowered = _lowered(cell)
    if lowered is None:  # no CPU lowering (dma cells) — see _lowered
        return []
    sig = _MAIN_SIG.search(lowered.as_text())
    n_leaves = cell.state_leaves
    if sig is None:
        return [Finding(
            checker="donation", where=where, rule="unparseable-lowering",
            detail="no @main signature in the lowered module",
        )]
    params = re.split(r"%arg\d+", sig.group(1))[1:]
    for i, param in enumerate(params[:n_leaves]):
        if "tf.aliasing_output" not in param and (
            "jax.buffer_donor" not in param
        ):
            findings.append(Finding(
                checker="donation", where=where, rule=f"state-leaf-{i}",
                detail=(
                    f"state-carry leaf {i} of {n_leaves} is neither "
                    "aliased nor marked donor in the lowering while the "
                    "run reported donate=True — the donated buffer is "
                    "silently copied every chunk"
                ),
            ))
    if compile_check and not findings:
        txt = lowered.compile().as_text()
        m = re.search(r"input_output_alias=\{(.*?)\}[,\s]*entry", txt, re.S)
        aliased = (
            {int(p) for p in _ALIAS_ENTRY.findall(m.group(1))} if m else set()
        )
        for i in range(n_leaves):
            if i not in aliased:
                findings.append(Finding(
                    checker="donation", where=where,
                    rule=f"compiled-state-leaf-{i}",
                    detail=(
                        f"state-carry leaf {i} of {n_leaves} has no entry "
                        "in the compiled input_output_alias map — donation "
                        "was requested but the compiler could not alias it "
                        "(shape/dtype mismatch between carry in and out?)"
                    ),
                ))
    return findings
