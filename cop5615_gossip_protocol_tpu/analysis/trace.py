"""Hardware-free tracing of every engine's chunk program.

One entry — ``trace_cell`` — builds the named engine's jitted chunk through
its run function's ``probe`` hook (models/runner.run for the single-device
chunked/fused paths, the parallel/ run functions for the six sharded
compositions). The program is TRACED, never executed, so a full matrix
audit runs in seconds on CPU with virtual devices; the captured
``TracedCell`` carries the chunk callable, ready-to-trace arguments, the
run's donation decision, and a cached closed jaxpr every checker shares.

``audit_engine`` (the benchmarks/comm_audit.py entry, kept under its
historical name) reduces a cell to an ``AuditReport`` of collective counts
by region — the record the wire-spec checker diffs declarations against.
"""

from __future__ import annotations

import dataclasses
import functools
import importlib

from . import jaxpr_walk
from .wire_specs import SPEC_HOMES

REMOTE_DMA = jaxpr_walk.REMOTE_DMA

# Engine name -> the run function (in its SPEC_HOMES module) that owns the
# probe hook. Keyed off the same registry as the wire contracts, so a
# composition cannot be traceable without a declared spec home.
_SHARDED_RUN_FNS = {
    "sharded": "run_sharded",
    "fused-sharded": "run_fused_sharded",
    "fused-pool-sharded": "run_fused_pool_sharded",
    "hbm-sharded": "run_stencil_hbm_sharded",
    "imp-hbm-sharded": "run_imp_hbm_sharded",
    "pool2-sharded": "run_pool2_sharded",
}
SHARDED_ENGINES = tuple(_SHARDED_RUN_FNS)
# Single-device cells go through models.runner.run, which dispatches on
# cfg.engine (and picks the fused tier from topology/population).
SINGLE_ENGINES = ("chunked", "fused")


@dataclasses.dataclass
class TracedCell:
    """One engine x config cell's chunk program, captured pre-execution."""

    engine: str
    topology: str
    algorithm: str
    n: int
    n_devices: int
    overlap: bool
    extras: dict
    fn: object  # the chunk callable (jitted for sharded compositions)
    args: tuple  # ready-to-trace arguments
    donate: bool  # the donation decision the run reported
    info: dict = dataclasses.field(default_factory=dict)  # extra probe
    # kwargs, e.g. the fused tier the single-device dispatch resolved
    # ("variant")

    @functools.cached_property
    def closed_jaxpr(self):
        import jax

        return jax.make_jaxpr(self.fn)(*self.args)

    @functools.cached_property
    def counts(self) -> dict:
        return jaxpr_walk.collect_collectives(self.closed_jaxpr.jaxpr)

    @property
    def state_leaves(self) -> int:
        """Leaf count of the state-carry argument (always argument 0 of
        every engine's chunk signature — the donated one)."""
        import jax

        return len(jax.tree_util.tree_leaves(self.args[0]))


@dataclasses.dataclass
class AuditReport:
    """Collective counts for one engine x config x schedule."""

    engine: str
    topology: str
    algorithm: str
    n: int
    n_devices: int
    overlap: bool
    # {"body": {prim: {"count": int, "bytes": int}}, "setup": {...}} —
    # "body" is inside the chunk's while loop (per round / super-step),
    # "setup" is the rest of the dispatch (paid once per chunk).
    counts: dict

    def body_count(self, prim: str) -> int:
        return self.counts["body"].get(prim, {}).get("count", 0)

    def setup_count(self, prim: str) -> int:
        return self.counts["setup"].get(prim, {}).get("count", 0)

    def body_bytes(self, prim: str) -> int:
        return self.counts["body"].get(prim, {}).get("bytes", 0)

    def body_bytes_out(self, prim: str) -> int:
        """Per-device RECEIVED payload bytes (the collective's output
        avals — what actually lands in each device's memory per
        round/super-step): an all_gather's output is the n_dev-wide full
        copy, a reduce_scatter's only the local shard, which is exactly
        the O(N) -> O(N/P + margins) delta the replicated-pool2 band wire
        claims (ISSUE 15)."""
        return self.counts["body"].get(prim, {}).get("bytes_out", 0)

    def halo_mechanism(self) -> str:
        """How this composition's halo/delivery bytes move between
        devices, decided from the counted program — never from config:
        in-kernel-dma (Pallas async remote copies, zero XLA collectives
        on the halo path), reduce-scatter (the replicated-pool2 band
        wire: banded reduce_scatters plus their margin ppermute volley),
        xla-ppermute (halo boundary wires), all-gather (the pool
        composition's plane gather), scatter (the chunked engine's
        psum_scatter fallback — reduce_scatter with NO margin ppermute),
        or none (no inter-device delivery in the body)."""
        if self.body_count(REMOTE_DMA):
            return "in-kernel-dma"
        if self.body_count("reduce_scatter") and self.body_count("ppermute"):
            return "reduce-scatter"
        if self.body_count("ppermute"):
            return "xla-ppermute"
        if self.body_count("all_gather"):
            return "all-gather"
        if self.body_count("reduce_scatter"):
            return "scatter"
        return "none"

    def to_record(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["halo_mechanism"] = self.halo_mechanism()
        return rec


def _capture_probe(sink: dict):
    def probe(chunk_fn, args, donate=False, **info):
        sink.update(fn=chunk_fn, args=args, donate=donate, info=info)
        return None

    return probe


def trace_cell(engine: str, topology: str, algorithm: str, n: int,
               n_devices: int, overlap: bool,
               cfg_overrides: dict | None = None) -> TracedCell:
    """Build one engine's jitted chunk through its run function's ``probe``
    hook and capture it without executing. ``engine`` is one of
    SHARDED_ENGINES ('sharded' = chunked XLA under shard_map,
    'fused-sharded' = VMEM lattice composition, 'fused-pool-sharded',
    'hbm-sharded', 'imp-hbm-sharded', 'pool2-sharded') or SINGLE_ENGINES
    ('chunked' / 'fused' — models.runner dispatch picks the fused tier)."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology

    overrides = dict(cfg_overrides or {})
    if engine in SINGLE_ENGINES:
        overrides.setdefault("engine", engine)
    cfg = SimConfig(
        n=n, topology=topology, algorithm=algorithm,
        overlap_collectives=overlap, **overrides,
    )
    topo = build_topology(topology, n)
    sink: dict = {}
    probe = _capture_probe(sink)
    if engine in SINGLE_ENGINES:
        from cop5615_gossip_protocol_tpu.models import runner

        runner.run(topo, cfg, probe=probe)
    elif engine in _SHARDED_RUN_FNS:
        from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(n_devices)
        mod = importlib.import_module(SPEC_HOMES[engine])
        run_fn = getattr(mod, _SHARDED_RUN_FNS[engine])
        run_fn(topo, cfg, mesh=mesh, probe=probe)
    else:
        raise ValueError(
            f"unknown engine {engine!r}: expected one of "
            f"{SINGLE_ENGINES + SHARDED_ENGINES}"
        )
    if "fn" not in sink:
        raise RuntimeError(
            f"probe hook never fired for engine {engine!r} — the run "
            "function returned without building a chunk"
        )
    return TracedCell(
        engine=engine, topology=topology, algorithm=algorithm, n=n,
        n_devices=n_devices, overlap=overlap, extras=dict(cfg_overrides or {}),
        fn=sink["fn"], args=sink["args"], donate=sink["donate"],
        info=sink.get("info") or {},
    )


def trace_batch_cells(topology: str, algorithm: str, n: int, lanes: int,
                      cfg_overrides: dict | None = None) -> list:
    """Capture the serving batch engine's programs (ISSUE 14) without
    executing them: the vmapped continuous chunk (``variant:
    'batch-chunk'``) and the lane-refill program (``'batch-refill'``),
    through ``models.sweep.probe_batch_programs`` — state arguments are
    eval_shape zeros, so this stays trace-only like every other cell."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models import sweep

    overrides = dict(cfg_overrides or {})
    overrides.setdefault("engine", "chunked")
    cfg = SimConfig(
        n=n, topology=topology, algorithm=algorithm, **overrides
    )
    topo = build_topology(topology, n)
    cells: list = []

    def probe(fn, args, donate=False, **info):
        cells.append(TracedCell(
            engine="batch", topology=topology, algorithm=algorithm, n=n,
            n_devices=1, overlap=True, extras=dict(cfg_overrides or {}),
            fn=fn, args=args, donate=donate, info=info,
        ))

    sweep.probe_batch_programs(topo, cfg, lanes, probe)
    if not cells:
        raise RuntimeError(
            "probe_batch_programs handed back no programs — the batch "
            "engine's probe path is broken"
        )
    return cells


def audit_engine(engine: str, topology: str, algorithm: str, n: int,
                 n_devices: int, overlap: bool,
                 cfg_overrides: dict | None = None) -> AuditReport:
    """Trace one cell and reduce it to collective counts by region — the
    benchmarks/comm_audit.py entry, unchanged in name and signature."""
    cell = trace_cell(
        engine, topology, algorithm, n, n_devices, overlap, cfg_overrides
    )
    return AuditReport(
        engine=engine, topology=topology, algorithm=algorithm, n=n,
        n_devices=n_devices, overlap=overlap, counts=cell.counts,
    )
