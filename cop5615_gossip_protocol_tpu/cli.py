"""CLI — reference-parity positional triple plus real flags.

The reference reads three raw positional args with no validation and no
flags (`dotnet run <numNodes> <topology> <algorithm>`, program.fs:19-21;
arg order per report.pdf p.2 §2 — note the reference's own source comments
at program.fs:20-21 label the two strings backwards). This CLI keeps that
triple — `python -m cop5615_gossip_protocol_tpu 1000 full gossip` — and
fails loudly on invalid input instead of the reference's silent
fall-through-to-ReadLine (program.fs:331-334).

Everything the reference hard-codes is a flag here: rumor threshold
(program.fs:102), delta (program.fs:187), termination rounds
(program.fs:135), plus seed/dtype/semantics/devices/fault-rate/
checkpointing (SURVEY.md §5 "Config / flag system").
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys
import time
from typing import Optional

from .config import SimConfig, normalize_algorithm, normalize_topology


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gossip-tpu",
        description=(
            "TPU-native gossip / push-sum simulator "
            "(usage parity: numNodes topology algorithm)"
        ),
    )
    p.add_argument("numNodes", type=int, help="requested node count")
    p.add_argument(
        "topology",
        help="line | full | 2D | Imp3D (reference spellings) or "
        "ring | grid2d | ref2d | imp2d | grid3d | torus3d",
    )
    p.add_argument("algorithm", help="gossip | push-sum")
    p.add_argument(
        "--backend",
        choices=["jax", "refsim", "akka"],
        default="jax",
        help="jax: the TPU-native batched engine (default); refsim: the "
        "native C++ discrete-event model of the reference's Akka actor "
        "semantics (native/refsim.cpp — the runnable stand-in for "
        "`dotnet run`, no .NET in this image); akka is an alias for refsim",
    )
    p.add_argument(
        "--semantics",
        choices=["batched", "reference"],
        default="batched",
        help="batched: honest synchronous rounds (benchmark mode); "
        "reference: replicate the reference's quirks Q1-Q9 incl. "
        "single-walk push-sum",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--dtype", choices=["float32", "float64", "bfloat16"], default=None,
                   help="default: float32 (float64 on CPU with --x64)")
    p.add_argument("--delta", type=float, default=None,
                   help="push-sum stability threshold (default per dtype; reference: 1e-10)")
    p.add_argument("--rumor-threshold", type=int, default=10)
    p.add_argument("--term-rounds", type=int, default=3)
    p.add_argument("--termination", choices=["local", "global"], default="local",
                   help="push-sum stop rule: local = the reference's per-node "
                   "consecutive-stability latch (program.fs:119-137); global "
                   "= stop when every node's per-round relative ratio change "
                   "is <= delta (the honest global-residual criterion)")
    p.add_argument("--max-rounds", type=int, default=1_000_000)
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="end-to-end run deadline: the chunk driver checks "
                   "it at every retired boundary (models/pipeline.py "
                   "cancellation hook — the same one the serving plane's "
                   "per-request deadline_ms uses) and a fired deadline "
                   "ends the run within one chunk as "
                   "outcome='deadline_exceeded' with partial state/"
                   "telemetry and exact rounds (run-record schema v5)")
    p.add_argument("--chunk-rounds", type=int, default=4096)
    p.add_argument("--pipeline-chunks", type=int, default=2,
                   help="speculative chunk pipelining depth: how many jit'd "
                   "chunks the host keeps in flight (chunk k+1 dispatches "
                   "before chunk k's predicate is read, hiding the "
                   "per-dispatch launch floor; 1 = serial loop; bitwise-"
                   "neutral by the overshoot contract, models/pipeline.py)")
    p.add_argument("--overlap-collectives", choices=["on", "off"],
                   default="on",
                   help="sharded-engine collective/compute overlap "
                   "(parallel/overlap.py): on (default) = batched "
                   "single-pair halo wires + the fused compositions' "
                   "termination psum deferred under the next super-step's "
                   "kernel; off = the serial per-plane/per-class schedule. "
                   "Bitwise-identical trajectories either way (pure "
                   "scheduling; tests/test_overlap.py)")
    p.add_argument("--halo-dma", choices=["auto", "on", "off"],
                   default="auto",
                   help="in-kernel halo delivery for the HBM-streaming x "
                   "sharded composition: auto (default) = Pallas "
                   "async-remote-copy neighbor DMA on TPU (zero XLA "
                   "collectives on the halo path, boundary-tile DMA "
                   "overlapped with interior tile streaming), batched "
                   "ppermute wire on CPU/interpret; on = force the DMA "
                   "kernel (TPU execution only); off = pin the XLA wire. "
                   "Bitwise transport-invariant trajectories")
    p.add_argument("--pool2-wire",
                   choices=["auto", "reduce_scatter", "all_gather"],
                   default="auto",
                   help="delivery wire of the replicated-pool2 "
                   "composition: reduce_scatter = each device receives "
                   "only the O(N/P) summary band its windows consume plus "
                   "the pooled margins (one banded reduce_scatter per "
                   "pool slot + one margin ppermute volley); all_gather = "
                   "the full O(N) summary copy per device per round. "
                   "auto (default) picks reduce_scatter when the mesh is "
                   "wider than the pool. Bitwise-identical trajectories "
                   "either way (pure wire packaging; "
                   "tests/test_pool2_sharded.py)")
    p.add_argument("--replicas", type=int, default=1,
                   help="run this many replicas (distinct per-replica key "
                   "streams, replica 0 = the unbatched run) of the "
                   "configuration in ONE vmapped chunked program and report "
                   "per-replica + mean/CI95 statistics (models/sweep.py); "
                   "chunked engines only")
    p.add_argument("--target-frac", type=float, default=None)
    p.add_argument("--suppress", choices=["auto", "on", "off"], default="auto",
                   help="suppress gossip sends to converged targets (auto: on in reference semantics)")
    p.add_argument("--fault-rate", type=float, default=0.0,
                   help="per-round probability a node fails to send (fault injection)")
    p.add_argument("--crash-rate", type=float, default=0.0,
                   help="crash-stop churn: per-round probability each node "
                   "dies permanently (dead nodes neither send nor advance; "
                   "push-sum mass parks on them, conserved)")
    p.add_argument("--crash-schedule", type=str, default=None,
                   metavar="ROUND:COUNT,...",
                   help="deterministic crash-stop schedule: kill COUNT "
                   "uniformly random nodes at each listed round "
                   "(mutually exclusive with --crash-rate)")
    p.add_argument("--revive-rate", type=float, default=0.0,
                   help="crash-recovery churn: per-round probability each "
                   "DEAD node rejoins (geometric dead-time; requires a "
                   "crash model). Gossip revivals rejoin susceptible; "
                   "push-sum rejoin semantics per --rejoin")
    p.add_argument("--revive-schedule", type=str, default=None,
                   metavar="ROUND:COUNT,...",
                   help="deterministic recovery schedule: rejoin COUNT "
                   "uniformly random dead nodes at each listed round "
                   "(mutually exclusive with --revive-rate; requires a "
                   "crash model)")
    p.add_argument("--rejoin", choices=["restore", "fresh"], default="restore",
                   help="push-sum revival semantics: restore = reclaim the "
                   "parked (s, w) mass (conserving); fresh = reset to "
                   "(s=x_i, w=0), discarding parked mass (the modeled "
                   "fault)")
    p.add_argument("--byzantine-rate", type=float, default=0.0,
                   help="adversarial plane: probability each node is "
                   "Byzantine from round 0 (adversaries stay ALIVE and "
                   "count toward quorum; behavior per --byzantine-mode)")
    p.add_argument("--byzantine-schedule", type=str, default=None,
                   metavar="ROUND:COUNT,...",
                   help="deterministic adversary onsets: turn COUNT "
                   "uniformly random nodes Byzantine at each listed round "
                   "(mutually exclusive with --byzantine-rate)")
    p.add_argument("--byzantine-mode",
                   choices=["mass_inflate", "mass_deflate", "stale_rumor",
                            "garble"],
                   default="mass_inflate",
                   help="what adversaries do: push-sum wire corruption "
                   "(mass_inflate = send the unhalved state, mass_deflate "
                   "= send negated mass, garble = swap s/w channels); "
                   "gossip state corruption (stale_rumor = perpetual rumor "
                   "re-injection, garble = fake convergence)")
    p.add_argument("--robust-agg", choices=["none", "clip", "trim"],
                   default="none",
                   help="push-sum countermeasure (chunked engine): bound "
                   "per-round accepted contributions — clip scales each "
                   "received (s, w) pair to a dynamic envelope; trim drops "
                   "the largest-|w| pool contribution channel "
                   "(delivery='pool')")
    p.add_argument("--mass-tolerance", type=float, default=None,
                   help="health sentinel (push-sum, chunked/sharded "
                   "engines): every round also checks state finiteness and "
                   "|sum(w) - n| against this tolerance; a trip ends the "
                   "run with outcome=unhealthy + the offending round "
                   "instead of converging wrong")
    p.add_argument("--strict-engine", action="store_true",
                   help="fail fast on engine errors instead of walking the "
                   "graceful-degradation ladder (fused->chunked, "
                   "sharded->single-device; models/runner.py). The "
                   "GOSSIP_TPU_STRICT_ENGINE env var overrides either way")
    p.add_argument("--dup-rate", type=float, default=0.0,
                   help="per-round probability a sent message is delivered "
                   "twice (at-least-once delivery; chunked engine, "
                   "scatter/stencil delivery)")
    p.add_argument("--delay-rounds", type=int, default=0,
                   help="defer every round's deliveries through a ring of "
                   "this depth (bounded message delay; chunked engine, "
                   "scatter/stencil delivery)")
    p.add_argument("--quorum", type=float, default=1.0,
                   help="crash-model termination: fraction of LIVE nodes "
                   "that must be converged to end the run (default 1.0)")
    p.add_argument("--stall-chunks", type=int, default=0,
                   help="watchdog: stop with outcome=stalled after this "
                   "many consecutive chunks without converged-count "
                   "progress (0 disables) — the reference's line-topology "
                   "hang as a measured event")
    p.add_argument("--delivery",
                   choices=["auto", "scatter", "stencil", "pool", "matmul"],
                   default="auto",
                   help="message delivery: stencil (shift-based, offset-structured "
                   "topologies) vs scatter-add vs pool (per-round shared "
                   "displacement pool, delivery as masked rolls — on the full "
                   "topology as offset-pool sampling, on imp2d/imp3d as pooled "
                   "long-range edges over the lattice stencil) vs matmul (the "
                   "MXU tier: the same pooled sampling stream delivered as a "
                   "blocked one-hot dot_general — gossip bitwise the pool "
                   "path); auto picks stencil where legal")
    p.add_argument("--pool-size", type=int, default=4,
                   help="displacement-pool width for --delivery pool/matmul "
                   "(power of two)")
    p.add_argument("--engine", choices=["auto", "chunked", "fused"], default="auto",
                   help="round engine: chunked (XLA while_loop) vs fused (Pallas "
                   "multi-round kernel, VMEM-resident state); auto fuses on TPU "
                   "where eligible")
    p.add_argument("--plan", choices=["hand", "auto"], default="hand",
                   help="plan selection: hand (the maintained dispatch "
                   "ladder) vs auto (the measured cost model — "
                   "analysis/cost.py scores the legal candidates from "
                   "analysis/calibration.json floors, picks the winner, "
                   "and logs a plan-chosen event with the ranked table)")
    p.add_argument("--devices", type=int, default=None,
                   help="shard the node dimension over this many devices")
    p.add_argument("--platform", choices=["auto", "cpu", "tpu"], default="auto",
                   help="force a JAX platform (cpu useful for dev boxes)")
    p.add_argument("--compile-cache", type=str, default=None, metavar="DIR",
                   help="enable XLA's persistent compilation cache at DIR "
                   "('auto' = ~/.cache/gossip_tpu_xla or "
                   "$GOSSIP_TPU_COMPILE_CACHE) so repeated runs stop "
                   "re-paying compile")
    p.add_argument("--x64", action="store_true", help="enable float64 support")
    p.add_argument("--distributed", action="store_true",
                   help="call jax.distributed.initialize for multi-host meshes "
                   "(auto-detected cluster env, e.g. TPU pods)")
    p.add_argument("--coordinator", type=str, default=None, metavar="HOST:PORT",
                   help="explicit jax.distributed coordinator address (implies "
                   "--distributed; use with --num-processes/--process-id for "
                   "clusters without auto-detection, incl. multi-process CPU)")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total process count for --coordinator")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's rank for --coordinator")
    p.add_argument("--profile", type=str, default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run into DIR "
                   "(viewable in TensorBoard/Perfetto; round phases are "
                   "named_scope-tagged sample / deliver / absorb, and chunk "
                   "boundaries carry chunkloop.dispatch / fetch / retire "
                   "annotations from the pipelined driver)")
    p.add_argument("--jsonl", type=str, default=None,
                   help="append the structured run record to this JSONL file")
    p.add_argument("--metrics-dump", type=str, default=None, metavar="FILE",
                   help="after the run, write the process metrics registry "
                   "(utils/obs.py) as Prometheus text exposition to FILE "
                   "('-' = stdout): run outcome/rounds counters, the full "
                   "wall budget (build/compile/dispatch/fetch/hook/"
                   "residual), per-chunk dispatch/fetch histograms, and "
                   "the warm-engine pool counters — the same vocabulary "
                   "the serving plane serves at GET /metrics; under "
                   "multi-process runs every process writes FILE.proc<k> "
                   "and process 0 federates them (counters summed, gauges "
                   "per-process, histograms bucket-merged) into FILE")
    p.add_argument("--step-timing", action="store_true",
                   help="clock super-step boundaries on the host "
                   "(cfg.step_timing): per-dispatch wall histogram, "
                   "straggler skew, and the measured side of the "
                   "autotuner's measured-vs-predicted table "
                   "(benchmarks/trend.py --step-timing); clock-only and "
                   "OFF by default — refused loudly where it would force "
                   "a host sync inside the overlapped super-step schedule "
                   "(use --overlap-collectives off there)")
    p.add_argument("--telemetry", action="store_true",
                   help="enable the in-program telemetry plane "
                   "(ops/telemetry.py): per-ROUND counters accumulated on "
                   "device inside the chunk program and fetched "
                   "asynchronously — no extra host syncs, donation and "
                   "pipelining stay on; the trajectory rides the RunResult "
                   "(and --trace-convergence serializes it)")
    p.add_argument("--trace-convergence", type=str, default=None,
                   metavar="FILE",
                   help="write the per-ROUND convergence trajectory (rounds, "
                   "converged/newly-converged counts, active count or "
                   "estimate error) as JSONL — implies --telemetry; the "
                   "counters come from the on-device telemetry plane, so "
                   "the run keeps its pipelined/donated hot path (the "
                   "pre-telemetry chunk-granularity host-sync sampler is "
                   "gone; field names are unchanged)")
    p.add_argument("--events", type=str, default=None, metavar="FILE",
                   help="append schema-versioned lifecycle events (run-start, "
                   "resume, crash-schedule-applied, chunk-retired with "
                   "dispatch/fetch timing splits, checkpoint-written, "
                   "watchdog-fired, run-end) as JSONL (utils/events.py)")
    p.add_argument("--checkpoint", type=str, default=None,
                   help="write round-state checkpoints to this .npz path")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="checkpoint every K chunks (with --checkpoint)")
    p.add_argument("--checkpoint-keep", type=int, default=1,
                   help="retain this many checkpoint generations "
                   "(utils/checkpoint.py): K >= 2 writes numbered "
                   "<stem>.gNNNNNN.npz generations with a manifest and "
                   "keeps the plain path linked to the newest, so a torn "
                   "or bit-flipped latest write costs one interval, not "
                   "the run; 1 (default) is the legacy single-file layout")
    p.add_argument("--strict-checkpoint", action="store_true",
                   help="fail fast when a checkpoint write fails (OSError "
                   "at the chunk-boundary hook) instead of the default "
                   "policy of emitting checkpoint-failed + continuing "
                   "with that interval's checkpoint lost "
                   "(models/pipeline.run_chunks hook_error)")
    p.add_argument("--resume", type=str, default=None,
                   help="resume from a checkpoint .npz, or 'auto' to restart "
                   "from the --checkpoint sidecar when it exists (fresh run "
                   "otherwise) — a killed long run rerun with identical "
                   "flags picks up from its last auto-checkpoint")
    p.add_argument("--quiet", action="store_true", help="suppress the JSON record on stdout")
    return p


def _main_refsim(args, parser) -> int:
    """--backend refsim|akka: run the native discrete-event reference
    simulator instead of the JAX engine (no JAX backend is ever
    initialized) — the north-star `--backend {akka|jax}` switch on the
    parity triple (BASELINE.json), with the C++ DES standing in for the
    Akka runtime."""
    from . import native
    from .utils import metrics

    # Flags that configure the JAX engine have no meaning in the native DES
    # (its constants ARE the reference's hard-coded ones) — fail loudly
    # rather than silently ignoring an explicit request. Compared against
    # the parser's own defaults so the guard cannot rot if one changes.
    def changed(dest):
        return getattr(args, dest) != parser.get_default(dest)

    # --semantics is deliberately absent: the native DES IS reference
    # semantics, so asking for it is redundant-but-correct, and "batched"
    # is indistinguishable from the default.
    inapplicable = {
        "--dtype": changed("dtype"),
        "--delta": changed("delta"),
        "--rumor-threshold": changed("rumor_threshold"),
        "--term-rounds": changed("term_rounds"),
        "--termination": changed("termination"),
        "--max-rounds": changed("max_rounds"),
        "--chunk-rounds": changed("chunk_rounds"),
        "--pipeline-chunks": changed("pipeline_chunks"),
        "--overlap-collectives": changed("overlap_collectives"),
        "--halo-dma": changed("halo_dma"),
        "--pool2-wire": changed("pool2_wire"),
        "--replicas": changed("replicas"),
        "--compile-cache": changed("compile_cache"),
        "--target-frac": changed("target_frac"),
        "--suppress": changed("suppress"),
        "--fault-rate": changed("fault_rate"),
        "--crash-rate/--crash-schedule": changed("crash_rate")
        or changed("crash_schedule"),
        "--revive-rate/--revive-schedule": changed("revive_rate")
        or changed("revive_schedule"),
        "--rejoin": changed("rejoin"),
        "--byzantine-rate/--byzantine-schedule": changed("byzantine_rate")
        or changed("byzantine_schedule"),
        "--byzantine-mode": changed("byzantine_mode"),
        "--robust-agg": changed("robust_agg"),
        "--mass-tolerance": changed("mass_tolerance"),
        "--strict-engine": changed("strict_engine"),
        "--dup-rate": changed("dup_rate"),
        "--delay-rounds": changed("delay_rounds"),
        "--quorum": changed("quorum"),
        "--stall-chunks": changed("stall_chunks"),
        "--delivery": changed("delivery"),
        "--pool-size": changed("pool_size"),
        "--engine": changed("engine"),
        "--plan": changed("plan"),
        "--devices": changed("devices"),
        "--platform": changed("platform"),
        "--x64": changed("x64"),
        "--distributed/--coordinator": changed("distributed")
        or changed("coordinator"),
        "--num-processes/--process-id": changed("num_processes")
        or changed("process_id"),
        "--profile": changed("profile"),
        "--checkpoint": changed("checkpoint") or changed("checkpoint_every")
        or changed("checkpoint_keep"),
        "--strict-checkpoint": changed("strict_checkpoint"),
        "--resume": changed("resume"),
        "--trace-convergence": changed("trace_convergence"),
        "--telemetry": changed("telemetry"),
        "--events": changed("events"),
        "--metrics-dump": changed("metrics_dump"),
        "--step-timing": changed("step_timing"),
    }
    bad = [flag for flag, set_ in inapplicable.items() if set_]
    if bad:
        print(
            f"Invalid: {', '.join(bad)} does not apply to --backend "
            f"{args.backend} (the native simulator runs the reference's "
            "exact semantics and hard-coded constants)",
            file=sys.stderr,
        )
        return 2
    try:
        algorithm = normalize_algorithm(args.algorithm)
        # The native engine models the reference, so reference topology
        # normalization applies ("2D" -> the line-wired ref2d, quirk Q6).
        kind = normalize_topology(args.topology, "reference")
    except ValueError as e:
        print(f"Invalid: {e}", file=sys.stderr)
        return 2
    if kind not in native.NATIVE_TOPOLOGIES:
        print(
            f"Invalid: topology {args.topology!r} is not one the reference "
            f"implements; --backend {args.backend} supports "
            f"{sorted(native.NATIVE_TOPOLOGIES)}",
            file=sys.stderr,
        )
        return 2
    print(
        f"Starting {algorithm} on {kind} "
        f"(native reference semantics, seed={args.seed})"
    )
    try:
        r = native.refsim_run(args.numNodes, kind, algorithm, seed=args.seed)
    except ValueError as e:
        print(f"Invalid: {e}", file=sys.stderr)
        return 2
    converged = r.ok and r.converged >= r.target
    if converged:
        print(metrics.convergence_line(r.wall_ms))
    else:
        # Mirror the standalone C++ CLI (refsim.cpp): no convergence time
        # ever happened, so none is printed — the reference's only
        # non-convergence behavior was hanging forever (program.fs:334).
        print(
            f"did not converge: {r.converged}/{r.target} nodes after "
            f"{r.events} events",
            file=sys.stderr,
        )
    record = {
        "backend": args.backend,
        "config": {
            "n": args.numNodes, "topology": kind, "algorithm": algorithm,
            "seed": args.seed,
        },
        "population": r.population,
        "target_count": r.target,
        "converged_count": r.converged,
        "converged": converged,
        "events": r.events,
        "max_queue": r.max_queue,
        "leader": r.leader,
        "wall_ms": r.wall_ms,
    }
    if not args.quiet:
        print(json.dumps(record))
    if args.jsonl:
        metrics.append_jsonl(args.jsonl, record)
    return 0 if record["converged"] else 1


def main(argv: Optional[list[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    if args.backend in ("refsim", "akka"):
        return _main_refsim(args, parser)

    import jax  # deferred so --platform can take effect before backend init

    from .utils.compat import ensure_partitionable_threefry

    # The cross-engine stream contract requires the partitionable threefry
    # (default on current JAX, off on older runtimes); opt in before any
    # trace exists so every engine's support gate sees it (utils/compat.py).
    ensure_partitionable_threefry()

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    if args.compile_cache is not None:
        from .utils.compat import enable_compilation_cache

        enable_compilation_cache(
            None if args.compile_cache == "auto" else args.compile_cache
        )
    if args.num_processes and args.devices and args.devices % args.num_processes:
        print(
            f"Invalid: --devices {args.devices} (global mesh size) must be "
            f"divisible by --num-processes {args.num_processes}",
            file=sys.stderr,
        )
        return 2
    if args.coordinator is not None and (
        args.num_processes is None or args.process_id is None
    ):
        print(
            "Invalid: --coordinator requires --num-processes and "
            "--process-id (there is no auto-detection to fill them in)",
            file=sys.stderr,
        )
        return 2
    if args.platform == "cpu" and args.devices and args.devices > 1:
        # Virtual CPU devices so sharded runs work on a dev box — the
        # fake-backend story the reference lacks (SURVEY.md §4). --devices is
        # the GLOBAL mesh size; each process hosts its share.
        from .utils import compat

        local = args.devices // (args.num_processes or 1)
        compat.set_host_device_count(max(local, 1))
    if args.x64:
        jax.config.update("jax_enable_x64", True)
    if args.distributed or args.coordinator is not None:
        from .parallel.mesh import initialize_distributed

        initialize_distributed(
            args.coordinator, args.num_processes, args.process_id
        )
    if jax.process_count() > 1 and jax.process_index() != 0:
        # One record per run, not per process: non-lead processes still
        # execute every collective but stay silent on stdout.
        args.quiet = True

    try:
        algorithm = normalize_algorithm(args.algorithm)
        kind = normalize_topology(args.topology, args.semantics)
        dtype = args.dtype or ("float64" if args.x64 else "float32")
        cfg = SimConfig(
            n=args.numNodes,
            topology=kind,
            algorithm=algorithm,
            semantics=args.semantics,
            seed=args.seed,
            dtype=dtype,
            delta=args.delta,
            rumor_threshold=args.rumor_threshold,
            term_rounds=args.term_rounds,
            termination=args.termination,
            max_rounds=args.max_rounds,
            chunk_rounds=args.chunk_rounds,
            pipeline_chunks=args.pipeline_chunks,
            overlap_collectives=args.overlap_collectives == "on",
            halo_dma=args.halo_dma,
            pool2_wire=args.pool2_wire,
            target_frac=args.target_frac,
            suppress_converged=None if args.suppress == "auto" else args.suppress == "on",
            fault_rate=args.fault_rate,
            crash_rate=args.crash_rate,
            crash_schedule=args.crash_schedule,
            revive_rate=args.revive_rate,
            revive_schedule=args.revive_schedule,
            rejoin=args.rejoin,
            byzantine_rate=args.byzantine_rate,
            byzantine_schedule=args.byzantine_schedule,
            byzantine_mode=args.byzantine_mode,
            robust_agg=args.robust_agg,
            dup_rate=args.dup_rate,
            delay_rounds=args.delay_rounds,
            quorum=args.quorum,
            stall_chunks=args.stall_chunks,
            mass_tolerance=args.mass_tolerance,
            strict_engine=args.strict_engine,
            strict_checkpoint=args.strict_checkpoint,
            delivery=args.delivery,
            pool_size=args.pool_size,
            engine=args.engine,
            plan=args.plan,
            n_devices=args.devices,
            # Config-level so sweep-engine contracts (e.g. --replicas with
            # --engine fused) fail HERE, before topology build.
            replicas=args.replicas,
            # --trace-convergence is the telemetry plane's serializer.
            telemetry=args.telemetry or bool(args.trace_convergence),
            step_timing=args.step_timing,
        )
    except ValueError as e:
        print(f"Invalid: {e}", file=sys.stderr)
        return 2

    from .models.runner import run
    from .ops.topology import build_topology
    from .utils import checkpoint as ckpt
    from .utils import metrics

    # Valid-but-suspect flag combinations (SimConfig.lint_warnings, e.g.
    # quorum < 1.0 without a crash model): warn loudly on stderr — and stamp
    # them into the run-start event below — rather than silently ignoring.
    lint = cfg.lint_warnings
    if jax.process_index() == 0:
        for w in lint:
            print(f"Warning: {w}", file=sys.stderr)
        print(metrics.banner(cfg))

    t0 = time.perf_counter()
    topo = build_topology(kind, args.numNodes, seed=args.seed, semantics=args.semantics)
    build_s = time.perf_counter() - t0

    if args.replicas > 1:
        # Vmapped replica sweep (models/sweep.py): one chunked program runs
        # all replicas; chunk-boundary hooks are per-run features.
        for flag, set_ in (
            ("--checkpoint", args.checkpoint),
            ("--resume", args.resume),
            ("--trace-convergence", args.trace_convergence),
            ("--events", args.events),
            # run_replicas collects per-replica trajectories (models/
            # sweep.py, tested via the API), but the CLI has no sweep
            # serializer — accepting the flag would pay the collection
            # cost and silently discard the data.
            ("--telemetry", args.telemetry),
            # The run-budget series a metrics dump exposes are per-RUN
            # fields (run_record schema v4); the sweep record has no
            # chunk_log/budget split to stamp.
            ("--metrics-dump", args.metrics_dump),
            # Super-step timing reads the per-run chunk_log; the sweep
            # record has none.
            ("--step-timing", args.step_timing),
            # A deadline is a per-run SLO; the sweep's serial chunk loop
            # supports it via the API (run_batched_keys deadline=), but
            # the CLI sweep record has no per-replica outcome channel for
            # partial results — run deadline diagnostics unbatched.
            ("--deadline-ms", args.deadline_ms),
        ):
            if set_:
                print(
                    f"Invalid: {flag} does not apply to --replicas sweeps "
                    "(per-run observability surfaces; run replicas "
                    "unbatched, or use models/sweep.run_replicas for "
                    "per-replica trajectories)",
                    file=sys.stderr,
                )
                return 2
        from .models.sweep import run_replicas

        try:
            sres = run_replicas(topo, cfg, args.replicas, keep_states=False)
        except (ValueError, NotImplementedError) as e:
            print(f"Invalid: {e}", file=sys.stderr)
            return 2
        record = sres.to_record()
        record["config"] = {
            "n": cfg.n, "topology": cfg.topology,
            "algorithm": cfg.algorithm, "seed": cfg.seed,
        }
        record["build_s"] = build_s
        if jax.process_index() == 0:
            ci = (
                f" ±{sres.rounds_ci95:.1f}" if sres.rounds_ci95 is not None
                else ""
            )
            print(
                f"{args.replicas} replicas: rounds mean "
                f"{sres.rounds_mean:.1f}{ci} (95% CI), wall "
                f"{sres.wall_ms:.2f} ms total "
                f"({sres.wall_ms / args.replicas:.2f} ms/replica)"
            )
        if not args.quiet:
            print(json.dumps(record))
        if args.jsonl and jax.process_index() == 0:
            metrics.append_jsonl(args.jsonl, record)
        return 0 if sres.all_converged else 1

    # Lifecycle event log (utils/events.py). Opened before the run so
    # run-start lands first even if the run dies.
    events = None
    if args.events and jax.process_index() == 0:
        from .utils.events import RunEventLog

        events = RunEventLog(args.events)
        events.emit(
            "run-start",
            config={"n": cfg.n, "topology": cfg.topology,
                    "algorithm": cfg.algorithm, "seed": cfg.seed,
                    "semantics": cfg.semantics},
            population=topo.n,
            warnings=list(lint),
        )
        if cfg.crash_model:
            events.emit(
                "crash-schedule-applied",
                crash_rate=cfg.crash_rate,
                crash_schedule=cfg.crash_schedule,
                revive_rate=cfg.revive_rate,
                revive_schedule=cfg.revive_schedule,
                rejoin=cfg.rejoin if cfg.revive_model else None,
                quorum=cfg.quorum,
            )
        if cfg.byzantine_model:
            events.emit(
                "byzantine-model-applied",
                byzantine_rate=cfg.byzantine_rate,
                byzantine_schedule=cfg.byzantine_schedule,
                byzantine_mode=cfg.byzantine_mode,
                robust_agg=cfg.robust_agg,
            )

    # The chunk-boundary hook API is CHECKPOINT-ONLY: a hook reads retired
    # device state, which turns off buffer donation and serializes the
    # boundary (models/pipeline.py). Convergence tracing no longer rides it
    # — the on-device telemetry plane (cfg.telemetry) carries the counters
    # with the hot path intact, and the legacy per-chunk
    # `int(jnp.sum(...))` host syncs are gone.
    hooks = []
    trace_prev = {"conv": 0}
    if args.checkpoint:
        counter = {"chunks": 0}

        def checkpoint_hook(rounds, state):
            counter["chunks"] += 1
            if counter["chunks"] % args.checkpoint_every == 0:
                if jax.process_count() > 1:
                    # Process-spanning state is not host-addressable; gather
                    # the full arrays (a collective — every process must
                    # participate), then only the lead process writes.
                    from jax.experimental import multihost_utils

                    state = type(state)(
                        *multihost_utils.process_allgather(
                            tuple(state), tiled=True
                        )
                    )
                    if jax.process_index() != 0:
                        return
                # Strip the sharded runner's device padding: a checkpoint
                # holds exactly n entries so it can be resumed under any
                # device count (including single-device).
                import numpy as np

                state = type(state)(
                    *(np.asarray(x)[: topo.n] for x in state)
                )
                info = ckpt.save(
                    args.checkpoint, state, rounds, cfg,
                    keep=args.checkpoint_keep,
                )
                if events is not None:
                    events.emit(
                        "checkpoint-written", rounds=rounds,
                        path=info["path"],
                        generation=info["generation"],
                        bytes=info["bytes"],
                        write_s=info["write_s"],
                    )

        hooks.append(checkpoint_hook)

    if not hooks:
        on_chunk = None
    elif len(hooks) == 1:
        on_chunk = hooks[0]
    else:
        def on_chunk(rounds, state):
            for h in hooks:
                h(rounds, state)

    start_state, start_round = None, 0
    resume_path = args.resume
    if resume_path == "auto":
        # Crash-only-restarts workflow: rerun the identical command line and
        # pick up from the periodic --checkpoint sidecar when one exists —
        # first launch (no sidecar yet) starts fresh.
        if not args.checkpoint:
            print(
                "Invalid: --resume auto needs --checkpoint PATH (the "
                "sidecar it restarts from)",
                file=sys.stderr,
            )
            return 2
        # Generation-aware existence probe: a quarantined or torn newest
        # file may leave the plain path dangling while an older intact
        # generation is still resumable.
        resume_path = (
            args.checkpoint if ckpt.candidate_paths(args.checkpoint)
            else None
        )
    if resume_path:
        import dataclasses
        import zipfile

        def _quarantine_event(**fields):
            if events is not None:
                events.emit("checkpoint-corrupt-quarantined", **fields)
            print(
                f"checkpoint generation {fields.get('path')} quarantined: "
                f"{fields.get('reason')}",
                file=sys.stderr,
            )

        # Beyond ValueError (stream-version mismatch, bad config), a kill
        # can leave a truncated .npz or a missing sidecar: BadZipFile /
        # OSError / KeyError. ckpt.save is atomic-rename so this is rare,
        # but --resume auto exists precisely for killed runs — it walks
        # generations newest-first (corrupt ones quarantined with a
        # structured event, ISSUE 19) and falls back to a fresh start only
        # when no intact generation remains; an explicit path still fails
        # loudly.
        try:
            if args.resume == "auto":
                hit = ckpt.load_latest_intact(
                    resume_path, on_event=_quarantine_event
                )
                if hit is None:
                    print(
                        f"checkpoint {resume_path} has no intact "
                        "generation; starting fresh",
                        file=sys.stderr,
                    )
                    resume_path = None
                else:
                    start_state, start_round, saved_cfg, hit_info = hit
                    resume_path = hit_info["path"]
            else:
                start_state, start_round, saved_cfg = ckpt.load(resume_path)
        except (ValueError, OSError, KeyError, zipfile.BadZipFile) as e:
            if args.resume == "auto":
                print(
                    f"checkpoint {resume_path} unusable ({e}); "
                    "starting fresh",
                    file=sys.stderr,
                )
                resume_path = None
            else:
                print(f"Invalid: {e}", file=sys.stderr)
                return 2
    if resume_path:
        # Resume is only bitwise-faithful if every stream-relevant knob
        # matches the original run; loop-control knobs may differ.
        # telemetry is observability, not stream state: a resumed run may
        # toggle it freely without touching the trajectory.
        # telemetry/mass_tolerance/strict_engine are observability and
        # harness-resilience knobs, not stream state: a resumed run may
        # toggle them without touching the trajectory (the sentinel can
        # change WHEN the loop stops, never what any round computes).
        loop_knobs = {"max_rounds": cfg.max_rounds, "chunk_rounds": cfg.chunk_rounds,
                      "n_devices": cfg.n_devices,
                      "pipeline_chunks": cfg.pipeline_chunks,
                      "overlap_collectives": cfg.overlap_collectives,
                      "halo_dma": cfg.halo_dma,
                      "pool2_wire": cfg.pool2_wire,
                      "telemetry": cfg.telemetry,
                      "mass_tolerance": cfg.mass_tolerance,
                      "strict_engine": cfg.strict_engine,
                      "strict_checkpoint": cfg.strict_checkpoint}
        if dataclasses.replace(saved_cfg, **loop_knobs) != cfg:
            print(
                "Invalid: checkpoint config mismatch — resume requires the "
                f"original flags (saved: {dataclasses.asdict(saved_cfg)})",
                file=sys.stderr,
            )
            return 2
        # Seed the trace baseline from the resumed state: nodes that
        # converged before the checkpoint are not "newly converged" in the
        # resumed run's first trace record.
        import numpy as np

        trace_prev["conv"] = int(np.asarray(start_state.conv).sum())
        if events is not None:
            events.emit("resume", rounds=start_round, path=str(resume_path))

    # Streaming trajectory writer: the telemetry collector hands each
    # retired chunk's fresh counter rows to this callback, which appends
    # them in the legacy trace schema (one fsync per chunk,
    # metrics.append_jsonl_many) — a killed run's trace file holds every
    # retired chunk's rounds, like the pre-telemetry per-chunk hook did,
    # without that hook's blocking syncs or donation opt-out.
    tele_writer = None
    if args.trace_convergence and jax.process_index() == 0:
        from .ops import telemetry as telemetry_mod

        # Highest absolute round already serialized: an engine retry or a
        # degradation-ladder rung (models/runner.run) restarts the run and
        # REPLAYS rounds whose rows this writer already fsynced — without
        # the high-water mark the trace would hold duplicate per-round
        # records and every round-count consumer would double-read them.
        # Replayed rounds are dropped; the file stays one record per round.
        trace_prev["hi"] = start_round

        def tele_writer(chunk_start, rows):
            skip = trace_prev["hi"] - chunk_start
            if skip > 0:
                if skip >= rows.shape[0]:
                    return  # the whole chunk was already written
                rows = rows[skip:]
                chunk_start += skip
            recs = telemetry_mod.rows_to_trace_records(
                rows, chunk_start, cfg.algorithm,
                prev_conv=trace_prev["conv"],
            )
            trace_prev["conv"] = recs[-1]["converged_count"] if recs else (
                trace_prev["conv"]
            )
            trace_prev["hi"] = chunk_start + rows.shape[0]
            metrics.append_jsonl_many(args.trace_convergence, recs)

    # SURVEY.md §5 tracing plan: the trace spans compile + run, and the
    # in-kernel named_scope tags split per-round cost into sample / deliver /
    # absorb when viewed in TensorBoard/Perfetto.
    trace_ctx = (
        jax.profiler.trace(args.profile) if args.profile
        else contextlib.nullcontext()
    )
    if args.deadline_ms is not None and args.deadline_ms <= 0:
        print(
            f"Invalid: --deadline-ms must be positive, got {args.deadline_ms}",
            file=sys.stderr,
        )
        return 2
    # The deadline clock starts at dispatch time, AFTER topology build and
    # argument validation: --deadline-ms bounds the run (the quantity the
    # serving deadline bounds too), not the process.
    deadline = (
        time.monotonic() + args.deadline_ms / 1e3
        if args.deadline_ms is not None else None
    )
    # The metrics dump wants the autotuner's plan-chosen verdict even when
    # no --events log is configured, so the event stream is teed: every
    # (name, fields) pair is kept for observe_run_record, and forwarded to
    # the durable log when one exists.
    captured_events: list = []
    on_run_event = None
    if events is not None or args.metrics_dump:
        def on_run_event(name, **fields):
            if args.metrics_dump:
                captured_events.append((name, dict(fields)))
            if events is not None:
                # engine-degraded events land in the log AT degradation
                # time — a later crash still leaves the rung walk durable.
                events.emit(name, **fields)
    try:
        with trace_ctx:
            result = run(
                topo, cfg, on_chunk=on_chunk,
                start_state=start_state, start_round=start_round,
                on_telemetry=tele_writer,
                on_event=on_run_event,
                deadline=deadline,
            )
    except (ValueError, NotImplementedError) as e:
        print(f"Invalid: {e}", file=sys.stderr)
        return 2
    result.build_s = build_s

    if events is not None:
        events.emit_chunks(result.chunk_log)
        # Lost-interval checkpoint writes the driver survived under the
        # ISSUE 19 continue policy, in boundary order (the registry
        # counter was bumped at failure time in run_chunks).
        for fail in result.hook_failures or ():
            events.emit("checkpoint-failed", **fail)
        if result.outcome == "stalled":
            events.emit("watchdog-fired", rounds=result.rounds)
        if result.outcome == "unhealthy":
            events.emit(
                "sentinel-tripped",
                rounds=result.rounds,
                unhealthy_round=result.unhealthy_round,
                mass_tolerance=cfg.mass_tolerance,
            )
        events.emit(
            "run-end",
            outcome=result.outcome,
            rounds=result.rounds,
            converged_count=result.converged_count,
            compile_s=result.compile_s,
            run_s=result.run_s,
            dispatch_s=result.dispatch_s,
            fetch_s=result.fetch_s,
        )

    if jax.process_index() == 0:
        print(metrics.reference_format(result))
    record = metrics.run_record(cfg, topo, result)
    if cfg.step_timing:
        # Per-super-step attribution (ISSUE 18): the chunk driver stamped
        # retire clocks into the chunk_log; fold them into the report the
        # measured-vs-predicted table and the metrics dump read, and ride
        # it on the run record so --jsonl trend lines carry it too.
        from .models import pipeline as pipeline_mod

        st_report = pipeline_mod.step_timing_report(result.chunk_log)
        if st_report is not None:
            record["step_timing"] = st_report
    if args.metrics_dump:
        # One scrape surface for one-shot runs (ISSUE 7): stamp the run
        # record + per-chunk splits into the process registry — which
        # already holds the warm-engine pool counters from this run — and
        # render the Prometheus text. Host-side post-processing only.
        # Schema v6 additions (ISSUE 18): the telemetry trajectory's
        # byzantine_count series, the autotuner's plan-chosen verdict, and
        # the per-super-step wall histogram when --step-timing is on.
        from .utils import obs

        obs.observe_run_record(
            record, chunk_log=result.chunk_log,
            telemetry=result.telemetry, events=captured_events,
        )
        if cfg.step_timing and record.get("step_timing") is not None:
            obs.observe_step_timing(record["step_timing"])
        if jax.process_count() > 1 and args.metrics_dump != "-":
            # Federated multi-process dump: every process writes its own
            # exposition; process 0 barriers, reads the parts back, and
            # merges them with the same obs.merge_prometheus the fleet
            # front's GET /metrics federation uses (counters summed,
            # gauges labelled per process, histograms bucket-merged).
            from jax.experimental import multihost_utils

            part = f"{args.metrics_dump}.proc{jax.process_index()}"
            obs.dump(part)
            multihost_utils.sync_global_devices("metrics-dump-parts")
            if jax.process_index() == 0:
                sources = {}
                for k in range(jax.process_count()):
                    with open(f"{args.metrics_dump}.proc{k}") as f:
                        sources[str(k)] = f.read()
                with open(args.metrics_dump, "w") as f:
                    f.write(obs.merge_prometheus(sources, label="process"))
        elif jax.process_index() == 0:
            obs.dump(args.metrics_dump)
    if not args.quiet:
        print(json.dumps(record))
    if args.jsonl and jax.process_index() == 0:
        # One record per run: on a shared filesystem every process appending
        # would interleave N duplicates.
        metrics.append_jsonl(args.jsonl, record)
    return 0 if result.converged else 1


if __name__ == "__main__":
    raise SystemExit(main())
