"""Benchmark suite — regenerates BENCH_TABLES.md (SURVEY.md §7 step 6).

Sweeps the exact grid the reference published (report.pdf p.4-5: N in
{20..1000} x {line, full, 2D, Imp3D} x {gossip, push-sum}) through the
old-vs-new harness (benchmarks/compare.py) and emits BASELINE.md §6-format
tables: per algorithm x topology, the published Akka number, the native
reference simulator's wall on this machine, and the TPU framework's batched
wall + rounds.

One command regenerates the checked-in record:

  python benchmarks/suite.py --out BENCH_TABLES.md

Off-grid scale rows (N where the reference caps out, report.pdf p.3 §4) are
added for the full topology to document the capability gap the rebuild
closes — the reference cannot run past N~2000 at all.
"""

from __future__ import annotations

import argparse
import datetime
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from benchmarks import baseline_data  # noqa: E402
from benchmarks.compare import MatchedRow, matched_run  # noqa: E402

# Past the reference's ceiling (report.pdf p.3 §4) — capability rows, no
# Akka column possible. Up to 2,097,152 the compiled fused pool engine
# (ops/fused_pool.py, VMEM-resident) runs; past its cap the HBM-streaming
# tier (ops/fused_pool2.py) carries to 2^27 at fused-class per-node cost.
# The top rows are 2^24 and 2^27 (the HBM-plane cap, one chip) —
# power-of-two populations take pool2's aligned single-window path (the
# mod-n blend is statically elided).
SCALE_N = (10_000, 100_000, 1_000_000, 2_000_000, 4_000_000, 16_777_216,
           134_217_728)  # 2^27: the HBM-plane cap row (VERDICT r3 #10)
# The native DES column stops here: the single-walk reference semantics it
# simulates needs ~30 s at 1M on this CPU and scales superlinearly.
REFSIM_SCALE_CAP = 1_000_000
# Grid-topology scale rows — the sparse-topology counterpart of the
# full-topology table. Delivery varies per topology: torus3d runs the
# stencil/fused path, imp3d's random long-range edges force sort-based
# scatter. Cube populations; push-sum only at 1M on the torus (a 100^3
# torus mixes slowly: ~37k rounds).
# (kind, n, algorithms, delivery, label-suffix, max_rounds or None=200k)
GRID_SCALE = (
    ("torus3d", 1_000_000, ("gossip", "push-sum"), "auto", "", None),
    ("torus3d", 8_000_000, ("gossip",), "auto", "", None),
    ("torus3d", 16_777_216, ("gossip",), "auto", "", None),
    # Non-wrap lattice at HBM-streaming scale (VERDICT r3 #2b: boundary
    # masks + signed shifts in ops/fused_stencil_hbm.py).
    ("grid2d", 8_000_000, ("gossip",), "auto", "", None),
    ("grid2d", 16_777_216, ("gossip",), "auto", "", None),
    # grid2d push-sum (VERDICT r5 #7 "missing" #3 — the last unbenched
    # topology x algorithm cell): a 1000^2 non-wrap grid mixes over
    # ~O(diameter^2) rounds, far beyond a table cell, so this is a
    # bounded-round throughput sample like the 10M torus config.
    ("grid2d", 1_000_000, ("push-sum",), "auto",
     " (bounded 50,000 rounds)", 50_000),
    # Chain-kind HBM-scale row (VERDICT r5 #7): ring at 2^24 exercises the
    # stencil HBM tier's wrap columns on a degree-2 chain — information
    # diffuses O(N) rounds on a chain, so bounded-round throughput sample.
    ("ring", 16_777_216, ("gossip",), "auto",
     " (bounded 2,000 rounds)", 2_000),
    # The reference's hardest config (Imp3D caps at 2000, report.pdf p.3),
    # both ways: the static random extra edge under sort-based scatter
    # (exact graph, addressing-bound — see the roofline section), and the
    # pooled long-range recast (same per-node marginals, rolls only,
    # fused engine) that puts imp3d at torus-class per-round cost — and
    # past the VMEM budget on the HBM-streaming imp tier (VERDICT r3 #2a,
    # ops/fused_imp_hbm.py).
    ("imp3d", 1_000_000, ("gossip", "push-sum"), "scatter",
     " (static/scatter)", None),
    ("imp3d", 1_000_000, ("gossip", "push-sum"), "pool",
     " (pooled/fused)", None),
    ("imp3d", 8_000_000, ("gossip",), "pool", " (pooled/fused)", None),
    ("imp3d", 16_777_216, ("gossip", "push-sum"), "pool",
     " (pooled/fused)", None),
)


def _fmt(x, nd=2, none="—"):
    return none if x is None else f"{x:,.{nd}f}"


def _fmt_us(x, noise=None):
    """Engine-µs/round cell: differentials under the measurement's own
    resolution bound print as a bound, not a fake 0.00 (VERDICT r3 Weak
    #4). engine_us_stats now GROWS the round spread until the differenced
    wall clears timer resolution (benchmarks/compare.py), so the per-row
    bound usually sits below the real per-round cost and small-N cells
    print numbers; the marker only survives where growth capped out."""
    from benchmarks.compare import ENGINE_US_NOISE

    if x is None:
        return "—"
    bound = ENGINE_US_NOISE if noise is None else noise
    if x < bound:
        return f"<{bound:.2g}"
    return f"{x:,.2f}"


def _table(rows: list[MatchedRow], sweeps=None) -> list[str]:
    """Per-cell table; with ``sweeps`` (models/sweep.SweepResult per row,
    the vmapped replica engine) two columns the reference never had:
    rounds mean±CI95 over seeds, and the per-replica amortized wall."""
    header = (
        "| #Nodes | Akka report (ms) | refsim native (ms) | gossip-tpu (ms) "
        "| tpu rounds | engine µs/round | speedup vs Akka |"
    )
    rule = "|---|---|---|---|---|---|---|"
    if sweeps is not None:
        header += " rounds mean±CI95 | sweep ms/replica |"
        rule += "---|---|"
    out = [header, rule]
    for i, r in enumerate(rows):
        sp = r.speedup_vs_akka
        line = (
            f"| {r.n:,} | {_fmt(r.akka_report_ms)} | {_fmt(r.refsim_ms)} "
            f"| {_fmt(r.tpu_ms)} | {r.tpu_rounds:,} "
            f"| {_fmt_us(r.tpu_us_per_round, r.tpu_us_noise)} "
            f"| {_fmt(sp, 1)}{'' if sp is None else 'x'} |"
        )
        if sweeps is not None:
            s = sweeps[i]
            ci = "" if s.rounds_ci95 is None else f" ±{s.rounds_ci95:,.1f}"
            line += (
                f" {s.rounds_mean:,.1f}{ci} (R={s.replicas}) "
                f"| {_fmt(s.wall_ms / s.replicas)} |"
            )
        out.append(line)
    return out


def _analysis(all_rows: dict, grid_n) -> list[str]:
    """Qualitative analysis of the measured grid — the counterpart of the
    reference report's own analysis section (report.pdf p.3-5), but keyed to
    *rounds to converge*, the quantity that survives the semantic recast
    (wall-clock at small N is dispatch-floor-bound, see the reading note
    above). Ranks are computed from the rows just measured, not hard-coded."""
    if not all_rows:
        return []
    n_top = max(grid_n)
    out = ["## Analysis (at the grid's largest point)", ""]
    for algo in ("gossip", "push-sum"):
        ranked = sorted(
            (rows[-1].tpu_rounds, topo)
            for (a, topo), rows in all_rows.items()
            if a == algo and rows
        )
        order = " < ".join(f"{t} ({r:,})" for r, t in ranked)
        out.append(f"- **{algo} rounds at N={n_top:,}:** {order}.")
    out += [
        "",
        "The ordering mirrors graph structure, and matches the trends in the "
        "reference's own tables (report.pdf p.4-5) once '2D' is read for what "
        "it is wired as:",
        "",
        "- **full** converges fastest: every node can reach every other, so "
        "rumor spread and mass mixing are O(log N) rounds (expander behavior).",
        "- **Imp3D** tracks full closely — the one uniformly random extra "
        "neighbor per node (program.fs:308-310) makes the lattice a "
        "small-world graph; this is the reference report's own observation "
        "that Imp3D is its second-fastest topology.",
        "- **line is slowest** — information must diffuse through an O(N) "
        "diameter. The reference's '2D' column tracks (even exceeds) its "
        "line column because its 2D *is* a line (quirk Q6, "
        "program.fs:242-248 — neighbors are wired {i-1, i+1}, the grid size "
        "is never used); the TPU column here measures the honest 4-neighbor "
        "grid instead (O(sqrt N) diameter — between line and Imp3D, exactly "
        "where a true 2D grid belongs), while the Q6 wiring itself is "
        "reproduced and pinned separately (ref2d, tests/test_topology.py). "
        "On slow-mixing graphs push-sum's local-stability criterion "
        "(|Δ(s/w)| <= δ for 3 consecutive receipt rounds) can latch long "
        "before global mass equilibrium — the same early-latch failure mode "
        "the reference has (its nodes also only compare their own "
        "consecutive ratios, program.fs:119-137).",
        "- **Wall-clock vs rounds decouple on TPU**: a round costs the same "
        "regardless of how many nodes are informed (dense batched kernel), "
        "so TPU wall scales with rounds x per-round cost, while the Akka "
        "wall scales with messages x per-message cost — which is why the "
        "speedup column grows with N everywhere, crossing 1x once the "
        "dispatch floor is amortized.",
        "",
    ]
    return out


def _cell_sweep(n, topology, algorithm, seed, replicas):
    """The 'benchmarks sweep' path: one vmapped dispatch runs all
    ``replicas`` seeds of a grid cell (models/sweep.py buckets same-shape
    cells by construction — a cell's seeds ARE its bucket). Compiled
    engines come from the warm pool under the canonical engine key
    (serving/keys.py, seed excluded), so identical-shape cells — and
    reruns of a cell at a different seed — reuse the live executable
    instead of retracing; the suite prints the pool's hit/miss tally at
    the end."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.config import normalize_topology
    from cop5615_gossip_protocol_tpu.models.sweep import run_replicas

    kind = normalize_topology(topology, semantics="batched")
    cfg = SimConfig(n=n, topology=kind, algorithm=algorithm, seed=seed)
    topo = build_topology(kind, n, seed=seed, semantics="batched")
    return run_replicas(topo, cfg, replicas, keep_states=False)


def _trajectory_section(seed: int, trajectory_path: str, grid_n) -> list[str]:
    """Run the smallest grid cell's full-topology gossip config with the
    telemetry plane on, write its per-round trajectory JSONL to
    ``trajectory_path``, and return the rounds-to-X% + ASCII-curve section
    (benchmarks/trajectory.py) for the output markdown — the telemetry
    smoke the CI bench job drives end to end."""
    from benchmarks import trajectory as traj_mod
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
    from cop5615_gossip_protocol_tpu.utils import metrics

    n = min(grid_n)
    cfg = SimConfig(n=n, topology="full", algorithm="gossip", seed=seed,
                    telemetry=True)
    topo = build_topology("full", n, seed=seed)
    res = run(topo, cfg)
    Path(trajectory_path).unlink(missing_ok=True)
    metrics.append_jsonl_many(
        trajectory_path,
        res.telemetry.to_trace_records(cfg.algorithm),
    )
    print(f"[suite] trajectory: full/gossip N={n} -> {trajectory_path} "
          f"({res.telemetry.rounds} rounds)", flush=True)
    return traj_mod.section(
        traj_mod.load_trace(trajectory_path), population=topo.n,
        title=f"Convergence trajectory (full gossip N={n:,}, telemetry "
        "plane)",
    )


def generate(out_path: str, seed: int, grid_n, scale_n, platform_note: str,
             replicas: int = 0, us_pairs: int = 3,
             us_budgets=None, trajectory_path: str | None = None) -> None:
    """_generate with the warm-pool tally GUARANTEED: the hit/miss line
    prints even when a cell degrades down the engine ladder or an error
    aborts the suite mid-run — the pool evidence the autotuner's
    amortization term relies on used to vanish on exactly the
    interesting (degraded) runs."""
    try:
        _generate(out_path, seed, grid_n, scale_n, platform_note,
                  replicas=replicas, us_pairs=us_pairs,
                  us_budgets=us_budgets, trajectory_path=trajectory_path)
    finally:
        from cop5615_gossip_protocol_tpu.serving import pool as pool_mod

        print(f"[suite] warm-engine pool: {pool_mod.default_pool().stats()}",
              flush=True)


def _generate(out_path: str, seed: int, grid_n, scale_n, platform_note: str,
              replicas: int = 0, us_pairs: int = 3,
              us_budgets=None, trajectory_path: str | None = None) -> None:
    lines = [
        "# BENCH_TABLES — old vs new on the reference's own grid",
        "",
        "Generated by `python benchmarks/suite.py --out BENCH_TABLES.md` "
        f"on {datetime.date.today().isoformat()}.",
        "",
        f"- **Akka report (ms)** — the reference's published wall-clock "
        "(report.pdf p.4-5, unspecified Windows PC; BASELINE.md).",
        "- **refsim native (ms)** — native/refsim.cpp, the discrete-event "
        "re-implementation of the reference's actor semantics, run on this "
        "machine's CPU (the runnable stand-in for `dotnet run`; no .NET in "
        "this image).",
        "- **gossip-tpu (ms)** — this framework, batched semantics, "
        f"steady-state wall excluding XLA compile. Platform: {platform_note}.",
        "",
        "Semantic recast (SURVEY.md §3.3): the reference's push-sum is a "
        "single random walk, the batched mode is synchronous all-node "
        "rounds — the join compares capability timing on identical "
        "(N, topology, algorithm), and message-level fidelity of the "
        "reference-semantics modes is pinned by tests/test_native.py.",
        "",
        "Reading the small-N cells honestly: the TPU wall has a flat "
        "~110-140 ms floor per run — measured per-LAUNCH overhead of the "
        "remote-tunnel TPU in this environment (one chunk launch covers a "
        "whole run at the default chunk_rounds=4096; the cost is launch "
        "plumbing, independent of rounds executed, not compute). Below "
        "N~100 that floor exceeds the whole Akka run, so speedups start "
        "under 1x; the framework's regime is scale (see the final table — "
        "at N=1,000,000 the reference cannot run at all, its native DES "
        "re-implementation takes ~31 s, and the fused pool engine converges "
        "in ~0.16 s, itself launch-overhead-bound). The **engine µs/round** "
        "column separates the two: it reruns each cell's compiled chunk at "
        "two fixed round budgets in one dispatch each and differences the "
        "walls, cancelling the floor exactly — that column measures the "
        "engine; the wall column shows the floor where it is irreducible "
        "(one dispatch must happen).",
        "",
        "Known data anomaly: the reference report's Imp3D gossip N=1000 cell "
        "repeats the 2D value to the hundredth of a millisecond — a likely "
        "transcription error in report.pdf p.4 (kept verbatim; see "
        "benchmarks/baseline_data.py) — so that row's speedup inherits it.",
        "",
    ]
    if replicas:
        lines.append(
            f"Replica-sweep columns: each cell additionally runs R="
            f"{replicas} seeds in ONE vmapped chunked dispatch "
            "(models/sweep.py; replica 0 = the tabulated run), reporting "
            "rounds mean ±95% CI and the per-replica amortized wall — "
            "dispatch/compile floors are paid once per cell, not per seed."
        )
        lines.append("")
    t_start = time.perf_counter()
    all_rows: dict[tuple[str, str], list[MatchedRow]] = {}
    for algo in ("gossip", "push-sum"):
        lines.append(f"## {algo}")
        lines.append("")
        for topo in baseline_data.REF_TOPOLOGIES:
            rows = []
            sweeps = [] if replicas else None
            for n in grid_n:
                rows.append(matched_run(
                    n, topo, algo, seed=seed, us_pairs=us_pairs,
                    us_budgets=us_budgets,
                ))
                if replicas:
                    sweeps.append(_cell_sweep(n, topo, algo, seed, replicas))
                print(
                    f"[suite] {algo}/{topo} N={n}: tpu {rows[-1].tpu_ms:.2f} ms "
                    f"({rows[-1].tpu_rounds} rounds), refsim {rows[-1].refsim_ms:.2f} ms",
                    flush=True,
                )
            all_rows[(algo, topo)] = rows
            lines.append(f"### {topo}")
            lines.append("")
            lines.extend(_table(rows, sweeps))
            lines.append("")
        lines.append("")

    lines.extend(_analysis(all_rows, grid_n))

    if trajectory_path:
        lines.extend(_trajectory_section(seed, trajectory_path, grid_n))

    if scale_n:
        lines.append("## Beyond the reference's ceiling (full topology, push-sum)")
        lines.append("")
        lines.append(
            "The reference caps out at N~2000 (report.pdf p.3 §4: its full "
            "topology builds an O(N^2) neighbor table with O(N^3) copy work, "
            "program.fs:201-206). The implicit-full recast has no ceiling "
            "short of device memory:"
        )
        lines.append("")
        lines.append("| #Nodes | gossip-tpu (ms) | tpu rounds | refsim native (ms) |")
        lines.append("|---|---|---|---|")
        for n in scale_n:
            from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

            cfg = SimConfig(
                n=n, topology="full", algorithm="push-sum", seed=seed,
                delivery="pool",
            )
            topo = build_topology("full", n)
            res = run(topo, cfg)
            # refsim can still run these (the native rebuild has no O(N^2)
            # table either) — a bonus column showing even the CPU DES beats
            # Akka's ceiling, while the TPU path wins the wall-clock.
            ref_ms = None
            if n <= REFSIM_SCALE_CAP:
                try:
                    from cop5615_gossip_protocol_tpu import native

                    ref_ms = native.refsim_run(
                        n, "full", "push-sum", seed=seed
                    ).wall_ms
                except Exception:
                    pass
            lines.append(
                f"| {n:,} | {_fmt(res.wall_ms)} | {res.rounds:,} | {_fmt(ref_ms)} |"
            )
            print(f"[suite] scale full/push-sum N={n}: {res.wall_ms:.2f} ms", flush=True)
        lines.append("")

    if scale_n:
        lines.append("## Beyond the reference's ceiling (grid topologies)")
        lines.append("")
        lines.append(
            "The sparse-topology counterpart: imperfect/perfect 3D grids are "
            "the reference's hardest configs (report.pdf p.3 §4 caps Imp3D "
            "at 2000 nodes). torus3d uses masked-shift (stencil) delivery "
            "(fused on-chip at ~1M nodes); imp3d appears twice — the exact "
            "static random-extra-edge graph under sort-based scatter "
            "(addressing-bound: ~8-12 ns/element is the chip's floor for "
            "random access, see the roofline section), and the pooled "
            "long-range recast (per-round re-draw from K shared "
            "displacements, same per-node marginals, rolls only — the "
            "fused imp engine) at torus-class per-round cost. push-sum "
            "only at 1M on the torus — a 100^3 torus mixes slowly (~37k "
            "rounds to local stability)."
        )
        lines.append("")
        lines.append("| topology | #Nodes | algorithm | gossip-tpu (ms) | tpu rounds |")
        lines.append("|---|---|---|---|---|")
        from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

        for kind, n, algos, delivery, label, cap in GRID_SCALE:
            topo = build_topology(kind, n, seed=seed)  # shared across algos
            for algo in algos:
                cfg = SimConfig(n=n, topology=kind, algorithm=algo,
                                seed=seed, max_rounds=cap or 200_000,
                                delivery=delivery)
                res = run(topo, cfg)
                lines.append(
                    f"| {kind}{label} | {topo.n:,} | {algo} "
                    f"| {_fmt(res.wall_ms)} | {res.rounds:,} |"
                )
                print(
                    f"[suite] scale {kind}{label}/{algo} N={topo.n}: "
                    f"{res.wall_ms:.2f} ms ({res.rounds} rounds)",
                    flush=True,
                )
        lines.append("")

    if scale_n:
        lines.extend(_northstar_section(seed))

    import jax as _jax

    if scale_n and _jax.default_backend() == "tpu":
        from benchmarks.roofline import section as roofline_section

        lines.extend(roofline_section())
        lines.extend(_termination_section(seed))

    if scale_n:
        # Dispatch-floor metrology (benchmarks/microbench.py): itemize the
        # per-run overhead the small-N reading note describes instead of
        # leaving it folded into the wall columns.
        from benchmarks.microbench import collect as micro_collect
        from benchmarks.microbench import section as micro_section

        lines.extend(micro_section(micro_collect()))

    lines.append(
        f"_Suite wall time: {time.perf_counter() - t_start:.0f} s._"
    )
    lines.append("")
    Path(out_path).write_text("\n".join(lines))
    print(f"[suite] wrote {out_path}")


# BASELINE.json's five named configs. The last two name multi-chip meshes
# (v4-8 / multi-host v4-32) this environment does not have — one v5e chip
# stands in, and the sharded collective program itself is exercised on the
# virtual 8-device CPU mesh (__graft_entry__.dryrun_multichip, which runs a
# 2M-node torus3d push-sum through the halo-exchange path every round-close).
# A 10M-node torus mixes over ~O(diameter^2) rounds — far beyond a table
# cell — so that row is a bounded-round throughput sample, marked as such.
NORTHSTAR_CONFIGS = (
    # (n, topology, algorithm, delivery, max_rounds or None=to convergence)
    (1_000, "line", "gossip", "auto", None),
    (10_000, "grid2d", "push-sum", "auto", None),
    # pooled long-range delivery — the r3 recast that takes this named
    # config off the sort-based scatter floor (static-graph numbers live
    # in the grid-scale table's imp3d static/scatter rows)
    (100_000, "imp2d", "push-sum", "pool", None),
    (1_000_000, "full", "gossip", "pool", None),
    (10_000_000, "torus3d", "push-sum", "auto", 2_000),  # auto routes the
    # fused stencil tiers; an explicit delivery pin would keep auto_ok off
)


def _termination_section(seed: int) -> list[str]:
    """Local-latch vs global-residual stop rule on the slow-mixing flagship
    (VERDICT r3 #7's BENCH_TABLES footnote)."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    n = 1_000_000
    topo = build_topology("torus3d", n)
    rows = []
    for term in ("local", "global"):
        for engine in ("chunked", "fused"):
            # Both criteria on both engines (VERDICT r3 #5: the fused
            # kernels implement the global residual in-kernel since r4):
            # same-engine rows isolate the criterion, same-criterion rows
            # isolate the per-round engine cost. engine='fused' (not
            # 'auto') so a silent fallback to chunked would fail loudly
            # instead of duplicating the chunked row.
            cfg = SimConfig(n=n, topology="torus3d", algorithm="push-sum",
                            seed=seed, termination=term, max_rounds=200_000,
                            engine=engine)
            res = run(topo, cfg)
            rows.append((term, engine, res))
            print(f"[suite] termination={term}/{engine}: {res.rounds} "
                  f"rounds, {res.wall_ms:.0f} ms, "
                  f"mae {res.estimate_mae:.2e}", flush=True)
    out = [
        "## Termination criterion: local latch vs global residual "
        "(torus3d 1M push-sum)",
        "",
        "The reference's own stop rule (program.fs:119-137) is per-node "
        "local stability; on slow-mixing graphs its straggler tail "
        "dominates. `--termination global` stops when every node's "
        "per-round RELATIVE ratio change is <= delta. Both criteria run "
        "on both engines (the fused kernels accumulate the per-round "
        "max-residual verdict in-kernel), so the table separates the "
        "stop-rule effect (rows) from the per-round engine cost (engine "
        "column):",
        "",
        "| criterion | engine | rounds | wall (ms) | estimate MAE "
        "| rel MAE |",
        "|---|---|---|---|---|---|",
    ]
    for term, engine, res in rows:
        out.append(
            f"| {term} | {engine} | {res.rounds:,} | {_fmt(res.wall_ms)} "
            f"| {res.estimate_mae:.2e} | {res.estimate_mae / res.true_mean:.1e} |"
        )
    out.append("")
    return out


def _northstar_section(seed: int) -> list[str]:
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    out = [
        "## BASELINE.json configs",
        "",
        "The five configs the north star names, measured on this "
        "environment's single chip (the v4-8 / v4-32 meshes the config list "
        "assumes are not available here; the multi-chip collective program "
        "is validated separately on a virtual 8-device mesh — "
        "`__graft_entry__.dryrun_multichip` runs a 2M-node torus3d push-sum "
        "through the halo-exchange delivery path). The 10M torus row is a "
        "bounded-round throughput sample: a torus that size needs ~O(10^5) "
        "rounds to mix, which is a property of the graph, not the engine.",
        "",
        "| config | population | status | wall (ms) | rounds | rounds/s |",
        "|---|---|---|---|---|---|",
    ]
    for n, kind, algo, delivery, cap in NORTHSTAR_CONFIGS:
        cfg = SimConfig(
            n=n, topology=kind, algorithm=algo, seed=seed, delivery=delivery,
            max_rounds=cap or 1_000_000,
        )
        try:
            topo = build_topology(kind, n, seed=seed)
            res = run(topo, cfg)
        except Exception as e:  # noqa: BLE001 — a failed row must not void
            # the many minutes of grid/scale measurements above it.
            out.append(f"| {n:,} {kind} {algo} | — | ERROR: {e} | — | — | — |")
            print(f"[suite] northstar {kind}/{algo} FAILED: {e}", flush=True)
            continue
        status = "converged" if res.converged else (
            f"bounded sample ({cap:,} rounds)" if cap else "DID NOT CONVERGE"
        )
        rps = res.to_record()["rounds_per_sec"] or 0.0
        out.append(
            f"| {n:,} {kind} {algo} | {topo.n:,} | {status} "
            f"| {_fmt(res.wall_ms)} | {res.rounds:,} | {rps:,.0f} |"
        )
        print(
            f"[suite] northstar {kind}/{algo} N={topo.n}: {res.wall_ms:.2f} ms "
            f"({res.rounds} rounds, {status})",
            flush=True,
        )
    out.append("")
    return out


def _calibrate(quick: bool) -> dict:
    """Schema-v1 calibration from REAL runs on the current host (ISSUE
    17): microbench floors (dispatch, addressing, rolls, one-hot MXU
    blend) plus one fused-kernel probe round measured through the same
    differential timing the bench tables use — so the vpu_op_ns floor is
    the backend-honest number (Pallas interpret mode on CPU, compiled on
    TPU), which is what keeps CPU plan choices on the chunked engines."""
    import jax

    from benchmarks.compare import engine_us_per_round
    from benchmarks.microbench import collect as micro_collect
    from cop5615_gossip_protocol_tpu.analysis import cost

    micro = micro_collect(quick=quick)
    # The fused probe runs the in-kernel threefry, which replicates the
    # partitionable stream only — same pin the execution suites use.
    jax.config.update("jax_threefry_partitionable", True)
    probe_n, probe_k = 4_096, 2
    print("[suite] autotune: probing the fused pool round "
          f"(n={probe_n}, K={probe_k})", flush=True)
    us = engine_us_per_round(
        "full", "push-sum", probe_n, engine="fused", delivery="pool",
        pool_size=probe_k, r1=4, r2=12,
    )
    fused_probe = {"n": probe_n, "pool_size": probe_k, "us_per_round": us}
    return {
        "schema": cost.CALIBRATION_SCHEMA,
        "host": {
            "backend": jax.default_backend(),
            "device_kind": getattr(jax.devices()[0], "device_kind",
                                   "unknown"),
            "device_count": len(jax.devices()),
        },
        "floors": cost.derive_floors(micro, fused_probe),
        "provenance": {
            "generated_by": "python benchmarks/suite.py --autotune",
            "date": datetime.date.today().isoformat(),
            "microbench_quick": bool(quick),
            "fused_probe": fused_probe,
        },
    }


def _autotune(args) -> int:
    """suite --autotune: regenerate analysis/calibration.json from real
    microbench/roofline-model probes on this host, then render the
    ranked plan decision table over the BENCH/serving cells
    (cost.AUTOTUNE_CELLS) as the --out markdown artifact. With
    --calibration FILE the measurement leg is skipped and selection runs
    against the fixed table — the CI determinism check renders twice and
    diffs."""
    import json

    from cop5615_gossip_protocol_tpu.analysis import cost
    from cop5615_gossip_protocol_tpu.utils.compat import (
        set_host_device_count,
    )

    # The sharded cells trace their wire term on a virtual mesh; request
    # enough host devices BEFORE the first computation initializes the
    # backend (CPU-only knob — a real TPU mesh is unaffected). Cells the
    # host still cannot serve render as explicit SKIPPED rows.
    try:
        set_host_device_count(
            max((ov.get("n_devices") or 1)
                for _, _, _, ov in cost.AUTOTUNE_CELLS)
        )
    except RuntimeError:
        pass  # backend already initialized; SKIPPED rows say so
    # Candidate legality consults the same support predicates as the
    # dispatch, and the fused tiers' in-kernel threefry requires the
    # partitionable stream — pin it (the execution suites' standard
    # runtime) so selection never depends on the ambient flag.
    import jax

    jax.config.update("jax_threefry_partitionable", True)

    out = Path(
        "PLAN_TABLE.md" if args.out == "BENCH_TABLES.md" else args.out
    )
    if args.calibration:
        cal = cost.load_calibration(args.calibration)
        print(f"[suite] autotune: fixed calibration {args.calibration}",
              flush=True)
    else:
        cal = _calibrate(quick=args.quick or args.smoke)
        cost.CALIBRATION_PATH.write_text(
            json.dumps(cal, indent=2, sort_keys=True) + "\n"
        )
        print(f"[suite] wrote {cost.CALIBRATION_PATH}", flush=True)
    lines = (
        ["# Plan selection — measured-cost autotuner decision table", "",
         f"Floors: {json.dumps(cal['floors'], sort_keys=True)}", ""]
        + cost.render_plan_table(
            cal, say=lambda m: print(f"[suite] autotune: {m}", flush=True)
        )
        + [""]
    )
    out.write_text("\n".join(lines))
    print(f"[suite] wrote {out}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="BENCH_TABLES.md")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--platform", choices=["auto", "cpu"], default="auto")
    ap.add_argument("--quick", action="store_true",
                    help="N<=200 cells only (CI smoke; full grid ~minutes)")
    ap.add_argument("--smoke", action="store_true",
                    help="smallest-N cells with truncated differential "
                    "budgets — exercises the whole code path in ~a minute "
                    "(the CI bench-smoke job)")
    ap.add_argument("--no-scale", action="store_true",
                    help="skip the beyond-reference scale rows")
    ap.add_argument("--replicas", type=int, default=0,
                    help="add vmapped replica-sweep columns (rounds "
                    "mean±CI95 over R seeds per cell, one dispatch per "
                    "cell; models/sweep.py)")
    ap.add_argument("--no-compile-cache", action="store_true",
                    help="skip the persistent XLA compilation cache "
                    "(enabled by default so repeated suite runs stop "
                    "re-paying compile)")
    ap.add_argument("--autotune", action="store_true",
                    help="regenerate analysis/calibration.json from real "
                    "microbench probes on this host and write the ranked "
                    "plan decision table (--out, default PLAN_TABLE.md) "
                    "instead of BENCH_TABLES (ISSUE 17)")
    ap.add_argument("--calibration", type=str, default=None, metavar="FILE",
                    help="with --autotune: skip measurement and run "
                    "selection against this fixed calibration file (the "
                    "CI determinism check)")
    ap.add_argument("--trajectory", type=str, default=None, metavar="FILE",
                    help="run the smallest grid cell with the telemetry "
                    "plane on, write its per-round trajectory JSONL here, "
                    "and add the rounds-to-X%% / ASCII-curve section "
                    "(benchmarks/trajectory.py) to the output markdown")
    args = ap.parse_args(argv)

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if not args.no_compile_cache:
        from cop5615_gossip_protocol_tpu.utils.compat import (
            enable_compilation_cache,
        )

        print(f"[suite] compile cache: {enable_compilation_cache()}",
              flush=True)
    if args.autotune:
        # Dispatch before anything probes jax.devices(): _autotune must
        # request the virtual mesh ahead of backend initialization.
        return _autotune(args)
    platform_note = (
        "CPU (forced)" if args.platform == "cpu"
        else jax.devices()[0].platform
    )
    if args.smoke:
        grid_n = (min(baseline_data.GRID_N),)
    elif args.quick:
        grid_n = tuple(n for n in baseline_data.GRID_N if n <= 200)
    else:
        grid_n = baseline_data.GRID_N
    scale_n = () if (args.no_scale or args.quick or args.smoke) else SCALE_N
    generate(
        args.out, args.seed, grid_n, scale_n, platform_note,
        replicas=args.replicas,
        us_pairs=1 if args.smoke else 3,
        us_budgets=(16, 128) if args.smoke else None,
        trajectory_path=args.trajectory,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
