"""Trajectory analyzer — rounds-to-X% tables and ASCII convergence curves.

Consumes the per-round trajectory JSONL the telemetry plane emits
(`--trace-convergence FILE`, ops/telemetry.py): one record per round with
``rounds``, ``converged_count``, ``newly_converged`` and either
``active_count`` (gossip) or ``estimate_mae`` (push-sum). Produces the
analysis BENCH_TABLES.md wants per flagship config:

- **rounds-to-X%** — the first round at which X% of the final converged
  count is reached, for the standard fractions. This is the number that
  survives engine and wall-clock changes: convergence SHAPE, not speed.
- **ASCII convergence curve** — converged fraction vs rounds on a fixed
  character grid, so a trajectory is legible in a terminal, a CI log, or
  a markdown code block without a plotting stack.

Usage:
  python benchmarks/trajectory.py TRACE.jsonl [--population N] [--md]
                                  [--width 64] [--height 12]
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

PERCENTILES = (10, 25, 50, 75, 90, 95, 99, 100)


def load_trace(path: str | Path) -> list[dict]:
    recs = []
    for line in Path(path).read_text().splitlines():
        if line.strip():
            recs.append(json.loads(line))
    if not recs:
        raise ValueError(f"{path}: empty trajectory")
    return recs


def rounds_to_fraction(recs: list[dict], denominator: int) -> dict[int, int | None]:
    """First ``rounds`` value at which converged_count reaches each
    PERCENTILES fraction of ``denominator`` — None if never reached, and
    None for fractions a PARTIAL trace (resume: first record past round 1)
    had already crossed before it begins: the true crossing round predates
    the file and reporting the trace's first round would be wrong."""
    first = recs[0]
    partial = first["rounds"] > 1
    out: dict[int, int | None] = {}
    for pct in PERCENTILES:
        need = pct * denominator / 100.0
        hit = None
        for r in recs:
            if r["converged_count"] >= need:
                hit = r["rounds"]
                break
        if partial and hit == first["rounds"] and first["converged_count"] >= need:
            hit = None  # crossed before the trace starts — unknowable here
        out[pct] = hit
    return out


def revival_rounds(recs: list[dict]) -> list[int]:
    """Rounds where crash-recovery revivals landed (the ``revived`` field
    telemetry schema v2 emits on rejoin rounds only) — empty for non-churn
    traces."""
    return [r["rounds"] for r in recs if r.get("revived", 0) > 0]


def byzantine_onset_rounds(recs: list[dict]) -> list[int]:
    """Rounds where adversaries turned: the cumulative ``byzantine`` count
    (telemetry schema v3) increased over the previous record. Empty for
    honest traces. Onsets, not every adversarial round — the count is
    monotone, so once positive every later round is adversarial and
    marking them all would bury the signal."""
    out = []
    prev = 0
    for r in recs:
        b = r.get("byzantine", 0)
        if b > prev:
            out.append(r["rounds"])
        prev = b
    return out


def ascii_curve(recs: list[dict], denominator: int,
                width: int = 64, height: int = 12) -> list[str]:
    """Converged fraction (y, 0..100%) vs rounds (x) on a width x height
    character grid — each column shows the max fraction reached in its
    round bucket. The x axis spans the TRACE's rounds (first..last), so a
    partial/resumed trace plots its own window instead of rendering the
    pre-trace rounds as a false flatline at 0%.

    Crash-recovery traces (any record with a ``revived`` count) get a
    marker row under the axis: ``^`` in every column where a revival
    landed, plus a summary line of the rejoin rounds — the shape of the
    curve is only interpretable next to when the population grew back.
    Adversarial traces (telemetry schema v3's ``byzantine`` count) get
    the same treatment with ``!`` at each onset round — a plateau or
    regression in the curve reads differently once you can see the
    adversaries turning."""
    first = recs[0]["rounds"]
    last = recs[-1]["rounds"]
    span = max(last - first + 1, 1)
    cols = [0.0] * width
    revive_cols = [False] * width
    byz_cols = [False] * width
    onsets = set(byzantine_onset_rounds(recs))
    for r in recs:
        x = min(width - 1, (r["rounds"] - first) * width // span)
        frac = r["converged_count"] / max(denominator, 1)
        cols[x] = max(cols[x], frac)
        if r.get("revived", 0) > 0:
            revive_cols[x] = True
        if r["rounds"] in onsets:
            byz_cols[x] = True
    # Forward-fill empty buckets (fewer rounds than columns).
    running = 0.0
    for x in range(width):
        running = max(running, cols[x])
        cols[x] = running
    lines = []
    for row in range(height, 0, -1):
        cut = row / height
        body = "".join("#" if c >= cut - 1e-12 and c > 0 else " "
                       for c in cols)
        label = f"{int(cut * 100):>4d}% |"
        lines.append(label + body)
    lines.append("      +" + "-" * width)
    left = f"{first:,} round" + ("s" if first > 1 else "")
    lines.append(
        f"       {left}{'':<{max(width - len(left) - len(f'{last:,}') - 1, 1)}}"
        f"{last:,}"
    )
    revs = revival_rounds(recs)
    if revs:
        lines.insert(
            height + 1,
            "       " + "".join("^" if m else " " for m in revive_cols),
        )
        shown = ", ".join(f"{r:,}" for r in revs[:12])
        more = f" (+{len(revs) - 12} more)" if len(revs) > 12 else ""
        lines.append(f"       ^ revivals at rounds: {shown}{more}")
    byz = sorted(onsets)
    if byz:
        # Marker row sits directly under the axis, above any revival row.
        lines.insert(
            height + 1,
            "       " + "".join("!" if m else " " for m in byz_cols),
        )
        shown = ", ".join(f"{r:,}" for r in byz[:12])
        more = f" (+{len(byz) - 12} more)" if len(byz) > 12 else ""
        final_ct = max(r.get("byzantine", 0) for r in recs)
        lines.append(
            f"       ! byzantine onsets at rounds: {shown}{more} "
            f"({final_ct:,} adversaries by the final round)"
        )
    return lines


def analyze(recs: list[dict], population: int | None = None) -> dict:
    final = recs[-1]
    denom = population or final["converged_count"]
    if denom <= 0:
        raise ValueError(
            "no nodes converged and no --population given; nothing to "
            "normalize the curve against"
        )
    out = {
        "rounds_total": final["rounds"],
        "converged_final": final["converged_count"],
        "denominator": denom,
        # A resumed run's trace starts mid-stream: percentiles crossed
        # before the file begins report None, and consumers should prefer
        # the uninterrupted run's trace for shape analysis.
        "partial_trace": recs[0]["rounds"] > 1,
        "rounds_to_pct": rounds_to_fraction(recs, denom),
        # Crash-recovery annotation (telemetry schema v2 traces): rounds
        # where revivals landed and the total rejoin count.
        "revival_rounds": revival_rounds(recs),
        "revived_total": sum(r.get("revived", 0) for r in recs),
        # Adversarial annotation (telemetry schema v3 traces): rounds where
        # the cumulative byzantine count grew, and its final value.
        "byzantine_onset_rounds": byzantine_onset_rounds(recs),
        "byzantine_final": max(
            (r.get("byzantine", 0) for r in recs), default=0
        ),
    }
    if "estimate_mae" in final:
        out["estimate_mae_final"] = final["estimate_mae"]
    if "active_count" in final:
        out["active_final"] = final["active_count"]
    return out


def section(recs: list[dict], population: int | None = None,
            title: str = "Convergence trajectory",
            width: int = 64, height: int = 12) -> list[str]:
    """Markdown section (BENCH_TABLES.md style) for one trajectory."""
    a = analyze(recs, population)
    denom = a["denominator"]
    lines = [
        f"## {title}",
        "",
        *(
            ["PARTIAL trace (starts mid-run, e.g. a resume): percentiles "
             "crossed before the trace begins show —.", ""]
            if a["partial_trace"] else []
        ),
        f"{a['rounds_total']:,} rounds traced; final converged "
        f"{a['converged_final']:,} / {denom:,}"
        + (
            f", estimate MAE {a['estimate_mae_final']:.3g}"
            if "estimate_mae_final" in a else ""
        )
        + ".",
        "",
        "| % converged | " + " | ".join(f"{p}%" for p in PERCENTILES) + " |",
        "|---|" + "---|" * len(PERCENTILES),
        "| rounds | " + " | ".join(
            "—" if a["rounds_to_pct"][p] is None
            else f"{a['rounds_to_pct'][p]:,}"
            for p in PERCENTILES
        ) + " |",
        "",
        "```",
        *ascii_curve(recs, denom, width=width, height=height),
        "```",
        "",
    ]
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="trajectory JSONL (--trace-convergence)")
    ap.add_argument("--population", type=int, default=None,
                    help="normalize against this population instead of the "
                    "final converged count")
    ap.add_argument("--md", action="store_true",
                    help="print the BENCH_TABLES.md-style markdown section")
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--height", type=int, default=12)
    args = ap.parse_args(argv)

    recs = load_trace(args.trace)
    if args.md:
        print("\n".join(section(
            recs, args.population, width=args.width, height=args.height
        )))
    else:
        a = analyze(recs, args.population)
        a["rounds_to_pct"] = {str(k): v for k, v in a["rounds_to_pct"].items()}
        print(json.dumps(a, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
