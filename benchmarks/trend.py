"""Perf-trajectory table — the bench history without git archaeology.

Every on-chip regen drops a ``BENCH_rNN.json`` (the flagship headline:
1M-node full-topology push-sum rounds/s with the engine-only split) and a
``MULTICHIP_rNN.json`` (the 8-device smoke verdict) at the repo root, but
until now the TRAJECTORY across revisions was only reconstructable by
walking git history. This tool rolls the committed snapshots into one
table — headline rounds/s, engine µs/round, flagship wall, compile,
multichip verdict, serving req/s — per revision, prints/writes it as
markdown, and (``--apply``) maintains the "Perf trajectory" section of
BENCH_TABLES.md idempotently. CI uploads the rendered table as an
artifact (bench-smoke job), so every run carries the full history.

Serving throughput has no ``SERVING_rNN.json`` convention (the loadgen
record is a CI artifact, not a committed snapshot): revisions gain a
serving column from ``--serving REV:RPS`` pins (the committed table
carries PR 6's measured 1,778 req/s) or ``--loadgen FILE --rev N`` to
read a ``benchmarks/loadgen.py --json`` record for the current revision.

``--ceilings`` recomputes the plan-level topology-ceilings section from
the pure plan functions (ISSUE 15: the replicated-pool2 rows per
delivery wire and mesh width, plus the host-sharded-construction
bounds); with ``--apply`` it installs idempotently under its own header,
like the matmul-tier section — and a bare ``--apply`` preserves every
previously applied section it does not regenerate (the pin-preservation
rule, tests/test_obs.py).

Usage::

    python benchmarks/trend.py [--root .] [--md out.md]
        [--serving 6:1778] [--loadgen loadgen.json --rev 7]
        [--ceilings] [--matmul-tier] [--apply]
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

SECTION_HEADER = "## Perf trajectory (benchmarks/trend.py)"
MATMUL_HEADER = (
    "## Delivery-tier trajectory — MXU matmul "
    "(benchmarks/trend.py --matmul-tier)"
)
CEILINGS_HEADER = (
    "## Topology ceilings past one chip "
    "(plan-level, benchmarks/trend.py --ceilings)"
)
BYZANTINE_HEADER = (
    "## Convergence degradation under Byzantine attack "
    "(benchmarks/trend.py --byzantine)"
)
PLAN_HEADER = (
    "## Plan selection — measured-cost autotuner "
    "(benchmarks/trend.py --autotune)"
)
STEP_TIMING_HEADER = (
    "## Measured vs predicted — per-super-step timing "
    "(benchmarks/trend.py --step-timing)"
)
DURABILITY_HEADER = (
    "## Checkpoint durability overhead "
    "(benchmarks/trend.py --durability)"
)


def load_snapshots(root: Path) -> dict:
    """{revision: {"bench": parsed-record|None, "multichip": dict|None}}
    from the committed BENCH_rNN.json / MULTICHIP_rNN.json snapshots."""
    revs: dict = {}
    for path in sorted(root.glob("BENCH_r*.json")):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", path.name)
        if not m:
            continue
        rec = json.loads(path.read_text())
        revs.setdefault(int(m.group(1)), {})["bench"] = rec.get("parsed")
    for path in sorted(root.glob("MULTICHIP_r*.json")):
        m = re.fullmatch(r"MULTICHIP_r(\d+)\.json", path.name)
        if not m:
            continue
        revs.setdefault(int(m.group(1)), {})["multichip"] = json.loads(
            path.read_text()
        )
    return revs


def parse_existing_serving(bench_tables: Path) -> dict:
    """Serving pins already applied to BENCH_TABLES.md's trajectory
    section: {revision: req/s}. Re-running ``--apply`` without repeating
    every historical ``--serving REV:RPS`` pin must not silently drop a
    measured figure from the table (the committed 1,778 req/s of r06 is a
    record, not a flag default) — explicit pins passed on the command
    line still win over parsed ones."""
    if not bench_tables.exists():
        return {}
    text = bench_tables.read_text()
    if SECTION_HEADER not in text:
        return {}
    section = text[text.index(SECTION_HEADER):]
    nxt = section.find("\n## ")
    if nxt > 0:
        section = section[:nxt]
    out: dict = {}
    for m in re.finditer(
        r"^\| r(\d+) \|.*\| ([\d,]+) \|\s*$", section, re.MULTILINE
    ):
        out[int(m.group(1))] = float(m.group(2).replace(",", ""))
    return out


def render(revs: dict, serving: dict) -> str:
    """Markdown table over the revision snapshots; ``serving`` maps
    revision -> req/s."""
    lines = [
        SECTION_HEADER,
        "",
        "Flagship = 1M-node full-topology push-sum on chip "
        "(BENCH_rNN.json); serving = benchmarks/loadgen.py closed-loop "
        "req/s on the CI-class CPU box. '—' = not measured at that "
        "revision.",
        "",
        "| rev | flagship rounds/s | engine µs/round | flagship wall ms "
        "| compile s | vs baseline | 8-dev smoke | serving req/s |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for rev in sorted(set(revs) | set(serving)):
        b = revs.get(rev, {}).get("bench") or {}
        mc = revs.get(rev, {}).get("multichip")

        def num(key, fmt, rec=b):
            v = rec.get(key)
            return format(v, fmt) if isinstance(v, (int, float)) else "—"

        mc_txt = "—"
        if mc is not None:
            mc_txt = (
                "skipped" if mc.get("skipped")
                else ("ok" if mc.get("ok") else "FAIL")
            )
        rps = serving.get(rev)
        wall = b.get("wall_s")
        lines.append(
            "| r{:02d} | {} | {} | {} | {} | {} | {} | {} |".format(
                rev,
                num("value", ",.0f"),
                num("engine_us_per_round", ".1f"),
                format(1e3 * wall, ".1f") if isinstance(
                    wall, (int, float)) else "—",
                num("compile_s", ".2f"),
                num("vs_baseline", ",.0f") + "x" if isinstance(
                    b.get("vs_baseline"), (int, float)) else "—",
                mc_txt,
                format(rps, ",.0f") if rps is not None else "—",
            )
        )
    lines.append("")
    return "\n".join(lines)


def render_ceilings(n_dev: int = 8) -> str:
    """The topology-ceilings section, RECOMPUTED from the plan functions
    instead of hand-typed: plan_imp_hbm_sharded_shape and
    plan_pool2_sharded are pure in (kind, n, cfg, n_dev) — no adjacency
    arrays, no device — so the admitted aggregate populations are
    verifiable on any box. ISSUE 15 adds the replicated-pool2 rows PER
    WIRE (the banded reduce_scatter delivery vs the gather-bound
    all_gather it replaces, at 8 and 16 devices — the gather rows go
    FLAT with mesh width, the band rows keep growing) and the
    host-sharded-construction rows (peak DRIVER-HOST build memory before
    vs after mesh.put_rows / build_topology rows=). The ms/round cells
    stay 'pending' until an on-chip regen (the BENCH_TABLES
    measured-on-CPU caveat protocol); everything else in this section is
    computed, not claimed."""
    sys.path.insert(0, str(REPO))
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)
    from cop5615_gossip_protocol_tpu import SimConfig
    from cop5615_gossip_protocol_tpu.ops.topology import build_full
    from cop5615_gossip_protocol_tpu.parallel.fused_imp_hbm_sharded import (
        plan_imp_hbm_sharded_shape,
    )
    from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
        plan_pool2_sharded,
    )

    def cfg(n, alg, nd, wire="auto"):
        return SimConfig(n=n, topology="full", algorithm=alg,
                         engine="fused", delivery="pool", n_devices=nd,
                         pool2_wire=wire)

    rows = []
    for alg in ("gossip", "push-sum"):
        best = None
        for g in range(600, 1200, 8):  # cubes bracketing 2^28..2^30
            n = g ** 3
            plan = plan_imp_hbm_sharded_shape(
                "imp3d", n, cfg(n, alg, n_dev), n_dev
            )
            if not isinstance(plan, str):
                best = (g, n)
        rows.append((
            "imp × HBM × sharded", "imp3d", alg, f"{n_dev} dev",
            "none admitted in the swept range" if best is None else
            f"{best[0]}³ = {best[1]:,} ({best[1] / (1 << 28):.2f} × 2^28)",
        ))
    for wire in ("all_gather", "reduce_scatter"):
        for nd in (n_dev, 2 * n_dev):
            for alg in ("gossip", "push-sum"):
                hi = None
                for p in range(27, 35):
                    n = 1 << p
                    plan = plan_pool2_sharded(
                        build_full(n, False), cfg(n, alg, nd, wire), nd
                    )
                    if not isinstance(plan, str):
                        hi = p
                rows.append((
                    f"replicated-pool2 ({wire})", "full", alg, f"{nd} dev",
                    "none admitted in the swept range" if hi is None else
                    f"2^{hi} = {1 << hi:,}",
                ))
    lines = [
        CEILINGS_HEADER,
        "",
        f"Plan-level aggregate population ceilings (base mesh {n_dev} "
        "devices; the replicated-pool2 rows sweep both delivery wires and "
        "two mesh widths — the all_gather rows are GATHER-BOUND and go "
        "flat, the ISSUE 15 banded reduce_scatter rows keep growing with "
        "the mesh). Computed from the pure plan functions on this box "
        "(hardware-free); ms/round cells are measured-on-chip only and "
        "stay pending until a TPU regen.",
        "",
        "| composition | topology | algorithm | mesh "
        "| aggregate plan ceiling | ms/round on chip |",
        "|---|---|---|---|---|---|",
    ]
    for comp, topo, alg, mesh, ceil in rows:
        lines.append(
            f"| {comp} | {topo} | {alg} | {mesh} | {ceil} | pending |"
        )
    lines += _host_build_ceiling_lines(n_dev)
    lines.append("")
    return "\n".join(lines)


def _host_build_ceiling_lines(n_dev: int) -> list:
    """Host-sharded-construction ceiling rows (ISSUE 15): peak DRIVER-HOST
    memory on the build path, before (global to_planes + init_state /
    global adjacency) vs after (mesh.put_rows per-shard callbacks +
    build_topology rows= slices), with the largest population a 16 GiB
    driver host can even BUILD under each. Byte models are per-node build
    peaks read off the code paths; the after-column is pinned by the
    allocation tracker in tests/test_hostmem.py (no global-N intermediate
    on the sharded build path)."""
    host_gib = 16
    budget = host_gib << 30
    # (label, legacy peak bytes/node, sharded peak bytes/node-equivalent)
    # Legacy peaks: canonical init_state + the padded to_planes copies
    # both alive at hand-off (pool2 push-sum 13+12, gossip 6+8; hbm
    # push-sum 13+16), torus3d adjacency = [n,6] i32 + stack transient +
    # degree. Host-sharded peaks: one per-device shard block at a time
    # (plane bytes / n_dev); the adjacency drops to ZERO (spec-only
    # build, analytic offsets).
    models = [
        ("replicated-pool2 state planes (push-sum)", 25.0, 12.0 / n_dev),
        ("replicated-pool2 state planes (gossip)", 14.0, 8.0 / n_dev),
        ("HBM × sharded state planes (push-sum)", 29.0, 16.0 / n_dev),
        ("torus3d adjacency build", 52.0, 0.0),
    ]
    lines = [
        "",
        f"Host-sharded construction (ISSUE 15): peak build memory on a "
        f"{host_gib} GiB driver host, legacy global build vs "
        "mesh.put_rows / build_topology rows= at "
        f"{n_dev} shards (allocation-tracked in tests/test_hostmem.py).",
        "",
        "| build path | legacy peak (per node) | legacy host bound "
        "| host-sharded peak (per node) | host-sharded bound |",
        "|---|---|---|---|---|",
    ]

    def bound(bytes_per_node):
        if bytes_per_node == 0.0:
            return "unbounded (spec-only build)"
        b = int(budget / bytes_per_node)
        return f"~2^{b.bit_length() - 1} ({b / (1 << 30):.2f} × 2^30)"

    for label, legacy, sharded in models:
        lines.append(
            f"| {label} | {legacy:.0f} B | {bound(legacy)} "
            f"| {sharded:.1f} B | {bound(sharded)} |"
        )
    return lines


def render_matmul_tier() -> str:
    """The ISSUE 12 delivery-tier row, measured on THIS box's CPU: the
    chunked matmul tier vs the chunked pool tier at full n=1024 (fixed
    identical rounds via an unreachable rumor threshold — the
    microbench/chunk_sync methodology, so both cells execute the same
    chunks x chunk_rounds and the comparison is batching-comparable to
    the trajectory table's fixed-round cells) plus the op-level pool
    aggregation pair, timed through benchmarks/microbench.delivery_forms
    — the ONE home of the deliver_pool-vs-deliver_matmul comparison
    surface, so this section and the Dispatch-floor rows cannot drift in
    methodology. On
    CPU there is no MXU, so the matmul column measures formulation
    overhead only; the on-chip regen fills the real rows (the BENCH
    protocol — same as the topology-ceilings ms/round cells)."""
    sys.path.insert(0, str(REPO))
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)

    from benchmarks.microbench import delivery_forms, time_delivery_form

    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    n, chunks, chunk_rounds = 1024, 16, 8
    topo = build_topology("full", n)
    per_round_us = {}
    for d in ("pool", "matmul"):
        cfg = SimConfig(
            n=n, topology="full", algorithm="gossip", seed=0, delivery=d,
            rumor_threshold=10**6, engine="chunked",
            chunk_rounds=chunk_rounds, max_rounds=chunks * chunk_rounds,
        )
        best = None
        for _ in range(3):
            res = run(topo, cfg)
            assert res.rounds == chunks * chunk_rounds
            best = res.run_s if best is None else min(best, res.run_s)
        per_round_us[d] = best / (chunks * chunk_rounds) * 1e6

    forms = delivery_forms(n, 4)
    agg_us = {
        "pool rolls": time_delivery_form(forms["pool_rolls"], 40),
        "one-hot dot_general": time_delivery_form(
            forms["onehot_dot_general"], 40
        ),
    }

    return "\n".join([
        MATMUL_HEADER,
        "",
        "MXU delivery tier (ISSUE 12) vs the pool tier it is "
        "stream-identical to, measured on this box's CPU (fixed "
        f"{chunks} x {chunk_rounds} rounds, min-of-3 — the fixed-round "
        "methodology of the trajectory cells above, so the columns are "
        "batching-comparable). Gossip trajectories are bitwise-identical "
        "across the two tiers (tests/test_delivery_matmul.py); on CPU "
        "the one-hot contraction has no MXU to land on, so its column is "
        "formulation overhead — the on-chip regen (MXU) is pending.",
        "",
        "| cell | chunked pool | chunked matmul | on-chip (MXU) |",
        "|---|---|---|---|",
        "| full n=1,024 gossip, µs/round | "
        f"{per_round_us['pool']:,.0f} | {per_round_us['matmul']:,.0f} "
        "| pending |",
        "| pool aggregation op (n=1,024, K=4), µs | "
        f"{agg_us['pool rolls']:,.0f} | "
        f"{agg_us['one-hot dot_general']:,.0f} | pending |",
        "",
    ])


def render_byzantine() -> str:
    """The ISSUE 16 convergence-degradation campaign: push-sum under the
    mass_inflate attack, swept over Byzantine fraction x topology x
    countermeasure, all on the chunked engine on this box's CPU. Every
    run is fully seeded (the adversary plane is config-pure,
    ops/faults.byzantine_plane), so the section regenerates
    byte-identically — numbers here are records, not estimates.

    Column semantics differ by design: the ``none`` column runs WITH the
    mass-conservation sentinel (--mass-tolerance 1e-3) — unmitigated
    adversaries are a DETECTION story, and the cell reports the exact
    round the sentinel tripped. The ``clip``/``trim`` columns run without
    it (config-enforced: robust aggregation discards weight by design,
    so robust_agg excludes mass_tolerance) — mitigation is a CONVERGENCE
    story, and the cells report rounds + estimate MAE. ``trim`` needs
    the full topology's uniform pool-slot channels (config-enforced),
    so the torus3d rows mark it n/a."""
    sys.path.insert(0, str(REPO))
    import warnings

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    fractions = (0.0, 0.02, 0.05, 0.10)
    topologies = (("full", 256, {"delivery": "pool"}), ("torus3d", 216, {}))
    max_rounds = 1500

    def cell(topo_name, n, extra, frac, agg):
        kw = dict(
            n=n, topology=topo_name, algorithm="push-sum", seed=0,
            engine="chunked", chunk_rounds=64, max_rounds=max_rounds,
            byzantine_rate=frac, byzantine_mode="mass_inflate",
            robust_agg=agg, **extra,
        )
        if agg == "none":
            kw["mass_tolerance"] = 1e-3
        topo = build_topology(topo_name, n)
        with warnings.catch_warnings():
            # robust_agg without adversaries (the fraction-0 control rows)
            # fires the SimConfig lint warning by design.
            warnings.simplefilter("ignore")
            r = run(topo, SimConfig(**kw))
        if r.outcome == "unhealthy":
            return f"unhealthy @ r {r.unhealthy_round}"
        mae = f"MAE {r.estimate_mae:.2f}"
        if r.outcome == "converged":
            return f"{r.rounds} r, {mae}"
        return f"no conv ({r.outcome}, {r.rounds} r), {mae}"

    lines = [
        BYZANTINE_HEADER,
        "",
        "Push-sum under the mass_inflate attack on the chunked engine "
        "(CPU, fully seeded — regenerates byte-identically). The `none` "
        "column runs with the mass-conservation sentinel "
        "(--mass-tolerance 1e-3): the cell is the exact round detection "
        "fired. The `clip`/`trim` columns run the countermeasure instead "
        "(robust_agg excludes mass_tolerance by config) and report "
        "rounds to convergence + estimate MAE against the true mean. "
        "trim is full-topology-only (uniform pool-slot channels). trim "
        "never biases but DISCARDS weight every round (ops/delivery."
        "deliver_pool_trimmed), so a run it fails to converge in time "
        "underflows its total float32 weight to zero — a 'no conv' trim "
        "cell with a garbage MAE is that failure mode, recorded.",
        "",
        "| topology | byz fraction | none (+ sentinel) | clip | trim |",
        "|---|---|---|---|---|",
    ]
    for topo_name, n, extra in topologies:
        for frac in fractions:
            row = [cell(topo_name, n, extra, frac, "none"),
                   cell(topo_name, n, extra, frac, "clip")]
            if topo_name == "full":
                row.append(cell(topo_name, n, extra, frac, "trim"))
            else:
                row.append("n/a")
            lines.append(
                f"| {topo_name} n={n} | {frac:.0%} | " + " | ".join(row)
                + " |"
            )
    lines.append("")
    return "\n".join(lines)


def render_autotune() -> str:
    """The ISSUE 17 plan-selection section: the measured-cost autotuner's
    decision table over analysis/cost.AUTOTUNE_CELLS, rendered against
    the COMMITTED calibration (analysis/calibration.json) — so the
    section is deterministic (records of the committed decision, not
    fresh measurements) and a re-apply is byte-identical until the
    calibration file itself is regenerated (benchmarks/suite.py
    --autotune)."""
    sys.path.insert(0, str(REPO))
    from cop5615_gossip_protocol_tpu.analysis import cost, matrix

    # The sharded cells trace their wire term on an 8-device virtual
    # mesh; pin the tracing runtime before JAX initializes a backend.
    matrix.setup_tracing_runtime()
    cal = cost.load_calibration()
    lines = [
        PLAN_HEADER,
        "",
        "Plan choices scored by the measured cost model "
        "(analysis/cost.py): per-round compute from roofline linear "
        "forms x microbench-calibrated floors, per-round wire from the "
        "candidate's TRACED receive bytes x the calibrated byte cost, "
        "plus the amortized dispatch floor. Rendered against the "
        "committed `analysis/calibration.json` "
        f"(schema v{cal.get('schema')}, host: "
        f"{cal.get('host', {}).get('device_kind', '?')}) — regenerate "
        "with `python benchmarks/suite.py --autotune`. The hand ladder "
        "stays the oracle: an `agree=**NO**` row is a bug "
        "(tests/test_autotune.py pins the parity sweep).",
        "",
    ]
    lines += cost.render_plan_table(cal)
    lines.append("")
    return "\n".join(lines)


def render_step_timing() -> str:
    """The ISSUE 18 feedback loop: run analysis/cost.STEP_TIMING_CELLS
    with cfg.step_timing=True (clock-only retire timestamps from the
    chunk driver) and join each cell's measured median us/round against
    the autotuner's scored floor from the committed calibration. Unlike
    --autotune this section IS a fresh measurement — the ratio column
    moves with the host — so it reads as a calibration health check, not
    a deterministic record; regenerate alongside `suite --autotune`."""
    sys.path.insert(0, str(REPO))
    from cop5615_gossip_protocol_tpu.analysis import cost

    cal = cost.load_calibration()
    lines = [
        STEP_TIMING_HEADER,
        "",
        "Measured per-dispatch super-step wall (cfg.step_timing=True — "
        "perf_counter retire stamps in models/pipeline.run_chunks, zero "
        "extra syncs) vs the autotuner's scored floor for the same cell "
        "(analysis/cost.measured_vs_predicted, committed "
        "`analysis/calibration.json` "
        f"schema v{cal.get('schema')}). A ratio far from 1 localizes a "
        "stale floor or a wrong linear form; the ROADMAP item-5 hardware "
        "campaign re-measures this table on chip.",
        "",
    ]
    lines += cost.measured_vs_predicted(
        cal, say=lambda s: print(f"[step-timing] {s}", file=sys.stderr)
    )
    lines.append("")
    return "\n".join(lines)


def render_durability() -> str:
    """The ISSUE 19 durability-overhead record: what the durable state
    plane (utils/checkpoint — per-array digests, sidecar, generation
    bookkeeping) costs, vs state size and algorithm. Archive bytes and
    rounds are deterministic records; the wall columns are fresh
    measurements on this box (a health check like --step-timing, not a
    byte-stable record). The resume column is the crash-only-restarts
    payoff: wall of a run resumed from the midpoint checkpoint vs the
    uninterrupted run (both post-compile)."""
    sys.path.insert(0, str(REPO))
    import statistics
    import tempfile
    import time as _time

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
    from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt

    cells = (
        ("gossip", 256, 32),
        ("gossip", 4096, 32),
        ("push-sum", 256, 32),
        ("push-sum", 4096, 32),
    )
    lines = [
        DURABILITY_HEADER,
        "",
        "Per-checkpoint cost of the durable state plane "
        "(utils/checkpoint.save: compressed npz + SHA-256 per array + "
        "digest sidecar; load re-verifies every digest before "
        "deserializing state). `write overhead` is the summed save wall "
        "as a fraction of the run's post-compile wall at one checkpoint "
        "per chunk boundary — the worst-case `--checkpoint-every 1` "
        "cadence. Chunked engine, full topology, this box's CPU.",
        "",
        "| cell | rounds | archive KiB | write ms (med) | "
        "verify+load ms | write overhead | resume wall / cold wall |",
        "|---|---|---|---|---|---|---|",
    ]
    tmp = Path(tempfile.mkdtemp(prefix="gossip_trend_durability_"))
    for alg, n, chunk in cells:
        cfg = SimConfig(n=n, topology="full", algorithm=alg,
                        chunk_rounds=chunk, max_rounds=4000)
        topo = build_topology("full", n)
        snaps = []
        res = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
        path = tmp / f"{alg.replace('-', '')}-{n}.npz"
        writes, nbytes = [], 0
        for r, s in snaps:
            info = ckpt.save(path, s, r, cfg)
            writes.append(info["write_s"])
            nbytes = info["bytes"]
        t0 = _time.perf_counter()
        ckpt.load(path)
        load_s = _time.perf_counter() - t0
        overhead = sum(writes) / max(res.run_s, 1e-9)
        mid_r, mid_s = snaps[len(snaps) // 2]
        ckpt.save(path, mid_s, mid_r, cfg)
        st, rnds, cfg2 = ckpt.load(path)
        resumed = run(topo, cfg2, start_state=st, start_round=rnds)
        ratio = resumed.run_s / max(res.run_s, 1e-9)
        lines.append(
            f"| {alg} full n={n} | {res.rounds} | {nbytes / 1024:.1f} | "
            f"{statistics.median(writes) * 1e3:.2f} | {load_s * 1e3:.2f} "
            f"| {overhead:.1%} | {ratio:.2f} |"
        )
        print(f"[durability] {alg} n={n}: rounds={res.rounds} "
              f"bytes={nbytes} saves={len(writes)}", file=sys.stderr)
    lines.append("")
    return "\n".join(lines)


def apply_to_bench_tables(table_md: str, bench_tables: Path,
                          header: str = SECTION_HEADER) -> None:
    """Idempotently install/replace one generated section: everything
    from ``header`` to the next '## ' heading (or EOF) is replaced, with
    exactly one blank line left before the next heading — so repeated
    applies are byte-stable (the ISSUE 15 idempotence pin caught the old
    form eating the separator on every second apply)."""
    text = bench_tables.read_text()
    if header in text:
        start = text.index(header)
        rest = text[start + len(header):]
        nxt = rest.find("\n## ")
        if nxt < 0:
            text = text[:start] + table_md
        else:
            end = start + len(header) + nxt + 1
            text = text[:start] + table_md + "\n" + text[end:]
    else:
        if not text.endswith("\n"):
            text += "\n"
        text += "\n" + table_md
    bench_tables.write_text(text)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", type=Path, default=REPO,
                    help="directory holding the BENCH_r*/MULTICHIP_r* "
                    "snapshots (default: the repo root)")
    ap.add_argument("--serving", action="append", default=[],
                    metavar="REV:RPS",
                    help="pin a serving req/s figure for a revision "
                    "(repeatable), e.g. --serving 6:1778")
    ap.add_argument("--loadgen", type=Path, default=None,
                    help="read the serving req/s for --rev from a "
                    "benchmarks/loadgen.py --json record")
    ap.add_argument("--rev", type=int, default=None,
                    help="revision number the --loadgen record belongs to")
    ap.add_argument("--md", type=Path, default=None,
                    help="write the markdown table here")
    ap.add_argument("--apply", action="store_true",
                    help="install/replace the 'Perf trajectory' section "
                    "in BENCH_TABLES.md (idempotent)")
    ap.add_argument("--ceilings", action="store_true",
                    help="append the plan-level topology-ceilings table "
                    "(ISSUE 10), recomputed from the pure plan functions")
    ap.add_argument("--matmul-tier", action="store_true",
                    help="measure and append the MXU-matmul delivery-tier "
                    "row (ISSUE 12): chunked matmul vs pool at full "
                    "n=1024 plus the pool-aggregation op pair, on this "
                    "box's CPU (on-chip regen pending); with --apply the "
                    "section installs into BENCH_TABLES.md idempotently")
    ap.add_argument("--byzantine", action="store_true",
                    help="run and append the Byzantine convergence-"
                    "degradation campaign (ISSUE 16): push-sum under "
                    "mass_inflate over fraction x topology x "
                    "countermeasure, fully seeded so repeated applies are "
                    "byte-identical; with --apply the section installs "
                    "into BENCH_TABLES.md idempotently")
    ap.add_argument("--autotune", action="store_true",
                    help="render and append the plan-selection decision "
                    "table (ISSUE 17): the measured-cost autotuner's "
                    "ranked plans over analysis/cost.AUTOTUNE_CELLS "
                    "against the COMMITTED analysis/calibration.json "
                    "(deterministic — no fresh measurement); with "
                    "--apply the section installs into BENCH_TABLES.md "
                    "idempotently")
    ap.add_argument("--step-timing", action="store_true",
                    help="run and append the measured-vs-predicted "
                    "step-timing table (ISSUE 18): per-super-step wall "
                    "from cfg.step_timing=True runs of the comparison "
                    "cells joined against the autotuner's scored floors "
                    "(a fresh measurement, not a deterministic record); "
                    "with --apply the section installs into "
                    "BENCH_TABLES.md idempotently")
    ap.add_argument("--durability", action="store_true",
                    help="run and append the checkpoint-durability "
                    "overhead table (ISSUE 19): per-checkpoint write / "
                    "verify+load walls, archive bytes and the resume-vs-"
                    "cold-start ratio vs state size (a fresh measurement "
                    "for the wall columns); with --apply the section "
                    "installs into BENCH_TABLES.md idempotently")
    args = ap.parse_args(argv)

    revs = load_snapshots(args.root)
    if not revs:
        print(f"no BENCH_r*/MULTICHIP_r* snapshots under {args.root}",
              file=sys.stderr)
        return 1

    # Pins already in the committed table survive a bare re-apply;
    # command-line pins override them.
    serving: dict = parse_existing_serving(args.root / "BENCH_TABLES.md")
    for pin in args.serving:
        try:
            rev_s, rps_s = pin.split(":", 1)
            serving[int(rev_s)] = float(rps_s)
        except ValueError:
            print(f"bad --serving pin {pin!r} (want REV:RPS)",
                  file=sys.stderr)
            return 2
    if args.loadgen is not None:
        if args.rev is None:
            print("--loadgen needs --rev (the revision the record "
                  "belongs to)", file=sys.stderr)
            return 2
        rec = json.loads(args.loadgen.read_text())
        rps = (rec.get("batched") or {}).get("rps")
        if rps is None:
            print(f"{args.loadgen} has no batched.rps field",
                  file=sys.stderr)
            return 2
        serving[args.rev] = float(rps)

    table = render(revs, serving)
    matmul_md = render_matmul_tier() if args.matmul_tier else None
    # Each generated section has its OWN "## " header and its own
    # idempotent apply (everything from the header to the next "## "
    # heading is replaced), so trajectory, ceilings and matmul-tier
    # compose — and a bare --apply preserves every previously applied
    # section it does not regenerate (the PR 9 pin-preservation rule,
    # extended to the ceilings section by ISSUE 15;
    # tests/test_obs.py pins the idempotence).
    ceilings_md = render_ceilings() if args.ceilings else None
    byzantine_md = render_byzantine() if args.byzantine else None
    autotune_md = render_autotune() if args.autotune else None
    step_timing_md = render_step_timing() if args.step_timing else None
    durability_md = render_durability() if args.durability else None
    out = table
    if ceilings_md is not None:
        out = out + "\n" + ceilings_md
    if matmul_md is not None:
        out = out + "\n" + matmul_md
    if byzantine_md is not None:
        out = out + "\n" + byzantine_md
    if autotune_md is not None:
        out = out + "\n" + autotune_md
    if step_timing_md is not None:
        out = out + "\n" + step_timing_md
    if durability_md is not None:
        out = out + "\n" + durability_md
    print(out)
    if args.md:
        args.md.write_text(out + "\n")
    if args.apply:
        apply_to_bench_tables(table, args.root / "BENCH_TABLES.md")
        if ceilings_md is not None:
            apply_to_bench_tables(
                ceilings_md, args.root / "BENCH_TABLES.md",
                header=CEILINGS_HEADER,
            )
        if matmul_md is not None:
            apply_to_bench_tables(
                matmul_md, args.root / "BENCH_TABLES.md",
                header=MATMUL_HEADER,
            )
        if byzantine_md is not None:
            apply_to_bench_tables(
                byzantine_md, args.root / "BENCH_TABLES.md",
                header=BYZANTINE_HEADER,
            )
        if autotune_md is not None:
            apply_to_bench_tables(
                autotune_md, args.root / "BENCH_TABLES.md",
                header=PLAN_HEADER,
            )
        if step_timing_md is not None:
            apply_to_bench_tables(
                step_timing_md, args.root / "BENCH_TABLES.md",
                header=STEP_TIMING_HEADER,
            )
        if durability_md is not None:
            apply_to_bench_tables(
                durability_md, args.root / "BENCH_TABLES.md",
                header=DURABILITY_HEADER,
            )
        print(f"[trend] applied to {args.root / 'BENCH_TABLES.md'}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
