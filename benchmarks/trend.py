"""Perf-trajectory table — the bench history without git archaeology.

Every on-chip regen drops a ``BENCH_rNN.json`` (the flagship headline:
1M-node full-topology push-sum rounds/s with the engine-only split) and a
``MULTICHIP_rNN.json`` (the 8-device smoke verdict) at the repo root, but
until now the TRAJECTORY across revisions was only reconstructable by
walking git history. This tool rolls the committed snapshots into one
table — headline rounds/s, engine µs/round, flagship wall, compile,
multichip verdict, serving req/s — per revision, prints/writes it as
markdown, and (``--apply``) maintains the "Perf trajectory" section of
BENCH_TABLES.md idempotently. CI uploads the rendered table as an
artifact (bench-smoke job), so every run carries the full history.

Serving throughput has no ``SERVING_rNN.json`` convention (the loadgen
record is a CI artifact, not a committed snapshot): revisions gain a
serving column from ``--serving REV:RPS`` pins (the committed table
carries PR 6's measured 1,778 req/s) or ``--loadgen FILE --rev N`` to
read a ``benchmarks/loadgen.py --json`` record for the current revision.

Usage::

    python benchmarks/trend.py [--root .] [--md out.md]
        [--serving 6:1778] [--loadgen loadgen.json --rev 7] [--apply]
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

SECTION_HEADER = "## Perf trajectory (benchmarks/trend.py)"
MATMUL_HEADER = (
    "## Delivery-tier trajectory — MXU matmul "
    "(benchmarks/trend.py --matmul-tier)"
)


def load_snapshots(root: Path) -> dict:
    """{revision: {"bench": parsed-record|None, "multichip": dict|None}}
    from the committed BENCH_rNN.json / MULTICHIP_rNN.json snapshots."""
    revs: dict = {}
    for path in sorted(root.glob("BENCH_r*.json")):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", path.name)
        if not m:
            continue
        rec = json.loads(path.read_text())
        revs.setdefault(int(m.group(1)), {})["bench"] = rec.get("parsed")
    for path in sorted(root.glob("MULTICHIP_r*.json")):
        m = re.fullmatch(r"MULTICHIP_r(\d+)\.json", path.name)
        if not m:
            continue
        revs.setdefault(int(m.group(1)), {})["multichip"] = json.loads(
            path.read_text()
        )
    return revs


def parse_existing_serving(bench_tables: Path) -> dict:
    """Serving pins already applied to BENCH_TABLES.md's trajectory
    section: {revision: req/s}. Re-running ``--apply`` without repeating
    every historical ``--serving REV:RPS`` pin must not silently drop a
    measured figure from the table (the committed 1,778 req/s of r06 is a
    record, not a flag default) — explicit pins passed on the command
    line still win over parsed ones."""
    if not bench_tables.exists():
        return {}
    text = bench_tables.read_text()
    if SECTION_HEADER not in text:
        return {}
    section = text[text.index(SECTION_HEADER):]
    nxt = section.find("\n## ")
    if nxt > 0:
        section = section[:nxt]
    out: dict = {}
    for m in re.finditer(
        r"^\| r(\d+) \|.*\| ([\d,]+) \|\s*$", section, re.MULTILINE
    ):
        out[int(m.group(1))] = float(m.group(2).replace(",", ""))
    return out


def render(revs: dict, serving: dict) -> str:
    """Markdown table over the revision snapshots; ``serving`` maps
    revision -> req/s."""
    lines = [
        SECTION_HEADER,
        "",
        "Flagship = 1M-node full-topology push-sum on chip "
        "(BENCH_rNN.json); serving = benchmarks/loadgen.py closed-loop "
        "req/s on the CI-class CPU box. '—' = not measured at that "
        "revision.",
        "",
        "| rev | flagship rounds/s | engine µs/round | flagship wall ms "
        "| compile s | vs baseline | 8-dev smoke | serving req/s |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for rev in sorted(set(revs) | set(serving)):
        b = revs.get(rev, {}).get("bench") or {}
        mc = revs.get(rev, {}).get("multichip")

        def num(key, fmt, rec=b):
            v = rec.get(key)
            return format(v, fmt) if isinstance(v, (int, float)) else "—"

        mc_txt = "—"
        if mc is not None:
            mc_txt = (
                "skipped" if mc.get("skipped")
                else ("ok" if mc.get("ok") else "FAIL")
            )
        rps = serving.get(rev)
        wall = b.get("wall_s")
        lines.append(
            "| r{:02d} | {} | {} | {} | {} | {} | {} | {} |".format(
                rev,
                num("value", ",.0f"),
                num("engine_us_per_round", ".1f"),
                format(1e3 * wall, ".1f") if isinstance(
                    wall, (int, float)) else "—",
                num("compile_s", ".2f"),
                num("vs_baseline", ",.0f") + "x" if isinstance(
                    b.get("vs_baseline"), (int, float)) else "—",
                mc_txt,
                format(rps, ",.0f") if rps is not None else "—",
            )
        )
    lines.append("")
    return "\n".join(lines)


def render_ceilings(n_dev: int = 8) -> str:
    """The ISSUE 10 'topology ceilings' rows, RECOMPUTED from the plan
    functions instead of hand-typed: plan_imp_hbm_sharded_shape and
    plan_pool2_sharded are pure in (kind, n, cfg, n_dev) — no adjacency
    arrays, no device — so the admitted aggregate populations are
    verifiable on any box. The ms/round cells stay 'pending' until an
    on-chip regen (the BENCH_TABLES protocol)."""
    sys.path.insert(0, str(REPO))
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)
    from cop5615_gossip_protocol_tpu import SimConfig
    from cop5615_gossip_protocol_tpu.ops.topology import build_full
    from cop5615_gossip_protocol_tpu.parallel.fused_imp_hbm_sharded import (
        plan_imp_hbm_sharded_shape,
    )
    from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
        plan_pool2_sharded,
    )

    def cfg(n, alg):
        return SimConfig(n=n, topology="full", algorithm=alg,
                         engine="fused", delivery="pool", n_devices=n_dev)

    rows = []
    for alg in ("gossip", "push-sum"):
        best = None
        for g in range(600, 1200, 8):  # cubes bracketing 2^28..2^30
            n = g ** 3
            plan = plan_imp_hbm_sharded_shape(
                "imp3d", n, cfg(n, alg), n_dev
            )
            if not isinstance(plan, str):
                best = (g, n)
        rows.append((
            "imp × HBM × sharded", "imp3d", alg,
            "none admitted in the swept range" if best is None else
            f"{best[0]}³ = {best[1]:,} ({best[1] / (1 << 28):.2f} × 2^28)",
        ))
    for alg in ("gossip", "push-sum"):
        hi = None
        for p in range(27, 33):
            n = 1 << p
            plan = plan_pool2_sharded(build_full(n, False), cfg(n, alg),
                                      n_dev)
            if not isinstance(plan, str):
                hi = p
        rows.append((
            "replicated-pool2", "full", alg,
            "none admitted in the swept range" if hi is None else
            f"2^{hi} = {1 << hi:,}",
        ))
    lines = [
        f"## Topology ceilings (plan-level, {n_dev} devices — "
        "benchmarks/trend.py --ceilings)",
        "",
        "| composition | topology | algorithm "
        "| aggregate plan ceiling | ms/round on chip |",
        "|---|---|---|---|---|",
    ]
    for comp, topo, alg, ceil in rows:
        lines.append(f"| {comp} | {topo} | {alg} | {ceil} | pending |")
    lines.append("")
    return "\n".join(lines)


def render_matmul_tier() -> str:
    """The ISSUE 12 delivery-tier row, measured on THIS box's CPU: the
    chunked matmul tier vs the chunked pool tier at full n=1024 (fixed
    identical rounds via an unreachable rumor threshold — the
    microbench/chunk_sync methodology, so both cells execute the same
    chunks x chunk_rounds and the comparison is batching-comparable to
    the trajectory table's fixed-round cells) plus the op-level pool
    aggregation pair, timed through benchmarks/microbench.delivery_forms
    — the ONE home of the deliver_pool-vs-deliver_matmul comparison
    surface, so this section and the Dispatch-floor rows cannot drift in
    methodology. On
    CPU there is no MXU, so the matmul column measures formulation
    overhead only; the on-chip regen fills the real rows (the BENCH
    protocol — same as the topology-ceilings ms/round cells)."""
    sys.path.insert(0, str(REPO))
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)

    from benchmarks.microbench import delivery_forms, time_delivery_form

    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    n, chunks, chunk_rounds = 1024, 16, 8
    topo = build_topology("full", n)
    per_round_us = {}
    for d in ("pool", "matmul"):
        cfg = SimConfig(
            n=n, topology="full", algorithm="gossip", seed=0, delivery=d,
            rumor_threshold=10**6, engine="chunked",
            chunk_rounds=chunk_rounds, max_rounds=chunks * chunk_rounds,
        )
        best = None
        for _ in range(3):
            res = run(topo, cfg)
            assert res.rounds == chunks * chunk_rounds
            best = res.run_s if best is None else min(best, res.run_s)
        per_round_us[d] = best / (chunks * chunk_rounds) * 1e6

    forms = delivery_forms(n, 4)
    agg_us = {
        "pool rolls": time_delivery_form(forms["pool_rolls"], 40),
        "one-hot dot_general": time_delivery_form(
            forms["onehot_dot_general"], 40
        ),
    }

    return "\n".join([
        MATMUL_HEADER,
        "",
        "MXU delivery tier (ISSUE 12) vs the pool tier it is "
        "stream-identical to, measured on this box's CPU (fixed "
        f"{chunks} x {chunk_rounds} rounds, min-of-3 — the fixed-round "
        "methodology of the trajectory cells above, so the columns are "
        "batching-comparable). Gossip trajectories are bitwise-identical "
        "across the two tiers (tests/test_delivery_matmul.py); on CPU "
        "the one-hot contraction has no MXU to land on, so its column is "
        "formulation overhead — the on-chip regen (MXU) is pending.",
        "",
        "| cell | chunked pool | chunked matmul | on-chip (MXU) |",
        "|---|---|---|---|",
        "| full n=1,024 gossip, µs/round | "
        f"{per_round_us['pool']:,.0f} | {per_round_us['matmul']:,.0f} "
        "| pending |",
        "| pool aggregation op (n=1,024, K=4), µs | "
        f"{agg_us['pool rolls']:,.0f} | "
        f"{agg_us['one-hot dot_general']:,.0f} | pending |",
        "",
    ])


def apply_to_bench_tables(table_md: str, bench_tables: Path,
                          header: str = SECTION_HEADER) -> None:
    """Idempotently install/replace one generated section: everything
    from ``header`` to the next '## ' heading (or EOF) is replaced."""
    text = bench_tables.read_text()
    if header in text:
        start = text.index(header)
        rest = text[start + len(header):]
        nxt = rest.find("\n## ")
        end = len(text) if nxt < 0 else start + len(header) + nxt + 1
        text = text[:start] + table_md + text[end:]
    else:
        if not text.endswith("\n"):
            text += "\n"
        text += "\n" + table_md
    bench_tables.write_text(text)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", type=Path, default=REPO,
                    help="directory holding the BENCH_r*/MULTICHIP_r* "
                    "snapshots (default: the repo root)")
    ap.add_argument("--serving", action="append", default=[],
                    metavar="REV:RPS",
                    help="pin a serving req/s figure for a revision "
                    "(repeatable), e.g. --serving 6:1778")
    ap.add_argument("--loadgen", type=Path, default=None,
                    help="read the serving req/s for --rev from a "
                    "benchmarks/loadgen.py --json record")
    ap.add_argument("--rev", type=int, default=None,
                    help="revision number the --loadgen record belongs to")
    ap.add_argument("--md", type=Path, default=None,
                    help="write the markdown table here")
    ap.add_argument("--apply", action="store_true",
                    help="install/replace the 'Perf trajectory' section "
                    "in BENCH_TABLES.md (idempotent)")
    ap.add_argument("--ceilings", action="store_true",
                    help="append the plan-level topology-ceilings table "
                    "(ISSUE 10), recomputed from the pure plan functions")
    ap.add_argument("--matmul-tier", action="store_true",
                    help="measure and append the MXU-matmul delivery-tier "
                    "row (ISSUE 12): chunked matmul vs pool at full "
                    "n=1024 plus the pool-aggregation op pair, on this "
                    "box's CPU (on-chip regen pending); with --apply the "
                    "section installs into BENCH_TABLES.md idempotently")
    args = ap.parse_args(argv)

    revs = load_snapshots(args.root)
    if not revs:
        print(f"no BENCH_r*/MULTICHIP_r* snapshots under {args.root}",
              file=sys.stderr)
        return 1

    # Pins already in the committed table survive a bare re-apply;
    # command-line pins override them.
    serving: dict = parse_existing_serving(args.root / "BENCH_TABLES.md")
    for pin in args.serving:
        try:
            rev_s, rps_s = pin.split(":", 1)
            serving[int(rev_s)] = float(rps_s)
        except ValueError:
            print(f"bad --serving pin {pin!r} (want REV:RPS)",
                  file=sys.stderr)
            return 2
    if args.loadgen is not None:
        if args.rev is None:
            print("--loadgen needs --rev (the revision the record "
                  "belongs to)", file=sys.stderr)
            return 2
        rec = json.loads(args.loadgen.read_text())
        rps = (rec.get("batched") or {}).get("rps")
        if rps is None:
            print(f"{args.loadgen} has no batched.rps field",
                  file=sys.stderr)
            return 2
        serving[args.rev] = float(rps)

    table = render(revs, serving)
    matmul_md = render_matmul_tier() if args.matmul_tier else None
    # The ceilings section rides the printed/--md output only: --apply
    # replaces BENCH_TABLES.md's trajectory section up to the next "## "
    # heading, so appending another "## " section to its input would
    # break the replace's idempotency (BENCH_TABLES keeps its own
    # hand-annotated ceilings section). The matmul-tier section has its
    # OWN header and its own idempotent apply, so it composes.
    out = table
    if args.ceilings:
        out = out + "\n" + render_ceilings()
    if matmul_md is not None:
        out = out + "\n" + matmul_md
    print(out)
    if args.md:
        args.md.write_text(out + "\n")
    if args.apply:
        apply_to_bench_tables(table, args.root / "BENCH_TABLES.md")
        if matmul_md is not None:
            apply_to_bench_tables(
                matmul_md, args.root / "BENCH_TABLES.md",
                header=MATMUL_HEADER,
            )
        print(f"[trend] applied to {args.root / 'BENCH_TABLES.md'}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
