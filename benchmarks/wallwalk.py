"""Wall-clock attribution report — name every millisecond (ISSUE 7).

VERDICT r5 #6 measured the flagship's ~88 ms non-engine wall and could
only call it "profiler-attributable": the chunkloop annotations put it in
a Perfetto trace, but no report DECOMPOSED it — and the COST-of-graph-
processing-using-actors paper (PAPERS.md) is exactly the cautionary tale
of frameworks whose overhead was never decomposed against a baseline.
This walker runs one configuration end to end, brackets every host phase
with ``perf_counter``, pulls the run-loop budget the pipelined driver now
measures (models/pipeline.py: dispatch / fetch / first-dispatch / hook /
aux splits, run-record schema v4), and prints the full wall as named
buckets:

    init         JAX import + backend touch (process-start cost)
    build        topology construction
    compile      trace + XLA compile (the engine's measured warmup)
    setup        run()'s engine setup — round-fn/plane/state builds +
                 device transfers (RunResult.setup_s, bracketed)
    dispatch     host time enqueueing chunks (the launch floor, summed)
    engine       host time blocked on the predicate readback minus aux
                 collection — the device-execution wait
    aux          telemetry aux-buffer collection (subset of the fetch
                 block, split out)
    hook         chunk-boundary callbacks: checkpoint IO + watchdog syncs
    finalize     result assembly after the loop (RunResult.finalize_s)
    record       run-record serialization
    loop*        run-loop remainder (pure Python bookkeeping) =
                 run_s − dispatch − fetch − hook
    harness*     run() wall not covered by any bracket above =
                 run_wall − compile − run_s − setup − finalize
    (unattributed = total − everything above)

The CLOSURE check is over DIRECTLY MEASURED buckets only: the starred
rows are subtraction-defined remainders, so they — plus any unattributed
gap — count AGAINST closure. An unbracketed cost sneaking into run()
lands in ``harness*`` and visibly drops the number (the sharded engines,
which do not bracket setup/finalize, show exactly that). Named buckets
must cover >= 90% of the non-engine wall (total − engine);
``--assert-closure`` makes it an exit code — the tier-1 pin
(tests/test_obs.py) and the bench-smoke CI step both drive it. ``--flagship`` selects the BENCH flagship config
(1M-node full-topology push-sum, pool delivery, fused engine — TPU); the
default is a CPU-sized stand-in exercising several chunk boundaries.

Usage::

    python benchmarks/wallwalk.py [--platform cpu] [--md out.md]
        [--json out.json] [--assert-closure 0.9] [--telemetry]
        [--checkpoint] [--flagship]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def walk(cfg_kw: dict, telemetry: bool = False,
         checkpoint: bool = False) -> dict:
    """Run one configuration with every host phase bracketed; returns the
    bucket dict (seconds) + closure metrics."""
    t_start = time.perf_counter()

    import jax  # noqa: F401 — the import IS the measured phase

    jax.devices()  # force backend init into the init bucket
    t_init = time.perf_counter()

    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models.runner import run
    from cop5615_gossip_protocol_tpu.utils import metrics

    cfg = SimConfig(telemetry=telemetry, **cfg_kw)
    topo = build_topology(cfg.topology, cfg.n, seed=cfg.seed,
                          semantics=cfg.semantics)
    t_build = time.perf_counter()

    on_chunk = None
    ckpt_path = None
    if checkpoint:
        # Exercise the hook/IO bucket: a real checkpoint write per chunk
        # boundary (the only legal use of the on_chunk hook).
        import tempfile

        from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt

        ckpt_path = tempfile.mktemp(suffix=".npz")

        def on_chunk(rounds, state):
            ckpt.save(ckpt_path, state, rounds, cfg)

    result = run(topo, cfg, on_chunk=on_chunk)
    t_run = time.perf_counter()

    record = metrics.run_record(cfg, topo, result)
    json.dumps(record)  # the serialization cost a --jsonl run pays
    t_record = time.perf_counter()
    if ckpt_path is not None:
        Path(ckpt_path).unlink(missing_ok=True)

    total = t_record - t_start
    engine_wait = result.fetch_s - result.aux_s
    run_wall = t_run - t_build
    # Directly bracketed buckets — each one is a perf_counter interval
    # around real code, never a difference of other buckets.
    buckets = {
        "init": t_init - t_start,
        "build": t_build - t_init,
        "compile": result.compile_s,
        "setup": result.setup_s,
        "dispatch": result.dispatch_s,
        "engine": engine_wait,
        "aux": result.aux_s,
        "hook": result.hook_s,
        "finalize": result.finalize_s,
        "record": t_record - t_run,
    }
    # Subtraction-defined remainders: run-loop bookkeeping, and run() wall
    # no bracket covers. These count AGAINST closure — they are where an
    # unmeasured cost would hide.
    derived = {
        "loop*": result.run_s - result.dispatch_s - result.fetch_s
                 - result.hook_s,
        "harness*": run_wall - result.compile_s - result.run_s
                    - result.setup_s - result.finalize_s,
    }
    unattributed = total - sum(buckets.values()) - sum(derived.values())
    non_engine = total - buckets["engine"]
    unnamed = (max(derived["loop*"], 0.0) + max(derived["harness*"], 0.0)
               + max(unattributed, 0.0))
    closure = (non_engine - unnamed) / non_engine if non_engine > 0 else 1.0
    buckets = {**buckets, **derived}
    return {
        "config": {k: cfg_kw[k] for k in sorted(cfg_kw)},
        "rounds": result.rounds,
        "outcome": result.outcome,
        "total_s": total,
        "engine_s": buckets["engine"],
        "non_engine_s": non_engine,
        "unattributed_s": unattributed,
        "closure": closure,
        "first_dispatch_s": result.first_dispatch_s,
        "chunks": len(result.chunk_log or ()),
        "buckets": buckets,
    }


def render_md(rep: dict) -> str:
    lines = [
        "## Wall-clock attribution (benchmarks/wallwalk.py)",
        "",
        f"config: `{rep['config']}` — {rep['rounds']} rounds "
        f"({rep['outcome']}), {rep['chunks']} chunks, total wall "
        f"{1e3 * rep['total_s']:.1f} ms",
        "",
        "| bucket | ms | % of total | % of non-engine |",
        "|---|---|---|---|",
    ]
    total = rep["total_s"]
    non_engine = rep["non_engine_s"]
    for name, s in rep["buckets"].items():
        ne = "—" if name == "engine" else f"{100 * s / non_engine:.1f}"
        lines.append(
            f"| {name} | {1e3 * s:.3f} | {100 * s / total:.1f} | {ne} |"
        )
    lines.append(
        f"| *unattributed* | {1e3 * rep['unattributed_s']:.3f} "
        f"| {100 * rep['unattributed_s'] / total:.1f} "
        f"| {100 * max(rep['unattributed_s'], 0) / non_engine:.1f} |"
    )
    lines += [
        "",
        f"first-dispatch (residual trace/first-execution cost): "
        f"{1e3 * rep['first_dispatch_s']:.3f} ms of the dispatch bucket",
        f"**closure: {100 * rep['closure']:.1f}%** of the non-engine wall "
        "is named (bar: >= 90%)",
        "",
    ]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--platform", choices=["auto", "cpu", "tpu"],
                    default="cpu")
    ap.add_argument("--n", type=int, default=1024)
    ap.add_argument("--topology", default="full")
    ap.add_argument("--algorithm", default="gossip")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chunk-rounds", type=int, default=8,
                    help="small default so the walk crosses several chunk "
                    "boundaries and the dispatch/fetch buckets are real")
    ap.add_argument("--max-rounds", type=int, default=100_000)
    ap.add_argument("--telemetry", action="store_true",
                    help="exercise the aux-collection bucket")
    ap.add_argument("--checkpoint", action="store_true",
                    help="exercise the hook/IO bucket (a checkpoint write "
                    "per chunk boundary)")
    ap.add_argument("--flagship", action="store_true",
                    help="the BENCH flagship config (1M full push-sum, "
                    "pool delivery, fused engine — requires TPU)")
    ap.add_argument("--assert-closure", type=float, default=None,
                    metavar="FRAC",
                    help="exit 1 unless the named non-engine buckets "
                    "cover at least FRAC of the non-engine wall")
    ap.add_argument("--md", type=str, default=None)
    ap.add_argument("--json", type=str, default=None)
    args = ap.parse_args(argv)

    import os

    if args.platform != "auto":
        os.environ.setdefault("JAX_PLATFORMS", args.platform)

    if args.flagship:
        cfg_kw = dict(
            n=1_000_000, topology="full", algorithm="push-sum",
            seed=args.seed, delivery="pool", engine="fused",
            chunk_rounds=256, max_rounds=100_000,
        )
    else:
        cfg_kw = dict(
            n=args.n, topology=args.topology, algorithm=args.algorithm,
            seed=args.seed, chunk_rounds=args.chunk_rounds,
            max_rounds=args.max_rounds,
        )

    rep = walk(cfg_kw, telemetry=args.telemetry,
               checkpoint=args.checkpoint)
    md = render_md(rep)
    print(md)
    if args.md:
        Path(args.md).write_text(md + "\n")
    if args.json:
        Path(args.json).write_text(json.dumps(rep, indent=2))
    if args.assert_closure is not None and rep["closure"] < args.assert_closure:
        print(
            f"FAIL: closure {rep['closure']:.3f} under the "
            f"{args.assert_closure} bar — "
            f"{1e3 * rep['unattributed_s']:.3f} ms unattributed",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
