"""Dispatch-floor metrology — measure, don't assert, the per-run overheads.

The BENCH tables' small-N reading note and the r4-#5 roofline discussion
both LEAN on overhead numbers ("~110-140 ms per-dispatch tunnel floor",
"~8-12 ns/element dynamic-address floor") that were asserted from ad-hoc
observations. This tool measures each one directly, on whatever backend it
runs on, and emits them as JSON plus the BENCH_TABLES.md "dispatch floor"
markdown section:

- **dispatch floor** — wall cost of one trivial jitted dispatch + blocking
  readback (median / p90 over reps): the price every chunk boundary paid
  before speculative pipelining, and the floor every small-N run still
  pays once.
- **per-chunk sync cost** — the REAL chunked engine driven over many
  chunks, serial (pipeline_chunks=1) vs pipelined: the per-chunk delta is
  the boundary cost the pipeline hides; the serial per-chunk wall
  calibrates pipeline depth (depth ~ floor/chunk_compute + 1).
- **buffer donation** — a steady-state carry update with and without
  ``donate_argnums``: the per-dispatch copy cost donation deletes.
- **dynamic-address floor** (r4-#5) — per-element cost of scatter-add /
  gather vs a circular roll, size-differenced so the dispatch floor
  cancels: the measured gap between random-access and streaming delivery.
- **delivery floor** (ISSUE 12) — the r4 dynamic-address floor extended to
  the MXU tier: per-DELIVERED-element cost of the three delivery
  formulations over identical sampled targets — scatter-add, the pool
  masked-roll form, and the blocked one-hot `dot_general`
  (ops/delivery.deliver_matmul). The matmul form does O(n/128) MACs per
  delivered element (the one-hot is dense per 128-column block), so its
  per-element cost scales with n and is reported AT each size rather than
  size-differenced; on CPU there is no MXU, so these numbers are the
  formulation overheads only — the on-chip re-measure is pending.
- **compile cache** — compile time of a fresh probe program with the
  persistent cache enabled; on a second process run the same probe is a
  cache hit, so the reported number collapses (the suite-level effect is
  recorded in CHANGES.md).

Usage:
  python benchmarks/microbench.py [--json OUT] [--md] [--quick]
                                  [--n N] [--platform auto|cpu]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _timed(fn, reps: int) -> dict:
    """Median/p90/min of ``fn()`` wall times in microseconds (fn must block
    until its result is ready)."""
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        samples.append((time.perf_counter() - t0) * 1e6)
    samples.sort()
    return {
        "median_us": statistics.median(samples),
        "p90_us": samples[int(0.9 * (len(samples) - 1))],
        "min_us": samples[0],
        "reps": reps,
    }


def dispatch_floor(reps: int) -> dict:
    """One trivial jitted dispatch + blocking readback."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((128,), jnp.float32)
    f(x).block_until_ready()  # compile outside the timed region
    return _timed(lambda: f(x).block_until_ready(), reps)


def chunk_sync_cost(
    n: int, chunks: int, chunk_rounds: int, depths, trials: int = 3
) -> dict:
    """Drive the real chunked engine over ``chunks`` dispatches at each
    pipeline depth. Convergence is unreachable (the engine_us_stats trick),
    so every variant executes the identical chunks x chunk_rounds rounds —
    wall differences are pure boundary/pipeline behavior. Min of ``trials``
    per depth: boundary costs are floors, so the minimum is the estimator
    robust to host scheduling noise."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    topo = build_topology("full", n)
    out = {"n": n, "chunks": chunks, "chunk_rounds": chunk_rounds}
    walls = {}
    for depth in depths:
        cfg = SimConfig(
            n=n, topology="full", algorithm="gossip", seed=0,
            rumor_threshold=10**6, engine="chunked",
            chunk_rounds=chunk_rounds, max_rounds=chunks * chunk_rounds,
            pipeline_chunks=depth,
        )
        best = None
        for _ in range(trials):
            res = run(topo, cfg)
            assert res.rounds == chunks * chunk_rounds, (res.rounds,)
            best = res.run_s if best is None else min(best, res.run_s)
        walls[depth] = best
        out[f"wall_s_depth{depth}"] = best
        out[f"per_chunk_us_depth{depth}"] = best / chunks * 1e6
    d0 = min(depths)
    for depth in depths:
        if depth != d0:
            out[f"boundary_us_hidden_depth{depth}"] = (
                (walls[d0] - walls[depth]) / chunks * 1e6
            )
    return out


def telemetry_overhead(
    n: int, chunks: int, chunk_rounds: int, trials: int = 3
) -> dict:
    """The in-program telemetry plane's cost on the REAL chunked engine:
    the same unreachable-convergence loop as chunk_sync_cost run with
    cfg.telemetry off vs on (per-round counter rows accumulated on device,
    fetched asynchronously — donation and pipelining stay on in both).
    The acceptance bar is <5% overhead; min-of-trials, like chunk_sync."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    topo = build_topology("full", n)
    out = {"n": n, "chunks": chunks, "chunk_rounds": chunk_rounds}
    cfgs = {
        tele: SimConfig(
            n=n, topology="full", algorithm="gossip", seed=0,
            rumor_threshold=10**6, engine="chunked",
            chunk_rounds=chunk_rounds, max_rounds=chunks * chunk_rounds,
            telemetry=tele,
        )
        for tele in (False, True)
    }
    walls = {False: None, True: None}
    # Interleaved off/on trials (not two sequential blocks): host load on a
    # shared CPU drifts on the seconds scale, and min-of-interleaved pairs
    # cancels it where sequential blocks alias it into the differential.
    for trial in range(trials + 1):
        for tele in (False, True):
            res = run(topo, cfgs[tele])
            assert res.rounds == chunks * chunk_rounds, (res.rounds,)
            if tele:
                assert res.telemetry is not None
                assert res.telemetry.rounds == res.rounds
            if trial == 0:
                continue  # warmup pair: first-touch costs land here
            best = walls[tele]
            walls[tele] = res.run_s if best is None else min(best, res.run_s)
    out["wall_s_off"] = walls[False]
    out["wall_s_on"] = walls[True]
    out["overhead_pct"] = (walls[True] / walls[False] - 1.0) * 100.0
    return out


def donation_cost(n: int, reps: int) -> dict:
    """Steady-state carry update with vs without buffer donation: the
    per-dispatch copy cost `donate_argnums` deletes."""
    import jax
    import jax.numpy as jnp

    def step(state):
        return tuple(x + 1 for x in state)

    plain = jax.jit(step)
    donating = jax.jit(step, donate_argnums=(0,))
    state = tuple(jnp.zeros((n,), jnp.float32) for _ in range(4))
    plain(state)[0].block_until_ready()
    t_plain = _timed(lambda: plain(state)[0].block_until_ready(), reps)

    carry = {"s": donating(tuple(jnp.copy(x) for x in state))}
    carry["s"][0].block_until_ready()

    def donated_step():
        carry["s"] = donating(carry["s"])
        carry["s"][0].block_until_ready()

    t_donate = _timed(donated_step, reps)
    return {
        "n": n,
        "plain_us": t_plain["median_us"],
        "donated_us": t_donate["median_us"],
        "copy_saved_us": t_plain["median_us"] - t_donate["median_us"],
    }


def addressing_floor(n1: int, n2: int, reps: int) -> dict:
    """Per-element cost of random-access vs streaming delivery, differenced
    over two sizes so the dispatch floor cancels exactly (the
    engine_us_per_round methodology, benchmarks/compare.py). This is the
    r4-#5 'dynamic-address/issue floor', finally measured."""
    import jax
    import jax.numpy as jnp

    out = {"n1": n1, "n2": n2}

    def per_elem(make):
        t = {}
        for n in (n1, n2):
            key = jax.random.PRNGKey(0)
            targets = jax.random.randint(key, (n,), 0, n, dtype=jnp.int32)
            vals = jnp.ones((n,), jnp.float32)
            f = jax.jit(make(n))
            f(vals, targets).block_until_ready()
            t[n] = _timed(
                lambda f=f, v=vals, tg=targets: f(v, tg).block_until_ready(),
                reps,
            )["median_us"]
        return (t[n2] - t[n1]) / (n2 - n1) * 1e3  # ns/element

    out["scatter_add_ns_per_elem"] = per_elem(
        lambda n: lambda v, t: jnp.zeros((n,), v.dtype).at[t].add(v)
    )
    out["gather_ns_per_elem"] = per_elem(lambda n: lambda v, t: v[t])
    out["roll_ns_per_elem"] = per_elem(
        lambda n: lambda v, t: jnp.roll(v, 1) + jnp.roll(v, -1)
    )
    return out


def delivery_forms(n: int, pool_size: int) -> dict:
    """The three delivery formulations over IDENTICAL pool-sampled targets
    (the matmul tier's stream): {name: (jitted fn, args)}. The ONE home
    for the op-level comparison surface — `delivery_floor` below and
    benchmarks/trend.py's matmul-tier section both time these forms, so
    the two tables cannot drift in what they measure."""
    import jax
    import jax.numpy as jnp

    from cop5615_gossip_protocol_tpu.ops import delivery, sampling

    kr = sampling.round_key(jax.random.PRNGKey(0), 3)
    offs = sampling.pool_offsets(kr, pool_size, n)
    choice = sampling.pool_choice_packed(kr, n, pool_size)
    ids = jnp.arange(n, dtype=jnp.int32)
    targets = sampling.targets_pool(choice, offs, ids, n)
    vals = jnp.ones((n,), jnp.float32)
    return {
        "scatter_add": (
            jax.jit(lambda v, t: delivery.deliver(v, t, n)),
            (vals, targets),
        ),
        "pool_rolls": (
            jax.jit(lambda v, c, o: delivery.deliver_pool(v[None], c, o)[0]),
            (vals, choice, offs),
        ),
        "onehot_dot_general": (
            jax.jit(lambda v, t: delivery.deliver_matmul(v, t, n)),
            (vals, targets),
        ),
    }


def time_delivery_form(form, reps: int) -> float:
    """Median µs of one (jitted fn, args) pair from `delivery_forms`
    (compile excluded)."""
    f, a = form
    f(*a).block_until_ready()
    return _timed(lambda: f(*a).block_until_ready(), reps)["median_us"]


def delivery_floor(n1: int, n2: int, pool_size: int, reps: int) -> dict:
    """Per-delivered-element cost of scatter-add vs pool masked rolls vs
    the blocked one-hot dot_general, over IDENTICAL pool-sampled targets
    (the matmul tier's stream). Scatter/roll report both the per-size
    medians and the size-differenced floor (dispatch cancels); the matmul
    form is O(n/128) MACs per element, so differencing would mix sizes of
    different work — it reports per-element at each size with the scaling
    documented. CPU numbers are formulation overheads (no MXU);
    BENCH_TABLES notes the on-chip re-measure as pending."""
    out = {"n1": n1, "n2": n2, "pool_size": pool_size}
    per_size: dict = {}
    for n in (n1, n2):
        per_size[n] = {}
        for name, form in delivery_forms(n, pool_size).items():
            us = time_delivery_form(form, reps)
            per_size[n][name] = us
            out[f"{name}_ns_per_elem_n{n}"] = us / n * 1e3
    for name in ("scatter_add", "pool_rolls"):
        out[f"{name}_ns_per_elem_diff"] = (
            (per_size[n2][name] - per_size[n1][name]) / (n2 - n1) * 1e3
        )
    return out


def compile_cache_probe(n: int, cache_dir: str) -> dict:
    """Compile a probe chunk with the persistent cache enabled (the caller
    enabled it BEFORE the process's first compile — the cache initializes
    lazily at first use and ignores a directory set afterwards). First
    process run: a real compile (populates the cache). Re-run the script:
    the same probe is a disk hit and this number collapses."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def probe(state, end):
        def body(c):
            s, i = c
            return (s * 1.000001 + jnp.float32(1.0), i + 1)

        return lax.while_loop(lambda c: c[1] < end, body, (state, 0))

    x = jnp.zeros((n,), jnp.float32)
    t0 = time.perf_counter()
    jax.jit(probe)(x, 8)[0].block_until_ready()
    compile_s = time.perf_counter() - t0
    entries = len(list(Path(cache_dir).iterdir()))
    return {
        "cache_dir": cache_dir,
        "probe_compile_s": compile_s,
        "cache_entries": entries,
    }


def collect(quick: bool = False, n: int | None = None) -> dict:
    import jax

    from cop5615_gossip_protocol_tpu.utils.compat import (
        enable_compilation_cache,
    )

    # BEFORE any compile: the persistent cache initializes lazily at the
    # process's first compilation and ignores a directory set afterwards.
    cache_dir = enable_compilation_cache()

    reps = 10 if quick else 40
    n_chunk = n or (4096 if quick else 65_536)
    chunks = 16 if quick else 64
    stats = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "dispatch_floor": dispatch_floor(reps),
        "chunk_sync": chunk_sync_cost(
            n_chunk, chunks, 8, depths=(1, 2, 4),
            trials=2 if quick else 3,
        ),
        "telemetry": telemetry_overhead(
            n_chunk, chunks, 8, trials=2 if quick else 3
        ),
        "donation": donation_cost(n or (1 << 16 if quick else 1 << 20), reps),
        "addressing": addressing_floor(
            1 << 14 if quick else 1 << 18,
            1 << 16 if quick else 1 << 20,
            reps,
        ),
        "delivery_floor": delivery_floor(
            1 << 10 if quick else 1 << 12,
            1 << 12 if quick else 1 << 14,
            4, reps,
        ),
        "compile_cache": compile_cache_probe(n_chunk, cache_dir),
    }
    floor_us = stats["dispatch_floor"]["median_us"]
    serial_chunk_us = stats["chunk_sync"]["per_chunk_us_depth1"]
    # Depth that covers the floor with in-flight compute, plus one being
    # executed: floor / COMPUTE-only chunk cost (the serial per-chunk wall
    # includes the floor itself — dividing by it would cap the ratio below
    # 1 and the formula would return a constant 2 on every backend).
    compute_us = max(serial_chunk_us - floor_us, 1.0)
    stats["recommended_pipeline_depth"] = max(
        2, min(8, round(floor_us / compute_us) + 1)
    )
    return stats


def section(stats: dict) -> list[str]:
    """BENCH_TABLES.md 'dispatch floor' section from collect() output."""
    ds = stats["dispatch_floor"]
    cs = stats["chunk_sync"]
    dn = stats["donation"]
    ad = stats["addressing"]
    cc = stats["compile_cache"]
    te = stats["telemetry"]
    dl = stats["delivery_floor"]
    hidden = cs.get("boundary_us_hidden_depth4")
    return [
        "## Dispatch floor (benchmarks/microbench.py)",
        "",
        f"Measured on `{stats['device']}` (backend: {stats['backend']}). "
        "These are the overheads the small-N reading note above names; the "
        "per-run floor itemized instead of folded into 'gossip-tpu (ms)'.",
        "",
        "| overhead | measured | note |",
        "|---|---|---|",
        f"| dispatch floor | {ds['median_us']:,.0f} µs (p90 "
        f"{ds['p90_us']:,.0f}) | one trivial jitted dispatch + blocking "
        "readback |",
        f"| per-chunk boundary, serial | {cs['per_chunk_us_depth1']:,.0f} "
        f"µs | real chunked engine, {cs['chunks']} chunks x "
        f"{cs['chunk_rounds']} rounds at n={cs['n']:,} |",
        f"| per-chunk boundary, pipelined x4 | "
        f"{cs['per_chunk_us_depth4']:,.0f} µs | same chunks with "
        "pipeline_chunks=4 (speculative dispatch) |",
        f"| boundary cost hidden by pipelining | "
        f"{0 if hidden is None else hidden:,.0f} µs/chunk | serial minus "
        "pipelined, per chunk |",
        f"| donation copy savings | {dn['copy_saved_us']:,.1f} µs/dispatch "
        f"| 4-plane carry at n={dn['n']:,} with donate_argnums |",
        f"| telemetry overhead | {te['overhead_pct']:+.1f}% | per-round "
        "on-device counter rows (cfg.telemetry) on the same chunk loop, "
        "donation + pipelining kept; acceptance bar <5% |",
        f"| scatter-add | {ad['scatter_add_ns_per_elem']:.2f} ns/elem | "
        "size-differenced (dispatch floor cancelled) — the r4-#5 "
        "dynamic-address floor, measured |",
        f"| gather | {ad['gather_ns_per_elem']:.2f} ns/elem | ditto |",
        f"| circular roll (stencil class) | "
        f"{ad['roll_ns_per_elem']:.2f} ns/elem | streaming delivery for "
        "comparison |",
        f"| delivery floor: scatter-add | "
        f"{dl['scatter_add_ns_per_elem_diff']:.2f} ns/elem | "
        f"same pool-sampled targets, sizes {dl['n1']:,}/{dl['n2']:,}, "
        "size-differenced (ISSUE 12) |",
        f"| delivery floor: pool masked rolls | "
        f"{dl['pool_rolls_ns_per_elem_diff']:.2f} ns/elem | "
        f"K={dl['pool_size']} rolls over the same targets, "
        "size-differenced |",
        (
            "| delivery floor: blocked one-hot dot_general | "
            "{:.2f} / {:.2f} ns/elem at n={:,}/{:,} | matmul tier "
            "(deliver_matmul): O(n/128) MACs per delivered element, so "
            "per-element cost scales with n — CPU formulation overhead "
            "only; on-chip (MXU) re-measure pending |"
        ).format(
            dl["onehot_dot_general_ns_per_elem_n%d" % dl["n1"]],
            dl["onehot_dot_general_ns_per_elem_n%d" % dl["n2"]],
            dl["n1"], dl["n2"],
        ),
        f"| probe compile (persistent cache) | {cc['probe_compile_s']:.2f} "
        f"s | cache at `{cc['cache_dir']}` ({cc['cache_entries']} "
        "entries); re-runs hit disk instead of recompiling |",
        "",
        f"Recommended pipeline depth at these costs: "
        f"{stats['recommended_pipeline_depth']} "
        "(floor/chunk-compute + 1; SimConfig.pipeline_chunks).",
        "",
    ]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", type=str, default=None,
                    help="write the stats dict to this path")
    ap.add_argument("--md", action="store_true",
                    help="print the BENCH_TABLES.md section to stdout")
    ap.add_argument("--quick", action="store_true",
                    help="small sizes / few reps (CI smoke)")
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--platform", choices=["auto", "cpu"], default="auto")
    args = ap.parse_args(argv)

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    stats = collect(quick=args.quick, n=args.n)
    if args.json:
        Path(args.json).write_text(json.dumps(stats, indent=2))
        print(f"[microbench] wrote {args.json}", file=sys.stderr)
    if args.md:
        print("\n".join(section(stats)))
    else:
        print(json.dumps(stats, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
