"""Load harness for the serving plane (ISSUE 6 / 14, ROADMAP item 2).

Spawns ``serve.py`` (or, with ``--fleet N``, the bucket-routed worker
fleet front — serving/fleet.py) as real OS processes, drives it with N
closed-loop clients (each client keeps exactly one request in flight over
a persistent connection — classic closed-loop load, so offered load
adapts to service capacity instead of queueing unboundedly), replays a
mixed small-N request trace spanning several key buckets, and reports
throughput + p50/p99 latency.

ISSUE 14 modes:

- ``--fleet N`` runs every phase against the fleet front (N workers,
  consistent-hash bucket routing, continuous batching on) — the
  configuration of the BENCH_TABLES serving row;
- ``--no-continuous`` passes the wave-at-a-time control flag through to
  the server(s) — the A/B for the continuous-batching win;
- ``--open-loop R1,R2,...`` replaces the closed loop with POISSON
  arrivals at each offered rate and reports latency vs offered load:
  the closed loop adapts its offered rate to capacity, so it can only
  ever show the ceiling it reached — the open loop shows the knee, and
  the saturation rate is MEASURED (highest offered rate whose achieved
  throughput stays within 5%) instead of inferred;
- ``--buckets B`` pressure-tests the warm-engine LRU past its capacity
  (ROADMAP flagged it unexamined beyond ~10 buckets): drives B distinct
  key buckets (distinct full-topology populations), then revisits a
  working set inside capacity, asserting the pool's miss/eviction/hit
  accounting and recording cold-vs-warm latency;
- ``--chaos-fleet`` SIGKILLs the worker that owns a driven bucket
  mid-load under the fleet front and asserts zero lost/duplicated
  terminal responses, the dead worker's buckets re-routing (front
  reroutes/quarantine counters), and exact identities on the drained
  fleet.

``--smoke`` is the CI serve-smoke contract (env-overridable pins):

  1. correctness: every response demultiplexes a valid telemetry
     trajectory (row count == rounds, last row's converged count == the
     result's) across >= 2 distinct buckets;
  2. throughput: sustained closed-loop requests/s >=
     GOSSIP_TPU_SERVE_RPS_FLOOR (default 1000) with p99 latency <=
     GOSSIP_TPU_SERVE_P99_MS (default 250 ms);
  3. batching beats a batching-off control (--no-batching server, same
     trace/clients) by >= GOSSIP_TPU_SERVE_BATCH_RATIO (default 1.3x);
  4. /stats counters add up (admission identities, admission.py) and the
     server shuts down cleanly (SIGINT -> exit 0 with a final stats line).

Default mode runs the same phases with longer windows and no hard pins —
the BENCH_TABLES.md "Serving plane" row generator
(``python benchmarks/loadgen.py --md serving.md --json serving.json``).

``--metrics-smoke`` is the CI metrics-smoke contract (ISSUE 7): the
server runs with ``--events``, a scraper thread GETs ``/metrics`` WHILE
the closed loop is driving (every scrape must parse as Prometheus text),
and after the drive the job asserts (a) the serving series satisfy the
same accounting identities ``/stats`` pins (received == admitted +
rejected + invalid, etc. — checked at quiescence; a mid-validation scrape
may transiently run one ahead), (b) every sampled response's span
breakdown (queue_wait/batch_assemble/engine/demux) sums to within 5% of
its measured service latency, and (c) one sampled response's trace_id
joins request-admitted -> batch-retired -> request-completed in the
server's event log (schema v4) — the request-lifecycle reconstruction the
tracing plane promises.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

# Mixed small-N trace: three distinct key buckets (topology/algorithm
# axes), all fast-converging small configs — the many-users regime the
# serving plane multiplexes. Seeds are assigned per request. The push-sum
# cell uses a load-test-grade delta (1e-3, ~40 rounds) and scatter
# delivery — the tight default delta's ~300-round straggler tail is an
# engine property, not a serving-plane one, and this harness measures the
# serving plane.
MIXED_SMALL_TRACE = (
    {"n": 32, "topology": "full", "algorithm": "gossip",
     "params": {"rumor_threshold": 5}},
    {"n": 36, "topology": "grid2d", "algorithm": "gossip",
     "params": {"rumor_threshold": 3}},
    {"n": 32, "topology": "full", "algorithm": "push-sum",
     "params": {"delta": 3e-3, "term_rounds": 1}},
)

# Mixed-DURATION trace (ISSUE 14, `--trace mixed-duration`): chunk_rounds
# 8 makes the retire grain finer than every request's duration, and the
# buckets span ~13-to-~76 rounds with real within-bucket seed variance
# (ring gossip 55-76, push-sum 24-34, full gossip 13-22 measured) — the
# convoy case the wave-at-a-time scheduler collapses on (finished lanes
# idle until the slowest wave member) and continuous batching exists for.
# The closed loop cannot expose the collapse (it adapts its offered rate);
# drive this trace with --open-loop.
MIXED_DURATION_TRACE = (
    {"n": 32, "topology": "full", "algorithm": "gossip",
     "params": {"rumor_threshold": 5, "chunk_rounds": 16}},
    # max_rounds bounds the stall-prone tail: a suppressed ring rumor can
    # die out on unlucky seeds (the reference's line-topology hang), and
    # an unbounded lane would otherwise sit at max occupancy for its
    # whole max_rounds (the serving lane budget caps residency by TIME;
    # this trace caps it by rounds so stalled requests retire as honest
    # outcome="max_rounds" results inside the measured phase).
    {"n": 64, "topology": "ring", "algorithm": "gossip",
     "params": {"rumor_threshold": 1, "chunk_rounds": 16,
                "max_rounds": 512}},
    {"n": 32, "topology": "full", "algorithm": "push-sum",
     "params": {"delta": 1e-3, "term_rounds": 1, "chunk_rounds": 16}},
)

TRACES = {
    "mixed-small": MIXED_SMALL_TRACE,
    "mixed-duration": MIXED_DURATION_TRACE,
}


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, "") or default)


def pick_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ServerProc:
    """One serve.py OS process: spawn, await readiness, drive, shut down
    cleanly (SIGINT -> exit 0)."""

    def __init__(self, extra_args=(), platform: str = "cpu",
                 window_ms: float = 3.0, max_lanes: int = 64,
                 env_extra: dict | None = None):
        self.port = pick_port()
        cmd = [
            sys.executable, str(REPO / "serve.py"),
            "--port", str(self.port),
            "--platform", platform,
            "--window-ms", str(window_ms),
            "--max-lanes", str(max_lanes),
            *extra_args,
        ]
        env = dict(os.environ)
        env.update(env_extra or {})
        env.setdefault("JAX_PLATFORMS", platform if platform != "auto" else "")
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(REPO), env=env,
        )
        self.host = "127.0.0.1"
        self.jsonl_port = -1
        self._tail: list = []
        self._await_ready()

    def _await_ready(self, timeout_s: float = 120.0) -> None:
        deadline = time.monotonic() + timeout_s
        ready = False
        # serve.py prints "SERVING host port" once the socket is bound.
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    "server exited before readiness: "
                    + "".join(self._tail[-20:])
                )
            self._tail.append(line)
            if line.startswith("SERVING "):
                parts = line.split()
                if len(parts) >= 4:
                    self.jsonl_port = int(parts[3])
                ready = True
                break
        if not ready:
            raise RuntimeError("server never printed SERVING line")
        # Drain stdout in the background so the server never blocks on a
        # full pipe; the final stats line is captured for shutdown checks.
        self._drain = threading.Thread(target=self._drain_stdout, daemon=True)
        self._drain.start()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                conn = http.client.HTTPConnection(self.host, self.port,
                                                  timeout=5)
                conn.request("GET", "/healthz")
                if conn.getresponse().status == 200:
                    conn.close()
                    return
            except OSError:
                time.sleep(0.05)
        raise RuntimeError("server /healthz never came up")

    def _drain_stdout(self) -> None:
        for line in self.proc.stdout:
            self._tail.append(line)
            if len(self._tail) > 200:
                del self._tail[:100]

    def stats(self) -> dict:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        conn.request("GET", "/stats")
        out = json.loads(conn.getresponse().read())
        conn.close()
        return out

    def shutdown(self, sig=signal.SIGINT, timeout_s: float = 60) -> dict:
        """Signal (SIGINT = fast stop, SIGTERM = graceful drain), await
        exit, assert rc == 0, return the final stats record the server
        prints on the way out."""
        self.proc.send_signal(sig)
        rc = self.proc.wait(timeout=timeout_s)
        if self._drain is not None:
            self._drain.join(timeout=10)
        if rc != 0:
            raise RuntimeError(
                f"server exited rc={rc}: " + "".join(self._tail[-20:])
            )
        for line in reversed(self._tail):
            if line.startswith("{"):
                rec = json.loads(line)
                if "server-stats" in rec:
                    return rec["server-stats"]
        raise RuntimeError("server printed no final stats line")

    def drain_shutdown(self, timeout_s: float = 120) -> dict:
        """SIGTERM: graceful drain (lame-duck healthz, structured
        shutting_down admissions, bounded drain window) then exit 0 with
        the final stats line — the ISSUE 8 drain contract."""
        return self.shutdown(sig=signal.SIGTERM, timeout_s=timeout_s)


class FleetProc(ServerProc):
    """The fleet front as one OS process tree (ISSUE 14): N serve.py
    workers behind the consistent-hash router
    (cop5615_gossip_protocol_tpu/serving/fleet.py). Same drive interface
    as ServerProc — host/port/jsonl_port point at the FRONT. The
    worker pid map (the chaos harness's kill targets) is parsed from the
    fleet-workers line printed before readiness."""

    STATS_KEY = "fleet-stats"

    def __init__(self, workers: int = 2, extra_args=(),
                 platform: str = "cpu", window_ms: float = 3.0,
                 max_lanes: int = 64, env_extra: dict | None = None):
        self.workers = workers
        cmd = [
            sys.executable, "-m",
            "cop5615_gossip_protocol_tpu.serving.fleet",
            "--workers", str(workers),
            # Everything unrecognized passes through to each worker.
            "--platform", platform,
            "--window-ms", str(window_ms),
            "--max-lanes", str(max_lanes),
            *extra_args,
        ]
        env = dict(os.environ)
        env.update(env_extra or {})
        env.setdefault("JAX_PLATFORMS", platform if platform != "auto" else "")
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(REPO), env=env,
        )
        self.host = "127.0.0.1"
        self.port = -1
        self.jsonl_port = -1
        self.worker_pids: dict = {}
        self.worker_ports: dict = {}  # wid -> worker HTTP port (direct scrapes)
        self._tail: list = []
        self._await_ready()

    def _await_ready(self, timeout_s: float = 300.0) -> None:
        # Pump stdout from the start and read lines off a queue so the
        # readiness deadline is REAL — a blocking readline on a
        # wedged-silent fleet would hang the harness past any timeout.
        import queue

        lines: queue.Queue = queue.Queue()

        def pump():
            for line in self.proc.stdout:
                self._tail.append(line)
                if len(self._tail) > 200:
                    del self._tail[:100]
                lines.put(line)
            lines.put(None)

        self._drain = threading.Thread(target=pump, daemon=True)
        self._drain.start()
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"fleet never printed FLEET line within {timeout_s:.0f}s"
                )
            try:
                line = lines.get(timeout=min(remaining, 1.0))
            except queue.Empty:
                continue
            if line is None:
                raise RuntimeError(
                    "fleet exited before readiness: "
                    + "".join(self._tail[-20:])
                )
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    rec = {}
                if "fleet-workers" in rec:
                    self.worker_pids = {
                        wid: info["pid"]
                        for wid, info in rec["fleet-workers"].items()
                    }
                    self.worker_ports = {
                        wid: info["port"]
                        for wid, info in rec["fleet-workers"].items()
                    }
            if line.startswith("FLEET "):
                parts = line.split()
                self.port = int(parts[2])
                self.jsonl_port = int(parts[3])
                return

    def shutdown(self, sig=signal.SIGTERM, timeout_s: float = 180) -> dict:
        self.proc.send_signal(sig)
        rc = self.proc.wait(timeout=timeout_s)
        if self._drain is not None:
            self._drain.join(timeout=10)
        if rc != 0:
            raise RuntimeError(
                f"fleet exited rc={rc}: " + "".join(self._tail[-20:])
            )
        for line in reversed(self._tail):
            if line.startswith("{"):
                rec = json.loads(line)
                if self.STATS_KEY in rec:
                    return rec[self.STATS_KEY]
        raise RuntimeError("fleet printed no final stats line")


def check_fleet_stats(final: dict, live_identities: bool = True) -> None:
    """The fleet accounting contract (ISSUE 14): the front answered every
    request it received (exactly one terminal response each), and every
    LIVE worker's drained /stats satisfies the full serving identities. A
    SIGKILLed worker's counters die with it — its requests either
    resolved before the kill or were rerouted and are accounted by the
    worker that actually answered, so the front identity is the
    fleet-wide exactly-once pin."""
    front = final["front"]
    assert front["received"] == front["responded"], front
    assert front["in_flight"] == 0, front
    if live_identities:
        for wid, snap in final["workers"].items():
            if not isinstance(snap, dict) or "received" not in snap:
                continue  # killed worker: no drained stats
            check_stats(snap, min_buckets=0)


_MAX_RETRIES = 6


class ClosedLoopClient(threading.Thread):
    """One closed-loop client: request -> wait -> next, over a persistent
    connection. ``transport`` picks the wire: "jsonl" (the socket
    transport — the throughput phases) or "http" (POST /run keep-alive —
    the correctness phase exercises the HTTP front too). Latencies are
    per-request wall seconds.

    Honest retry behavior (ISSUE 8 satellite): a 429 is retried with
    jittered exponential backoff honoring the server's ``Retry-After`` /
    ``retry_after_s`` hint, and retries are counted SEPARATELY
    (``self.retries``) from fresh sends — throughput comparisons stay
    apples-to-apples (a retried request is one request, not two).

    ``chaos`` mode sends mixed-priority, mixed-deadline traffic and
    treats every structured verdict (200 / 429 / shed / deadline /
    shutting_down / timeout) as a TERMINAL response tallied in
    ``self.terminal`` — only transport failures and unstructured bodies
    count as errors. ``self.sent``/``self.answered`` pin the
    exactly-one-terminal-response guarantee."""

    CHAOS_PRIORITIES = ("interactive", "batch", "best_effort")
    # ms; None = no deadline. The 60 ms cell is tight enough to shed
    # under backlog while a warm uncontended run still beats it.
    CHAOS_DEADLINES = (None, 10_000, 60)

    def __init__(self, host, port, trace, seed0: int, deadline: float,
                 max_requests: int | None = None, telemetry: bool = False,
                 transport: str = "jsonl", users: int = 1,
                 chaos: bool = False):
        super().__init__(daemon=True)
        self.host, self.port = host, port
        self.trace = trace
        self.seed0 = seed0
        self.deadline = deadline
        self.max_requests = max_requests
        self.telemetry = telemetry
        self.transport = transport
        # >1 multiplexes this many closed-loop USERS over one connection
        # via the {"requests": [...]} envelope (jsonl transport only): one
        # socket/JSON round trip per wave carries every user's next
        # request — the client shape that keeps transport overhead off the
        # serving plane's ledger.
        self.users = users
        self.chaos = chaos
        self.latencies: list = []
        self.responses: list = []
        self.errors: list = []
        self.retries = 0
        self.sent = 0       # distinct requests sent (retries excluded)
        self.answered = 0   # distinct requests that got a terminal verdict
        self.terminal: dict = {}  # terminal-verdict tally, chaos mode
        # Fleet-front reroute accounting (ISSUE 18): Σ of the per-response
        # reroute stamps, and the rerouted payloads themselves (their
        # front spans must show retry_s > 0).
        self.reroutes = 0
        self.rerouted_responses: list = []

    def _tally_fleet(self, payload: dict) -> None:
        """Sum the front's per-response reroute stamp at RECEIVE time —
        retried 429s included — so the client-side sum equals the front's
        ``reroutes`` counter delta exactly (each failed forward attempt
        increments the counter once and lands once in some response's
        ``fleet.reroutes``)."""
        fl = payload.get("fleet")
        if not isinstance(fl, dict):
            return
        n = int(fl.get("reroutes") or 0)
        if n > 0:
            self.reroutes += n
            if len(self.rerouted_responses) < 64:
                self.rerouted_responses.append(payload)

    def _body(self, i: int, user: int = 0) -> dict:
        # Each user walks the trace at its own offset so one wave spans
        # every bucket (they co-batch server-side).
        body = dict(self.trace[(i + user) % len(self.trace)])
        body["schema_version"] = 1
        body["seed"] = self.seed0 + 10_000 * user + i
        if self.telemetry:
            body["telemetry"] = True
        if self.chaos:
            body["schema_version"] = 2
            body["priority"] = self.CHAOS_PRIORITIES[
                (i + user) % len(self.CHAOS_PRIORITIES)
            ]
            dl = self.CHAOS_DEADLINES[
                (i + 2 * user) % len(self.CHAOS_DEADLINES)
            ]
            if dl is not None:
                body["deadline_ms"] = dl
        return body

    def _backoff_s(self, payload: dict, attempt: int) -> float:
        """Jittered exponential backoff floor-bounded by the server's
        Retry-After hint — scaled down in chaos mode (the chaos drive is
        seconds long; honesty there means honoring ORDER and jitter, not
        parking for 30 s)."""
        import random

        hint = payload.get("retry_after_s") or 0.5
        if self.chaos:
            hint = min(hint, 0.25)
        return hint * (2 ** attempt) * (0.75 + 0.5 * random.random())

    def _run_http(self) -> None:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=120)
        i = 0
        attempt = 0
        while time.monotonic() < self.deadline:
            if self.max_requests is not None and i >= self.max_requests:
                break
            body = self._body(i)
            if attempt == 0:
                self.sent += 1
            t0 = time.monotonic()
            try:
                conn.request(
                    "POST", "/run", json.dumps(body),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                payload = json.loads(resp.read())
                status = resp.status
                self._tally_fleet(payload)
            except OSError as e:
                if self.chaos:
                    # A connection torn down before the SEND completed is
                    # not a dropped response; mid-retry, the last 429 WAS
                    # this request's terminal verdict (same rule as the
                    # loop-exit path below).
                    if attempt == 0:
                        self.sent -= 1
                    else:
                        self._classify(429, {"error": "admission-rejected"})
                    return
                self.errors.append(f"{type(e).__name__}: {e}")
                conn.close()
                conn = http.client.HTTPConnection(self.host, self.port,
                                                  timeout=120)
                continue
            if status == 429 and attempt < _MAX_RETRIES:
                self.retries += 1
                time.sleep(min(self._backoff_s(payload, attempt), 5.0))
                attempt += 1
                continue
            self._record(t0, status, payload)
            attempt = 0
            i += 1
            if payload.get("error") == "shutting_down":
                break  # honest client: the server is draining — stop
        if attempt > 0:
            # The loop ended mid-retry: the last 429 WAS this request's
            # terminal response.
            self._classify(429, {"error": "admission-rejected"})
        conn.close()

    def _run_jsonl(self) -> None:
        sock = socket.create_connection((self.host, self.port), timeout=120)
        rfile = sock.makefile("rb")
        i = 0
        attempt = 0
        try:
            while time.monotonic() < self.deadline:
                if self.max_requests is not None and i >= self.max_requests:
                    break
                if self.users > 1:
                    wave = {"requests": [
                        self._body(i, u) for u in range(self.users)
                    ]}
                else:
                    wave = self._body(i)
                if attempt == 0:
                    self.sent += self.users if self.users > 1 else 1
                t0 = time.monotonic()
                try:
                    sock.sendall(json.dumps(wave).encode() + b"\n")
                except OSError:
                    self.sent -= self.users if self.users > 1 else 1
                    if not self.chaos:
                        self.errors.append("jsonl send failed")
                    return
                line = rfile.readline()
                if not line:
                    if self.chaos:
                        # Drained server closed after answering — but THIS
                        # wave's requests never got a verdict: that IS a
                        # dropped response (sent stays counted; the
                        # sent == answered pin catches it).
                        return
                    self.errors.append("jsonl connection closed")
                    break
                payload = json.loads(line)
                if self.users == 1:
                    self._tally_fleet(payload)
                if self.users > 1:
                    lat = time.monotonic() - t0
                    members = payload.get("responses")
                    if not payload.get("ok") or not isinstance(members, list):
                        self.errors.append(f"bad envelope: {str(payload)[:200]}")
                    else:
                        for m in members:
                            self.latencies.append(lat)
                            self._tally_fleet(m)
                            self._classify(m.get("status"), m)
                    i += 1
                    continue
                status = payload.get("status", 0)
                if status == 429 and attempt < _MAX_RETRIES:
                    self.retries += 1
                    time.sleep(min(self._backoff_s(payload, attempt), 5.0))
                    attempt += 1
                    continue
                self._record(t0, status, payload)
                attempt = 0
                i += 1
                if payload.get("error") == "shutting_down":
                    break  # honest client: the server is draining
            if attempt > 0:
                # Ended mid-retry: the last 429 was the terminal verdict.
                self._classify(429, {"error": "admission-rejected"})
        finally:
            rfile.close()
            sock.close()

    def _classify(self, status, payload: dict) -> None:
        """Terminal-verdict bookkeeping shared by both transports."""
        self.answered += 1
        if self.chaos:
            if status == 200:
                key = f"200:{payload.get('result', {}).get('outcome')}"
            else:
                key = f"{status}:{payload.get('error')}"
            self.terminal[key] = self.terminal.get(key, 0) + 1
            structured = status == 200 or (
                isinstance(payload.get("error"), str)
                and 400 <= (status or 0) < 600 and status != 500
            )
            if not structured:
                self.errors.append(f"status {status}: {str(payload)[:200]}")
            elif status == 200 and (self.telemetry
                                    or len(self.responses) < 64):
                self.responses.append(payload)
            return
        if status != 200 or not payload.get("ok"):
            self.errors.append(f"status {status}: {str(payload)[:200]}")
        elif self.telemetry or len(self.responses) < 64:
            self.responses.append(payload)

    def _record(self, t0: float, status: int, payload: dict) -> None:
        self.latencies.append(time.monotonic() - t0)
        self._classify(status, payload)

    def run(self) -> None:
        try:
            if self.transport == "jsonl":
                self._run_jsonl()
            else:
                self._run_http()
        except Exception as e:  # noqa: BLE001 — a client crash must be
            # visible as an error, not a silently shorter phase
            self.errors.append(f"client crash {type(e).__name__}: {e}")


def drive(server: ServerProc, clients: int, duration_s: float,
          trace=MIXED_SMALL_TRACE, max_requests_per_client=None,
          telemetry: bool = False, transport: str = "jsonl",
          conns: int | None = None) -> dict:
    """Run one closed-loop phase with ``clients`` total users spread over
    ``conns`` connections (threads); returns aggregate throughput/latency.
    """
    port = server.jsonl_port if transport == "jsonl" else server.port
    if transport == "jsonl" and server.jsonl_port < 0:
        transport, port = "http", server.port
    if conns is None or transport == "http":
        conns = clients
    conns = min(conns, clients)
    base, extra = divmod(clients, conns)
    deadline = time.monotonic() + duration_s
    pool = [
        ClosedLoopClient(
            server.host, port, trace, seed0=1_000_000 * (c + 1),
            deadline=deadline, max_requests=max_requests_per_client,
            telemetry=telemetry, transport=transport,
            users=base + (1 if c < extra else 0),
        )
        for c in range(conns)
    ]
    t0 = time.monotonic()
    for c in pool:
        c.start()
    for c in pool:
        c.join(timeout=duration_s + 300)
    elapsed = time.monotonic() - t0
    lat = sorted(x for c in pool for x in c.latencies)
    errors = [e for c in pool for e in c.errors]
    responses = [r for c in pool for r in c.responses]
    n = len(lat)
    from cop5615_gossip_protocol_tpu.serving.admission import percentile

    return {
        "clients": clients,
        "elapsed_s": elapsed,
        "requests": n,
        "errors": len(errors),
        "error_samples": errors[:5],
        "rps": n / elapsed if elapsed > 0 else 0.0,
        "p50_ms": 1e3 * percentile(lat, 0.50) if lat else None,
        "p99_ms": 1e3 * percentile(lat, 0.99) if lat else None,
        "responses": responses,
    }


def check_telemetry_responses(responses: list) -> int:
    """Every telemetry response must demultiplex a valid per-request
    trajectory: one row per executed round, final converged count matching
    the result. Returns the number checked."""
    checked = 0
    for r in responses:
        res = r["result"]
        traj = r.get("telemetry")
        assert traj is not None and len(traj) > 0, f"no telemetry in {r}"
        assert len(traj) == res["rounds"], (len(traj), res["rounds"])
        assert traj[-1]["converged_count"] == res["converged_count"], r
        assert res["outcome"] == "converged", r
        assert traj[-1]["rounds"] == res["rounds"]
        checked += 1
    return checked


def scrape_metrics_text(host: str, port: int) -> str:
    """GET /metrics, asserting 200 — raw exposition text."""
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    text = resp.read().decode()
    conn.close()
    assert resp.status == 200, resp.status
    return text


def scrape_metrics(server) -> dict:
    """GET /metrics and parse the Prometheus exposition — a malformed
    line fails here, loudly (utils/obs.parse_prometheus)."""
    from cop5615_gossip_protocol_tpu.utils import obs

    return obs.parse_prometheus(scrape_metrics_text(server.host, server.port))


def check_metrics_identities(parsed: dict) -> dict:
    """The /stats accounting identities, re-asserted on the /metrics
    series (at quiescence). Returns the counter values for the record."""
    from cop5615_gossip_protocol_tpu.utils.obs import metric_value as mv

    vals = {
        name: mv(parsed, f"gossip_tpu_serving_{name}_total")
        for name in ("received", "admitted", "rejected", "invalid",
                     "completed", "failed", "batched_requests",
                     "shed", "timed_out", "timed_out_dispatched")
    }
    assert None not in vals.values(), vals
    in_flight = mv(parsed, "gossip_tpu_serving_in_flight")
    assert vals["received"] == (
        vals["admitted"] + vals["rejected"] + vals["invalid"]
    ), vals
    assert vals["admitted"] == (
        vals["completed"] + vals["failed"] + vals["shed"]
        + vals["timed_out"] + in_flight
    ), (vals, in_flight)
    assert vals["batched_requests"] == (
        vals["completed"] + vals["failed"] + vals["timed_out_dispatched"]
    ), vals
    # The histogram count must agree with the completion counter, and the
    # service quantiles must exist once traffic flowed.
    svc_count = mv(parsed, "gossip_tpu_serving_service_seconds_count")
    assert svc_count == vals["completed"], (svc_count, vals)
    vals["in_flight"] = in_flight
    return vals


def check_span_closure(responses: list, tol: float = 0.05) -> int:
    """Every sampled response's span breakdown must sum to within ``tol``
    of its measured service latency (the spans partition the service wall
    by construction — serving/batcher.py). Returns the number checked."""
    checked = 0
    for r in responses:
        sv = r.get("serving") or {}
        spans = sv.get("spans")
        assert spans is not None and sv.get("trace_id"), r
        assert set(spans) == {"queue_wait_s", "batch_assemble_s",
                              "engine_s", "demux_s"}, spans
        total = sum(spans.values())
        service_s = sv["service_ms"] / 1e3
        assert abs(total - service_s) <= tol * max(service_s, 1e-6), (
            total, service_s, spans
        )
        checked += 1
    return checked


def check_trace_join(response: dict, events_path: str) -> list:
    """One trace_id joins admission -> batch-retired -> response events
    (ISSUE 7 acceptance): the sampled response's id must appear on a
    request-admitted event, inside a batch-retired event's trace_ids, and
    on a request-completed event whose spans match the response's."""
    from cop5615_gossip_protocol_tpu.utils.events import read_events

    tid = response["serving"]["trace_id"]
    joined = [
        e for e in read_events(events_path)
        if e.get("trace_id") == tid or tid in (e.get("trace_ids") or ())
    ]
    kinds = [e["event"] for e in joined]
    assert kinds.count("request-admitted") == 1, kinds
    assert kinds.count("batch-retired") == 1, kinds
    assert kinds.count("request-completed") == 1, kinds
    # File order: completion is emitted by the executor strictly after its
    # batch-retired line. The admitted line is written by the submitter
    # thread concurrently with the executor, so its position is asserted
    # by presence, not order (the t_wall/t_req stamps give the timeline).
    assert kinds.index("batch-retired") < kinds.index("request-completed"), (
        kinds
    )
    done = next(e for e in joined if e["event"] == "request-completed")
    assert done["spans"] == response["serving"]["spans"], (done, response)
    return joined


def check_federated_identities(front_parsed: dict, per_worker: dict) -> dict:
    """The federation identity (ISSUE 18): every summed counter on the
    front's federated /metrics equals the sum of the same family scraped
    DIRECTLY from each worker, the bucket-merged service histogram's
    count equals the fleet-wide completion total, and per-worker gauges
    re-appear under their ``worker`` label. Exact at quiescence (the
    counters are frozen, so the two scrape instants can't disagree)."""
    from cop5615_gossip_protocol_tpu.utils.obs import metric_value as mv

    vals = {}
    for name in ("received", "admitted", "rejected", "invalid",
                 "completed", "failed", "batched_requests",
                 "shed", "timed_out", "timed_out_dispatched"):
        fam = f"gossip_tpu_serving_{name}_total"
        fed = mv(front_parsed, fam)
        per = sum(mv(p, fam) or 0.0 for p in per_worker.values())
        assert fed == per, (fam, fed, per)
        vals[name] = fed
    fed_count = mv(front_parsed, "gossip_tpu_serving_service_seconds_count")
    per_count = sum(
        mv(p, "gossip_tpu_serving_service_seconds_count") or 0.0
        for p in per_worker.values()
    )
    assert fed_count == per_count == vals["completed"], (
        fed_count, per_count, vals
    )
    for wid in per_worker:
        g = mv(front_parsed, "gossip_tpu_serving_in_flight", worker=wid)
        assert g is not None, (wid, "in_flight gauge missing worker label")
    return vals


def check_fleet_trace_join(response: dict, front_events_path: str,
                           worker_events_prefix: str) -> dict:
    """One trace_id joins BOTH halves of the front->worker hop from the
    two event logs alone (ISSUE 18): the owning worker's admission ->
    batch-retired -> request-completed lifecycle (check_trace_join) plus
    the front's front-request-completed carrying the front span clocks."""
    from cop5615_gossip_protocol_tpu.serving.admission import (
        FRONT_SPAN_NAMES,
    )
    from cop5615_gossip_protocol_tpu.utils.events import read_events

    fl = response.get("fleet") or {}
    tid = fl.get("trace_id")
    assert tid, response
    assert response["serving"]["trace_id"] == tid, (
        "front and worker disagree on the trace id", response
    )
    wid = fl["worker"]
    worker_joined = check_trace_join(
        response, f"{worker_events_prefix}.{wid}.jsonl"
    )
    front_joined = [
        e for e in read_events(front_events_path)
        if e.get("trace_id") == tid
    ]
    kinds = [e["event"] for e in front_joined]
    assert kinds.count("front-request-completed") == 1, kinds
    done = next(
        e for e in front_joined if e["event"] == "front-request-completed"
    )
    assert done["worker"] == wid, (done, wid)
    assert set(done["spans"]) == set(FRONT_SPAN_NAMES), done
    return {
        "worker_events": [e["event"] for e in worker_joined],
        "front_events": kinds,
    }


def check_stats(stats: dict, min_buckets: int = 2) -> None:
    """The /stats identities the admission counters promise (ISSUE 8:
    the admitted partition gains shed + timed_out, the occupancy identity
    gains timed_out_dispatched — serving/admission.py)."""
    assert stats["received"] == (
        stats["admitted"] + stats["rejected"] + stats["invalid"]
    ), stats
    assert stats["admitted"] == (
        stats["completed"] + stats["failed"] + stats["shed"]
        + stats["timed_out"] + stats["in_flight"]
    ), stats
    assert stats["batched_requests"] == (
        stats["completed"] + stats["failed"] + stats["timed_out_dispatched"]
    ), stats
    # The ISSUE 8 headline identity, exact at quiescence.
    assert stats["received"] == (
        stats["completed"] + stats["failed"] + stats["rejected"]
        + stats["invalid"] + stats["timed_out"] + stats["shed"]
        + stats["in_flight"]
    ), stats
    assert len(stats["buckets"]) >= min_buckets, stats["buckets"]


def warm_width_ladder(server: "ServerProc", clients: int, conns: int,
                      trace=MIXED_SMALL_TRACE) -> int:
    """Warm the engine pool for every lane WIDTH the measured phases can
    hit (compiles are a property of process start, not steady-state
    serving — without the ladder, a first-occupancy-of-this-width batch
    mid-phase would eat a multi-second trace+compile and pollute p99).
    Client counts land synchronized-bucket occupancy in each power-of-two
    width between the server's min_lanes floor (8) and ``clients``.
    Returns the number of warm requests served; raises on any error."""
    ladder, w = [], 8
    while w < clients:
        ladder.append(w)
        w *= 2
    ladder.append(clients)
    total = 0
    for w in ladder:
        warm = drive(server, clients=w, conns=min(conns, w),
                     duration_s=120.0, max_requests_per_client=3,
                     trace=trace)
        total += warm["requests"]
        if warm["errors"]:
            raise AssertionError(f"warm phase errors: {warm['error_samples']}")
    print(f"[loadgen] warm: {total} requests over user ladder {ladder}, "
          "0 errors", flush=True)
    return total


def fmt_row(label: str, phase: dict, extra: str = "") -> str:
    return (
        f"| {label} | {phase['clients']} | {phase['requests']:,} "
        f"| {phase['rps']:,.0f} | {phase['p50_ms']:.1f} "
        f"| {phase['p99_ms']:.1f} | {extra} |"
    )


def run_metrics_smoke(args) -> int:
    """The metrics-smoke CI contract (module docstring): live /metrics
    under traffic, accounting identities on the Prometheus series, span
    closure on every sampled response, and the trace-id lifecycle join
    through the server's event log."""
    import tempfile

    events_path = tempfile.mktemp(prefix="serve_events_", suffix=".jsonl")
    print(f"[loadgen] metrics-smoke: spawning serve.py with --events "
          f"{events_path}", flush=True)
    server = ServerProc(
        extra_args=("--events", events_path), platform=args.platform,
        window_ms=args.window_ms, max_lanes=args.max_lanes,
    )
    record: dict = {}
    try:
        # Same width ladder as the smoke path, so the measured phase (and
        # its event stream) reflects steady-state serving, not compiles.
        warm_width_ladder(server, args.clients, args.conns)

        # Live scraper: /metrics must stay parseable WHILE the closed loop
        # drives (and cost no device syncs — the drive throughput itself
        # is pinned by the separate serve-smoke job).
        live = {"scrapes": 0, "error": None, "stop": False}

        def scraper():
            while not live["stop"]:
                try:
                    scrape_metrics(server)
                    live["scrapes"] += 1
                except Exception as e:  # noqa: BLE001 — reported below
                    live["error"] = f"{type(e).__name__}: {e}"
                    return
                time.sleep(0.25)

        th = threading.Thread(target=scraper)
        th.start()
        phase = drive(server, clients=args.clients, conns=args.conns,
                      duration_s=min(args.duration, 8.0))
        live["stop"] = True
        th.join(timeout=10)
        assert live["error"] is None, f"live scrape failed: {live['error']}"
        assert live["scrapes"] >= 2, "scraper never ran under traffic"
        assert phase["requests"] > 0 and not phase["errors"], (
            phase["errors"], phase["error_samples"]
        )
        print(f"[loadgen] {live['scrapes']} live /metrics scrapes parsed "
              f"under {phase['rps']:,.0f} req/s", flush=True)

        # Quiesced: the hard identity asserts on the Prometheus series.
        parsed = scrape_metrics(server)
        vals = check_metrics_identities(parsed)
        print(f"[loadgen] /metrics identities hold: {vals}", flush=True)

        n_spans = check_span_closure(phase["responses"])
        print(f"[loadgen] span closure (<=5%) on {n_spans} responses",
              flush=True)

        sample = phase["responses"][0]
        joined = check_trace_join(sample, events_path)
        print(f"[loadgen] trace {sample['serving']['trace_id']} joins "
              f"{[e['event'] for e in joined]}", flush=True)

        record = {
            "live_scrapes": live["scrapes"],
            "rps": phase["rps"],
            "requests": phase["requests"],
            "identities": vals,
            "span_closure_checked": n_spans,
            "trace_join": [e["event"] for e in joined],
            "trace_id": sample["serving"]["trace_id"],
        }
        server.shutdown()
    finally:
        if server.proc.poll() is None:
            server.proc.kill()
        Path(events_path).unlink(missing_ok=True)

    if args.json:
        Path(args.json).write_text(json.dumps(record, indent=2))
    if args.md:
        Path(args.md).write_text("\n".join([
            "## Metrics smoke (benchmarks/loadgen.py --metrics-smoke)",
            "",
            f"- {record['live_scrapes']} live /metrics scrapes parsed "
            f"under {record['rps']:,.0f} req/s",
            f"- accounting identities hold on the Prometheus series: "
            f"{record['identities']}",
            f"- span breakdown sums to service latency (<=5%) on "
            f"{record['span_closure_checked']} responses",
            f"- trace {record['trace_id']} joins "
            f"{' -> '.join(record['trace_join'])}",
            "",
        ]) + "\n")
    print("[loadgen] metrics-smoke passed", flush=True)
    return 0


def run_metrics_smoke_fleet(args) -> int:
    """The ``--metrics-smoke --fleet N`` CI leg (ISSUE 18): the front's
    FEDERATED /metrics stays parseable under load; at quiescence every
    summed counter equals the sum of direct per-worker scrapes and the
    bucket-merged histogram count equals the fleet-wide completions
    (check_federated_identities); the front-local gossip_tpu_fleet_*
    series are live; and a sampled response's trace_id joins across BOTH
    event logs — the front's and the owning worker's
    (check_fleet_trace_join)."""
    import shutil
    import tempfile

    from cop5615_gossip_protocol_tpu.utils import obs

    workers = args.fleet
    tmpdir = tempfile.mkdtemp(prefix="fleet_obs_")
    front_events = os.path.join(tmpdir, "front.jsonl")
    worker_prefix = os.path.join(tmpdir, "worker")
    print(f"[loadgen] metrics-smoke --fleet {workers}: front events "
          f"{front_events}, worker events {worker_prefix}.<wid>.jsonl",
          flush=True)
    fleet = FleetProc(
        workers=workers,
        extra_args=("--events", front_events,
                    "--worker-events", worker_prefix),
        platform=args.platform, window_ms=args.window_ms,
        max_lanes=args.max_lanes,
    )
    record: dict = {}
    try:
        clients = min(args.clients, 32)
        warm_width_ladder(fleet, clients, conns=clients)

        live = {"scrapes": 0, "error": None, "stop": False}

        def scraper():
            while not live["stop"]:
                try:
                    scrape_metrics(fleet)  # federated: front + N workers
                    live["scrapes"] += 1
                except Exception as e:  # noqa: BLE001 — reported below
                    live["error"] = f"{type(e).__name__}: {e}"
                    return
                time.sleep(0.25)

        th = threading.Thread(target=scraper)
        th.start()
        # conns == clients keeps every response on the single-request
        # path (the one the front stamps spans on and logs
        # front-request-completed for — the trace-join sample).
        phase = drive(fleet, clients=clients, conns=clients,
                      duration_s=min(args.duration, 8.0))
        live["stop"] = True
        th.join(timeout=10)
        assert live["error"] is None, (
            f"live federated scrape failed: {live['error']}"
        )
        assert live["scrapes"] >= 2, "scraper never ran under traffic"
        assert phase["requests"] > 0 and not phase["errors"], (
            phase["errors"], phase["error_samples"]
        )
        print(f"[loadgen] {live['scrapes']} live federated /metrics "
              f"scrapes parsed under {phase['rps']:,.0f} req/s", flush=True)

        # Quiesced: the federation identities against DIRECT per-worker
        # scrapes (the front must re-expose exactly what the workers hold).
        front_parsed = obs.parse_prometheus(
            scrape_metrics_text(fleet.host, fleet.port)
        )
        per_worker = {
            wid: obs.parse_prometheus(scrape_metrics_text(fleet.host, port))
            for wid, port in fleet.worker_ports.items()
        }
        vals = check_federated_identities(front_parsed, per_worker)
        print(f"[loadgen] federation identities hold over {workers} "
              f"workers: {vals}", flush=True)

        alive = obs.metric_value(
            front_parsed, "gossip_tpu_fleet_workers_alive"
        )
        assert alive == workers, (alive, workers)
        arc_total = sum(
            obs.metric_value(
                front_parsed, "gossip_tpu_fleet_ring_arc_fraction",
                worker=wid,
            ) or 0.0
            for wid in fleet.worker_ports
        )
        assert abs(arc_total - 1.0) < 1e-9, arc_total
        responded = obs.metric_value(
            front_parsed, "gossip_tpu_fleet_responded_total"
        )
        assert responded is not None and responded > 0, responded

        n_spans = check_span_closure(phase["responses"])
        sample = phase["responses"][0]
        join = check_fleet_trace_join(sample, front_events, worker_prefix)
        tid = sample["fleet"]["trace_id"]
        print(f"[loadgen] trace {tid} joins front "
              f"{join['front_events']} + worker {join['worker_events']}",
              flush=True)

        record = {
            "workers": workers,
            "live_scrapes": live["scrapes"],
            "rps": phase["rps"],
            "requests": phase["requests"],
            "identities": vals,
            "workers_alive": alive,
            "span_closure_checked": n_spans,
            "trace_id": tid,
            "trace_join": join,
        }
        final = fleet.shutdown()
        check_fleet_stats(final)
    finally:
        if fleet.proc.poll() is None:
            fleet.proc.kill()
        shutil.rmtree(tmpdir, ignore_errors=True)

    if args.json:
        Path(args.json).write_text(json.dumps(record, indent=2))
    if args.md:
        Path(args.md).write_text("\n".join([
            f"## Federated metrics smoke (benchmarks/loadgen.py "
            f"--metrics-smoke --fleet {workers})",
            "",
            f"- {record['live_scrapes']} live federated /metrics scrapes "
            f"parsed under {record['rps']:,.0f} req/s",
            f"- summed counters equal direct per-worker sums and the "
            f"bucket-merged histogram count equals completions: "
            f"{record['identities']}",
            f"- front-local series live ({record['workers_alive']:.0f} "
            "workers alive, ring arc fractions sum to 1)",
            f"- span breakdown sums to service latency (<=5%) on "
            f"{record['span_closure_checked']} responses",
            f"- trace {record['trace_id']} joins front "
            f"{' -> '.join(record['trace_join']['front_events'])} and "
            f"worker "
            f"{' -> '.join(record['trace_join']['worker_events'])}",
            "",
        ]) + "\n")
    print("[loadgen] metrics-smoke --fleet passed", flush=True)
    return 0


def run_chaos_serve(args) -> int:
    """The chaos-serve CI contract (ISSUE 8): drive mixed-priority,
    mixed-deadline traffic against a live server while the env-gated
    fault injector wedges one bucket's dispatch and a mid-load SIGTERM
    drains the server — then assert

      1. every submitted request received exactly ONE structured terminal
         response (Σ client sent == Σ client answered; 200 / 429 / shed /
         deadline_exceeded / shutting_down / timeout vocabulary only),
      2. zero HTTP 500s / unstructured failures,
      3. the /stats + Prometheus accounting identities hold exactly on
         the final drained stats (in_flight == 0),
      4. the quarantine cycle — executor-stuck -> engine-quarantined ->
         quarantine-half-open -> quarantine-recovered — and the
         server-drain event appear in the event log.
    """
    import tempfile

    from cop5615_gossip_protocol_tpu.utils.events import read_events

    events_path = tempfile.mktemp(prefix="chaos_serve_", suffix=".jsonl")
    arm_s = _env_float("GOSSIP_TPU_CHAOS_ARM_S", 45.0)
    wedge_s = 8.0
    env = {
        # Wedge the full-topology gossip bucket once, armed only after
        # the warm phase (arm_s is measured from batcher start).
        "GOSSIP_TPU_SERVE_WEDGE": f"gossip/full:{wedge_s}:1:{arm_s}",
        "GOSSIP_TPU_SERVE_STUCK_MIN_S": "2.5",
        # mult 0 pins the warm budget at exactly stuck_min_s: the wedge
        # detection latency is deterministic, independent of the warm
        # bucket's (compile-inflated) p99.
        "GOSSIP_TPU_SERVE_STUCK_MULT": "0",
        "GOSSIP_TPU_SERVE_QUARANTINE_S": "2.5",
        "GOSSIP_TPU_STRICT_ENGINE": "0",
    }
    print(f"[loadgen] chaos-serve: spawning serve.py (wedge armed at "
          f"t={arm_s:.0f}s, {wedge_s:.0f}s wedge, budget 2.5s, "
          f"quarantine 2.5s)", flush=True)
    t_spawn = time.monotonic()
    server = ServerProc(
        extra_args=("--events", events_path, "--drain-window", "30",
                    "--request-timeout", "90"),
        platform=args.platform, window_ms=args.window_ms,
        max_lanes=args.max_lanes, env_extra=env,
    )
    clients = min(args.clients, 12)
    try:
        warm_width_ladder(server, clients, conns=clients)
        # The injector arms on the server's clock; wait it out so the
        # wedge lands mid-drive, not mid-warmup.
        wait = arm_s + 1.0 - (time.monotonic() - t_spawn)
        if wait > 0:
            print(f"[loadgen] chaos: waiting {wait:.0f}s for the "
                  "injector to arm", flush=True)
            time.sleep(wait)

        # The chaos drive: mixed-priority, mixed-deadline closed-loop
        # traffic; SIGTERM fires mid-drive, clients keep sending ~3s into
        # the drain (collecting shutting_down verdicts), then stop.
        sigterm_after = 9.0
        deadline = time.monotonic() + sigterm_after + 3.0
        pool = [
            ClosedLoopClient(
                server.host, server.jsonl_port, MIXED_SMALL_TRACE,
                seed0=1_000_000 * (c + 1), deadline=deadline,
                transport="jsonl", users=1, chaos=True,
            )
            for c in range(clients)
        ]
        for c in pool:
            c.start()
        time.sleep(sigterm_after)
        print("[loadgen] chaos: SIGTERM (graceful drain) mid-load",
              flush=True)
        final_stats = server.drain_shutdown()
        for c in pool:
            c.join(timeout=120)

        sent = sum(c.sent for c in pool)
        answered = sum(c.answered for c in pool)
        retries = sum(c.retries for c in pool)
        errors = [e for c in pool for e in c.errors]
        terminal: dict = {}
        for c in pool:
            for k, v in c.terminal.items():
                terminal[k] = terminal.get(k, 0) + v
        print(f"[loadgen] chaos: {sent} sent, {answered} answered, "
              f"{retries} retries, verdicts {terminal}", flush=True)

        # 1. exactly one structured terminal response per submitted
        # request, 2. nothing unstructured / no 500s.
        assert not errors, f"unstructured outcomes: {errors[:5]}"
        assert sent == answered, (
            f"dropped responses: sent {sent} != answered {answered}"
        )
        assert answered > 0, "chaos drive sent no traffic"
        assert not any(k.startswith("500") for k in terminal), terminal

        # 3. accounting identities, exact on the drained final stats.
        check_stats(final_stats, min_buckets=2)
        assert final_stats["in_flight"] == 0, final_stats
        assert final_stats["received"] == (
            final_stats["completed"] + final_stats["failed"]
            + final_stats["rejected"] + final_stats["invalid"]
            + final_stats["timed_out"] + final_stats["shed"]
        ), final_stats
        print(f"[loadgen] chaos: identities exact on final stats "
              f"({ {k: final_stats[k] for k in ('received', 'completed', 'failed', 'rejected', 'shed', 'timed_out')} })",
              flush=True)

        # 4. the quarantine cycle + drain in the event log.
        kinds = [e["event"] for e in read_events(events_path)]
        cycle = [k for k in kinds if k in (
            "executor-stuck", "engine-quarantined", "quarantine-half-open",
            "quarantine-recovered",
        )]
        assert cycle[:2] == ["executor-stuck", "engine-quarantined"], cycle
        assert "quarantine-half-open" in cycle, cycle
        assert "quarantine-recovered" in cycle, cycle
        assert "server-drain" in kinds, kinds[-10:]
        print(f"[loadgen] chaos: quarantine cycle {cycle}; server-drain "
              "logged", flush=True)

        record = {
            "sent": sent, "answered": answered, "retries": retries,
            "terminal": terminal, "final_stats": final_stats,
            "quarantine_cycle": cycle,
        }
    finally:
        if server.proc.poll() is None:
            server.proc.kill()
        Path(events_path).unlink(missing_ok=True)

    if args.json:
        Path(args.json).write_text(json.dumps(record, indent=2))
    if args.md:
        Path(args.md).write_text("\n".join([
            "## Chaos-serve (benchmarks/loadgen.py --chaos)",
            "",
            f"- {record['sent']} requests sent, {record['answered']} "
            "answered — exactly one structured terminal response each, "
            "zero 500s",
            f"- {record['retries']} honest 429 retries (jittered backoff "
            "honoring Retry-After), counted separately from fresh sends",
            f"- terminal verdicts: {record['terminal']}",
            f"- accounting identities exact on the drained final stats; "
            f"in_flight == 0",
            f"- quarantine cycle: {' -> '.join(record['quarantine_cycle'])}",
            "",
        ]) + "\n")
    print("[loadgen] chaos-serve passed", flush=True)
    return 0


def drive_open_loop(server, rate: float, duration_s: float,
                    trace=MIXED_SMALL_TRACE, conns: int = 128,
                    seed0: int = 0) -> dict:
    """One open-loop phase: Poisson arrivals at ``rate`` req/s for
    ``duration_s``. Latency is measured from the SCHEDULED arrival time,
    so client-side queueing when the server (or client pool) saturates
    shows up in the percentiles instead of silently throttling the
    offered load — the property the closed loop cannot have."""
    import queue
    import random

    jobs: queue.Queue = queue.Queue()
    lock = threading.Lock()
    lats: list = []
    statuses: dict = {}
    errors: list = []

    def connect():
        # Bounded retry: a pool-sized connect burst can transiently
        # outrun even a deep accept backlog on a loaded 1-core box; a
        # worker that gives up shrinks the measured capacity silently.
        for attempt in range(20):
            try:
                s = socket.create_connection(
                    (server.host, server.jsonl_port), timeout=120
                )
                return s, s.makefile("rb")
            except OSError:
                time.sleep(0.02 * (attempt + 1))
        return None, None

    def worker():
        sock, rfile = connect()
        if sock is None:
            with lock:
                errors.append("connect: retries exhausted")
            return
        try:
            while True:
                job = jobs.get()
                if job is None:
                    return
                t_arr, body = job
                try:
                    sock.sendall(json.dumps(body).encode() + b"\n")
                    payload = json.loads(rfile.readline())
                except (OSError, json.JSONDecodeError, ValueError) as e:
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    # Reconnect instead of limping on a dead socket —
                    # a lost worker biases the whole phase's capacity.
                    rfile.close()
                    sock.close()
                    sock, rfile = connect()
                    if sock is None:
                        with lock:
                            errors.append("reconnect: retries exhausted")
                        return
                    continue
                lat = time.monotonic() - t_arr
                status = payload.get("status", 0)
                with lock:
                    statuses[status] = statuses.get(status, 0) + 1
                    if status == 200:
                        lats.append(lat)
        finally:
            if sock is not None:
                rfile.close()
                sock.close()

    pool = [threading.Thread(target=worker, daemon=True)
            for _ in range(conns)]
    for th in pool:
        th.start()
    rng = random.Random(0xA11CE + seed0)
    t0 = time.monotonic()
    t_end = t0 + duration_s
    t_next = t0
    offered = 0
    i = 0
    while t_next < t_end:
        now = time.monotonic()
        if now < t_next:
            time.sleep(min(t_next - now, 0.005))
            continue
        body = dict(trace[i % len(trace)])
        body["schema_version"] = 1
        body["seed"] = seed0 + i
        jobs.put((t_next, body))
        offered += 1
        i += 1
        t_next += rng.expovariate(rate)
    for _ in pool:
        jobs.put(None)
    for th in pool:
        th.join(timeout=duration_s + 120)
    elapsed = time.monotonic() - t0
    lats.sort()
    ok = statuses.get(200, 0)
    from cop5615_gossip_protocol_tpu.serving.admission import percentile

    return {
        "offered_rps": rate,
        "offered": offered,
        "ok": ok,
        "rejected": statuses.get(429, 0),
        "other": {
            str(s): c for s, c in statuses.items() if s not in (200, 429)
        },
        "errors": len(errors),
        "error_samples": errors[:5],
        "elapsed_s": elapsed,
        "achieved_rps": ok / elapsed if elapsed > 0 else 0.0,
        "p50_ms": 1e3 * percentile(lats, 0.50) if lats else None,
        "p99_ms": 1e3 * percentile(lats, 0.99) if lats else None,
    }


def _spawn_server(args, extra_args=()):
    """ServerProc or FleetProc per --fleet, with the --no-continuous
    control flag passed through."""
    extra = tuple(extra_args)
    if args.no_continuous:
        extra = ("--no-continuous",) + extra
    if args.fleet:
        print(f"[loadgen] spawning fleet front ({args.fleet} workers, "
              f"window={args.window_ms}ms, lanes={args.max_lanes}, "
              f"continuous={'off' if args.no_continuous else 'on'})",
              flush=True)
        return FleetProc(
            workers=args.fleet, extra_args=extra, platform=args.platform,
            window_ms=args.window_ms, max_lanes=args.max_lanes,
        )
    print(f"[loadgen] spawning serve.py (platform={args.platform}, "
          f"window={args.window_ms}ms, lanes={args.max_lanes}, "
          f"continuous={'off' if args.no_continuous else 'on'})",
          flush=True)
    return ServerProc(
        extra_args=extra, platform=args.platform,
        window_ms=args.window_ms, max_lanes=args.max_lanes,
    )


def run_open_loop(args) -> int:
    """Latency vs offered load (ISSUE 14 satellite): Poisson arrivals at
    each rate in ``--open-loop``, reporting achieved throughput and
    latency percentiles per offered rate; the measured saturation rate is
    the highest offered rate whose achieved throughput stays within 5%
    (and whose arrivals were neither rejected nor errored)."""
    rates = [float(r) for r in args.open_loop.split(",") if r]
    trace = TRACES[args.trace]
    server = _spawn_server(args)
    rows: list = []
    try:
        warm_width_ladder(server, args.clients, args.conns, trace=trace)
        for k, rate in enumerate(rates):
            phase = drive_open_loop(
                server, rate, duration_s=min(args.duration, 10.0),
                trace=trace, conns=args.open_conns,
                seed0=1_000_000 * (k + 1),
            )
            rows.append(phase)
            print(
                f"[loadgen] open-loop {rate:,.0f} req/s offered -> "
                f"{phase['achieved_rps']:,.0f} achieved "
                f"(p50 {phase['p50_ms'] or float('nan'):.1f} ms, "
                f"p99 {phase['p99_ms'] or float('nan'):.1f} ms, "
                f"{phase['rejected']} rejected, {phase['errors']} errors)",
                flush=True,
            )
        final_stats = server.shutdown()
    finally:
        if server.proc.poll() is None:
            server.proc.kill()

    saturation = None
    for phase in rows:
        if (phase["achieved_rps"] >= 0.95 * phase["offered_rps"]
                and phase["rejected"] == 0 and phase["errors"] == 0):
            saturation = phase["offered_rps"]
    lines = [
        "## Serving plane — latency vs offered load "
        "(benchmarks/loadgen.py --open-loop)",
        "",
        f"Poisson arrivals over the {args.trace} trace; "
        f"{'fleet of ' + str(args.fleet) + ' workers' if args.fleet else 'single server'}, "
        f"continuous batching {'off' if args.no_continuous else 'on'}. "
        "Latency measured from scheduled arrival (client queueing "
        "included). Saturation = highest offered rate achieved within "
        "5%, zero rejects/errors: "
        + (f"**{saturation:,.0f} req/s**." if saturation else "not reached "
           "at the offered rates."),
        "",
        "| offered req/s | achieved req/s | p50 ms | p99 ms | rejected "
        "| errors |",
        "|---|---|---|---|---|---|",
    ]
    for p in rows:
        lines.append(
            f"| {p['offered_rps']:,.0f} | {p['achieved_rps']:,.0f} "
            f"| {p['p50_ms']:.1f} | {p['p99_ms']:.1f} "
            f"| {p['rejected']} | {p['errors']} |"
            if p["p50_ms"] is not None else
            f"| {p['offered_rps']:,.0f} | {p['achieved_rps']:,.0f} "
            f"| — | — | {p['rejected']} | {p['errors']} |"
        )
    lines.append("")
    record = {"open_loop": rows, "saturation_rps": saturation,
              "fleet": args.fleet,
              "continuous": not args.no_continuous,
              "final_stats": final_stats}
    if args.md:
        Path(args.md).write_text("\n".join(lines) + "\n")
    if args.json:
        Path(args.json).write_text(json.dumps(record, indent=2))
    print("\n".join(lines), flush=True)
    return 0


def run_bucket_pressure(args) -> int:
    """Warm-engine LRU pressure test (ISSUE 14 satellite; ROADMAP item 2
    flagged the pool unexamined past ~10 buckets). Drives ``--buckets``
    DISTINCT key buckets (distinct full-topology populations — every one
    compiles its own batch engine) through one server, then re-visits a
    working set inside the pool capacity, asserting the pool accounting:

      - cold pass: >= B pool misses; evictions start once B exceeds the
        LRU capacity (GOSSIP_TPU_ENGINE_POOL_CAP, default 64);
      - warm pass over the most-recent ``capacity/2`` buckets: ZERO new
        misses (the working set stayed resident through the churn);
      - an evicted early bucket re-misses (recompiles) on re-visit.

    Reports cold-vs-warm latency and the measured capacity economics for
    the BENCH_TABLES "Warm-engine LRU under bucket churn" row."""
    B = args.buckets
    server = _spawn_server(args)
    try:
        sock = socket.create_connection(
            (server.host, server.jsonl_port), timeout=300
        )
        rfile = sock.makefile("rb")

        def visit(i: int, seed: int) -> float:
            body = {
                "schema_version": 1, "n": 16 + i, "topology": "full",
                "algorithm": "gossip", "seed": seed,
                "params": {"rumor_threshold": 3},
            }
            t0 = time.monotonic()
            sock.sendall(json.dumps(body).encode() + b"\n")
            payload = json.loads(rfile.readline())
            assert payload.get("status") == 200, payload
            return time.monotonic() - t0

        def pool_stats() -> dict:
            return server.stats()["engine_pool"]

        base = pool_stats()
        cap = base["capacity"]
        print(f"[loadgen] bucket pressure: {B} buckets vs pool capacity "
              f"{cap}", flush=True)
        t0 = time.monotonic()
        cold = [visit(i, seed=i) for i in range(B)]
        cold_s = time.monotonic() - t0
        after_cold = pool_stats()
        miss_cold = after_cold["misses"] - base["misses"]
        evict_cold = after_cold["evictions"] - base["evictions"]
        assert miss_cold >= B, (miss_cold, B)
        if B > cap:
            assert evict_cold >= B - cap, (evict_cold, B, cap)
            assert after_cold["entries"] <= cap, after_cold

        # Warm pass: the most recent cap/2 buckets must all be resident.
        ws = min(cap // 2, B)
        warm: list = []
        for _ in range(2):
            warm.extend(visit(i, seed=1000 + i)
                        for i in range(B - ws, B))
        after_warm = pool_stats()
        miss_warm = after_warm["misses"] - after_cold["misses"]
        hit_warm = after_warm["hits"] - after_cold["hits"]
        assert miss_warm == 0, (
            f"{miss_warm} misses re-visiting the {ws} most recent "
            "buckets — the LRU evicted inside the working set"
        )
        assert hit_warm >= 2 * ws, (hit_warm, ws)

        # An early (evicted) bucket re-misses on re-visit.
        recompile = None
        if B > cap:
            t0 = time.monotonic()
            visit(0, seed=2000)
            recompile = time.monotonic() - t0
            after_re = pool_stats()
            assert after_re["misses"] - after_warm["misses"] == 1, (
                after_re, after_warm
            )

        final_stats = server.shutdown()
    finally:
        if server.proc.poll() is None:
            server.proc.kill()

    from cop5615_gossip_protocol_tpu.serving.admission import percentile

    cold_sorted = sorted(cold)
    warm_sorted = sorted(warm)
    record = {
        "buckets": B, "capacity": cap,
        "cold_pass_s": cold_s,
        "cold_p50_ms": 1e3 * percentile(cold_sorted, 0.5),
        "warm_p50_ms": 1e3 * percentile(warm_sorted, 0.5),
        "misses_cold": miss_cold, "evictions_cold": evict_cold,
        "warm_working_set": ws, "warm_hits": hit_warm,
        "recompile_s": recompile,
        "final_stats": final_stats,
    }
    lines = [
        "## Warm-engine LRU under bucket churn "
        "(benchmarks/loadgen.py --buckets)",
        "",
        f"{B} distinct key buckets (distinct full-topology populations) "
        f"through one server, pool capacity {cap} "
        "(GOSSIP_TPU_ENGINE_POOL_CAP).",
        "",
        f"- cold pass: {miss_cold} pool misses, {evict_cold} evictions, "
        f"p50 {record['cold_p50_ms']:,.0f} ms/bucket (compile-bound), "
        f"{cold_s:.1f} s total",
        f"- warm working set (the {ws} most recent buckets, 2 passes): "
        f"0 new misses, {hit_warm} hits, p50 "
        f"{record['warm_p50_ms']:.1f} ms",
        (f"- evicted bucket re-visit: 1 re-miss, {recompile:.2f} s "
         "recompile" if recompile is not None else
         "- no evictions at this bucket count"),
        "",
    ]
    if args.md:
        Path(args.md).write_text("\n".join(lines) + "\n")
    if args.json:
        Path(args.json).write_text(json.dumps(record, indent=2))
    print("\n".join(lines), flush=True)
    print("[loadgen] bucket-pressure checks passed", flush=True)
    return 0


def run_chaos_fleet(args) -> int:
    """The ISSUE 14 worker-kill chaos contract: drive mixed-priority
    mixed-deadline traffic against the fleet front, SIGKILL the worker
    that OWNS a driven bucket mid-load, then gracefully drain — and
    assert

      1. every submitted request received exactly ONE structured
         terminal response (Σ sent == Σ answered, zero unstructured
         outcomes, zero 500s) — kills included;
      2. the dead worker's buckets re-routed (front worker_failures +
         reroutes observed, and post-kill requests keep succeeding);
      3. the front identity (received == responded, in_flight == 0) and
         every LIVE worker's /stats identities hold exactly on the
         drained fleet.
    """
    workers = 3
    print(f"[loadgen] chaos-fleet: spawning {workers}-worker fleet",
          flush=True)
    fleet = FleetProc(
        workers=workers,
        extra_args=("--request-timeout", "90"),
        platform=args.platform, window_ms=args.window_ms,
        max_lanes=args.max_lanes,
    )
    clients = min(args.clients, 12)
    try:
        warm_width_ladder(fleet, clients, conns=clients)

        # Find the worker that owns the gossip/full bucket (trace[0]) —
        # the kill must hit a bucket under live traffic to exercise
        # re-routing, not a bystander.
        sock = socket.create_connection(
            (fleet.host, fleet.jsonl_port), timeout=60
        )
        rfile = sock.makefile("rb")
        probe = dict(MIXED_SMALL_TRACE[0])
        probe.update(schema_version=1, seed=987654)
        sock.sendall(json.dumps(probe).encode() + b"\n")
        resp = json.loads(rfile.readline())
        victim = resp["fleet"]["worker"]
        rfile.close()
        sock.close()
        victim_pid = fleet.worker_pids[victim]
        print(f"[loadgen] chaos-fleet: victim {victim} (pid {victim_pid}) "
              f"owns {probe['algorithm']}/{probe['topology']}", flush=True)

        # Reroute baseline AFTER warm + probe, BEFORE the chaos drive:
        # from here on only the chaos pool talks to the front, so the
        # counter delta must equal the client-measured reroute sum
        # exactly (ISSUE 18 satellite).
        reroutes_before = fleet.stats()["front"]["reroutes"]

        kill_after = 3.0
        sigterm_after = 9.0
        deadline = time.monotonic() + sigterm_after + 3.0
        pool = [
            ClosedLoopClient(
                fleet.host, fleet.jsonl_port, MIXED_SMALL_TRACE,
                seed0=1_000_000 * (c + 1), deadline=deadline,
                transport="jsonl", users=1, chaos=True,
            )
            for c in range(clients)
        ]
        for c in pool:
            c.start()
        time.sleep(kill_after)
        print(f"[loadgen] chaos-fleet: SIGKILL {victim} mid-load",
              flush=True)
        os.kill(victim_pid, signal.SIGKILL)
        time.sleep(sigterm_after - kill_after)
        print("[loadgen] chaos-fleet: SIGTERM (graceful fleet drain) "
              "mid-load", flush=True)
        final = fleet.shutdown(sig=signal.SIGTERM)
        for c in pool:
            c.join(timeout=120)

        sent = sum(c.sent for c in pool)
        answered = sum(c.answered for c in pool)
        errors = [e for c in pool for e in c.errors]
        terminal: dict = {}
        for c in pool:
            for k, v in c.terminal.items():
                terminal[k] = terminal.get(k, 0) + v
        print(f"[loadgen] chaos-fleet: {sent} sent, {answered} answered, "
              f"verdicts {terminal}", flush=True)

        assert not errors, f"unstructured outcomes: {errors[:5]}"
        assert sent == answered, (
            f"dropped responses: sent {sent} != answered {answered}"
        )
        assert answered > 0, "chaos-fleet drive sent no traffic"
        assert not any(k.startswith("500") for k in terminal), terminal
        ok_count = sum(v for k, v in terminal.items()
                       if k.startswith("200"))
        assert ok_count > 0, terminal

        front = final["front"]
        assert front["worker_failures"] >= 1, front
        assert front["reroutes"] >= 1, front
        # ISSUE 18 satellite: the front's reroute counter moved by
        # EXACTLY the reroutes the clients measured on their response
        # stamps, and every rerouted response clocks its failed attempts
        # in the front's retry_s span.
        measured_reroutes = sum(c.reroutes for c in pool)
        assert front["reroutes"] - reroutes_before == measured_reroutes, (
            front["reroutes"], reroutes_before, measured_reroutes
        )
        assert measured_reroutes >= 1, "kill produced no observed reroutes"
        rerouted = [r for c in pool for r in c.rerouted_responses]
        assert rerouted, "no rerouted response payloads retained"
        for r in rerouted:
            spans = (r.get("fleet") or {}).get("spans")
            if spans is None:
                continue  # group-forwarded member: front spans ride
                # single-request responses only
            assert spans["retry_s"] > 0.0, r
        n_retry = sum(
            1 for r in rerouted if (r.get("fleet") or {}).get("spans")
        )
        assert n_retry >= 1, "no rerouted response carried front spans"
        print(f"[loadgen] chaos-fleet: reroute identity exact "
              f"({measured_reroutes} measured == counter delta), "
              f"retry_s > 0 on {n_retry} rerouted responses", flush=True)
        check_fleet_stats(final)
        live = [wid for wid, s in final["workers"].items()
                if isinstance(s, dict) and "received" in s]
        assert victim not in live, (victim, list(final["workers"]))
        assert len(live) == workers - 1, final["workers"]
        print(f"[loadgen] chaos-fleet: front identity exact "
              f"({front}), {len(live)} live workers' identities exact",
              flush=True)
        record = {
            "sent": sent, "answered": answered, "terminal": terminal,
            "victim": victim, "front": front,
            "live_workers": live,
            "measured_reroutes": measured_reroutes,
            "rerouted_with_retry_s": n_retry,
        }
    finally:
        if fleet.proc.poll() is None:
            fleet.proc.kill()

    if args.json:
        Path(args.json).write_text(json.dumps(record, indent=2))
    if args.md:
        Path(args.md).write_text("\n".join([
            "## Chaos-fleet (benchmarks/loadgen.py --chaos-fleet)",
            "",
            f"- {workers}-worker fleet; worker {record['victim']} "
            "(owner of the driven gossip/full bucket) SIGKILLed "
            "mid-load, fleet SIGTERM-drained mid-load",
            f"- {record['sent']} requests sent, {record['answered']} "
            "answered — exactly one structured terminal response each, "
            "zero 500s",
            f"- terminal verdicts: {record['terminal']}",
            f"- dead worker's buckets re-routed: "
            f"{record['front']['worker_failures']} worker failures, "
            f"{record['front']['reroutes']} reroutes, front "
            "received == responded exactly",
            f"- reroute identity exact: {record['measured_reroutes']} "
            "client-measured reroutes == the front counter delta; "
            f"retry_s > 0 on {record['rerouted_with_retry_s']} rerouted "
            "responses' front spans",
            f"- {len(record['live_workers'])} surviving workers drained "
            "with exact /stats identities",
            "",
        ]) + "\n")
    print("[loadgen] chaos-fleet passed", flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="drive an already-running server (host:port) "
                    "instead of spawning serve.py; skips the control phase "
                    "and shutdown checks")
    ap.add_argument("--platform", choices=["auto", "cpu", "tpu"],
                    default="cpu")
    ap.add_argument("--clients", type=int, default=128,
                    help="total closed-loop users")
    ap.add_argument("--conns", type=int, default=4,
                    help="connections (threads) the users multiplex over "
                    "via the JSONL batch envelope")
    ap.add_argument("--trials", type=int, default=2,
                    help="throughput-phase trials; the best is reported "
                    "(min-over-trials rejects scheduler-noise outliers)")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="throughput-phase seconds")
    ap.add_argument("--control-duration", type=float, default=None,
                    help="batching-off control seconds (default: duration)")
    ap.add_argument("--window-ms", type=float, default=3.0)
    ap.add_argument("--max-lanes", type=int, default=32,
                    help="server-side batch width cap (32 keeps the "
                    "per-bucket compiled-width count at two on this "
                    "trace's occupancies)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI serve-smoke: shorter phases, HARD pins on "
                    "rps/p99/batching-ratio/stats (env-overridable)")
    ap.add_argument("--metrics-smoke", action="store_true",
                    help="CI metrics-smoke: live /metrics scrape under "
                    "traffic, Prometheus identity checks, span-closure "
                    "and trace-id-join asserts (module docstring); "
                    "replaces the throughput/control phases")
    ap.add_argument("--chaos", action="store_true",
                    help="CI chaos-serve: mixed-priority mixed-deadline "
                    "traffic while the env-gated injector wedges one "
                    "bucket's dispatch and SIGTERM drains the server "
                    "mid-load; asserts exactly-one-terminal-response, "
                    "exact identities, zero 500s, and the quarantine -> "
                    "half-open -> recovery cycle (run_chaos_serve)")
    ap.add_argument("--md", type=str, default=None,
                    help="write the latency table as markdown here")
    ap.add_argument("--json", type=str, default=None,
                    help="write the raw phase records as JSON here")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="drive the bucket-routed worker fleet front "
                    "(serving/fleet.py) with N serve.py workers instead "
                    "of a single server (ISSUE 14)")
    ap.add_argument("--no-continuous", action="store_true",
                    help="pass the wave-at-a-time control flag to the "
                    "server(s): continuous batching OFF (the A/B "
                    "baseline for the ISSUE 14 win)")
    ap.add_argument("--open-loop", type=str, default=None,
                    metavar="R1,R2,...",
                    help="open-loop mode: Poisson arrivals at each "
                    "offered rate (req/s), latency-vs-offered-load "
                    "table + measured saturation rate (run_open_loop)")
    ap.add_argument("--open-conns", type=int, default=128,
                    help="open-loop client pool size (threads, each "
                    "with a persistent JSONL connection)")
    ap.add_argument("--trace", choices=sorted(TRACES),
                    default="mixed-small",
                    help="request trace: mixed-small (the r06-comparable "
                    "3-bucket trace) or mixed-duration (chunk_rounds 8, "
                    "~13-76-round spread — the ISSUE 14 convoy case)")
    ap.add_argument("--buckets", type=int, default=0, metavar="B",
                    help="warm-engine LRU pressure mode: drive B "
                    "distinct key buckets and assert the pool "
                    "miss/eviction/hit accounting (run_bucket_pressure)")
    ap.add_argument("--chaos-fleet", action="store_true",
                    help="CI chaos-fleet: SIGKILL the worker owning a "
                    "driven bucket mid-load under the fleet front; "
                    "assert exactly-one-terminal-response, re-route, "
                    "and exact identities on the drained fleet "
                    "(run_chaos_fleet)")
    args = ap.parse_args(argv)

    if args.metrics_smoke:
        if args.fleet:
            return run_metrics_smoke_fleet(args)
        return run_metrics_smoke(args)
    if args.chaos:
        return run_chaos_serve(args)
    if args.chaos_fleet:
        return run_chaos_fleet(args)
    if args.buckets:
        return run_bucket_pressure(args)
    if args.open_loop:
        return run_open_loop(args)

    if args.smoke:
        args.duration = min(args.duration, 8.0)
    control_duration = args.control_duration or args.duration

    rps_floor = _env_float("GOSSIP_TPU_SERVE_RPS_FLOOR", 1000.0)
    p99_ms_bound = _env_float("GOSSIP_TPU_SERVE_P99_MS", 250.0)
    ratio_floor = _env_float("GOSSIP_TPU_SERVE_BATCH_RATIO", 1.3)

    trace = TRACES[args.trace]
    record: dict = {"trace_buckets": len(trace), "trace": args.trace}
    trace_desc = ", ".join(
        f"{t['algorithm']}/{t['topology']}/n{t['n']}"
        for t in trace
    )
    lines = [
        "## Serving plane (benchmarks/loadgen.py closed loop)",
        "",
        f"{args.trace} trace, {len(trace)} key buckets "
        f"({trace_desc}); {args.clients} closed-loop users over "
        f"{args.conns} JSONL-socket connections (telemetry phase rides "
        "HTTP POST /run).",
        "",
        "| phase | clients | requests | req/s | p50 ms | p99 ms | note |",
        "|---|---|---|---|---|---|---|",
    ]

    if args.url:
        host, port = args.url.rsplit(":", 1)

        class _Remote:
            jsonl_port = -1  # remote JSONL port unknown: phases ride HTTP

            def stats(self):
                return ServerProc.stats(self)

        server = _Remote()
        server.host, server.port = host.replace("http://", ""), int(port)
    else:
        server = _spawn_server(args)

    # Phase 0 — warm: populate the warm-engine pool for every bucket and
    # lane width the measured phases can hit (warm_width_ladder).
    warm_width_ladder(server, args.clients, args.conns, trace=trace)

    # Phase 1 — correctness: telemetry demux on every response, over the
    # HTTP front (the throughput phases ride the JSONL socket — this
    # phase keeps POST /run honest too).
    tele = drive(server, clients=4, duration_s=120.0,
                 max_requests_per_client=6, telemetry=True,
                 transport="http", trace=trace)
    checked = check_telemetry_responses(tele["responses"])
    print(f"[loadgen] telemetry demux: {checked} responses valid",
          flush=True)
    record["telemetry_checked"] = checked
    lines.append(fmt_row("telemetry demux", tele, "every response checked"))

    # Phase 2 — throughput (batched), best of N trials.
    batched = None
    for trial in range(max(args.trials, 1)):
        t = drive(server, clients=args.clients, conns=args.conns,
                  duration_s=args.duration, trace=trace)
        print(f"[loadgen] batched trial {trial + 1}: {t['rps']:,.0f} req/s "
              f"(p50 {t['p50_ms']:.1f} ms, p99 {t['p99_ms']:.1f} ms, "
              f"{t['errors']} errors)", flush=True)
        if batched is None or t["rps"] > batched["rps"]:
            batched = t
    print(f"[loadgen] batched best: {batched['rps']:,.0f} req/s "
          f"(p50 {batched['p50_ms']:.1f} ms, p99 {batched['p99_ms']:.1f} "
          f"ms)", flush=True)
    record["batched"] = {k: v for k, v in batched.items() if k != "responses"}
    lines.append(fmt_row("batched", batched, "micro-batcher on"))

    stats = server.stats()
    if args.fleet:
        front = stats["front"]
        assert front["received"] == front["responded"], front
        buckets = set()
        for snap in stats["workers"].values():
            if isinstance(snap, dict) and "buckets" in snap:
                check_stats(snap, min_buckets=0)
                buckets.update(snap["buckets"])
        assert len(buckets) >= 2, buckets
        record["stats"] = stats
        print(f"[loadgen] fleet stats ok: front {front}, "
              f"buckets {sorted(buckets)}", flush=True)
    else:
        check_stats(stats, min_buckets=2)
        record["stats"] = stats
        print(f"[loadgen] stats ok: {stats['batches']} batches, "
              f"occupancy mean {stats['batch_occupancy_mean']:.1f}, "
              f"refills {stats.get('refills')}, "
              f"lane fill mean {stats.get('lane_fill_mean')}, "
              f"buckets {list(stats['buckets'])}", flush=True)

    ratio = None
    if args.fleet and not args.url:
        # Fleet mode: graceful drain + the fleet accounting contract;
        # the batching-off control is a single-server concept — the
        # fleet row's baseline is the committed single-server trend row.
        final = server.shutdown()
        check_fleet_stats(final)
        record["fleet_final"] = final
        print("[loadgen] clean fleet drain (rc=0, front + live-worker "
              "identities exact)", flush=True)
    elif not args.url:
        final_stats = server.shutdown()
        check_stats(final_stats, min_buckets=2)
        print("[loadgen] clean shutdown (rc=0, final stats consistent)",
              flush=True)

        # Phase 3 — control: identical trace/clients, batching OFF.
        print("[loadgen] spawning --no-batching control", flush=True)
        control_server = ServerProc(
            extra_args=("--no-batching",), platform=args.platform,
            window_ms=args.window_ms, max_lanes=args.max_lanes,
        )
        cwarm = drive(control_server, clients=args.clients,
                      conns=args.conns, duration_s=120.0,
                      max_requests_per_client=2, trace=trace)
        if cwarm["errors"]:
            raise AssertionError(
                f"control warm errors: {cwarm['error_samples']}"
            )
        control = drive(control_server, clients=args.clients,
                        conns=args.conns, duration_s=control_duration,
                        trace=trace)
        control_server.shutdown()
        ratio = (batched["rps"] / control["rps"]) if control["rps"] else None
        print(f"[loadgen] control (batching off): {control['rps']:,.0f} "
              f"req/s -> batching speedup {ratio:.2f}x", flush=True)
        record["control"] = {
            k: v for k, v in control.items() if k != "responses"
        }
        record["batching_ratio"] = ratio
        lines.append(fmt_row("batching-off control", control,
                             f"batching speedup {ratio:.2f}x"))

    lines.append("")
    failures = []
    if batched["errors"]:
        failures.append(
            f"batched phase had {batched['errors']} errors: "
            f"{batched['error_samples']}"
        )
    if args.smoke:
        if batched["rps"] < rps_floor:
            failures.append(
                f"throughput {batched['rps']:,.0f} req/s under the "
                f"GOSSIP_TPU_SERVE_RPS_FLOOR={rps_floor:,.0f} pin"
            )
        if batched["p99_ms"] > p99_ms_bound:
            failures.append(
                f"p99 {batched['p99_ms']:.1f} ms over the "
                f"GOSSIP_TPU_SERVE_P99_MS={p99_ms_bound:.0f} pin"
            )
        if ratio is not None and ratio < ratio_floor:
            failures.append(
                f"batching speedup {ratio:.2f}x under the "
                f"GOSSIP_TPU_SERVE_BATCH_RATIO={ratio_floor} pin"
            )

    if args.md:
        Path(args.md).write_text("\n".join(lines) + "\n")
        print(f"[loadgen] wrote {args.md}", flush=True)
    if args.json:
        Path(args.json).write_text(json.dumps(record, indent=2))
        print(f"[loadgen] wrote {args.json}", flush=True)
    print("\n".join(lines), flush=True)

    if failures:
        for f in failures:
            print(f"[loadgen] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[loadgen] all checks passed", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
