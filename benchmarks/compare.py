"""Old-vs-new comparison harness (SURVEY.md §7 step 7).

Joins, on one matched (N, topology, algorithm, seed) config:

- the **published Akka number** from report.pdf p.4-5 where the grid has one
  (benchmarks/baseline_data.py) — the reference's own hardware/runtime;
- the **native reference simulator** (native/refsim.cpp via
  cop5615_gossip_protocol_tpu.native) — the runnable stand-in for
  `dotnet run N topology algorithm` in this image (no .NET runtime),
  reproducing the reference's actor semantics as a discrete-event model;
- the **TPU framework** in batched semantics — the honest synchronous-round
  mode the framework actually ships (wall-clock excludes XLA compile, which
  is reported separately; the reference's Stopwatch likewise excludes
  topology build, program.fs:175).

The semantic recast is documented in SURVEY.md §3.3: the reference's
push-sum is a single random walk, so its wall-clock measures walk cover
time, while the batched mode measures synchronous rounds — the join is
old-vs-new *capability* timing on identical (N, topology, algorithm), not a
claim that the two algorithms do identical message-by-message work.
Message-level behavioral equivalence of the reference-semantics JAX modes
against the native oracle is pinned separately by tests/test_native.py.

Usage:
  python benchmarks/compare.py 1000 line gossip
  python benchmarks/compare.py 1000 2D push-sum --seed 3
  python benchmarks/compare.py --grid          # full N<=2000 sweep, all cells
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from benchmarks import baseline_data  # noqa: E402


# Reference CLI spelling -> native refsim spelling (refsim accepts lowercase).
_NATIVE_NAME = {"line": "line", "full": "full", "2D": "2d", "Imp3D": "imp3d"}


@dataclasses.dataclass
class MatchedRow:
    """One joined old-vs-new measurement."""

    n: int
    topology: str  # reference CLI spelling
    algorithm: str
    seed: int
    akka_report_ms: float | None  # report.pdf, None off-grid
    refsim_ms: float  # native DES wall (this machine)
    refsim_events: int  # mailbox deliveries to convergence
    tpu_ms: float  # batched-mode steady-state wall
    tpu_rounds: int
    tpu_compile_s: float
    tpu_converged: bool
    tpu_us_per_round: float | None = None  # differential engine cost (see
    # engine_us_per_round) — what the engine costs per round once the
    # per-dispatch tunnel floor is subtracted out
    tpu_us_noise: float | None = None  # per-round resolution bound at the
    # (possibly grown) round spread — differentials below it render as a
    # bound, not a number (suite.py _fmt_us)

    @property
    def speedup_vs_akka(self) -> float | None:
        if self.akka_report_ms is None or self.tpu_ms <= 0:
            return None
        return self.akka_report_ms / self.tpu_ms

    def to_record(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["speedup_vs_akka"] = self.speedup_vs_akka
        return rec


def default_round_spread(n: int) -> tuple[int, int]:
    """(r1, r2) fixed-round budgets for the differential timing at
    population n — the ONE policy home (bench.py, roofline.py, and the
    grid sweep all measure through it, so their numbers are comparable).

    The r5 calibration (RUNLOG r5) showed the old narrow spreads were the
    source of VERDICT r4 Weak #1's irreproducible headline: at 1M the
    512->2560 differential signal (~100 ms) is the same order as the
    remote-tunnel launch floor (~100-175 ms observed), so floor drift
    between the two runs swung the quotient 28-64 us/round. These spreads
    size the signal to >=~0.5 s — an order above the floor's wobble —
    after which interleaved pairs agree within a few percent."""
    if n <= 65_536:
        return 1024, 131_072  # sub-us rounds: ~0.1 s signal minimum
    if n <= 4_000_000:
        return 512, 16_384  # ~50 us rounds -> ~0.8 s signal
    if n <= 64_000_000:
        return 64, 1024  # ~2-7 ms rounds -> >=2 s signal
    return 64, 320  # 2^27-class ~15 ms rounds -> ~4 s signal


# Differenced-wall signal target for the adaptive budget growth: a pair
# whose (w2 - w1) clears this is an order above timer resolution and the
# scheduler jitter of a quiet machine, so the quotient is a real number,
# not a noise readout. The growth cap bounds how long one cell may spend
# chasing a sub-nanosecond round (the N=20 class).
MIN_DIFF_SIGNAL_S = 0.2
MAX_GROWN_WALL_S = 4.0
MAX_GROWN_ROUNDS = 1 << 23


def engine_us_stats(
    kind: str, algorithm: str, n: int, seed: int = 0, pairs: int = 3,
    r1: int | None = None, r2: int | None = None, grow: bool | None = None,
    **overrides,
) -> dict:
    """Per-round engine cost statistics with the per-dispatch launch floor
    differenced out (VERDICT r3 #8, r4 #2).

    A to-convergence run at small N is one chunk dispatch whose wall is
    ~100-175 ms of remote-tunnel launch plumbing regardless of rounds — it
    measures the tunnel, not the engine. Here the SAME compiled chunk runs
    with convergence disabled (gossip: unreachable rumor threshold;
    push-sum: unreachable term counter) at two fixed round budgets;
    (t2 - t1) / (r2 - r1) cancels the floor and the compile exactly
    because both runs share one executable. ``pairs`` (r1, r2) runs are
    INTERLEAVED in time so slow floor drift hits both budgets equally;
    the returned dict carries the per-pair differentials plus their
    median/min/max — callers quote the median and the spread, never a
    single pair (the r4 lesson: a lone narrow-spread pair wobbled 1.8x).

    When the budgets come from the default policy (``grow`` unset and no
    explicit r1/r2), the spread is GROWN before the timed pairs: r2
    quadruples until the differenced wall clears ``MIN_DIFF_SIGNAL_S`` or a
    wall/round cap is hit — so a sub-µs-round cell prints a real number
    instead of the old "<0.5" floor marker (each growth step recompiles:
    chunk_rounds tracks r2 so both budgets stay one dispatch). The returned
    ``noise_us`` is the per-round resolution bound at the final spread —
    differentials below it are still rendered as a bound by callers."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    no_conv = (
        {"rumor_threshold": 10**6}
        if algorithm == "gossip"
        else {"term_rounds": 1_000_000}
    )
    d1, d2 = default_round_spread(n)
    if grow is None:
        grow = r1 is None and r2 is None
    r1 = d1 if r1 is None else r1
    r2 = d2 if r2 is None else r2
    topo = build_topology(kind, n, seed=seed, semantics="batched")

    def one(cap, chunk):
        cfg = SimConfig(
            n=n, topology=kind, algorithm=algorithm, semantics="batched",
            seed=seed, max_rounds=cap, chunk_rounds=chunk,
            **{**no_conv, **overrides},
        )
        res = run(topo, cfg)
        assert res.rounds == cap, (res.rounds, cap)
        return res.run_s

    per_pair = []
    if grow:
        # Budget calibration: the first pair doubles as a measurement once
        # the spread is wide enough, so a well-sized default costs nothing
        # extra. Growth keeps r1 fixed (the floor-anchoring short run) and
        # quadruples r2 until the differenced wall clears the signal bar.
        while True:
            w1 = one(r1, max(r1, r2))
            w2 = one(r2, max(r1, r2))
            if (
                (w2 - w1) >= MIN_DIFF_SIGNAL_S
                or w2 >= MAX_GROWN_WALL_S
                or r2 >= MAX_GROWN_ROUNDS
            ):
                per_pair.append((w2 - w1) / (r2 - r1) * 1e6)
                break
            r2 *= 4
    for _ in range(pairs - len(per_pair)):
        w1 = one(r1, max(r1, r2))
        w2 = one(r2, max(r1, r2))
        # Raw differential, deliberately UNclamped (VERDICT r3 Weak #4):
        # the true per-round cost can still sit below the resolution bound
        # when growth capped out — that is a statement about the bound,
        # not "free"; callers render it as below-noise rather than 0.00.
        per_pair.append((w2 - w1) / (r2 - r1) * 1e6)
    per_pair_sorted = sorted(per_pair)
    median = per_pair_sorted[len(per_pair_sorted) // 2]
    return {
        "us_per_round": median,
        "us_min": per_pair_sorted[0],
        "us_max": per_pair_sorted[-1],
        "pairs": per_pair,
        "r1": r1,
        "r2": r2,
        # Per-round resolution bound at the final spread: a ~5 ms timer/
        # scheduler readout wobble divided across the differenced rounds.
        "noise_us": 5e-3 / (r2 - r1) * 1e6,
    }


def engine_us_per_round(
    kind: str, algorithm: str, n: int, seed: int = 0,
    r1: int | None = None, r2: int | None = None, **overrides,
) -> float:
    """Median-of-3-pairs differential per-round engine cost in
    microseconds — engine_us_stats' headline number."""
    return engine_us_stats(
        kind, algorithm, n, seed=seed, pairs=3, r1=r1, r2=r2, **overrides
    )["us_per_round"]


# Fallback noise bound for rows measured without engine_us_stats' own
# per-spread "noise_us" (pre-growth records): differentials below it are
# indistinguishable from dispatch jitter at the old default spreads and
# render as "<0.5" instead of a number. Rows measured through the adaptive
# growth carry a much tighter per-row bound (MatchedRow.tpu_us_noise) —
# grown spreads push it below real per-round costs, so small-N cells print
# numbers instead of the floor marker.
ENGINE_US_NOISE = 0.5


def matched_run(
    n: int,
    topology: str,
    algorithm: str,
    seed: int = 0,
    max_rounds: int = 1_000_000,
    us_pairs: int = 3,
    us_budgets: tuple[int, int] | None = None,
) -> MatchedRow:
    """Run both sides on one matched config and join the results."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
    from cop5615_gossip_protocol_tpu import native
    from cop5615_gossip_protocol_tpu.config import normalize_topology

    if topology not in _NATIVE_NAME:
        raise ValueError(
            f"topology {topology!r} is not a reference CLI spelling; "
            f"expected one of {sorted(_NATIVE_NAME)}"
        )

    # Native side: reference semantics by construction.
    t0 = time.perf_counter()
    ref = native.refsim_run(n, _NATIVE_NAME[topology], algorithm, seed=seed)
    refsim_host_ms = (time.perf_counter() - t0) * 1e3
    if not ref.ok:
        raise RuntimeError(
            f"refsim did not converge on n={n} {topology} {algorithm} "
            f"(events={ref.events}) — cannot join an unconverged oracle run"
        )

    # TPU side: honest batched mode (the framework's real mode). "2D" maps
    # to the honest grid2d here — comparing against the reference's "2D"
    # *label*; its wiring bug (Q6) is reproduced by ref2d/tests, not re-run
    # in the perf join.
    kind = normalize_topology(topology, semantics="batched")
    cfg = SimConfig(
        n=n, topology=kind, algorithm=algorithm, semantics="batched",
        seed=seed, max_rounds=max_rounds,
    )
    topo = build_topology(kind, n, seed=seed, semantics="batched")
    result = run(topo, cfg)
    r1, r2 = us_budgets if us_budgets is not None else (None, None)
    us_stats = engine_us_stats(
        kind, algorithm, n, seed=seed, pairs=us_pairs, r1=r1, r2=r2
    )

    return MatchedRow(
        n=n,
        topology=topology,
        algorithm=algorithm,
        seed=seed,
        akka_report_ms=baseline_data.akka_ms(topology, algorithm, n),
        refsim_ms=ref.wall_ms if ref.wall_ms > 0 else refsim_host_ms,
        refsim_events=ref.events,
        tpu_ms=result.wall_ms,
        tpu_rounds=result.rounds,
        tpu_compile_s=result.compile_s,
        tpu_converged=result.converged,
        tpu_us_per_round=us_stats["us_per_round"],
        tpu_us_noise=us_stats["noise_us"],
    )


def _fmt(x, nd=2, none="—"):
    return none if x is None else f"{x:,.{nd}f}"


HEADER = (
    "| N | topology | algorithm | Akka report (ms) | refsim native (ms) "
    "| gossip-tpu (ms) | tpu rounds | speedup vs Akka |"
)
RULE = "|---|---|---|---|---|---|---|---|"


def row_markdown(r: MatchedRow) -> str:
    return (
        f"| {r.n} | {r.topology} | {r.algorithm} | {_fmt(r.akka_report_ms)} "
        f"| {_fmt(r.refsim_ms)} | {_fmt(r.tpu_ms)} | {r.tpu_rounds} "
        f"| {_fmt(r.speedup_vs_akka, 1)}{'' if r.speedup_vs_akka is None else 'x'} |"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("n", type=int, nargs="?")
    ap.add_argument("topology", nargs="?", help="line | full | 2D | Imp3D")
    ap.add_argument("algorithm", nargs="?", help="gossip | push-sum")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--grid", action="store_true",
                    help="sweep the full report.pdf grid (N<=1000, 8 cells/N)")
    ap.add_argument("--platform", choices=["auto", "cpu"], default="auto")
    ap.add_argument("--jsonl", type=str, default=None)
    args = ap.parse_args(argv)

    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.grid:
        configs = [
            (n, topo, algo)
            for algo in ("gossip", "push-sum")
            for topo in baseline_data.REF_TOPOLOGIES
            for n in baseline_data.GRID_N
        ]
    else:
        if args.n is None or args.topology is None or args.algorithm is None:
            ap.error("need `N topology algorithm` or --grid")
        configs = [(args.n, args.topology, args.algorithm)]

    print(HEADER)
    print(RULE)
    for n, topo, algo in configs:
        row = matched_run(n, topo, algo, seed=args.seed)
        print(row_markdown(row), flush=True)
        if args.jsonl:
            with open(args.jsonl, "a") as f:
                f.write(json.dumps(row.to_record()) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
