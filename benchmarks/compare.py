"""Old-vs-new comparison harness (SURVEY.md §7 step 7).

Joins, on one matched (N, topology, algorithm, seed) config:

- the **published Akka number** from report.pdf p.4-5 where the grid has one
  (benchmarks/baseline_data.py) — the reference's own hardware/runtime;
- the **native reference simulator** (native/refsim.cpp via
  cop5615_gossip_protocol_tpu.native) — the runnable stand-in for
  `dotnet run N topology algorithm` in this image (no .NET runtime),
  reproducing the reference's actor semantics as a discrete-event model;
- the **TPU framework** in batched semantics — the honest synchronous-round
  mode the framework actually ships (wall-clock excludes XLA compile, which
  is reported separately; the reference's Stopwatch likewise excludes
  topology build, program.fs:175).

The semantic recast is documented in SURVEY.md §3.3: the reference's
push-sum is a single random walk, so its wall-clock measures walk cover
time, while the batched mode measures synchronous rounds — the join is
old-vs-new *capability* timing on identical (N, topology, algorithm), not a
claim that the two algorithms do identical message-by-message work.
Message-level behavioral equivalence of the reference-semantics JAX modes
against the native oracle is pinned separately by tests/test_native.py.

Usage:
  python benchmarks/compare.py 1000 line gossip
  python benchmarks/compare.py 1000 2D push-sum --seed 3
  python benchmarks/compare.py --grid          # full N<=2000 sweep, all cells
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from benchmarks import baseline_data  # noqa: E402


# Reference CLI spelling -> native refsim spelling (refsim accepts lowercase).
_NATIVE_NAME = {"line": "line", "full": "full", "2D": "2d", "Imp3D": "imp3d"}


@dataclasses.dataclass
class MatchedRow:
    """One joined old-vs-new measurement."""

    n: int
    topology: str  # reference CLI spelling
    algorithm: str
    seed: int
    akka_report_ms: float | None  # report.pdf, None off-grid
    refsim_ms: float  # native DES wall (this machine)
    refsim_events: int  # mailbox deliveries to convergence
    tpu_ms: float  # batched-mode steady-state wall
    tpu_rounds: int
    tpu_compile_s: float
    tpu_converged: bool
    tpu_us_per_round: float | None = None  # differential engine cost (see
    # engine_us_per_round) — what the engine costs per round once the
    # per-dispatch tunnel floor is subtracted out

    @property
    def speedup_vs_akka(self) -> float | None:
        if self.akka_report_ms is None or self.tpu_ms <= 0:
            return None
        return self.akka_report_ms / self.tpu_ms

    def to_record(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["speedup_vs_akka"] = self.speedup_vs_akka
        return rec


def engine_us_per_round(
    kind: str, algorithm: str, n: int, seed: int = 0,
    r1: int = 512, r2: int = 2560, **overrides,
) -> float:
    """Per-round engine cost in microseconds, with the per-dispatch launch
    floor differenced out (VERDICT r3 #8).

    A to-convergence run at small N is one chunk dispatch whose wall is
    ~110-140 ms of remote-tunnel launch plumbing regardless of rounds — it
    measures the tunnel, not the engine. Here the SAME compiled chunk runs
    twice with convergence disabled (gossip: unreachable rumor threshold;
    push-sum: unreachable term counter), executing exactly r1 and r2 rounds
    in one dispatch each; (t2 - t1) / (r2 - r1) cancels the floor and the
    compile exactly because both runs share one executable."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    no_conv = (
        {"rumor_threshold": 10**6}
        if algorithm == "gossip"
        else {"term_rounds": 1_000_000}
    )
    if n <= 65_536 and r1 == 512 and r2 == 2560:
        # Small populations: sub-us rounds need a wider budget spread to
        # rise above the tunnel's per-dispatch jitter (+-ms).
        r1, r2 = 1024, 16_384
    elif n > 64_000_000 and r1 == 512 and r2 == 2560:
        # 2^27-class rounds cost ~15 ms each; the default spread would run
        # for minutes while the differential is already thousands of x the
        # jitter at these costs.
        r1, r2 = 64, 320
    topo = build_topology(kind, n, seed=seed, semantics="batched")
    walls = []
    for cap in (r1, r2):
        cfg = SimConfig(
            n=n, topology=kind, algorithm=algorithm, semantics="batched",
            seed=seed, max_rounds=cap, chunk_rounds=max(r1, r2),
            **{**no_conv, **overrides},
        )
        best = None
        for _ in range(3):  # min-of-3: robust to dispatch jitter spikes
            res = run(topo, cfg)
            assert res.rounds == cap, (res.rounds, cap)
            best = res.run_s if best is None else min(best, res.run_s)
        walls.append(best)
    # Raw differential, deliberately UNclamped (VERDICT r3 Weak #4): at
    # small N the true per-round cost can sit below the dispatch jitter and
    # the difference may come out <= 0 — that is a statement about the
    # noise bound, not "free", and callers must render it as below-noise
    # (ENGINE_US_NOISE) rather than 0.00.
    return (walls[1] - walls[0]) / (r2 - r1) * 1e6


# Differentials below this are indistinguishable from dispatch jitter at
# the default round spreads; render as "<0.5" instead of a number.
ENGINE_US_NOISE = 0.5


def matched_run(
    n: int,
    topology: str,
    algorithm: str,
    seed: int = 0,
    max_rounds: int = 1_000_000,
) -> MatchedRow:
    """Run both sides on one matched config and join the results."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
    from cop5615_gossip_protocol_tpu import native
    from cop5615_gossip_protocol_tpu.config import normalize_topology

    if topology not in _NATIVE_NAME:
        raise ValueError(
            f"topology {topology!r} is not a reference CLI spelling; "
            f"expected one of {sorted(_NATIVE_NAME)}"
        )

    # Native side: reference semantics by construction.
    t0 = time.perf_counter()
    ref = native.refsim_run(n, _NATIVE_NAME[topology], algorithm, seed=seed)
    refsim_host_ms = (time.perf_counter() - t0) * 1e3
    if not ref.ok:
        raise RuntimeError(
            f"refsim did not converge on n={n} {topology} {algorithm} "
            f"(events={ref.events}) — cannot join an unconverged oracle run"
        )

    # TPU side: honest batched mode (the framework's real mode). "2D" maps
    # to the honest grid2d here — comparing against the reference's "2D"
    # *label*; its wiring bug (Q6) is reproduced by ref2d/tests, not re-run
    # in the perf join.
    kind = normalize_topology(topology, semantics="batched")
    cfg = SimConfig(
        n=n, topology=kind, algorithm=algorithm, semantics="batched",
        seed=seed, max_rounds=max_rounds,
    )
    topo = build_topology(kind, n, seed=seed, semantics="batched")
    result = run(topo, cfg)
    us_round = engine_us_per_round(kind, algorithm, n, seed=seed)

    return MatchedRow(
        n=n,
        topology=topology,
        algorithm=algorithm,
        seed=seed,
        akka_report_ms=baseline_data.akka_ms(topology, algorithm, n),
        refsim_ms=ref.wall_ms if ref.wall_ms > 0 else refsim_host_ms,
        refsim_events=ref.events,
        tpu_ms=result.wall_ms,
        tpu_rounds=result.rounds,
        tpu_compile_s=result.compile_s,
        tpu_converged=result.converged,
        tpu_us_per_round=us_round,
    )


def _fmt(x, nd=2, none="—"):
    return none if x is None else f"{x:,.{nd}f}"


HEADER = (
    "| N | topology | algorithm | Akka report (ms) | refsim native (ms) "
    "| gossip-tpu (ms) | tpu rounds | speedup vs Akka |"
)
RULE = "|---|---|---|---|---|---|---|---|"


def row_markdown(r: MatchedRow) -> str:
    return (
        f"| {r.n} | {r.topology} | {r.algorithm} | {_fmt(r.akka_report_ms)} "
        f"| {_fmt(r.refsim_ms)} | {_fmt(r.tpu_ms)} | {r.tpu_rounds} "
        f"| {_fmt(r.speedup_vs_akka, 1)}{'' if r.speedup_vs_akka is None else 'x'} |"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("n", type=int, nargs="?")
    ap.add_argument("topology", nargs="?", help="line | full | 2D | Imp3D")
    ap.add_argument("algorithm", nargs="?", help="gossip | push-sum")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--grid", action="store_true",
                    help="sweep the full report.pdf grid (N<=1000, 8 cells/N)")
    ap.add_argument("--platform", choices=["auto", "cpu"], default="auto")
    ap.add_argument("--jsonl", type=str, default=None)
    args = ap.parse_args(argv)

    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.grid:
        configs = [
            (n, topo, algo)
            for algo in ("gossip", "push-sum")
            for topo in baseline_data.REF_TOPOLOGIES
            for n in baseline_data.GRID_N
        ]
    else:
        if args.n is None or args.topology is None or args.algorithm is None:
            ap.error("need `N topology algorithm` or --grid")
        configs = [(args.n, args.topology, args.algorithm)]

    print(HEADER)
    print(RULE)
    for n, topo, algo in configs:
        row = matched_run(n, topo, algo, seed=args.seed)
        print(row_markdown(row), flush=True)
        if args.jsonl:
            with open(args.jsonl, "a") as f:
                f.write(json.dumps(row.to_record()) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
