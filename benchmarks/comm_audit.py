"""Comm audit — collectives per round/super-step, counted from the jaxpr.

A comm-volume regression (an engine quietly re-growing per-plane wires, a
collective slipping inside the hot loop) historically only surfaced as an
on-chip ms/round drift — which needs a TPU session to even notice. This
tool walks the jitted chunk program of each sharded engine (the engines
expose it through their ``probe`` hook — the program is TRACED, never
executed, so the audit runs in seconds on CPU) and reports, per engine x
topology x overlap schedule:

- collectives INSIDE the chunk's while body — the per-round (chunked
  engine) / per-super-step (fused compositions) steady-state cost;
- collectives OUTSIDE the body — per-dispatch setup (the overlap
  schedule's pre-loop exchange and drain psum live here);
- payload bytes per collective class (operand aval sizes).

tests/test_comm_audit.py pins the counts, so a regression fails tier-1 on
CPU without needing a TPU — including the tentpole pin that the batched
halo wire is exactly ONE ppermute pair per super-step (down from one pair
per plane per class).

Usage:
  python benchmarks/comm_audit.py                # markdown table to stdout
  python benchmarks/comm_audit.py --json FILE    # CI artifact
  python benchmarks/comm_audit.py --quick        # XLA engines only (skip
                                                 # the fused-composition
                                                 # traces, ~seconds)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

COLLECTIVE_PRIMS = (
    "ppermute", "psum", "all_gather", "reduce_scatter", "all_to_all",
)


@dataclasses.dataclass
class AuditReport:
    """Collective counts for one engine x config x schedule."""

    engine: str
    topology: str
    algorithm: str
    n: int
    n_devices: int
    overlap: bool
    # {"body": {prim: {"count": int, "bytes": int}}, "setup": {...}} —
    # "body" is inside the chunk's while loop (per round / super-step),
    # "setup" is the rest of the dispatch (paid once per chunk).
    counts: dict

    def body_count(self, prim: str) -> int:
        return self.counts["body"].get(prim, {}).get("count", 0)

    def setup_count(self, prim: str) -> int:
        return self.counts["setup"].get(prim, {}).get("count", 0)

    def body_bytes(self, prim: str) -> int:
        return self.counts["body"].get(prim, {}).get("bytes", 0)

    def to_record(self) -> dict:
        return dataclasses.asdict(self)


def _aval_bytes(aval) -> int:
    try:
        import numpy as np

        return int(np.prod(aval.shape)) * aval.dtype.itemsize
    except Exception:  # noqa: BLE001 — abstract tokens etc. carry no bytes
        return 0


def _sub_jaxprs(eqn):
    """(jaxpr, enters_loop_body) for every sub-jaxpr of an eqn. A while
    loop's cond and body both run once per iteration, so both count as
    loop-body regions; everything else inherits the caller's region."""
    for name, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for v in vals:
            jx = getattr(v, "jaxpr", None)
            if jx is not None:
                yield jx, eqn.primitive.name == "while"
            elif hasattr(v, "eqns"):
                yield v, eqn.primitive.name == "while"


def _walk(jaxpr, counts: dict, in_body: bool) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            region = counts["body" if in_body else "setup"]
            slot = region.setdefault(name, {"count": 0, "bytes": 0})
            slot["count"] += 1
            slot["bytes"] += sum(_aval_bytes(v.aval) for v in eqn.invars)
        for sub, enters_body in _sub_jaxprs(eqn):
            _walk(sub, counts, in_body or enters_body)


def count_collectives(fn, args) -> dict:
    """Trace ``fn(*args)`` to a jaxpr and count collective primitives by
    region (inside/outside while bodies). Never executes the program."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    counts = {"body": {}, "setup": {}}
    _walk(jaxpr.jaxpr, counts, False)
    return counts


# --- engine probes ---------------------------------------------------------


def _probe(counts_sink):
    def probe(chunk_fn, args):
        counts_sink.update(count_collectives(chunk_fn, args))
        return None

    return probe


def audit_engine(engine: str, topology: str, algorithm: str, n: int,
                 n_devices: int, overlap: bool,
                 cfg_overrides: dict | None = None) -> AuditReport:
    """Build one sharded engine's jitted chunk through its run function's
    ``probe`` hook and count its collectives. ``engine`` is one of
    'sharded' (chunked XLA), 'fused-sharded' (VMEM lattice composition),
    'fused-pool-sharded', 'hbm-sharded'."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

    cfg = SimConfig(
        n=n, topology=topology, algorithm=algorithm,
        overlap_collectives=overlap, **(cfg_overrides or {}),
    )
    topo = build_topology(topology, n)
    mesh = make_mesh(n_devices)
    counts: dict = {}
    probe = _probe(counts)
    if engine == "sharded":
        from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

        run_sharded(topo, cfg, mesh=mesh, probe=probe)
    elif engine == "fused-sharded":
        from cop5615_gossip_protocol_tpu.parallel.fused_sharded import (
            run_fused_sharded,
        )

        run_fused_sharded(topo, cfg, mesh=mesh, probe=probe)
    elif engine == "fused-pool-sharded":
        from cop5615_gossip_protocol_tpu.parallel.fused_pool_sharded import (
            run_fused_pool_sharded,
        )

        run_fused_pool_sharded(topo, cfg, mesh=mesh, probe=probe)
    elif engine == "hbm-sharded":
        from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
            run_stencil_hbm_sharded,
        )

        run_stencil_hbm_sharded(topo, cfg, mesh=mesh, probe=probe)
    else:
        raise ValueError(f"unknown engine {engine!r}")
    return AuditReport(
        engine=engine, topology=topology, algorithm=algorithm, n=n,
        n_devices=n_devices, overlap=overlap, counts=counts,
    )


# (engine, topology, algorithm, n, n_devices, extra cfg) — the audited
# grid. Populations are the smallest each composition's plan accepts; the
# counts are shape-independent (the jaxpr structure is), so small is right.
AUDIT_GRID = (
    ("sharded", "torus3d", "gossip", 4096, 8, {}),
    ("sharded", "torus3d", "push-sum", 4096, 8, {}),
    ("sharded", "full", "push-sum", 1024, 8, {"delivery": "pool"}),
    # Non-divisible ring: no exact halo plan -> scatter + reduce-scatter
    # fallback (wire batching does not apply; audited for the record).
    ("sharded", "ring", "gossip", 1001, 8, {}),
    ("fused-sharded", "torus3d", "gossip", 131072, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("fused-sharded", "torus3d", "push-sum", 131072, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("fused-pool-sharded", "full", "gossip", 131072, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("fused-pool-sharded", "full", "push-sum", 131072, 2,
     {"engine": "fused", "delivery": "pool"}),
    # 125000 (the interpret-suite torus), not the 2^24 flagship: the jaxpr
    # structure — and hence every count — is population-independent, and
    # the smaller planes keep the CI trace in seconds.
    ("hbm-sharded", "torus3d", "gossip", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("hbm-sharded", "torus3d", "push-sum", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8}),
)


def _fmt_bytes(b: int) -> str:
    if b >= 1 << 20:
        return f"{b / (1 << 20):.1f} MiB"
    if b >= 1 << 10:
        return f"{b / (1 << 10):.1f} KiB"
    return f"{b} B"


def table(reports) -> list[str]:
    out = [
        "| engine | topology | algorithm | overlap | ppermute/step "
        "| psum/step | all_gather/step | reduce_scatter/step "
        "| wire bytes/step | setup collectives |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in reports:
        wire_bytes = sum(
            r.body_bytes(p)
            for p in ("ppermute", "all_gather", "reduce_scatter")
        )
        setup = sum(r.setup_count(p) for p in COLLECTIVE_PRIMS)
        out.append(
            f"| {r.engine} | {r.topology} | {r.algorithm} "
            f"| {'on' if r.overlap else 'off'} "
            f"| {r.body_count('ppermute')} | {r.body_count('psum')} "
            f"| {r.body_count('all_gather')} "
            f"| {r.body_count('reduce_scatter')} "
            f"| {_fmt_bytes(wire_bytes)} | {setup} |"
        )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", type=str, default=None, metavar="FILE",
                    help="write the reports as JSONL (CI artifact)")
    ap.add_argument("--quick", action="store_true",
                    help="XLA chunked engine only (skip the fused-"
                    "composition traces)")
    ap.add_argument("--devices", type=int, default=None,
                    help="override the audited mesh sizes (XLA rows only)")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from cop5615_gossip_protocol_tpu.utils import compat

    jax.config.update("jax_threefry_partitionable", True)
    need = max(
        args.devices or 0,
        max(g[4] for g in AUDIT_GRID),
    )
    compat.set_host_device_count(need)

    reports = []
    for engine, topo, algo, n, n_dev, extra in AUDIT_GRID:
        if args.quick and engine != "sharded":
            continue
        if args.devices and engine == "sharded":
            n_dev = args.devices
        for overlap in (True, False):
            r = audit_engine(engine, topo, algo, n, n_dev, overlap, extra)
            reports.append(r)
            print(
                f"[comm_audit] {engine}/{topo}/{algo} overlap="
                f"{'on' if overlap else 'off'}: "
                f"body ppermute={r.body_count('ppermute')} "
                f"psum={r.body_count('psum')} "
                f"all_gather={r.body_count('all_gather')} "
                f"reduce_scatter={r.body_count('reduce_scatter')}",
                file=sys.stderr, flush=True,
            )

    print("\n".join(
        ["# Comm audit — collectives per round/super-step", ""]
        + table(reports)
    ))
    if args.json:
        with open(args.json, "w") as f:
            for r in reports:
                f.write(json.dumps(r.to_record()) + "\n")
        print(f"[comm_audit] wrote {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
