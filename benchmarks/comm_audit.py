"""Comm audit — collectives per round/super-step, counted from the jaxpr.

A comm-volume regression (an engine quietly re-growing per-plane wires, a
collective slipping inside the hot loop) historically only surfaced as an
on-chip ms/round drift — which needs a TPU session to even notice. This
tool walks the jitted chunk program of each sharded engine (the engines
expose it through their ``probe`` hook — the program is TRACED, never
executed, so the audit runs in seconds on CPU) and reports, per engine x
topology x overlap schedule:

- collectives INSIDE the chunk's while body — the per-round (chunked
  engine) / per-super-step (fused compositions) steady-state cost;
- collectives OUTSIDE the body — per-dispatch setup (the overlap
  schedule's pre-loop exchange and drain psum live here);
- IN-KERNEL remote DMAs (``pltpu.make_async_remote_copy`` starts inside
  Pallas kernels — the walker descends into pallas_call jaxprs and
  classifies ``dma_start`` by its device_id operand), so the ISSUE 9
  "zero XLA collectives on the halo path" claim is a counted fact: the
  halo-delivery MECHANISM column reports in-kernel-dma vs xla-ppermute
  vs all-gather vs scatter per composition;
- payload bytes per collective class (operand aval sizes; remote DMAs
  report the sliced transfer size).

tests/test_comm_audit.py pins the counts, so a regression fails tier-1 on
CPU without needing a TPU — including the tentpole pins that the batched
halo wire is exactly ONE ppermute pair per super-step and that the DMA
transport keeps ZERO XLA collectives on the halo path (the remote-DMA
kernel is traced hardware-free through the probe hook with
halo_dma='on').

Usage:
  python benchmarks/comm_audit.py                # markdown table to stdout
  python benchmarks/comm_audit.py --json FILE    # CI artifact
  python benchmarks/comm_audit.py --quick        # XLA engines only (skip
                                                 # the fused-composition
                                                 # traces, ~seconds)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

COLLECTIVE_PRIMS = (
    "ppermute", "psum", "all_gather", "reduce_scatter", "all_to_all",
)

# Pseudo-collective: an in-kernel async remote copy (neighbor DMA). Not an
# XLA collective — counted separately so the mechanism column can assert
# the halo path carries NO XLA collective while still shipping bytes.
REMOTE_DMA = "remote_dma"


@dataclasses.dataclass
class AuditReport:
    """Collective counts for one engine x config x schedule."""

    engine: str
    topology: str
    algorithm: str
    n: int
    n_devices: int
    overlap: bool
    # {"body": {prim: {"count": int, "bytes": int}}, "setup": {...}} —
    # "body" is inside the chunk's while loop (per round / super-step),
    # "setup" is the rest of the dispatch (paid once per chunk).
    counts: dict

    def body_count(self, prim: str) -> int:
        return self.counts["body"].get(prim, {}).get("count", 0)

    def setup_count(self, prim: str) -> int:
        return self.counts["setup"].get(prim, {}).get("count", 0)

    def body_bytes(self, prim: str) -> int:
        return self.counts["body"].get(prim, {}).get("bytes", 0)

    def halo_mechanism(self) -> str:
        """How this composition's halo/delivery bytes move between
        devices, decided from the counted program — never from config:
        in-kernel-dma (Pallas async remote copies, zero XLA collectives
        on the halo path), xla-ppermute (halo boundary wires),
        all-gather (the pool composition's plane gather), scatter
        (reduce_scatter fallback), or none (no inter-device delivery in
        the body)."""
        if self.body_count(REMOTE_DMA):
            return "in-kernel-dma"
        if self.body_count("ppermute"):
            return "xla-ppermute"
        if self.body_count("all_gather"):
            return "all-gather"
        if self.body_count("reduce_scatter"):
            return "scatter"
        return "none"

    def to_record(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["halo_mechanism"] = self.halo_mechanism()
        return rec


def _aval_bytes(aval) -> int:
    try:
        import numpy as np

        return int(np.prod(aval.shape)) * aval.dtype.itemsize
    except Exception:  # noqa: BLE001 — abstract tokens etc. carry no bytes
        return 0


def _sub_jaxprs(eqn):
    """(jaxpr, enters_loop_body) for every sub-jaxpr of an eqn. A while
    loop's cond and body both run once per iteration, so both count as
    loop-body regions; everything else inherits the caller's region."""
    for name, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for v in vals:
            jx = getattr(v, "jaxpr", None)
            if jx is not None:
                yield jx, eqn.primitive.name == "while"
            elif hasattr(v, "eqns"):
                yield v, eqn.primitive.name == "while"


def _remote_dma_info(eqn):
    """(is_remote, bytes) for a Pallas ``dma_start`` eqn. The primitive's
    flat operands unflatten through its ``tree`` param into (src_ref,
    src_transforms, dst_ref, dst_transforms, sems...); a REMOTE copy
    carries a non-empty device_id leaf at the tail, a local HBM<->VMEM
    copy carries None. Bytes = the sliced source shape (the NDIndexer's
    static slice sizes) x itemsize; 0 when the indexer cannot be sized."""
    import jax

    try:
        tup = jax.tree_util.tree_unflatten(eqn.params["tree"], eqn.invars)
    except Exception:  # noqa: BLE001 — unfamiliar tree layout
        return False, 0
    dev = tup[-1]
    if dev is None or dev == ():
        return False, 0
    size = 0
    try:
        src, src_transforms = tup[0], tup[1]
        import numpy as np

        shape = None
        for tr in src_transforms or ():
            get_shape = getattr(tr, "get_indexer_shape", None)
            if get_shape is not None:
                shape = tuple(get_shape())
        if shape is None:
            shape = tuple(src.aval.shape)
        size = int(np.prod(shape)) * src.aval.dtype.itemsize
    except Exception:  # noqa: BLE001 — bytes are best-effort
        size = 0
    return True, size


def _walk(jaxpr, counts: dict, in_body: bool) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            region = counts["body" if in_body else "setup"]
            slot = region.setdefault(name, {"count": 0, "bytes": 0})
            slot["count"] += 1
            slot["bytes"] += sum(_aval_bytes(v.aval) for v in eqn.invars)
        elif name == "dma_start":
            remote, size = _remote_dma_info(eqn)
            if remote:
                region = counts["body" if in_body else "setup"]
                slot = region.setdefault(
                    REMOTE_DMA, {"count": 0, "bytes": 0}
                )
                slot["count"] += 1
                slot["bytes"] += size
        for sub, enters_body in _sub_jaxprs(eqn):
            _walk(sub, counts, in_body or enters_body)


def count_collectives(fn, args) -> dict:
    """Trace ``fn(*args)`` to a jaxpr and count collective primitives by
    region (inside/outside while bodies). Never executes the program."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    counts = {"body": {}, "setup": {}}
    _walk(jaxpr.jaxpr, counts, False)
    return counts


# --- engine probes ---------------------------------------------------------


def _probe(counts_sink):
    def probe(chunk_fn, args):
        counts_sink.update(count_collectives(chunk_fn, args))
        return None

    return probe


def audit_engine(engine: str, topology: str, algorithm: str, n: int,
                 n_devices: int, overlap: bool,
                 cfg_overrides: dict | None = None) -> AuditReport:
    """Build one sharded engine's jitted chunk through its run function's
    ``probe`` hook and count its collectives. ``engine`` is one of
    'sharded' (chunked XLA), 'fused-sharded' (VMEM lattice composition),
    'fused-pool-sharded', 'hbm-sharded'."""
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

    cfg = SimConfig(
        n=n, topology=topology, algorithm=algorithm,
        overlap_collectives=overlap, **(cfg_overrides or {}),
    )
    topo = build_topology(topology, n)
    mesh = make_mesh(n_devices)
    counts: dict = {}
    probe = _probe(counts)
    if engine == "sharded":
        from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

        run_sharded(topo, cfg, mesh=mesh, probe=probe)
    elif engine == "fused-sharded":
        from cop5615_gossip_protocol_tpu.parallel.fused_sharded import (
            run_fused_sharded,
        )

        run_fused_sharded(topo, cfg, mesh=mesh, probe=probe)
    elif engine == "fused-pool-sharded":
        from cop5615_gossip_protocol_tpu.parallel.fused_pool_sharded import (
            run_fused_pool_sharded,
        )

        run_fused_pool_sharded(topo, cfg, mesh=mesh, probe=probe)
    elif engine == "hbm-sharded":
        from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
            run_stencil_hbm_sharded,
        )

        run_stencil_hbm_sharded(topo, cfg, mesh=mesh, probe=probe)
    elif engine == "imp-hbm-sharded":
        from cop5615_gossip_protocol_tpu.parallel.fused_imp_hbm_sharded import (
            run_imp_hbm_sharded,
        )

        run_imp_hbm_sharded(topo, cfg, mesh=mesh, probe=probe)
    elif engine == "pool2-sharded":
        from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
            run_pool2_sharded,
        )

        run_pool2_sharded(topo, cfg, mesh=mesh, probe=probe)
    else:
        raise ValueError(f"unknown engine {engine!r}")
    return AuditReport(
        engine=engine, topology=topology, algorithm=algorithm, n=n,
        n_devices=n_devices, overlap=overlap, counts=counts,
    )


# (engine, topology, algorithm, n, n_devices, extra cfg) — the audited
# grid. Populations are the smallest each composition's plan accepts; the
# counts are shape-independent (the jaxpr structure is), so small is right.
AUDIT_GRID = (
    ("sharded", "torus3d", "gossip", 4096, 8, {}),
    ("sharded", "torus3d", "push-sum", 4096, 8, {}),
    ("sharded", "full", "push-sum", 1024, 8, {"delivery": "pool"}),
    # Non-divisible ring: no exact halo plan -> scatter + reduce-scatter
    # fallback (wire batching does not apply; audited for the record).
    ("sharded", "ring", "gossip", 1001, 8, {}),
    ("fused-sharded", "torus3d", "gossip", 131072, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("fused-sharded", "torus3d", "push-sum", 131072, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("fused-pool-sharded", "full", "gossip", 131072, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("fused-pool-sharded", "full", "push-sum", 131072, 2,
     {"engine": "fused", "delivery": "pool"}),
    # 125000 (the interpret-suite torus), not the 2^24 flagship: the jaxpr
    # structure — and hence every count — is population-independent, and
    # the smaller planes keep the CI trace in seconds.
    ("hbm-sharded", "torus3d", "gossip", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    ("hbm-sharded", "torus3d", "push-sum", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8}),
    # The in-kernel-DMA halo transport (ISSUE 9): halo_dma='on' builds the
    # async-remote-copy kernel, which the probe hook TRACES hardware-free
    # — the audit pins zero XLA collectives on the halo path (the psum is
    # the deferred termination verdict, not halo delivery).
    ("hbm-sharded", "torus3d", "gossip", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8, "halo_dma": "on"}),
    ("hbm-sharded", "torus3d", "push-sum", 125000, 2,
     {"engine": "fused", "chunk_rounds": 8, "halo_dma": "on"}),
    # imp x HBM x sharded (ISSUE 10): the lattice classes ride the halo
    # wire (ppermute pair / in-kernel DMA), the pooled long-range classes
    # ONE all_gather of the windowed send summaries per super-step.
    ("imp-hbm-sharded", "imp3d", "gossip", 27000, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("imp-hbm-sharded", "imp3d", "push-sum", 27000, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("imp-hbm-sharded", "imp3d", "gossip", 27000, 2,
     {"engine": "fused", "delivery": "pool", "halo_dma": "on"}),
    ("imp-hbm-sharded", "imp3d", "push-sum", 27000, 2,
     {"engine": "fused", "delivery": "pool", "halo_dma": "on"}),
    # Replicated-pool2 (ISSUE 10): the full topology past one chip's HBM —
    # the ONLY wire is the all_gather of the compact send summaries (plus
    # the termination psum); zero ppermutes, zero stragglers.
    ("pool2-sharded", "full", "gossip", 262144, 2,
     {"engine": "fused", "delivery": "pool"}),
    ("pool2-sharded", "full", "push-sum", 262144, 2,
     {"engine": "fused", "delivery": "pool"}),
)


def _fmt_bytes(b: int) -> str:
    if b >= 1 << 20:
        return f"{b / (1 << 20):.1f} MiB"
    if b >= 1 << 10:
        return f"{b / (1 << 10):.1f} KiB"
    return f"{b} B"


def table(reports) -> list[str]:
    out = [
        "| engine | topology | algorithm | overlap | mechanism "
        "| ppermute/step | psum/step | all_gather/step "
        "| reduce_scatter/step | remote dma/step | wire bytes/step "
        "| setup collectives |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in reports:
        wire_bytes = sum(
            r.body_bytes(p)
            for p in ("ppermute", "all_gather", "reduce_scatter",
                      REMOTE_DMA)
        )
        setup = sum(r.setup_count(p) for p in COLLECTIVE_PRIMS)
        out.append(
            f"| {r.engine} | {r.topology} | {r.algorithm} "
            f"| {'on' if r.overlap else 'off'} "
            f"| {r.halo_mechanism()} "
            f"| {r.body_count('ppermute')} | {r.body_count('psum')} "
            f"| {r.body_count('all_gather')} "
            f"| {r.body_count('reduce_scatter')} "
            f"| {r.body_count(REMOTE_DMA)} "
            f"| {_fmt_bytes(wire_bytes)} | {setup} |"
        )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", type=str, default=None, metavar="FILE",
                    help="write the reports as JSONL (CI artifact)")
    ap.add_argument("--quick", action="store_true",
                    help="XLA chunked engine only (skip the fused-"
                    "composition traces)")
    ap.add_argument("--devices", type=int, default=None,
                    help="override the audited mesh sizes (XLA rows only)")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from cop5615_gossip_protocol_tpu.utils import compat

    jax.config.update("jax_threefry_partitionable", True)
    need = max(
        args.devices or 0,
        max(g[4] for g in AUDIT_GRID),
    )
    compat.set_host_device_count(need)

    reports = []
    for engine, topo, algo, n, n_dev, extra in AUDIT_GRID:
        if args.quick and engine != "sharded":
            continue
        if args.devices and engine == "sharded":
            n_dev = args.devices
        for overlap in (True, False):
            r = audit_engine(engine, topo, algo, n, n_dev, overlap, extra)
            reports.append(r)
            print(
                f"[comm_audit] {engine}/{topo}/{algo} overlap="
                f"{'on' if overlap else 'off'} "
                f"mech={r.halo_mechanism()}: "
                f"body ppermute={r.body_count('ppermute')} "
                f"psum={r.body_count('psum')} "
                f"all_gather={r.body_count('all_gather')} "
                f"reduce_scatter={r.body_count('reduce_scatter')} "
                f"remote_dma={r.body_count(REMOTE_DMA)}",
                file=sys.stderr, flush=True,
            )

    print("\n".join(
        ["# Comm audit — collectives per round/super-step", ""]
        + table(reports)
    ))
    if args.json:
        with open(args.json, "w") as f:
            for r in reports:
                f.write(json.dumps(r.to_record()) + "\n")
        print(f"[comm_audit] wrote {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
