"""Comm audit — collectives per round/super-step, counted from the jaxpr.

A comm-volume regression (an engine quietly re-growing per-plane wires, a
collective slipping inside the hot loop) historically only surfaced as an
on-chip ms/round drift — which needs a TPU session to even notice. This
tool reports, per engine x topology x overlap schedule, the collectives
inside the chunk's while body (per round / super-step), the per-dispatch
setup collectives, the in-kernel remote-DMA counts, the payload bytes,
and the halo-delivery MECHANISM column (in-kernel-dma vs xla-ppermute vs
all-gather vs scatter) — all from TRACED programs, never executed.

Since ISSUE 11 this is a thin CLI over the static-auditor package: the
region-aware jaxpr walker lives in
``cop5615_gossip_protocol_tpu/analysis/jaxpr_walk.py`` (pallas_call
descent + ``dma_start`` device-id classification included), the probe-hook
tracing in ``analysis/trace.py``, and the audited grid in
``analysis/matrix.AUDIT_GRID``. The expected counts are DECLARED by each
composition as a ``WIRE_SPEC`` (analysis/wire_specs.py);
tests/test_comm_audit.py pins declaration <-> trace agreement, and
``python -m cop5615_gossip_protocol_tpu.analysis`` audits the whole
matrix (wire counts + host-sync + dtype + donation + PRNG tags + lints).

Usage:
  python benchmarks/comm_audit.py                # markdown table to stdout
  python benchmarks/comm_audit.py --json FILE    # CI artifact
  python benchmarks/comm_audit.py --quick        # XLA engines only (skip
                                                 # the fused-composition
                                                 # traces, ~seconds)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from cop5615_gossip_protocol_tpu.analysis.jaxpr_walk import (  # noqa: E402,F401
    COLLECTIVE_PRIMS,
    REMOTE_DMA,
    WIRE_PRIMS,
    body_recv_bytes,
    body_wire_bytes,
    count_collectives,
)
from cop5615_gossip_protocol_tpu.analysis.matrix import AUDIT_GRID  # noqa: E402
from cop5615_gossip_protocol_tpu.analysis.trace import (  # noqa: E402,F401
    AuditReport,
    audit_engine,
)


def _fmt_bytes(b: int) -> str:
    if b >= 1 << 20:
        return f"{b / (1 << 20):.1f} MiB"
    if b >= 1 << 10:
        return f"{b / (1 << 10):.1f} KiB"
    return f"{b} B"


def table(reports) -> list[str]:
    # "wire bytes" sums the operand payloads each device FEEDS the
    # collectives; "recv bytes" the result payloads each device RECEIVES
    # (the output avals) — the honest column for asymmetric collectives:
    # an all_gather receives the n_dev-wide copy, a reduce_scatter only
    # the local shard. The replicated-pool2 O(N) -> O(N/P + margins)
    # band-wire delta (ISSUE 15) shows up in recv bytes. Both columns are
    # computed by the shared jaxpr_walk reducers — the same formula the
    # cost model's wire term uses (ISSUE 17).
    out = [
        "| engine | topology | algorithm | overlap | mechanism "
        "| ppermute/step | psum/step | all_gather/step "
        "| reduce_scatter/step | remote dma/step | wire bytes/step "
        "| recv bytes/step | setup collectives |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in reports:
        wire_bytes = body_wire_bytes(r.counts)
        recv_bytes = body_recv_bytes(r.counts)
        setup = sum(r.setup_count(p) for p in COLLECTIVE_PRIMS)
        out.append(
            f"| {r.engine} | {r.topology} | {r.algorithm} "
            f"| {'on' if r.overlap else 'off'} "
            f"| {r.halo_mechanism()} "
            f"| {r.body_count('ppermute')} | {r.body_count('psum')} "
            f"| {r.body_count('all_gather')} "
            f"| {r.body_count('reduce_scatter')} "
            f"| {r.body_count(REMOTE_DMA)} "
            f"| {_fmt_bytes(wire_bytes)} | {_fmt_bytes(recv_bytes)} "
            f"| {setup} |"
        )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", type=str, default=None, metavar="FILE",
                    help="write the reports as JSONL (CI artifact)")
    ap.add_argument("--quick", action="store_true",
                    help="XLA chunked engine only (skip the fused-"
                    "composition traces)")
    ap.add_argument("--devices", type=int, default=None,
                    help="override the audited mesh sizes (XLA rows only)")
    args = ap.parse_args(argv)

    from cop5615_gossip_protocol_tpu.analysis.matrix import (
        setup_tracing_runtime,
    )

    setup_tracing_runtime(extra_devices=args.devices or 0)

    reports = []
    for engine, topo, algo, n, n_dev, extra in AUDIT_GRID:
        if args.quick and engine != "sharded":
            continue
        if args.devices and engine == "sharded":
            n_dev = args.devices
        for overlap in (True, False):
            r = audit_engine(engine, topo, algo, n, n_dev, overlap, extra)
            reports.append(r)
            print(
                f"[comm_audit] {engine}/{topo}/{algo} overlap="
                f"{'on' if overlap else 'off'} "
                f"mech={r.halo_mechanism()}: "
                f"body ppermute={r.body_count('ppermute')} "
                f"psum={r.body_count('psum')} "
                f"all_gather={r.body_count('all_gather')} "
                f"reduce_scatter={r.body_count('reduce_scatter')} "
                f"remote_dma={r.body_count(REMOTE_DMA)}",
                file=sys.stderr, flush=True,
            )

    print("\n".join(
        ["# Comm audit — collectives per round/super-step", ""]
        + table(reports)
    ))
    if args.json:
        with open(args.json, "w") as f:
            for r in reports:
                f.write(json.dumps(r.to_record()) + "\n")
        print(f"[comm_audit] wrote {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
