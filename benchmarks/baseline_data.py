"""The reference's published benchmark record as data.

Transcribed from report.pdf p.4-5 (digested in BASELINE.md) — the only
numbers the reference ever published. Hardware unspecified (personal Windows
machine, .NET Core 3.1, Akka.NET 1.4.25, single process); metric is
wall-clock convergence time in ms as printed by the parent actor
(program.fs:51-52, 58-59), timed from protocol kickoff to the N-th
convergence report.

Topology names use the reference CLI spellings (program.fs:150):
line / full / 2D / Imp3D.
"""

from __future__ import annotations

GRID_N = (20, 100, 200, 300, 400, 500, 600, 700, 800, 900, 1000)

REF_TOPOLOGIES = ("line", "full", "2D", "Imp3D")

# report.pdf p.4 — gossip convergence time (ms). The Imp3D value at N=1000
# duplicates the 2D cell and contradicts the Imp3D trend (~500 ms); kept
# verbatim, flagged in BASELINE.md as a likely report typo.
AKKA_GOSSIP_MS = {
    "line": dict(zip(GRID_N, (20.68, 129.49, 436.40, 875.73, 1992.27, 2618.29,
                              3214.54, 7548.45, 5522.17, 6626.31, 7322.90))),
    "full": dict(zip(GRID_N, (18.97, 27.61, 152.29, 150.24, 212.32, 267.38,
                              367.72, 522.16, 1553.60, 828.07, 1167.20))),
    "2D": dict(zip(GRID_N, (20.11, 116.36, 860.62, 1063.35, 1092.14, 3226.73,
                            4851.94, 5207.95, 9621.80, 12614.34, 12203.49))),
    "Imp3D": dict(zip(GRID_N, (30.04, 33.91, 27.16, 153.85, 130.73, 124.69,
                               271.62, 261.95, 547.16, 519.38, 12203.49))),
}

# report.pdf p.5 — push-sum convergence time (ms).
AKKA_PUSHSUM_MS = {
    "line": dict(zip(GRID_N, (74.78, 2717.23, 8695.51, 15517.12, 13251.76,
                              14271.60, 38139.77, 26987.17, 54484.09,
                              32632.50, 147447.74))),
    "full": dict(zip(GRID_N, (19.83, 25.84, 46.13, 105.55, 85.54, 112.69,
                              148.56, 130.43, 151.46, 261.58, 418.63))),
    "2D": dict(zip(GRID_N, (134.88, 1360.50, 15806.46, 11654.63, 23125.06,
                            33201.60, 89039.30, 58778.68, 89820.94, 4738.33,
                            26818.37))),
    "Imp3D": dict(zip(GRID_N, (27.06, 140.76, 119.85, 128.65, 232.29, 174.68,
                               302.16, 286.17, 531.63, 434.52, 541.43))),
}

# report.pdf p.3 §4 — largest network size the reference handled.
AKKA_MAX_N = {
    ("full", "gossip"): 2000, ("full", "push-sum"): 2000,
    ("2D", "gossip"): 1100, ("2D", "push-sum"): 1000,
    ("line", "gossip"): 1200, ("line", "push-sum"): 1000,
    ("Imp3D", "gossip"): 2000, ("Imp3D", "push-sum"): 2000,
}


def akka_ms(topology: str, algorithm: str, n: int) -> float | None:
    """Reference wall-clock for a grid cell, or None if the report has no
    number for that config."""
    table = AKKA_GOSSIP_MS if algorithm == "gossip" else AKKA_PUSHSUM_MS
    return table.get(topology, {}).get(n)
