"""Roofline accounting per engine (VERDICT r3 #4).

For each delivery/engine tier at a representative population this module
measures the per-round cost on the real chip (differential fixed-round
timing — benchmarks/compare.engine_us_per_round — so launch plumbing and
compile cancel exactly) and sets it against a documented LOWER-BOUND model
of the algorithmic HBM bytes each round must move. Implied bandwidth over
the v5e's 819 GB/s HBM roofline classifies each tier:

- **HBM-streaming** tiers (chunked XLA paths, the pool2 engine) are judged
  by % of roofline; anything far under it is explained (XLA materializes
  intermediates the model's fused lower bound does not);
- **VMEM-resident** tiers (the fused engines) move ~zero HBM bytes per
  round by design — their per-round cost is VPU-op-bound, and the table
  reports the implied VMEM-traffic bandwidth instead (v5e VMEM feeds the
  VPU at multiple TB/s, so these rows sit far above the HBM roofline —
  that is the point of the engines);
- **addressing-bound** tiers (sort-based scatter on static irregular
  edges) are bounded by the chip's per-element dynamic-address cost —
  measured at ~8-12 ns/element across every formulation tried (XLA
  gather/scatter, sorted static-index scatter, inverse-table gathers,
  Pallas per-edge loops; see the r3 microbenchmark series) — not by
  bandwidth; the model reports that floor instead.

Byte models (per node per round, f32=4B planes; lower bounds assume
perfect producer-consumer fusion — one read per consumed plane, one write
per produced plane):

- chunked stencil push-sum, C displacement classes: state r/w (s,w,term,
  conv) 32 B + C masked-roll passes reading both send channels, 8C B.
- chunked pool push-sum, K slots: 32 B state + 8K B roll reads + ~1 B
  packed choice words.
- pool2 push-sum, K slots: p1 reads s,w (8) and writes ds,dw,choice (12);
  p2 reads K windows of 3 planes (12K), own state (16), writes state (16)
  — 52 + 12K B (the module docstring's accounting).
- VMEM-resident engines: HBM ~0; VMEM traffic estimated as the kernel's
  plane passes (reported for context, not judged against the HBM roof).

Usage: python benchmarks/roofline.py  (requires the TPU; ~2-3 min)
Emits the markdown section BENCH_TABLES.md embeds.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

HBM_ROOF_GBS = 819.0  # v5e chip HBM bandwidth
# v5e VPU 32-bit elementwise issue roof: 8 sublanes x 128 lanes x 4 ALUs
# x ~940 MHz ~ 3.85 T ops/s. The ops models below count vector ops per
# node per round (threefry words amortized over their packing) — a +-30%
# estimate whose job is classifying rows as issue-bound vs
# latency/slice-bound, not precision.
VPU_ROOF_OPS = 3.85e12
# v5e MXU roof for the matmul tier's f32-accumulate one-hot contractions:
# the chip's 197 TFLOPs is the bf16 systolic peak; f32-accumulate one-hot
# work lands near a quarter of it. Like VPU_ROOF_OPS this is a
# CLASSIFICATION constant (which unit a row roofs against), not a
# precision claim.
MXU_ROOF_FLOPS = 4.9e13

# (label, kind, algorithm, n, cfg overrides, bound class,
#  model bytes/node/round or None, model VPU ops/node/round or None,
#  model MXU FLOPs/node/round or None, justification)
POINTS = (
    ("chunked scatter", "imp3d", "push-sum", 1_000_000,
     dict(delivery="scatter", engine="chunked"), "addressing-bound",
     None, None, None,
     "sort-based scatter over n random static edges; the chip's "
     "~8-12 ns/element dynamic-address floor (measured across every "
     "gather/scatter formulation) x 2 channels bounds the round, not HBM"),
    ("chunked stencil", "torus3d", "push-sum", 1_000_000,
     dict(delivery="stencil", engine="chunked"), "HBM-streaming",
     32 + 8 * 12, None, None,
     "12 displacement classes; XLA materializes each masked roll as its "
     "own HBM pass instead of fusing into one sweep"),
    ("chunked pool", "full", "push-sum", 1_048_576,
     dict(delivery="pool", engine="chunked", pool_size=4), "HBM-streaming",
     32 + 8 * 4 + 1, None, None,
     "K=4 masked dynamic rolls; same XLA materialization overhead"),
    ("fused stencil2", "torus3d", "push-sum", 1_000_000,
     dict(delivery="stencil", engine="fused"), "VMEM-resident",
     None, 390, None,
     "state resident across the whole chunk; ops model: full-width "
     "sampling word ~100 + 12-column select ~25 + 12 classes x ~20 "
     "(2-plane masked tile gathers + lane roll) + absorb ~25"),
    ("fused pool", "full", "push-sum", 1_000_000,
     dict(delivery="pool", engine="fused", pool_size=2), "VMEM-resident",
     None, 86, None,
     "state resident across the whole chunk; ops model: packed choice "
     "~13 + sends ~8 + 2 slots x ~20 gather + absorb ~25. n = 1,000,000 "
     "— bench.py's EXACT flagship config, so this row and the bench "
     "headline are the same measurement (the r4 tables' 2^20 row was a "
     "silently different config, VERDICT r4 Weak #1)"),
    ("fused pool (matmul)", "full", "push-sum", 1_000_000,
     dict(delivery="matmul", engine="fused", pool_size=2), "MXU-matmul",
     None, 70, 2048,
     "ISSUE 12: the fused pool round with the lane-rotation blend moved "
     "onto the MXU as 128x128 one-hot tiles (bitwise the roll blend); "
     "MXU model: 2 slots x 2 planes x 2 one-hot dots x 128 MACs x 2 "
     "FLOPs/MAC = 2048 FLOPs/node/round, leaving the VPU sampling + "
     "absorb + the per-slot one-hot mask regen (~70 ops). The column "
     "answers 'which unit does this row roof against' — the dense tier "
     "is the first engine whose round has a non-zero MXU column at all"),
    ("fused imp", "imp3d", "push-sum", 1_000_000,
     dict(delivery="pool", engine="fused", pool_size=4), "VMEM-resident",
     None, 360, None,
     "lattice + pooled long-range classes, state resident; ops model: "
     "word ~100 + choice ~13 + class select ~20 + 10 classes x ~20 + "
     "absorb ~25"),
    ("pool2 (HBM stream)", "full", "push-sum", 16_777_216,
     dict(delivery="pool", engine="fused", pool_size=2), "HBM-streaming",
     44, None, None,
     "r4 zero-send-plane design: raw-window reads + in-consumer choice "
     "regen + packed term/conv; the remaining gap to the roof is the "
     "synchronous per-tile write volley (RUNLOG r4) — see the MXU column "
     "note below for the r6 per-unit attribution"),
    ("stencil hbm", "torus3d", "push-sum", 16_777_216,
     dict(delivery="stencil", engine="fused"), "HBM-streaming",
     45, None, None,
     "r5 one-sweep redesign (VERDICT r4 #4): raw-state cluster windows + "
     "in-consumer sampling regen — own 32 B r/w + 2 value planes through "
     "ONE shared cluster window (~12 B) + mirrors. A sub-100% row here is "
     "VPU time, not bandwidth: the ~100-op threefry regen and the "
     "10-class masked reads exceed the shrunk byte model's DMA time (the "
     "MXU FLOPs / arithmetic-intensity column makes the per-unit "
     "attribution explicit), so the byte model no longer binds the round"),
)


def section() -> list[str]:
    from benchmarks.compare import engine_us_per_round

    out = [
        "## Roofline accounting per engine (push-sum, measured on-chip)",
        "",
        "Per-round cost via differential fixed-round timing (launch floor "
        "and compile cancel), set against a lower-bound model of the "
        "algorithmic HBM bytes per round. Implied GB/s over the v5e's "
        f"{HBM_ROOF_GBS:.0f} GB/s HBM roofline classifies each tier; "
        "VMEM-resident engines move ~zero HBM bytes per round by design "
        "and are VPU-op-bound (their implied 'bandwidth' would be VMEM "
        "traffic, far above the HBM roof — that is the point); the "
        "sort-based scatter tier is bounded by the chip's measured "
        "~8-12 ns/element dynamic-address floor, not bandwidth. "
        "VMEM-resident rows carry a vector-ops model instead "
        f"(% of the ~{VPU_ROOF_OPS/1e12:.1f} T ops/s 32-bit issue roof; "
        "VERDICT r3 #9): rows well under ~50% are not issue-bound either "
        "— their tiled gathers are dynamic-slice/roll sequences whose "
        "dependency chains and sub-tile moves cap issue, the same class "
        "of floor the r3 microbenchmarks measured for every "
        "dynamic-addressing formulation. The MXU FLOPs / "
        "arithmetic-intensity columns (ISSUE 12) say which UNIT each row "
        "roofs against: every pre-matmul engine carries a zero MXU "
        "column (the chip's dominant FLOPs source idle — ROADMAP 5a); "
        "the dense matmul tier moves the delivery blend onto 128x128 "
        "one-hot MXU tiles "
        f"(% of a ~{MXU_ROOF_FLOPS/1e12:.0f} T FLOPs f32-accumulate "
        "roof), and intensity = MXU FLOPs / HBM byte for the streaming "
        "rows.",
        "",
        "| engine tier | config | µs/round | model B/node/round | "
        "implied GB/s | % HBM roof | model ops/node/round | % VPU issue "
        "| model MXU FLOPs/node/round | % MXU roof | arith intensity "
        "(FLOP/B) | bound class |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    from benchmarks.compare import ENGINE_US_NOISE

    notes = []
    for (label, kind, _algo, n, overrides, klass, model_b, model_ops,
         model_mxu, why) in POINTS:
        # Spread policy lives in benchmarks.compare.default_round_spread —
        # the same widths bench.py quotes, so the rows are comparable.
        us = engine_us_per_round(kind, "push-sum", n, **overrides)
        below_noise = us < ENGINE_US_NOISE  # unclamped differential: render
        # as a bound, never divide by it (these points sit at >=100 us in
        # practice; this guards the contract, not an expected case)
        if model_b is not None and not below_noise:
            gbs = n * model_b / (us * 1e-6) / 1e9
            pct = f"{100 * gbs / HBM_ROOF_GBS:.0f}%"
            gbs_s = f"{gbs:,.0f}"
            model_s = str(model_b)
        else:
            gbs_s, pct = "—", "—"
            model_s = str(model_b) if model_b is not None else "—"
        if model_ops is not None and not below_noise:
            vpu = n * model_ops / (us * 1e-6)
            vpu_s = f"{100 * vpu / VPU_ROOF_OPS:.0f}%"
            ops_s = f"~{model_ops}"
        else:
            vpu_s, ops_s = "—", "—"
        if model_mxu is not None:
            mxu_s = f"~{model_mxu:,}"
            mxu_pct = (
                "—" if below_noise
                else f"{100 * n * model_mxu / (us * 1e-6) / MXU_ROOF_FLOPS:.0f}%"
            )
        else:
            # Zero, not '—': the idle MXU is the finding the column exists
            # to make visible (ROADMAP 5a).
            mxu_s, mxu_pct = "0", "0%"
        # Intensity is MXU FLOPs per algorithmic HBM byte — defined for
        # every row with a byte model (streaming tiers), where a 0.0 is
        # the idle-MXU finding made quantitative; VMEM-resident rows move
        # ~no HBM bytes, so the ratio is undefined there ('—').
        ai_s = (
            f"{(model_mxu or 0) / model_b:.1f}"
            if model_b is not None else "—"
        )
        us_s = f"<{ENGINE_US_NOISE}" if below_noise else f"{us:,.1f}"
        out.append(
            f"| {label} | {kind} {n:,} | {us_s} | {model_s} "
            f"| {gbs_s} | {pct} | {ops_s} | {vpu_s} | {mxu_s} | {mxu_pct} "
            f"| {ai_s} | {klass} |"
        )
        notes.append(f"- **{label}**: {why}.")
        print(f"[roofline] {label}: {us:.1f} us/round", flush=True)
    out.append("")
    out.extend(notes)
    out.append("")
    return out


def export_models() -> dict:
    """Machine-readable export of the roofline MODEL (no hardware
    needed): the roof constants, the per-node-per-round compute linear
    forms the cost model scores with (analysis/cost.COMPUTE_MODELS — one
    home, re-exported here so the calibration artifact and the model can
    be diffed offline), and the POINTS byte/op models. The measured
    us/round column still needs the chip (``section()``)."""
    from cop5615_gossip_protocol_tpu.analysis.cost import COMPUTE_MODELS

    return {
        "schema": 1,
        "roofs": {
            "hbm_gbs": HBM_ROOF_GBS,
            "vpu_ops_per_s": VPU_ROOF_OPS,
            "mxu_flops_per_s": MXU_ROOF_FLOPS,
        },
        "compute_models": COMPUTE_MODELS,
        "points": [
            {
                "label": label, "kind": kind, "algorithm": algo, "n": n,
                "overrides": overrides, "bound_class": klass,
                "model_bytes_per_node_round": model_b,
                "model_vpu_ops_per_node_round": model_ops,
                "model_mxu_flops_per_node_round": model_mxu,
            }
            for (label, kind, algo, n, overrides, klass, model_b,
                 model_ops, model_mxu, _why) in POINTS
        ],
    }


def main(argv=None) -> int:
    import argparse
    import json

    import jax

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", type=str, default=None, metavar="FILE",
                    help="write the roofline MODEL (roof constants + "
                    "linear forms + POINTS models) as JSON — "
                    "hardware-free; the measured table still needs the "
                    "chip")
    args = ap.parse_args(argv)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(export_models(), f, indent=2, sort_keys=True)
        print(f"[roofline] wrote {args.json}", file=sys.stderr)
        if jax.default_backend() != "tpu":
            return 0
    if jax.default_backend() != "tpu":
        print("roofline accounting needs the real chip", file=sys.stderr)
        return 2
    print("\n".join(section()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
