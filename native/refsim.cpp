// refsim — native discrete-event simulator of the reference Akka.NET program.
//
// The reference (program.fs, F#/Akka.NET) is a single-process actor system:
// per-node ChildActors exchange mailbox messages, a ParentActor counts
// convergence reports and kills the process. This module re-implements that
// *semantic model* — not the code — as a C++ discrete-event engine: one global
// FIFO event queue stands in for Akka's fair thread-pool dispatcher, each
// event is one mailbox message, and actor state lives in flat arrays.
//
// Role in the framework (SURVEY.md §7 step 7): the runnable stand-in for
// `dotnet run N topology algorithm` (no .NET in this image) — the baseline the
// comparison harness joins against the TPU path — and a deterministic oracle
// for the reference-semantics JAX modes at small N.
//
// Reference-fidelity notes (citations are program.fs:LINE):
//   Q1  population = nodes+1, convergence target = nodes   (:152-154 vs :178)
//   Q2  gossip converges on the 11th receipt               (:102-105)
//   Q3  converged gossip nodes keep spreading              (:92 only gates the target)
//   Q4  push-sum termRound starts at 1                     (:79)
//   Q5  push-sum reports pre-absorb (sum, weight)          (:138 before :140-141)
//   Q6  "2D" is wired as a line over ceil(sqrt N)^2 nodes  (:227-248)
//   Q8  Imp3D spawns orphan actors the lattice never wires (:267-313)
//   Q9  Imp3D random extra drawn from [0, nodes-1), self/dup edges kept (:308-310)
// Deliberate divergence (Q7): the reference constructs a fresh time-seeded
// Random() per message — irreproducible, correlated streams. Here one seeded
// mt19937_64 drives everything, so runs are bit-reproducible; partner draws
// reduce the raw 64-bit word modulo the span (bias <= span/2^64, negligible).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <chrono>
#include <deque>
#include <random>
#include <string>
#include <vector>

namespace {

struct Topology {
  int population = 0;   // actors spawned (includes the Q1 extra)
  int target = 0;       // converged-node count that ends the run
  bool implicit_full = false;
  std::vector<std::vector<int>> rows;  // empty when implicit_full
};

void wire_line(Topology& t, int pop) {
  t.rows.assign(pop, {});
  for (int i = 0; i < pop; ++i) {
    if (i > 0) t.rows[i].push_back(i - 1);
    if (i < pop - 1) t.rows[i].push_back(i + 1);
  }
}

// Mirrors ops/topology.py build_line/build_ref2d/build_full/build_imp3d with
// reference=True — the same rounding rules, checked against each other in
// tests/test_native.py.
bool build_topology(const std::string& kind, int n, uint64_t seed, Topology& t) {
  if (n <= 0) return false;
  std::mt19937_64 rng(seed);
  if (kind == "line") {
    t.population = n + 1;
    t.target = n;
    wire_line(t, t.population);
    return true;
  }
  if (kind == "ref2d" || kind == "2d") {  // Q6: rounded up, wired as a line
    int side = static_cast<int>(std::ceil(std::sqrt(static_cast<double>(n))));
    int sq = side * side;
    t.population = sq + 1;
    t.target = sq;
    wire_line(t, t.population);
    return true;
  }
  if (kind == "full") {
    t.population = n + 1;
    t.target = n;
    t.implicit_full = true;  // partner = uniform j != i over the population
    return true;
  }
  if (kind == "imp3d") {
    // C3: N rounds down via floor(N^0.33334)^3 (:27-31); the lattice side
    // uses the different exponent floor(N^0.34) (:268) — mismatch makes Q8
    // orphans possible.
    int rounded = static_cast<int>(std::floor(std::pow(n, 0.33334)));
    rounded = rounded * rounded * rounded;
    if (rounded < 1) rounded = 1;
    int g = static_cast<int>(std::floor(std::pow(n, 0.34)));
    if (g < 1) g = 1;
    t.population = rounded + 1;
    t.target = rounded;
    t.rows.assign(t.population, {});
    long long g3 = static_cast<long long>(g) * g * g;
    int limit = static_cast<int>(std::min<long long>(g3, rounded));
    int zmul = g * g;
    for (int z = 0; z < g; ++z)
      for (int y = 0; y < g; ++y)
        for (int x = 0; x < g; ++x) {
          int i = z * zmul + y * g + x;
          if (i >= limit) continue;
          auto& r = t.rows[i];
          if (x > 0) r.push_back(i - 1);
          if (x < g - 1 && i + 1 < limit) r.push_back(i + 1);
          if (y > 0) r.push_back(i - g);
          if (y < g - 1 && i + g < limit) r.push_back(i + g);
          if (z > 0) r.push_back(i - zmul);
          if (z < g - 1 && i + zmul < limit) r.push_back(i + zmul);
          // Q9: Random().Next(0, nodes-1) — exclusive upper bound, never the
          // last node; self-edges and duplicates are kept as drawn.
          int span = rounded - 1 > 0 ? rounded - 1 : 1;
          r.push_back(static_cast<int>(rng() % static_cast<uint64_t>(span)));
        }
    return true;
  }
  return false;
}

enum MsgType : int {
  kActivate = 0,      // ActivateChildActor — gossip spreader self-loop (:89-95)
  kCall = 1,          // CallChildActor — rumor receipt (:97-105)
  kComputePushSum = 2 // ComputePushSum(s, w, delta) (:119-143)
};

struct Event {
  int type;
  int target;
  double s, w;
};

struct Engine {
  const Topology& topo;
  std::mt19937_64 rng;
  std::deque<Event> queue;
  long long events_processed = 0;
  long long max_queue_depth = 0;  // 1 for push-sum: single walk (SURVEY.md §3.3)
  int converged_count = 0;

  // ChildActor state (:74-88)
  std::vector<int> msg_count;       // gossip receipts
  std::vector<double> sum, weight;  // push-sum mass
  std::vector<int> term_round;      // consecutive sub-delta receipts
  std::vector<uint8_t> converged;   // doubles as the shared registry (C6, :71)

  Engine(const Topology& t, uint64_t seed)
      : topo(t),
        rng(seed ^ 0x9E3779B97F4A7C15ull),  // decorrelate from topology draws
        msg_count(t.population, 0),
        sum(t.population),
        weight(t.population, 1.0),
        term_round(t.population, 1),  // Q4
        converged(t.population, 0) {
    // InitializeVariables i → sum <- i (:107-108, :159)
    for (int i = 0; i < t.population; ++i) sum[i] = static_cast<double>(i);
  }

  int degree(int i) const {
    if (topo.implicit_full) return topo.population - 1;
    return static_cast<int>(topo.rows[i].size());
  }

  // Uniform random neighbor — the reference's neighbours.[Random().Next(0, deg)]
  // (:91, :112, :126, :142). Returns -1 for a degree-0 orphan: the reference
  // actor throws IndexOutOfRange there and Akka's supervision restarts it,
  // silently losing the message (Q8) — callers drop the event to match.
  int random_neighbor(int i) {
    int deg = degree(i);
    if (deg <= 0) return -1;
    uint64_t r = rng() % static_cast<uint64_t>(deg);
    if (topo.implicit_full) {
      // shift-sampling j != i over the population
      int j = static_cast<int>((i + 1 + r) % topo.population);
      return j;
    }
    return topo.rows[i][static_cast<size_t>(r)];
  }

  void gossip_activate(int i) {
    int nbr = random_neighbor(i);
    if (nbr < 0) return;  // orphan leader: protocol never starts (Q8)
    if (!converged[nbr]) queue.push_back({kCall, nbr, 0, 0});  // registry probe (:92)
    queue.push_back({kActivate, i, 0, 0});  // perpetual self-loop (Q3, :95)
  }

  void gossip_call(int i) {
    if (msg_count[i] == 0) queue.push_back({kActivate, i, 0, 0});  // join spreaders (:99-100)
    if (msg_count[i] == 10) {  // Q2: check precedes increment → 11th receipt (:102-105)
      ++converged_count;
      converged[i] = 1;
    }
    ++msg_count[i];
  }

  void push_sum_compute(int i, double s_in, double w_in, double delta) {
    if (converged[i]) {  // relay untouched (:125-127)
      int nbr = random_neighbor(i);
      if (nbr >= 0) queue.push_back({kComputePushSum, nbr, s_in, w_in});
      return;
    }
    double new_sum = sum[i] + s_in;
    double new_weight = weight[i] + w_in;
    double cal = std::fabs(sum[i] / weight[i] - new_sum / new_weight);
    if (cal > delta) {
      term_round[i] = 0;  // reset (:130-131)
    } else {
      ++term_round[i];  // (:132-133)
      if (term_round[i] == 3) {  // C = 3 (:135)
        converged[i] = 1;
        ++converged_count;  // Q5: parent sees pre-absorb (sum, weight) (:138)
      }
    }
    sum[i] = new_sum / 2.0;      // (:140)
    weight[i] = new_weight / 2.0;  // (:141)
    int nbr = random_neighbor(i);
    if (nbr >= 0) queue.push_back({kComputePushSum, nbr, sum[i], weight[i]});
  }

  // Kickoff (C13): gossip leaders get ActivateChildActor except on full,
  // which sends CallChildActor (:181, :218, :258, :323); push-sum leaders
  // halve and forward — PushSum delta handler (:110-116). The delta rides
  // along in run(), matching the reference threading it per message.
  void kickoff(bool gossip, int leader) {
    if (gossip) {
      if (topo.implicit_full) {
        queue.push_back({kCall, leader, 0, 0});
      } else {
        queue.push_back({kActivate, leader, 0, 0});
      }
      return;
    }
    sum[leader] /= 2.0;
    weight[leader] /= 2.0;
    int nbr = random_neighbor(leader);
    if (nbr >= 0) queue.push_back({kComputePushSum, nbr, sum[leader], weight[leader]});
  }

  // Drain the mailbox until the parent's count reaches the target
  // (:49-53, :56-60) or the event budget runs out (the reference would hang).
  bool run(double delta, long long max_events) {
    while (!queue.empty() && converged_count < topo.target &&
           events_processed < max_events) {
      max_queue_depth =
          std::max(max_queue_depth, static_cast<long long>(queue.size()));
      Event e = queue.front();
      queue.pop_front();
      ++events_processed;
      switch (e.type) {
        case kActivate: gossip_activate(e.target); break;
        case kCall: gossip_call(e.target); break;
        case kComputePushSum: push_sum_compute(e.target, e.s, e.w, delta); break;
      }
    }
    return converged_count >= topo.target;
  }
};

}  // namespace

extern "C" {

struct RefSimResult {
  long long events;     // mailbox messages processed
  long long max_queue;  // peak mailbox depth (push-sum: 1 — single walk)
  double wall_ms;       // wall-clock from kickoff to convergence (Stopwatch, :22)
  int population;       // actors spawned (Q1 includes the extra)
  int target;           // parent's AllNodes count
  int converged;        // converged nodes at exit
  int leader;           // kickoff node drawn this run
  int ok;               // 1 iff converged
};

// Run one simulation. topology in {line, 2d/ref2d, full, imp3d} (lowercase),
// algorithm in {gossip, push-sum}. max_events <= 0 selects a default budget.
// Returns 0 on success, nonzero on invalid arguments.
int refsim_run(int n, const char* topology, const char* algorithm,
               uint64_t seed, long long max_events, RefSimResult* out) {
  if (!topology || !algorithm || !out) return 1;
  std::string topo_s(topology), algo_s(algorithm);
  bool gossip;
  if (algo_s == "gossip") gossip = true;
  else if (algo_s == "push-sum" || algo_s == "pushsum") gossip = false;
  else return 2;

  Topology topo;
  if (!build_topology(topo_s, n, seed, topo)) return 3;
  if (max_events <= 0) max_events = 500'000'000LL;

  Engine eng(topo, seed);
  // leader = Random().Next(0, nodes) — over the target range, not the Q1
  // extra actor (:173).
  int leader = static_cast<int>(eng.rng() % static_cast<uint64_t>(topo.target));

  auto t0 = std::chrono::steady_clock::now();
  eng.kickoff(gossip, leader);  // delta fixed at every kickoff site (:187 etc.)
  bool ok = eng.run(1e-10, max_events);
  auto t1 = std::chrono::steady_clock::now();

  out->events = eng.events_processed;
  out->max_queue = eng.max_queue_depth;
  out->wall_ms = std::chrono::duration<double, std::milli>(t1 - t0).count();
  out->population = topo.population;
  out->target = topo.target;
  out->converged = eng.converged_count;
  out->leader = leader;
  out->ok = ok ? 1 : 0;
  return 0;
}

// Topology introspection for cross-validation against the Python builders.
// First call with degrees == nullptr to learn population/max_deg; then call
// with buffers of size [population] and [population * max_deg].
// Implicit `full` reports max_deg 0. Returns 0 on success.
int refsim_topology(int n, const char* topology, uint64_t seed,
                    int* population, int* target, int* max_deg,
                    int* degrees, int* neighbors) {
  if (!topology || !population || !target || !max_deg) return 1;
  Topology topo;
  if (!build_topology(std::string(topology), n, seed, topo)) return 3;
  *population = topo.population;
  *target = topo.target;
  int md = 0;
  for (const auto& r : topo.rows) md = std::max(md, static_cast<int>(r.size()));
  *max_deg = md;
  if (!degrees || !neighbors || topo.implicit_full || md == 0) return 0;
  for (int i = 0; i < topo.population; ++i) {
    const auto& r = topo.rows[i];
    degrees[i] = static_cast<int>(r.size());
    for (int j = 0; j < static_cast<int>(r.size()); ++j)
      neighbors[i * md + j] = r[j];
    for (int j = static_cast<int>(r.size()); j < md; ++j)
      neighbors[i * md + j] = 0;
  }
  return 0;
}

}  // extern "C"

#ifdef REFSIM_MAIN
// CLI matching the reference's `dotnet run <numNodes> <topology> <algorithm>`
// surface, printing its exact convergence banner (:51-52).
#include <cstdio>
#include <cstdlib>

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s <numNodes> <topology> <algorithm> [seed]\n", argv[0]);
    return 2;
  }
  int n = std::atoi(argv[1]);
  std::string topo(argv[2]);
  std::string algo(argv[3]);
  for (auto& c : topo) c = static_cast<char>(std::tolower(c));
  for (auto& c : algo) c = static_cast<char>(std::tolower(c));
  uint64_t seed = argc > 4 ? std::strtoull(argv[4], nullptr, 10) : 0;
  RefSimResult r;
  int rc = refsim_run(n, topo.c_str(), algo.c_str(), seed, 0, &r);
  if (rc != 0) {
    std::fprintf(stderr, "refsim: invalid arguments (rc=%d)\n", rc);
    return rc;
  }
  if (!r.ok) {
    std::fprintf(stderr, "refsim: did not converge (%d/%d after %lld events)\n",
                 r.converged, r.target, r.events);
    return 1;
  }
  std::printf("-----------------------------------------------------------\n");  // 59 dashes, program.fs:51
  std::printf("Convergence Time: %f ms\n", r.wall_ms);
  std::printf("events: %lld population: %d leader: %d\n", r.events, r.population,
              r.leader);
  return 0;
}
#endif
